"""Numeric tests for loss ops vs numpy references (SURVEY §4: OpTest parity).

Reference semantics: paddle/fluid/operators/softmax_with_cross_entropy_op.*
"""
import numpy as np
import pytest

from paddle_tpu.ops.registry import get_op


def _swce(logits, label, **attrs):
    return get_op('softmax_with_cross_entropy').fn(logits, label, **attrs)


def _np_logsoftmax(x, axis=-1):
    m = x.max(axis=axis, keepdims=True)
    e = np.exp(x - m)
    return (x - m) - np.log(e.sum(axis=axis, keepdims=True))


class TestSoftmaxWithCrossEntropy:
    def test_matches_numpy(self):
        rng = np.random.RandomState(0)
        logits = rng.randn(6, 10).astype(np.float32)
        label = rng.randint(0, 10, (6, 1)).astype(np.int64)
        loss, sm = _swce(logits, label)
        logp = _np_logsoftmax(logits)
        want = -np.take_along_axis(logp, label, -1)
        np.testing.assert_allclose(np.asarray(loss), want, rtol=1e-5)
        np.testing.assert_allclose(np.asarray(sm), np.exp(logp), rtol=1e-5)

    def test_negative_ignore_index_masks(self):
        """ignore_index=-1 (the BERT MLM sentinel) must zero those rows."""
        rng = np.random.RandomState(1)
        logits = rng.randn(5, 4).astype(np.float32)
        label = np.array([0, -1, 2, -1, 3], np.int64)[:, None]
        loss, _ = _swce(logits, label, ignore_index=-1)
        loss = np.asarray(loss)
        assert loss[1, 0] == 0.0 and loss[3, 0] == 0.0
        assert (loss[[0, 2, 4], 0] > 0).all()

    def test_axis0_matches_last_axis_on_transpose(self):
        rng = np.random.RandomState(2)
        logits = rng.randn(7, 5).astype(np.float32)  # classes on axis 0
        label = rng.randint(0, 7, (5,)).astype(np.int64)
        label[2] = -1
        l0, sm0 = _swce(logits, label, axis=0, ignore_index=-1)
        l1, sm1 = _swce(logits.T, label[:, None], axis=-1, ignore_index=-1)
        assert np.asarray(l0).shape == (1, 5)
        np.testing.assert_allclose(np.asarray(l0)[0], np.asarray(l1)[:, 0],
                                   rtol=1e-5)
        np.testing.assert_allclose(np.asarray(sm0).T, np.asarray(sm1),
                                   rtol=1e-5)

    def test_soft_label(self):
        rng = np.random.RandomState(3)
        logits = rng.randn(4, 6).astype(np.float32)
        soft = rng.rand(4, 6).astype(np.float32)
        soft /= soft.sum(-1, keepdims=True)
        loss, _ = _swce(logits, soft, soft_label=True)
        want = -(soft * _np_logsoftmax(logits)).sum(-1, keepdims=True)
        np.testing.assert_allclose(np.asarray(loss), want, rtol=1e-5)


class TestCrossEntropy:
    def test_negative_ignore_index(self):
        rng = np.random.RandomState(4)
        probs = rng.rand(4, 3).astype(np.float32) + 0.1
        probs /= probs.sum(-1, keepdims=True)
        label = np.array([0, -1, 2, 1], np.int64)[:, None]
        loss = np.asarray(get_op('cross_entropy').fn(
            probs, label, ignore_index=-1))
        assert loss[1, 0] == 0.0
        np.testing.assert_allclose(
            loss[0, 0], -np.log(probs[0, 0] + 1e-8), rtol=1e-5)
