"""Detection suite: iou/box_coder/priors/anchors/NMS/match/YOLO/proposals."""
import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers


def _run(build, feed=None):
    main, start = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, start):
        outs = build()
        outs = outs if isinstance(outs, (list, tuple)) else [outs]
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(start)
        return exe.run(main, feed=feed or {}, fetch_list=list(outs))


def test_iou_similarity():
    x = np.array([[0, 0, 2, 2], [1, 1, 3, 3]], 'float32')
    y = np.array([[0, 0, 2, 2]], 'float32')

    iou, = _run(lambda: layers.iou_similarity(
        layers.assign(x), layers.assign(y)))
    np.testing.assert_allclose(iou[0, 0], 1.0, rtol=1e-6)
    np.testing.assert_allclose(iou[1, 0], 1.0 / 7.0, rtol=1e-5)


def test_box_coder_roundtrip():
    priors = np.array([[1, 1, 3, 3], [2, 2, 6, 6]], 'float32')
    var = np.array([[0.1, 0.1, 0.2, 0.2]] * 2, 'float32')
    gt = np.array([[1.5, 1.5, 3.5, 3.5]], 'float32')

    def build():
        p = layers.assign(priors)
        v = layers.assign(var)
        t = layers.assign(gt)
        enc = layers.box_coder(p, v, t, code_type='encode_center_size')
        dec = layers.box_coder(p, v, enc, code_type='decode_center_size',
                               axis=0)
        return enc, dec

    enc, dec = _run(build)
    assert enc.shape == (1, 2, 4)
    # decode(encode(gt)) == gt against each prior
    np.testing.assert_allclose(dec[0, 0], gt[0], atol=1e-5)
    np.testing.assert_allclose(dec[0, 1], gt[0], atol=1e-4)


def test_prior_box_counts_and_range():
    def build():
        feat = layers.assign(np.zeros((1, 8, 4, 4), 'float32'))
        img = layers.assign(np.zeros((1, 3, 32, 32), 'float32'))
        box, var = layers.prior_box(feat, img, min_sizes=[8.0],
                                    max_sizes=[16.0], aspect_ratios=[2.0],
                                    flip=True, clip=True)
        return box, var

    box, var = _run(build)
    # priors: ar {1, 2, 0.5} + max_size square = 4
    assert box.shape == (4, 4, 4, 4) and var.shape == box.shape
    assert box.min() >= 0.0 and box.max() <= 1.0
    # center prior of cell (0,0) with ar=1: size 8/32=0.25 around (4/32)
    np.testing.assert_allclose(box[0, 0, 0], [0, 0, 0.25, 0.25], atol=1e-6)


def test_anchor_generator_shapes():
    def build():
        feat = layers.assign(np.zeros((1, 8, 3, 3), 'float32'))
        a, v = layers.anchor_generator(feat, anchor_sizes=[32.0, 64.0],
                                       aspect_ratios=[1.0],
                                       stride=[16.0, 16.0])
        return a, v

    a, v = _run(build)
    assert a.shape == (3, 3, 2, 4)
    # anchors centered at (8, 8) for cell (0, 0)
    np.testing.assert_allclose((a[0, 0, 0, 0] + a[0, 0, 0, 2]) / 2, 8.0,
                               atol=1e-4)


def test_multiclass_nms_suppresses():
    # two near-identical boxes + one distinct; C=2 with background=0
    boxes = np.array([[[0, 0, 10, 10], [0, 0, 10, 9.5], [20, 20, 30, 30]]],
                     'float32')
    scores = np.zeros((1, 2, 3), 'float32')
    scores[0, 1] = [0.9, 0.8, 0.7]     # class 1 scores per box

    def build():
        b = layers.assign(boxes)
        s = layers.assign(scores)
        return layers.multiclass_nms(b, s, score_threshold=0.1, nms_top_k=3,
                                     keep_top_k=3, nms_threshold=0.5)

    out, = _run(build)
    assert out.shape == (1, 3, 6)
    kept = out[0][out[0, :, 0] >= 0]
    assert len(kept) == 2                        # overlap suppressed
    np.testing.assert_allclose(sorted(kept[:, 1], reverse=True), [0.9, 0.7],
                               rtol=1e-6)


def test_bipartite_match_greedy():
    # gt0 best matches prior1; gt1 then takes prior0
    dist = np.array([[[0.6, 0.9, 0.1], [0.5, 0.8, 0.2]]], 'float32')

    def build():
        return layers.bipartite_match(layers.assign(dist))

    m, md = _run(lambda: list(build()))
    assert m.shape == (1, 3)
    assert m[0, 1] == 0 and m[0, 0] == 1 and m[0, 2] == -1
    np.testing.assert_allclose(md[0, 1], 0.9, rtol=1e-6)


def test_yolo_box_decode():
    B, A, C, H = 1, 1, 2, 2
    x = np.zeros((B, A * (5 + C), H, H), 'float32')
    x[0, 4] = 10.0            # conf ≈ 1
    x[0, 5] = 10.0            # class 0 ≈ 1
    x[0, 6] = -10.0           # class 1 ≈ 0

    def build():
        xv = layers.assign(x)
        img = layers.assign(np.array([[64, 64]], 'int32'))
        return layers.yolo_box(xv, img, anchors=[16, 16], class_num=C,
                               conf_thresh=0.5, downsample_ratio=32)

    boxes, scores = _run(build)
    assert boxes.shape == (1, 4, 4) and scores.shape == (1, 4, 2)
    # cell (0,0): center = (0.5/2)*64 = 16; w = e^0 * 16 * 64/64 = 16
    np.testing.assert_allclose(boxes[0, 0], [8, 8, 24, 24], atol=1e-3)
    assert scores[0, 0, 0] > 0.99 and scores[0, 0, 1] < 0.01


def test_yolov3_loss_responds_to_targets():
    B, C, H = 1, 2, 4
    rng = np.random.RandomState(0)
    x = rng.randn(B, 3 * (5 + C), H, H).astype('float32') * 0.1
    gt = np.zeros((B, 2, 4), 'float32')
    gt[0, 0] = [0.5, 0.5, 0.4, 0.4]          # one valid gt, one padding row
    lab = np.zeros((B, 2), 'int64')

    def build():
        xv = layers.assign(x)
        gb = layers.assign(gt)
        gl = layers.assign(lab)
        return layers.yolov3_loss(xv, gb, gl,
                                  anchors=[10, 13, 16, 30, 33, 23],
                                  anchor_mask=[0, 1, 2], class_num=C,
                                  ignore_thresh=0.7, downsample_ratio=8)

    loss, = _run(build)
    assert loss.shape == (1,) and np.isfinite(loss).all() and loss[0] > 0


def test_generate_proposals_fixed_shape():
    B, A, H, W = 1, 2, 4, 4
    rng = np.random.RandomState(0)
    scores = rng.rand(B, A, H, W).astype('float32')
    deltas = (rng.randn(B, 4 * A, H, W) * 0.1).astype('float32')
    anchors = np.zeros((H, W, A, 4), 'float32')
    for i in range(H):
        for j in range(W):
            for a in range(A):
                cx, cy = j * 8 + 4, i * 8 + 4
                s = 8 * (a + 1)
                anchors[i, j, a] = [cx - s / 2, cy - s / 2,
                                    cx + s / 2, cy + s / 2]
    var = np.full((H, W, A, 4), 1.0, 'float32')

    def build():
        return layers.generate_proposals(
            layers.assign(scores), layers.assign(deltas),
            layers.assign(np.array([[32, 32, 1.0]], 'float32')),
            layers.assign(anchors), layers.assign(var),
            pre_nms_top_n=16, post_nms_top_n=5, return_rois_num=True)

    rois, probs, num = _run(lambda: list(build()))
    assert rois.shape == (1, 5, 4) and probs.shape == (1, 5)
    assert 1 <= int(num[0]) <= 5
    assert (rois[0, :int(num[0])] >= 0).all() and \
           (rois[0, :int(num[0])] <= 31).all()


def test_ssd_loss_and_focal_loss():
    B, M, C, G = 1, 4, 3, 2
    rng = np.random.RandomState(0)
    priors = np.array([[0.0, 0.0, 0.4, 0.4], [0.3, 0.3, 0.7, 0.7],
                       [0.5, 0.5, 0.9, 0.9], [0.1, 0.6, 0.4, 0.9]],
                      'float32')
    gt = np.zeros((B, G, 4), 'float32')
    gt[0, 0] = [0.05, 0.05, 0.35, 0.35]
    lab = np.ones((B, G), 'int64')

    def build():
        loc = layers.assign((rng.randn(B, M, 4) * 0.1).astype('float32'))
        conf = layers.assign(rng.randn(B, M, C).astype('float32'))
        l = layers.ssd_loss(loc, conf, layers.assign(gt), layers.assign(lab),
                            layers.assign(priors))
        x = layers.assign(rng.randn(5, C).astype('float32'))
        fl = layers.sigmoid_focal_loss(
            x, layers.assign(np.array([[1], [0], [2], [1], [0]], 'int64')),
            layers.assign(np.array([3], 'int32')))
        return l, fl

    l, fl = _run(build)
    assert l.shape == (1, 1) and np.isfinite(l).all() and l[0, 0] > 0
    assert fl.shape == (5, 3) and np.isfinite(fl).all()


def test_distribute_and_collect_fpn():
    rois = np.array([[0, 0, 20, 20], [0, 0, 300, 300]], 'float32')

    def build():
        r = layers.assign(rois)
        multi, restore = layers.distribute_fpn_proposals(r, 2, 5, 4, 224)
        scores = layers.assign(np.array([[0.9, 0.1]], 'float32'))
        col = layers.collect_fpn_proposals(
            layers.assign(rois[None]), scores, 2, 2, post_nms_top_n=2)
        return multi, restore, col

    multi, restore, col = _run(build)
    assert multi.shape == (4, 2, 4)
    # small roi → lowest level (2), big roi → higher level
    assert (multi[0][0] == rois[0]).all() and (multi[0][1] == 0).all()
    np.testing.assert_allclose(col[0], rois[0])   # highest score first


def test_box_clip_and_polygon_transform():
    def build():
        b = layers.assign(np.array([[[-5, -5, 50, 50]]], 'float32'))
        info = layers.assign(np.array([[40, 40, 1.0]], 'float32'))
        clipped = layers.box_clip(b, info)
        poly = layers.polygon_box_transform(
            layers.assign(np.zeros((1, 8, 2, 2), 'float32')))
        return clipped, poly

    clipped, poly = _run(build)
    np.testing.assert_allclose(clipped[0, 0], [0, 0, 39, 39])
    # zero offsets → absolute coords = 4 * (col, row)
    np.testing.assert_allclose(poly[0, 0], [[0, 4], [0, 4]])
    np.testing.assert_allclose(poly[0, 1], [[0, 0], [4, 4]])


def test_rpn_target_assign_op():
    anchors = np.array([[0, 0, 10, 10], [20, 20, 30, 30], [100, 100, 110, 110]],
                       'float32')
    gt = np.array([[0, 0, 10, 10], [0, 0, 0, 0]], 'float32')

    def build():
        return list(layers.rpn_target_assign(
            layers.assign(np.zeros((3, 4), 'float32')),
            layers.assign(np.zeros((3, 2), 'float32')),
            layers.assign(anchors), None, layers.assign(gt)))

    _, _, tgt, label, inw = _run(build)
    assert label[0] == 1           # perfect overlap → fg
    assert label[1] == 0 and label[2] == 0
    assert inw.shape == (3, 4) and inw[0].sum() == 4


def test_bipartite_match_ignores_zero_padding_rows():
    # row 1 is an all-zero padding gt; prior 1 must stay unmatched
    dist = np.array([[[0.9, 0.0, 0.0], [0.0, 0.0, 0.0]]], 'float32')
    m, md = _run(lambda: list(layers.bipartite_match(layers.assign(dist))))
    assert m[0, 0] == 0 and m[0, 1] == -1 and m[0, 2] == -1


def test_generate_proposal_labels_shapes():
    rois = np.array([[0, 0, 10, 10], [20, 20, 40, 40]], 'float32')
    gtb = np.array([[0, 0, 11, 11]], 'float32')
    cls = np.array([2], 'int64')

    def build():
        return list(layers.generate_proposal_labels(
            layers.assign(rois), layers.assign(cls), None,
            layers.assign(gtb), None))

    r, lab, tgt, w1, w2 = _run(build)
    assert tgt.shape == (2, 4)                  # per-roi targets, not pairwise
    assert lab[0] == 2 and lab[1] == 0          # IoU>=0.5 → fg class, else bg
