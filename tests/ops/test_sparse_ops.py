"""Unit tests for the sparse embedding fast path's building blocks
(paddle_tpu/ops/sparse_ops.py, docs/SPARSE.md): knobs, the nnz bucket
ladder, COO coalescing, the SparseRowsGrad accumulation algebra, the
rows-only update kernels vs their dense counterparts, and the per-row
quantization codec of the sparse push."""
import os

import numpy as np
import jax.numpy as jnp
import pytest

from paddle_tpu.ops import sparse_ops as sp
from paddle_tpu.ops.registry import get_op
from paddle_tpu.parallel import quant_collectives as qc


# ---------------------------------------------------------------------------
# knobs (strict parse)
# ---------------------------------------------------------------------------

def test_knob_strict_parse(monkeypatch):
    monkeypatch.setenv('PADDLE_TPU_SPARSE_GRAD', '2')
    with pytest.raises(ValueError, match='PADDLE_TPU_SPARSE_GRAD'):
        sp.sparse_grad_enabled()
    monkeypatch.setenv('PADDLE_TPU_SPARSE_GRAD', '0')
    assert sp.sparse_grad_enabled() is False
    monkeypatch.setenv('PADDLE_TPU_SPARSE_NNZ_BUCKET', 'abc')
    with pytest.raises(ValueError, match='PADDLE_TPU_SPARSE_NNZ_BUCKET'):
        sp.bucket_floor()
    monkeypatch.setenv('PADDLE_TPU_SPARSE_NNZ_BUCKET', '0')
    with pytest.raises(ValueError):
        sp.bucket_floor()
    monkeypatch.setenv('PADDLE_TPU_EMBED_OOB', 'warn')
    with pytest.raises(ValueError, match='PADDLE_TPU_EMBED_OOB'):
        sp.oob_policy()
    monkeypatch.setenv('PADDLE_TPU_EMBED_OOB', 'clip')
    assert sp.oob_policy() == 'clip'


def test_nnz_bucket_ladder(monkeypatch):
    monkeypatch.setenv('PADDLE_TPU_SPARSE_NNZ_BUCKET', '64')
    assert sp.nnz_bucket(1) == 64
    assert sp.nnz_bucket(64) == 64
    assert sp.nnz_bucket(65) == 128
    assert sp.nnz_bucket(4000) == 4096
    # ladder is powers-of-two multiples of the floor: bounded variants
    rungs = {sp.nnz_bucket(n) for n in range(1, 3000)}
    assert rungs == {64, 128, 256, 512, 1024, 2048, 4096}


# ---------------------------------------------------------------------------
# coalescing
# ---------------------------------------------------------------------------

def test_coalesce_dedups_and_pads():
    ids = jnp.asarray([3, 1, 3, 7, 1, 3], jnp.int32)
    vals = jnp.asarray(np.arange(12, dtype=np.float32).reshape(6, 2))
    rows, out = sp.coalesce_rows(ids, vals, vocab=10, bucket=8)
    rows, out = np.asarray(rows), np.asarray(out)
    assert rows.shape == (8,) and out.shape == (8, 2)
    # unique, sorted, padded with the vocab sentinel
    assert rows[:3].tolist() == [1, 3, 7]
    assert (rows[3:] == 10).all()
    # duplicate rows summed
    dense = np.zeros((10, 2), np.float32)
    np.add.at(dense, np.asarray(ids), np.asarray(vals))
    for r, v in zip(rows, out):
        if r < 10:
            assert np.allclose(v, dense[r])
    assert (out[3:] == 0).all()


def test_coalesce_clips_bad_ids_like_dense_gather():
    ids = jnp.asarray([-5, 99, 2], jnp.int32)   # vocab 10: clip to 0, 9
    vals = jnp.ones((3, 4), jnp.float32)
    rows, out = sp.coalesce_rows(ids, vals, vocab=10, bucket=4)
    rows = np.asarray(rows)
    assert set(rows[rows < 10].tolist()) == {0, 2, 9}


def test_scatter_drops_sentinel_rows():
    rows = jnp.asarray([1, 5, 10, 10], jnp.int32)   # 10 = pad sentinel
    vals = jnp.ones((4, 3), jnp.float32)
    p = jnp.zeros((10, 3), jnp.float32)
    out = np.asarray(sp.sparse_sgd(p, rows, vals, jnp.float32(1.0)))
    assert np.count_nonzero(out) == 6      # rows 1 and 5 only
    assert (out[1] == -1).all() and (out[5] == -1).all()


# ---------------------------------------------------------------------------
# SparseRowsGrad algebra
# ---------------------------------------------------------------------------

def _grad(ids, vals, vocab=20, dim=2, bucket=8):
    rows, out = sp.coalesce_rows(jnp.asarray(ids, jnp.int32),
                                 jnp.asarray(vals, jnp.float32),
                                 vocab, bucket=bucket)
    return sp.SparseRowsGrad(rows, out, vocab, dim)


def test_sparse_grad_add_sparse():
    g1 = _grad([1, 2], np.ones((2, 2)))
    g2 = _grad([2, 3], np.ones((2, 2)))
    s = g1 + g2
    assert isinstance(s, sp.SparseRowsGrad)
    dense = np.asarray(s.densify())
    assert np.allclose(dense[1], 1) and np.allclose(dense[2], 2) \
        and np.allclose(dense[3], 1)
    assert np.count_nonzero(dense) == 6


def test_sparse_grad_add_dense_densifies():
    g = _grad([0, 1], np.ones((2, 2)))
    d = jnp.full((20, 2), 0.5)
    for s in (g + d, d + g):       # __add__ and __radd__
        assert not isinstance(s, sp.SparseRowsGrad)
        s = np.asarray(s)
        assert np.allclose(s[0], 1.5) and np.allclose(s[5], 0.5)


def test_sparse_grad_shape_mismatch_raises():
    with pytest.raises(ValueError, match='cannot accumulate'):
        _grad([1], np.ones((1, 2)), vocab=20) \
            + _grad([1], np.ones((1, 2)), vocab=30)


def test_sparse_grad_is_pytree():
    import jax
    g = _grad([1, 2], np.ones((2, 2)))
    leaves = jax.tree_util.tree_leaves(g)
    assert len(leaves) == 2
    g2 = jax.tree_util.tree_map(lambda x: x, g)
    assert isinstance(g2, sp.SparseRowsGrad)
    assert (g2.vocab, g2.dim) == (20, 2)


# ---------------------------------------------------------------------------
# rows-only updates vs the dense kernels (touched rows identical,
# untouched rows frozen)
# ---------------------------------------------------------------------------

def _coo(ids, vocab, dim, rng):
    vals = rng.randn(len(ids), dim).astype(np.float32)
    dense = np.zeros((vocab, dim), np.float32)
    np.add.at(dense, np.asarray(ids), vals)
    rows, cvals = sp.coalesce_rows(jnp.asarray(ids, jnp.int32),
                                   jnp.asarray(vals), vocab, bucket=8)
    return rows, cvals, dense


def test_sparse_sgd_matches_dense_on_touched_rows():
    rng = np.random.RandomState(0)
    V, D = 12, 4
    p = rng.randn(V, D).astype(np.float32)
    rows, vals, dense_g = _coo([2, 5, 2], V, D, rng)
    ref = np.asarray(get_op('sgd').fn(p, dense_g, 0.1))
    out = np.asarray(sp.sparse_sgd(p, rows, vals, 0.1))
    assert np.allclose(out, ref, atol=1e-6)


def test_sparse_adagrad_matches_dense():
    rng = np.random.RandomState(1)
    V, D = 12, 4
    p = rng.randn(V, D).astype(np.float32)
    m = np.abs(rng.randn(V, D)).astype(np.float32)
    rows, vals, dense_g = _coo([0, 3, 3, 11], V, D, rng)
    ref_p, ref_m = get_op('adagrad').fn(p, dense_g, m, 0.1)
    out_p, out_m = sp.sparse_adagrad(p, rows, vals, m, 0.1)
    # dense adagrad with a zero grad leaves a row unchanged → full parity
    assert np.allclose(np.asarray(out_p), np.asarray(ref_p), atol=1e-6)
    assert np.allclose(np.asarray(out_m), np.asarray(ref_m), atol=1e-6)


def test_sparse_momentum_touched_rows_and_lazy_untouched():
    rng = np.random.RandomState(2)
    V, D = 10, 3
    p = rng.randn(V, D).astype(np.float32)
    vel = rng.randn(V, D).astype(np.float32)
    rows, vals, dense_g = _coo([1, 4], V, D, rng)
    ref_p, ref_v = get_op('momentum').fn(p, dense_g, vel, 0.1, mu=0.9)
    out_p, out_v = sp.sparse_momentum(p, rows, vals, vel, 0.1, mu=0.9)
    for r in (1, 4):
        assert np.allclose(np.asarray(out_p)[r], np.asarray(ref_p)[r],
                           atol=1e-6)
        assert np.allclose(np.asarray(out_v)[r], np.asarray(ref_v)[r],
                           atol=1e-6)
    # LAZY: untouched rows keep param AND velocity frozen (dense decays)
    untouched = [r for r in range(V) if r not in (1, 4)]
    assert np.allclose(np.asarray(out_p)[untouched], p[untouched])
    assert np.allclose(np.asarray(out_v)[untouched], vel[untouched])


def test_sparse_adam_lazy_semantics():
    rng = np.random.RandomState(3)
    V, D = 10, 3
    p = rng.randn(V, D).astype(np.float32)
    m1 = np.zeros((V, D), np.float32)
    m2 = np.zeros((V, D), np.float32)
    b1p = np.full((1,), 0.9, np.float32)
    b2p = np.full((1,), 0.999, np.float32)
    rows, vals, dense_g = _coo([7, 2], V, D, rng)
    ref = get_op('adam').fn(p, dense_g, m1, m2, b1p, b2p, 0.01)
    out = sp.sparse_adam(p, rows, vals, m1, m2, b1p, b2p, 0.01)
    for r in (2, 7):
        assert np.allclose(np.asarray(out[0])[r], np.asarray(ref[0])[r],
                           atol=1e-6)
    # beta powers advance globally, same as dense
    assert np.allclose(np.asarray(out[3]), np.asarray(ref[3]))
    assert np.allclose(np.asarray(out[4]), np.asarray(ref[4]))
    untouched = [r for r in range(V) if r not in (2, 7)]
    assert np.allclose(np.asarray(out[0])[untouched], p[untouched])


# ---------------------------------------------------------------------------
# per-row quantization codec + wire accounting (the sparse push)
# ---------------------------------------------------------------------------

def test_rowwise_quant_roundtrip_bound():
    rng = np.random.RandomState(4)
    v = rng.randn(32, 16).astype(np.float32) * 10
    q, s = qc.rowwise_quantize(jnp.asarray(v))
    rt = np.asarray(qc.rowwise_dequantize(q, s))
    # symmetric int8: error bounded by scale/2 = absmax/254 per row
    bound = np.abs(v).max(axis=1, keepdims=True) / 254.0 + 1e-7
    assert (np.abs(rt - v) <= bound).all()


def test_rowwise_quant_zero_rows_exact():
    v = jnp.zeros((4, 8), jnp.float32)
    q, s = qc.rowwise_quantize(v)
    assert (np.asarray(s) == 0).all()
    assert (np.asarray(qc.rowwise_dequantize(q, s)) == 0).all()


def test_sparse_wire_bytes_arithmetic():
    # 4096 rows × 64 dims, 8 replicas
    f32 = qc.sparse_wire_bytes(4096, 64, 'f32', 8)
    bf16 = qc.sparse_wire_bytes(4096, 64, 'bf16', 8)
    i8 = qc.sparse_wire_bytes(4096, 64, 'int8', 8)
    assert f32 == 4096 * 4 + 4096 * 64 * 4
    assert bf16 == 4096 * 4 + 4096 * 64 * 2
    assert i8 == 4096 * 4 + 4096 * 64 + 4096 * 4
    assert qc.sparse_wire_bytes(4096, 64, 'int8', 1) == 0
    # acceptance-shaped ratios (the bench asserts the same)
    dense = qc.wire_bytes(1_000_000 * 64, 'f32', 8)
    assert dense / i8 > 100
    assert f32 / i8 >= 3.5


def test_record_sparse_lookup_metrics():
    from paddle_tpu.observability import registry
    before = sp.sparse_metrics_snapshot()
    sp.record_sparse_lookup(100, 128, dedup_rows=50, table='t0')
    after = sp.sparse_metrics_snapshot()
    assert after['sparse_lookup_ids_total'] - \
        before['sparse_lookup_ids_total'] == 100
    assert after['sparse_grad_rows_total'] - \
        before['sparse_grad_rows_total'] == 128
    g = registry.gauge('sparse_dedup_ratio', '')
    assert g.labels(table='t0').value == pytest.approx(2.0)
