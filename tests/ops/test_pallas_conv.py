"""Conv-efficiency kernels (ops/pallas_conv.py): exact parity of the
space-to-depth stem re-layout and the fused 1×1 conv+BN+act kernel
(pallas interpret mode on CPU) against the reference formulations."""
import numpy as np
import pytest

import jax.numpy as jnp

from paddle_tpu.ops.nn_ops import conv2d
from paddle_tpu.ops.pallas_conv import (stem_space_to_depth,
                                        fused_conv1x1_bn_act)


@pytest.mark.parametrize('hw', [224, 32, 30])
def test_stem_s2d_exact_parity(hw):
    rng = np.random.RandomState(0)
    x = rng.randn(2, hw, hw, 3).astype(np.float32)
    w = (rng.randn(7, 7, 3, 8) * 0.1).astype(np.float32)
    want = np.asarray(conv2d(x, w, stride=2, padding=3,
                             data_format='NHWC'))
    got = np.asarray(stem_space_to_depth(x, w, data_format='NHWC'))
    assert got.shape == want.shape, (got.shape, want.shape)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_stem_s2d_grad_flows():
    import jax
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(1, 16, 16, 3).astype(np.float32))
    w = jnp.asarray((rng.randn(7, 7, 3, 4) * 0.1).astype(np.float32))

    g_s2d = jax.grad(lambda w: jnp.sum(
        stem_space_to_depth(x, w, data_format='NHWC') ** 2))(w)
    g_ref = jax.grad(lambda w: jnp.sum(
        conv2d(x, w, stride=2, padding=3, data_format='NHWC') ** 2))(w)
    np.testing.assert_allclose(np.asarray(g_s2d), np.asarray(g_ref),
                               rtol=1e-3, atol=1e-4)


@pytest.mark.parametrize('act', [None, 'relu'])
def test_fused_conv1x1_pallas_interpret_parity(act):
    rng = np.random.RandomState(2)
    b, hw, c, o = 2, 8, 16, 12
    x = rng.randn(b, hw, hw, c).astype(np.float32)
    w = (rng.randn(1, 1, c, o) * 0.2).astype(np.float32)
    scale = (rng.rand(o) + 0.5).astype(np.float32)
    shift = (rng.randn(o) * 0.1).astype(np.float32)
    want = np.asarray(conv2d(x, w, stride=1, padding=0,
                             data_format='NHWC')) * scale + shift
    if act == 'relu':
        want = np.maximum(want, 0.0)
    got = np.asarray(fused_conv1x1_bn_act(x, w, scale, shift, act=act,
                                          force_pallas=True))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_fused_conv1x1_xla_fallback_matches():
    rng = np.random.RandomState(3)
    x = rng.randn(1, 4, 4, 8).astype(np.float32)
    w = (rng.randn(1, 1, 8, 6) * 0.2).astype(np.float32)
    scale = np.ones(6, np.float32)
    shift = np.zeros(6, np.float32)
    a = np.asarray(fused_conv1x1_bn_act(x, w, scale, shift, act='relu',
                                        force_pallas=True))
    b = np.asarray(fused_conv1x1_bn_act(x, w, scale, shift, act='relu',
                                        force_pallas=False))
    np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_resnet_stem_s2d_model_parity():
    """ResNet NHWC with the s2d stem produces the same forward as without
    (same weights — checkpoint compatible by construction)."""
    from paddle_tpu import dygraph
    from paddle_tpu.models.resnet import ConvBNLayer
    from paddle_tpu.dygraph.tape import Tensor
    rng = np.random.RandomState(4)
    x = rng.randn(2, 31, 31, 3).astype(np.float32)
    with dygraph.guard():
        from paddle_tpu.core.random import seed
        seed(0)
        plain = ConvBNLayer(3, 8, 7, stride=2, act='relu',
                            data_format='NHWC')
        seed(0)
        s2d = ConvBNLayer(3, 8, 7, stride=2, act='relu',
                          data_format='NHWC', space_to_depth=True)
        plain.eval()
        s2d.eval()
        # identical init (same seed) → identical outputs if the layout
        # transform is exact
        y0 = np.asarray(plain(Tensor(x, stop_gradient=True)).numpy())
        y1 = np.asarray(s2d(Tensor(x, stop_gradient=True)).numpy())
    np.testing.assert_allclose(y1, y0, rtol=1e-4, atol=1e-4)


def test_stem_s2d_requires_nhwc_7x7():
    from paddle_tpu.models.resnet import ConvBNLayer
    from paddle_tpu import dygraph
    with dygraph.guard():
        with pytest.raises(ValueError, match='space_to_depth'):
            ConvBNLayer(3, 8, 3, stride=1, data_format='NHWC',
                        space_to_depth=True)
