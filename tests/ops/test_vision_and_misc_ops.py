"""ROI family, deformable conv, and misc long-tail ops vs numpy references."""
import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers


def _run(build, feed=None):
    main, start = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, start):
        outs = build()
        outs = outs if isinstance(outs, (list, tuple)) else [outs]
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(start)
        return exe.run(main, feed=feed or {}, fetch_list=list(outs))


def test_roi_pool_identity_bin():
    # one roi covering a 2x2 region, 1x1 pooling → max of region
    x = np.arange(16, dtype='float32').reshape(1, 1, 4, 4)
    rois = np.array([[0, 0, 1, 1]], dtype='float32')  # x1,y1,x2,y2

    def build():
        xv = layers.data('x', shape=[1, 4, 4], dtype='float32')
        rv = layers.data('rois', shape=[4], dtype='float32')
        return layers.roi_pool(xv, rv, 1, 1, 1.0)

    out, = _run(build, {'x': x, 'rois': rois})
    assert out.shape == (1, 1, 1, 1)
    assert float(out[0, 0, 0, 0]) == 5.0  # max of [[0,1],[4,5]]


def test_roi_pool_bins():
    x = np.arange(16, dtype='float32').reshape(1, 1, 4, 4)
    rois = np.array([[0, 0, 3, 3]], dtype='float32')

    def build():
        xv = layers.data('x', shape=[1, 4, 4], dtype='float32')
        rv = layers.data('rois', shape=[4], dtype='float32')
        return layers.roi_pool(xv, rv, 2, 2, 1.0)

    out, = _run(build, {'x': x, 'rois': rois})
    np.testing.assert_allclose(out[0, 0], [[5, 7], [13, 15]])


def test_roi_align_center():
    x = np.ones((1, 2, 6, 6), dtype='float32') * 3.0
    rois = np.array([[1, 1, 4, 4]], dtype='float32')

    def build():
        xv = layers.data('x', shape=[2, 6, 6], dtype='float32')
        rv = layers.data('rois', shape=[4], dtype='float32')
        return layers.roi_align(xv, rv, 2, 2, 1.0, sampling_ratio=2)

    out, = _run(build, {'x': x, 'rois': rois})
    np.testing.assert_allclose(out, np.full((1, 2, 2, 2), 3.0), rtol=1e-6)


def test_psroi_pool_channel_select():
    # C = oc * ph * pw = 1*2*2; constant per channel → out[0,i,j] = const of ch i*2+j
    x = np.stack([np.full((4, 4), c, 'float32') for c in range(4)])[None]
    rois = np.array([[0, 0, 3, 3]], dtype='float32')

    def build():
        xv = layers.data('x', shape=[4, 4, 4], dtype='float32')
        rv = layers.data('rois', shape=[4], dtype='float32')
        return layers.psroi_pool(xv, rv, 1, 1.0, 2, 2)

    out, = _run(build, {'x': x, 'rois': rois})
    np.testing.assert_allclose(out[0, 0], [[0, 1], [2, 3]], rtol=1e-6)


def test_deformable_conv_zero_offset_matches_conv():
    rng = np.random.RandomState(0)
    x = rng.randn(1, 3, 5, 5).astype('float32')
    kh = kw = 3

    def build():
        xv = layers.data('x', shape=[3, 5, 5], dtype='float32')
        off = layers.zeros([1, 2 * kh * kw, 5, 5], 'float32')
        mask = layers.ones([1, kh * kw, 5, 5], 'float32')
        out = layers.deformable_conv(xv, off, mask, 4, 3, padding=1,
                                     param_attr=fluid.ParamAttr(
                                         initializer=fluid.initializer.
                                         ConstantInitializer(0.1)),
                                     bias_attr=False)
        ref = layers.conv2d(xv, 4, 3, padding=1,
                            param_attr=fluid.ParamAttr(
                                initializer=fluid.initializer.
                                ConstantInitializer(0.1)),
                            bias_attr=False)
        return out, ref

    out, ref = _run(build, {'x': x})
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_scatter_nd_shape_rank_size_sum():
    def build():
        idx = layers.assign(np.array([[1], [3]], 'int32'))
        upd = layers.assign(np.array([9.0, 10.0], 'float32'))
        s = layers.scatter_nd(idx, upd, [5])
        xv = layers.assign(np.zeros((2, 3), 'float32'))
        return s, layers.shape(xv), layers.rank(xv), layers.size(xv), \
            layers.sum([upd, upd])

    s, shp, rk, sz, sm = _run(build)
    np.testing.assert_allclose(s, [0, 9, 0, 10, 0])
    assert list(shp) == [2, 3] and int(rk) == 2 and int(sz) == 6
    np.testing.assert_allclose(sm, [18.0, 20.0])


def test_hash_deterministic_in_range():
    def build():
        xv = layers.assign(np.array([[1, 2], [1, 2], [3, 4]], 'int64'))
        return layers.hash(xv, hash_size=1000, num_hash=2)

    h, = _run(build)
    assert h.shape == (3, 2, 1)
    assert (h >= 0).all() and (h < 1000).all()
    assert (h[0] == h[1]).all() and not (h[0] == h[2]).all()


def test_similarity_focus():
    x = np.zeros((1, 2, 2, 2), 'float32')
    x[0, 0] = [[5.0, 1.0], [2.0, 4.0]]   # greedy: (0,0) then (1,1)

    def build():
        xv = layers.data('x', shape=[2, 2, 2], dtype='float32')
        return layers.similarity_focus(xv, axis=1, indexes=[0])

    out, = _run(build, {'x': x})
    want = np.zeros((1, 2, 2, 2), 'float32')
    want[:, :, 0, 0] = 1
    want[:, :, 1, 1] = 1
    np.testing.assert_allclose(out, want)


def test_cvm_and_filter_by_instag():
    def build():
        xv = layers.assign(np.arange(8, dtype='float32').reshape(2, 4))
        cv = layers.assign(np.array([[1.0, 0.0], [3.0, 1.0]], 'float32'))
        kept = layers.continuous_value_model(xv, cv, use_cvm=False)
        ins = layers.assign(np.arange(6, dtype='float32').reshape(3, 2))
        tags = layers.assign(np.array([[1], [2], [3]], 'int64'))
        filt = layers.assign(np.array([1, 3], 'int64'))
        out, w, _ = layers.filter_by_instag(ins, tags, filt)
        return kept, out, w

    kept, out, w = _run(build)
    np.testing.assert_allclose(kept, [[2, 3], [6, 7]])
    np.testing.assert_allclose(w[:, 0], [1, 0, 1])
    np.testing.assert_allclose(out[1], [0, 0])


def test_crf_layers_end_to_end():
    B, T, N = 2, 4, 3
    rng = np.random.RandomState(1)
    em = rng.randn(B, T, N).astype('float32')
    lab = rng.randint(0, N, (B, T)).astype('int64')

    def build():
        ev = layers.data('em', shape=[T, N], dtype='float32')
        lv = layers.data('lab', shape=[T], dtype='int64')
        nll = layers.linear_chain_crf(ev, lv,
                                      param_attr=fluid.ParamAttr(name='crf_w'))
        path = layers.crf_decoding(ev, 'crf_w')
        return nll, path

    nll, path = _run(build, {'em': em, 'lab': lab})
    assert nll.shape == (B, 1) and (nll > 0).all()
    assert path.shape == (B, T)


def test_py_func_callback():
    def double_plus_one(a):
        return np.asarray(a) * 2 + 1

    main, start = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, start):
        x = layers.data('x', shape=[3], dtype='float32',
                        append_batch_size=False)
        out = main.global_block().create_var(
            name='pyfunc_out', shape=[3], dtype='float32')
        layers.py_func(double_plus_one, x, out)
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(start)
        r, = exe.run(main, feed={'x': np.array([1, 2, 3], 'float32')},
                     fetch_list=[out])
    np.testing.assert_allclose(r, [3, 5, 7])


def test_lod_reset_feeds_sequence_ops():
    main, start = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, start):
        x = layers.data('x', shape=[3, 2], dtype='float32')
        x2 = layers.lod_reset(x, target_lod=[0, 1, 3])
        # lengths [1, 2] — mean over valid steps only
        pooled = layers.sequence_pool(x2, 'average')
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(start)
        xin = np.arange(12, dtype='float32').reshape(2, 3, 2)
        r, = exe.run(main, feed={'x': xin}, fetch_list=[pooled])
    np.testing.assert_allclose(r[0], xin[0, 0])
    np.testing.assert_allclose(r[1], xin[1, :2].mean(0))


def test_ctc_greedy_decoder_masks_pad_frames():
    B, T, C = 2, 4, 3   # blank = 2
    x = np.zeros((B, T, C), 'float32')
    x[0, :, 0] = 1.0                    # row 0: 0,0,0,0 → merges to [0]
    x[1, 0, 1] = 1.0                    # row 1: 1,(pad frames argmax 1...)
    x[1, 1:, 1] = 1.0

    def build():
        xv = layers.data('x', shape=[T, C], dtype='float32')
        lv = layers.data('lens', shape=[1], dtype='int64')
        return layers.ctc_greedy_decoder(xv, blank=2, input_length=lv)

    main, start = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, start):
        out, lens = build()
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(start)
        o, l = exe.run(main, feed={'x': x, 'lens': np.array([4, 1], 'int64')},
                       fetch_list=[out, lens])
    assert list(l) == [1, 1]
    assert o[0][0] == 0 and o[1][0] == 1
    assert (o[:, 1:] == -1).all()


def test_chunk_eval_masks_padding():
    # one chunk in row 0 (B-0 at t=0), padding after t=1 would fake chunks
    inf = np.array([[0, 1, 0, 0]], 'int64')   # B-0 I-0 B-0 B-0
    lab = np.array([[0, 1, 0, 0]], 'int64')

    def run(with_len):
        main, start = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, start):
            iv = layers.data('inf', shape=[4], dtype='int64')
            lv = layers.data('lab', shape=[4], dtype='int64')
            args = dict(seq_length=layers.assign(np.array([2], 'int64'))) \
                if with_len else {}
            outs = layers.chunk_eval(iv, lv, 'IOB', 1, **args)
        exe = fluid.Executor()
        with fluid.scope_guard(fluid.Scope()):
            exe.run(start)
            return exe.run(main, feed={'inf': inf, 'lab': lab},
                           fetch_list=list(outs))

    full = run(False)
    masked = run(True)
    assert int(full[3]) == 3      # unmasked: 3 inferred chunks
    assert int(masked[3]) == 1    # masked to length 2: just the B-0 I-0 chunk
    assert float(masked[0]) == 1.0 and float(masked[1]) == 1.0


def test_fused_attention_matches_reference():
    """fused_attention (XLA fallback on CPU) == explicit softmax(QK^T)V,
    with bias and causal masking."""
    import numpy as np
    import jax.numpy as jnp
    import jax
    from paddle_tpu.ops.registry import get_op
    rng = np.random.RandomState(0)
    b, h, s, d = 2, 3, 8, 16
    q, k, v = (rng.standard_normal((b, h, s, d)).astype(np.float32) * 0.5
               for _ in range(3))
    bias = rng.standard_normal((b, h, s, s)).astype(np.float32)
    scale = 1.0 / np.sqrt(d)
    out = np.asarray(get_op('fused_attention').fn(q, k, v, bias,
                                                  sm_scale=scale))
    scores = np.einsum('bhqd,bhkd->bhqk', q, k) * scale + bias
    probs = np.asarray(jax.nn.softmax(jnp.asarray(scores), axis=-1))
    ref = np.einsum('bhqk,bhkd->bhqd', probs, v)
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)
    # causal: upper-triangle keys must not contribute
    outc = np.asarray(get_op('fused_attention').fn(q, k, v, None,
                                                   sm_scale=scale,
                                                   causal=True))
    scores2 = np.einsum('bhqd,bhkd->bhqk', q, k) * scale
    mask = np.tril(np.ones((s, s), bool))
    scores2 = np.where(mask, scores2, -1e30)
    probs2 = np.asarray(jax.nn.softmax(jnp.asarray(scores2), axis=-1))
    ref2 = np.einsum('bhqk,bhkd->bhqd', probs2, v)
    np.testing.assert_allclose(outc, ref2, rtol=1e-5, atol=1e-5)
