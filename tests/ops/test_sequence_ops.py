"""Sequence ops: masked padded-batch formulation vs numpy references."""
import numpy as np

from paddle_tpu.ops import sequence_ops as S


def test_sequence_mask():
    out = S.sequence_mask.__wrapped__ if hasattr(S.sequence_mask, '__wrapped__') \
        else S.sequence_mask
    r = np.asarray(S.sequence_mask(np.array([2, 0, 3]), maxlen=4))
    np.testing.assert_array_equal(
        r, [[1, 1, 0, 0], [0, 0, 0, 0], [1, 1, 1, 0]])


def test_sequence_softmax_masked():
    x = np.array([[1.0, 2.0, 3.0], [5.0, 1.0, 7.0]], np.float32)
    r = np.asarray(S.sequence_softmax(x, np.array([3, 2])))
    np.testing.assert_allclose(r[0], np.exp(x[0]) / np.exp(x[0]).sum(),
                               rtol=1e-5)
    e = np.exp(x[1, :2])
    np.testing.assert_allclose(r[1, :2], e / e.sum(), rtol=1e-5)
    assert r[1, 2] == 0.0


def test_sequence_pool_variants():
    x = np.arange(12, dtype=np.float32).reshape(2, 3, 2)
    lens = np.array([2, 3])
    avg, _ = S.sequence_pool(x, lens, pool_type='average')
    np.testing.assert_allclose(np.asarray(avg)[0], x[0, :2].mean(0), rtol=1e-6)
    mx, idx = S.sequence_pool(x, lens, pool_type='max')
    np.testing.assert_allclose(np.asarray(mx)[0], x[0, :2].max(0), rtol=1e-6)
    last, _ = S.sequence_pool(x, lens, pool_type='last')
    np.testing.assert_allclose(np.asarray(last)[0], x[0, 1], rtol=1e-6)
    np.testing.assert_allclose(np.asarray(last)[1], x[1, 2], rtol=1e-6)
    first, _ = S.sequence_pool(x, lens, pool_type='first')
    np.testing.assert_allclose(np.asarray(first)[1], x[1, 0], rtol=1e-6)


def test_sequence_reverse():
    x = np.arange(8, dtype=np.float32).reshape(2, 4, 1)
    r = np.asarray(S.sequence_reverse(x, np.array([3, 4])))
    np.testing.assert_allclose(r[0, :, 0], [2, 1, 0, 3])
    np.testing.assert_allclose(r[1, :, 0], [7, 6, 5, 4])


def test_sequence_concat_left_packs():
    a = np.array([[[1.], [2.], [0.]], [[5.], [0.], [0.]]], np.float32)
    b = np.array([[[3.], [0.]], [[6.], [7.]]], np.float32)
    out, out_len = S.sequence_concat([a, b], [np.array([2, 1]),
                                              np.array([1, 2])])
    out = np.asarray(out)
    np.testing.assert_allclose(out[0, :3, 0], [1, 2, 3])
    np.testing.assert_allclose(out[1, :3, 0], [5, 6, 7])
    np.testing.assert_array_equal(np.asarray(out_len), [3, 3])


def test_sequence_pad_unpad_roundtrip():
    x = np.ones((2, 3, 2), np.float32)
    out, lens = S.sequence_pad(x, 9.0, np.array([1, 3]), maxlen=4)
    out = np.asarray(out)
    assert out.shape == (2, 4, 2)
    assert (out[0, 1:] == 9.0).all() and (out[0, 0] == 1.0).all()
    assert (out[1, 3] == 9.0).all()
    unp = np.asarray(S.sequence_unpad(out, np.array([1, 3])))
    assert (unp[0, 1:] == 0).all() and (unp[0, 0] == 1).all()


def test_sequence_reshape():
    x = np.arange(12, dtype=np.float32).reshape(2, 3, 2)
    out, new_len = S.sequence_reshape(x, np.array([2, 3]), new_dim=3)
    out = np.asarray(out)
    assert out.shape == (2, 2, 3)
    np.testing.assert_allclose(out[0, 0], [0, 1, 2])
    # row 0 had 2*2=4 valid elems → 4/3 isn't integral; ref requires
    # divisibility — we just check row 1 (3*2=6 → 2 rows of 3)
    np.testing.assert_allclose(out[1].reshape(-1), x[1].reshape(-1))
    assert np.asarray(new_len)[1] == 2


def test_sequence_slice():
    x = np.arange(10, dtype=np.float32).reshape(2, 5, 1)
    out, lens = S.sequence_slice(x, np.array([1, 2]), np.array([2, 3]))
    out = np.asarray(out)
    np.testing.assert_allclose(out[0, :2, 0], [1, 2])
    assert (out[0, 2:] == 0).all()
    np.testing.assert_allclose(out[1, :3, 0], [7, 8, 9])


def test_sequence_expand_as():
    x = np.array([[[1.0, 2.0]], [[3.0, 4.0]]], np.float32)  # (B,1,D)
    y = np.zeros((2, 3, 5), np.float32)
    out = np.asarray(S.sequence_expand_as(x, y, np.array([2, 3])))
    np.testing.assert_allclose(out[0, 0], [1, 2])
    np.testing.assert_allclose(out[0, 1], [1, 2])
    assert (out[0, 2] == 0).all()
    np.testing.assert_allclose(out[1, 2], [3, 4])


def test_sequence_enumerate():
    x = np.array([[1, 2, 3, 4]], np.int64)
    out = np.asarray(S.sequence_enumerate(x, np.array([3]), win_size=2,
                                          pad_value=0))
    np.testing.assert_array_equal(out[0, 0], [1, 2])
    np.testing.assert_array_equal(out[0, 1], [2, 3])
    np.testing.assert_array_equal(out[0, 2], [3, 0])


def test_sequence_scatter():
    x = np.zeros((1, 5), np.float32)
    idx = np.array([[1, 3, 3]], np.int64)
    upd = np.array([[10.0, 20.0, 5.0]], np.float32)
    out = np.asarray(S.sequence_scatter(x, idx, upd, np.array([3])))
    np.testing.assert_allclose(out[0], [0, 10, 0, 25, 0])


def test_sequence_conv_shape():
    x = np.random.RandomState(0).randn(2, 5, 3).astype(np.float32)
    w = np.random.RandomState(1).randn(9, 4).astype(np.float32)
    out = np.asarray(S.sequence_conv(x, w, None, np.array([5, 2])))
    assert out.shape == (2, 5, 4)
    assert (out[1, 2:] == 0).all()
    # middle step of a full row sees [x0,x1,x2] context
    ctx = np.concatenate([x[0, 0], x[0, 1], x[0, 2]])
    np.testing.assert_allclose(out[0, 1], ctx @ w, rtol=2e-5, atol=1e-5)
