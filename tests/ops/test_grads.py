"""OpTest-style gradient checks over the op registry (SURVEY §4; ref
python/paddle/fluid/tests/unittests/op_test.py:1261 check_grad).

Every registered op must be classified: either a GRAD_SPECS entry (finite
difference check via jax.test_util.check_grads on small shapes) or a
NONDIFF entry with a reason string. A completeness guard fails when a new
op lands unclassified."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.test_util import check_grads

import paddle_tpu  # noqa: F401  (registers all ops)
from paddle_tpu.ops.registry import _REGISTRY, get_op

R = np.random.RandomState


def f32(a):
    return np.asarray(a, np.float32)


def away(rng, shape, lo=0.2, hi=1.0):
    """Floats bounded away from 0 (kink-free for abs/relu/sign-like ops)."""
    return f32(rng.uniform(lo, hi, shape) * np.where(
        rng.rand(*shape) < 0.5, -1.0, 1.0))


def pos(rng, shape, lo=0.3, hi=2.0):
    return f32(rng.uniform(lo, hi, shape))


def probs(rng, shape):
    x = rng.uniform(0.1, 1.0, shape)
    return f32(x / x.sum(-1, keepdims=True))


def S(args, diff=(0,), attrs=None, tol=2e-2, eps=None):
    return {'args': args, 'diff': diff, 'attrs': attrs or {}, 'tol': tol,
            'eps': eps}


def _std(shape):
    return lambda rng: [f32(rng.standard_normal(shape))]


# ---------------------------------------------------------------------------
# differentiable ops: name → spec(args builder, diff arg indices, attrs)
# ---------------------------------------------------------------------------
GRAD_SPECS = {
    # --- contrib text-matching ops ---
    'match_matrix_tensor': S(
        lambda r: [f32(r.standard_normal((2, 3, 4))),
                   f32(r.standard_normal((2, 5, 4))),
                   f32(r.standard_normal((4, 2, 4)))],
        diff=(0, 1, 2), attrs={'channel_num': 2}),
    'var_conv_2d': S(
        lambda r: [f32(r.standard_normal((2, 2, 5, 5))),
                   f32(r.standard_normal((3, 2, 3, 3)))],
        diff=(0, 1), attrs={'stride': 1}),
    'sequence_topk_avg_pooling': S(
        lambda r: [f32(0.1 * np.arange(48).reshape(2, 2, 3, 4) +
                       r.uniform(0, 0.03, (2, 2, 3, 4)))],
        attrs={'topks': [1, 2], 'channel_num': 2}),
    'fused_embedding_seq_pool': S(
        lambda r: [np.array([[1, 2, 0], [3, 4, 5]], np.int64),
                   f32(r.standard_normal((7, 4)))],
        diff=(1,), attrs={'combiner': 'mean'}),
    'search_pyramid_hash': S(
        lambda r: [np.array([[3, 4, 5, 6], [8, 9, 1, 2]], np.int64),
                   f32(r.standard_normal((64, 8)))],
        diff=(1,),
        attrs={'num_emb': 8, 'space_len': 64, 'pyramid_layer': 3,
               'rand_len': 8, 'drop_out_percent': 0.0,
               'is_training': False, 'seed': 1}),
    # --- unary elementwise ---
    'abs': S(lambda r: [away(r, (3, 4))]),
    'acos': S(lambda r: [f32(r.uniform(-0.8, 0.8, (3, 4)))]),
    'asin': S(lambda r: [f32(r.uniform(-0.8, 0.8, (3, 4)))]),
    'atan': S(_std((3, 4))),
    'brelu': S(lambda r: [pos(r, (3, 4), 1.0, 5.0)]),
    'cos': S(_std((3, 4))),
    'cumsum': S(_std((3, 4)), attrs={'axis': 1}),
    'cosh': S(_std((3, 4))),
    'elu': S(lambda r: [away(r, (3, 4))]),
    'erf': S(_std((3, 4))),
    'exp': S(_std((3, 4))),
    'gelu': S(_std((3, 4))),
    'hard_shrink': S(lambda r: [away(r, (3, 4), 0.7, 1.5)]),
    'hard_sigmoid': S(lambda r: [f32(r.uniform(-1.5, 1.5, (3, 4)))]),
    'hard_swish': S(lambda r: [f32(r.uniform(-2.0, 2.0, (3, 4)))]),
    'leaky_relu': S(lambda r: [away(r, (3, 4))]),
    'log': S(lambda r: [pos(r, (3, 4))]),
    'log_softmax': S(_std((3, 4))),
    'logsigmoid': S(_std((3, 4))),
    'logsumexp': S(_std((3, 4))),
    'mean': S(_std((3, 4))),
    'pow': S(lambda r: [pos(r, (3, 4))], attrs={'factor': 1.7}),
    'reciprocal': S(lambda r: [pos(r, (3, 4), 0.5, 2.0)]),
    'relu': S(lambda r: [away(r, (3, 4))]),
    # fused (add, act) pair from the IR pass pipeline: x + y kept away
    # from relu's kink by construction
    'fused_elemwise_add_activation': S(
        lambda r: [away(r, (3, 4), 1.0, 2.0),
                   f32(r.uniform(-0.3, 0.3, (3, 4)))],
        diff=(0, 1), attrs={'functor': 'relu'}),
    'relu6': S(lambda r: [pos(r, (3, 4), 0.5, 5.0)]),
    'rsqrt': S(lambda r: [pos(r, (3, 4))]),
    'scale': S(_std((3, 4)), attrs={'scale': 2.5, 'bias': 0.3}),
    'selu': S(lambda r: [away(r, (3, 4))]),
    'sigmoid': S(_std((3, 4))),
    'sin': S(_std((3, 4))),
    'sinh': S(_std((3, 4))),
    'soft_relu': S(_std((3, 4))),
    'softmax': S(_std((3, 4))),
    'softplus': S(_std((3, 4))),
    'softshrink': S(lambda r: [away(r, (3, 4), 0.8, 1.5)]),
    'softsign': S(_std((3, 4))),
    'sqrt': S(lambda r: [pos(r, (3, 4))]),
    'square': S(_std((3, 4))),
    'stanh': S(_std((3, 4))),
    'swish': S(_std((3, 4))),
    'tanh': S(_std((3, 4))),
    'tanh_shrink': S(_std((3, 4))),
    'thresholded_relu': S(lambda r: [pos(r, (3, 4), 1.3, 2.0)]),
    'increment': S(_std((1,))),
    'clip': S(lambda r: [f32(r.uniform(-0.8, 0.8, (3, 4)))],
              attrs={'min': -1.0, 'max': 1.0}),
    'clip_by_norm': S(_std((3, 4)), attrs={'max_norm': 1.0}),
    'l2_normalize': S(lambda r: [away(r, (3, 4), 0.5, 1.5)]),
    'norm': S(lambda r: [away(r, (3, 4), 0.5, 1.5)]),
    'add_position_encoding': S(_std((2, 3, 8))),
    'label_smooth': S(lambda r: [probs(r, (3, 4)), None],
                      attrs={'epsilon': 0.1}),
    # --- binary / broadcast ---
    'elementwise_add': S(lambda r: [f32(r.standard_normal((3, 4))),
                                    f32(r.standard_normal((3, 4)))],
                         diff=(0, 1)),
    'elementwise_sub': S(lambda r: [f32(r.standard_normal((3, 4))),
                                    f32(r.standard_normal((3, 4)))],
                         diff=(0, 1)),
    'elementwise_mul': S(lambda r: [f32(r.standard_normal((3, 4))),
                                    f32(r.standard_normal((3, 4)))],
                         diff=(0, 1)),
    'elementwise_div': S(lambda r: [f32(r.standard_normal((3, 4))),
                                    pos(r, (3, 4), 0.5, 2.0)], diff=(0, 1)),
    'elementwise_max': S(lambda r: [f32(r.uniform(1.0, 2.0, (3, 4))),
                                    f32(r.uniform(-2.0, -1.0, (3, 4)))],
                         diff=(0, 1)),
    'elementwise_min': S(lambda r: [f32(r.uniform(1.0, 2.0, (3, 4))),
                                    f32(r.uniform(-2.0, -1.0, (3, 4)))],
                         diff=(0, 1)),
    'elementwise_pow': S(lambda r: [pos(r, (3, 4)), pos(r, (3, 4))],
                         diff=(0, 1)),
    'elementwise_mod': S(lambda r: [pos(r, (3, 4), 5.0, 9.0),
                                    pos(r, (3, 4), 1.8, 2.2)], diff=(0,)),
    'matmul': S(lambda r: [f32(r.standard_normal((3, 4))),
                           f32(r.standard_normal((4, 5)))], diff=(0, 1)),
    'mul': S(lambda r: [f32(r.standard_normal((3, 4))),
                        f32(r.standard_normal((4, 5)))], diff=(0, 1)),
    'dot': S(lambda r: [f32(r.standard_normal((3, 4))),
                        f32(r.standard_normal((3, 4)))], diff=(0, 1)),
    'kron': S(lambda r: [f32(r.standard_normal((2, 3))),
                         f32(r.standard_normal((3, 2)))], diff=(0, 1)),
    'fsp': S(lambda r: [f32(r.standard_normal((1, 2, 4, 4))),
                        f32(r.standard_normal((1, 3, 4, 4)))], diff=(0, 1)),
    'cos_sim': S(lambda r: [away(r, (3, 4), 0.5, 1.5),
                            away(r, (3, 4), 0.5, 1.5)], diff=(0, 1)),
    'bilinear_tensor_product': S(
        lambda r: [f32(r.standard_normal((2, 3))),
                   f32(r.standard_normal((2, 4))),
                   f32(r.standard_normal((5, 3, 4)) * 0.3), None],
        diff=(0, 1, 2)),
    'prelu': S(lambda r: [away(r, (3, 4)), f32([0.25])], diff=(0, 1)),
    'fused_attention': S(
        lambda r: [f32(r.standard_normal((1, 2, 4, 8)) * 0.3),
                   f32(r.standard_normal((1, 2, 4, 8)) * 0.3),
                   f32(r.standard_normal((1, 2, 4, 8)) * 0.3), None],
        diff=(0, 1, 2), attrs={'sm_scale': 0.35}),
    # --- reductions ---
    'reduce_sum': S(_std((3, 4))),
    'reduce_mean': S(_std((3, 4))),
    'reduce_max': S(lambda r: [f32(np.arange(12).reshape(3, 4)
                                   + r.uniform(0, 0.3, (3, 4)))]),
    'reduce_min': S(lambda r: [f32(np.arange(12).reshape(3, 4)
                                   + r.uniform(0, 0.3, (3, 4)))]),
    'reduce_prod': S(lambda r: [pos(r, (3, 4), 0.5, 1.5)]),
    'sum': S(lambda r: [[f32(r.standard_normal((3, 4))),
                         f32(r.standard_normal((3, 4)))]], diff=()),
    # --- losses ---
    'cross_entropy': S(lambda r: [probs(r, (3, 5)),
                                  r.randint(0, 5, (3, 1)).astype(np.int64)]),
    'softmax_with_cross_entropy': S(
        lambda r: [f32(r.standard_normal((3, 5))),
                   r.randint(0, 5, (3, 1)).astype(np.int64)]),
    'sigmoid_cross_entropy_with_logits': S(
        lambda r: [f32(r.standard_normal((3, 4))),
                   f32(r.randint(0, 2, (3, 4)))]),
    'sigmoid_focal_loss': S(
        lambda r: [f32(r.standard_normal((4, 3))),
                   r.randint(0, 4, (4, 1)).astype(np.int64),
                   np.asarray([2], np.int32)],
        attrs={'gamma': 2.0, 'alpha': 0.25}),
    'square_error_cost': S(lambda r: [f32(r.standard_normal((3, 4))),
                                      f32(r.standard_normal((3, 4)))],
                           diff=(0, 1)),
    'smooth_l1_loss': S(lambda r: [f32(r.standard_normal((3, 4))),
                                   f32(r.standard_normal((3, 4)) + 3.0),
                                   None, None], diff=(0, 1)),
    'huber_loss': S(lambda r: [f32(r.standard_normal((3, 1))),
                               f32(r.standard_normal((3, 1)) + 3.0)],
                    diff=(0, 1)),
    'kldiv_loss': S(lambda r: [np.log(probs(r, (3, 4))),
                               probs(r, (3, 4))], attrs={'reduction': 'mean'}),
    'log_loss': S(lambda r: [f32(r.uniform(0.15, 0.85, (3, 1))),
                             f32(r.randint(0, 2, (3, 1)))]),
    'bpr_loss': S(lambda r: [f32(r.standard_normal((3, 4))),
                             r.randint(0, 4, (3, 1)).astype(np.int64)]),
    'rank_loss': S(lambda r: [f32(r.randint(0, 2, (3, 1))),
                              f32(r.standard_normal((3, 1))),
                              f32(r.standard_normal((3, 1)))], diff=(1, 2)),
    'margin_rank_loss': S(lambda r: [f32(np.where(r.rand(3, 1) < .5, -1, 1)),
                                     f32(r.standard_normal((3, 1)) + 2),
                                     f32(r.standard_normal((3, 1)) - 2)],
                          diff=(1, 2)),
    'dice_loss': S(lambda r: [probs(r, (4, 3)),
                              r.randint(0, 3, (4, 1)).astype(np.int64)]),
    'teacher_student_sigmoid_loss': S(
        lambda r: [f32(r.standard_normal((4, 1))),
                   f32(r.uniform(0.1, 0.9, (4, 1)))]),
    'center_loss': S(
        lambda r: [f32(r.standard_normal((4, 6))),
                   r.randint(0, 5, (4, 1)).astype(np.int64),
                   f32(r.standard_normal((5, 6))), f32([0.5])],
        attrs={'cluster_num': 5, 'need_update': False}),
    'hsigmoid': S(lambda r: [f32(r.standard_normal((3, 4))),
                             r.randint(0, 6, (3, 1)).astype(np.int64),
                             f32(r.standard_normal((5, 4)) * 0.3),
                             f32(r.standard_normal((5,)) * 0.1)],
                  diff=(0, 2, 3), attrs={'num_classes': 6}),
    'warpctc': S(lambda r: [f32(r.standard_normal((6, 2, 5))),
                            r.randint(1, 5, (2, 3)).astype(np.int64),
                            np.asarray([6, 5], np.int64),
                            np.asarray([3, 2], np.int64)],
                 attrs={'blank': 0}, tol=4e-2),
    'linear_chain_crf': S(
        lambda r: [f32(r.standard_normal((2, 5, 4))),
                   f32(r.standard_normal((6, 4)) * 0.3),
                   r.randint(0, 4, (2, 5)).astype(np.int64),
                   np.asarray([5, 3], np.int64)],
        diff=(0, 1), tol=4e-2),
    # --- nn ---
    'conv2d': S(lambda r: [f32(r.standard_normal((1, 2, 5, 5))),
                           f32(r.standard_normal((3, 2, 3, 3)) * 0.3)],
                diff=(0, 1)),
    'conv2d_stem_s2d': S(lambda r: [
        f32(r.standard_normal((1, 15, 15, 3))),
        f32(r.standard_normal((7, 7, 3, 4)) * 0.2)], diff=(0, 1)),
    'fused_conv1x1_bn_act': S(lambda r: [
        f32(r.standard_normal((1, 4, 4, 6))),
        f32(r.standard_normal((1, 1, 6, 5)) * 0.3),
        f32(r.random(5) + 0.5), f32(r.standard_normal(5) * 0.1)],
        diff=(0, 1, 2, 3)),
    'conv2d_transpose': S(lambda r: [f32(r.standard_normal((1, 2, 4, 4))),
                                     f32(r.standard_normal((2, 3, 3, 3))
                                         * 0.3)], diff=(0, 1)),
    'conv3d': S(lambda r: [f32(r.standard_normal((1, 1, 4, 4, 4))),
                           f32(r.standard_normal((2, 1, 3, 3, 3)) * 0.3)],
                diff=(0, 1)),
    'conv3d_transpose': S(lambda r: [f32(r.standard_normal((1, 2, 3, 3, 3))),
                                     f32(r.standard_normal((2, 2, 3, 3, 3))
                                         * 0.3)], diff=(0, 1)),
    'deformable_conv': S(
        lambda r: [f32(r.standard_normal((1, 2, 5, 5))),
                   f32(r.standard_normal((1, 18, 3, 3)) * 0.1),
                   f32(r.uniform(0.3, 0.7, (1, 9, 3, 3))),
                   f32(r.standard_normal((3, 2, 3, 3)) * 0.3)],
        diff=(0, 3), tol=4e-2),
    'pool2d': S(_std((1, 2, 6, 6)),
                attrs={'pool_size': 2, 'pool_type': 'avg',
                       'pool_stride': 2}),
    'pool3d': S(_std((1, 1, 4, 4, 4)),
                attrs={'pool_size': 2, 'pool_type': 'avg',
                       'pool_stride': 2}),
    'adaptive_pool2d': S(_std((1, 2, 6, 6)),
                         attrs={'pool_size': [3, 3], 'pool_type': 'avg'}),
    'adaptive_pool3d': S(_std((1, 1, 4, 4, 4)),
                         attrs={'pool_size': [2, 2, 2], 'pool_type': 'avg'}),
    'maxout': S(_std((2, 4, 3, 3)), attrs={'groups': 2}),
    'batch_norm': S(lambda r: [f32(r.standard_normal((2, 3, 4, 4))),
                               pos(r, (3,)), f32(r.standard_normal((3,))),
                               f32(r.standard_normal((3,)) * 0.1),
                               pos(r, (3,), 0.5, 1.5)], diff=(0, 1, 2)),
    'layer_norm': S(lambda r: [f32(r.standard_normal((3, 4))),
                               pos(r, (4,)), f32(r.standard_normal((4,)))],
                    diff=(0, 1, 2)),
    'instance_norm': S(lambda r: [f32(r.standard_normal((2, 3, 4, 4))),
                                  pos(r, (3,)),
                                  f32(r.standard_normal((3,)))],
                       diff=(0, 1, 2)),
    'group_norm': S(lambda r: [f32(r.standard_normal((2, 4, 3, 3))),
                               pos(r, (4,)), f32(r.standard_normal((4,)))],
                    diff=(0, 1, 2), attrs={'groups': 2}),
    'data_norm': S(lambda r: [f32(r.standard_normal((3, 4))),
                              f32(np.full((4,), 10.0)),
                              f32(r.standard_normal((4,))),
                              f32(np.full((4,), 10.0))], diff=(0,),
                   attrs={'is_test': True}),
    'spectral_norm': S(lambda r: [f32(r.standard_normal((4, 3)))],
                       tol=4e-2),
    'affine_channel': S(lambda r: [f32(r.standard_normal((2, 3, 4, 4))),
                                   pos(r, (3,)),
                                   f32(r.standard_normal((3,)))],
                        diff=(0, 1, 2)),
    'affine_grid': S(lambda r: [f32(r.standard_normal((2, 2, 3)) * 0.3)],
                     attrs={'out_shape': [2, 1, 4, 4]}),
    'grid_sampler': S(lambda r: [f32(r.standard_normal((1, 2, 4, 4))),
                                 f32(r.uniform(-0.8, 0.8, (1, 3, 3, 2)))],
                      diff=(0, 1), tol=4e-2),
    'interpolate': S(_std((1, 2, 4, 4)),
                     attrs={'out_shape': [8, 8], 'method': 'bilinear'}),
    'pixel_shuffle': S(_std((1, 4, 3, 3)), attrs={'upscale_factor': 2}),
    'unfold': S(_std((1, 2, 4, 4)), attrs={'kernel_sizes': 2}),
    'im2sequence': S(_std((1, 2, 4, 4)), attrs={'filter_size': 2}),
    'lrn': S(_std((1, 6, 3, 3))),
    'dropout': S(_std((3, 4)), attrs={'dropout_prob': 0.5, 'is_test': True}),
    'pad': S(_std((2, 3)), attrs={'paddings': [0, 1, 1, 0]}),
    'pad2d': S(_std((1, 2, 3, 3)), attrs={'paddings': [1, 1, 1, 1]}),
    'pad_constant_like': S(lambda r: [f32(r.standard_normal((4, 5))),
                                      f32(r.standard_normal((2, 3)))],
                           diff=(1,)),
    'lookup_table': S(lambda r: [f32(r.standard_normal((8, 4))),
                                 r.randint(0, 8, (3, 1)).astype(np.int64)]),
    'row_conv': S(lambda r: [f32(r.standard_normal((2, 5, 4))),
                             f32(r.standard_normal((3, 4)) * 0.3)],
                  diff=(0, 1)),
    'tree_conv': S(lambda r: [f32(r.standard_normal((1, 4, 3))),
                              r.randint(0, 3, (1, 3, 2)).astype(np.int64),
                              f32(r.standard_normal((3, 3, 2, 2)) * 0.3)],
                   diff=(0, 2)),
    'cvm': S(lambda r: [np.concatenate([pos(r, (3, 2), 1.0, 5.0),
                                        f32(r.standard_normal((3, 4)))], 1),
                        pos(r, (3, 2), 1.0, 5.0)], diff=(0,)),
    'temporal_shift': S(_std((4, 4, 3, 3)), attrs={'seg_num': 2}),
    'shuffle_channel': S(_std((1, 4, 3, 3)), attrs={'group': 2}),
    'space_to_depth': S(_std((1, 2, 4, 4)), attrs={'blocksize': 2}),
    'multiplex': S(lambda r: [np.asarray([0, 1, 0], np.int64),
                              [f32(r.standard_normal((3, 4))),
                               f32(r.standard_normal((3, 4)))]], diff=()),
    # --- rnn ---
    'lstm': S(lambda r: [f32(r.standard_normal((2, 3, 8)) * 0.3),
                         f32(r.standard_normal((2, 2)) * 0.3),
                         f32(r.standard_normal((2, 2)) * 0.3),
                         f32(r.standard_normal((2, 8)) * 0.3),
                         f32(r.standard_normal((8,)) * 0.1),
                         None, None, None], diff=(0, 3, 4)),
    'gru': S(lambda r: [f32(r.standard_normal((2, 3, 6)) * 0.3),
                        f32(r.standard_normal((2, 2)) * 0.3),
                        f32(r.standard_normal((2, 4)) * 0.3),
                        f32(r.standard_normal((2, 2)) * 0.3), None],
             diff=(0, 2, 3)),
    'gru_unit': S(lambda r: [f32(r.standard_normal((2, 6)) * 0.3),
                             f32(r.standard_normal((2, 2)) * 0.3),
                             f32(r.standard_normal((2, 6)) * 0.3), None],
                  diff=(0, 1, 2)),
    'lstm_unit': S(lambda r: [f32(r.standard_normal((2, 8)) * 0.3),
                              f32(r.standard_normal((2, 2)) * 0.3)],
                   diff=(0, 1)),
    # --- sequence (length-masked) ---
    'sequence_softmax': S(lambda r: [f32(r.standard_normal((2, 4))),
                                     np.asarray([3, 4], np.int64)]),
    'sequence_pool': S(lambda r: [f32(r.standard_normal((2, 4, 3))),
                                  np.asarray([3, 4], np.int64)],
                       attrs={'pool_type': 'average'}),
    'sequence_pad': S(lambda r: [f32(r.standard_normal((2, 4, 3))),
                                 f32([0.0]), np.asarray([3, 4], np.int64)]),
    'sequence_unpad': S(lambda r: [f32(r.standard_normal((2, 4, 3))),
                                   np.asarray([3, 4], np.int64)]),
    'sequence_reverse': S(lambda r: [f32(r.standard_normal((2, 4, 3))),
                                     np.asarray([3, 4], np.int64)]),
    'sequence_expand_as': S(lambda r: [f32(r.standard_normal((2, 3))),
                                       f32(r.standard_normal((2, 4, 3))),
                                       np.asarray([3, 4], np.int64)]),
    'sequence_conv': S(lambda r: [f32(r.standard_normal((2, 4, 3))),
                                  f32(r.standard_normal((9, 5)) * 0.3),
                                  None, np.asarray([3, 4], np.int64)],
                       diff=(0, 1)),
    'sequence_reshape': S(lambda r: [f32(r.standard_normal((2, 4, 2))),
                                     np.asarray([4, 2], np.int64)],
                          attrs={'new_dim': 4}),
    'sequence_slice': S(lambda r: [f32(r.standard_normal((2, 4, 3))),
                                   np.asarray([[1], [0]], np.int64),
                                   np.asarray([[2], [3]], np.int64),
                                   np.asarray([4, 3], np.int64)]),
    'sequence_scatter': S(
        lambda r: [f32(r.standard_normal((2, 5))),
                   np.asarray([[0, 1, 2], [1, 2, 3]], np.int64),
                   f32(r.standard_normal((2, 3))),
                   np.asarray([3, 3], np.int64)], diff=(0, 2)),
    'sequence_concat': S(lambda r: [[f32(r.standard_normal((2, 3, 4))),
                                     f32(r.standard_normal((2, 2, 4)))],
                                    [np.asarray([3, 2], np.int64),
                                     np.asarray([2, 2], np.int64)]],
                         diff=()),
    'lod_reset': S(lambda r: [f32(r.standard_normal((2, 4))), None],
                   attrs={'target_lod': [2, 4]}),
    # --- tensor manipulation (linear: grads flow through gather/scatter) ---
    'concat': S(lambda r: [[f32(r.standard_normal((2, 3))),
                            f32(r.standard_normal((2, 3)))]], diff=()),
    'stack': S(lambda r: [[f32(r.standard_normal((2, 3))),
                           f32(r.standard_normal((2, 3)))]], diff=()),
    'split': S(_std((4, 6)), attrs={'num_or_sections': 2, 'dim': 1}),
    'unstack': S(_std((3, 4))),
    'reshape': S(_std((3, 4)), attrs={'shape': [4, 3]}),
    'transpose': S(_std((3, 4)), attrs={'perm': [1, 0]}),
    'transpose_batch_time': S(_std((3, 4, 2))),
    'flatten': S(_std((2, 3, 4))),
    'flatten2': S(_std((2, 3, 4))),
    'squeeze': S(_std((3, 1, 4))),
    'unsqueeze': S(_std((3, 4)), attrs={'axes': [1]}),
    'expand': S(_std((2, 3)), attrs={'expand_times': [2, 1]}),
    'expand_as': S(lambda r: [f32(r.standard_normal((1, 3))),
                              f32(r.standard_normal((4, 3)))]),
    'tile': S(_std((2, 3)), attrs={'repeat_times': [2, 2]}),
    'reverse': S(_std((3, 4)), attrs={'axis': [0]}),
    'slice': S(_std((4, 5)),
               attrs={'axes': [0, 1], 'starts': [1, 0], 'ends': [3, 4]}),
    'strided_slice': S(_std((4, 6)),
                       attrs={'axes': [1], 'starts': [0], 'ends': [6],
                              'strides': [2]}),
    'crop_tensor': S(_std((4, 5)),
                     attrs={'shape': [2, 3], 'offsets': [1, 1]}),
    'gather': S(lambda r: [f32(r.standard_normal((5, 3))),
                           np.asarray([0, 2, 4], np.int64)]),
    'gather_nd': S(lambda r: [f32(r.standard_normal((4, 3))),
                              np.asarray([[0], [2]], np.int64)]),
    'scatter': S(lambda r: [f32(r.standard_normal((5, 3))),
                            np.asarray([1, 3], np.int64),
                            f32(r.standard_normal((2, 3)))], diff=(0, 2)),
    'scatter_nd': S(lambda r: [np.asarray([[1], [3]], np.int64),
                               f32(r.standard_normal((2, 3)))], diff=(1,),
                    attrs={'shape': [5, 3]}),
    'scatter_nd_add': S(lambda r: [f32(r.standard_normal((5, 3))),
                                   np.asarray([[1], [3]], np.int64),
                                   f32(r.standard_normal((2, 3)))],
                        diff=(0, 2)),
    'where': S(lambda r: [r.rand(3, 4) < 0.5,
                          f32(r.standard_normal((3, 4))),
                          f32(r.standard_normal((3, 4)))], diff=(1, 2)),
    'top_k': S(lambda r: [f32(np.arange(12).reshape(3, 4)
                              + r.uniform(0, 0.3, (3, 4)))],
               attrs={'k': 2}),
    'diag': S(_std((4,))),
    'matrix_diag_part': S(_std((3, 3))),
    'assign': S(_std((3, 4))),
    'cast': S(_std((3, 4)), attrs={'dtype': 'float32'}),
    'fill_zeros_like': S(_std((3, 4))),
    # --- detection (differentiable heads) ---
    'roi_align': S(lambda r: [f32(r.standard_normal((1, 2, 6, 6))),
                              f32([[0.5, 0.5, 4.0, 4.0]]),
                              np.asarray([0], np.int64)],
                   attrs={'pooled_height': 2, 'pooled_width': 2},
                   tol=4e-2),
    'roi_pool': S(lambda r: [f32(r.standard_normal((1, 2, 6, 6))),
                             f32([[0.5, 0.5, 4.0, 4.0]]),
                             np.asarray([0], np.int64)],
                  attrs={'pooled_height': 2, 'pooled_width': 2}),
    'prroi_pool': S(lambda r: [f32(r.standard_normal((1, 2, 6, 6))),
                               f32([[0.5, 0.5, 4.0, 4.0]]),
                               np.asarray([0], np.int64)],
                    attrs={'pooled_height': 2, 'pooled_width': 2},
                    tol=4e-2),
    'psroi_pool': S(lambda r: [f32(r.standard_normal((1, 4, 6, 6))),
                               f32([[0.5, 0.5, 4.0, 4.0]]),
                               np.asarray([0], np.int64)],
                    attrs={'output_channels': 1, 'pooled_height': 2,
                           'pooled_width': 2}, tol=4e-2),
    'yolov3_loss': S(
        lambda r: [f32(r.standard_normal((1, 12, 4, 4)) * 0.3),
                   f32(r.uniform(0.2, 0.6, (1, 2, 4))),
                   r.randint(0, 1, (1, 2)).astype(np.int64),
                   f32(np.ones((1, 2)))],
        attrs={'anchors': [10, 13, 16, 30], 'anchor_mask': [0, 1],
               'class_num': 1, 'use_label_smooth': False}, tol=5e-2),
    'box_encode_per_row': S(lambda r: [f32([[1., 1., 4., 4.]]),
                                       f32([[1.5, 1.5, 4.5, 4.5]])],
                            diff=(0, 1), tol=4e-2),
    'iou_similarity': S(lambda r: [f32([[1., 1., 4., 4.]]),
                                   f32([[2., 2., 5., 5.]])], diff=(0, 1),
                        tol=4e-2),
    'box_clip': S(lambda r: [f32([[[1., 1., 4., 4.]]]),
                             f32([[8., 8., 1.]])], diff=(0,)),
    # linear map: central difference is exact for any eps; the large eps
    # suppresses f32 cancellation noise from the big positional base values
    'polygon_box_transform': S(_std((1, 8, 3, 3)), eps=0.5, tol=4e-2),
}
# ---------------------------------------------------------------------------
# explicitly nondifferentiable / not-gradient-tested ops, with reasons
# ---------------------------------------------------------------------------
NONDIFF = {
    # integer / boolean outputs
    'arg_max': 'integer index output', 'arg_min': 'integer index output',
    'argsort': 'permutation/index output',
    'equal': 'boolean output', 'not_equal': 'boolean output',
    'less_than': 'boolean output', 'less_equal': 'boolean output',
    'greater_than': 'boolean output', 'greater_equal': 'boolean output',
    'logical_and': 'boolean output', 'logical_or': 'boolean output',
    'logical_xor': 'boolean output', 'logical_not': 'boolean output',
    'is_empty': 'boolean output', 'isfinite': 'boolean output',
    'has_inf': 'boolean output', 'has_nan': 'boolean output',
    'one_hot': 'integer input / constant output',
    'sequence_mask': 'integer mask output',
    'sequence_enumerate': 'integer id output',
    'shape': 'metadata output', 'rank': 'metadata output',
    'size': 'metadata output',
    'shard_index': 'integer id output', 'hash': 'integer hash output',
    'sign': 'piecewise-constant (zero gradient)',
    'ceil': 'piecewise-constant (zero gradient)',
    'floor': 'piecewise-constant (zero gradient)',
    'round': 'piecewise-constant (zero gradient)',
    'elementwise_floordiv': 'integer/piecewise-constant output',
    'unique_with_counts': 'integer index/count outputs',
    'where_index': 'integer index output',
    'mean_iou': 'confusion-matrix counting (integer)',
    'accuracy': 'metric (integer comparison)',
    'auc': 'metric (threshold counting)',
    'chunk_eval': 'metric (span counting)',
    'detection_map': 'metric (greedy integer matching)',
    'edit_distance': 'integer distance',
    'similarity_focus': 'binary mask output (argmax selection)',
    # constant / generator ops
    'fill_constant': 'constant output',
    'fill_constant_batch_size_like': 'constant output',
    'fill_any_like': 'constant output', 'eye': 'constant output',
    'linspace': 'constant output', 'range': 'constant output',
    'gaussian_random': 'random generator',
    'gaussian_random_batch_size_like': 'random generator',
    'uniform_random': 'random generator',
    'uniform_random_batch_size_like': 'random generator',
    'truncated_gaussian_random': 'random generator',
    'randint': 'random integer generator',
    'randperm': 'random permutation generator',
    'sampling_id': 'stochastic id sampling',
    'random_crop': 'stochastic crop (index selection)',
    'shuffle_batch': 'stochastic permutation',
    'nce': 'stochastic negative sampling (loss checked in layer tests)',
    'dpsgd': 'stochastic update op (noise injection)',
    # optimizer update ops — golden-value tested in test_optimizers.py
    'sgd': 'optimizer update (golden-tested)',
    'momentum': 'optimizer update (golden-tested)',
    'lars_momentum': 'optimizer update (golden-tested)',
    'adam': 'optimizer update (golden-tested)',
    'adamax': 'optimizer update (golden-tested)',
    'adagrad': 'optimizer update (golden-tested)',
    'decayed_adagrad': 'optimizer update (golden-tested)',
    'adadelta': 'optimizer update (golden-tested)',
    'rmsprop': 'optimizer update (golden-tested)',
    'ftrl': 'optimizer update (golden-tested)',
    'lamb': 'optimizer update (golden-tested)',
    'dgc_momentum': 'optimizer update (golden-tested)',
    'fused_sgd': 'multi-tensor optimizer update (bitwise parity vs per-'
                 'param sgd in test_ir_passes.py)',
    'fused_momentum': 'multi-tensor optimizer update (bitwise parity vs '
                      'per-param momentum in test_ir_passes.py)',
    'fused_lars_momentum': 'multi-tensor optimizer update (bitwise parity '
                           'vs per-param lars_momentum in '
                           'test_fleet_runtime.py)',
    'fused_adam': 'multi-tensor optimizer update (bitwise parity vs per-'
                  'param adam in test_ir_passes.py)',
    'sparse_sgd': 'rows-only optimizer update (parity vs dense sgd in '
                  'tests/ops/test_sparse_ops.py)',
    'sparse_momentum': 'rows-only optimizer update (parity vs dense '
                       'momentum in tests/ops/test_sparse_ops.py)',
    'sparse_adagrad': 'rows-only optimizer update (parity vs dense '
                      'adagrad in tests/ops/test_sparse_ops.py)',
    'sparse_adam': 'rows-only lazy optimizer update (parity vs dense '
                   'adam in tests/ops/test_sparse_ops.py)',
    'check_finite_and_unscale': 'AMP bookkeeping (tested in test_amp.py)',
    'update_loss_scaling': 'AMP bookkeeping (tested in test_amp.py)',
    # control-flow / array plumbing
    '__array_length__': 'TensorArray plumbing',
    '__array_read__': 'TensorArray plumbing',
    '__array_write__': 'TensorArray plumbing',
    'print': 'side-effect op',
    'c_sync_calc_stream': 'no-op stream sync',
    'c_sync_comm_stream': 'no-op stream sync',
    # collectives need a mesh/shard_map context
    'c_allreduce_sum': 'collective (tested in test_parallel.py)',
    'c_allreduce_sum_bucket': 'collective (bucketed gradient sync — '
                              'tested in test_bucket_allreduce.py / '
                              'test_quant_collectives.py)',
    'c_allreduce_max': 'collective (tested in test_parallel.py)',
    'c_allreduce_min': 'collective (tested in test_parallel.py)',
    'c_allreduce_prod': 'collective (tested in test_parallel.py)',
    'c_allgather': 'collective (tested in test_parallel.py)',
    'c_broadcast': 'collective (tested in test_parallel.py)',
    'c_reducescatter': 'collective (tested in test_parallel.py)',
    # selection / assignment ops with index outputs (forward-tested in
    # tests/ops/test_detection_ops.py)
    'anchor_generator': 'constant anchor grid',
    'prior_box': 'constant prior grid',
    'density_prior_box': 'constant prior grid',
    'bipartite_match': 'integer matching',
    'box_coder': 'box transform (forward-tested; encode uses log/div of '
                 'constant priors)',
    'box_decoder_and_assign': 'argmax assignment',
    'multiclass_nms': 'index selection (NMS)',
    'locality_aware_nms': 'index selection (NMS)',
    'generate_proposals': 'index selection (NMS)',
    'collect_fpn_proposals': 'index selection (top-k)',
    'distribute_fpn_proposals': 'integer level routing',
    'rpn_target_assign': 'integer target assignment',
    'retinanet_target_assign': 'integer target assignment',
    'target_assign': 'integer target assignment',
    'ssd_loss': 'internally uses integer matching; forward-tested',
    'yolo_box': 'inference-only box decode',
    'roi_perspective_transform': 'integer mask output dominates',
    'deformable_roi_pooling': 'forward-tested (sampling indices)',
    'crf_decoding': 'integer viterbi path',
    'ctc_greedy_decoder': 'integer decode',
    'beam_search_step': 'integer beam selection',
    'gather_tree': 'integer beam backtrace',
    'filter_by_instag': 'integer filtering',
    'get_tensor_from_selected_rows': 'identity plumbing',
    'merge_selected_rows': 'identity plumbing',
    'quantize_linear': 'integer quantized output',
    'dequantize_linear': 'paired with quantize_linear',
    'fake_quantize_dequantize_abs_max':
        'STE surrogate gradient (intentionally differs from numeric diff; '
        'QAT path tested in test_inference.py)',
    'fake_channel_wise_quantize_dequantize_abs_max':
        'STE surrogate gradient',
    'fake_quantize_dequantize_moving_average_abs_max':
        'STE surrogate gradient',
    'reduce_all': 'boolean output', 'reduce_any': 'boolean output',
    'paged_attention':
        'inference-only decode-phase cache read (serving/decode/); training '
        'gradients flow through whole-sequence attention, parity tested in '
        'tests/ops/test_paged_attention.py',
    'paged_prefill_attention':
        'inference-only prefill-phase cache read (serving/decode/); '
        'parity tested in tests/ops/test_paged_attention.py',
}



def test_registry_fully_classified():
    """Every registered op is either gradient-checked or has a reason."""
    names = set(_REGISTRY)
    specs = set(GRAD_SPECS)
    nd = set(NONDIFF)
    unknown = (specs | nd) - names
    assert not unknown, f"classified but not registered: {sorted(unknown)}"
    both = specs & nd
    assert not both, f"doubly classified: {sorted(both)}"
    missing = names - specs - nd
    assert not missing, (
        f"ops with no gradient classification: {sorted(missing)} — add a "
        f"GRAD_SPECS entry or a NONDIFF reason")


def _scalarize(res):
    total = jnp.zeros((), jnp.float32)
    for leaf in jax.tree_util.tree_leaves(res):
        leaf = jnp.asarray(leaf)
        if jnp.issubdtype(leaf.dtype, jnp.inexact):
            total = total + jnp.sum(leaf.astype(jnp.float32))
    return total


@pytest.mark.parametrize('name', sorted(GRAD_SPECS))
def test_check_grad(name):
    spec = GRAD_SPECS[name]
    opdef = get_op(name)
    rng = R(0)
    args = spec['args'](rng)
    attrs = dict(spec['attrs'])
    if opdef.needs_rng:
        attrs['key'] = jax.random.PRNGKey(0)
    diff = spec['diff']
    if not diff:
        # variadic-input op: differentiate the first element of the first
        # list-valued argument
        li = next(i for i, a in enumerate(args) if isinstance(a, list))

        def f(first):
            full = list(args)
            lst = list(full[li])
            lst[0] = first
            full[li] = lst
            return _scalarize(opdef.fn(*full, **attrs))
        dargs = (jnp.asarray(args[li][0]),)
    else:
        def f(*dargs):
            full = list(args)
            for i, d in zip(diff, dargs):
                full[i] = d
            return _scalarize(opdef.fn(*full, **attrs))
        dargs = tuple(jnp.asarray(args[i]) for i in diff)
    tol = spec['tol']
    check_grads(f, dargs, order=1, modes=['rev'], atol=tol, rtol=tol,
                eps=spec['eps'])
