"""Op-level contract of ops/nn_ops.py paged_attention /
paged_prefill_attention: bitwise parity vs whole-sequence attention at the
same padded key extent, across ragged length mixes and block-boundary
lengths, plus clean block reuse (no stale-cache bleed) and the
pallas-fallback accounting."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddle_tpu.ops.nn_ops import (paged_attention, paged_prefill_attention,
                                   pallas_fallback_stats,
                                   reset_pallas_fallback_stats)

H, D, BS, MAXBPS = 2, 16, 4, 4
E = MAXBPS * BS          # padded context extent
SCALE = 1.0 / np.sqrt(D)
NEG = -1e9


def whole_seq_reference(q_rows, k_rows, v_rows):
    """The unfused MultiHeadAttention chain at extent E: matmul·α +
    additive causal bias, softmax, matmul — per-row ground truth."""
    q4, k4, v4 = (jnp.asarray(x[None]) for x in (q_rows, k_rows, v_rows))
    s = jnp.matmul(q4, jnp.swapaxes(k4, -1, -2)) \
        * jnp.asarray(SCALE, jnp.float32)
    s = s + jnp.asarray(np.triu(np.full((E, E), NEG, 'float32'),
                                1)[None, None])
    return np.asarray(jnp.matmul(jax.nn.softmax(s, -1), v4))[0]


def build_cache(rng, num_blocks, tables_rows):
    """Fill per-slot rows into distinct blocks; returns (pages, tables,
    per-slot row arrays)."""
    k_pages = np.zeros((H, num_blocks, BS, D), 'float32')
    v_pages = np.zeros_like(k_pages)
    tables, k_rows, v_rows = [], [], []
    nxt = 1
    for nb in tables_rows:
        kr = rng.randn(H, E, D).astype('float32')
        vr = rng.randn(H, E, D).astype('float32')
        table = []
        for j in range(nb):
            table.append(nxt)
            k_pages[:, nxt] = kr[:, j * BS:(j + 1) * BS]
            v_pages[:, nxt] = vr[:, j * BS:(j + 1) * BS]
            nxt += 1
        table += [0] * (MAXBPS - nb)
        tables.append(table)
        k_rows.append(kr)
        v_rows.append(vr)
    return k_pages, v_pages, np.asarray(tables, np.int32), k_rows, v_rows


def test_decode_parity_ragged_mix():
    """Slots with wildly different context lengths in ONE batched call each
    match their own whole-sequence reference row bitwise."""
    rng = np.random.RandomState(0)
    lens = [1, 3, 7, 12, 16]          # ragged, includes min and max context
    k_pages, v_pages, tables, k_rows, v_rows = build_cache(
        rng, 64, [MAXBPS] * len(lens))
    q_rows = [rng.randn(H, E, D).astype('float32') for _ in lens]
    q = np.stack([qr[:, c - 1] for qr, c in zip(q_rows, lens)])
    out = np.asarray(paged_attention(q, k_pages, v_pages, tables,
                                     np.asarray(lens, np.int32),
                                     sm_scale=float(SCALE)))
    for i, c in enumerate(lens):
        ref = whole_seq_reference(q_rows[i], k_rows[i], v_rows[i])
        assert np.array_equal(out[i], ref[:, c - 1]), f'slot {i} (c={c})'


@pytest.mark.parametrize('c', [BS, BS + 1, 2 * BS - 1, 2 * BS, E])
def test_decode_parity_block_boundaries(c):
    """len % block_size ∈ {0, 1, block_size-1} and the full-table case."""
    rng = np.random.RandomState(c)
    k_pages, v_pages, tables, k_rows, v_rows = build_cache(rng, 16, [MAXBPS])
    q_rows = rng.randn(H, E, D).astype('float32')
    q = q_rows[:, c - 1][None]
    out = np.asarray(paged_attention(q, k_pages, v_pages, tables,
                                     np.asarray([c], np.int32),
                                     sm_scale=float(SCALE)))
    ref = whole_seq_reference(q_rows, k_rows[0], v_rows[0])
    assert np.array_equal(out[0], ref[:, c - 1])


def test_prefill_parity_rows():
    """paged_prefill_attention rows 0..P-1 equal the whole-sequence rows,
    at a bucket extent SMALLER than the padded context."""
    rng = np.random.RandomState(1)
    k_pages, v_pages, tables, k_rows, v_rows = build_cache(rng, 16, [MAXBPS])
    q_rows = rng.randn(H, E, D).astype('float32')
    Lq = 8                             # bucket < E
    out = np.asarray(paged_prefill_attention(
        q_rows[None, :, :Lq], k_rows[0][None, :, :Lq],
        v_rows[0][None, :, :Lq], k_pages, v_pages, tables[:1],
        sm_scale=float(SCALE)))
    ref = whole_seq_reference(q_rows, k_rows[0], v_rows[0])
    assert np.array_equal(out[0], ref[:, :Lq])


def test_block_reuse_no_stale_bleed():
    """A freed block refilled with garbage, then reused by a new request,
    contributes NOTHING beyond the new context: outputs with clean vs
    garbage pool tails are bitwise identical (masked probabilities are
    exactly zero in the XLA fallback)."""
    rng = np.random.RandomState(2)
    c = 5                              # context: block 0 full + 1 token
    k_rows = rng.randn(H, E, D).astype('float32')
    v_rows = rng.randn(H, E, D).astype('float32')
    q = rng.randn(1, H, D).astype('float32')
    table = np.asarray([[1, 2, 0, 0]], np.int32)
    lens = np.asarray([c], np.int32)

    def run(fill):
        k_pages = np.full((H, 8, BS, D), fill, 'float32')
        v_pages = np.full_like(k_pages, fill)
        for j in range(2):
            k_pages[:, j + 1] = k_rows[:, j * BS:(j + 1) * BS]
            v_pages[:, j + 1] = v_rows[:, j * BS:(j + 1) * BS]
        # stale garbage INSIDE the table beyond the context: positions
        # c.. of block 2 keep whatever the previous tenant wrote
        k_pages[:, 2, c - BS:] = fill
        v_pages[:, 2, c - BS:] = fill
        return np.asarray(paged_attention(q, k_pages, v_pages, table, lens,
                                          sm_scale=float(SCALE)))

    clean = run(0.0)
    stale = run(1e6)                   # previous request's leftovers
    assert np.array_equal(clean, stale)


def test_fallback_stats_count_and_warn_once():
    """The pallas-unavailable fallback warns ONCE per process through
    log_helper and counts every fallback trace afterwards."""
    import logging
    from paddle_tpu.ops import nn_ops
    reset_pallas_fallback_stats()
    records = []

    class Grab(logging.Handler):
        def emit(self, record):
            records.append(record)

    logger = logging.getLogger('paddle_tpu.ops.nn_ops')
    h = Grab()
    logger.addHandler(h)
    try:
        nn_ops._pallas_fallback('fused_attention', ValueError('no kernel'),
                                (1, 2, 8, 16))
        nn_ops._pallas_fallback('paged_attention', ValueError('no kernel'),
                                (4, 2, 16))
        nn_ops._pallas_fallback('fused_attention', ValueError('again'),
                                (1, 2, 16, 16))
    finally:
        logger.removeHandler(h)
    stats = pallas_fallback_stats()
    assert stats['count'] == 3
    assert stats['warned'] is True
    assert 'paged_attention' not in stats['last']  # last was fused again
    assert len(records) == 1, 'must warn exactly once per process'
    # the at-export collector surfaces the count as a gauge
    from paddle_tpu.observability import registry
    d = registry.to_dict()
    g = d.get('attention_pallas_fallbacks')
    assert g and g['samples'][0]['value'] == 3.0
    reset_pallas_fallback_stats()
