"""tier-1 guard for the decode-engine bench: tools/bench_decode.py --smoke
must run end-to-end on CPU, keep per-request BITWISE token parity between
the paged continuous-batching engine and the uncached whole-sequence
baseline, show continuous batching beating drain-then-refill, replay the
sampled section bitwise, and show speculative verify rounds beating
lockstep steps. The full-size acceptance margins (≥1.5× tokens/s for
continuous-vs-drain AND speculative-vs-lockstep) are recorded in PERF.md
§13; the smoke bounds here are structural (step counts, deterministic for
the seeded workload) so CI noise cannot flake them."""
import json
import os
import subprocess
import sys

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), '..', '..'))

ENGINE_FIELDS = {'requests', 'tokens', 'slots', 'tokens_per_s', 'wall_s',
                 'steps', 'mean_slot_occupancy', 'prefill_s', 'decode_s',
                 'bitwise_equal'}


def test_bench_decode_smoke_runs_on_cpu():
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    r = subprocess.run(
        [sys.executable, os.path.join('tools', 'bench_decode.py'),
         '--smoke'],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stderr[-3000:]
    lines = [json.loads(ln) for ln in r.stdout.splitlines() if ln.strip()]
    benches = {d['bench']: d for d in lines if 'bench' in d}
    assert {'decode_uncached_baseline', 'decode_engine_continuous',
            'decode_engine_drain', 'decode_sampled',
            'decode_engine_speculative'} <= set(benches)

    base = benches['decode_uncached_baseline']
    assert base['tokens'] > 0 and base['tokens_per_s'] > 0

    cont = benches['decode_engine_continuous']
    drain = benches['decode_engine_drain']
    assert ENGINE_FIELDS <= set(cont), cont
    # hard guarantees: every request's streamed tokens equal the uncached
    # whole-sequence decode, under BOTH admission policies
    assert cont['bitwise_equal'] is True, cont
    assert drain['bitwise_equal'] is True, drain
    assert cont['tokens'] == base['tokens'] == drain['tokens']
    # continuous batching admits into freed slots: structurally fewer
    # lockstep steps and higher occupancy than drain-then-refill. These are
    # DETERMINISTIC for the seeded workload (smoke measures 37 vs 73), so
    # they gate hard; wall-clock ratios (1.78x full size, PERF.md §13) are
    # reported but not asserted — a loaded CI box cannot flake them.
    assert cont['steps'] * 1.3 <= drain['steps'], (cont, drain)
    assert cont['mean_slot_occupancy'] > drain['mean_slot_occupancy']
    assert 'speedup_vs_drain' in cont and 'speedup_vs_uncached' in cont

    # sampled: pinned request_ids make the two passes bitwise-identical
    sampled = benches['decode_sampled']
    assert sampled['replayable'] is True, sampled
    assert sampled['tokens'] == base['tokens']

    # speculative: still bitwise greedy, and the (S, k) verify rounds beat
    # lockstep structurally (smoke measures 21 vs 37 steps, deterministic;
    # the wall-clock ratio — 1.64x full size — stays out of the gate)
    spec = benches['decode_engine_speculative']
    assert ENGINE_FIELDS <= set(spec), spec
    assert spec['bitwise_equal'] is True, spec
    assert spec['tokens'] == base['tokens']
    assert spec['steps'] * 1.5 <= cont['steps'], (spec, cont)
    assert spec['spec_rounds'] == spec['steps']
    assert 0.0 <= spec['acceptance'] <= 1.0
    assert 'speedup_vs_lockstep' in spec
