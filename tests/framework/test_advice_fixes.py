"""Regression tests for the r4 advisor findings: lstm_unit gate layout,
save() artifact filenames, gru_unit bias shape, optimizer-var predicate."""
import os

import numpy as np

import paddle_tpu as fluid
import paddle_tpu.layers as L
from paddle_tpu.ops.registry import get_op


def _sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


def test_lstm_unit_op_matches_reference_gate_order():
    """ref lstm_unit_op.h: i at 0, f at D, o at 2D, candidate g at 3D."""
    rng = np.random.RandomState(0)
    B, D = 3, 5
    x = rng.randn(B, 4 * D).astype(np.float32)
    c_prev = rng.randn(B, D).astype(np.float32)
    h, c = get_op('lstm_unit').fn(x, c_prev, forget_bias=0.5)

    i, f, o, g = x[:, :D], x[:, D:2 * D], x[:, 2 * D:3 * D], x[:, 3 * D:]
    want_c = c_prev * _sigmoid(f + 0.5) + _sigmoid(i) * np.tanh(g)
    want_h = np.tanh(want_c) * _sigmoid(o)
    np.testing.assert_allclose(np.asarray(c), want_c, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(h), want_h, rtol=1e-5, atol=1e-6)


def test_basic_lstm_unit_matches_reference_ijfo_layout():
    """ref contrib/layers/rnn_impl.py:816 splits gates as i, j, f, o —
    a DIFFERENT layout from the lstm_unit op; weights exchanged with the
    reference BasicLSTMUnit must stay compatible."""
    from paddle_tpu import dygraph
    from paddle_tpu.contrib.extra import BasicLSTMUnit
    rng = np.random.RandomState(1)
    B, I, D = 2, 3, 4
    with dygraph.guard():
        cell = BasicLSTMUnit(hidden_size=D, forget_bias=1.0)
        x = fluid.dygraph.to_variable(rng.randn(B, I).astype(np.float32))
        hp = fluid.dygraph.to_variable(rng.randn(B, D).astype(np.float32))
        cp = fluid.dygraph.to_variable(rng.randn(B, D).astype(np.float32))
        h, c = cell(x, hp, cp)
        w = np.asarray(cell.weight.value)
        b = np.asarray(cell.bias.value)
        xv, hv, cv = (np.asarray(t.value) for t in (x, hp, cp))
        got_h, got_c = np.asarray(h.value), np.asarray(c.value)

    gates = np.concatenate([xv, hv], -1) @ w + b
    i, j, f, o = np.split(gates, 4, axis=-1)
    want_c = cv * _sigmoid(f + 1.0) + _sigmoid(i) * np.tanh(j)
    want_h = np.tanh(want_c) * _sigmoid(o)
    np.testing.assert_allclose(got_c, want_c, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(got_h, want_h, rtol=1e-5, atol=1e-6)


def test_save_writes_exact_pdparams_filename(tmp_path):
    """np.savez(str) appends '.npz'; save() must produce the documented
    {path}.pdparams / {path}.pdopt artifacts byte-for-name."""
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.data('x', [4, 3], 'float32')
        y = L.fc(x, size=2)
        loss = L.reduce_mean(y)
        fluid.optimizer.SGD(0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    path = str(tmp_path / 'ckpt' / 'model')
    fluid.io.save(prog, path)
    assert os.path.exists(path + '.pdparams'), os.listdir(tmp_path / 'ckpt')
    assert os.path.exists(path + '.pdopt')
    assert not os.path.exists(path + '.pdparams.npz')
    state = fluid.io.load_program_state(path)
    assert any(k for k in state)


def test_gru_unit_bias_matches_reference_shape():
    """ref layers/rnn.py:2675: bias_size = [1, 3 * size]."""
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        D = 4
        x = fluid.data('x', [2, 3 * D], 'float32')
        h = fluid.data('h', [2, D], 'float32')
        L.gru_unit(x, h, 3 * D)
        biases = [v for v in prog.list_vars()
                  if 'gru_unit' in v.name and v.shape == (1, 3 * D)]
        assert biases, [(v.name, v.shape) for v in prog.list_vars()]


def test_is_belong_to_optimizer_uses_tag_not_name():
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.data('x', [4, 3], 'float32')
        y = L.fc(x, size=2)
        loss = L.reduce_mean(y)
        # a USER persistable var whose name contains '@' — must NOT be
        # classified as optimizer state
        tricky = L.create_global_var([1], 1.0, 'float32', persistable=True,
                                     name='user@stat')
        fluid.optimizer.Momentum(0.1, momentum=0.9).minimize(loss)
    opt_vars = [v.name for v in prog.list_vars()
                if fluid.io.is_belong_to_optimizer(v)]
    assert 'user@stat' not in opt_vars
    # momentum velocity slots ARE classified
    assert any('velocity' in n or 'momentum' in n.lower() or '_' in n
               for n in opt_vars), opt_vars
    assert opt_vars, "no optimizer vars tagged at all"


def test_belong_to_optimizer_tag_survives_program_roundtrip(tmp_path):
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.data('x', [4, 3], 'float32')
        loss = L.reduce_mean(L.fc(x, size=2))
        fluid.optimizer.Momentum(0.1, momentum=0.9).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    path = str(tmp_path / 'model')
    fluid.io.save(prog, path)
    from paddle_tpu.io import _program_from_dict
    import json
    with open(path + '.pdmodel') as f:
        p2 = _program_from_dict(json.load(f))
    before = sorted(v.name for v in prog.list_vars()
                    if fluid.io.is_belong_to_optimizer(v))
    after = sorted(v.name for v in p2.list_vars()
                   if fluid.io.is_belong_to_optimizer(v))
    assert before and before == after
