"""Fleet distributed parity on the 8-device CPU mesh (VERDICT r1 #2/#3):
strategy knobs change observable behavior, PS-mode scripts run unmodified,
DP grads == single-device grads through the CompiledProgram path, TP parity
through the fleet-installed mesh, true divergent-replica LocalSGD.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.parallel import (fleet, DistributedStrategy, make_mesh,
                                 mesh_guard, set_default_mesh,
                                 get_default_mesh, LocalSGDStep,
                                 column_parallel_matmul, row_parallel_matmul)


@pytest.fixture(autouse=True)
def _reset_mesh():
    old = get_default_mesh()
    yield
    set_default_mesh(old)


def _linreg_program(opt_builder, w_name):
    main, start = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, start):
        x = layers.data('x', shape=[2], dtype='float32')
        y = layers.data('y', shape=[1], dtype='float32')
        pred = layers.fc(x, 1, bias_attr=False,
                         param_attr=fluid.ParamAttr(
                             name=w_name,
                             initializer=fluid.initializer.
                             ConstantInitializer(0.0)))
        loss = layers.mean(layers.square_error_cost(pred, y))
        opt_builder(loss)
    return main, start, loss


def test_gradient_merge_steps_honored():
    """strategy.gradient_merge_steps=2 → params update every 2nd step only."""
    fleet.init()
    strat = DistributedStrategy()
    strat.gradient_merge_steps = 2

    def build(loss):
        fleet.distributed_optimizer(
            fluid.optimizer.SGD(0.1), strategy=strat).minimize(loss)

    main, start, loss = _linreg_program(build, 'fleet_gm_w')
    exe = fluid.Executor()
    X = np.ones((4, 2), 'float32')
    Y = np.ones((4, 1), 'float32')
    with fluid.scope_guard(fluid.Scope()):
        exe.run(start)
        w0, = exe.run(main, feed={'x': X, 'y': Y}, fetch_list=['fleet_gm_w'])
        np.testing.assert_allclose(w0, 0.0)        # off-step: no update
        w1, = exe.run(main, feed={'x': X, 'y': Y}, fetch_list=['fleet_gm_w'])
        assert np.abs(w1).sum() > 0                # merge step: applied


def test_local_sgd_knob_honored():
    """use_local_sgd + local_sgd_steps=3 → one sync/update per 3 steps."""
    fleet.init()
    strat = DistributedStrategy()
    strat.use_local_sgd = True
    strat.local_sgd_steps = 3

    def build(loss):
        fleet.distributed_optimizer(
            fluid.optimizer.SGD(0.1), strategy=strat).minimize(loss)

    main, start, loss = _linreg_program(build, 'fleet_ls_w')
    exe = fluid.Executor()
    X = np.ones((4, 2), 'float32')
    Y = np.ones((4, 1), 'float32')
    with fluid.scope_guard(fluid.Scope()):
        exe.run(start)
        for step in range(6):
            w, = exe.run(main, feed={'x': X, 'y': Y},
                         fetch_list=['fleet_ls_w'])
            if step in (0, 1, 3, 4):
                ref = 0.0 if step < 3 else w_after_first
                np.testing.assert_allclose(w, ref, rtol=1e-6,
                                           err_msg=f'step {step}')
            elif step == 2:
                assert np.abs(w).sum() > 0
                w_after_first = w


def test_dp_grads_equal_single_device():
    """SURVEY §4: CompiledProgram DP grads == single-device grads."""
    main, start = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, start):
        x = layers.data('x', shape=[4], dtype='float32')
        y = layers.data('y', shape=[1], dtype='float32')
        h = layers.fc(x, 8, act='tanh',
                      param_attr=fluid.ParamAttr(name='dp_w1'))
        pred = layers.fc(h, 1, param_attr=fluid.ParamAttr(name='dp_w2'))
        loss = layers.mean(layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(0.0).minimize(loss)   # lr 0: params frozen

    rng = np.random.RandomState(0)
    X = rng.randn(16, 4).astype('float32')        # 16 % 8 == 0
    Y = rng.randn(16, 1).astype('float32')
    grads = ['dp_w1@GRAD', 'dp_w2@GRAD']

    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(start)
        single = exe.run(main, feed={'x': X, 'y': Y}, fetch_list=grads)

    set_default_mesh(make_mesh({'dp': 8}))
    compiled = fluid.CompiledProgram(main).with_data_parallel(
        loss_name=loss.name)
    exe2 = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe2.run(start)
        sharded = exe2.run(compiled, feed={'x': X, 'y': Y}, fetch_list=grads)

    for s, d, name in zip(single, sharded, grads):
        np.testing.assert_allclose(s, d, rtol=1e-5, atol=1e-6,
                                   err_msg=name)


def test_fleet_dp_loss_and_params_match_single():
    """Same training trajectory with and without the 8-way sharded feeds."""
    def build(loss):
        fleet.distributed_optimizer(
            fluid.optimizer.SGD(0.1), strategy=DistributedStrategy()
        ).minimize(loss)

    rng = np.random.RandomState(1)
    X = rng.randn(32, 2).astype('float32')
    Y = (X @ np.array([[1.0], [-2.0]], 'float32')).astype('float32')

    def train(parallel):
        fleet.init()
        main, start, loss = _linreg_program(build, 'fleet_dp_w')
        prog = main
        if parallel:
            prog = fluid.CompiledProgram(main).with_data_parallel(
                loss_name=loss.name)
        exe = fluid.Executor()
        with fluid.scope_guard(fluid.Scope()):
            exe.run(start)
            for _ in range(5):
                out = exe.run(prog, feed={'x': X, 'y': Y},
                              fetch_list=['fleet_dp_w'])
            return out[0]

    w_single = train(False)
    set_default_mesh(make_mesh({'dp': 8}))
    w_dp = train(True)
    np.testing.assert_allclose(w_single, w_dp, rtol=1e-5, atol=1e-6)


def test_tp_parity_through_fleet_mesh():
    """TP matmuls pick up the fleet-installed hybrid mesh (dp×tp)."""
    fleet.init(mesh_shape={'dp': 4, 'tp': 2})
    mesh = get_default_mesh()
    assert set(mesh.axis_names) == {'dp', 'tp'}
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(4, 16).astype('float32'))
    w1 = jnp.asarray(rng.randn(16, 32).astype('float32'))
    w2 = jnp.asarray(rng.randn(32, 16).astype('float32'))
    h = column_parallel_matmul(x, w1)            # mesh=None → fleet default
    y = row_parallel_matmul(h, w2)
    np.testing.assert_allclose(np.asarray(y), np.asarray(x @ w1 @ w2),
                               rtol=1e-4, atol=1e-4)


def test_ps_script_runs_unmodified():
    """A reference-shaped PS fleet script trains end-to-end (lowered to
    collective DP; ref: incubate/fleet/parameter_server/distribute_transpiler
    usage pattern)."""
    from paddle_tpu.incubate.fleet.parameter_server.distribute_transpiler \
        import fleet as ps_fleet
    from paddle_tpu.incubate.fleet.base import role_maker

    role = role_maker.PaddleCloudRoleMaker()
    ps_fleet.init(role)
    assert not ps_fleet.is_server()
    assert ps_fleet.is_worker()

    main, start = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, start):
        x = layers.data('x', shape=[4], dtype='float32')
        y = layers.data('y', shape=[1], dtype='float32')
        pred = layers.fc(x, 1)
        loss = layers.mean(layers.square_error_cost(pred, y))
        # the lowering must announce the semantics change exactly once
        import paddle_tpu.transpiler as _tp
        _tp._ps_warned = False
        with pytest.warns(UserWarning, match='SYNCHRONOUS collective'):
            opt = ps_fleet.distributed_optimizer(
                fluid.optimizer.SGD(0.05),
                fluid.DistributeTranspilerConfig())
        opt.minimize(loss)

    if ps_fleet.is_server():
        ps_fleet.init_server()
        ps_fleet.run_server()
    else:
        ps_fleet.init_worker()
        exe = fluid.Executor()
        rng = np.random.RandomState(3)
        X = rng.randn(16, 4).astype('float32')
        Y = (X @ rng.randn(4, 1)).astype('float32')
        with fluid.scope_guard(fluid.Scope()):
            exe.run(start)
            losses = [float(exe.run(main, feed={'x': X, 'y': Y},
                                    fetch_list=[loss])[0])
                      for _ in range(20)]
        ps_fleet.stop_worker()
        assert losses[-1] < losses[0] * 0.5


def test_distribute_transpiler_shim():
    main, start = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, start):
        x = layers.data('x', shape=[2], dtype='float32')
        pred = layers.fc(x, 1)
        loss = layers.mean(pred)
        fluid.optimizer.SGD(0.1).minimize(loss)

    config = fluid.DistributeTranspilerConfig()
    t = fluid.DistributeTranspiler(config=config)
    t.transpile(trainer_id=0, program=main,
                pservers='127.0.0.1:6174,127.0.0.1:6175', trainers=2,
                startup_program=start)
    trainer_prog = t.get_trainer_program()
    assert trainer_prog is main                    # collective DP: unchanged
    ps_prog = t.get_pserver_program('127.0.0.1:6174')
    assert isinstance(ps_prog, fluid.Program)
    with pytest.raises(ValueError):
        t.get_pserver_program('10.0.0.1:9999')
    # trainer program still runs
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(t.get_startup_program())
        out = exe.run(trainer_prog,
                      feed={'x': np.ones((4, 2), 'float32')},
                      fetch_list=[loss])
    assert np.isfinite(out[0]).all()


def test_local_sgd_divergent_replicas():
    """True LocalSGD (shard_map path): replicas diverge between syncs and
    equalize on the sync step; k=1 matches fully-synchronous DP."""
    mesh = make_mesh({'dp': 8})
    rng = np.random.RandomState(4)
    W = rng.randn(3, 1).astype('float32')
    X = rng.randn(64, 3).astype('float32')
    Y = (X @ W).astype('float32')
    batch = np.concatenate([X, Y], axis=1)       # (64, 4) shardable

    def loss_fn(params, b):
        x, y = b[:, :3], b[:, 3:]
        return jnp.mean((x @ params['w'] - y) ** 2)

    k = 4
    step = LocalSGDStep(loss_fn, {'w': np.zeros((3, 1), 'float32')},
                        mesh, k_steps=k, lr=0.05)
    for t in range(k - 1):
        step(batch)
    assert not step.replicas_in_sync()           # diverged mid-window
    step(batch)                                  # k-th step → pmean
    assert step.replicas_in_sync()

    # k=1 == synchronous DP (global-mean gradient every step)
    sync = LocalSGDStep(loss_fn, {'w': np.zeros((3, 1), 'float32')},
                        mesh, k_steps=1, lr=0.05)
    w_ref = jnp.zeros((3, 1))
    for t in range(5):
        sync(batch)
        g = jax.grad(lambda p: loss_fn({'w': p}, jnp.asarray(batch)))(w_ref)
        w_ref = w_ref - 0.05 * g
    np.testing.assert_allclose(np.asarray(sync.averaged_params()['w']),
                               np.asarray(w_ref), rtol=1e-4, atol=1e-5)
