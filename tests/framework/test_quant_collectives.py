"""Block-quantized collectives (parallel/quant_collectives.py): codec
round-trip error bounds (all-zero / single-element / tail cases), EQuARX
two-phase all-reduce vs the exact psum on the 8-device CPU mesh, the
f32 passthrough's bitwise exactness, comm-dtype strict parsing, the
wired sync points (LocalSGD / geo-SGD / FSDP / dygraph bundles), and the
bytes-on-wire telemetry (the ≥3.5x int8 acceptance at the counter level).
"""
import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P
import pytest

from paddle_tpu import observability as obs
from paddle_tpu.core import compat
from paddle_tpu.parallel import quant_collectives as qc
from paddle_tpu.parallel.mesh import make_mesh


@pytest.fixture
def mesh8():
    return make_mesh({'dp': 8})


def _allreduce(X, mesh, comm, block_size=None, op='sum'):
    """Row i of X = device i's local value; returns the replicated result."""
    fn = qc.qallreduce_sum if op == 'sum' else qc.qallreduce_mean

    def body(v):
        return fn(v[0], 'dp', comm_dtype=comm, block_size=block_size)[None]

    return np.asarray(compat.shard_map(
        body, mesh=mesh, in_specs=P('dp'), out_specs=P('dp'))(
        jnp.asarray(X)))


# ---------------------------------------------------------------------------
# strict parsing
# ---------------------------------------------------------------------------

def test_comm_dtype_strict_parse(monkeypatch):
    monkeypatch.delenv(qc.ENV_COMM_DTYPE, raising=False)
    assert qc.resolve_comm_dtype() == 'f32'
    assert qc.resolve_comm_dtype('int8') == 'int8'
    with pytest.raises(ValueError) as e:
        qc.resolve_comm_dtype('int4')
    for name in qc.SUPPORTED_COMM_DTYPES:
        assert name in str(e.value)            # message lists the set
    # env wins over the argument, and parses strictly too
    monkeypatch.setenv(qc.ENV_COMM_DTYPE, 'bf16')
    assert qc.resolve_comm_dtype('int8') == 'bf16'
    monkeypatch.setenv(qc.ENV_COMM_DTYPE, 'fp8')
    with pytest.raises(ValueError, match='PADDLE_TPU_COMM_DTYPE'):
        qc.resolve_comm_dtype()


def test_distributed_strategy_comm_dtype_strict():
    from paddle_tpu.parallel import DistributedStrategy
    s = DistributedStrategy()
    assert s.comm_dtype == 'f32'
    s.comm_dtype = 'int8'
    assert s.comm_dtype == 'int8'
    with pytest.raises(ValueError) as e:
        s.comm_dtype = 'float16'
    assert 'int8' in str(e.value) and 'bf16' in str(e.value)


# ---------------------------------------------------------------------------
# codec round trip
# ---------------------------------------------------------------------------

@pytest.mark.parametrize('size', [1, 17, 255, 256, 257, 4097])
def test_block_roundtrip_error_bound(size):
    """Per-block bound of the symmetric round-to-nearest codec: every
    element's round-trip error <= its block's absmax/254. Sizes cover the
    single-element and non-multiple-of-block-size tails."""
    rng = np.random.RandomState(size)
    bs = 64
    x = (rng.randn(size) * rng.uniform(0.1, 100)).astype('float32')
    q, s = qc.block_quantize(x, block_size=bs)
    rt = np.asarray(qc.block_dequantize(q, s, shape=(size,), block_size=bs))
    padded = -(-size // bs) * bs
    blocks = np.pad(x, (0, padded - size)).reshape(-1, bs)
    bound = np.repeat(np.abs(blocks).max(1) / 254.0, bs)[:size]
    assert np.all(np.abs(rt - x) <= bound * (1 + 1e-6) + 1e-30)


def test_block_roundtrip_exact_cases():
    # all-zero: scale 0 decodes to exact zeros (no 0/0)
    q, s = qc.block_quantize(np.zeros(300, np.float32), block_size=128)
    assert np.all(np.asarray(s) == 0)
    assert np.all(np.asarray(
        qc.block_dequantize(q, s, shape=(300,), block_size=128)) == 0)
    # single element: its own absmax maps to exactly +/-127
    for v in (3.7, -0.001, 1e-20):
        q, s = qc.block_quantize(np.asarray([v], np.float32))
        rt = qc.block_dequantize(q, s, shape=(1,))
        np.testing.assert_allclose(np.asarray(rt), [np.float32(v)],
                                   rtol=1e-6)


# ---------------------------------------------------------------------------
# two-phase all-reduce on the 8-device mesh
# ---------------------------------------------------------------------------

def test_qallreduce_f32_passthrough_bitwise(mesh8):
    rng = np.random.RandomState(0)
    X = rng.randn(8, 1000).astype('float32')

    def psum_body(v):
        return lax.psum(v[0], 'dp')[None]

    want = np.asarray(compat.shard_map(
        psum_body, mesh=mesh8, in_specs=P('dp'), out_specs=P('dp'))(
        jnp.asarray(X)))
    got = _allreduce(X, mesh8, 'f32')
    assert np.array_equal(got, want)            # bitwise, not approximate


@pytest.mark.parametrize('size', [1, 130, 1000, 2048])
def test_qallreduce_int8_error_bound(mesh8, size):
    """Error contract: two codec stages around an exact f32 partial sum —
    elementwise error <= sum_i absmax_i/254 + absmax_reduced/254 (using
    the loose global-absmax form of the per-block bound)."""
    rng = np.random.RandomState(size)
    X = (rng.randn(8, size) * rng.uniform(0.5, 5, (8, 1))).astype('float32')
    want = X.sum(0)
    got = _allreduce(X, mesh8, 'int8')
    assert got.shape == (8, size)
    for i in range(8):                           # replicated result
        assert np.array_equal(got[i], got[0])
    bound = (np.abs(X).max(axis=1).sum() + np.abs(want).max()) / 254.0
    err = np.abs(got[0] - want).max()
    assert err <= bound * (1 + 1e-6), (err, bound)
    if size >= 1000:
        assert err / np.abs(want).max() < 0.02   # quality, not just bound


def test_qallreduce_all_zero_and_mean(mesh8):
    Z = np.zeros((8, 513), np.float32)
    assert np.all(_allreduce(Z, mesh8, 'int8') == 0)
    rng = np.random.RandomState(1)
    X = rng.randn(8, 512).astype('float32')
    got = _allreduce(X, mesh8, 'int8', op='mean')
    err = np.abs(got[0] - X.mean(0)).max()
    assert err < np.abs(X.mean(0)).max() * 0.1 + 0.05


def test_qallreduce_bf16(mesh8):
    rng = np.random.RandomState(2)
    X = rng.randn(8, 700).astype('float32')
    got = _allreduce(X, mesh8, 'bf16')
    want = X.sum(0)
    # bf16 has ~8 mantissa bits: relative error ~2^-8 per codec pass
    assert np.abs(got[0] - want).max() <= np.abs(X).max() * 8 * 2 ** -7


def test_qreduce_scatter_matches_psum_scatter(mesh8):
    rng = np.random.RandomState(3)
    X = rng.randn(8, 16, 24).astype('float32')

    def f32_body(v):
        return qc.qreduce_scatter_sum(v[0], 'dp', comm_dtype='f32',
                                      scattered_dimension=1)[None]

    def ref_body(v):
        return lax.psum_scatter(v[0], 'dp', scatter_dimension=1,
                                tiled=True)[None]

    for body in (f32_body,):
        got = np.asarray(compat.shard_map(
            body, mesh=mesh8, in_specs=P('dp'), out_specs=P('dp'))(
            jnp.asarray(X)))
        want = np.asarray(compat.shard_map(
            ref_body, mesh=mesh8, in_specs=P('dp'), out_specs=P('dp'))(
            jnp.asarray(X)))
        assert np.array_equal(got, want)         # exact passthrough

    def int8_body(v):
        return qc.qreduce_scatter_sum(v[0], 'dp', comm_dtype='int8',
                                      scattered_dimension=1)[None]

    got = np.asarray(compat.shard_map(
        int8_body, mesh=mesh8, in_specs=P('dp'), out_specs=P('dp'))(
        jnp.asarray(X)))
    full = X.sum(0)                              # (16, 24)
    for d in range(8):       # device d holds tile d of the scattered dim
        tile = full[:, d * 3:(d + 1) * 3]
        err = np.abs(got[d] - tile).max()
        assert err <= (np.abs(X).max() * 8 / 254.0) * (1 + 1e-6)


def test_qreduce_scatter_indivisible_raises(mesh8):
    def body(v):
        return qc.qreduce_scatter_sum(v[0], 'dp', comm_dtype='int8')[None]

    with pytest.raises(ValueError, match='not divisible'):
        compat.shard_map(body, mesh=mesh8, in_specs=P('dp'),
                         out_specs=P('dp'))(jnp.ones((8, 9, 4)))


# ---------------------------------------------------------------------------
# wired sync points
# ---------------------------------------------------------------------------

def test_fsdp_reduce_scatter_grads():
    from paddle_tpu.parallel.fsdp import (param_shard_bytes,
                                          reduce_scatter_grads)
    mesh = make_mesh({'fsdp': 8})
    rng = np.random.RandomState(0)
    g = {'w1': rng.randn(8, 16, 24).astype('float32'),
         'bias': rng.randn(8, 5).astype('float32')}   # 5: replicated path
    for comm, tol in (('f32', 0.0), ('int8', None)):
        out = reduce_scatter_grads(g, mesh, comm_dtype=comm)
        assert np.asarray(out['w1']).shape == (16, 24)
        assert np.asarray(out['bias']).shape == (5,)
        # the sharded output holds 1/8 of the bytes per device
        assert param_shard_bytes(out['w1']) * 8 == 16 * 24 * 4
        for name in g:
            want = g[name].sum(0)
            err = np.abs(np.asarray(out[name]) - want).max()
            bound = (np.abs(g[name]).max() * 9 / 254.0) * (1 + 1e-6) \
                if tol is None else 0.0
            assert err <= bound, (comm, name, err)


def test_local_sgd_int8_parity(mesh8):
    """LocalSGD with int8 sync tracks the f32 run closely (same data) and
    replicas still converge to one value at sync boundaries."""
    from paddle_tpu.parallel import LocalSGDStep
    rng = np.random.RandomState(0)
    wt = rng.randn(3, 1).astype('float32')
    batches = [rng.randn(16, 3).astype('float32') for _ in range(4)]

    def loss_fn(p, b):
        x, y = b[..., :-1], b[..., -1:]
        return jnp.mean((x @ p['w'] - y) ** 2)

    finals = {}
    for comm in ('f32', 'int8'):
        step = LocalSGDStep(loss_fn, {'w': np.zeros((3, 1), np.float32)},
                            mesh8, k_steps=2, lr=0.05, comm_dtype=comm)
        for x in batches:
            step(np.concatenate([x, x @ wt], -1))
        assert step.replicas_in_sync(rtol=1e-5), comm
        finals[comm] = np.asarray(step.averaged_params()['w'])
    np.testing.assert_allclose(finals['int8'], finals['f32'], atol=0.05)


def test_geo_sgd_int8_parity(mesh8):
    from paddle_tpu.parallel import GeoSGDStep
    rng = np.random.RandomState(1)
    wt = rng.randn(3, 1).astype('float32')
    batches = [rng.randn(16, 3).astype('float32') for _ in range(4)]

    def loss_fn(p, b):
        x, y = b[..., :-1], b[..., -1:]
        return jnp.mean((x @ p['w'] - y) ** 2)

    finals = {}
    for comm in ('f32', 'int8'):
        step = GeoSGDStep(loss_fn, {'w': np.zeros((3, 1), np.float32)},
                          mesh8, need_push_nums=2, lr=0.05, comm_dtype=comm)
        for x in batches:
            step(np.concatenate([x, x @ wt], -1))
        assert step.replicas_in_sync(rtol=1e-4), comm
        finals[comm] = np.asarray(step.base_params()['w'])
    np.testing.assert_allclose(finals['int8'], finals['f32'], atol=0.05)


def test_dygraph_bundle_one_reduce_per_dtype():
    """apply_collective_grads' bundling: ALL grads flatten into one bundle
    per dtype and the reducer runs ONCE per bundle, not per parameter."""
    from paddle_tpu import dygraph
    from paddle_tpu.dygraph.nn import Linear
    from paddle_tpu.dygraph.parallel import _allreduce_bundles
    with dygraph.guard():
        model = Linear(6, 4)
        params = list(model.parameters())       # weight + bias
        assert len(params) >= 2
        rng = np.random.RandomState(0)
        wants = []
        for p in params:
            g = rng.randn(*np.shape(p.value)).astype('float32')
            p.grad = jnp.asarray(g)
            wants.append(g)
        calls = []

        def fake_reduce(flat):
            calls.append(int(flat.shape[0]))
            return flat * 2.0

        n_calls = _allreduce_bundles(params, fake_reduce)
        assert n_calls == 1 and len(calls) == 1     # ONE reduce for all
        assert calls[0] == sum(g.size for g in wants)
        for p, g in zip(params, wants):
            np.testing.assert_allclose(np.asarray(p.grad), g * 2, rtol=1e-6)

        # mixed dtypes: one bundle per dtype group
        params[0].grad = jnp.asarray(wants[0], jnp.bfloat16)
        calls.clear()
        assert _allreduce_bundles(params, fake_reduce) == 2
        assert len(calls) == 2


def test_static_c_allreduce_unbound_axis_is_identity():
    """The graph op lowers to identity outside a shard_map (single-replica
    semantics) — what fleet's inserted sync points do on the GSPMD
    executor — and to a real psum when the axis is bound."""
    from paddle_tpu.ops.registry import get_op
    x = jnp.asarray(np.arange(6.0, dtype=np.float32))
    out = get_op('c_allreduce_sum').fn(x, axis='dp')
    assert np.array_equal(np.asarray(out), np.asarray(x))
    mesh = make_mesh({'dp': 8})
    got = np.asarray(compat.shard_map(
        lambda v: get_op('c_allreduce_sum').fn(v[0], axis='dp')[None],
        mesh=mesh, in_specs=P('dp'), out_specs=P('dp'))(
        jnp.ones((8, 4))))
    assert np.all(got == 8.0)


# ---------------------------------------------------------------------------
# telemetry
# ---------------------------------------------------------------------------

def test_collective_telemetry_counters():
    """bytes-on-wire accounting: the int8/f32 ratio at the counter level
    is the >=3.5x acceptance; the error histogram records codec passes."""
    with obs.telemetry_guard(True):
        obs.reset()
        elems = 1 << 20
        qc.record_collective('testpath', elems, 'int8', 8)
        qc.record_collective('testpath', elems, 'f32', 8)
        qc.record_quant_error(
            'testpath', np.random.RandomState(0).randn(4096)
            .astype('float32'), 'int8')
        m = obs.registry.to_dict()
        by_dtype = {s['labels']['dtype']: s['value']
                    for s in m['collective_bytes_on_wire']['samples']}
        assert by_dtype['f32'] / by_dtype['int8'] >= 3.5
        f32eq = sum(s['value']
                    for s in m['collective_bytes_f32_equiv']['samples'])
        assert f32eq == 2 * by_dtype['f32']     # one equiv line per call
        calls = sum(s['value']
                    for s in m['collective_sync_calls']['samples'])
        assert calls == 2
        errs = m['collective_quant_rel_error']['samples']
        assert sum(s['count'] for s in errs) == 1
        assert 0 < max(s['max'] for s in errs) < 0.05
    # axis size 1 moves zero bytes (passthrough is local)
    assert qc.wire_bytes(elems, 'int8', 1) == 0


def test_local_sgd_records_sync_bytes(mesh8):
    from paddle_tpu.parallel import LocalSGDStep

    def loss_fn(p, b):
        return jnp.mean((b[..., :-1] @ p['w'] - b[..., -1:]) ** 2)

    rng = np.random.RandomState(0)
    with obs.telemetry_guard(True):
        obs.reset()
        step = LocalSGDStep(loss_fn, {'w': np.zeros((3, 1), np.float32)},
                            mesh8, k_steps=2, lr=0.05, comm_dtype='int8')
        for _ in range(4):                       # 2 sync boundaries
            step(rng.randn(16, 4).astype('float32'))
        m = obs.registry.to_dict()
        calls = {s['labels']['path']: s['value']
                 for s in m['collective_sync_calls']['samples']}
        assert calls.get('local_sgd') == 2
