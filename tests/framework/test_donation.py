"""Donation-safety suite (hot-path memory overhaul): donated train-step
buffers are invalidated (deleted-buffer semantics), fetch-aliased variables
are provably excluded, BuildStrategy/env opt-outs work, and the bf16
gradient-merge accumulators keep the lax.cond branches dtype-consistent."""
import warnings

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import dygraph
from paddle_tpu.dygraph.jit import TrainStep
from paddle_tpu.dygraph.nn import Linear
from paddle_tpu.dygraph.tape import dispatch_op


def _mse(m, x, y):
    d = dispatch_op('elementwise_sub', {'x': m(x), 'y': y}, {})
    sq = dispatch_op('elementwise_mul', {'x': d, 'y': d}, {})
    return dispatch_op('reduce_mean', {'x': sq}, {})


def _batch(seed=0):
    rng = np.random.RandomState(seed)
    return (rng.randn(8, 4).astype(np.float32),
            rng.randn(8, 1).astype(np.float32))


@pytest.fixture(autouse=True)
def _quiet_cpu_donation_warning():
    # CPU XLA cannot alias donated buffers and warns; jax still invalidates
    # the donated arrays, which is exactly the semantics under test
    with warnings.catch_warnings():
        warnings.simplefilter('ignore')
        yield


# ---------------------------------------------------------------------------
# TrainStep (dygraph fused path)
# ---------------------------------------------------------------------------

def test_train_step_donates_params_and_slots():
    x, y = _batch()
    with dygraph.guard():
        m = Linear(4, 1)
        opt = fluid.optimizer.Momentum(
            0.1, momentum=0.9, parameter_list=m.parameters())
        step = TrainStep(m, _mse, opt)
        old_w = m.weight.value
        step(x, y)
        assert old_w.is_deleted(), \
            "param buffer must be donated into the fused step"
        old_slot = step._slots['weight']['velocity']
        step(x, y)
        assert old_slot.is_deleted(), \
            "optimizer-state buffer must be donated into the fused step"
        # the live handles were rebound to the step outputs and still work
        assert np.isfinite(np.asarray(m.weight.value)).all()


def test_train_step_donate_false_keeps_buffers():
    x, y = _batch()
    with dygraph.guard():
        m = Linear(4, 1)
        opt = fluid.optimizer.SGD(0.1, parameter_list=m.parameters())
        step = TrainStep(m, _mse, opt, donate=False)
        old_w = m.weight.value
        step(x, y)
        assert not old_w.is_deleted()
        np.testing.assert_allclose(np.asarray(old_w), np.asarray(old_w))


def test_train_step_donation_numerics_unchanged():
    x, y = _batch()
    got = {}
    for donate in (True, False):
        with dygraph.guard():
            from paddle_tpu.core.random import seed as set_seed
            set_seed(3)
            m = Linear(4, 1)
            opt = fluid.optimizer.SGD(0.1, parameter_list=m.parameters())
            step = TrainStep(m, _mse, opt, donate=donate)
            for _ in range(3):
                loss = step(x, y)
            got[donate] = (float(loss),
                           {n: np.asarray(p.value)
                            for n, p in m.named_parameters()})
    assert got[True][0] == pytest.approx(got[False][0], rel=1e-6)
    for n in got[True][1]:
        np.testing.assert_allclose(got[True][1][n], got[False][1][n],
                                   rtol=1e-6, atol=1e-7)


def test_gradient_merge_bf16_accumulators():
    """ADVICE r5: bf16 params + accum_steps>1 must compile (accumulators in
    the gradient dtype; both lax.cond branches agree) and keep bf16 params."""
    import jax.numpy as jnp
    x, y = _batch()
    with dygraph.guard():
        m = Linear(4, 1)
        for p in m.parameters():
            p.value = p.value.astype(jnp.bfloat16)
        opt = fluid.optimizer.Momentum(
            0.05, momentum=0.9, parameter_list=m.parameters())
        step = TrainStep(m, _mse, opt, accum_steps=2)
        w0 = np.asarray(m.weight.value, np.float32).copy()
        losses = [float(step(x, y)) for _ in range(4)]
        assert m.weight.value.dtype == jnp.bfloat16
        assert step._acc['weight'].dtype == jnp.bfloat16
        assert all(np.isfinite(losses))
        assert not np.allclose(np.asarray(m.weight.value, np.float32), w0), \
            "two merged applications must have moved the params"


# ---------------------------------------------------------------------------
# Executor (static path)
# ---------------------------------------------------------------------------

def _build_sgd_program():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data(name='x', shape=[4, 3], dtype='float32')
        y = fluid.data(name='y', shape=[4, 1], dtype='float32')
        pred = fluid.layers.fc(input=x, size=1)
        loss = fluid.layers.reduce_mean(fluid.layers.square(pred - y))
        fluid.optimizer.SGD(0.1).minimize(loss)
    return main, startup, loss


def _feed(seed=0):
    rng = np.random.RandomState(seed)
    return {'x': rng.randn(4, 3).astype(np.float32),
            'y': rng.randn(4, 1).astype(np.float32)}


def test_executor_donates_nonfetched_state():
    main, startup, loss = _build_sgd_program()
    exe = fluid.Executor()
    exe.run(startup)
    scope = fluid.global_scope()
    pname = next(n for n in (v.name for v in main.list_vars()
                             if v.persistable) if '.w_' in n)
    old = scope.find(pname)
    exe.run(main, feed=_feed(), fetch_list=[loss.name])
    assert old.is_deleted(), \
        "non-fetched persistable state must be donated into the step"
    # the scope now holds the step's output buffer — further runs work
    out = exe.run(main, feed=_feed(), fetch_list=[loss.name])
    assert np.isfinite(out[0]).all()


def test_executor_fetch_aliased_var_never_donated():
    main, startup, loss = _build_sgd_program()
    exe = fluid.Executor()
    exe.run(startup)
    scope = fluid.global_scope()
    pname = next(n for n in (v.name for v in main.list_vars()
                             if v.persistable) if '.w_' in n)
    old = scope.find(pname)
    before = np.asarray(old).copy()
    outs = exe.run(main, feed=_feed(), fetch_list=[loss.name, pname])
    assert not old.is_deleted(), \
        "a fetch-aliased persistable must be excluded from donation"
    np.testing.assert_allclose(np.asarray(old), before)   # still readable
    assert np.isfinite(outs[1]).all()


def test_executor_build_strategy_inplace_off_disables_donation():
    main, startup, loss = _build_sgd_program()
    exe = fluid.Executor()
    exe.run(startup)
    scope = fluid.global_scope()
    pname = next(n for n in (v.name for v in main.list_vars()
                             if v.persistable) if '.w_' in n)
    bs = fluid.compiler.BuildStrategy()
    bs.enable_inplace = False
    cp = fluid.compiler.CompiledProgram(main, build_strategy=bs)
    old = scope.find(pname)
    exe.run(cp, feed=_feed(), fetch_list=[loss.name])
    assert not old.is_deleted()


def test_executor_env_hatch_disables_donation(monkeypatch):
    monkeypatch.setenv('PADDLE_TPU_DONATE', '0')
    main, startup, loss = _build_sgd_program()
    exe = fluid.Executor()
    exe.run(startup)
    scope = fluid.global_scope()
    pname = next(n for n in (v.name for v in main.list_vars()
                             if v.persistable) if '.w_' in n)
    old = scope.find(pname)
    exe.run(main, feed=_feed(), fetch_list=[loss.name])
    assert not old.is_deleted()


def test_executor_donation_numerics_unchanged(monkeypatch):
    results = {}
    for donate in ('1', '0'):
        monkeypatch.setenv('PADDLE_TPU_DONATE', donate)
        main, startup, loss = _build_sgd_program()
        exe = fluid.Executor()
        exe.run(startup)
        pname = next(n for n in (v.name for v in main.list_vars()
                                 if v.persistable) if '.w_' in n)
        fluid.global_scope().set(
            pname, np.full_like(
                np.asarray(fluid.global_scope().find(pname)), 0.25))
        vals = [exe.run(main, feed=_feed(i), fetch_list=[loss.name])[0]
                for i in range(3)]
        results[donate] = np.concatenate([np.ravel(v) for v in vals])
    np.testing.assert_allclose(results['1'], results['0'], rtol=1e-6)
