"""Elastic resize drills (ISSUE 19): real multi-process fleets resized
across restore.

1. **Shrink 4→2 under a crash:** a 4-worker fleet is SIGKILLed mid-run;
   the fleet restarts at 2 workers from the 4-wide sharded checkpoint
   (reshard-on-restore: full values reassembled, re-laid onto the 2-wide
   mesh). Acceptance is bitwise: two independent 2-worker resumes from
   byte-identical copies of the same checkpoint directory produce
   identical loss trajectories — resharding is deterministic, and the
   crash loss books in the CRASH bucket (resizes stays 0).
2. **Scheduled grow 2→4:** ``PADDLE_TPU_ELASTIC_RESIZE=at_step=N:nproc=4``
   makes every worker commit a synchronous checkpoint at the boundary,
   write ``resize.json``, and exit FLEET_EXIT_CODE (75) — the PR 12
   resume ladder. The relaunched 4-worker fleet resumes at N+1 with
   goodput booking the resize exactly once: ``resizes == 1``,
   ``lost_steps == 0`` (scheduled ≠ crash), ``resize_lost_s > 0``.
"""
import json
import os
import shutil
import signal
import sys

import pytest

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), '..', '..'))

# Same deterministic fleet program as test_fleet_crash_resume.py, plus the
# resize exit: when end_of_step returns True with `resize_requested` set,
# the loop leaves through exit_for_resume (75) after flushing the manager.
TRAIN_SCRIPT = r'''
import json, os, sys
import numpy as np
import paddle_tpu as fluid
from paddle_tpu import layers as L
from paddle_tpu import resilience
from paddle_tpu.fleet_runtime import (bootstrap, check_poisoned,
                                      exit_for_resume, FLEET_EXIT_CODE)

ckpt_dir, log_path, total_steps = sys.argv[1], sys.argv[2], int(sys.argv[3])
bootstrap()
import jax
rank = jax.process_index()

fluid.seed(1234)
main, startup = fluid.Program(), fluid.Program()
with fluid.program_guard(main, startup):
    x = L.data('cx', [8], dtype='float32')
    y = L.data('cy', [1], dtype='float32')
    h = L.fc(x, size=16, act='relu')
    h = L.dropout(h, dropout_prob=0.3)
    pred = L.fc(h, size=1)
    loss = L.reduce_mean(L.square_error_cost(pred, y))
    from paddle_tpu.parallel import DistributedStrategy, fleet
    fleet.init(mesh_shape={'fsdp': jax.device_count()})
    strat = DistributedStrategy()
    strat.sharding = True
    fleet.distributed_optimizer(
        fluid.optimizer.Adam(learning_rate=1e-2), strategy=strat,
    ).minimize(loss)

exe = fluid.Executor()
exe.run(startup)

blk = main.global_block()
loader = fluid.DataLoader.from_generator(
    feed_list=[blk.var('cx'), blk.var('cy')], capacity=4)
loader.shard_for_fleet()

def epoch_batches(epoch, n=5):
    rng = np.random.RandomState(100 + epoch)
    return [(rng.randn(8, 8).astype(np.float32),
             rng.randn(8, 1).astype(np.float32)) for _ in range(n)]

loader.set_batch_generator(lambda: iter(epoch_batches(loader.epoch)))

mgr = resilience.CheckpointManager(ckpt_dir, every_n_steps=3, keep=3)
step = 0
got = mgr.restore()
if got is not None:
    arrays, meta = got
    resilience.restore_training_state(arrays, meta, executor=exe,
                                      program=main, loader=loader)
    step = meta['step']
    if rank == 0:
        with open(log_path + '.goodput', 'w') as f:
            json.dump(mgr.goodput.meta(), f)

log = open(log_path, 'a') if rank == 0 else None
stopped = False
while step < total_steps and not stopped:
    for batch in loader():
        try:
            lv = exe.run(main, feed=batch, fetch_list=[loss])[0]
        except Exception:
            rec = check_poisoned()
            if rec is not None:
                mgr.close()
                exit_for_resume(rec)
            raise
        step += 1
        if log:
            log.write(json.dumps({'step': step,
                                  'loss': np.asarray(lv).tobytes().hex()})
                      + '\n')
            log.flush()
        stopped = mgr.end_of_step(
            step, lambda: resilience.capture_training_state(
                executor=exe, program=main, loader=loader))
        if stopped or step >= total_steps:
            break
mgr.wait()
mgr.close()
if log:
    log.close()
if mgr.resize_requested is not None:
    # the elastic ladder: checkpoint committed + resize.json written by
    # end_of_step; leave through the fleet resume exit (75)
    exit_for_resume()
if mgr.fleet_poisoned is not None:
    exit_for_resume(mgr.fleet_poisoned)
'''


def _write_script(tmp_path):
    script = tmp_path / 'elastic_train.py'
    if not script.exists():
        script.write_text(TRAIN_SCRIPT)
    return script


def _run_fleet(tmp_path, name, nproc, ckpt_dir, total_steps, env=None,
               rank_env=None, timeout=240):
    """Launch an `nproc`-worker fleet; returns (rcs, {step: loss_hex})."""
    sys.path.insert(0, REPO)
    from paddle_tpu.fleet_runtime.bootstrap import local_fleet
    script = _write_script(tmp_path)
    log = tmp_path / f'{name}.jsonl'
    base = {
        'PYTHONPATH': REPO,
        'PADDLE_TPU_METRICS_DIR': str(tmp_path / f'{name}_metrics'),
        'PADDLE_TPU_WATCHDOG': '1',
        'PADDLE_TPU_WATCHDOG_FLOOR_S': '6',
        'PADDLE_TPU_WATCHDOG_COLD_S': '90',
        'PADDLE_TPU_VERIFY': 'off',
    }
    base.update(env or {})
    outs = []

    def stdout(rank):
        f = open(tmp_path / f'{name}.r{rank}.out', 'w')
        outs.append(f)
        return f

    fl = local_fleet(nproc, script, args=[ckpt_dir, log, total_steps],
                     env=base, rank_env=rank_env, stdout=stdout, cwd=REPO)
    rcs = fl.wait(timeout=timeout)
    for f in outs:
        f.close()
    losses = {}
    if log.exists():
        for line in log.read_text().splitlines():
            if line.strip():
                rec = json.loads(line)
                losses[rec['step']] = rec['loss']
    return rcs, losses


def _rank_out(tmp_path, name, rank):
    p = tmp_path / f'{name}.r{rank}.out'
    return p.read_text()[-3000:] if p.exists() else '<no output>'


def test_shrink_4_to_2_bitwise_vs_same_size_reference(tmp_path):
    """Kill a 4-worker fleet, resume TWICE at 2 workers from byte-equal
    checkpoint copies: both resumes restore the 4-wide sharded state onto
    the 2-wide mesh and must agree bitwise step for step."""
    total = 10
    ck = tmp_path / 'ck4'
    rcs, crash = _run_fleet(
        tmp_path, 'crash4', 4, ck, total,
        rank_env={2: {'PADDLE_TPU_FAULT_INJECT': 'kill@step=8'}})
    assert rcs[2] == -signal.SIGKILL, (rcs, _rank_out(tmp_path, 'crash4', 2))
    assert 0 not in rcs, (rcs, _rank_out(tmp_path, 'crash4', 0))
    assert max(crash) >= 7          # the step-6 checkpoint committed
    from paddle_tpu.resilience import snapshot as snap
    ck0 = snap.latest_checkpoint(str(ck))
    assert ck0 is not None and ck0.step == 6 and ck0.manifest['world'] == 4

    # byte-identical second copy BEFORE any resume touches the directory
    ck_copy = tmp_path / 'ck4_copy'
    shutil.copytree(ck, ck_copy)

    rcs, resumed = _run_fleet(tmp_path, 'shrink', 2, ck, total)
    assert rcs == [0, 0], (rcs, _rank_out(tmp_path, 'shrink', 0),
                           _rank_out(tmp_path, 'shrink', 1))
    rcs, reference = _run_fleet(tmp_path, 'shrinkref', 2, ck_copy, total)
    assert rcs == [0, 0], (rcs, _rank_out(tmp_path, 'shrinkref', 0),
                           _rank_out(tmp_path, 'shrinkref', 1))

    # both played exactly steps 7..total after restoring step 6
    assert sorted(resumed) == list(range(7, total + 1))
    assert sorted(reference) == sorted(resumed)
    mismatches = {s: (resumed[s], reference[s]) for s in resumed
                  if resumed[s] != reference[s]}
    assert not mismatches, \
        f'reshard-on-restore is not deterministic: {mismatches}'

    # the crash loss books as CRASH loss — the resize bucket stays empty
    gp = json.loads((tmp_path / 'shrink.jsonl.goodput').read_text())
    assert gp['restarts'] == 1, gp
    assert gp['lost_steps'] == max(crash) - 6, gp
    assert gp['resizes'] == 0 and gp['resize_lost_s'] == 0.0, gp


@pytest.mark.slow
def test_grow_4_to_8_bitwise_vs_same_size_reference(tmp_path):
    """The wide leg of the acceptance drill (slow: an 8-process fleet on
    one host): the SAME 4-wide crashed checkpoint restores onto nproc=8
    with bitwise-deterministic resharding, proven the same way as the
    shrink leg — two independent 8-worker resumes from byte-identical
    checkpoint copies must agree step for step."""
    total = 10
    ck = tmp_path / 'ck4'
    rcs, crash = _run_fleet(
        tmp_path, 'crash4w', 4, ck, total,
        rank_env={2: {'PADDLE_TPU_FAULT_INJECT': 'kill@step=8'}})
    assert rcs[2] == -signal.SIGKILL, rcs
    assert 0 not in rcs, (rcs, _rank_out(tmp_path, 'crash4w', 0))
    from paddle_tpu.resilience import snapshot as snap
    ck0 = snap.latest_checkpoint(str(ck))
    assert ck0 is not None and ck0.step == 6 and ck0.manifest['world'] == 4

    ck_copy = tmp_path / 'ck4w_copy'
    shutil.copytree(ck, ck_copy)

    rcs, resumed = _run_fleet(tmp_path, 'grow8', 8, ck, total, timeout=480)
    assert rcs == [0] * 8, (rcs, _rank_out(tmp_path, 'grow8', 0))
    rcs, reference = _run_fleet(tmp_path, 'grow8ref', 8, ck_copy, total,
                                timeout=480)
    assert rcs == [0] * 8, (rcs, _rank_out(tmp_path, 'grow8ref', 0))

    assert sorted(resumed) == list(range(7, total + 1))
    assert sorted(reference) == sorted(resumed)
    mismatches = {s: (resumed[s], reference[s]) for s in resumed
                  if resumed[s] != reference[s]}
    assert not mismatches, \
        f'reshard-on-restore is not deterministic at 8 wide: {mismatches}'
    gp = json.loads((tmp_path / 'grow8.jsonl.goodput').read_text())
    assert gp['restarts'] == 1 and gp['resizes'] == 0, gp


def test_scheduled_grow_2_to_4_books_resize_not_crash(tmp_path):
    from paddle_tpu.elastic.schedule import read_resize_request
    from paddle_tpu.fleet_runtime import FLEET_EXIT_CODE
    ck = tmp_path / 'ck2'
    rcs, losses = _run_fleet(
        tmp_path, 'grow', 2, ck, 12,
        env={'PADDLE_TPU_ELASTIC_RESIZE': 'at_step=5:nproc=4'})
    # every worker leaves through the resume ladder at the SAME boundary
    assert rcs == [FLEET_EXIT_CODE] * 2, \
        (rcs, _rank_out(tmp_path, 'grow', 0), _rank_out(tmp_path, 'grow', 1))
    assert max(losses) == 5
    req = read_resize_request(str(ck))
    assert req is not None, os.listdir(ck)
    assert req['step'] == 5 and req['target_nproc'] == 4 \
        and req['from_nproc'] == 2, req
    # the resize checkpoint is synchronous AT the boundary: durable step 5
    from paddle_tpu.resilience import snapshot as snap
    ck0 = snap.latest_checkpoint(str(ck))
    assert ck0 is not None and ck0.step == 5, ck0

    # the restarter's move: relaunch at target_nproc
    rcs, resumed = _run_fleet(tmp_path, 'grown', req['target_nproc'], ck, 8)
    assert rcs == [0, 0, 0, 0], (rcs, _rank_out(tmp_path, 'grown', 0))
    assert sorted(resumed) == list(range(6, 9))   # resumed at 6, no replay
    gp = json.loads((tmp_path / 'grown.jsonl.goodput').read_text())
    assert gp['restarts'] == 1, gp
    assert gp['resizes'] == 1, gp
    assert gp['lost_steps'] == 0 and gp['lost_s'] == 0.0, gp
    assert gp['resize_lost_s'] > 0.0, gp
