"""Accuracy-gated MNIST convergence (VERDICT r4 item 8): static and dygraph
recipes train to ≥97% test accuracy in a bounded step budget, deterministic
(seeded). Runs on real-format IDX fixture files (written by the test,
parsed by the REAL paddle.dataset.mnist IDX loader — the synthetic fallback
never engages), with class-dependent digit patterns an MLP must actually
learn."""
import gzip
import os
import struct

import numpy as np
import pytest

import paddle_tpu as fluid
import paddle_tpu.layers as L


def _write_idx(dirname, prefix, images, labels):
    """Genuine IDX format (magic 2051/2049, big-endian dims), gzipped —
    the same bytes http://yann.lecun.com/exdb/mnist serves."""
    n = images.shape[0]
    with gzip.open(os.path.join(dirname, prefix + '-images-idx3-ubyte.gz'),
                   'wb') as f:
        f.write(struct.pack('>IIII', 2051, n, 28, 28))
        f.write(images.astype(np.uint8).tobytes())
    with gzip.open(os.path.join(dirname, prefix + '-labels-idx1-ubyte.gz'),
                   'wb') as f:
        f.write(struct.pack('>II', 2049, n))
        f.write(labels.astype(np.uint8).tobytes())


def _make_corpus(tmp_path, n_train=2048, n_test=512):
    """Digit-like classes: each class is a fixed random 28×28 prototype,
    samples add pixel noise. Learnable to ~100% by an MLP, not trivially
    linearly separable from raw pixels alone at high noise."""
    rng = np.random.RandomState(0)
    protos = rng.randint(0, 256, (10, 28, 28))

    def sample(n, seed):
        r = np.random.RandomState(seed)
        labels = r.randint(0, 10, n)
        noise = r.randint(-80, 80, (n, 28, 28))
        imgs = np.clip(protos[labels] + noise, 0, 255)
        return imgs, labels

    d = str(tmp_path / 'mnist')
    os.makedirs(d, exist_ok=True)
    _write_idx(d, 'train', *sample(n_train, 1))
    _write_idx(d, 't10k', *sample(n_test, 2))
    return d


def _readers(tmp_path):
    from paddle_tpu.datasets import _mnist_reader
    d = _make_corpus(tmp_path)
    train = _mnist_reader(os.path.join(d, 'train-images-idx3-ubyte.gz'),
                          os.path.join(d, 'train-labels-idx1-ubyte.gz'),
                          0, 0)
    test = _mnist_reader(os.path.join(d, 't10k-images-idx3-ubyte.gz'),
                         os.path.join(d, 't10k-labels-idx1-ubyte.gz'), 0, 1)
    assert not train.is_synthetic and not test.is_synthetic, \
        "fixture not picked up — synthetic fallback engaged"
    return train, test


def _batches(reader, bs):
    xs, ys = [], []
    for img, lab in reader():
        xs.append(np.asarray(img).reshape(-1))
        ys.append(lab)
        if len(xs) == bs:
            yield (np.stack(xs).astype(np.float32),
                   np.asarray(ys, np.int64)[:, None])
            xs, ys = [], []


def test_static_mnist_accuracy_gate(tmp_path):
    train, test = _readers(tmp_path)
    from paddle_tpu.core.random import seed as set_seed
    set_seed(0)
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        img = fluid.data('img', [64, 784], 'float32')
        lab = fluid.data('label', [64, 1], 'int64')
        h = L.fc(img, size=128, act='relu')
        logits = L.fc(h, size=10)
        loss = L.reduce_mean(L.softmax_with_cross_entropy(logits, lab))
        fluid.optimizer.Adam(1e-3).minimize(loss)
    infer = prog.clone(for_test=True)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    for epoch in range(3):
        for x, y in _batches(train, 64):
            exe.run(prog, feed={'img': x, 'label': y}, fetch_list=[loss])
    correct = total = 0
    for x, y in _batches(test, 64):
        lg, = exe.run(infer, feed={'img': x, 'label': y},
                      fetch_list=[logits])
        correct += (np.asarray(lg).argmax(1) == y[:, 0]).sum()
        total += len(y)
    acc = correct / total
    assert acc >= 0.97, f"static MNIST accuracy {acc:.4f} < 0.97"


def test_dygraph_mnist_accuracy_gate(tmp_path):
    train, test = _readers(tmp_path)
    from paddle_tpu import dygraph
    from paddle_tpu.dygraph.nn import Linear
    from paddle_tpu.dygraph.jit import TrainStep
    from paddle_tpu.dygraph.tape import dispatch_op, Tensor
    from paddle_tpu.core.random import seed as set_seed
    with dygraph.guard():
        set_seed(0)

        class MLP(dygraph.Layer):
            def __init__(self):
                super().__init__()
                self.fc1 = Linear(784, 128, act='relu')
                self.fc2 = Linear(128, 10)

            def forward(self, x):
                return self.fc2(self.fc1(x))

        model = MLP()
        opt = fluid.optimizer.Adam(1e-3,
                                   parameter_list=model.parameters())

        def loss_fn(m, x, y):
            lg = m(x)
            l, _ = dispatch_op('softmax_with_cross_entropy',
                               {'logits': lg, 'label': y}, {})
            return dispatch_op('reduce_mean', {'x': l}, {})

        step = TrainStep(model, loss_fn, opt)
        for epoch in range(3):
            for x, y in _batches(train, 64):
                step(x, y)
        model.eval()
        correct = total = 0
        for x, y in _batches(test, 64):
            lg = model(Tensor(x, stop_gradient=True))
            correct += (np.asarray(lg.numpy()).argmax(1) == y[:, 0]).sum()
            total += len(y)
    acc = correct / total
    assert acc >= 0.97, f"dygraph MNIST accuracy {acc:.4f} < 0.97"
