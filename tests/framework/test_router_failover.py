"""Router failover against REAL replica processes: kill -9 one replica
mid-stream and assert the acceptance contract — the dead replica's
in-flight stream errors, every non-in-flight request (queued or submitted
right after the kill) completes bitwise through the survivor, zero drops.

Replicas are ``python -m paddle_tpu.serving.tier.replica`` subprocesses
(seeded tiny LM — every process builds identical weights, so the in-process
reference model produces the exact bytes any replica must answer with)."""
import json
import os
import signal
import subprocess
import sys
import threading
import time

from paddle_tpu.dygraph import guard
from paddle_tpu.models.causal_lm import greedy_generate
from paddle_tpu.serving import Router
from paddle_tpu.serving.tier.replica import DEFAULT_SEED, build_tiny_lm

_REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


def _spawn_replica():
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    env.pop('PADDLE_TPU_TELEMETRY', None)
    proc = subprocess.Popen(
        [sys.executable, '-m', 'paddle_tpu.serving.tier.replica',
         '--port', '0', '--slots', '2', '--seed', str(DEFAULT_SEED)],
        cwd=_REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL, text=True)
    deadline = time.monotonic() + 180
    line = ''
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if line.strip():
            break
        if proc.poll() is not None:
            raise RuntimeError(f'replica died at startup rc={proc.returncode}')
    ready = json.loads(line)
    assert ready['ready'] and ready['pid'] == proc.pid
    return proc, f"http://127.0.0.1:{ready['port']}"


def _counter(name):
    from paddle_tpu.observability import registry
    d = registry.to_dict().get(name)
    if not d or not d['samples']:
        return 0.0
    return sum(s['value'] for s in d['samples'])


def test_kill9_midstream_drops_zero_non_inflight_requests():
    """Two replica processes behind a router; one long stream pinned on
    each. kill -9 the first replica: its stream dies with an error event,
    the other long stream and EIGHT concurrently-submitted short requests
    all complete bitwise — reroutes observed, zero drops."""
    with guard():
        model = build_tiny_lm()
        # engine geometry matches the replica CLI defaults
        pad_len = -(-(16 + 16) // 4) * 4
        long_prompt, short_prompt = [3, 5, 7], [9, 2]
        long_ref = greedy_generate(model, long_prompt, 16, pad_len=pad_len)
        short_ref = greedy_generate(model, short_prompt, 4, pad_len=pad_len)

    procs, urls = [], []
    for _ in range(2):
        p, u = _spawn_replica()
        procs.append(p)
        urls.append(u)
    try:
        router = Router(urls, health_poll_s=0.5)
        assert all(r.healthy and r.warmed for r in router.replicas)

        # one long in-flight stream per replica (loads tie at 1, so the
        # post-kill shorts are guaranteed to try the dead replica too)
        gens, iters = [], []
        for _ in range(2):
            g = router.stream_generate(long_prompt, max_new_tokens=16)
            it = g.events()
            next(it)                          # streaming has begun
            gens.append(g)
            iters.append(it)
        assert {g.replica for g in gens} == set(urls)
        victim_idx = urls.index(gens[0].replica)
        victim = procs[victim_idx]

        os.kill(victim.pid, signal.SIGKILL)   # the real thing

        # non-in-flight requests submitted right after the kill: the router
        # still believes both replicas are healthy, so several dispatches
        # hit the corpse and must reroute — with zero client-visible drops
        r0 = _counter('router_requests_rerouted')
        results, errors = [None] * 8, []

        def short(i):
            try:
                results[i] = router.generate(short_prompt, max_new_tokens=4,
                                             timeout=60)
            except Exception as e:            # a drop — must not happen
                errors.append((i, e))

        threads = [threading.Thread(target=short, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(120)

        # the victim's stream (gens[0] by construction) is the ONLY casualty
        victim_events = list(iters[0])
        assert any('error' in e and not e.get('done')
                   for e in victim_events), victim_events
        # the survivor's long stream completes bitwise
        surv_events = list(iters[1])
        done = [e for e in surv_events if e.get('done')]
        assert done and done[0]['tokens'] == long_ref

        assert not errors, f'dropped non-in-flight requests: {errors}'
        assert all(r is not None for r in results)
        assert all(r['tokens'] == short_ref for r in results)
        survivor_url = urls[1 - victim_idx]
        assert all(r['replica'] == survivor_url for r in results)
        assert _counter('router_requests_rerouted') - r0 >= 1

        # the fleet keeps serving: a fresh request routes normally
        fin = router.generate(short_prompt, max_new_tokens=4)
        assert fin['tokens'] == short_ref
        assert victim.wait(timeout=10) == -signal.SIGKILL
        router.close()
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait(timeout=10)
