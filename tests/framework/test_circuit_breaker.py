"""Serving circuit breaker (paddle_tpu/serving/breaker.py, ISSUE 8): a
persistently failing engine trips the breaker — queued + new requests fail
FAST with the typed EngineUnhealthy instead of waiting out their deadlines,
/healthz reports degraded — and a recovered engine restores service through
the half-open probe without a restart. Covers the state machine, the
MicroBatcher and DecodeScheduler wirings, and the HTTP front end."""
import json
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers, observability
from paddle_tpu.serving import (CircuitBreaker, EngineUnhealthy,
                                InferenceEngine, InvalidRequest, MicroBatcher,
                                ServingServer)
from paddle_tpu.serving.decode.scheduler import DecodeScheduler


def _metric(name):
    d = observability.registry.to_dict().get(name)
    if not d or not d['samples']:
        return 0.0
    return sum(s['value'] for s in d['samples'])


# ---------------------------------------------------------------------------
# state machine
# ---------------------------------------------------------------------------

def test_breaker_state_machine():
    b = CircuitBreaker(failure_threshold=3, reset_after_s=0.15)
    assert b.state == 'closed' and b.allow()
    assert not b.record_failure()
    assert not b.record_failure()
    b.record_success()                     # non-consecutive: counter resets
    assert not b.record_failure()
    assert not b.record_failure()
    assert b.record_failure()              # 3rd consecutive → trips
    assert b.state == 'open' and b.trips == 1
    assert not b.allow()                   # open: reject
    time.sleep(0.2)
    assert b.allow()                       # cooldown elapsed → half-open probe
    assert b.state == 'half_open'
    assert b.record_failure()              # failed probe → re-open (a trip)
    assert b.state == 'open' and b.trips == 2
    time.sleep(0.2)
    assert b.allow()
    b.record_success()                     # probe succeeded → closed
    assert b.state == 'closed' and b.allow()


# ---------------------------------------------------------------------------
# micro-batcher wiring
# ---------------------------------------------------------------------------

class _FlakyEngine:
    """Duck-typed engine whose failure mode is a switch."""

    def __init__(self, max_batch_size=4):
        self.max_batch_size = max_batch_size
        self.fail = False
        self.runs = 0

    def validate(self, inputs):
        arr = np.asarray(inputs['x'], np.float32)
        if arr.ndim != 2:
            raise InvalidRequest('rank')
        return {'x': arr}, arr.shape[0]

    def run_batch(self, feed, nrows=None):
        self.runs += 1
        if self.fail:
            raise RuntimeError('device on fire')
        return [feed['x'][:nrows] * 2.0]


def _one(value=1.0):
    return {'x': np.full((1, 3), value, np.float32)}


def test_batcher_trips_fails_queued_fast_and_recovers():
    eng = _FlakyEngine()
    b = MicroBatcher(eng, batch_timeout_ms=0, breaker_failures=3,
                     breaker_reset_s=0.2)
    try:
        assert np.array_equal(b.predict(_one())[0], np.full((1, 3), 2.0))
        eng.fail = True
        # three separate failed BATCHES (submit+wait serially so they can't
        # coalesce into one)
        for _ in range(3):
            f = b.submit(_one())
            with pytest.raises(Exception):
                f.result(10)
        assert b.breaker.state == 'open'

        # new submissions reject FAST (typed, pre-queue) — the <10ms bar
        t0 = time.perf_counter()
        with pytest.raises(EngineUnhealthy):
            b.submit(_one())
        assert time.perf_counter() - t0 < 0.010
        runs_when_open = eng.runs

        # recovery: engine heals, cooldown passes, the next request is the
        # half-open probe and service resumes — no restart
        eng.fail = False
        time.sleep(0.25)
        out, = b.predict(_one(3.0))
        assert np.array_equal(out, np.full((1, 3), 6.0))
        assert b.breaker.state == 'closed'
        assert eng.runs == runs_when_open + 1
        assert np.array_equal(b.predict(_one())[0], np.full((1, 3), 2.0))
    finally:
        b.close(drain=False)


def test_batcher_trip_fails_already_queued_requests_immediately():
    """Requests sitting in the queue when the breaker trips must not wait
    out their deadlines — they fail with EngineUnhealthy at the trip."""
    eng = _FlakyEngine()
    eng.fail = True
    b = MicroBatcher(eng, batch_timeout_ms=0, breaker_failures=1,
                     breaker_reset_s=30, start=False)
    # 8 single-row requests > max_batch_size=4: the first coalesced batch
    # fails and trips; the rest are still queued at the trip
    futures = [b.submit(_one(), timeout_ms=60_000) for _ in range(8)]
    b._worker.start()
    # first batch fails → trips → the rest of the queue fails immediately,
    # despite 60s deadlines
    t0 = time.perf_counter()
    outcomes = []
    for f in futures:
        with pytest.raises(Exception) as ei:
            f.result(10)
        outcomes.append(ei.value)
    assert time.perf_counter() - t0 < 5.0
    assert any(isinstance(e, EngineUnhealthy) for e in outcomes)
    assert _metric('serving_breaker_trips') >= 1
    b.close(drain=False)


def test_breaker_metrics_exported():
    eng = _FlakyEngine()
    eng.fail = True
    before_trips = _metric('serving_breaker_trips')
    b = MicroBatcher(eng, batch_timeout_ms=0, breaker_failures=1,
                     breaker_reset_s=30)
    try:
        f = b.submit(_one())
        with pytest.raises(Exception):
            f.result(10)
        deadline = time.monotonic() + 5
        while b.breaker.state != 'open' and time.monotonic() < deadline:
            time.sleep(0.01)
        with pytest.raises(EngineUnhealthy):
            b.submit(_one())
        assert _metric('serving_breaker_trips') == before_trips + 1
        assert _metric('serving_breaker_rejected') >= 1
        assert _metric('serving_breaker_state') == 2.0   # open
    finally:
        b.close(drain=False)


# ---------------------------------------------------------------------------
# decode-scheduler wiring
# ---------------------------------------------------------------------------

class _FlakyDecodeEngine:
    """Duck-typed decode engine: echoes prompt-token+1 until the budget."""

    def __init__(self, slots=2):
        self.slots = slots
        self.eos_id = None
        self.fail = False
        self._tables = 0

    def validate(self, prompt_ids, max_new_tokens):
        return [int(t) for t in prompt_ids], int(max_new_tokens)

    def reserve_table(self, prompt_len, max_new_tokens, prompt=None):
        self._tables += 1
        return {'id': self._tables}

    def release_table(self, table):
        pass

    def prefill(self, prompt, table):
        if self.fail:
            raise RuntimeError('decode engine on fire')
        return prompt[-1] + 1

    def decode_step(self, tokens, tables):
        if self.fail:
            raise RuntimeError('decode engine on fire')
        return [0 if t is None else t + 1 for t in tokens]


def test_decode_scheduler_trips_and_recovers_via_probe():
    eng = _FlakyDecodeEngine()
    sched = DecodeScheduler(eng, breaker_failures=2, breaker_reset_s=0.2)
    try:
        assert sched.generate([5], max_new_tokens=3,
                              result_timeout=30) == [6, 7, 8]
        eng.fail = True
        for _ in range(2):
            s = sched.submit([5], max_new_tokens=2)
            with pytest.raises(Exception):
                s.result(10)
        deadline = time.monotonic() + 5
        while sched.breaker.state != 'open' and time.monotonic() < deadline:
            time.sleep(0.01)
        assert sched.breaker.state == 'open'
        t0 = time.perf_counter()
        with pytest.raises(EngineUnhealthy):
            sched.submit([5], max_new_tokens=2)
        assert time.perf_counter() - t0 < 0.010
        # heal + cooldown → probe generation closes the breaker
        eng.fail = False
        time.sleep(0.25)
        assert sched.generate([9], max_new_tokens=2,
                              result_timeout=30) == [10, 11]
        assert sched.breaker.state == 'closed'
    finally:
        sched.close(drain=False)


def test_decode_trip_fails_waiting_requests_fast():
    eng = _FlakyDecodeEngine(slots=1)
    eng.fail = True
    sched = DecodeScheduler(eng, breaker_failures=1, breaker_reset_s=30,
                            start=False)
    streams = [sched.submit([5], max_new_tokens=2, timeout_ms=60_000)
               for _ in range(3)]
    sched._worker.start()
    t0 = time.perf_counter()
    errors = []
    for s in streams:
        with pytest.raises(Exception) as ei:
            s.result(10)
        errors.append(ei.value)
    assert time.perf_counter() - t0 < 5.0
    assert any(isinstance(e, EngineUnhealthy) for e in errors)
    sched.close(drain=False)


# ---------------------------------------------------------------------------
# HTTP front end: /healthz degraded + 503 mapping
# ---------------------------------------------------------------------------

FEATURES = 6


@pytest.fixture(scope='module')
def saved_model(tmp_path_factory):
    main, start = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, start):
        x = layers.data('x', shape=[FEATURES], dtype='float32')
        out = layers.fc(x, 3, act='softmax')
    exe = fluid.Executor()
    path = str(tmp_path_factory.mktemp('breaker') / 'model')
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(start)
        fluid.io.save_inference_model(path, ['x'], [out], exe, main)
    return path


def _get(url):
    try:
        r = urllib.request.urlopen(url, timeout=30)
        return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_healthz_degraded_while_tripped_then_ok_after_probe(saved_model):
    eng = InferenceEngine(saved_model, max_batch_size=4)
    eng.warmup()
    srv = ServingServer(eng, port=0, batch_timeout_ms=0).start()
    batcher = srv.batcher
    batcher.breaker.failure_threshold = 2
    batcher.breaker.reset_after_s = 0.2
    url = f'http://127.0.0.1:{srv.port}'
    try:
        code, body = _get(url + '/healthz')
        assert code == 200 and body['status'] == 'ok'

        real_run = eng.run_batch
        eng.run_batch = lambda *a, **k: (_ for _ in ()).throw(
            RuntimeError('device on fire'))
        for _ in range(2):
            f = batcher.submit({'x': np.zeros((1, FEATURES), np.float32)})
            with pytest.raises(Exception):
                f.result(10)
        deadline = time.monotonic() + 5
        while batcher.breaker.state != 'open' and \
                time.monotonic() < deadline:
            time.sleep(0.01)

        code, body = _get(url + '/healthz')
        assert code == 503 and body['status'] == 'degraded'
        assert body['breakers'] == {'predict': 'open'}

        # POST /predict while open → typed 503 EngineUnhealthy
        req = urllib.request.Request(
            url + '/predict',
            data=json.dumps(
                {'inputs': {'x': np.zeros((1, FEATURES)).tolist()}}).encode(),
            headers={'Content-Type': 'application/json'})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=30)
        assert ei.value.code == 503
        assert json.loads(ei.value.read())['error'] == 'EngineUnhealthy'

        # heal → cooldown → probe through the real engine → healthy again
        eng.run_batch = real_run
        time.sleep(0.25)
        out = batcher.predict({'x': np.zeros((1, FEATURES), np.float32)})
        assert out[0].shape == (1, 3)
        code, body = _get(url + '/healthz')
        assert code == 200 and body['status'] == 'ok'
    finally:
        srv.shutdown(drain=False)
