"""Self-healing acceptance tests (ISSUE 8), in the test_crash_resume.py
style — each run is a separate interpreter driven purely by env knobs:

1. ``nan@step=N`` under ``policy=rollback``: the supervisor restores the
   last good checkpoint, skips the poisoned window via the DataLoader
   cursor (data moves FORWARD), the run completes, and two identically-
   faulted runs produce BITWISE-identical trajectories.
2. ``hang@step=N`` with the watchdog armed: the wedged boundary produces a
   faulthandler all-thread stack-dump artifact and a nonzero exit
   (WATCHDOG_EXIT_CODE) within deadline+grace — and a fresh process then
   resumes from ``latest()`` and finishes with the reference trajectory.
"""
import json
import os
import subprocess
import sys

from paddle_tpu.resilience.watchdog import WATCHDOG_EXIT_CODE

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), '..', '..'))

# Deterministic supervised training program: dropout (per-step RNG), Adam
# (slot state), epoch-keyed batches (DataLoader cursor), checkpoint every 3
# steps, supervisor policy=rollback wired through mgr.end_of_step(loss=...).
TRAIN_SCRIPT = r'''
import json, os, sys
import numpy as np
import paddle_tpu as fluid
from paddle_tpu import layers as L
from paddle_tpu import resilience

ckpt_dir, log_path, total_steps = sys.argv[1], sys.argv[2], int(sys.argv[3])

fluid.seed(4321)
main, startup = fluid.Program(), fluid.Program()
with fluid.program_guard(main, startup):
    x = L.data('hx', [8], dtype='float32')
    y = L.data('hy', [1], dtype='float32')
    h = L.fc(x, size=16, act='relu')
    h = L.dropout(h, dropout_prob=0.3)
    pred = L.fc(h, size=1)
    loss = L.reduce_mean(L.square_error_cost(pred, y))
    fluid.optimizer.Adam(learning_rate=1e-2).minimize(loss)

exe = fluid.Executor()
exe.run(startup)

blk = main.global_block()
loader = fluid.DataLoader.from_generator(
    feed_list=[blk.var('hx'), blk.var('hy')], capacity=4)

def epoch_batches(epoch, n=5):
    rng = np.random.RandomState(200 + epoch)
    return [(rng.randn(4, 8).astype(np.float32),
             rng.randn(4, 1).astype(np.float32)) for _ in range(n)]

loader.set_batch_generator(lambda: iter(epoch_batches(loader.epoch)))

mgr = resilience.CheckpointManager(ckpt_dir, every_n_steps=3, keep=2)
sup = resilience.TrainingSupervisor(policy='rollback', manager=mgr,
                                    executor=exe, program=main,
                                    loader=loader)
step = 0
got = mgr.restore()
if got is not None:
    arrays, meta = got
    resilience.restore_training_state(arrays, meta, executor=exe,
                                      program=main, loader=loader)
    step = meta['step']

log = open(log_path, 'a')
stopped = False
while step < total_steps and not stopped:
    for batch in loader():
        lv = exe.run(main, feed=batch, fetch_list=[loss])[0]
        step += 1
        log.write(json.dumps({'step': step,
                              'loss': np.asarray(lv).tobytes().hex()}) + '\n')
        log.flush()
        stopped = mgr.end_of_step(
            step, lambda: resilience.capture_training_state(
                executor=exe, program=main, loader=loader), loss=lv)
        v = mgr.last_verdict
        if v is not None and v.action == 'rollback':
            log.write(json.dumps({'rollback_at': step,
                                  'resume': v.resume_step}) + '\n')
            log.flush()
            step = v.resume_step
            break            # restart loader(): cursor already moved past
                             # the poisoned window
        if stopped or step >= total_steps:
            break
sup.close()
mgr.wait()
mgr.close()
log.close()
'''


def _run(tmp_path, name, ckpt_dir, total_steps, extra_env=None, timeout=300):
    script = tmp_path / 'train.py'
    if not script.exists():
        script.write_text(TRAIN_SCRIPT)
    log = tmp_path / f'{name}.jsonl'
    env = dict(os.environ, JAX_PLATFORMS='cpu', PYTHONPATH=REPO)
    for k in ('PADDLE_TPU_FAULT_INJECT', 'PADDLE_TPU_ASYNC',
              'PADDLE_TPU_SUPERVISOR', 'PADDLE_TPU_WATCHDOG',
              'PADDLE_TPU_METRICS_DIR', 'PADDLE_TPU_TELEMETRY'):
        env.pop(k, None)
    env.update(extra_env or {})
    r = subprocess.run(
        [sys.executable, str(script), str(ckpt_dir), str(log),
         str(total_steps)],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=timeout)
    lines = []
    if log.exists():
        lines = [json.loads(ln) for ln in log.read_text().splitlines()
                 if ln.strip()]
    return r, lines


def test_nan_rollback_recovers_and_is_bitwise_deterministic(tmp_path):
    """nan@step=8 under policy=rollback: checkpoints land at 3 and 6; the
    poisoned step 8 rolls back to 6 with the data cursor skipping forward;
    the run completes — and two identically-faulted runs are BITWISE
    identical, line for line."""
    total = 12
    fault = {'PADDLE_TPU_FAULT_INJECT': 'nan@step=8'}
    r1, lines1 = _run(tmp_path, 'faulted1', tmp_path / 'ck1', total,
                      extra_env=fault)
    assert r1.returncode == 0, r1.stderr[-3000:]
    r2, lines2 = _run(tmp_path, 'faulted2', tmp_path / 'ck2', total,
                      extra_env=fault)
    assert r2.returncode == 0, r2.stderr[-3000:]

    rollbacks = [ln for ln in lines1 if 'rollback_at' in ln]
    assert rollbacks == [{'rollback_at': 8, 'resume': 6}], rollbacks
    steps = [ln['step'] for ln in lines1 if 'step' in ln]
    assert steps[-1] == total                 # recovered and finished
    assert steps.count(7) == 2                # 7, 8 replayed after rollback

    # THE acceptance: identically-faulted runs are bitwise identical
    assert lines1 == lines2

    # the poisoned batch descriptor was quarantined
    q = (tmp_path / 'ck1' / 'quarantine.jsonl').read_text().splitlines()
    rec = json.loads(q[0])
    assert rec['step'] == 8 and rec['reason'] == 'nonfinite'
    assert rec['action'] == 'rollback' and rec['batch'] is not None


def test_hang_watchdog_dumps_stacks_aborts_and_resume_succeeds(tmp_path):
    """hang@step=6: the wedged boundary breaches the train_loop lease →
    all-thread stack dump + exit WATCHDOG_EXIT_CODE, well inside
    deadline+grace; a fresh process resumes from latest() and replays the
    reference trajectory bitwise (a hang corrupts nothing)."""
    total = 9
    r_ref, ref_lines = _run(tmp_path, 'ref', tmp_path / 'ck_ref', total)
    assert r_ref.returncode == 0, r_ref.stderr[-3000:]
    ref = {ln['step']: ln['loss'] for ln in ref_lines if 'step' in ln}

    metrics_dir = tmp_path / 'artifacts'
    ck = tmp_path / 'ck_hang'
    r_hang, hang_lines = _run(
        tmp_path, 'hang', ck, total, timeout=240,
        extra_env={'PADDLE_TPU_FAULT_INJECT': 'hang@step=6',
                   'PADDLE_TPU_WATCHDOG': '1',
                   'PADDLE_TPU_WATCHDOG_FLOOR_S': '2',
                   'PADDLE_TPU_WATCHDOG_COLD_S': '120',
                   'PADDLE_TPU_WATCHDOG_POLL_S': '0.1',
                   'PADDLE_TPU_METRICS_DIR': str(metrics_dir)})
    assert r_hang.returncode == WATCHDOG_EXIT_CODE, \
        f'rc={r_hang.returncode}: {r_hang.stderr[-2000:]}'
    hung = {ln['step']: ln['loss'] for ln in hang_lines if 'step' in ln}
    assert max(hung) == 6                     # wedged at the step-6 boundary

    # the breach is diagnosable post-mortem: all-thread stacks + record
    dumps = [p for p in os.listdir(metrics_dir)
             if p.startswith('watchdog_stacks_')]
    assert dumps, os.listdir(metrics_dir)
    text = (metrics_dir / dumps[0]).read_text()
    assert 'Thread' in text or 'File' in text
    breach = json.loads((metrics_dir / 'watchdog_breach.json').read_text())
    assert breach['name'] == 'train_loop' and breach['aborting'] is True
    assert breach['held_seconds'] >= breach['deadline_seconds']

    # resume: a fresh process finishes the job with the reference
    # trajectory (checkpoints at 3 and 6 exist; the hang corrupted nothing)
    r_res, res_lines = _run(tmp_path, 'resume', ck, total)
    assert r_res.returncode == 0, r_res.stderr[-3000:]
    resumed = {ln['step']: ln['loss'] for ln in res_lines if 'step' in ln}
    assert max(resumed) == total
    mismatches = {s: (resumed[s], ref[s]) for s in resumed
                  if resumed[s] != ref[s]}
    assert not mismatches, mismatches
