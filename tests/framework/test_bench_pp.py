"""tier-1 guard for the pipeline-schedule bench: tools/bench_pp.py must
run end-to-end under JAX_PLATFORMS=cpu at smoke sizes and demonstrate the
ISSUE 20 acceptance margins — 1F1B bitwise-identical to GPipe at the same
auto-cut, 1F1B peak residency below GPipe both PREDICTED (staged planner)
and MEASURED (XLA memory_analysis temp bytes), and the cost-model
auto-cut within 5% of the best manually-enumerated cut on bert_layer."""
import json
import os
import subprocess
import sys

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), '..', '..'))

SCHED_FIELDS = {'steps', 'batch', 'microbatches', 'cut_vars', 'schedules',
                'bitwise_identical', 'predicted_1f1b_le_gpipe',
                'measured_1f1b_le_gpipe'}
CUT_FIELDS = {'candidates', 'auto_cut', 'auto_cost', 'best_manual_cut',
              'best_manual_cost', 'balance', 'within_tolerance'}


def test_bench_pp_smoke_runs_on_cpu():
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    for knob in ('PADDLE_TPU_PP_SCHEDULE', 'PADDLE_TPU_PP_MICROBATCHES',
                 'PADDLE_TPU_HBM_BUDGET_MB'):
        env.pop(knob, None)
    r = subprocess.run(
        [sys.executable, os.path.join('tools', 'bench_pp.py'), '--smoke'],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stderr[-3000:]
    lines = [json.loads(ln) for ln in r.stdout.splitlines() if ln.strip()]
    benches = {d['bench']: d for d in lines if 'bench' in d}
    assert {'pipeline_schedules', 'pipeline_autocut'} <= set(benches)

    sc = benches['pipeline_schedules']
    assert SCHED_FIELDS <= set(sc), sc
    # 1F1B is the same arithmetic as the GPipe scan — bitwise, not close
    assert sc['bitwise_identical'] is True, sc
    # the schedule's win: one wave of residuals in flight instead of m —
    # claimed by the planner AND confirmed by the compiler
    assert sc['predicted_1f1b_le_gpipe'] is True, sc
    assert sc['measured_1f1b_le_gpipe'] is True, sc
    for sched in ('gpipe', '1f1b'):
        row = sc['schedules'][sched]
        assert row['steps_per_s'] > 0
        assert row['predicted_host_peak_bytes'] > 0
        assert row['measured_temp_bytes'] > 0

    ac = benches['pipeline_autocut']
    assert CUT_FIELDS <= set(ac), ac
    assert ac['candidates'] >= 2
    # cost-model auto-cut within 5% of the best enumerated manual cut
    assert ac['within_tolerance'] is True, ac
    assert ac['auto_cost'] >= ac['best_manual_cost'] > 0
