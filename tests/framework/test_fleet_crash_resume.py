"""THE fleet acceptance tests (ISSUE 12): real multi-process fleets — 2
``jax.distributed`` CPU workers spawned by ``fleet_runtime.local_fleet``
with the full PADDLE_* env wired — trained through the REAL executor spine
(fsdp-sharded state, global-array feeds, per-host DataLoader sharding,
partitioner-sharded checkpoints).

1. ``kill -9`` one worker mid-epoch → restart the fleet → resume from the
   sharded checkpoint → the stitched loss trajectory is BITWISE-identical
   to an uninterrupted 2-worker run; each host's shard files contain only
   the tiles it owns (Σ shard bytes ≈ 1× state, not p copies).
2. A watchdog breach on ONE worker propagates: the breached worker posts
   the poison flag and exits 70; the healthy worker observes the flag at
   its next step boundary and exits FLEET_EXIT_CODE (75); the restarted
   fleet resumes and goodput books the lost work exactly once.
"""
import json
import os
import signal
import sys

import numpy as np
import pytest

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), '..', '..'))
NPROC = 2

# Deterministic fleet training program: fsdp-sharded params+slots over the
# 2-process mesh, dropout (per-step RNG stream), epoch-keyed global
# batches row-sharded per host, Adam slots, sharded checkpoints every 3
# steps. Loss is the fleet-global mean — identical on every host; host 0
# logs it per step as hex bytes (bitwise comparison).
TRAIN_SCRIPT = r'''
import json, os, sys
import numpy as np
import paddle_tpu as fluid
from paddle_tpu import layers as L
from paddle_tpu import resilience
from paddle_tpu.fleet_runtime import (bootstrap, check_poisoned,
                                      exit_for_resume, FLEET_EXIT_CODE)

ckpt_dir, log_path, total_steps = sys.argv[1], sys.argv[2], int(sys.argv[3])
bootstrap()
import jax
rank = jax.process_index()

fluid.seed(1234)
main, startup = fluid.Program(), fluid.Program()
with fluid.program_guard(main, startup):
    x = L.data('cx', [8], dtype='float32')
    y = L.data('cy', [1], dtype='float32')
    h = L.fc(x, size=16, act='relu')
    h = L.dropout(h, dropout_prob=0.3)
    pred = L.fc(h, size=1)
    loss = L.reduce_mean(L.square_error_cost(pred, y))
    from paddle_tpu.parallel import DistributedStrategy, fleet
    fleet.init(mesh_shape={'fsdp': jax.device_count()})
    strat = DistributedStrategy()
    strat.sharding = True                     # ZeRO: fsdp-sharded state
    fleet.distributed_optimizer(
        fluid.optimizer.Adam(learning_rate=1e-2), strategy=strat,
    ).minimize(loss)

exe = fluid.Executor()
exe.run(startup)

blk = main.global_block()
loader = fluid.DataLoader.from_generator(
    feed_list=[blk.var('cx'), blk.var('cy')], capacity=4)
loader.shard_for_fleet()

def epoch_batches(epoch, n=5):
    rng = np.random.RandomState(100 + epoch)
    return [(rng.randn(8, 8).astype(np.float32),
             rng.randn(8, 1).astype(np.float32)) for _ in range(n)]

loader.set_batch_generator(lambda: iter(epoch_batches(loader.epoch)))

mgr = resilience.CheckpointManager(ckpt_dir, every_n_steps=3, keep=2)
supervisor = resilience.TrainingSupervisor(manager=mgr)
step = 0
got = mgr.restore()
if got is not None:
    arrays, meta = got
    resilience.restore_training_state(arrays, meta, executor=exe,
                                      program=main, loader=loader)
    step = meta['step']
    if rank == 0:
        with open(log_path + '.goodput', 'w') as f:
            json.dump(mgr.goodput.meta(), f)

log = open(log_path, 'a') if rank == 0 else None
stopped = False
while step < total_steps and not stopped:
    for batch in loader():
        try:
            lv = exe.run(main, feed=batch, fetch_list=[loss])[0]
        except Exception:
            # a dead peer surfaces on the survivors as a collective
            # error; when the fleet is poisoned that IS the signal to
            # exit for resume (docs/RESILIENCE.md "Fleet propagation")
            rec = check_poisoned()
            if rec is not None:
                mgr.close()
                exit_for_resume(rec)
            raise
        step += 1
        if log:
            log.write(json.dumps({'step': step,
                                  'loss': np.asarray(lv).tobytes().hex()})
                      + '\n')
            log.flush()
        stopped = mgr.end_of_step(
            step, lambda: resilience.capture_training_state(
                executor=exe, program=main, loader=loader),
            loss=float(np.asarray(lv)))
        if stopped or step >= total_steps:
            break
mgr.wait()
mgr.close()
if log:
    log.close()
if mgr.fleet_poisoned is not None:
    exit_for_resume(mgr.fleet_poisoned)
'''


def _write_script(tmp_path):
    script = tmp_path / 'fleet_train.py'
    if not script.exists():
        script.write_text(TRAIN_SCRIPT)
    return script


def _run_fleet(tmp_path, name, ckpt_dir, total_steps, env=None,
               rank_env=None, timeout=240):
    """Launch the 2-worker fleet; returns (rcs, {step: loss_hex})."""
    sys.path.insert(0, REPO)
    from paddle_tpu.fleet_runtime.bootstrap import local_fleet
    script = _write_script(tmp_path)
    log = tmp_path / f'{name}.jsonl'
    base = {
        'PYTHONPATH': REPO,
        'PADDLE_TPU_METRICS_DIR': str(tmp_path / f'{name}_metrics'),
        # a worker whose peer died blocks in the next collective: the
        # watchdog turns that into exit-for-resume instead of a hang
        'PADDLE_TPU_WATCHDOG': '1',
        'PADDLE_TPU_WATCHDOG_FLOOR_S': '6',
        'PADDLE_TPU_WATCHDOG_COLD_S': '90',
        'PADDLE_TPU_VERIFY': 'off',
    }
    base.update(env or {})
    outs = []

    def stdout(rank):
        f = open(tmp_path / f'{name}.r{rank}.out', 'w')
        outs.append(f)
        return f

    fl = local_fleet(NPROC, script, args=[ckpt_dir, log, total_steps],
                     env=base, rank_env=rank_env, stdout=stdout, cwd=REPO)
    rcs = fl.wait(timeout=timeout)
    for f in outs:
        f.close()
    losses = {}
    if log.exists():
        for line in log.read_text().splitlines():
            if line.strip():
                rec = json.loads(line)
                losses[rec['step']] = rec['loss']
    return rcs, losses


def _rank_out(tmp_path, name, rank):
    p = tmp_path / f'{name}.r{rank}.out'
    return p.read_text()[-3000:] if p.exists() else '<no output>'


def test_fleet_kill9_resume_bitwise_and_sharded_bytes(tmp_path):
    total = 12
    # reference: one uninterrupted 2-worker fleet
    rcs, ref = _run_fleet(tmp_path, 'ref', tmp_path / 'ck_ref', total)
    assert rcs == [0, 0], (rcs, _rank_out(tmp_path, 'ref', 0),
                           _rank_out(tmp_path, 'ref', 1))
    assert sorted(ref) == list(range(1, total + 1))

    # --- sharded-checkpoint acceptance on the reference run's files ---
    from paddle_tpu.resilience import snapshot as snap
    ck = snap.latest_checkpoint(str(tmp_path / 'ck_ref'))
    assert ck is not None and ck.sharded and ck.manifest['world'] == NPROC
    arrays, _ = snap.read_checkpoint(ck)
    state_bytes = sum(a.nbytes for a in arrays.values())
    manifests = []
    for sh in ck.manifest['shards']:
        with open(os.path.join(ck.directory, sh['manifest'])) as f:
            manifests.append(json.load(f))

    def tile_bytes(manifest):
        total = 0
        for rec in manifest['arrays'].values():
            itemsize = np.dtype(rec['dtype']).itemsize
            for t in rec['tiles']:
                n = 1
                for a, b in t['index']:
                    n *= (b - a)
                total += n * itemsize
        return total

    per_host = [tile_bytes(m) for m in manifests]
    # tiles PARTITION the state: Σ over hosts == 1× state exactly — each
    # fsdp tile saved by exactly one owner, never p replicas
    assert sum(per_host) == state_bytes, (per_host, state_bytes)
    # and every host persisted a real share (≈ 1/p of the fsdp state)
    assert min(per_host) > 0.2 * state_bytes, (per_host, state_bytes)
    # tiles are disjoint across hosts; replicated values live on host 0
    for key, rec in manifests[0]['arrays'].items():
        other = manifests[1]['arrays'].get(key)
        if other is None:
            continue
        mine = {tuple(map(tuple, t['index'])) for t in rec['tiles']}
        theirs = {tuple(map(tuple, t['index'])) for t in other['tiles']}
        assert not (mine & theirs), f'{key}: tile {mine & theirs} saved twice'
    r1_full = [k for k, rec in manifests[1]['arrays'].items()
               for t in rec['tiles']
               if all(a == 0 and b == d for (a, b), d in
                      zip(t['index'], rec['global_shape']))]
    assert not r1_full, f'host 1 saved full (host-0-owned) values: {r1_full}'

    # --- crash: SIGKILL worker 1 at the step-8 boundary ---
    ckc = tmp_path / 'ck_crash'
    rcs, crash = _run_fleet(
        tmp_path, 'crash', ckc, total,
        rank_env={1: {'PADDLE_TPU_FAULT_INJECT': 'kill@step=8'}})
    assert rcs[1] == -signal.SIGKILL, (rcs, _rank_out(tmp_path, 'crash', 1))
    # worker 0 exited for resume, NOT cleanly and NOT by hanging: its
    # watchdog breached on the dead collective (70) or the runtime
    # surfaced the dead peer as an error
    assert rcs[0] not in (0, None), (rcs, _rank_out(tmp_path, 'crash', 0))
    assert max(crash) <= 9
    assert all(crash[s] == ref[s] for s in crash), 'pre-crash divergence'

    # --- restart the whole fleet: resume from the sharded checkpoint ---
    rcs, resumed = _run_fleet(tmp_path, 'resume', ckc, total)
    assert rcs == [0, 0], (rcs, _rank_out(tmp_path, 'resume', 0),
                           _rank_out(tmp_path, 'resume', 1))
    assert min(resumed) <= 8 and max(resumed) == total
    mismatches = {s: (resumed[s], ref[s]) for s in resumed
                  if resumed[s] != ref[s]}
    assert not mismatches, \
        f'resumed fleet diverged from uninterrupted fleet: {mismatches}'


def test_fleet_watchdog_breach_propagates_and_books_lost_work(tmp_path):
    """Watchdog breach on worker 1 (injected boundary hang inside its
    supervisor's train_loop lease) → poison flag → worker 0 exits
    FLEET_EXIT_CODE at its next boundary; the restarted fleet resumes
    from the last committed checkpoint and books the lost steps once."""
    total = 12
    ck = tmp_path / 'ck_poison'
    env = {
        'PADDLE_TPU_WATCHDOG_FLOOR_S': '30',
        'PADDLE_TPU_WATCHDOG_COLD_S': '120',
    }
    rank_env = {
        # worker 0 ONLY dwells at each boundary so the KV poison path
        # (not its own watchdog) is what takes it down — deterministic
        # propagation; the dwell must not inflate worker 1's
        # boundary-interval history, so it is per-rank
        0: {'PADDLE_TPU_FLEET_POISON_GRACE_S': '3.5'},
        1: {
            'PADDLE_TPU_FAULT_INJECT': 'hang@step=7',
            # tighter deadlines on the hanging worker only (but with
            # enough slack that a slow warm-up step can't breach
            # spuriously): its train_loop lease breaches ~2s into the
            # hang, posts poison, exits 70
            'PADDLE_TPU_WATCHDOG_FLOOR_S': '2',
            'PADDLE_TPU_WATCHDOG_FACTOR': '4',
            'PADDLE_TPU_WATCHDOG_COLD_S': '60',
        },
    }
    rcs, losses = _run_fleet(tmp_path, 'poison', ck, total, env=env,
                             rank_env=rank_env)
    from paddle_tpu.resilience.watchdog import WATCHDOG_EXIT_CODE
    from paddle_tpu.fleet_runtime import FLEET_EXIT_CODE
    assert rcs[1] == WATCHDOG_EXIT_CODE, \
        (rcs, _rank_out(tmp_path, 'poison', 1))
    assert rcs[0] == FLEET_EXIT_CODE, \
        (rcs, _rank_out(tmp_path, 'poison', 0))
    assert 6 <= max(losses) <= 8
    # the breach left a diagnosable record on the hanging worker
    mdir = tmp_path / 'poison_metrics'
    assert (mdir / 'watchdog_breach.json').exists()

    # --- restart: clean resume, lost work booked exactly once ---
    rcs, resumed = _run_fleet(tmp_path, 'recover', ck, total)
    assert rcs == [0, 0], (rcs, _rank_out(tmp_path, 'recover', 0),
                           _rank_out(tmp_path, 'recover', 1))
    assert max(resumed) == total
    gp = json.loads((tmp_path / 'recover.jsonl.goodput').read_text())
    # checkpoint landed at step 6; the poisoned fleet reached boundary 7
    # (worker 0's heartbeat) → exactly one lost step, booked once
    assert gp['restarts'] == 1, gp
    assert gp['lost_steps'] == max(losses) - 6, gp
