"""Real-format fixtures for the dataset zoo (VERDICT r4 item 9): each
parser is exercised against a tiny staged sample of its ACTUAL on-disk
format (IDX covered in test_mnist_convergence) — the synthetic fallback
must not engage."""
import gzip
import io
import os
import pickle
import struct
import tarfile
import zipfile

import numpy as np
import pytest


# ---------------------------------------------------------------------------
# fixture builders
# ---------------------------------------------------------------------------

def _tar_add_bytes(tf, name, data):
    info = tarfile.TarInfo(name)
    info.size = len(data)
    tf.addfile(info, io.BytesIO(data))


def _gz(text):
    return gzip.compress(text.encode())


# ---------------------------------------------------------------------------
# cifar: pickled batch dicts in a tar.gz
# ---------------------------------------------------------------------------

def test_cifar10_pickle_tarball(tmp_path, monkeypatch):
    rng = np.random.RandomState(0)
    batch = {b'data': rng.randint(0, 256, (10, 3072)).astype(np.uint8),
             b'labels': rng.randint(0, 10, 10).tolist()}
    tpath = tmp_path / 'cifar-10-python.tar.gz'
    with tarfile.open(tpath, 'w:gz') as tf:
        _tar_add_bytes(tf, 'cifar-10-batches-py/data_batch_1',
                       pickle.dumps(batch))
        _tar_add_bytes(tf, 'cifar-10-batches-py/test_batch',
                       pickle.dumps(batch))
    from paddle_tpu import datasets
    r = datasets.cifar10_train(data_dir=str(tmp_path))
    assert not r.is_synthetic
    samples = list(r())
    assert len(samples) == 10
    img, lab = samples[0]
    assert img.shape == (3, 32, 32) and 0 <= lab < 10
    assert img.min() >= -1.0 and img.max() <= 1.0


def test_cifar100_fine_labels(tmp_path, monkeypatch):
    import paddle_tpu.dataset.cifar as cifar
    rng = np.random.RandomState(1)
    batch = {b'data': rng.randint(0, 256, (6, 3072)).astype(np.uint8),
             b'fine_labels': rng.randint(0, 100, 6).tolist()}
    d = tmp_path / 'cifar'
    d.mkdir()
    with tarfile.open(d / 'cifar-100-python.tar.gz', 'w:gz') as tf:
        _tar_add_bytes(tf, 'cifar-100-python/train', pickle.dumps(batch))
        _tar_add_bytes(tf, 'cifar-100-python/test', pickle.dumps(batch))
    monkeypatch.setattr(cifar, 'DATA_HOME', str(tmp_path))
    monkeypatch.setattr(cifar, '_path',
                        lambda name: str(d / name))
    r = cifar.train100()
    samples = list(r())
    assert len(samples) == 6
    assert samples[0][0].shape == (3072,)


# ---------------------------------------------------------------------------
# conll05: gzipped words/props columns inside a tarball + dict files
# ---------------------------------------------------------------------------

def test_conll05_srl_tarball(tmp_path, monkeypatch):
    import paddle_tpu.dataset.conll05 as conll05
    words = "The\ncat\nchased\na\nmouse\n\n"
    # col0: predicate lemma; col1: the tag column for that predicate
    props = ("-\t(A0*\n-\t*)\nchase\t(V*)\n-\t(A1*\n-\t*)\n\n")
    tdir = tmp_path / 'conll05st'
    tdir.mkdir()
    tpath = tdir / 'conll05st-tests.tar.gz'
    with tarfile.open(tpath, 'w:gz') as tf:
        _tar_add_bytes(
            tf, 'conll05st-release/test.wsj/words/test.wsj.words.gz',
            _gz(words))
        _tar_add_bytes(
            tf, 'conll05st-release/test.wsj/props/test.wsj.props.gz',
            _gz(props))
    (tdir / 'wordDict.txt').write_text(
        "The\ncat\nchased\na\nmouse\nbos\neos\n")
    (tdir / 'verbDict.txt').write_text("chased\n")
    (tdir / 'targetDict.txt').write_text("B-A0\nI-A0\nB-A1\nI-A1\nB-V\nO\n")
    monkeypatch.setattr(conll05, '_DIR', str(tdir))
    monkeypatch.setattr(conll05, '_TAR', str(tpath))
    r = conll05.test()
    assert not r.is_synthetic
    samples = list(r())
    assert len(samples) == 1
    sample = samples[0]
    assert len(sample) == 9             # the 9-feature SRL tuple
    assert len(sample[0]) == 5          # sentence length
    label_dict = conll05.get_dict()[2]
    assert sample[8][2] == label_dict['B-V']  # 'chased' tagged B-V


# ---------------------------------------------------------------------------
# imdb: aclImdb tarball of per-review .txt members
# ---------------------------------------------------------------------------

def test_imdb_acl_tarball(tmp_path, monkeypatch):
    import paddle_tpu.dataset.imdb as imdb
    tpath = tmp_path / 'aclImdb_v1.tar.gz'
    docs = {
        'aclImdb/train/pos/0_9.txt': b"A wonderful movie, truly great!",
        'aclImdb/train/pos/1_8.txt': b"great fun and great acting",
        'aclImdb/train/neg/0_2.txt': b"Terrible. awful plot, bad acting",
        'aclImdb/train/neg/1_1.txt': b"bad bad bad waste of time",
        'aclImdb/test/pos/0_9.txt': b"great film",
        'aclImdb/test/neg/0_1.txt': b"bad film",
    }
    with tarfile.open(tpath, 'w:gz') as tf:
        for name, data in docs.items():
            _tar_add_bytes(tf, name, data)
    monkeypatch.setattr(imdb, '_TAR', str(tpath))
    word_idx = imdb.build_dict('aclImdb/train/((pos)|(neg))/.*\\.txt$', 0)
    assert 'great' in word_idx and 'bad' in word_idx
    r = imdb.train(word_idx)
    assert not r.is_synthetic
    samples = list(r())
    assert len(samples) == 4
    labels = sorted(l for _, l in samples)
    assert labels == [0, 0, 1, 1]       # pos first (0), neg second (1)
    ids, _ = samples[0]
    assert all(isinstance(i, int) for i in ids)


# ---------------------------------------------------------------------------
# imikolov: PTB text inside simple-examples.tgz
# ---------------------------------------------------------------------------

def test_imikolov_ptb_tarball(tmp_path, monkeypatch):
    import paddle_tpu.dataset.imikolov as imikolov
    tpath = tmp_path / 'simple-examples.tgz'
    train_text = "the cat sat\nthe dog ran\nthe cat ran\n"
    valid_text = "the dog sat\n"
    with tarfile.open(tpath, 'w:gz') as tf:
        _tar_add_bytes(tf, './simple-examples/data/ptb.train.txt',
                       train_text.encode())
        _tar_add_bytes(tf, './simple-examples/data/ptb.valid.txt',
                       valid_text.encode())
    monkeypatch.setattr(imikolov, '_TAR', str(tpath))
    word_idx = imikolov.build_dict(min_word_freq=1)
    assert 'the' in word_idx and '<unk>' in word_idx
    r = imikolov.train(word_idx, 2, imikolov.DataType.NGRAM)
    assert not r.is_synthetic
    grams = list(r())
    assert all(len(g) == 2 for g in grams)
    # 3 sentences × (4 tokens + <s>/<e> = 5 bigram windows each... ) > 0
    assert len(grams) == 12
    seqs = list(imikolov.train(word_idx, -1, imikolov.DataType.SEQ)())
    src, trg = seqs[0]
    assert src[0] == word_idx['<s>'] and trg[-1] == word_idx['<e>']


# ---------------------------------------------------------------------------
# movielens: ml-1m zip of ::-separated .dat files
# ---------------------------------------------------------------------------

def test_movielens_ml1m_zip(tmp_path, monkeypatch):
    import paddle_tpu.dataset.movielens as ml
    zpath = tmp_path / 'ml-1m.zip'
    movies = ("1::Toy Story (1995)::Animation|Children's|Comedy\n"
              "2::Jumanji (1995)::Adventure|Fantasy\n")
    users = "1::F::1::10::48067\n2::M::25::15::55117\n"
    ratings = ("1::1::5::978300760\n1::2::3::978302109\n"
               "2::1::4::978301968\n2::2::2::978300275\n")
    with zipfile.ZipFile(zpath, 'w') as z:
        z.writestr('ml-1m/movies.dat', movies)
        z.writestr('ml-1m/users.dat', users)
        z.writestr('ml-1m/ratings.dat', ratings)
    monkeypatch.setattr(ml, '_ZIP', str(zpath))
    monkeypatch.setattr(ml, 'MOVIE_INFO', None)
    monkeypatch.setattr(ml, '_IS_SYNTHETIC', False)
    r = ml.train()
    assert not r.is_synthetic
    samples = list(r()) + list(ml.test()())
    assert len(samples) == 4            # all ratings, split train/test
    assert ml.max_movie_id() == 2 and ml.max_user_id() == 2
    title_dict = ml.get_movie_title_dict()
    assert 'toy' in title_dict and 'jumanji' in title_dict
    # sample tail is [rating]
    assert samples[0][-1][0] in (2.0, 3.0, 4.0, 5.0)


# ---------------------------------------------------------------------------
# mq2007: LETOR "<score> qid:<id> k:v ... #docid" rows
# ---------------------------------------------------------------------------

def test_mq2007_letor_file(tmp_path):
    import paddle_tpu.dataset.mq2007 as mq
    lines = []
    rng = np.random.RandomState(0)
    for qid in (10, 11):
        for score in (2, 1, 0):
            feats = ' '.join(f'{i + 1}:{rng.rand():.4f}' for i in range(5))
            lines.append(f'{score} qid:{qid} {feats} #docid = {qid}-{score}')
    path = tmp_path / 'train.txt'
    path.write_text('\n'.join(lines) + '\n')
    qls = mq.query_filter(mq.load_from_text(str(path)))
    assert len(qls) == 2 and all(len(ql) == 3 for ql in qls)
    pairs = list(getattr(mq, '__reader__')(filepath=str(path),
                                           format='pairwise'))
    assert pairs and all(p[0] == 1 and len(p) == 3 for p in pairs)
    # pointwise yields ONE point per query (ref mq2007.py:314 semantics)
    points = list(getattr(mq, '__reader__')(filepath=str(path),
                                            format='pointwise'))
    assert len(points) == 2
    score, vec = points[0]
    assert vec.shape == (5,)


# ---------------------------------------------------------------------------
# sentiment: movie_reviews/pos|neg/*.txt directory
# ---------------------------------------------------------------------------

def test_sentiment_movie_reviews_dir(tmp_path, monkeypatch):
    import paddle_tpu.dataset.sentiment as sent
    d = tmp_path / 'movie_reviews'
    for sub, texts in (('pos', ['a fine film', 'great story']),
                       ('neg', ['a dull film', 'poor story'])):
        (d / sub).mkdir(parents=True)
        for i, t in enumerate(texts):
            (d / sub / f'cv{i}.txt').write_text(t)
    monkeypatch.setattr(sent, '_DIR', str(d))
    monkeypatch.setattr(sent, '_word_dict', None)
    monkeypatch.setattr(sent, 'NUM_TRAINING_INSTANCES', 3)
    monkeypatch.setattr(sent, 'NUM_TOTAL_INSTANCES', 4)
    wd = sent.get_word_dict()
    assert 'film' in wd and 'story' in wd
    r = sent.train()
    assert not r.is_synthetic
    samples = list(r())
    assert len(samples) == 3
    assert {l for _, l in samples} <= {0, 1}


# ---------------------------------------------------------------------------
# uci_housing: whitespace-separated floats
# ---------------------------------------------------------------------------

def test_uci_housing_data_file(tmp_path, monkeypatch):
    import paddle_tpu.dataset.uci_housing as uci
    rng = np.random.RandomState(0)
    rows = rng.rand(20, 14)
    text = '\n'.join(' '.join(f'{v:.6f}' for v in row) for row in rows)
    d = tmp_path / 'uci_housing'
    d.mkdir()
    (d / 'housing.data').write_text(text + '\n')
    monkeypatch.setattr(uci, 'DATA_HOME', str(tmp_path))
    monkeypatch.setattr(uci, '_cache', {})
    train, test = uci.train(), uci.test()
    assert not train.is_synthetic
    tr, te = list(train()), list(test())
    assert len(tr) == 16 and len(te) == 4   # 20 × 0.2 test ratio
    x, y = tr[0]
    assert x.shape == (13,) and y.shape == (1,)


# ---------------------------------------------------------------------------
# wmt14: tarball with dict members + tab-separated parallel text
# ---------------------------------------------------------------------------

def test_wmt14_tarball(tmp_path, monkeypatch):
    import paddle_tpu.dataset.wmt14 as wmt14
    tpath = tmp_path / 'wmt14.tgz'
    dict_text = "<s>\n<e>\n<unk>\nthe\ncat\nkatze\ndie\n"
    train_text = "the cat\tdie katze\nthe the\tdie die\n"
    with tarfile.open(tpath, 'w:gz') as tf:
        _tar_add_bytes(tf, 'wmt14/src.dict', dict_text.encode())
        _tar_add_bytes(tf, 'wmt14/trg.dict', dict_text.encode())
        _tar_add_bytes(tf, 'wmt14/train/train', train_text.encode())
        _tar_add_bytes(tf, 'wmt14/test/test', train_text.encode())
    monkeypatch.setattr(wmt14, '_TAR', str(tpath))
    r = wmt14.train(dict_size=7)
    assert not r.is_synthetic
    samples = list(r())
    assert len(samples) == 2
    src, trg, trg_next = samples[0]
    sd, td = wmt14.get_dict(7, reverse=False)
    assert src[0] == sd['<s>'] and src[-1] == sd['<e>']
    assert trg_next[-1] == td['<e>']
    assert sd['cat'] in src and td['katze'] in trg


# ---------------------------------------------------------------------------
# wmt16: tarball + on-the-fly vocab build
# ---------------------------------------------------------------------------

def test_wmt16_tarball_and_vocab(tmp_path, monkeypatch):
    import paddle_tpu.dataset.wmt16 as wmt16
    d = tmp_path / 'wmt16'
    d.mkdir()
    tpath = d / 'wmt16.tar.gz'
    text = "the cat\tdie katze\nthe dog\tder hund\n"
    with tarfile.open(tpath, 'w:gz') as tf:
        _tar_add_bytes(tf, 'wmt16/train', text.encode())
        _tar_add_bytes(tf, 'wmt16/val', text.encode())
        _tar_add_bytes(tf, 'wmt16/test', text.encode())
    monkeypatch.setattr(wmt16, '_DIR', str(d))
    monkeypatch.setattr(wmt16, '_TAR', str(tpath))
    r = wmt16.train(src_dict_size=8, trg_dict_size=8)
    assert not r.is_synthetic
    samples = list(r())
    assert len(samples) == 2
    src, trg, trg_next = samples[0]
    # vocab was BUILT from the tar and saved to <dir>/en.dict
    assert os.path.exists(os.path.join(str(d), 'en.dict'))
    en = wmt16.get_dict('en', 8)
    assert 'the' in en
    assert src[0] == en['<s>'] and src[-1] == en['<e>']


# ---------------------------------------------------------------------------
# voc2012: VOC tar with JPEG images + PNG masks (real codecs)
# ---------------------------------------------------------------------------

def test_voc2012_tarball(tmp_path, monkeypatch):
    PIL = pytest.importorskip('PIL')
    from PIL import Image
    import paddle_tpu.dataset.voc2012 as voc
    rng = np.random.RandomState(0)

    def jpg_bytes():
        img = Image.fromarray(
            rng.randint(0, 256, (32, 48, 3)).astype(np.uint8))
        buf = io.BytesIO()
        img.save(buf, format='JPEG')
        return buf.getvalue()

    def png_bytes():
        lab = Image.fromarray(
            rng.randint(0, 21, (32, 48)).astype(np.uint8))
        buf = io.BytesIO()
        lab.save(buf, format='PNG')
        return buf.getvalue()

    tpath = tmp_path / 'VOCtrainval_11-May-2012.tar'
    with tarfile.open(tpath, 'w') as tf:
        _tar_add_bytes(tf,
                       'VOCdevkit/VOC2012/ImageSets/Segmentation/'
                       'trainval.txt', b'img0\nimg1\n')
        for n in ('img0', 'img1'):
            _tar_add_bytes(tf, f'VOCdevkit/VOC2012/JPEGImages/{n}.jpg',
                           jpg_bytes())
            _tar_add_bytes(tf,
                           f'VOCdevkit/VOC2012/SegmentationClass/{n}.png',
                           png_bytes())
    monkeypatch.setattr(voc, '_TAR', str(tpath))
    r = voc.train()
    assert not r.is_synthetic
    samples = list(r())
    assert len(samples) == 2
    img, lab = samples[0]
    assert img.shape == (3, 32, 48) and lab.shape == (32, 48)
    assert lab.max() < 21


# ---------------------------------------------------------------------------
# flowers: image tarball + .mat label/split files (scipy)
# ---------------------------------------------------------------------------

def test_flowers_mat_and_tarball(tmp_path, monkeypatch):
    pytest.importorskip('scipy')
    PIL = pytest.importorskip('PIL')
    from PIL import Image
    from scipy.io import savemat
    import paddle_tpu.dataset.flowers as flowers
    rng = np.random.RandomState(0)
    tpath = tmp_path / '102flowers.tgz'
    with tarfile.open(tpath, 'w:gz') as tf:
        for i in (1, 2, 3):
            img = Image.fromarray(
                rng.randint(0, 256, (300, 280, 3)).astype(np.uint8))
            buf = io.BytesIO()
            img.save(buf, format='JPEG')
            _tar_add_bytes(tf, f'jpg/image_{i:05d}.jpg', buf.getvalue())
    labels_path = tmp_path / 'imagelabels.mat'
    setid_path = tmp_path / 'setid.mat'
    savemat(str(labels_path), {'labels': np.array([[1, 2, 3]])})
    savemat(str(setid_path), {'trnid': np.array([[1, 2]]),
                              'tstid': np.array([[3]]),
                              'valid': np.array([[3]])})
    monkeypatch.setattr(flowers, '_TAR', str(tpath))
    monkeypatch.setattr(flowers, '_LABELS', str(labels_path))
    monkeypatch.setattr(flowers, '_SETID', str(setid_path))
    r = flowers.train()
    assert not r.is_synthetic
    samples = list(r())
    assert len(samples) == 2            # trnid = images 1, 2
    img, lab = samples[0]
    assert img.shape[0] == 3 and img.shape[1] == 224
    assert lab in (0, 1)                # labels are 1-based in the .mat
