"""tools/bench_plan.py smoke in tier-1: the memory planner runs in ≤1%
of the cold lower+compile it informs, and auto-remat fits a simulated
HBM budget the unplanned program exceeds with bitwise losses.

Runs in a SUBPROCESS: the latency acceptance divides plan time by a COLD
lower+compile, and an in-suite process has every cache warm — the
denominator would be a warmed-up fraction of the real cost."""
import json
import os
import subprocess
import sys

_REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), '..', '..'))


def test_bench_plan_smoke():
    env = dict(os.environ)
    env['JAX_PLATFORMS'] = 'cpu'
    env.pop('PADDLE_TPU_HBM_BUDGET_MB', None)
    env.pop('PADDLE_TPU_ALLREDUCE_BUCKET_MB', None)
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, 'tools', 'bench_plan.py'),
         '--smoke', '--iters', '3'],
        capture_output=True, text=True, timeout=420, env=env,
        cwd=_REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    rows = {}
    for line in proc.stdout.splitlines():
        line = line.strip()
        if line.startswith('{'):
            d = json.loads(line)
            rows[d['bench']] = d
    lat = rows['plan_latency']
    # acceptance: planning ≤1% of cold lower+compile (ISSUE 14); smoke
    # sizes have the LEAST compile to amortize against, so full size
    # only gets better
    assert lat['plan_frac_of_compile'] <= 0.01, lat
    assert lat['predicted_peak_mib'] > 0
    remat = rows['plan_remat']
    assert remat['exceeds_without_remat'], remat
    assert remat['fits_budget'], remat
    assert remat['checkpoints'] >= 1
    assert remat['bitwise_identical'], remat
    acc = rows['plan_acceptance']
    assert acc['ok'], acc
