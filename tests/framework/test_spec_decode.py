"""Speculative decoding + per-request sampling (serving/decode/):
spec-greedy bitwise parity across ragged accept lengths and block-boundary
rollbacks, the PADDLE_TPU_SPEC_DECODE=0 escape hatch, typed sampling
validation (scheduler + HTTP 400 naming the field), and the replay drill —
the same request_id + params through a FRESH subprocess reproduces the
sampled stream bitwise."""
import json
import os
import subprocess
import sys
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from paddle_tpu import profiler
from paddle_tpu.dygraph import guard
from paddle_tpu.models.causal_lm import (CausalLMConfig, TransformerLM,
                                         greedy_generate, sampled_generate)
from paddle_tpu.serving import (DecodeEngine, DecodeScheduler, InvalidRequest,
                                ServingServer)
from paddle_tpu.serving.decode.drafter import NGramDrafter, build_drafter
from paddle_tpu.serving.decode.sampling import (SamplingParams, TokenSampler,
                                                derive_stream_seed)

_REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


@pytest.fixture(scope='module')
def lm():
    with guard():
        model = TransformerLM(CausalLMConfig.tiny())
        model.eval()
        yield model


@pytest.fixture(scope='module')
def seeded_lm():
    """Deterministic weights (the replica seed) — the step-count assertion
    below depends on n-gram acceptance, which depends on the weights."""
    from paddle_tpu.serving.tier.replica import build_tiny_lm
    with guard():
        yield build_tiny_lm()


def make_engine(model, **kw):
    kw.setdefault('slots', 4)
    kw.setdefault('block_size', 4)
    kw.setdefault('max_blocks', 64)
    kw.setdefault('max_prompt_len', 16)
    kw.setdefault('max_new_tokens_cap', 16)
    return DecodeEngine(model, **kw)


def _counter(name):
    from paddle_tpu.observability import registry
    d = registry.to_dict().get(name)
    if not d or not d['samples']:
        return 0.0
    return sum(s['value'] for s in d['samples'])


_WORK = [((3, 7, 12, 5), (10, 4, 16, 7)),       # (prompt lens, budgets)
         ((9, 1, 16, 2), (12, 16, 2, 9))]


def _workload(seed=0):
    rng = np.random.RandomState(seed)
    lens, budgets = _WORK[seed % len(_WORK)]
    prompts = [list(map(int, rng.randint(3, 100, n))) for n in lens]
    return list(zip(prompts, budgets))


# -- validation ------------------------------------------------------------

def test_sampling_params_validation_unit():
    assert SamplingParams.validate(None).greedy
    p = SamplingParams.validate({'temperature': 0.7, 'top_k': 5,
                                 'top_p': 0.9, 'seed': 42})
    assert (p.temperature, p.top_k, p.top_p, p.seed) == (0.7, 5, 0.9, 42)
    assert not p.greedy
    assert SamplingParams.validate(p).to_dict() == p.to_dict()
    assert SamplingParams.validate({'top_p': 1.0}).greedy   # boundary ok
    for bad, field in (({'temperature': -0.1}, 'temperature'),
                       ({'temperature': float('inf')}, 'temperature'),
                       ({'temperature': True}, 'temperature'),
                       ({'top_k': -1}, 'top_k'),
                       ({'top_k': 1.5}, 'top_k'),
                       ({'top_p': 0.0}, 'top_p'),
                       ({'top_p': 1.5}, 'top_p'),
                       ({'seed': 'abc'}, 'seed'),
                       ({'typo_knob': 1}, 'typo_knob'),
                       ('not-a-dict', 'SamplingParams')):
        with pytest.raises(InvalidRequest) as ei:
            SamplingParams.validate(bad)
        assert field in str(ei.value), (bad, str(ei.value))


def test_submit_rejects_bad_sampling_and_request_id(lm):
    eng = make_engine(lm)
    before = _counter('decode_requests_rejected_invalid')
    with DecodeScheduler(eng) as sched:
        with pytest.raises(InvalidRequest, match='temperature'):
            sched.submit([1, 2], max_new_tokens=2,
                         sampling={'temperature': -1})
        with pytest.raises(InvalidRequest, match='unknown sampling'):
            sched.submit([1, 2], max_new_tokens=2, sampling={'temp': 0.5})
        with pytest.raises(InvalidRequest, match='request_id'):
            sched.submit([1, 2], max_new_tokens=2, request_id='a\nb')
        with pytest.raises(InvalidRequest, match='request_id'):
            sched.submit([1, 2], max_new_tokens=2, request_id='x' * 200)
    assert _counter('decode_requests_rejected_invalid') - before >= 4


def test_http_400_names_bad_field(lm):
    eng = make_engine(lm)
    sched = DecodeScheduler(eng)
    srv = ServingServer(None, port=0, generator=sched).start()
    url = f'http://127.0.0.1:{srv.port}/generate'

    def post(body):
        req = urllib.request.Request(url, data=json.dumps(body).encode())
        return urllib.request.urlopen(req)

    try:
        for body, field in (({'prompt': [1, 2], 'temperature': -1},
                             'temperature'),
                            ({'prompt': [1, 2], 'top_p': 2.0}, 'top_p'),
                            ({'prompt': [1, 2], 'tempreture': 0.5},
                             'tempreture')):
            with pytest.raises(urllib.error.HTTPError) as ei:
                post(body)
            assert ei.value.code == 400
            msg = json.loads(ei.value.read())['message']
            assert field in msg, (body, msg)
        # a valid sampled request streams, and the same request_id replays
        body = {'prompt': [5, 9, 2], 'max_new_tokens': 6, 'stream': False,
                'temperature': 0.8, 'top_k': 20, 'request_id': 'http-replay'}
        one = json.load(post(body))
        two = json.load(post(body))
        assert one['tokens'] == two['tokens'] and len(one['tokens']) == 6
        assert one['request_id'] == 'http-replay'
    finally:
        srv.shutdown()
        sched.close()


# -- sampling: greedy unchanged, sampled replayable ------------------------

def test_greedy_sampling_params_bitwise_unchanged(lm):
    """temperature=0 (explicit or default) is EXACT argmax — the engine's
    pre-sampling bitwise contract, untouched by the sampling machinery."""
    eng = make_engine(lm)
    prompt = [5, 9, 2, 44]
    ref = greedy_generate(lm, prompt, 8, pad_len=eng.padded_context)
    with DecodeScheduler(eng) as sched:
        plain = sched.submit(prompt, max_new_tokens=8).result(120)
        explicit = sched.submit(prompt, max_new_tokens=8,
                                sampling={'temperature': 0.0},
                                request_id='greedy-ignores-id').result(120)
    assert plain == ref and explicit == ref


def test_sampled_stream_matches_uncached_reference_and_replays(lm):
    """A sampled stream is a pure function of (request_id, params, prompt,
    weights): it equals the uncached whole-sequence sampled_generate
    reference, resubmission replays it bitwise, a different id diverges."""
    eng = make_engine(lm)
    prompt = [7, 3, 11, 60]
    params = {'temperature': 0.8, 'top_k': 24, 'top_p': 0.95}
    rid = 'replay-drill'
    sampler = TokenSampler(SamplingParams.validate(params), rid)
    ref = sampled_generate(lm, prompt, 10, sampler.sample,
                           pad_len=eng.padded_context)
    with DecodeScheduler(eng) as sched:
        s1 = sched.submit(prompt, max_new_tokens=10, sampling=params,
                          request_id=rid)
        got = s1.result(120)
        again = sched.submit(prompt, max_new_tokens=10, sampling=params,
                             request_id=rid).result(120)
        other = sched.submit(prompt, max_new_tokens=10, sampling=params,
                             request_id='another-id').result(120)
    assert got == ref
    assert again == got                       # bitwise replay
    assert other != got                       # the id IS the seed
    assert s1.request_id == rid
    # explicit seed wins over the request_id
    assert derive_stream_seed('x', seed=7) == 7
    assert derive_stream_seed('x') != derive_stream_seed('y')


# -- speculative decoding: parity + perf structure -------------------------

def test_spec_greedy_parity_and_fewer_steps(seeded_lm):
    """The acceptance bar: speculative greedy streams are array_equal to
    non-speculative greedy (which equals the uncached reference), and the
    verify rounds take FEWER decode steps than lockstep on the same
    workload."""
    work = _workload(0) + _workload(1)

    def run(**kw):
        eng = make_engine(seeded_lm, **kw)
        before = _counter('decode_steps')
        with DecodeScheduler(eng) as sched:
            streams = [sched.submit(p, max_new_tokens=m) for p, m in work]
            outs = [s.result(240) for s in streams]
        assert eng.pool.allocator.used == 0
        return outs, _counter('decode_steps') - before

    refs, steps_lockstep = run()
    spec, steps_spec = run(spec_decode=True, spec_k=4)
    assert spec == refs
    assert steps_spec < steps_lockstep, (steps_spec, steps_lockstep)
    assert _counter('decode_spec_rounds') > 0


class _OffsetOracle:
    """Drafts the TRUE greedy continuation shifted by ``off`` token ids:
    off=0 → every draft accepted (full-k rounds), off≠0 → every draft
    rejected (0-accept rounds, a rollback at every block boundary)."""

    def __init__(self, prompt, ref, off=0):
        self.plen, self.ref, self.off = len(prompt), list(ref), int(off)

    def propose(self, history, n):
        i = len(history) - self.plen
        return [(t + self.off) % 128 for t in self.ref[i:i + int(n)]]


def test_spec_ragged_accept_lengths_bitwise(lm):
    """Force the accept-length extremes through oracle drafters: all-k
    accepts, all-0 accepts (every round rolls its tail back, including at
    block boundaries — block_size=4, contexts cross many), and eos retiring
    a request mid-round. Every case must be bitwise greedy."""
    prompt = [3, 5, 7, 11, 13]
    eng0 = make_engine(lm)
    ref = greedy_generate(lm, prompt, 16, pad_len=eng0.padded_context)
    del eng0

    def run(off, **submit_kw):
        eng = make_engine(lm, spec_decode=True, spec_k=4)
        drafter = _OffsetOracle(prompt, ref, off)
        drafted = _counter('decode_spec_draft_tokens')
        accepted = _counter('decode_spec_accepted_tokens')
        with DecodeScheduler(eng, drafter=drafter) as sched:
            out = sched.submit(prompt, max_new_tokens=16,
                               **submit_kw).result(240)
        assert eng.pool.allocator.used == 0
        return (out, _counter('decode_spec_draft_tokens') - drafted,
                _counter('decode_spec_accepted_tokens') - accepted)

    full, drafted, accepted = run(0)
    assert full == ref
    assert drafted > 0 and accepted == drafted    # oracle: full-k accepts
    none, drafted, accepted = run(1)
    assert none == ref
    assert drafted > 0 and accepted == 0          # all rejected, all rolled
    # eos mid-verify-window retires the request before the window ends
    eos = ref[2]
    expect = ref[:ref.index(eos) + 1]             # first occurrence stops it
    eng = make_engine(lm, spec_decode=True, spec_k=4)
    with DecodeScheduler(eng, drafter=_OffsetOracle(prompt, ref)) as sched:
        s = sched.submit(prompt, max_new_tokens=16, eos_id=eos)
        assert s.result(240) == expect
        assert s.finish_reason == 'stop'
    assert eng.pool.allocator.used == 0


def test_spec_sampled_stream_identical_to_lockstep(lm):
    """Sampled slots ride the verify step one token at a time: the stream
    equals the non-speculative sampled stream (same draws, same indexes)
    and still replays from its request_id."""
    prompt = [9, 2, 31]
    params = {'temperature': 1.1, 'top_p': 0.9}

    def run(**kw):
        eng = make_engine(lm, **kw)
        with DecodeScheduler(eng) as sched:
            return sched.submit(prompt, max_new_tokens=8, sampling=params,
                                request_id='spec-sampled').result(240)

    lockstep = run()
    assert run(spec_decode=True) == lockstep
    assert run(spec_decode=True) == lockstep      # replay under spec


def test_spec_warmup_precompiles_verify_shape(lm):
    """warmup() covers the (S, k) verify shape too: spec generations add
    ZERO eager kernel-cache misses afterwards, and ``warmed`` stays False
    until the spec shape has compiled."""
    eng = make_engine(lm, spec_decode=True)
    assert not eng.warmed
    timings = eng.warmup()
    assert eng.warmed and 'spec_step' in timings
    profiler.reset_eager_kernel_cache_stats()
    with DecodeScheduler(eng) as sched:
        outs = [sched.submit(p, max_new_tokens=m).result(240)
                for p, m in _workload(0)]
    assert all(len(o) for o in outs)
    stats = profiler.eager_kernel_cache_stats()
    assert stats['misses'] == 0, stats


# -- knobs -----------------------------------------------------------------

def test_spec_escape_hatch_env_zero_wins(lm, monkeypatch):
    monkeypatch.setenv('PADDLE_TPU_SPEC_DECODE', '0')
    eng = make_engine(lm, spec_decode=True)       # arg says on; env 0 wins
    assert not eng.spec_enabled
    prompt = [5, 9, 2]
    ref = greedy_generate(lm, prompt, 6, pad_len=eng.padded_context)
    with DecodeScheduler(eng) as sched:
        assert sched.drafter is None
        assert sched.submit(prompt, max_new_tokens=6).result(120) == ref


def test_spec_env_knobs(lm, monkeypatch):
    monkeypatch.setenv('PADDLE_TPU_SPEC_DECODE', '1')
    monkeypatch.setenv('PADDLE_TPU_SPEC_K', '3')
    eng = make_engine(lm)
    assert eng.spec_enabled and eng.spec_k == 3
    monkeypatch.setenv('PADDLE_TPU_SPEC_DRAFTER', 'off')
    with DecodeScheduler(eng, start=False) as sched:
        assert sched.drafter is None              # knob resolved 'off'
    monkeypatch.setenv('PADDLE_TPU_SPEC_DRAFTER', 'bogus')
    with pytest.raises(ValueError, match='bogus'):
        DecodeScheduler(eng, start=False).close()
    with pytest.raises(ValueError):
        make_engine(lm, spec_decode=True, spec_k=1)


def test_ngram_drafter_and_build(lm):
    d = NGramDrafter()
    #              0  1  2  3  4  5  6
    history = [7, 8, 9, 4, 7, 8, 9]
    assert d.propose(history, 2) == [4, 7]        # longest suffix [7,8,9]
    assert d.propose([1, 2, 3], 4) == []          # no earlier occurrence
    assert d.propose([5], 3) == []                # history too short
    assert d.propose(history, 0) == []
    assert build_drafter('off', 32) is None
    assert isinstance(build_drafter(None, 32), NGramDrafter)
    dm = build_drafter('draft_model', 32, draft_model=lm)
    assert dm.propose([3, 5, 7], 2) == greedy_generate(lm, [3, 5, 7], 2,
                                                       pad_len=32)
    with pytest.raises(InvalidRequest, match='supported'):
        build_drafter('nope', 32)
    sentinel = NGramDrafter()
    assert build_drafter(sentinel, 32) is sentinel   # duck-typed pass-through


# -- replay drill: fresh subprocess ----------------------------------------

def _spawn_replica(*extra):
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    env.pop('PADDLE_TPU_TELEMETRY', None)
    proc = subprocess.Popen(
        [sys.executable, '-m', 'paddle_tpu.serving.tier.replica',
         '--port', '0', '--slots', '2', *extra],
        cwd=_REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL, text=True)
    deadline = time.monotonic() + 180
    line = ''
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if line.strip():
            break
        if proc.poll() is not None:
            raise RuntimeError(f'replica died at startup rc={proc.returncode}')
    ready = json.loads(line)
    assert ready['ready']
    return proc, f"http://127.0.0.1:{ready['port']}"


def test_replay_drill_fresh_subprocess_bitwise():
    """The restart-safety contract end to end: the same request_id + params
    posted to a FRESH replica process — even one running with speculative
    decoding ON — returns the bitwise-identical sampled stream."""
    body = json.dumps({'prompt': [5, 9, 2, 44], 'max_new_tokens': 8,
                       'stream': False, 'temperature': 0.9, 'top_k': 12,
                       'top_p': 0.8, 'request_id': 'drill-1'}).encode()

    def post_once(*extra):
        proc, url = _spawn_replica(*extra)
        try:
            req = urllib.request.Request(url + '/generate', data=body)
            reply = json.load(urllib.request.urlopen(req, timeout=120))
        finally:
            proc.kill()
            proc.wait()
        assert reply['request_id'] == 'drill-1'
        assert len(reply['tokens']) == 8
        return reply['tokens']

    first = post_once()
    again = post_once('--spec-decode', '1')       # fresh pid, spec on
    assert first == again
