"""contrib.slim QAT/PTQ coverage (VERDICT r3 weak #5: previously only the
int8 Predictor path was tested). Ref: python/paddle/fluid/contrib/slim/
quantization QuantizationTransformPass / FreezePass / PostTrainingQuant."""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import dygraph, layers
from paddle_tpu.contrib import slim


def _mlp():
    from paddle_tpu.dygraph.container import Sequential
    return Sequential(
        dygraph.nn.Linear(8, 16, act='relu'),
        dygraph.nn.Linear(16, 4))


def test_quant_aware_wraps_quantizable_layers():
    with dygraph.guard():
        m = _mlp()
        slim.quant_aware(m)
        wrapped = [s for _, s in m.named_sublayers()
                   if isinstance(s, slim.FakeQuantWrapper)]
        assert len(wrapped) == 2


def test_quant_aware_output_close_to_float_and_trains():
    rng = np.random.RandomState(0)
    xv = rng.standard_normal((4, 8)).astype(np.float32)
    with dygraph.guard():
        fluid.framework.manual_seed(0)
        m = _mlp()
        ref = np.asarray(m(dygraph.to_variable(xv)).numpy())
        slim.quant_aware(m)
        m.train()
        # EMA observers start cold (scale=1) and clip on early steps —
        # warm them up like real QAT, then compare in eval mode
        for _ in range(25):
            m(dygraph.to_variable(xv))
        m.eval()
        out = np.asarray(m(dygraph.to_variable(xv)).numpy())
        m.train()
        # 8-bit fake quant-dequant stays close to the float forward
        denom = max(np.abs(ref).max(), 1e-6)
        assert np.abs(out - ref).max() / denom < 0.15
        # QAT model still trains (STE gradients flow to the inner weights)
        opt = fluid.optimizer.SGD(learning_rate=0.1,
                                  parameter_list=m.parameters())
        losses = []
        for _ in range(12):
            pred = m(dygraph.to_variable(xv))
            loss = layers.reduce_mean(layers.square_error_cost(
                pred, dygraph.to_variable(np.ones((4, 4), np.float32))))
            loss.backward()
            opt.minimize(loss)
            opt.clear_gradients()
            losses.append(float(np.asarray(loss.numpy()).reshape(())[()]))
        assert losses[-1] < losses[0] * 0.5


def test_convert_strips_wrappers_and_reports_scales():
    with dygraph.guard():
        m = _mlp()
        slim.quant_aware(m)
        m.train()
        m(dygraph.to_variable(np.ones((2, 8), np.float32)))
        m2, scales = slim.convert(m)
        assert not any(isinstance(s, slim.FakeQuantWrapper)
                       for _, s in m2.named_sublayers())
        assert len(scales) == 2
        for info in scales.values():
            assert info['activation'] > 0
            assert (info['weight'] > 0).all()


def test_quant_post_calibration_scales():
    rng = np.random.RandomState(1)
    with dygraph.guard():
        m = _mlp()

        def calib():
            for _ in range(4):
                yield rng.standard_normal((2, 8)).astype(np.float32) * 3.0

        scales = slim.quant_post(m, calib, num_batches=3)
        assert len(scales) == 2
        first = next(iter(scales.values()))
        # activations were fed with |x| up to ~3σ·3 — scale reflects it
        assert first['activation'] > 1.0
        assert first['weight'].shape[0] in (8, 16)
