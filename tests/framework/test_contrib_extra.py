"""contrib extras: decoupled weight decay (AdamW), basic_lstm/gru,
contrib layer fns, PTQ class wrappers (ref contrib/ surface)."""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import dygraph, layers
from paddle_tpu.contrib import extra


def test_extend_with_decoupled_weight_decay_dygraph():
    AdamW = extra.extend_with_decoupled_weight_decay(
        fluid.optimizer.AdamOptimizer)
    with dygraph.guard():
        fc = dygraph.nn.Linear(4, 2, bias_attr=False)
        opt = AdamW(weight_decay=0.1, learning_rate=0.0,
                    parameter_list=fc.parameters())
        w0 = np.asarray(fc.weight.numpy()).copy()
        out = fc(dygraph.to_variable(np.ones((2, 4), np.float32)))
        loss = layers.reduce_mean(out)
        loss.backward()
        opt.minimize(loss)
        # lr=0 → inner Adam step is a no-op; with DECOUPLED decay the
        # weights also stay put (decay is coeff*lr*w = 0), proving the
        # decay is lr-scaled rather than folded into the gradient
        np.testing.assert_allclose(np.asarray(fc.weight.numpy()), w0,
                                   rtol=1e-6)

    with dygraph.guard():
        fc = dygraph.nn.Linear(4, 2, bias_attr=False)
        opt = AdamW(weight_decay=0.5, learning_rate=0.1,
                    parameter_list=fc.parameters())
        w0 = np.asarray(fc.weight.numpy()).copy()
        out = fc(dygraph.to_variable(np.zeros((2, 4), np.float32)))
        loss = layers.reduce_mean(out)
        loss.backward()
        opt.minimize(loss)
        # zero input → zero grad for the weight → pure decay shrink
        np.testing.assert_allclose(np.asarray(fc.weight.numpy()),
                                   w0 * (1 - 0.05), rtol=1e-4)


def test_basic_lstm_and_gru_train_static():
    """basic_lstm/basic_gru are static layers with TRAINABLE weights."""
    main, start = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, start):
        x = layers.data('x', [5, 8], dtype='float32',
                        append_batch_size=False)
        x.shape = (-1, 5, 8)
        h, last_h, last_c = extra.basic_lstm(x, None, None, hidden_size=6)
        g, last_g = extra.basic_gru(x, None, hidden_size=6)
        loss = layers.reduce_mean(layers.square(h)) +             layers.reduce_mean(layers.square(g))
        fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)
    assert len(main.all_parameters()) == 6     # 3 lstm + 3 gru weights
    exe = fluid.Executor()
    exe.run(start)
    xv = np.random.RandomState(0).standard_normal((2, 5, 8))         .astype('float32')
    losses = []
    for _ in range(5):
        hv, lv = exe.run(main, feed={'x': xv}, fetch_list=[h, loss])
        losses.append(float(np.ravel(lv)[0]))
    assert hv.shape == (2, 5, 6)
    assert losses[-1] < losses[0]              # weights actually train

    # stateful round-trip: last states feed back as init states
    main2, start2 = fluid.Program(), fluid.Program()
    with fluid.program_guard(main2, start2):
        x3 = layers.data('x3', [5, 8], dtype='float32')
        h1, lh, lc = extra.basic_lstm(x3, None, None, hidden_size=6)
        h2, lh2, lc2 = extra.basic_lstm(x3, lh, lc, hidden_size=6)
        assert lh.shape[0] == 1 and h2.shape[-1] == 6
    exe2 = fluid.Executor()
    exe2.run(start2)
    out2, = exe2.run(main2,
                     feed={'x3': np.zeros((2, 5, 8), np.float32)},
                     fetch_list=[h2])
    assert out2.shape == (2, 5, 6)

    with pytest.raises(NotImplementedError):
        with fluid.program_guard(fluid.Program(), fluid.Program()):
            x2 = layers.data('x2', [5, 8], dtype='float32')
            extra.basic_lstm(x2, None, None, hidden_size=4, num_layers=2)


def test_basic_units_step():
    with dygraph.guard():
        cell = extra.BasicLSTMUnit(hidden_size=4)
        x = dygraph.to_variable(np.ones((3, 5), np.float32))
        h0 = dygraph.to_variable(np.zeros((3, 4), np.float32))
        c0 = dygraph.to_variable(np.zeros((3, 4), np.float32))
        h, c = cell(x, h0, c0)
        assert h.shape == (3, 4) and c.shape == (3, 4)
        gru = extra.BasicGRUUnit(hidden_size=4)
        h2 = gru(x, h0)
        assert h2.shape == (3, 4)


def test_contrib_layer_fns():
    with dygraph.guard():
        a = dygraph.to_variable(np.ones((2, 4), np.float32))
        b = dygraph.to_variable(np.ones((2, 4), np.float32) * 2)
        out = extra.fused_elemwise_activation(a, b,
                                              ['elementwise_add', 'relu'])
        np.testing.assert_allclose(np.asarray(out.numpy()),
                                   np.full((2, 4), 3.0))
        pc = extra.partial_concat([a, b], start_index=1, length=2)
        assert np.asarray(pc.numpy()).shape == (2, 4)
        ps = extra.partial_sum([a, b], start_index=0, length=3)
        np.testing.assert_allclose(np.asarray(ps.numpy()),
                                   np.full((2, 3), 3.0))


def test_post_training_quantization_class():
    from paddle_tpu.contrib.slim import PostTrainingQuantization
    from paddle_tpu.dygraph.container import Sequential
    rng = np.random.RandomState(0)
    with dygraph.guard():
        m = Sequential(dygraph.nn.Linear(4, 8), dygraph.nn.Linear(8, 2))

        def reader():
            for _ in range(3):
                yield rng.standard_normal((2, 4)).astype('float32')

        ptq = PostTrainingQuantization(model=m, sample_generator=reader,
                                       batch_nums=2)
        scales = ptq.quantize()
        assert len(scales) == 2 and ptq.scales is scales


def test_weight_quantization_class():
    from paddle_tpu.contrib.slim import WeightQuantization
    with dygraph.guard():
        fc = dygraph.nn.Linear(4, 2)
        wq = WeightQuantization(model=fc)
        # Linear itself is quantizable when wrapped in a parent
        from paddle_tpu.dygraph.container import Sequential
        m = Sequential(fc)
        scales = WeightQuantization(model=m).quantize_weight_to_int()
        assert len(scales) == 1
        s = next(iter(scales.values()))
        assert (s > 0).all()
