"""End-to-end sparse embedding fast path (docs/SPARSE.md): sparse-vs-
dense parity on both spines (dygraph tape + static executor), the DeepFM
recipe, vocab-sharded tables on a CPU mesh, the quantized sparse push,
OOB-id validation, and the escape hatches."""
import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers as L
import paddle_tpu.dygraph as dygraph
from paddle_tpu.dygraph import Embedding, Linear, to_variable
from paddle_tpu.dygraph.tape import dispatch_op, Tensor
from paddle_tpu.core.random import default_generator
from paddle_tpu.ops import sparse_ops as sp


def _dy_mlp_losses(is_sparse, opt_name, steps=4, vary_ids=True, seed=11):
    """Embedding-MLP dygraph run; returns (losses, final table)."""
    with dygraph.guard():
        default_generator.seed(seed)
        emb = Embedding([60, 8], is_sparse=is_sparse)
        fc = Linear(8, 4)
        params = emb.parameters() + fc.parameters()
        opt = {'sgd': lambda: fluid.optimizer.SGD(0.1,
                                                  parameter_list=params),
               'adam': lambda: fluid.optimizer.Adam(
                   0.01, parameter_list=params),
               'adagrad': lambda: fluid.optimizer.Adagrad(
                   0.05, parameter_list=params),
               'momentum': lambda: fluid.optimizer.MomentumOptimizer(
                   0.05, parameter_list=params)}[opt_name]()
        rng = np.random.RandomState(3)
        losses = []
        for i in range(steps):
            ids = rng.randint(0, 60, (4, 3)) if vary_ids \
                else np.array([[1, 2, 3], [3, 4, 1]])
            x = emb(to_variable(ids.astype(np.int64)))
            y = fc(x)
            loss = dispatch_op('reduce_mean', {'x': y * y}, {})
            loss.backward()
            opt.minimize(loss)
            opt.clear_gradients()
            losses.append(float(loss.numpy()))
        return losses, np.asarray(emb.weight.value)


@pytest.mark.parametrize('opt_name', ['sgd', 'adagrad'])
def test_dygraph_parity_varying_ids(opt_name):
    """SGD/Adagrad: a zero dense gradient is an exact no-op, so rows-only
    updates must reproduce the dense trajectory even when every batch
    touches a different id set."""
    ld, wd = _dy_mlp_losses(False, opt_name)
    ls, ws = _dy_mlp_losses(True, opt_name)
    assert np.allclose(ld, ls, atol=1e-6), (ld, ls)
    assert np.allclose(wd, ws, atol=1e-6)


@pytest.mark.parametrize('opt_name', ['adam', 'momentum'])
def test_dygraph_parity_fixed_ids(opt_name):
    """Adam/momentum carry per-row state that dense updates decay even
    for untouched rows; with a FIXED id set the lazy rows-only update is
    exactly the dense one."""
    ld, wd = _dy_mlp_losses(False, opt_name, vary_ids=False)
    ls, ws = _dy_mlp_losses(True, opt_name, vary_ids=False)
    assert np.allclose(ld, ls, atol=1e-6)
    assert np.allclose(wd, ws, atol=1e-5)


def test_dygraph_grad_is_rows_only():
    with dygraph.guard():
        default_generator.seed(1)
        emb = Embedding([40, 4], is_sparse=True)
        out = emb(to_variable(np.array([[1, 2, 2]], np.int64)))
        loss = dispatch_op('reduce_sum', {'x': out}, {})
        loss.backward()
        g = emb.weight.grad
        assert isinstance(g, sp.SparseRowsGrad)
        assert g.nnz == sp.nnz_bucket(3)
        rows = np.asarray(g.rows)
        assert set(rows[rows < 40].tolist()) == {1, 2}
        # gradient() API densifies for user code
        dense = emb.weight.gradient()
        assert dense.shape == (40, 4)
        assert np.allclose(dense[2], 2.0) and np.allclose(dense[1], 1.0)


def test_dygraph_knob_off_restores_dense(monkeypatch):
    monkeypatch.setenv('PADDLE_TPU_SPARSE_GRAD', '0')
    with dygraph.guard():
        default_generator.seed(1)
        emb = Embedding([40, 4], is_sparse=True)
        out = emb(to_variable(np.array([[1, 2]], np.int64)))
        dispatch_op('reduce_sum', {'x': out}, {}).backward()
        assert not isinstance(emb.weight.grad, sp.SparseRowsGrad)


def test_dygraph_padding_idx_rows_get_zero_grad():
    with dygraph.guard():
        default_generator.seed(1)
        emb = Embedding([40, 4], is_sparse=True, padding_idx=2)
        out = emb(to_variable(np.array([[1, 2, 3]], np.int64)))
        dispatch_op('reduce_sum', {'x': out}, {}).backward()
        dense = emb.weight.gradient()
        assert np.allclose(dense[2], 0.0)
        assert np.allclose(dense[1], 1.0) and np.allclose(dense[3], 1.0)


def test_unsupported_sparse_optimizer_raises():
    with dygraph.guard():
        default_generator.seed(1)
        emb = Embedding([40, 4], is_sparse=True)
        opt = fluid.optimizer.AdadeltaOptimizer(
            parameter_list=emb.parameters())
        out = emb(to_variable(np.array([[1]], np.int64)))
        dispatch_op('reduce_sum', {'x': out}, {}).backward()
        with pytest.raises(ValueError, match='sparse'):
            opt.minimize(out)


# ---------------------------------------------------------------------------
# static spine
# ---------------------------------------------------------------------------

def _static_run(is_sparse, opt_name='sgd', steps=5, deepfm=False, V=200):
    import paddle_tpu.core.scope as sm
    from paddle_tpu.core.scope import Scope
    from paddle_tpu.core import unique_name
    # fresh name counter per run so the sparse and dense builds declare
    # identical var names (the fixture only resets between tests)
    unique_name.generator = unique_name.UniqueNameGenerator()
    default_generator.seed(42)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        if deepfm:
            ids = L.data('ids', [6], dtype='int64')
            vals = L.data('vals', [6], dtype='float32')
            label = L.data('label', [1], dtype='float32')
            w1 = L.embedding(ids, size=[V, 1], is_sparse=is_sparse)
            emb = L.embedding(ids, size=[V, 8], is_sparse=is_sparse)
            v3 = L.unsqueeze(vals, axes=[2])
            first = L.reduce_sum(w1 * v3, dim=1)
            e = emb * v3
            sum_sq = L.square(L.reduce_sum(e, dim=1))
            sq_sum = L.reduce_sum(L.square(e), dim=1)
            second = 0.5 * L.reduce_sum(sum_sq - sq_sum, dim=1,
                                        keep_dim=True)
            deep = L.fc(e, size=16, act='relu')
            logit = L.fc(L.concat([first, second, deep], axis=1), size=1)
            loss = L.reduce_mean(
                L.sigmoid_cross_entropy_with_logits(logit, label))
        else:
            ids = L.data('ids', [5], dtype='int64')
            label = L.data('label', [1], dtype='float32')
            emb = L.embedding(ids, size=[V, 16], is_sparse=is_sparse)
            h = L.fc(emb, size=8, act='relu')
            out = L.fc(h, size=1)
            loss = L.reduce_mean(L.square_error_cost(out, label))
        {'sgd': lambda: fluid.optimizer.SGD(0.1),
         'adagrad': lambda: fluid.optimizer.Adagrad(0.05),
         'adam': lambda: fluid.optimizer.Adam(0.01)}[opt_name]() \
            .minimize(loss)
    exe = fluid.Executor()
    old = sm._global_scope
    sm._global_scope = Scope()
    try:
        exe.run(startup)
        rng = np.random.RandomState(0)
        losses = []
        for _ in range(steps):
            f = {'ids': rng.randint(0, V, (4, 6 if deepfm else 5))
                 .astype(np.int64),
                 'label': rng.rand(4, 1).astype(np.float32)}
            if deepfm:
                f['vals'] = rng.rand(4, 6).astype(np.float32)
            l, = exe.run(main, feed=f, fetch_list=[loss])
            losses.append(float(l))
        tables = {v.name: np.asarray(sm._global_scope.find(v.name))
                  for v in main.all_parameters()
                  if len(v.shape) == 2 and v.shape[0] == V}
        return losses, tables, main
    finally:
        sm._global_scope = old


@pytest.mark.parametrize('opt_name', ['sgd', 'adagrad'])
def test_static_parity_embedding_mlp(opt_name):
    ld, td, _ = _static_run(False, opt_name)
    ls, ts, _ = _static_run(True, opt_name)
    assert np.allclose(ld, ls, atol=1e-5), (ld, ls)
    for name in td:
        assert np.allclose(td[name], ts[name], atol=1e-5)


def test_static_parity_deepfm():
    ld, td, _ = _static_run(False, 'adagrad', deepfm=True)
    ls, ts, main = _static_run(True, 'adagrad', deepfm=True)
    assert np.allclose(ld, ls, atol=1e-5), (ld, ls)
    for name in td:
        assert np.allclose(td[name], ts[name], atol=1e-5)
    # the program really took the sparse path: marker carries the COO
    # outputs and sparse_* update ops exist
    blk = main.global_block()
    types = {op.type for op in blk.ops}
    assert 'sparse_adagrad' in types
    marker = next(op for op in blk.ops if op.type == '__backward__')
    assert len(marker.attrs['sparse_params']) == 2
    assert len(marker.outputs['SparseRows']) == 2


def test_static_dense_reader_falls_back():
    """A table ALSO read by a dense op (weight tying) must keep the
    dense gradient path — sparsifying would drop the dense use's
    contribution."""
    default_generator.seed(7)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = L.data('ids', [3], dtype='int64')
        emb = L.embedding(ids, size=[30, 8], is_sparse=True)
        h = L.reduce_sum(emb, dim=1)
        w = main.global_block().var(
            [v.name for v in main.all_parameters()][0])
        tied = L.matmul(h, w, transpose_y=True)     # dense reuse
        loss = L.reduce_mean(tied)
        fluid.optimizer.SGD(0.1).minimize(loss)
    marker = next(op for op in main.global_block().ops
                  if op.type == '__backward__')
    assert not marker.attrs.get('sparse_params')
    assert w.name in marker.attrs['params']


def test_static_metrics_recorded():
    from paddle_tpu.ops.sparse_ops import sparse_metrics_snapshot
    before = sparse_metrics_snapshot()
    _static_run(True, 'sgd', steps=3)
    after = sparse_metrics_snapshot()
    assert after['sparse_lookup_ids_total'] > \
        before['sparse_lookup_ids_total']
    assert after['sparse_grad_rows_total'] > \
        before['sparse_grad_rows_total']


def test_static_knob_off_keeps_dense_marker(monkeypatch):
    monkeypatch.setenv('PADDLE_TPU_SPARSE_GRAD', '0')
    _, _, main = _static_run(True, 'sgd', steps=1)
    marker = next(op for op in main.global_block().ops
                  if op.type == '__backward__')
    assert not marker.attrs.get('sparse_params')


def test_gradient_merge_rejects_sparse():
    default_generator.seed(7)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = L.data('ids', [3], dtype='int64')
        emb = L.embedding(ids, size=[30, 8], is_sparse=True)
        loss = L.reduce_mean(emb)
        opt = fluid.optimizer.GradientMergeOptimizer(
            fluid.optimizer.SGD(0.1), k_steps=2)
        with pytest.raises(RuntimeError, match='sparse'):
            opt.minimize(loss)


def test_eval_clone_of_sparse_program_runs():
    """clone(for_test=True) drops the marker; the stamped lookup ops must
    run as plain dense gathers outside a sparse trace."""
    import paddle_tpu.core.scope as sm
    from paddle_tpu.core.scope import Scope
    default_generator.seed(5)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = L.data('ids', [4], dtype='int64')
        emb = L.embedding(ids, size=[50, 8], is_sparse=True)
        out = L.reduce_sum(emb, dim=[1, 2])
        loss = L.reduce_mean(out)
        fluid.optimizer.SGD(0.1).minimize(loss)
    test_prog = main.clone(for_test=True)
    exe = fluid.Executor()
    old = sm._global_scope
    sm._global_scope = Scope()
    try:
        exe.run(startup)
        f = {'ids': np.array([[1, 2, 3, 4]], np.int64)}
        # eval FIRST: the train step updates the table in place, and the
        # train fetch observes the pre-update forward
        eval_out, = exe.run(test_prog, feed=f, fetch_list=[out])
        train_out, = exe.run(main, feed=dict(
            f, label=np.ones((1, 1), np.float32)), fetch_list=[out])
        assert np.array_equal(train_out, eval_out)
    finally:
        sm._global_scope = old


# ---------------------------------------------------------------------------
# serving validate() OOB satellite
# ---------------------------------------------------------------------------

def test_serving_validate_rejects_oob_ids(tmp_path, monkeypatch):
    import paddle_tpu.core.scope as sm
    from paddle_tpu.core.scope import Scope
    from paddle_tpu.serving import InferenceEngine, InvalidRequest
    default_generator.seed(5)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = L.data('ids', [4], dtype='int64')
        emb = L.embedding(ids, size=[50, 8])
        out = L.reduce_sum(emb, dim=[1, 2])
    exe = fluid.Executor()
    old = sm._global_scope
    sm._global_scope = Scope()
    try:
        exe.run(startup)
        fluid.io.save_inference_model(str(tmp_path), ['ids'], [out], exe,
                                      main_program=main)
    finally:
        sm._global_scope = old
    eng = InferenceEngine(str(tmp_path), max_batch_size=4)
    assert 'ids' in eng.id_bounds and eng.id_bounds['ids'][0] == 50
    ok, _ = eng.validate({'ids': np.array([[0, 1, 2, 49]], np.int64)})
    assert ok['ids'].shape == (1, 4)
    with pytest.raises(InvalidRequest, match='outside'):
        eng.validate({'ids': np.array([[0, 1, 2, 55]], np.int64)})
    with pytest.raises(InvalidRequest, match='outside'):
        eng.validate({'ids': np.array([[-1, 1, 2, 3]], np.int64)})
    monkeypatch.setenv('PADDLE_TPU_EMBED_OOB', 'clip')   # escape hatch
    ok, _ = eng.validate({'ids': np.array([[0, 1, 2, 55]], np.int64)})
    assert ok['ids'].shape == (1, 4)


def test_executor_full_verify_rejects_oob(monkeypatch):
    import paddle_tpu.core.scope as sm
    from paddle_tpu.core.scope import Scope
    monkeypatch.setenv('PADDLE_TPU_VERIFY', 'full')
    default_generator.seed(5)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = L.data('ids', [3], dtype='int64')
        emb = L.embedding(ids, size=[20, 4], is_sparse=True)
        loss = L.reduce_mean(emb)
        fluid.optimizer.SGD(0.1).minimize(loss)
    exe = fluid.Executor()
    old = sm._global_scope
    sm._global_scope = Scope()
    try:
        exe.run(startup)
        exe.run(main, feed={'ids': np.array([[1, 2, 3]], np.int64)},
                fetch_list=[loss])
        with pytest.raises(ValueError, match='outside'):
            exe.run(main, feed={'ids': np.array([[1, 2, 30]], np.int64)},
                    fetch_list=[loss])
        monkeypatch.setenv('PADDLE_TPU_EMBED_OOB', 'clip')
        exe.run(main, feed={'ids': np.array([[1, 2, 30]], np.int64)},
                fetch_list=[loss])
    finally:
        sm._global_scope = old


# ---------------------------------------------------------------------------
# vocab-sharded tables (CPU mesh)
# ---------------------------------------------------------------------------

@pytest.fixture
def mesh8():
    from paddle_tpu.partition import make_mesh
    if len(jax.devices()) < 8:
        pytest.skip('needs 8 devices')
    return make_mesh


def test_sharded_lookup_bitwise(mesh8):
    from paddle_tpu.partition.sparse import VocabShardedTable
    rng = np.random.RandomState(0)
    V, D = 64, 8
    init = rng.randn(V, D).astype(np.float32)
    t = VocabShardedTable(V, D, mesh8({'tp': 4}), axis='tp', init=init)
    for n in (1, 7, 16, 33):
        ids = rng.randint(0, V, (n,)).astype(np.int64)
        assert np.array_equal(np.asarray(t.lookup(ids)), init[ids])
    # 2-D id batches keep their shape
    ids2 = rng.randint(0, V, (3, 5)).astype(np.int64)
    out = np.asarray(t.lookup(ids2))
    assert out.shape == (3, 5, D)
    assert np.array_equal(out, init[ids2])


def test_sharded_push_parity_vs_dense(mesh8):
    from paddle_tpu.partition.sparse import VocabShardedTable
    rng = np.random.RandomState(1)
    V, D = 64, 8
    init = rng.randn(V, D).astype(np.float32)
    ids = rng.randint(0, V, (13,))
    vals = rng.randn(13, D).astype(np.float32)
    rows, cvals = sp.coalesce_rows(jnp.asarray(ids, jnp.int32),
                                   jnp.asarray(vals), V)
    dense = np.zeros((V, D), np.float32)
    r_, v_ = np.asarray(rows), np.asarray(cvals)
    np.add.at(dense, r_[r_ < V], v_[r_ < V])
    t = VocabShardedTable(V, D, mesh8({'tp': 4}), axis='tp', init=init)
    t.sgd_push(rows, cvals, 0.1)
    assert np.allclose(t.full_table(), init - 0.1 * dense, atol=1e-6)


def test_sharded_dp_push_f32_exact_int8_bounded(mesh8):
    from paddle_tpu.partition.sparse import VocabShardedTable
    rng = np.random.RandomState(2)
    V, D = 64, 8
    init = rng.randn(V, D).astype(np.float32)
    mesh = mesh8({'dp': 2, 'tp': 4})
    per_replica = []
    dense = np.zeros((V, D), np.float32)
    for _ in range(2):
        ids = rng.randint(0, V, (8,))
        vals = rng.randn(8, D).astype(np.float32)
        r, v = sp.coalesce_rows(jnp.asarray(ids, jnp.int32),
                                jnp.asarray(vals), V, bucket=8)
        per_replica.append((r, v))
        r_, v_ = np.asarray(r), np.asarray(v)
        np.add.at(dense, r_[r_ < V], v_[r_ < V])
    rows_st = jnp.concatenate([r for r, _ in per_replica])
    vals_st = jnp.concatenate([v for _, v in per_replica])
    ref = init - 0.1 * dense
    t = VocabShardedTable(V, D, mesh, axis='tp', init=init)
    t.sgd_push(rows_st, vals_st, 0.1, dp_axis='dp', comm_dtype='f32')
    assert np.allclose(t.full_table(), ref, atol=1e-6)
    t8 = VocabShardedTable(V, D, mesh, axis='tp', init=init)
    t8.sgd_push(rows_st, vals_st, 0.1, dp_axis='dp', comm_dtype='int8')
    err = np.abs(t8.full_table() - ref).max()
    bound = 0.1 * 2 * np.abs(vals_st).max() / 127.0 + 1e-6
    assert 0 < err <= bound


def test_sharded_table_strict_errors(mesh8):
    from paddle_tpu.partition.sparse import VocabShardedTable
    with pytest.raises(ValueError, match='divisible'):
        VocabShardedTable(63, 4, mesh8({'tp': 4}), axis='tp')
    with pytest.raises(ValueError, match='no axis'):
        VocabShardedTable(64, 4, mesh8({'tp': 4}), axis='fsdp')
