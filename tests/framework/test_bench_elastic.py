"""tier-1 guard for the elastic bench: tools/bench_elastic.py --smoke must
run end-to-end on CPU and hold the subsystem's hard guarantees — the
autoscaler ramp completes every Poisson arrival with the reference bytes
(zero drops through scale-up AND drain-backed scale-down), the replica
count follows the load within [min, max], every decision carries its
trigger, and the goodput resize bucket stays separate from crash loss.
Timings (time-to-routable, drain seconds) are reported but not asserted so
a loaded CI box cannot flake them."""
import json
import os
import subprocess
import sys

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), '..', '..'))


def test_bench_elastic_smoke_runs_on_cpu():
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    r = subprocess.run(
        [sys.executable, os.path.join('tools', 'bench_elastic.py'),
         '--smoke'],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stderr[-3000:]
    lines = [json.loads(ln) for ln in r.stdout.splitlines() if ln.strip()]
    benches = {d['bench']: d for d in lines if 'bench' in d}
    assert {'elastic_autoscale_ramp',
            'elastic_resize_accounting'} <= set(benches)

    ramp = benches['elastic_autoscale_ramp']
    assert ramp['dropped'] == 0 and not ramp['errors'], ramp
    assert ramp['completed'] == ramp['requests']
    assert ramp['bitwise_equal'] is True
    # the tier followed the load: grew under pressure, within the cap,
    # and drained back down when it fell off
    assert 1 < ramp['max_replicas_seen'] <= ramp['max_replicas_cap']
    assert ramp['scaled_up'] and ramp['scaled_down'], ramp
    assert ramp['final_replicas'] == 1, ramp
    assert all(d['trigger'] for d in ramp['decisions'])
    assert ramp['time_to_routable_s']['count'] >= 1

    acct = benches['elastic_resize_accounting']
    assert acct['buckets_separate'] is True, acct
    assert acct['crash']['lost_steps'] == acct['predicted_lost_steps']
    assert acct['resize']['lost_steps'] == 0
    assert acct['resize']['resizes'] == 1
