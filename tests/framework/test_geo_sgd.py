"""Geo-SGD delayed delta-sum sync (VERDICT r4 item 7): replicas truly
diverge between pushes and the base advances by the SUM of deltas at each
k-step boundary (ref: transpiler/geo_sgd_transpiler.py semantics)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as fluid
from paddle_tpu.parallel.mesh import make_mesh
from paddle_tpu.parallel.geo_sgd import GeoSGDStep

N = 4
K = 3


def _mesh():
    devs = jax.devices()[:N]
    if len(devs) < N:
        pytest.skip(f'needs {N} devices')
    return make_mesh({'dp': N}, devs)


def _loss(params, batch):
    x, y = batch[..., :-1], batch[..., -1:]
    return jnp.mean((x @ params['w'] - y) ** 2)


def _make_step(mesh, k=K, lr=0.05):
    w0 = np.zeros((3, 1), np.float32)
    return GeoSGDStep(_loss, {'w': w0}, mesh, need_push_nums=k, lr=lr,
                      axis='dp')


def _batch(rng, w_true):
    x = rng.randn(N * 4, 3).astype(np.float32)
    return np.concatenate([x, x @ w_true], -1)


def test_replicas_diverge_then_sync_every_k_steps():
    mesh = _mesh()
    step = _make_step(mesh)
    rng = np.random.RandomState(0)
    w_true = rng.randn(3, 1).astype(np.float32)
    for t in range(2 * K):
        step(_batch(rng, w_true))
        boundary = (t % K) == (K - 1)
        reps = np.asarray(step.replica_params()['w'])
        spread = np.abs(reps - reps[:1]).max()
        if boundary:
            assert spread < 1e-6, f"step {t}: not synced at boundary"
        else:
            assert spread > 1e-6, f"step {t}: no divergence between pushes"


def test_base_moves_by_sum_of_deltas():
    mesh = _mesh()
    step = _make_step(mesh)
    rng = np.random.RandomState(1)
    w_true = rng.randn(3, 1).astype(np.float32)
    base0 = np.asarray(step.base_params()['w']).copy()
    batches = [_batch(rng, w_true) for _ in range(K)]
    # track per-replica locals just before the push
    for t, b in enumerate(batches):
        if t == K - 1:
            pre_push = np.asarray(step.replica_params()['w']).copy()
            last_batch = b
        step(b)
    # manually advance the pre-push replicas one more local SGD step each,
    # then sum their deltas onto the base
    shards = np.split(last_batch, N, axis=0)
    expect_deltas = np.zeros_like(base0)
    for r in range(N):
        w = jnp.asarray(pre_push[r])
        g = jax.grad(lambda w: _loss({'w': w}, jnp.asarray(shards[r])))(w)
        w_after = np.asarray(w - 0.05 * g)
        expect_deltas += (w_after - base0)
    want_base = base0 + expect_deltas
    got_base = np.asarray(step.base_params()['w'])
    np.testing.assert_allclose(got_base, want_base, rtol=1e-4, atol=1e-5)
    # all replicas reset to the new base
    reps = np.asarray(step.replica_params()['w'])
    np.testing.assert_allclose(reps, np.broadcast_to(want_base, reps.shape),
                               rtol=1e-4, atol=1e-5)


def test_geo_sgd_converges():
    mesh = _mesh()
    step = _make_step(mesh, k=2, lr=0.1)
    rng = np.random.RandomState(2)
    w_true = rng.randn(3, 1).astype(np.float32)
    losses = [float(step(_batch(rng, w_true))) for _ in range(40)]
    assert losses[-1] < 0.05 * losses[0], (losses[0], losses[-1])


def test_ps_mode_warns_once():
    import warnings
    import paddle_tpu.transpiler as tp
    tp._ps_warned = False
    t = tp.GeoSgdTranspiler()
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter('always')
        t.transpile(0, program=fluid.Program(), trainers=2)
        t2 = tp.DistributeTranspiler()
        t2.transpile(0, program=fluid.Program(), trainers=2)
    msgs = [str(x.message) for x in w if 'SYNCHRONOUS collective' in
            str(x.message)]
    assert len(msgs) == 1, msgs  # once per process, not per call


def test_geo_transpiler_builds_executable_step():
    mesh = _mesh()
    import paddle_tpu.transpiler as tp
    t = tp.GeoSgdTranspiler()
    t.config.geo_sgd_need_push_nums = 2
    step = t.build_geo_step(_loss, {'w': np.zeros((3, 1), np.float32)},
                            mesh, lr=0.1)
    rng = np.random.RandomState(3)
    w_true = rng.randn(3, 1).astype(np.float32)
    l0 = float(step(_batch(rng, w_true)))
    for _ in range(19):
        l = float(step(_batch(rng, w_true)))
    assert l < l0
