"""tier-1 guard for the async-pipeline bench: tools/bench_pipeline.py must
run end-to-end under JAX_PLATFORMS=cpu at smoke sizes and demonstrate the
PERF.md §12 acceptance margins — async (K=2) ≥ 1.3× sync steady-state
steps/s with bitwise-identical fetched losses, and the staged-feed path
passing every DataLoader byte through without a second device_put."""
import json
import os
import subprocess
import sys

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), '..', '..'))

PIPE_FIELDS = {'steps', 'k', 'io_ms', 'compute_ms', 'sync_steps_per_s',
               'async_steps_per_s', 'speedup', 'theoretical_ceiling',
               'bitwise_identical'}


def test_bench_pipeline_smoke_runs_on_cpu():
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    env.pop('PADDLE_TPU_ASYNC', None)
    r = subprocess.run(
        [sys.executable, os.path.join('tools', 'bench_pipeline.py'),
         '--smoke'],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stderr[-3000:]
    lines = [json.loads(ln) for ln in r.stdout.splitlines() if ln.strip()]
    benches = {d['bench']: d for d in lines if 'bench' in d}
    assert {'async_pipeline', 'staged_feed_passthrough'} <= set(benches)

    ap = benches['async_pipeline']
    assert PIPE_FIELDS <= set(ap), ap
    # correctness is non-negotiable: the pipeline reorders HOST work only
    assert ap['bitwise_identical'] is True, ap
    # acceptance: ≥1.3× steady-state steps/s for async (K=2) over sync
    # with a host-bound reader + compute-bound step (the reader latency is
    # sized 1:1 to measured compute, so the theoretical ceiling is 2×)
    assert ap['speedup'] >= 1.3, ap
    assert ap['sync_steps_per_s'] > 0 and ap['async_steps_per_s'] > 0

    sf = benches['staged_feed_passthrough']
    assert sf['zero_copy'] is True, sf
    assert sf['passthrough_bytes'] == sf['staged_bytes'] > 0, sf
