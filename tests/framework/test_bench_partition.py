"""tools/bench_partition.py smoke in tier-1: spec resolution is
milliseconds-per-Program (zero tracing), the partitioner's specs agree
with the retired per-module plumbing, and the dp×fsdp / dp×tp
SpmdTrainStep compositions hold parity with quantized-collective sync
counters asserted."""
import os
import sys

import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(__file__), '..', '..', 'tools'))


@pytest.fixture(autouse=True)
def _fresh_partitioner():
    from paddle_tpu import partition
    partition.reset_partitioner()
    yield
    partition.reset_partitioner()


def test_bench_partition_smoke():
    from bench_partition import measure_all
    r = measure_all(smoke=True)
    res = r['partition_spec_resolution']
    assert res['vars_resolved'] > 0
    # spec resolution must stay build-time noise: a whole Program in
    # well under a second even at smoke sizes on a loaded CI host
    assert res['resolve_s'] < 1.0, res
    assert r['partition_parity']['ok']
    assert r['partition_parity']['assertions'] >= 15
    comp = r['partition_composition']
    assert comp['ok']
    assert comp['dp_fsdp_max_rel_err'] < 1e-3, comp
    assert comp['dp_tp_max_rel_err'] < 1e-3, comp
    # bucketing: sync calls per step stay below one-per-param-per-axis
    assert comp['dp_fsdp_sync_calls_per_step'] <= 6
