"""Inference stack (SURVEY 2.9, VERDICT r1 #5/#10): save/load_inference_model
round-trip, Predictor fp32/bf16/int8, StableHLO export.

ref: python/paddle/fluid/io.py save/load_inference_model +
paddle/fluid/inference AnalysisPredictor + slim int8 deploy flow.
"""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.inference import (Config, Predictor, create_paddle_predictor,
                                  export_stablehlo, export_program_stablehlo)


@pytest.fixture
def saved_model(tmp_path):
    """Train-ish tiny model, save as inference model, return (dir, ref_out,
    X)."""
    main, start = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, start):
        x = layers.data('x', shape=[8], dtype='float32')
        h = layers.fc(x, 16, act='relu',
                      param_attr=fluid.ParamAttr(name='inf_w1'))
        out = layers.fc(h, 4, act='softmax',
                        param_attr=fluid.ParamAttr(name='inf_w2'))
    exe = fluid.Executor()
    rng = np.random.RandomState(0)
    X = rng.randn(8, 8).astype('float32')
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(start)
        ref, = exe.run(main, feed={'x': X}, fetch_list=[out])
        fluid.io.save_inference_model(str(tmp_path / 'model'), ['x'], [out],
                                      exe, main)
    return str(tmp_path / 'model'), ref, X


def test_save_load_predictor_roundtrip(saved_model):
    model_dir, ref, X = saved_model
    pred = Predictor(model_dir)
    assert pred.get_input_names() == ['x']
    out, = pred.run([X])
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)
    # dict feed form
    out2, = pred.run({'x': X})
    np.testing.assert_allclose(out2, ref, rtol=1e-5)


def test_predictor_bf16(saved_model):
    model_dir, ref, X = saved_model
    pred = create_paddle_predictor(Config(model_dir).enable_bf16())
    out, = pred.run([X])
    # bf16 ~3 decimal digits; softmax output stays close
    np.testing.assert_allclose(out, ref, rtol=0.1, atol=0.02)


def test_predictor_int8_accuracy_drop_small(saved_model):
    model_dir, ref, X = saved_model
    pred = create_paddle_predictor(Config(model_dir).enable_int8())
    assert 'inf_w1' in pred.quantized_params     # weights really quantized
    assert 'inf_w2' in pred.quantized_params
    out, = pred.run([X])
    # int8 per-channel weight quant: small but non-zero degradation
    err = np.max(np.abs(out - ref))
    assert err < 0.05, f"int8 accuracy drop too large: {err}"
    assert not np.allclose(out, ref, rtol=0, atol=0), \
        "outputs bit-identical — quantization did not take effect"
    # argmax (top-1 class) preserved on every row
    np.testing.assert_array_equal(np.argmax(out, 1), np.argmax(ref, 1))


def test_predictor_int8_with_slim_scales(saved_model):
    """Scales from slim-style calibration (abs-max per out-channel) are
    consumed when provided explicitly."""
    model_dir, ref, X = saved_model
    base = Predictor(model_dir)
    with fluid.scope_guard(base._scope):
        w1 = np.asarray(base._scope.find('inf_w1'))
    scales = {'inf_w1': np.max(np.abs(w1), axis=1)}
    pred = Predictor(Config(model_dir).enable_int8(quant_scales=scales))
    np.testing.assert_allclose(pred.quantized_params['inf_w1'],
                               np.maximum(scales['inf_w1'], 1e-8), rtol=1e-6)
    out, = pred.run([X])
    assert np.max(np.abs(out - ref)) < 0.05


def test_stablehlo_export_program(saved_model, tmp_path):
    model_dir, ref, X = saved_model
    pred = Predictor(model_dir)
    path = str(tmp_path / 'model.stablehlo')
    text = export_program_stablehlo(pred.program, {'x': (8, 8)},
                                    pred.fetch_vars, path=path,
                                    scope=pred._scope)
    assert 'stablehlo' in text or 'func.func' in text
    assert 'dot' in text or 'dot_general' in text   # the matmuls are there
    import os
    assert os.path.exists(path)


def test_stablehlo_export_fn():
    import jax.numpy as jnp

    def f(a, b):
        return jnp.tanh(a @ b)

    text = export_stablehlo(f, (np.ones((2, 3), np.float32),
                                np.ones((3, 4), np.float32)))
    assert 'func.func' in text
