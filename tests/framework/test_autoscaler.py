"""Serving-tier autoscaler (ISSUE 19): the control loop over REAL
in-process replicas — ramp up under load (queue/TTFT triggers, capped at
max), cold-replica warmup gating on scale-up (never routes cold, fast
admission poll), drain-then-retire on scale-down (zero drops), hysteresis
bounds, and the decision journal / autoscale_* metrics."""
import threading
import time

import pytest

from paddle_tpu.dygraph import guard
from paddle_tpu.elastic.autoscaler import AutoscaleConfig, Autoscaler
from paddle_tpu.elastic.launcher import CallableReplicaLauncher
from paddle_tpu.models.causal_lm import greedy_generate
from paddle_tpu.serving import Router, ServingServer
from paddle_tpu.serving.tier import knobs
from paddle_tpu.serving.tier.replica import build_replica_stack, build_tiny_lm


@pytest.fixture(scope='module')
def lm():
    with guard():
        yield build_tiny_lm()


class _InProcReplica:
    def __init__(self, lm, model_lock, replica_id, warm=True):
        self.engine, self.scheduler, _ = build_replica_stack(
            model=lm, model_lock=model_lock, replica_id=replica_id)
        if warm:
            self.engine.warmup()
        self.server = ServingServer(None, port=0,
                                    generator=self.scheduler).start()
        self.url = f'http://127.0.0.1:{self.server.port}'

    def shutdown(self, drain=True):
        self.scheduler.close(drain=drain, timeout=10)
        self.server.shutdown(drain=drain)


def _counter(name):
    from paddle_tpu.observability import registry
    d = registry.to_dict().get(name)
    if not d or not d['samples']:
        return 0.0
    return sum(s['value'] for s in d['samples'])


def _wait_until(pred, timeout=30.0, poll=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(poll)
    return False


# -- config knobs ----------------------------------------------------------

def test_autoscale_config_strict_parse(monkeypatch):
    monkeypatch.setenv(knobs.ENV_AUTOSCALE_MIN, 'two')
    with pytest.raises(ValueError, match=knobs.ENV_AUTOSCALE_MIN):
        AutoscaleConfig.from_env()
    monkeypatch.setenv(knobs.ENV_AUTOSCALE_MIN, '0')
    with pytest.raises(ValueError, match='>= 1'):
        AutoscaleConfig.from_env()
    monkeypatch.setenv(knobs.ENV_AUTOSCALE_MIN, '5')
    monkeypatch.setenv(knobs.ENV_AUTOSCALE_MAX, '2')
    with pytest.raises(ValueError, match=knobs.ENV_AUTOSCALE_MAX):
        AutoscaleConfig.from_env()
    monkeypatch.setenv(knobs.ENV_AUTOSCALE_MAX, '8')
    monkeypatch.setenv(knobs.ENV_AUTOSCALE_UP_QUEUE, '6.5')
    cfg = AutoscaleConfig.from_env()
    assert (cfg.min_replicas, cfg.max_replicas, cfg.up_queue) == (5, 8, 6.5)
    monkeypatch.delenv(knobs.ENV_AUTOSCALE, raising=False)
    assert AutoscaleConfig.enabled_from_env() is False
    monkeypatch.setenv(knobs.ENV_AUTOSCALE, '1')
    assert AutoscaleConfig.enabled_from_env() is True
    monkeypatch.setenv(knobs.ENV_AUTOSCALE, 'maybe')
    with pytest.raises(ValueError, match=knobs.ENV_AUTOSCALE):
        AutoscaleConfig.enabled_from_env()


# -- router elastic membership ---------------------------------------------

def test_add_replica_dedup_and_remove_unknown():
    router = Router(['http://127.0.0.1:1'], health_poll_s=60, start=False)
    try:
        assert len(router.replicas) == 1
        rep = router.add_replica('http://127.0.0.1:1/', fast_poll=False)
        assert rep is router.replicas[0]          # dedup, no second entry
        assert len(router.replicas) == 1
        router.add_replica('http://127.0.0.1:2', fast_poll=False)
        assert len(router.replicas) == 2
        router.remove_replica('http://127.0.0.1:2/')
        assert len(router.replicas) == 1
        with pytest.raises(KeyError):
            router.remove_replica('http://127.0.0.1:2')
    finally:
        router.close()


# -- the ramp drill --------------------------------------------------------

def test_autoscaler_ramp_up_and_down_zero_drops(lm):
    """Load ramp against a 1-replica tier: the autoscaler grows to max on
    queue/TTFT pressure (each new replica admitted only once warm), then
    drains back to min when sustained-low — with every request across the
    whole ramp completing with the reference bytes."""
    lock = threading.RLock()
    replicas = {}                  # url -> _InProcReplica
    n_launched = [0]

    def launch():
        n_launched[0] += 1
        rep = _InProcReplica(lm, lock, f'auto-{n_launched[0]}', warm=False)
        replicas[rep.url] = rep
        return rep.url

    def retire(url):
        replicas.pop(url).shutdown()

    seed = _InProcReplica(lm, lock, 'auto-0', warm=True)
    replicas[seed.url] = seed
    launcher = CallableReplicaLauncher(launch, retire)
    router = Router([seed.url], health_poll_s=60, start=False)
    cfg = AutoscaleConfig(min_replicas=1, max_replicas=3, cooldown_s=5.0,
                          up_queue=2.0, up_ttft_s=1.0, down_occupancy=0.25,
                          down_delay_s=10.0)
    scaler = Autoscaler(router, launcher, cfg, start=False)

    prompt = [5, 9, 2, 44]
    ref = greedy_generate(lm, prompt, 4, pad_len=seed.engine.padded_context)
    results, errors = [], []

    def one_request():
        try:
            results.append(router.generate(prompt, max_new_tokens=4))
        except Exception as e:   # noqa: BLE001 — the drill counts drops
            errors.append(e)

    def stuff(**series):
        # scripted decision inputs (the windowed series are process-wide
        # in-proc, so per-replica signals are injected, not scraped)
        for r in router.replicas:
            if r.routable():
                r.series = {k: dict(v) for k, v in series.items()}

    try:
        # ---- ramp up: queue pressure → up #1, capped cold gate ----------
        router.poll_once()
        stuff(queue_depth={'mean': 8.0})
        d1 = scaler.tick(now=100.0)
        assert d1 and (d1['action'], d1['trigger']) == ('up', 'queue_depth')
        assert len(router.replicas) == 2 and len(launcher.launched) == 1
        new_url = launcher.launched[0]
        cold = router._replica_by_url(new_url)
        router.poll_once()
        # the warmup gate: launched cold, polled, still NOT routable
        assert cold.healthy and not cold.warmed and not cold.routable()
        # traffic while one replica is cold lands only on warm replicas
        threads = [threading.Thread(target=one_request) for _ in range(4)]
        [t.start() for t in threads]
        [t.join(30) for t in threads]
        assert not errors, errors
        assert all(r['replica'] == seed.url for r in results[-4:])

        # warmup completes → the FAST admission poll flips it routable in
        # well under the 60s regular poll period (satellite: short initial
        # backoff, time-to-routable not quantized to the poll interval)
        replicas[new_url].engine.warmup()
        t_warm = time.monotonic()
        assert _wait_until(cold.routable, timeout=20), cold.url
        assert time.monotonic() - t_warm < 10.0

        # ---- up #2 on TTFT SLO pressure, then the max_replicas cap ------
        stuff(queue_depth={'mean': 0.5}, ttft={'p99': 3.0})
        d2 = scaler.tick(now=106.0)
        assert d2 and (d2['action'], d2['trigger']) == ('up', 'ttft_p99')
        assert len(router.replicas) == 3
        third = launcher.launched[1]
        replicas[third].engine.warmup()
        assert _wait_until(router._replica_by_url(third).routable,
                           timeout=20)
        stuff(queue_depth={'mean': 9.0}, ttft={'p99': 3.0})
        assert scaler.tick(now=112.0) is None          # at max: no decision
        assert len(router.replicas) == 3 == cfg.max_replicas

        # burst across the full tier — every request completes, bitwise
        threads = [threading.Thread(target=one_request) for _ in range(8)]
        [t.start() for t in threads]
        [t.join(60) for t in threads]
        assert not errors, errors
        assert all(r['tokens'] == ref for r in results), results

        # ---- ramp down: sustained low → drain → retire, twice -----------
        router.poll_once()
        stuff(queue_depth={'mean': 0.0}, occupancy={'mean': 0.0})
        assert scaler.tick(now=200.0) is None          # low_since arming
        d3 = scaler.tick(now=211.0)                    # sustained >= 10s
        assert d3 and (d3['action'], d3['trigger']) == ('down', 'occupancy')
        victim1 = d3['url']
        assert router._replica_by_url(victim1).draining
        assert scaler.draining() == [victim1]
        router.poll_once()                             # observe empty queue
        stuff(queue_depth={'mean': 0.0}, occupancy={'mean': 0.0})
        scaler.tick(now=212.0)                         # drained → retired
        assert launcher.retired == [victim1]
        assert len(router.replicas) == 2
        stuff(queue_depth={'mean': 0.0}, occupancy={'mean': 0.0})
        d4 = scaler.tick(now=223.0)
        assert d4 and d4['action'] == 'down'
        router.poll_once()
        scaler.tick(now=224.0)
        assert len(router.replicas) == 1 == cfg.min_replicas
        assert len(launcher.retired) == 2
        # floor: no further scale-down below min_replicas
        stuff(queue_depth={'mean': 0.0}, occupancy={'mean': 0.0})
        assert scaler.tick(now=300.0) is None

        # a request through the shrunk tier still completes — zero drops
        # across the whole ramp, scale-down included
        one_request()
        assert not errors, errors
        assert results[-1]['tokens'] == ref

        # ---- the journal + metrics: every decision recorded, with its
        # trigger
        acts = [(d['action'], d['trigger']) for d in scaler.decisions]
        assert acts == [('up', 'queue_depth'), ('up', 'ttft_p99'),
                        ('down', 'occupancy'), ('down', 'occupancy')]
        assert all('signals' in d and 'unix_time' in d
                   for d in scaler.decisions)
        assert _counter('autoscale_decisions') >= 4

        def hist_count(name):
            from paddle_tpu.observability import registry
            d = registry.to_dict().get(name)
            return sum(s.get('count', 0) for s in d['samples']) if d else 0

        assert hist_count('autoscale_time_to_routable_seconds') >= 2
        assert hist_count('autoscale_drain_seconds') >= 2
    finally:
        scaler.close()
        router.close()
        for rep in list(replicas.values()):
            try:
                rep.shutdown()
            except Exception:
                pass


def test_autoscaler_min_replicas_floor_spawns():
    """Below min_replicas the scaler launches unconditionally (cold tier
    bring-up), trigger recorded as min_replicas."""
    calls = []
    launcher = CallableReplicaLauncher(
        lambda: calls.append(1) or f'http://127.0.0.1:{len(calls)}',
        lambda url: None)
    router = Router(['http://127.0.0.1:1'], health_poll_s=60, start=False)
    router.remove_replica('http://127.0.0.1:1')
    cfg = AutoscaleConfig(min_replicas=2, max_replicas=3, cooldown_s=0.0)
    scaler = Autoscaler(router, launcher, cfg, start=False)
    try:
        d = scaler.tick(now=1.0)
        assert d and d['trigger'] == 'min_replicas'
        d = scaler.tick(now=2.0)
        assert d and d['trigger'] == 'min_replicas'
        assert len(router.replicas) == 2
        assert scaler.tick(now=3.0) is None       # floor satisfied
    finally:
        scaler.close()
        router.close()
