"""Serving-tier router (paddle_tpu/serving/tier/router.py) over in-process
replicas: strict knob parsing, least-loaded dispatch, routed bitwise
parity, breaker-aware draining + half-open probe re-admission, cold-replica
warmup gating, rolling restarts behind drain, mid-stream failover
semantics, GenerationStream result metadata, and the router HTTP front."""
import json
import threading
import time
import urllib.request

import pytest

from paddle_tpu.dygraph import guard
from paddle_tpu.models.causal_lm import greedy_generate
from paddle_tpu.serving import (NoReplicaAvailable, Router, RouterServer,
                                ServingServer)
from paddle_tpu.serving.tier import knobs
from paddle_tpu.serving.tier.replica import build_replica_stack, build_tiny_lm


@pytest.fixture(scope='module')
def lm():
    with guard():
        yield build_tiny_lm()


class _InProcReplica:
    """One in-process replica stack + HTTP listener (the real subprocess
    drill lives in test_router_failover.py)."""

    def __init__(self, lm, model_lock, replica_id, warm=True, **kw):
        self.engine, self.scheduler, _ = build_replica_stack(
            model=lm, model_lock=model_lock, replica_id=replica_id, **kw)
        if warm:
            self.engine.warmup()
        self.server = ServingServer(None, port=0,
                                    generator=self.scheduler).start()
        self.url = f'http://127.0.0.1:{self.server.port}'

    def shutdown(self, drain=True):
        self.scheduler.close(drain=drain, timeout=10)
        self.server.shutdown(drain=drain)


@pytest.fixture()
def pair(lm):
    lock = threading.RLock()
    reps = [_InProcReplica(lm, lock, f'rep-{i}') for i in range(2)]
    yield reps
    for r in reps:
        try:
            r.shutdown()
        except Exception:
            pass


def _counter(name):
    from paddle_tpu.observability import registry
    d = registry.to_dict().get(name)
    if not d or not d['samples']:
        return 0.0
    return sum(s['value'] for s in d['samples'])


# -- strict knob parse -----------------------------------------------------

def test_router_knob_strict_parse(monkeypatch):
    monkeypatch.setenv('PADDLE_TPU_ROUTER_PORT', 'auto')
    with pytest.raises(ValueError, match='PADDLE_TPU_ROUTER_PORT'):
        knobs.parse_int_env(knobs.ENV_ROUTER_PORT, 8180, minimum=0,
                            maximum=65535)
    monkeypatch.setenv('PADDLE_TPU_ROUTER_PORT', '99999')
    with pytest.raises(ValueError, match='<= 65535'):
        knobs.parse_int_env(knobs.ENV_ROUTER_PORT, 8180, minimum=0,
                            maximum=65535)
    monkeypatch.setenv('PADDLE_TPU_ROUTER_HEALTH_POLL_S', 'fast')
    with pytest.raises(ValueError, match='PADDLE_TPU_ROUTER_HEALTH_POLL_S'):
        knobs.parse_float_env(knobs.ENV_ROUTER_HEALTH_POLL_S, 1.0)
    monkeypatch.setenv('PADDLE_TPU_ROUTER_HEALTH_POLL_S', '0')
    with pytest.raises(ValueError, match='> 0'):
        knobs.parse_float_env(knobs.ENV_ROUTER_HEALTH_POLL_S, 1.0)
    monkeypatch.setenv('PADDLE_TPU_ROUTER_REPLICAS', 'localhost')
    with pytest.raises(ValueError, match='PADDLE_TPU_ROUTER_REPLICAS'):
        knobs.parse_replicas_env()
    monkeypatch.setenv('PADDLE_TPU_ROUTER_REPLICAS',
                       'http://a:1,b:2, http://c:3/')
    assert knobs.parse_replicas_env() == \
        ['http://a:1', 'http://b:2', 'http://c:3']


# -- routing ---------------------------------------------------------------

def test_routed_parity_and_result_metadata(lm, pair):
    """Any replica answers any request with the reference bytes, and the
    final event carries replica + restart-safe request identity."""
    with Router([r.url for r in pair], health_poll_s=0.2) as router:
        prompt = [5, 9, 2, 44]
        ref = greedy_generate(lm, prompt, 6,
                              pad_len=pair[0].engine.padded_context)
        finals = [router.generate(prompt, max_new_tokens=6)
                  for _ in range(4)]
        for fin in finals:
            assert fin['tokens'] == ref
            assert fin['replica'] in [r.url for r in pair]
            assert fin['replica_id'] in ('rep-0', 'rep-1')
            assert fin['request_id']
            assert fin['retries'] == 0
        assert len({f['request_id'] for f in finals}) == 4   # unique ids


def test_least_loaded_dispatch(lm, pair):
    """With one replica pinned by a long generation, short requests land
    on the idle one."""
    with Router([r.url for r in pair], health_poll_s=10) as router:
        long_s = pair[0].scheduler.submit([3, 5, 7], max_new_tokens=16)
        router.poll_once()            # observe rep-0's busy slot
        fins = [router.generate([9, 2], max_new_tokens=2) for _ in range(3)]
        assert all(f['replica'] == pair[1].url for f in fins)
        long_s.result(120)


def test_cold_replica_not_routed_until_warm(lm):
    """The warmup gate: a cold replica is alive but unroutable; it joins
    the rotation once its ladder + decode step have precompiled."""
    lock = threading.RLock()
    cold = _InProcReplica(lm, lock, 'cold', warm=False)
    try:
        health = json.load(urllib.request.urlopen(cold.url + '/healthz'))
        assert health['status'] == 'ok'
        assert health['warmup'] == {'decode': False, 'done': False}
        with Router([cold.url], health_poll_s=10,
                    connect_timeout=2) as router:
            assert not router.replicas[0].routable()
            with pytest.raises(NoReplicaAvailable):
                router.generate([1, 2], max_new_tokens=2, timeout=0.5)
            cold.engine.warmup()
            router.poll_once()
            assert router.replicas[0].routable()
            assert len(router.generate([1, 2],
                                       max_new_tokens=2)['tokens']) == 2
        health = json.load(urllib.request.urlopen(cold.url + '/healthz'))
        assert health['warmup'] == {'decode': True, 'done': True}
        assert health['replica'] == 'cold'
    finally:
        cold.shutdown()


def test_degraded_replica_drained_then_probe_readmits(lm):
    """Breaker awareness end-to-end: a tripped replica reports degraded and
    is drained; after its cooldown the router routes exactly one probe,
    which closes the breaker and re-admits the replica."""
    lock = threading.RLock()
    rep = _InProcReplica(lm, lock, 'trippy')
    rep.scheduler.breaker.failure_threshold = 2
    rep.scheduler.breaker.reset_after_s = 0.4
    try:
        with Router([rep.url], health_poll_s=10, connect_timeout=2) as router:
            assert router.replicas[0].routable()
            rep.scheduler.breaker.record_failure()
            rep.scheduler.breaker.record_failure()        # trips -> open
            router.poll_once()
            assert not router.replicas[0].routable()      # degraded: drained
            p0 = _counter('router_probes')
            time.sleep(0.5)                               # cooldown elapses
            router.poll_once()
            assert router.replicas[0].half_open
            assert router.replicas[0].routable()          # as the probe
            fin = router.generate([1, 2], max_new_tokens=2)
            assert len(fin['tokens']) == 2
            assert _counter('router_probes') - p0 >= 1
            router.poll_once()
            assert router.replicas[0].healthy             # breaker closed
    finally:
        rep.shutdown()


def test_midstream_failover_kills_only_inflight_stream(lm, pair):
    """An abruptly dying replica errors its in-flight stream; requests
    submitted right after reroute to the survivor with zero drops."""
    with Router([r.url for r in pair], health_poll_s=10) as router:
        gen = router.stream_generate([3, 5, 7], max_new_tokens=16)
        events = gen.events()
        next(events)                              # streaming has begun
        victim = next(r for r in pair if r.url == gen.replica)
        survivor = next(r for r in pair if r.url != gen.replica)
        victim.shutdown(drain=False)              # dies mid-stream
        tail = list(events)
        assert any('error' in e and not e.get('done') for e in tail), tail
        # new requests reroute with zero drops
        ref = greedy_generate(lm, [9, 2], 3,
                              pad_len=pair[0].engine.padded_context)
        fins = [router.generate([9, 2], max_new_tokens=3) for _ in range(4)]
        assert all(f['tokens'] == ref for f in fins)
        assert all(f['replica'] == survivor.url for f in fins)


def test_rolling_restart_behind_drain(lm, pair):
    """Both replicas restart one at a time behind a drain while traffic
    keeps flowing: every request issued during the roll completes."""
    lock = threading.RLock()
    ref_ctx = pair[0].engine.padded_context
    ref = greedy_generate(lm, [5, 9, 2], 3, pad_len=ref_ctx)
    with Router([r.url for r in pair], health_poll_s=0.2) as router:
        results, errors = [], []
        stop = threading.Event()

        def traffic():
            while not stop.is_set():
                try:
                    results.append(
                        router.generate([5, 9, 2], max_new_tokens=3))
                except Exception as e:
                    errors.append(e)
                time.sleep(0.02)

        t = threading.Thread(target=traffic, daemon=True)
        t.start()
        by_url = {r.url: r for r in pair}

        def restart(url):
            rep = by_url.pop(url)
            rep.shutdown()
            fresh = _InProcReplica(lm, lock, rep.server.replica_id + '-r2')
            by_url[fresh.url] = fresh
            return fresh.url

        r0 = _counter('router_rolling_restarts')
        router.rolling_restart(restart, drain_timeout=30, warm_timeout=60,
                               poll_interval=0.05)
        stop.set()
        t.join(30)
        pair[:] = list(by_url.values())           # fixture teardown
        assert _counter('router_rolling_restarts') - r0 == 2
        assert not errors, errors
        assert results and all(f['tokens'] == ref for f in results)
        restarted = {f['replica_id'] for f in results}
        assert any(rid.endswith('-r2') for rid in restarted), restarted


# -- HTTP front end --------------------------------------------------------

def test_router_http_e2e(lm, pair):
    ref = greedy_generate(lm, [5, 9, 2, 44], 6,
                          pad_len=pair[0].engine.padded_context)
    with Router([r.url for r in pair], health_poll_s=0.2) as router:
        with RouterServer(router, port=0).start() as rs:
            url = f'http://127.0.0.1:{rs.port}'
            # streaming NDJSON with routing metadata on the done line
            req = urllib.request.Request(
                url + '/generate',
                data=json.dumps({'prompt': [5, 9, 2, 44],
                                 'max_new_tokens': 6}).encode())
            lines = [json.loads(ln) for ln in
                     urllib.request.urlopen(req).read().splitlines()]
            assert [ln['token'] for ln in lines if 'token' in ln] == ref
            done = lines[-1]
            assert done['done'] and done['replica'] in [r.url for r in pair]
            assert done['retries'] == 0 and done['request_id']
            # non-streaming
            req = urllib.request.Request(
                url + '/generate',
                data=json.dumps({'prompt': [5, 9, 2, 44],
                                 'max_new_tokens': 6,
                                 'stream': False}).encode())
            body = json.load(urllib.request.urlopen(req))
            assert body['tokens'] == ref and body['replica']
            # replica 4xx relayed verbatim (bad prompt -> 400)
            req = urllib.request.Request(
                url + '/generate',
                data=json.dumps({'prompt': ['x']}).encode())
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req)
            assert ei.value.code == 400
            # healthz + metrics
            h = json.load(urllib.request.urlopen(url + '/healthz'))
            assert h['status'] == 'ok' and h['routable'] == 2
            prom = urllib.request.urlopen(url + '/metrics').read().decode()
            assert 'paddle_tpu_router_requests' in prom
            assert 'paddle_tpu_router_replicas_routable' in prom


def test_stream_meta_on_generation_stream(lm):
    """Satellite: GenerationStream exposes replica id + restart-safe
    request id directly (scheduler-level, no HTTP)."""
    eng, sched, _ = build_replica_stack(model=lm, replica_id='meta-rep')
    try:
        s1 = sched.submit([1, 2, 3], max_new_tokens=2)
        s2 = sched.submit([1, 2, 3], max_new_tokens=2)
        s1.result(120), s2.result(120)
        assert s1.meta['replica_id'] == s2.meta['replica_id'] == 'meta-rep'
        assert s1.meta['request_id'] != s2.meta['request_id']
        assert len(s1.request_id) == 16
    finally:
        sched.close()


def test_no_replica_available_is_typed(lm):
    """A router whose only replica is unreachable raises the typed
    NoReplicaAvailable (HTTP 503) after its bounded wait."""
    router = Router(['http://127.0.0.1:9'], health_poll_s=10,
                    connect_timeout=0.5, start=False)
    with pytest.raises(NoReplicaAvailable, match='no routable replica'):
        router.generate([1, 2], max_new_tokens=2, timeout=0.6)
    n = _counter('router_no_replica')
    assert n >= 1
    router.close()
