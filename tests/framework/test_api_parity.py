"""Executable API-parity audit vs the reference tree (SURVEY §2).

Walks the reference modules' public names (__all__, falling back to
top-level defs) and asserts paddle_tpu exposes every one. Runs only when
the read-only reference checkout is present; the curated module list is
the same inventory the SURVEY tracks.
"""
import ast
import os

import pytest

import paddle_tpu as pt

REF_ROOT = '/root/reference/python/paddle'

pytestmark = pytest.mark.skipif(
    not os.path.isdir(REF_ROOT),
    reason='reference checkout not mounted')


def ref_public(path):
    import warnings
    with open(path) as f:
        with warnings.catch_warnings():
            # the reference sources carry pre-PEP-675 escape sequences;
            # their SyntaxWarnings are not ours to fix
            warnings.simplefilter('ignore', SyntaxWarning)
            tree = ast.parse(f.read())
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if getattr(t, 'id', None) == '__all__':
                    try:
                        return set(ast.literal_eval(node.value))
                    except (ValueError, TypeError):
                        pass
    return {n.name for n in tree.body
            if isinstance(n, (ast.FunctionDef, ast.ClassDef))
            and not n.name.startswith('_')}


def ref_path(mod):
    p = os.path.join(REF_ROOT, *mod.split('.')) + '.py'
    if not os.path.exists(p):
        p = os.path.join(REF_ROOT, *mod.split('.'), '__init__.py')
    return p


FLUID_MODULES = [
    'fluid.average', 'fluid.backward', 'fluid.clip', 'fluid.communicator',
    'fluid.compiler', 'fluid.data_feed_desc', 'fluid.data_feeder',
    'fluid.dataset', 'fluid.debugger', 'fluid.default_scope_funcs',
    'fluid.device_worker', 'fluid.distribute_lookup_table',
    'fluid.dygraph_grad_clip', 'fluid.evaluator', 'fluid.executor',
    'fluid.framework', 'fluid.initializer', 'fluid.input',
    'fluid.install_check', 'fluid.io', 'fluid.layers',
    'fluid.lod_tensor', 'fluid.metrics', 'fluid.net_drawer', 'fluid.nets',
    'fluid.op', 'fluid.optimizer', 'fluid.parallel_executor',
    'fluid.param_attr', 'fluid.profiler', 'fluid.regularizer',
    'fluid.trainer_desc', 'fluid.trainer_factory', 'fluid.unique_name',
]

# names whose absence is an accepted, documented design difference
ALLOWED_MISSING = {
    # none currently — keep empty so new gaps fail loudly
}


def _have(mod_name):
    """Names visible for a fluid module: its namesake attr + the package
    root (fluid flattens most submodules into the top level)."""
    short = mod_name.split('.')[-1]
    names = set(dir(pt))
    tgt = getattr(pt, short, None)
    if tgt is not None:
        names |= set(dir(tgt))
    return names


@pytest.mark.parametrize('mod', FLUID_MODULES)
def test_fluid_module_parity(mod):
    names = ref_public(ref_path(mod))
    have = _have(mod)
    missing = sorted(n for n in names
                     if n not in have and n not in ALLOWED_MISSING)
    assert not missing, f'{mod}: missing {missing}'


def test_fluid_layers_full_all():
    """layers has its own dynamically-built __all__ in the reference —
    aggregate the submodules directly."""
    base = os.path.join(REF_ROOT, 'fluid', 'layers')
    names = set()
    for f in os.listdir(base):
        if f.endswith('.py') and f != '__init__.py':
            names |= ref_public(os.path.join(base, f))
    have = set(dir(pt.layers)) | set(dir(pt))
    missing = sorted(n for n in names if n not in have)
    assert not missing, f'fluid.layers aggregate: missing {missing}'


def test_dygraph_parity():
    base = os.path.join(REF_ROOT, 'fluid', 'dygraph')
    names = set()
    for f in os.listdir(base):
        if f.endswith('.py'):
            names |= ref_public(os.path.join(base, f))
    have = set(dir(pt.dygraph)) | set(dir(pt))
    missing = sorted(n for n in names if n not in have)
    assert not missing, f'dygraph: missing {missing}'


def test_contrib_parity():
    mods = ['contrib.decoder.beam_search_decoder',
            'contrib.extend_optimizer.extend_optimizer_with_weight_decay',
            'contrib.layers.nn', 'contrib.layers.metric_op',
            'contrib.layers.rnn_impl', 'contrib.memory_usage_calc',
            'contrib.model_stat', 'contrib.op_frequence',
            'contrib.quantize.quantize_transpiler',
            'contrib.reader.distributed_reader',
            'contrib.utils.hdfs_utils', 'contrib.utils.lookup_table_utils']
    have = set(dir(pt.contrib)) | set(dir(pt))
    for m in mods:
        names = ref_public(ref_path('fluid.' + m))
        missing = sorted(n for n in names
                         if n not in have and n != 'summary')
        # model_stat has no __all__; 'summary' checked explicitly:
        assert hasattr(pt.contrib, 'summary')
        assert not missing, f'{m}: missing {missing}'


def test_fleet_utils_parity():
    from paddle_tpu.incubate.fleet import utils as fu
    import paddle_tpu.incubate.fleet.utils.utils as fuu
    import paddle_tpu.incubate.fleet.utils.fleet_util as fut
    for mod, have in [('fluid.incubate.fleet.utils.fleet_util', dir(fut)),
                      ('fluid.incubate.fleet.utils.fleet_barrier_util',
                       dir(fu.fleet_barrier_util)),
                      ('fluid.incubate.fleet.utils.utils', dir(fuu))]:
        names = ref_public(ref_path(mod))
        missing = sorted(n for n in names if n not in set(have))
        assert not missing, f'{mod}: missing {missing}'
    # FleetUtil methods themselves
    ref_methods = {
        'rank0_print', 'set_zero', 'print_global_auc', 'get_global_auc',
        'load_fleet_model', 'save_fleet_model', 'write_model_donefile',
        'write_xbox_donefile', 'get_last_save_model', 'get_last_save_xbox',
        'get_online_pass_interval', 'get_global_metrics',
        'print_global_metrics', 'save_paddle_inference_model',
        'draw_from_program', 'check_two_programs'}
    from paddle_tpu.incubate.fleet.utils import FleetUtil
    missing = sorted(m for m in ref_methods if not hasattr(FleetUtil, m))
    assert not missing, f'FleetUtil missing {missing}'


def test_log_helper_and_annotations_parity():
    import paddle_tpu.log_helper as lh
    import paddle_tpu.annotations as an
    assert not {n for n in ref_public(ref_path('fluid.log_helper'))
                if not hasattr(lh, n)}
    assert not {n for n in ref_public(ref_path('fluid.annotations'))
                if not hasattr(an, n)}


def test_data_generator_parity():
    from paddle_tpu.incubate import data_generator as dg
    names = ref_public(ref_path('fluid.incubate.data_generator'))
    missing = sorted(n for n in names if not hasattr(dg, n))
    assert not missing, f'data_generator: missing {missing}'


def test_slim_parity():
    """The slim compression suite: distillation / prune / NAS / searcher /
    core / graph public names all exposed by paddle_tpu.contrib.slim."""
    from paddle_tpu.contrib import slim
    mods = ['contrib.slim.core.strategy',
            'contrib.slim.core.compressor',
            'contrib.slim.distillation.distiller',
            'contrib.slim.distillation.distillation_strategy',
            'contrib.slim.prune.pruner',
            'contrib.slim.searcher.controller',
            'contrib.slim.nas.search_space']
    have = set(dir(slim))
    # accepted design differences: the socket controller server / search
    # agent and the MKLDNN strategies have no TPU meaning (documented in
    # slim/nas.py); ConfigFactory covers config.py
    allowed = {'ControllerServer', 'SearchAgent'}
    for m in mods:
        names = ref_public(ref_path('fluid.' + m))
        missing = sorted(n for n in names if n not in have
                         and n not in allowed)
        assert not missing, f'{m}: missing {missing}'
    # prune strategies (module has no __all__ at top in some versions)
    for name in ['UniformPruneStrategy', 'SensitivePruneStrategy',
                 'LightNASStrategy', 'QuantizationStrategy',
                 'ConfigFactory', 'GraphWrapper']:
        assert hasattr(slim, name), name


def test_dataset_zoo_parity():
    base = os.path.join(REF_ROOT, 'dataset')
    for f in os.listdir(base):
        if not f.endswith('.py') or f in ('__init__.py',
                                          'tests', 'common.py'):
            continue
        short = f[:-3]
        sub = getattr(pt.dataset, short, None)
        if sub is None:
            # cifar module naming etc. must exist
            pytest.fail(f'paddle.dataset.{short} missing')
        names = ref_public(os.path.join(base, f))
        # the reference conll05 __all__ contains the typo'd entry
        # 'test, get_dict' — treat comma-joined entries as separate names
        names = {p.strip() for n in names for p in n.split(',')}
        missing = sorted(n for n in names
                         if not hasattr(sub, n) and n not in (
                             'convert', 'fetch'))
        assert not missing, f'dataset.{short}: missing {missing}'


def test_optimizer_class_list():
    names = ref_public(ref_path('fluid.optimizer'))
    missing = sorted(n for n in names if not hasattr(pt.optimizer, n))
    assert not missing, f'optimizer: missing {missing}'
