"""ADVICE r5 leftovers (slim): ConfigFactory must honor the compressor's
LISTED strategy order (callback ordering parity with the reference
config.py), and Context.run_eval_graph must actually subsample the reader
when `sampled_rate` is given instead of silently evaluating everything."""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.contrib.slim.core import ConfigFactory, Context
from paddle_tpu.contrib.slim.graph import GraphWrapper


# ---------------------------------------------------------------------------
# ConfigFactory: compressor.strategies order wins over definition order
# ---------------------------------------------------------------------------
_TWO_STRATEGIES = """
version: 1.0
strategies:
  prune_strategy:
    class: UniformPruneStrategy
    start_epoch: 0
    end_epoch: 1
  quant_strategy:
    class: QuantizationStrategy
    start_epoch: 2
    end_epoch: 3
compressor:
  epoch: 4
  strategies: [quant_strategy, prune_strategy]
"""


def test_config_factory_preserves_listed_strategy_order():
    factory = ConfigFactory(_TWO_STRATEGIES)
    names = [type(s).__name__ for s in factory.strategies]
    # YAML defines prune first; the compressor LISTS quant first — the
    # listed order drives callback ordering, like the reference
    assert names == ['QuantizationStrategy', 'UniformPruneStrategy']


def test_config_factory_definition_order_without_listing():
    spec = _TWO_STRATEGIES.split('compressor:')[0] + 'compressor:\n  epoch: 4\n'
    factory = ConfigFactory(spec)
    names = [type(s).__name__ for s in factory.strategies]
    assert names == ['UniformPruneStrategy', 'QuantizationStrategy']


def test_config_factory_unknown_listed_strategy_raises():
    bad = _TWO_STRATEGIES.replace('[quant_strategy, prune_strategy]',
                                  '[quant_strategy, nonexistent]')
    with pytest.raises(ValueError, match='nonexistent'):
        ConfigFactory(bad)


# ---------------------------------------------------------------------------
# Context.run_eval_graph sampled_rate
# ---------------------------------------------------------------------------
def _eval_context(n_batches):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data(name='x', shape=[1], dtype='float32')
        out = fluid.layers.scale(x, scale=1.0)
    exe = fluid.Executor()
    exe.run(startup)
    graph = GraphWrapper(main, in_nodes={'x': 0}, out_nodes={'val': out.name})
    batch_vals = [float(i) for i in range(n_batches)]
    calls = []

    def reader():
        for v in batch_vals:
            calls.append(v)
            yield {'x': np.asarray([v], np.float32)}

    ctx = Context(eval_graph=graph, eval_reader=reader)
    return ctx, batch_vals, calls


def _expected_subset(vals, rate, cached_id):
    rng = np.random.RandomState(cached_id)
    picked = [v for v in vals if rng.random_sample() < rate]
    return picked or [vals[0]]


def test_run_eval_graph_subsamples_reader():
    ctx, vals, _ = _eval_context(20)
    full = ctx.run_eval_graph()
    assert full['val'] == pytest.approx(np.mean(vals))
    sub = ctx.run_eval_graph(sampled_rate=0.3, cached_id=7)
    assert sub['val'] == pytest.approx(
        np.mean(_expected_subset(vals, 0.3, 7)))
    # a 0.3 sample of 20 distinct values almost surely differs from the
    # full mean; equality here would mean the rate was ignored again
    assert sub['val'] != pytest.approx(full['val'])


def test_run_eval_graph_sampling_deterministic_per_cached_id():
    ctx, vals, _ = _eval_context(16)
    a = ctx.run_eval_graph(sampled_rate=0.5, cached_id=3)
    b = ctx.run_eval_graph(sampled_rate=0.5, cached_id=3)
    assert a['val'] == b['val']
    c = ctx.run_eval_graph(sampled_rate=0.5, cached_id=4)
    assert c['val'] == pytest.approx(
        np.mean(_expected_subset(vals, 0.5, 4)))


def test_run_eval_graph_sampled_rate_never_yields_zero_batches():
    ctx, vals, _ = _eval_context(3)
    # rate so small the rng keeps nothing → fall back to the first batch
    res = ctx.run_eval_graph(sampled_rate=1e-9, cached_id=0)
    assert res['val'] == pytest.approx(vals[0])


def test_run_eval_graph_rejects_bad_sampled_rate():
    ctx, _, _ = _eval_context(2)
    with pytest.raises(ValueError, match='sampled_rate'):
        list(ctx._sampled_batches(1.5, 0))
