"""End-to-end telemetry acceptance (ISSUE 2): a 2-step MNIST training run
with PADDLE_TPU_TELEMETRY=1 must produce (a) valid chrome-trace JSON with
executor-phase and tape-dispatch spans, (b) a metrics dump with compile-cache
hit/miss, donation counts, and DataLoader wait-time populated, and (c) a
tools/telemetry_report.py summary rendered from those artifacts."""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), '..', '..'))

TRAIN_SCRIPT = r"""
import numpy as np
import paddle_tpu as fluid
from paddle_tpu import layers, nets, dygraph
from paddle_tpu import reader as R
from paddle_tpu.datasets import mnist_train

# static 2-step MNIST train fed through the instrumented DataLoader
img = layers.data('img', [1, 28, 28])
label = layers.data('label', [1], dtype='int64')
conv = nets.simple_img_conv_pool(img, 4, 5, 2, 2, act='relu')
pred = layers.fc(conv, size=10, act='softmax')
loss = layers.reduce_mean(layers.cross_entropy(pred, label))
fluid.optimizer.SGDOptimizer(0.1).minimize(loss)
exe = fluid.Executor()
exe.run(fluid.default_startup_program())

train = R.batch(mnist_train(), 8, drop_last=True)

def batches():
    for i, b in enumerate(train()):
        if i >= 2:
            break
        yield {'img': np.stack([s[0].reshape(1, 28, 28)
                                for s in b]).astype('float32'),
               'label': np.stack([[s[1]] for s in b]).astype('int64')}

loader = fluid.DataLoader.from_generator(capacity=4)
loader.set_batch_generator(batches)
steps = 0
for feed in loader:
    l, = exe.run(feed=feed, fetch_list=[loss])
    steps += 1
assert steps == 2, steps

# a short eager segment so tape-dispatch spans/histograms populate too
with dygraph.guard():
    t = dygraph.to_variable(np.ones((4, 4), np.float32))
    for _ in range(3):
        t = dygraph.dispatch_op('scale', {'x': t}, {'scale': 0.5})
print('E2E_TRAIN_OK', float(np.ravel(l)[0]))
# artifacts are dumped by the observability atexit hook
"""


@pytest.fixture(scope='module')
def run_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp('telemetry_run')
    env = dict(os.environ, JAX_PLATFORMS='cpu',
               PADDLE_TPU_TELEMETRY='1',
               PADDLE_TPU_METRICS_DIR=str(d))
    r = subprocess.run([sys.executable, '-c', TRAIN_SCRIPT], cwd=REPO,
                       env=env, capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, (r.stdout + r.stderr)[-3000:]
    assert 'E2E_TRAIN_OK' in r.stdout
    return d


def test_chrome_trace_valid_with_span_tree(run_dir):
    doc = json.loads((run_dir / 'trace.json').read_text())
    events = doc['traceEvents']
    names = [e['name'] for e in events]
    for required in ('executor/run', 'executor/prepare', 'executor/lower',
                     'executor/execute', 'executor/fetch', 'tape/scale'):
        assert required in names, sorted(set(names))
    # ≥1 complete span tree: every phase event nests inside a run event
    runs = [e for e in events if e['name'] == 'executor/run']
    phases = [e for e in events if e['name'].startswith('executor/')
              and e['name'] != 'executor/run' and e['ph'] == 'X']
    assert runs and phases
    nested = [p for p in phases
              if any(r['tid'] == p['tid'] and r['ts'] <= p['ts'] and
                     p['ts'] + p['dur'] <= r['ts'] + r['dur'] + 1e-3
                     for r in runs)]
    assert len(nested) == len(phases), (len(nested), len(phases))


def test_metrics_dump_populated(run_dir):
    md = json.loads((run_dir / 'metrics.json').read_text())['metrics']

    def val(name):
        return sum(s['value'] for s in md[name]['samples'])

    assert val('executor_steps') == 2
    assert val('compile_cache_misses') == 1    # one program+shape compile
    assert val('compile_cache_hits') == 1      # step 2 reuses it
    assert val('executor_donated_buffers') > 0
    assert val('dataloader_batches') == 2
    assert md['dataloader_wait_seconds']['samples'][0]['count'] >= 2
    assert 'dataloader_last_wait_seconds' in md
    assert md['tape_dispatch_seconds']['samples']
    # prometheus exposition written alongside
    prom = (run_dir / 'metrics.prom').read_text()
    assert '# TYPE paddle_tpu_executor_steps counter' in prom
    # structured per-step JSONL got one record per executor step
    recs = [json.loads(ln) for ln in
            (run_dir / 'steps.jsonl').read_text().splitlines()]
    assert sum(1 for r in recs if r.get('kind') == 'executor') == 2


def test_telemetry_report_cli(run_dir):
    r = subprocess.run(
        [sys.executable, os.path.join('tools', 'telemetry_report.py'),
         str(run_dir)],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr[-2000:]
    out = r.stdout
    for section in ('Run summary', 'Slowest eager ops', 'Cache hit rates',
                    'Input pipeline', 'Compile-time breakdown'):
        assert section in out, out
    assert 'executor steps:        2' in out
    assert 'starvation fraction' in out
    assert 'scale' in out                      # eager op made the table


def test_telemetry_report_no_artifacts_exits_2(tmp_path):
    r = subprocess.run(
        [sys.executable, os.path.join('tools', 'telemetry_report.py'),
         str(tmp_path / 'nope')],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert r.returncode == 2
    assert 'no metrics.json' in r.stderr
