"""tier-1 guard for the serving load bench: tools/bench_serving.py --smoke
must run end-to-end under JAX_PLATFORMS=cpu, show the micro-batcher beating
the serial single-request baseline, keep bitwise parity, and produce typed
overload rejections that surface in the Prometheus export. The full-size
acceptance margin (≥5× at batch 16 on CPU) is recorded in PERF.md §11; the
smoke bound here is soft so CI noise cannot flake it."""
import json
import os
import subprocess
import sys

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), '..', '..'))

BATCHER_FIELDS = {'clients', 'requests', 'max_batch_size', 'batch_timeout_ms',
                  'throughput_req_s', 'p50_ms', 'p99_ms', 'batches',
                  'mean_batch_rows', 'mean_padding_waste', 'bitwise_equal',
                  'speedup_vs_serial'}


def test_bench_serving_smoke_runs_on_cpu():
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    r = subprocess.run(
        [sys.executable, os.path.join('tools', 'bench_serving.py'),
         '--smoke'],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stderr[-3000:]
    lines = [json.loads(ln) for ln in r.stdout.splitlines() if ln.strip()]
    benches = {d['bench']: d for d in lines if 'bench' in d}
    assert {'serving_serial_baseline', 'serving_batcher',
            'serving_open_loop', 'serving_overload'} <= set(benches)

    serial = benches['serving_serial_baseline']
    assert serial['throughput_req_s'] > 0 and serial['p99_ms'] > 0

    b = benches['serving_batcher']
    assert BATCHER_FIELDS <= set(b), b
    # hard guarantees: responses bitwise-equal to the serial baseline, and
    # real coalescing happened (well past a single request per device call)
    assert b['bitwise_equal'] is True, b
    assert b['mean_batch_rows'] > 2, b
    assert 0 <= b['mean_padding_waste'] < 1, b
    # soft timing bound (PERF.md §11 records 5.4x at full size; smoke noise
    # still clears 2x comfortably — measured 5.7x)
    assert b['speedup_vs_serial'] > 2.0, b

    ol = benches['serving_open_loop']
    # open-loop Poisson: completion-stamped tail latency, every submitted
    # request accounted for (answered + rejected + failed == offered)
    assert ol['p99_ms'] is not None and ol['p99_ms'] >= ol['p50_ms']
    assert ol['answered'] > 0 and ol['failed'] == 0
    assert ol['answered'] + ol['rejected_overload'] == ol['requests']
    assert ol['achieved_req_s'] > 0

    o = benches['serving_overload']
    # burst > queue_depth: typed rejections, every admitted request answered
    assert o['rejected'] > 0 and o['answered'] > 0, o
    assert o['rejected'] + o['answered'] == o['burst'], o
    assert o['rejections_in_prometheus'] is True, o
