"""dp×pp and pp×tp composition through SpmdTrainStep (ISSUE 20): stage
stacks sharded over the 'pp' mesh axis via the ('stage','pp') rule, the
pipeline schedule running INSIDE the same shard_map as the dp gradient
sync and the Megatron tp tiling — one dist-strategy surface, no second
lowering path.

Every test compares the sharded trajectory against a single-device SGD
reference: losses AND materialized params after several steps."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu import partition
from paddle_tpu.parallel.tensor_parallel import mp_allreduce, mp_copy
from paddle_tpu.partition.spmd_step import SpmdTrainStep

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason='needs 8 (virtual) devices')


@pytest.fixture(autouse=True)
def _fresh_partitioner():
    partition.reset_partitioner()
    yield
    partition.reset_partitioner()


def _fixture():
    rng = np.random.RandomState(0)
    params = {'stages.w': (rng.randn(2, 16, 16) * 0.1).astype('float32'),
              'head.w': (rng.randn(16, 1) * 0.1).astype('float32')}
    X = rng.randn(16, 16).astype('float32')
    return params, X, X[:, :1].copy()


def _reference(params, X, Y, loss_fn, steps=5, lr=0.1):
    ps = {k: jnp.asarray(v) for k, v in params.items()}
    out = []
    for _ in range(steps):
        l, g = jax.value_and_grad(loss_fn)(ps, (jnp.asarray(X),
                                                jnp.asarray(Y)))
        ps = {k: v - lr * g[k] for k, v in ps.items()}
        out.append(float(l))
    return out, ps


def _tail_fn(pf, y, b):
    return jnp.mean(((y @ pf['head.w']) - b[1]) ** 2)


def _ref_dense(ps, b):
    x, yl = b
    h = jnp.tanh(x @ ps['stages.w'][0])
    h = jnp.tanh(h @ ps['stages.w'][1])
    return jnp.mean(((h @ ps['head.w']) - yl) ** 2)


@pytest.mark.parametrize('schedule', ['gpipe', '1f1b'])
def test_spmd_step_dp_pp_composition(schedule):
    """2-way data parallel × 2-stage pipeline: stage grads funnel through
    the pipeline backward, then the dp sync — trajectory matches the
    single-device reference."""
    params, X, Y = _fixture()
    ref_losses, ref_ps = _reference(params, X, Y, _ref_dense)
    p = partition.configure(mesh_shape={'dp': 2, 'pp': 2})
    step = SpmdTrainStep(
        None, params, partitioner=p, lr=0.1,
        pipeline=dict(stage_fn=lambda sp, x: jnp.tanh(x @ sp['stages.w']),
                      tail_fn=_tail_fn, stage_params=['stages.w'],
                      x_fn=lambda b: b[0], num_microbatches=4,
                      schedule=schedule))
    # stage stacks are device-varying tiles (one stage per pp shard)
    assert step.param_kind('stages.w') == 'tp'
    assert step.param_kind('head.w') == 'replicated'
    losses = [float(step((X, Y))) for _ in range(5)]
    np.testing.assert_allclose(losses, ref_losses, rtol=2e-5, atol=1e-6)
    got = step.materialize()
    for n in params:
        np.testing.assert_allclose(got[n], np.asarray(ref_ps[n]),
                                   rtol=2e-4, atol=5e-5, err_msg=n)


def test_spmd_step_pp_tp_composition():
    """2-stage pipeline × 4-way Megatron tensor parallelism INSIDE each
    stage (column ffn1 / row ffn2, 'f' and 'g' collectives): stage
    param tiles stay sharded over BOTH pp and tp, trajectory matches."""
    rng = np.random.RandomState(0)
    _, X, Y = _fixture()
    params = {
        'stages.ffn1.w': (rng.randn(2, 16, 32) * 0.1).astype('float32'),
        'stages.ffn2.w': (rng.randn(2, 32, 16) * 0.1).astype('float32'),
        'head.w': (rng.randn(16, 1) * 0.1).astype('float32')}

    def ref_loss(ps, b):
        x, yl = b
        h = x
        for s in range(2):
            h = jnp.tanh(jnp.maximum(h @ ps['stages.ffn1.w'][s], 0.0)
                         @ ps['stages.ffn2.w'][s])
        return jnp.mean(((h @ ps['head.w']) - yl) ** 2)

    def stage_fn(sp, x):
        x = mp_copy(x, 'tp')                            # Megatron 'f'
        h = jnp.maximum(x @ sp['stages.ffn1.w'], 0.0)   # local columns
        return jnp.tanh(mp_allreduce(h @ sp['stages.ffn2.w'], 'tp'))

    ref_losses, ref_ps = _reference(params, X, Y, ref_loss)
    p = partition.configure(mesh_shape={'pp': 2, 'tp': 4})
    step = SpmdTrainStep(
        None, params, partitioner=p, lr=0.1,
        pipeline=dict(stage_fn=stage_fn, tail_fn=_tail_fn,
                      stage_params=['stages.ffn1.w', 'stages.ffn2.w'],
                      x_fn=lambda b: b[0], num_microbatches=2))
    # per-stage Megatron tiling survives the pp stacking: the column
    # weight's local shard is (1 stage, 16, 32/4)
    w = step.sharded_params()['stages.ffn1.w']
    assert w.addressable_shards[0].data.shape == (1, 16, 8)
    losses = [float(step((X, Y))) for _ in range(5)]
    np.testing.assert_allclose(losses, ref_losses, rtol=2e-5, atol=1e-6)
    got = step.materialize()
    for n in params:
        np.testing.assert_allclose(got[n], np.asarray(ref_ps[n]),
                                   rtol=2e-4, atol=5e-5, err_msg=n)


def test_spmd_step_pipeline_requires_stage_axis():
    """A mesh without the 'stage' logical axis cannot host the pipeline
    composition — the error names the rule, not a shape mismatch."""
    params, _, _ = _fixture()
    p = partition.configure(mesh_shape={'dp': 4})
    with pytest.raises(ValueError, match="stage"):
        SpmdTrainStep(
            None, params, partitioner=p, lr=0.1,
            pipeline=dict(stage_fn=lambda sp, x: x, tail_fn=_tail_fn,
                          stage_params=['stages.w'],
                          num_microbatches=2))


def test_spmd_step_pipeline_stage_count_mismatch_raises():
    params, _, _ = _fixture()
    params['stages.w'] = params['stages.w'][:1]       # 1 stage, pp=2
    p = partition.configure(mesh_shape={'pp': 2})
    with pytest.raises(ValueError, match='stage'):
        SpmdTrainStep(
            None, params, partitioner=p, lr=0.1,
            pipeline=dict(stage_fn=lambda sp, x: x, tail_fn=_tail_fn,
                          stage_params=['stages.w'],
                          num_microbatches=2))


def test_spmd_step_pipeline_interleaved_not_implemented():
    params, _, _ = _fixture()
    p = partition.configure(mesh_shape={'pp': 2})
    with pytest.raises(NotImplementedError, match='interleaved'):
        SpmdTrainStep(
            None, params, partitioner=p, lr=0.1,
            pipeline=dict(stage_fn=lambda sp, x: x, tail_fn=_tail_fn,
                          stage_params=['stages.w'],
                          num_microbatches=2, schedule='interleaved'))
