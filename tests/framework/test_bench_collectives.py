"""tier-1 guard for the collectives bench: tools/bench_collectives.py must
run end-to-end under JAX_PLATFORMS=cpu at smoke sizes and demonstrate the
ISSUE 9 acceptances: int8 block-quantized all-reduce cuts telemetry-counted
bytes-on-wire >= 3.5x vs f32 with convergence parity on the MNIST recipe,
and the bucketing pass is bitwise pass-on/off at comm_dtype=f32."""
import json
import os
import subprocess
import sys

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), '..', '..'))


def test_bench_collectives_smoke_runs_on_cpu():
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    env.pop('PADDLE_TPU_COMM_DTYPE', None)
    env.pop('PADDLE_TPU_ALLREDUCE_BUCKET_MB', None)
    env.pop('PADDLE_TPU_PASSES', None)
    flags = env.get('XLA_FLAGS', '')
    if 'xla_force_host_platform_device_count' not in flags:
        env['XLA_FLAGS'] = (
            flags + ' --xla_force_host_platform_device_count=8').strip()
    r = subprocess.run(
        [sys.executable, os.path.join('tools', 'bench_collectives.py'),
         '--smoke'],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stderr[-3000:]
    lines = [json.loads(ln) for ln in r.stdout.splitlines() if ln.strip()]
    benches = {d['bench']: d for d in lines if 'bench' in d}
    assert {'collectives_bytes', 'collectives_steps',
            'collectives_convergence', 'collectives_bucketing'} <= \
        set(benches)

    by = benches['collectives_bytes']
    # THE acceptance: int8 bytes-on-wire reduction >= 3.5x, telemetry-counted
    assert by['acceptance_ge_3_5x'] is True, by
    assert by['bytes_reduction_int8'] >= 3.5, by
    assert by['reduction_bf16'] == 2.0, by
    # the f32 path is exact (bitwise psum passthrough)
    assert by['f32_exact'] is True, by
    assert by['max_rel_err_f32'] == 0.0, by
    # quantized error is small but nonzero (it really quantized)
    assert 0 < by['max_rel_err_int8'] < 0.05, by

    st = benches['collectives_steps']
    # the quantized step is a real train step on every dtype
    for comm in ('f32', 'bf16', 'int8'):
        assert st[f'steps_per_s_{comm}'] > 0, st

    cv = benches['collectives_convergence']
    # EQuARX quality claim at bench scale: int8 final loss tracks f32
    assert cv['parity'] is True, cv
    assert cv['both_converged'] is True, cv

    bk = benches['collectives_bucketing']
    assert bk['bitwise_identical'] is True, bk
    assert bk['buckets'] >= 2 and bk['bucketed_ops'] == \
        bk['allreduce_ops'], bk
