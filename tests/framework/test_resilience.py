"""Resilience subsystem (ISSUE 7, docs/RESILIENCE.md): atomic checkpoint
format + torn-file discovery, keep-N retention, IO retry with fault
injection, preemption handling, DataLoader resume cursor, bitwise resume on
both training spines, goodput/lost-work accounting, and the SIGTERM-safe
serving drain. The subprocess `kill -9` crash test lives in
test_crash_resume.py.
"""
import json
import logging
import os
import signal
import time
import urllib.request

import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers as L
from paddle_tpu import observability as obs
from paddle_tpu import resilience
from paddle_tpu.core import unique_name
from paddle_tpu.resilience.fault import FaultInjector
from paddle_tpu.resilience.manager import CheckpointManager
from paddle_tpu.resilience.preemption import PreemptionGuard
from paddle_tpu.resilience import snapshot as snap


def _mgr(directory, **kw):
    kw.setdefault('install_signal_handlers', False)
    return CheckpointManager(str(directory), **kw)


# ---------------------------------------------------------------------------
# format: atomic commit, discovery, torn files, retention
# ---------------------------------------------------------------------------

def test_checkpoint_roundtrip_preserves_values_and_dtypes(tmp_path):
    with _mgr(tmp_path) as mgr:
        arrays = {'scope/w': jnp.arange(6, dtype=jnp.float32).reshape(2, 3),
                  'scope/m': jnp.full((3,), 1.5, jnp.bfloat16),
                  'scope/i': np.arange(4, dtype=np.int32)}
        mgr.save(7, arrays, {'note': 'x'})
        mgr.wait()
        got, meta = mgr.restore()
    assert meta['step'] == 7 and meta['note'] == 'x'
    assert np.array_equal(got['scope/w'], np.arange(6).reshape(2, 3))
    assert got['scope/m'].dtype == jnp.bfloat16          # widened + cast back
    assert np.array_equal(got['scope/m'].astype(np.float32), np.full(3, 1.5))
    assert got['scope/i'].dtype == np.int32


def test_latest_skips_torn_payload_with_warning(tmp_path):
    records = []
    h = logging.Handler()
    h.emit = records.append
    logging.getLogger('paddle_tpu.resilience.snapshot').addHandler(h)
    try:
        with _mgr(tmp_path, keep=5) as mgr:
            mgr.save(1, {'w': np.zeros(4)})
            mgr.save(2, {'w': np.ones(4)})
            mgr.wait()
            ck2 = mgr.latest()
            assert ck2.step == 2
            # torn write: truncate the newest payload mid-file
            with open(ck2.payload_path, 'r+b') as f:
                f.truncate(11)
            ck = mgr.latest()
            assert ck is not None and ck.step == 1       # fell back, no crash
        assert any('torn' in r.getMessage() for r in records)
    finally:
        logging.getLogger('paddle_tpu.resilience.snapshot').removeHandler(h)


def test_latest_skips_corrupt_payload_and_orphan_manifest(tmp_path):
    with _mgr(tmp_path, keep=5) as mgr:
        mgr.save(3, {'w': np.zeros(8)})
        mgr.save(4, {'w': np.ones(8)})
        mgr.wait()
        ck4 = mgr.latest()
        # same-size corruption: only the CRC can catch it
        raw = bytearray(open(ck4.payload_path, 'rb').read())
        raw[len(raw) // 2] ^= 0xFF
        with open(ck4.payload_path, 'wb') as f:
            f.write(raw)
        assert mgr.latest().step == 3
        # manifest without payload
        os.unlink(mgr.latest().payload_path)
        assert mgr.latest() is None
    # a payload without a manifest is invisible (not committed)
    snap.atomic_write_bytes(str(tmp_path / 'ckpt-00000009.npz'), b'garbage')
    assert resilience.latest_checkpoint(str(tmp_path)) is None


def test_keep_last_n_retention(tmp_path):
    with _mgr(tmp_path, keep=2) as mgr:
        for s in range(1, 6):
            mgr.save(s, {'w': np.full(4, s, np.float32)})
        mgr.wait()
        steps = [c.step for c in mgr.all_checkpoints()]
    assert steps == [4, 5]
    names = sorted(os.listdir(tmp_path))
    assert not any(n.startswith('ckpt-000000') and n[5:13].isdigit()
                   and int(n[5:13]) < 4 for n in names), names


def test_async_save_overlaps_and_does_not_block(tmp_path):
    """save() with handles must return without materializing: a handle
    whose np.asarray is deliberately slow only blocks the writer thread."""
    class SlowHandle:
        def __init__(self, v, delay):
            self._v, self._delay = v, delay

        def __array__(self, dtype=None, copy=None):
            time.sleep(self._delay)
            return np.asarray(self._v)

    with _mgr(tmp_path) as mgr:
        t0 = time.perf_counter()
        mgr.save(1, {'w': SlowHandle(np.ones(4), 0.3)})
        submit_s = time.perf_counter() - t0
        assert submit_s < 0.1, f'save() stalled {submit_s:.3f}s'
        mgr.wait()
        assert mgr.latest().step == 1


# ---------------------------------------------------------------------------
# fault injection + retry/backoff
# ---------------------------------------------------------------------------

def test_fault_spec_parsing():
    fi = FaultInjector('kill@step=8, io_fail@times=2')
    assert fi.active and fi._kill_step == 8 and fi._io_times == 2
    assert not FaultInjector('').active
    with pytest.raises(ValueError):
        FaultInjector('explode@step=1')
    with pytest.raises(ValueError):
        FaultInjector('kill=3')


def test_io_failures_are_retried_with_backoff(tmp_path):
    with obs.telemetry_guard(True):
        obs.reset()
        mgr = _mgr(tmp_path, retries=3, backoff_s=0.01)
        mgr._fault = FaultInjector('io_fail@times=2')
        mgr.save(5, {'w': np.ones(3)})
        mgr.wait()                                 # no raise: retries won
        assert mgr.latest().step == 5
        m = obs.registry.to_dict()
        assert sum(s['value'] for s in m['checkpoint_retries']['samples']) == 2
        assert sum(s['value']
                   for s in m['fault_injections']['samples']) == 2
        mgr.close()


def test_io_failures_exhausting_retries_surface_on_wait(tmp_path):
    mgr = _mgr(tmp_path, retries=1, backoff_s=0.01)
    mgr._fault = FaultInjector('io_fail@times=5')
    mgr.save(5, {'w': np.ones(3)})
    with pytest.raises(OSError):
        mgr.wait()
    assert mgr.latest() is None                    # nothing half-committed
    mgr.close()


# ---------------------------------------------------------------------------
# preemption
# ---------------------------------------------------------------------------

def test_preemption_triggers_final_checkpoint_and_stop(tmp_path):
    with _mgr(tmp_path, every_n_steps=100) as mgr:     # cadence never due
        state = {'w': np.arange(3, dtype=np.float32)}
        assert mgr.end_of_step(1, lambda: (state, {})) is False
        mgr.request_preemption()
        assert mgr.end_of_step(2, lambda: (state, {})) is True
        ck = mgr.latest()
        assert ck is not None and ck.step == 2
        assert ck.meta['preempted'] is True


def test_sigterm_sets_preemption_flag():
    guard = PreemptionGuard().install()
    try:
        assert guard.installed and not guard.requested
        os.kill(os.getpid(), signal.SIGTERM)
        for _ in range(100):
            if guard.requested:
                break
            time.sleep(0.01)
        assert guard.requested
    finally:
        guard.uninstall()


def test_fault_kill_hook_runs_at_step_boundary(tmp_path):
    """kill@step must target exactly its step (the real SIGKILL is proven
    in test_crash_resume.py; here we only assert the trigger precision by
    pointing the injector at a step that never comes)."""
    with _mgr(tmp_path, every_n_steps=100) as mgr:
        mgr._fault = FaultInjector('kill@step=999')
        for s in range(1, 5):
            assert mgr.end_of_step(s, lambda: ({}, {})) is False


# ---------------------------------------------------------------------------
# DataLoader cursor
# ---------------------------------------------------------------------------

def _epoch_batches(epoch, n=5):
    rng = np.random.RandomState(50 + epoch)
    return [(rng.randn(2, 4).astype(np.float32),) for _ in range(n)]


def _make_loader():
    with unique_name.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = L.data('cur_x', [4], dtype='float32')
        loader = fluid.DataLoader.from_generator(feed_list=[x], capacity=2)
    loader.set_batch_generator(lambda: iter(_epoch_batches(loader.epoch)))
    return loader


def test_loader_cursor_tracks_and_resumes_mid_epoch():
    ref = []
    loader = _make_loader()
    for _ in range(2):
        for b in loader():
            ref.append(np.asarray(b['cur_x']).tobytes())
    assert loader.epoch == 2 and len(ref) == 10

    loader2 = _make_loader()
    seen, cursor = [], None
    it = iter(loader2())
    for i in range(3):
        seen.append(np.asarray(next(it)['cur_x']).tobytes())
    cursor = loader2.state_dict()
    assert cursor == {'epoch': 0, 'batch': 3}

    # "new process": fresh loader, restore the cursor, consume the rest
    loader3 = _make_loader()
    loader3.set_state_dict(cursor)
    for _ in range(2):
        for b in loader3():
            seen.append(np.asarray(b['cur_x']).tobytes())
        if len(seen) >= 10:
            break
    assert seen == ref


def test_loader_cursor_epoch_boundary_resume():
    ref = []
    loader = _make_loader()
    for _ in range(2):
        for b in loader():
            ref.append(np.asarray(b['cur_x']).tobytes())
    # cursor exactly at an exhausted epoch (consumed all, not rolled over)
    loader2 = _make_loader()
    it = iter(loader2())
    got = [np.asarray(next(it)['cur_x']).tobytes() for _ in range(5)]
    cursor = loader2.state_dict()
    assert cursor == {'epoch': 0, 'batch': 5}
    loader3 = _make_loader()
    loader3.set_state_dict(cursor)
    for _ in range(2):
        for b in loader3():
            got.append(np.asarray(b['cur_x']).tobytes())
        if len(got) >= 10:
            break
    assert got == ref


# ---------------------------------------------------------------------------
# bitwise resume: executor spine (in-process; subprocess version with a real
# kill -9 lives in test_crash_resume.py)
# ---------------------------------------------------------------------------

def _build_static():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = L.data('rx', [8], dtype='float32')
        y = L.data('ry', [1], dtype='float32')
        h = L.fc(x, size=16, act='relu')
        h = L.dropout(h, dropout_prob=0.3)
        pred = L.fc(h, size=1)
        loss = L.reduce_mean(L.square_error_cost(pred, y))
        fluid.optimizer.Adam(learning_rate=1e-2).minimize(loss)
    return main, startup, loss


def _static_batches(epoch, n=6):
    rng = np.random.RandomState(100 + epoch)
    return [(rng.randn(4, 8).astype(np.float32),
             rng.randn(4, 1).astype(np.float32)) for _ in range(n)]


def _run_static(total_steps, ckpt_dir=None, resume=False, every=3):
    losses = {}
    with unique_name.guard():
        fluid.seed(1234)
        main, startup, loss = _build_static()
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor()
            exe.run(startup)
            blk = main.global_block()
            loader = fluid.DataLoader.from_generator(
                feed_list=[blk.var('rx'), blk.var('ry')], capacity=4)
            loader.set_batch_generator(
                lambda: iter(_static_batches(loader.epoch)))
            step, mgr = 0, None
            if ckpt_dir:
                mgr = _mgr(ckpt_dir, every_n_steps=every, keep=2)
                if resume:
                    got = mgr.restore()
                    if got is not None:
                        arrays, meta = got
                        resilience.restore_training_state(
                            arrays, meta, executor=exe, program=main,
                            scope=scope, loader=loader)
                        step = meta['step']
            while step < total_steps:
                for batch in loader():
                    lv = exe.run(main, feed=batch, fetch_list=[loss])[0]
                    step += 1
                    losses[step] = np.asarray(lv).tobytes()
                    if mgr is not None:
                        mgr.end_of_step(
                            step,
                            lambda: resilience.capture_training_state(
                                executor=exe, program=main, scope=scope,
                                loader=loader))
                    if step >= total_steps:
                        break
            if mgr is not None:
                mgr.wait()
                mgr.close()
    return losses


def test_executor_spine_bitwise_resume(tmp_path):
    """Adam + dropout + mid-epoch cursor: stop at 7 (checkpoints at 3, 6),
    resume, and the remaining trajectory is BITWISE the uninterrupted one —
    RNG salts, optimizer slots, and the data stream all line up."""
    ref = _run_static(10)
    d = str(tmp_path / 'ck')
    first = _run_static(7, ckpt_dir=d)
    assert all(first[k] == ref[k] for k in first)
    second = _run_static(10, ckpt_dir=d, resume=True)
    assert sorted(second) == [7, 8, 9, 10]          # resumed from step 6
    assert all(second[k] == ref[k] for k in second), \
        'resumed loss trajectory is not bitwise-identical'


def test_executor_snapshot_is_donation_protected_until_materialized():
    """snapshot_persistables registers window protection: the executor must
    not donate a pending handle's buffer (the snapshot's integrity), and
    protection drains once the writer materializes."""
    with unique_name.guard():
        fluid.seed(0)
        main, startup, loss = _build_static()
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor()
            exe.run(startup)
            # warm the compiled step, then take the point-in-time reference
            x, y = _static_batches(0)[0]
            exe.run(main, feed={'rx': x, 'ry': y}, fetch_list=[loss])
            pre = {n: np.asarray(scope.find(n)) for n in
                   (v.name for v in main.list_vars() if v.persistable)}
            handles = exe.snapshot_persistables(main, scope)
            assert set(exe._window.protected_names()) == set(handles)
            # run a step while the snapshot is pending: donation must skip
            # the protected buffers, so materializing afterwards still
            # yields the PRE-step values (without protection the donated
            # buffers would be invalidated or overwritten in place)
            x2, y2 = _static_batches(0)[1]
            exe.run(main, feed={'rx': x2, 'ry': y2}, fetch_list=[loss])
            mats = {n: np.asarray(h) for n, h in handles.items()}
            for n, v in pre.items():
                assert np.array_equal(mats[n], v), \
                    f'snapshot of {n} was clobbered by the next step'
            # materialized handles drop their protection → donation resumes
            assert exe._window.protected_names() == set()


# ---------------------------------------------------------------------------
# bitwise resume: fused TrainStep spine
# ---------------------------------------------------------------------------

def _make_trainstep():
    from paddle_tpu import dygraph
    from paddle_tpu.dygraph.nn import Linear
    from paddle_tpu.dygraph.jit import TrainStep
    from paddle_tpu.dygraph.tape import dispatch_op
    with unique_name.guard():
        fluid.seed(7)

        class M(dygraph.Layer):
            def __init__(self):
                super().__init__()
                self.l1 = Linear(8, 16, act='relu')
                self.l2 = Linear(16, 1)

            def forward(self, x):
                return self.l2(self.l1(x))

        m = M()
        opt = fluid.optimizer.Adam(learning_rate=1e-2,
                                   parameter_list=list(m.parameters()))

        def loss_fn(layer, x, y):
            d = dispatch_op('elementwise_sub', {'x': layer(x), 'y': y}, {})
            sq = dispatch_op('elementwise_mul', {'x': d, 'y': d}, {})
            return dispatch_op('reduce_mean', {'x': sq}, {})

        return TrainStep(m, loss_fn, opt)


def test_trainstep_bitwise_resume_through_checkpoint(tmp_path):
    from paddle_tpu import dygraph
    rng = np.random.RandomState(0)
    data = [(rng.randn(4, 8).astype('f4'), rng.randn(4, 1).astype('f4'))
            for _ in range(10)]
    with dygraph.guard():
        ts_ref = _make_trainstep()
        ref = [np.asarray(ts_ref(x, y)).tobytes() for x, y in data]

        ts_a = _make_trainstep()
        half = [np.asarray(ts_a(x, y)).tobytes() for x, y in data[:5]]
        assert half == ref[:5]
        with _mgr(tmp_path) as mgr:
            arrays, meta = resilience.capture_training_state(
                train_step=ts_a)
            mgr.save(5, arrays, meta)
            mgr.wait()
            # donation is on by default: the snapshot cloned on-device, so
            # continuing to train must not perturb the checkpoint
            np.asarray(ts_a(*data[5]))
            got, got_meta = mgr.restore()

        ts_b = _make_trainstep()
        resilience.restore_training_state(got, got_meta, train_step=ts_b)
        rest = [np.asarray(ts_b(x, y)).tobytes() for x, y in data[5:]]
    assert rest == ref[5:], \
        'TrainStep resume is not bitwise-identical'


# ---------------------------------------------------------------------------
# goodput / lost-work accounting
# ---------------------------------------------------------------------------

def test_goodput_books_lost_work_on_restart(tmp_path):
    with obs.telemetry_guard(True):
        obs.reset()
        mgr = _mgr(tmp_path, every_n_steps=5)
        state = {'w': np.ones(2)}
        for s in range(1, 8):          # checkpoint at 5; heartbeat to 7
            mgr.end_of_step(s, lambda: (state, {}))
        mgr.wait()
        # "crash": a new manager (new incarnation) restores
        mgr2 = _mgr(tmp_path, every_n_steps=5)
        arrays, meta = mgr2.restore()
        assert meta['step'] == 5
        assert mgr2.goodput.lost_steps == 2        # steps 6, 7 are replayed
        assert mgr2.goodput.restarts == 1
        m = obs.registry.to_dict()
        assert sum(s['value'] for s in m['restarts_total']['samples']) == 1
        assert sum(s['value']
                   for s in m['restart_lost_steps']['samples']) == 2
        g = meta['goodput']
        assert g['steps'] == 5 and g['productive_s'] >= 0
        mgr.close()
        mgr2.close()


def test_checkpoint_metrics_flow_through_registry(tmp_path):
    with obs.telemetry_guard(True):
        obs.reset()
        with _mgr(tmp_path, every_n_steps=2) as mgr:
            state = {'w': np.ones((64,), np.float32)}
            for s in range(1, 5):
                mgr.end_of_step(s, lambda: (state, {}))
            mgr.wait()
        m = obs.registry.to_dict()
        assert sum(s['value'] for s in m['checkpoint_saves']['samples']) == 2
        assert sum(s['value'] for s in m['checkpoint_bytes']['samples']) > 0
        stall = m['checkpoint_stall_seconds']['samples'][0]
        assert stall['count'] == 2
        assert any(s['value'] == 4 for s in
                   m['checkpoint_last_step']['samples'])
        assert 'goodput_ratio' in m


# ---------------------------------------------------------------------------
# serving: SIGTERM → draining healthz → graceful close, with timeout cap
# ---------------------------------------------------------------------------

@pytest.fixture(scope='module')
def _serving_model(tmp_path_factory):
    d = str(tmp_path_factory.mktemp('srvmodel'))
    with unique_name.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = L.data('sx', [4], dtype='float32')
            out = L.fc(x, size=2)
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor()
            exe.run(startup)
            fluid.io.save_inference_model(d, ['sx'], [out], exe,
                                          main_program=main)
    return d


def test_serving_sigterm_drains_then_stops(_serving_model, monkeypatch):
    from paddle_tpu.serving.engine import InferenceEngine
    from paddle_tpu.serving.server import ServingServer
    eng = InferenceEngine(_serving_model, max_batch_size=2)
    real = eng.run_batch
    monkeypatch.setattr(
        eng, 'run_batch',
        lambda feed, nrows=None: (time.sleep(0.15), real(feed, nrows))[1])
    srv = ServingServer(eng, port=0, batch_timeout_ms=0).start()
    srv.install_signal_handlers()
    try:
        url = f'http://127.0.0.1:{srv.port}'
        assert urllib.request.urlopen(url + '/healthz').status == 200
        futs = [srv.batcher.submit({'sx': [[float(i)] * 4]})
                for i in range(4)]                  # ~0.6s of queued work
        os.kill(os.getpid(), signal.SIGTERM)
        deadline = time.time() + 5
        code = None
        while time.time() < deadline:
            try:
                urllib.request.urlopen(url + '/healthz', timeout=1)
            except urllib.error.HTTPError as e:
                code = e.code
                break
            except OSError:
                break                   # listener already gone: drained fast
            time.sleep(0.02)
        if code is not None:
            assert code == 503          # draining window observed
        for f in futs:                  # graceful: everything admitted runs
            assert len(f.result(10)) == 1
        for _ in range(100):
            if srv.batcher.closed:
                break
            time.sleep(0.05)
        assert srv.batcher.closed
    finally:
        srv.uninstall_signal_handlers()
        srv.shutdown()


def test_serving_drain_timeout_escalates_to_fail_fast(_serving_model,
                                                     monkeypatch):
    from paddle_tpu.serving.batcher import MicroBatcher
    from paddle_tpu.serving.errors import EngineClosed
    from paddle_tpu.serving.engine import InferenceEngine
    from paddle_tpu.serving.server import ServingServer
    eng = InferenceEngine(_serving_model, max_batch_size=1)
    real = eng.run_batch
    monkeypatch.setattr(
        eng, 'run_batch',
        lambda feed, nrows=None: (time.sleep(0.4), real(feed, nrows))[1])
    srv = ServingServer(eng, port=0, batch_timeout_ms=0,
                        queue_depth=64).start()
    futs = [srv.batcher.submit({'sx': [[1.0] * 4]}) for _ in range(8)]
    monkeypatch.setenv('PADDLE_TPU_DRAIN_TIMEOUT_S', '0.5')
    t0 = time.perf_counter()
    srv.shutdown(drain=True)            # ~3.2s of queued work vs 0.5s cap
    elapsed = time.perf_counter() - t0
    assert elapsed < 3.0, f'drain was not capped ({elapsed:.1f}s)'
    outcomes = {'ok': 0, 'closed': 0}
    for f in futs:
        try:
            f.result(5)
            outcomes['ok'] += 1
        except EngineClosed:
            outcomes['closed'] += 1
    assert outcomes['closed'] > 0, outcomes   # tail failed fast, not hung
    assert outcomes['ok'] + outcomes['closed'] == 8
