"""Long-tail fluid module parity: io save/load/program_state, average,
evaluator, install_check, dygraph_grad_clip, input, default_scope_funcs,
op introspection, net_drawer, data_feed_desc, communicator, trainer
machinery, distribute_lookup_table, debugger repr/nan-inf."""
import os

import numpy as np
import pytest

import paddle_tpu as fluid


def _build_regression(scope_reset=True):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data('x', [4, 3], 'float32')
        y = fluid.data('y', [4, 1], 'float32')
        pred = fluid.layers.fc(x, 1, name='fcio')
        loss = fluid.layers.reduce_mean(
            fluid.layers.square_error_cost(pred, y))
        opt = fluid.optimizer.SGD(0.1)
        opt.minimize(loss)
    return main, startup, loss


# ---------------------------------------------------------------- io ----

def test_io_predicates_and_program_queries():
    main, startup, _ = _build_regression()
    params = fluid.io.get_program_parameter(main)
    persist = fluid.io.get_program_persistable_vars(main)
    assert params and all(fluid.io.is_parameter(p) for p in params)
    assert set(p.name for p in params) <= set(v.name for v in persist)
    assert all(fluid.io.is_persistable(v) for v in persist)


def test_io_save_load_roundtrip(tmp_path):
    main, startup, loss = _build_regression()
    exe = fluid.Executor()
    exe.run(startup)
    x = np.random.rand(4, 3).astype('float32')
    y = np.random.rand(4, 1).astype('float32')
    exe.run(main, feed={'x': x, 'y': y}, fetch_list=[loss])
    w_name = fluid.io.get_program_parameter(main)[0].name
    w_before = fluid.io.get_parameter_value_by_name(w_name, exe, main)

    path = str(tmp_path / 'model')
    fluid.save(main, path)

    # perturb, then restore
    fluid.global_scope().set(w_name, np.zeros_like(w_before))
    fluid.load(main, path, exe)
    np.testing.assert_allclose(
        fluid.io.get_parameter_value_by_name(w_name, exe, main), w_before)

    state = fluid.io.load_program_state(path)
    assert w_name in state
    state[w_name] = state[w_name] + 1.0
    n = fluid.io.set_program_state(main, state)
    assert n >= 1
    np.testing.assert_allclose(
        fluid.io.get_parameter_value_by_name(w_name, exe, main),
        w_before + 1.0)


def test_set_program_state_shape_mismatch(tmp_path):
    main, startup, _ = _build_regression()
    exe = fluid.Executor()
    exe.run(startup)
    w_name = fluid.io.get_program_parameter(main)[0].name
    with pytest.raises(ValueError):
        fluid.io.set_program_state(main, {w_name: np.zeros((99, 99))})


# ----------------------------------------------------------- average ----

def test_weighted_average():
    wa = fluid.average.WeightedAverage()
    with pytest.raises(ValueError):
        wa.eval()
    wa.add(1.0, 1)
    wa.add(3.0, 3)
    assert wa.eval() == pytest.approx(2.5)
    wa.reset()
    wa.add(np.array([2.0, 4.0]), 2)
    assert wa.eval() == pytest.approx(3.0)


# --------------------------------------------------------- evaluator ----

def test_evaluator_aliases_warn():
    with pytest.warns(DeprecationWarning):
        ed = fluid.evaluator.EditDistance('distance')
    assert isinstance(ed, fluid.metrics.EditDistance)


def test_install_check_run_check():
    fluid.install_check.run_check()


# -------------------------------------------------- dygraph_grad_clip ----

def test_dygraph_grad_clip_classes():
    import jax.numpy as jnp
    pg = [(None, jnp.array([3.0, -4.0])), (None, None)]
    v = fluid.dygraph_grad_clip.GradClipByValue(1.0)(pg)
    np.testing.assert_allclose(v[0][1], [1.0, -1.0])
    assert v[1][1] is None
    n = fluid.dygraph_grad_clip.GradClipByNorm(2.5)(pg)
    np.testing.assert_allclose(np.linalg.norm(n[0][1]), 2.5, rtol=1e-5)
    g = fluid.dygraph_grad_clip.GradClipByGlobalNorm(1.0)(
        [(None, jnp.array([3.0])), (None, jnp.array([4.0]))])
    total = np.sqrt(sum(float(np.sum(np.square(x[1]))) for x in g))
    assert total == pytest.approx(1.0, rel=1e-5)


# ------------------------------------------------------------- input ----

def test_input_module_embedding_one_hot():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = fluid.data('ids', [4], 'int64')
        emb = fluid.input.embedding(ids, size=[10, 8])
        oh = fluid.input.one_hot(ids, 10)
    exe = fluid.Executor()
    exe.run(startup)
    e, o = exe.run(main, feed={'ids': np.array([1, 2, 3, 0])},
                   fetch_list=[emb, oh])
    assert e.shape == (4, 8) and o.shape == (4, 10)
    np.testing.assert_allclose(o.sum(-1), 1.0)


# ----------------------------------------------- default_scope_funcs ----

def test_default_scope_funcs():
    dsf = fluid.default_scope_funcs
    base = dsf.get_cur_scope()
    dsf.enter_local_scope()
    dsf.var('tmp_x')
    assert dsf.get_cur_scope() is not base
    dsf.leave_local_scope()
    assert dsf.get_cur_scope() is base

    def inner():
        dsf.var('scoped_y')
        return 42
    assert dsf.scoped_function(inner) == 42


# ---------------------------------------------------------------- op ----

def test_op_protos_and_factory():
    protos = fluid.op.get_all_op_protos()
    assert len(protos) > 250
    relu = [p for p in protos if p.type == 'relu'][0]
    assert 'x' in relu.inputs and 'Out' in relu.outputs
    desc = fluid.op.Operator(type='scale', x='a', Out='b', scale=2.0)
    assert desc['type'] == 'scale' and desc['attrs']['scale'] == 2.0
    with pytest.raises(ValueError):
        fluid.op.OpInfo('definitely_not_an_op')


# -------------------------------------------------------- net_drawer ----

def test_net_drawer(tmp_path):
    main, startup, _ = _build_regression()
    path = str(tmp_path / 'g.dot')
    text = fluid.net_drawer.draw_graph(startup, main, path=path)
    assert os.path.exists(path)
    assert 'digraph G' in text and 'matmul' in text or 'mul' in text


# ----------------------------------------------------- data_feed_desc ----

def test_data_feed_desc_roundtrip(tmp_path):
    proto = tmp_path / 'feed.proto'
    proto.write_text('''
name: "MultiSlotDataFeed"
batch_size: 2
multi_slot_desc {
  slots {
    name: "words"
    type: "uint64"
    is_dense: false
    is_used: false
  }
  slots {
    name: "label"
    type: "uint64"
    is_dense: false
    is_used: false
  }
}''')
    d = fluid.DataFeedDesc(str(proto))
    d.set_batch_size(128)
    d.set_dense_slots(['words'])
    d.set_use_slots(['words', 'label'])
    text = d.desc()
    assert 'batch_size: 128' in text
    assert d.proto_desc['multi_slot_desc']['slots'][0]['is_dense'] is True
    assert d.proto_desc['multi_slot_desc']['slots'][1]['is_used'] is True


# ------------------------------------------------------ communicator ----

def test_communicator_lifecycle():
    c = fluid.Communicator(fluid.Program())
    assert not c.is_running()
    c.start()
    assert c.is_running()
    c.stop()
    assert not c.is_running()


# ------------------------------------------------- trainer machinery ----

def test_trainer_factory_defaults():
    from paddle_tpu.trainer_factory import TrainerFactory
    t = TrainerFactory()._create_trainer(None)
    t._set_program(fluid.Program())
    t._gen_trainer_desc()
    assert t.proto_desc['class_name'] == 'MultiTrainer'
    assert t.proto_desc['device_worker_name'] == 'HogwildWorker'

    t2 = TrainerFactory()._create_trainer(
        {'trainer': 'DistMultiTrainer', 'device_worker': 'DownpourSGD'})
    t2._set_program(fluid.Program())
    t2._gen_trainer_desc()
    assert t2.proto_desc['class_name'] == 'DistMultiTrainer'
    assert t2.proto_desc['device_worker_name'] == 'DownpourWorker'


def test_fetch_handler_monitor():
    import time
    from paddle_tpu.trainer_factory import FetchHandler, FetchHandlerMonitor
    fluid.global_scope().set('fh_var', np.array([7.0]))
    seen = []

    class H(FetchHandler):
        def handler(self, res):
            seen.append(res['v'])
    h = H(var_dict={'v': 'fh_var'}, period_secs=0.05)
    m = FetchHandlerMonitor(fluid.global_scope(), h)
    m.start()
    time.sleep(0.2)
    m.stop()
    assert seen and np.asarray(seen[-1]) == pytest.approx([7.0])


# -------------------------------------- distribute_lookup_table scan ----

def test_find_distributed_lookup_table():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = fluid.data('dlt_ids', [4], 'int64')
        emb = fluid.layers.embedding(ids, size=[30, 8], is_distributed=True)
    name = fluid.distribute_lookup_table.find_distributed_lookup_table(main)
    assert name is not None
    ins = fluid.distribute_lookup_table \
        .find_distributed_lookup_table_inputs(main, name)
    outs = fluid.distribute_lookup_table \
        .find_distributed_lookup_table_outputs(main, name)
    assert 'dlt_ids' in ins and outs


# ---------------------------------------------------------- debugger ----

def test_debugger_reprs_and_nan_inf():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data('dx', [2, 2], 'float32')
        h = fluid.layers.log(x)          # NaN for negative input
        out = fluid.layers.reduce_sum(h)
    var = main.global_block().var('dx')
    assert 'dx' in fluid.debugger.repr_var(var)
    op = main.global_block().ops[-1]
    assert 'reduce_sum' in fluid.debugger.repr_op(op)

    exe = fluid.Executor()
    exe.run(startup)
    fluid.debugger.prepare_fast_nan_inf_debug(main)
    # clean input -> passes through and returns fetches
    r = fluid.debugger.run_fast_nan_inf_debug(
        exe, main, feed={'dx': np.ones((2, 2), 'float32')},
        fetch_list=[out])
    assert np.isfinite(r[0]).all()
    with pytest.raises(RuntimeError, match='NaN/Inf'):
        fluid.debugger.run_fast_nan_inf_debug(
            exe, main, feed={'dx': -np.ones((2, 2), 'float32')},
            fetch_list=[out])


# ----------------------------------------------------- layers.utils ----

def test_layers_nest_utils():
    u = fluid.layers.utils
    assert u.convert_to_list(3, 2, 'k') == [3, 3]
    assert u.convert_to_list([1, 2], 2, 'k') == [1, 2]
    with pytest.raises(ValueError):
        u.convert_to_list([1], 2, 'k')
    nest = {'a': [1, (2, 3)], 'b': 4}
    flat = u.flatten(nest)
    assert flat == [1, 2, 3, 4]
    rebuilt = u.pack_sequence_as(nest, [x * 10 for x in flat])
    assert rebuilt == {'a': [10, (20, 30)], 'b': 40}
    assert u.map_structure(lambda x: x + 1, nest) == \
        {'a': [2, (3, 4)], 'b': 5}
    u.assert_same_structure(nest, rebuilt)
    with pytest.raises((ValueError, TypeError)):
        u.assert_same_structure(nest, [1, 2, 3, 4])
    assert u.is_sequence([1]) and not u.is_sequence('abc')


def test_dygraph_tracer_and_patches():
    from paddle_tpu import dygraph
    t = dygraph.Tracer()
    t.eval_mode(); t.train_mode()
    dygraph.monkey_patch_varbase()
    dygraph.monkey_patch_math_varbase()
    with dygraph.guard():
        out = t.trace_op('scale', {'x': dygraph.to_variable(
            np.array([2.0], 'float32'))}, {}, {'scale': 3.0})
        np.testing.assert_allclose(out.numpy(), [6.0])
