"""nets.py composites + profiler op-timer surface (ref test model:
unittests/test_nets.py, test_profiler.py)."""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import nets, profiler

RNG = np.random.RandomState(11)


def _run(build, feeds):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        fetch = build()
    exe = fluid.Executor()
    exe.run(startup)
    fetch = fetch if isinstance(fetch, (list, tuple)) else [fetch]
    return exe.run(main, feed=feeds, fetch_list=list(fetch))


def test_simple_img_conv_pool():
    x = RNG.rand(2, 1, 8, 8).astype('float32')

    def build():
        xv = fluid.data('ni_x', [2, 1, 8, 8], 'float32')
        return nets.simple_img_conv_pool(xv, num_filters=4, filter_size=3,
                                         pool_size=2, pool_stride=2,
                                         act='relu')
    r, = _run(build, {'ni_x': x})
    # conv pad 0: 8→6; pool 2/2: 6→3
    assert r.shape == (2, 4, 3, 3)
    assert (r >= 0).all()


def test_img_conv_group():
    x = RNG.rand(2, 3, 8, 8).astype('float32')

    def build():
        xv = fluid.data('ig_x', [2, 3, 8, 8], 'float32')
        return nets.img_conv_group(xv, conv_num_filter=[4, 4], pool_size=2,
                                   pool_stride=2, conv_with_batchnorm=True)
    r, = _run(build, {'ig_x': x})
    # conv pad 1 keeps 8; pool 2/2: 8→4
    assert r.shape == (2, 4, 4, 4)


def test_sequence_conv_pool():
    x = RNG.rand(3, 6, 8).astype('float32')

    def build():
        xv = fluid.data('sc_x', [3, 6, 8], 'float32')
        return nets.sequence_conv_pool(xv, num_filters=5, filter_size=3)
    r, = _run(build, {'sc_x': x})
    assert r.shape == (3, 5)


def test_glu_halves_dim():
    x = RNG.rand(2, 6).astype('float32')

    def build():
        xv = fluid.data('gl_x', [2, 6], 'float32')
        return nets.glu(xv, dim=-1)
    r, = _run(build, {'gl_x': x})
    a, b = x[:, :3], x[:, 3:]
    np.testing.assert_allclose(r, a / (1 + np.exp(-b)), rtol=1e-5)


def test_scaled_dot_product_attention():
    q = RNG.rand(2, 4, 8).astype('float32')

    def build():
        qv = fluid.data('at_q', [2, 4, 8], 'float32')
        kv = fluid.data('at_k', [2, 4, 8], 'float32')
        vv = fluid.data('at_v', [2, 4, 8], 'float32')
        return nets.scaled_dot_product_attention(qv, kv, vv, num_heads=2)
    r, = _run(build, {'at_q': q, 'at_k': q, 'at_v': q})
    assert r.shape == (2, 4, 8)
    # attention over identical k/v rows is a convex combination: bounded
    assert r.min() >= q.min() - 1e-5 and r.max() <= q.max() + 1e-5


def test_profiler_records_and_reports():
    # the summary now goes through log_helper, not print(): capture by
    # attaching a handler to the module logger
    import io
    import logging
    stream = io.StringIO()
    handler = logging.StreamHandler(stream)
    log = logging.getLogger('paddle_tpu.profiler')
    log.addHandler(handler)
    try:
        profiler.reset_profiler()
        profiler.start_profiler(state='CPU')
        with profiler.record_event('my_region'):
            x = np.zeros(10)
            for _ in range(3):
                x = x + 1
        with profiler.record_event('my_region'):
            pass
        times = profiler.get_op_times()
        assert 'my_region' in times and times['my_region'][0] == 2
        profiler.stop_profiler(sorted_key='calls')
    finally:
        log.removeHandler(handler)
    assert 'my_region' in stream.getvalue()
    profiler.reset_profiler()
    assert profiler.get_op_times() == {}


def test_profiler_context_manager():
    with profiler.profiler(state='CPU', sorted_key='total'):
        with profiler.record_event('ctx_region'):
            pass
