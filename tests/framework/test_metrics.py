"""metrics.py coverage (ref python/paddle/fluid/metrics.py tests) + reader
decorator behavior (ref python/paddle/reader/tests/decorator_test.py)."""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers, metrics


def test_accuracy_metric():
    m = metrics.Accuracy()
    m.update(0.8, 10)
    m.update(0.6, 30)
    assert abs(m.eval() - (0.8 * 10 + 0.6 * 30) / 40) < 1e-9
    m.reset()
    m.update(1.0, 5)
    assert m.eval() == 1.0


def test_precision_recall():
    p = metrics.Precision()
    preds = np.array([0.9, 0.2, 0.8, 0.1])
    labels = np.array([1, 0, 0, 1])
    p.update(preds, labels)
    # predicted positive: idx 0, 2 → tp=1, fp=1
    assert abs(p.eval() - 0.5) < 1e-9
    r = metrics.Recall()
    r.update(preds, labels)
    # actual positive: idx 0, 3 → tp=1, fn=1
    assert abs(r.eval() - 0.5) < 1e-9


def test_chunk_evaluator():
    m = metrics.ChunkEvaluator()
    m.update(np.array([10]), np.array([8]), np.array([6]))
    prec, rec, f1 = m.eval()
    assert abs(prec - 0.6) < 1e-9
    assert abs(rec - 0.75) < 1e-9
    assert abs(f1 - 2 * 0.6 * 0.75 / 1.35) < 1e-9


def test_edit_distance_metric():
    m = metrics.EditDistance()
    m.update(np.array([1.0, 0.0, 2.0]), np.array([3]))
    avg, err = m.eval()
    assert abs(avg - 1.0) < 1e-9
    assert abs(err - 2 / 3) < 1e-9


def test_auc_metric_perfect_classifier():
    m = metrics.Auc(num_thresholds=255)
    preds = np.array([[0.1, 0.9]] * 50 + [[0.9, 0.1]] * 50)
    labels = np.array([1] * 50 + [0] * 50)
    m.update(preds, labels)
    assert m.eval() > 0.99
    m2 = metrics.Auc(num_thresholds=255)
    rng = np.random.RandomState(0)
    m2.update(rng.rand(400, 2), rng.randint(0, 2, 400))
    assert 0.35 < m2.eval() < 0.65   # random classifier ≈ 0.5


def test_composite_metric():
    c = metrics.CompositeMetric()
    c.add_metric(metrics.Precision())
    c.add_metric(metrics.Recall())
    preds = np.array([0.9, 0.2])
    labels = np.array([1, 1])
    c.update(preds, labels)
    prec, rec = c.eval()
    assert abs(prec - 1.0) < 1e-9
    assert abs(rec - 0.5) < 1e-9


def test_detection_map_builds_and_runs():
    x = layers.data('det', [7], dtype='float32')
    gl = layers.data('gl', [1], dtype='int64')
    gb = layers.data('gb', [4], dtype='float32')
    m = metrics.DetectionMAP(x, gl, gb, class_num=3)
    cur, accum = m.get_map_var()
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    # one detection of class 1 exactly on the one class-1 gt → mAP = 1
    det = np.array([[1, 0.9, 1, 1, 3, 3, 0]], np.float32)
    cv, av = exe.run(feed={'det': det,
                           'gl': np.array([[1]], np.int64),
                           'gb': np.array([[1, 1, 3, 3]], np.float32)},
                     fetch_list=[cur, accum])
    np.testing.assert_allclose(cv, [1.0], rtol=1e-5)
    np.testing.assert_allclose(av, [1.0], rtol=1e-5)
    # a miss (wrong class) halves the running mean
    cv, av = exe.run(feed={'det': det,
                           'gl': np.array([[2]], np.int64),
                           'gb': np.array([[1, 1, 3, 3]], np.float32)},
                     fetch_list=[cur, accum])
    np.testing.assert_allclose(cv, [0.0], atol=1e-6)
    np.testing.assert_allclose(av, [0.5], rtol=1e-5)


# ---------------------------------------------------------------------------
# reader decorators (SURVEY §2.7)
# ---------------------------------------------------------------------------
def _range_reader(n):
    def r():
        for i in range(n):
            yield i
    return r


def test_reader_batch_and_drop_last():
    from paddle_tpu import reader
    out = list(reader.batch(_range_reader(7), 3)())
    assert out == [[0, 1, 2], [3, 4, 5], [6]]
    out = list(reader.batch(_range_reader(7), 3, drop_last=True)())
    assert out == [[0, 1, 2], [3, 4, 5]]


def test_reader_shuffle_preserves_items():
    from paddle_tpu import reader
    out = list(reader.shuffle(_range_reader(20), 10)())
    assert sorted(out) == list(range(20))


def test_reader_buffered_and_firstn():
    from paddle_tpu import reader
    assert list(reader.buffered(_range_reader(5), 2)()) == list(range(5))
    assert list(reader.firstn(_range_reader(100), 4)()) == [0, 1, 2, 3]


def test_reader_map_chain_compose():
    from paddle_tpu import reader
    doubled = list(reader.map_readers(lambda a: a * 2, _range_reader(3))())
    assert doubled == [0, 2, 4]
    chained = list(reader.chain(_range_reader(2), _range_reader(2))())
    assert chained == [0, 1, 0, 1]
    composed = list(reader.compose(_range_reader(3), _range_reader(3))())
    assert composed == [(0, 0), (1, 1), (2, 2)]


def test_reader_xmap_order():
    from paddle_tpu import reader
    out = list(reader.xmap_readers(lambda a: a + 1, _range_reader(8),
                                   process_num=2, buffer_size=4,
                                   order=True)())
    assert out == list(range(1, 9))
