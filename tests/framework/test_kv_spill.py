"""Tiered HBM→host prefix cache (PADDLE_TPU_PREFIX_CACHE_HOST_MB):
spill→reinject bitwise parity, the publish-time MAX_BLOCKS cap (cause-
labeled eviction metrics), host-LRU byte bounding, the walked-path
exclusion regression (an insert must never orphan the subtree it stands
on), and a kill -9 subprocess drill — the spill tier is process-local, so
dying mid-spill can never corrupt anything a fresh process sees."""
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from paddle_tpu.dygraph import guard
from paddle_tpu.models.causal_lm import greedy_generate
from paddle_tpu.serving import DecodeEngine, DecodeScheduler, PrefixCache
from paddle_tpu.serving.decode.kv_cache import BlockTable
from paddle_tpu.serving.tier.replica import build_tiny_lm

_REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


@pytest.fixture(scope='module')
def lm():
    with guard():
        yield build_tiny_lm()


def make_engine(model, **kw):
    kw.setdefault('slots', 2)
    kw.setdefault('block_size', 4)
    kw.setdefault('max_blocks', 64)
    kw.setdefault('max_prompt_len', 16)
    kw.setdefault('max_new_tokens_cap', 8)
    kw.setdefault('prefix_cache', True)
    return DecodeEngine(model, **kw)


def _counter(name, **labels):
    from paddle_tpu.observability import registry
    d = registry.to_dict().get(name)
    if not d or not d['samples']:
        return 0.0
    return sum(s['value'] for s in d['samples']
               if not labels or s.get('labels') == labels)


PROMPT = [7, 3, 11, 5, 9, 2, 44, 8, 13]           # two whole 4-token blocks


# -- spill → reinject parity (the load-bearing contract) -------------------

@pytest.mark.parametrize('dtype', ['f32', 'int8'])
def test_spill_reinject_bitwise_equals_resident_hit(lm, monkeypatch, dtype):
    """Cold generation, spill EVERY cached block to host RAM, run the same
    prompt again: the hit reinjects from the host tier and must produce
    the cold generation's exact bytes — at f32 (byte-identical payload
    roundtrip) and at int8 (quantized payload + scales roundtrip)."""
    monkeypatch.setenv('PADDLE_TPU_PREFIX_CACHE_HOST_MB', '8')
    eng = make_engine(lm, kv_dtype=dtype)
    pc = eng.prefix_cache
    s0 = _counter('kv_cache_spill_count')
    b0 = _counter('kv_cache_bytes_spilled')
    r0 = _counter('kv_cache_reinject_count')
    with DecodeScheduler(eng) as sched:
        cold = sched.submit(PROMPT, max_new_tokens=6).result(240)
        resident = pc.resident_blocks
        assert resident == 2
        while pc._spill_or_evict_one():
            pass
        assert pc.resident_blocks == 0
        assert pc.spilled_blocks == resident
        assert pc.host_bytes > 0
        assert _counter('kv_cache_spill_count') - s0 == resident
        assert _counter('kv_cache_bytes_spilled') - b0 == pc.host_bytes
        hit = sched.submit(PROMPT, max_new_tokens=6).result(240)
    assert hit == cold
    if dtype == 'f32':
        assert cold == greedy_generate(lm, PROMPT, 6,
                                       pad_len=eng.padded_context)
    assert _counter('kv_cache_reinject_count') - r0 == resident
    assert pc.spilled_blocks == 0                 # promoted back to HBM
    assert pc.resident_blocks == resident


def test_evict_idle_drops_host_tier_too(lm, monkeypatch):
    monkeypatch.setenv('PADDLE_TPU_PREFIX_CACHE_HOST_MB', '8')
    eng = make_engine(lm)
    pc = eng.prefix_cache
    with DecodeScheduler(eng) as sched:
        sched.submit(PROMPT, max_new_tokens=4).result(240)
    while pc._spill_or_evict_one():
        pass
    assert pc.spilled_blocks > 0
    # a cold re-publish over the spilled path promotes the nodes in place
    with DecodeScheduler(eng) as sched:
        sched.submit(PROMPT, max_new_tokens=4).result(240)
    assert pc.spilled_blocks == 0 and pc.resident_blocks == 2
    pc.evict_idle()
    assert pc.resident_blocks == 0 and pc.spilled_blocks == 0
    assert pc.host_bytes == 0
    assert eng.pool.allocator.used == 0


# -- publish-time cap (the satellite bugfix) -------------------------------

def test_max_blocks_cap_enforced_on_publish(lm, monkeypatch):
    """PADDLE_TPU_PREFIX_CACHE_MAX_BLOCKS must bound residency at PUBLISH
    time too (pre-fix it only triggered on allocation pressure): three
    disjoint 2-block prompts through a cap of 2 keep residency ≤ 2 and
    count prefix_cache_evictions{cause=cap}."""
    monkeypatch.setenv('PADDLE_TPU_PREFIX_CACHE_MAX_BLOCKS', '2')
    monkeypatch.delenv('PADDLE_TPU_PREFIX_CACHE_HOST_MB', raising=False)
    eng = make_engine(lm)
    c0 = _counter('prefix_cache_evictions', cause='cap')
    prompts = [[t] * 9 for t in (5, 6, 7)]
    with DecodeScheduler(eng) as sched:
        for p in prompts:
            sched.submit(p, max_new_tokens=4).result(240)
    pc = eng.prefix_cache
    assert pc.resident_blocks <= 2
    assert pc.resident_blocks == len(pc.resident_block_ids())
    assert _counter('prefix_cache_evictions', cause='cap') - c0 > 0


def test_cap_spills_instead_of_dropping_when_host_configured(lm,
                                                             monkeypatch):
    monkeypatch.setenv('PADDLE_TPU_PREFIX_CACHE_MAX_BLOCKS', '2')
    monkeypatch.setenv('PADDLE_TPU_PREFIX_CACHE_HOST_MB', '8')
    eng = make_engine(lm)
    pc = eng.prefix_cache
    prompts = [[t] * 9 for t in (5, 6, 7)]
    with DecodeScheduler(eng) as sched:
        colds = [sched.submit(p, max_new_tokens=4).result(240)
                 for p in prompts]
        assert pc.resident_blocks <= 2
        assert pc.spilled_blocks > 0              # cap moved blocks to host
        # the capped-out prompt still hits — served back via reinjection
        r0 = _counter('kv_cache_reinject_count')
        h0 = _counter('prefix_cache_hits')
        again = sched.submit(prompts[0], max_new_tokens=4).result(240)
    assert again == colds[0]
    assert _counter('prefix_cache_hits') - h0 == 1
    assert _counter('kv_cache_reinject_count') - r0 > 0


# -- host LRU bounding -----------------------------------------------------

def test_host_tier_lru_cap_drops_oldest():
    from paddle_tpu.serving.tier.prefix_cache import _HostTier, _Node
    t = _HostTier(100)
    n1, n2, n3 = _Node(None), _Node(None), _Node(None)
    assert t.put(n1, b'x' * 40) == []
    assert t.put(n2, b'y' * 40) == []
    t.touch(n1)                                   # n2 becomes the LRU entry
    assert t.put(n3, b'z' * 40) == [n2]
    assert t.bytes <= 100
    assert n1 in t and n3 in t and n2 not in t
    assert t.pop(n1) == b'x' * 40
    assert t.bytes == 40


def test_host_overflow_drops_trie_path_for_real(lm, monkeypatch):
    """When the LRU lets a payload go, its spilled trie node must go too —
    the prompt becomes an honest MISS (re-prefilled bitwise) instead of a
    dangling path match would try to reinject."""
    monkeypatch.setenv('PADDLE_TPU_PREFIX_CACHE_HOST_MB', '8')
    eng = make_engine(lm)
    pc = eng.prefix_cache
    with DecodeScheduler(eng) as sched:
        cold = sched.submit(PROMPT, max_new_tokens=4).result(240)
        while pc._spill_or_evict_one():
            pass
        assert pc.spilled_blocks == 2
        pc._host.cap = 1                          # force total overflow
        other = [9] * 9
        sched.submit(other, max_new_tokens=4).result(240)
        while pc._spill_or_evict_one():
            pass
        # every spill overflowed the 1-byte cap: all host entries dropped,
        # nothing dangles
        assert pc.spilled_blocks == 0 and pc.host_bytes == 0
        assert pc.match(PROMPT) == []             # honest miss, no crash
        m0 = _counter('prefix_cache_misses')
        again = sched.submit(PROMPT, max_new_tokens=4).result(240)
    assert again == cold
    assert _counter('prefix_cache_misses') - m0 >= 1


# -- walked-path exclusion regression --------------------------------------

def test_insert_never_orphans_the_walked_path(lm):
    """Regression: a publish that hits the cap while standing on an IDLE
    cached node (refcount 1 — its request already finished) must not evict
    that node: unlinking it would attach the new child to a detached
    subtree and leak its block. With the fix, the walk's own path is
    excluded from victim selection and the publish simply stops."""
    eng = make_engine(lm, prefix_cache=False)
    pool = eng.pool
    pc = PrefixCache(pool, max_blocks=1, host_mb=0)
    bs = pool.block_size
    prefix = [5, 6, 7, 8]
    # request Q publishes the one-block prefix, then finishes
    q_blocks = pool.allocator.allocate(1)
    pc.insert(prefix, BlockTable(q_blocks, bs))
    pool.allocator.release(q_blocks)
    assert pc.resident_blocks == 1
    node_a = pc._root.children[tuple(prefix)]
    assert pool.allocator.refcount(node_a.block) == 1   # idle, evictable
    # request R (cold admission, private copies) publishes prefix + suffix:
    # chunk 2 needs a block, the cap is reached, and the only idle victim
    # is the node R's walk is standing on
    r_blocks = pool.allocator.allocate(2)
    pc.insert(prefix + [9, 10, 11, 12], BlockTable(r_blocks, bs))
    pool.allocator.release(r_blocks)
    # the walked node survived; nothing was orphaned or leaked
    assert pc._root.children[tuple(prefix)] is node_a
    assert node_a.block is not None
    assert pc.resident_blocks == len(pc.resident_block_ids()) == 1
    assert pool.allocator.used == pc.resident_blocks
    assert pc.match(prefix + [0]) == [node_a.block]
    pool.allocator.release([node_a.block])


def test_reinject_survives_host_lru_dropping_a_path_node(lm):
    """Regression: during reinjection, the pressure spill of a NON-path
    victim can overflow the host LRU, which drops entries front-first —
    possibly a LATER still-spilled node of the very path being
    reinjected (``exclude`` shields path nodes from victim selection,
    not from the byte-cap drop). match must truncate into an honest
    shorter hit, not raise KeyError, and must not leak pool blocks."""
    eng = make_engine(lm, prefix_cache=False)
    pool = eng.pool
    pc = PrefixCache(pool, max_blocks=0, host_mb=8)
    bs = pool.block_size
    pa = list(range(5, 5 + 2 * bs))               # two whole blocks
    blocks = pool.allocator.allocate(2)
    pc.insert(pa, BlockTable(blocks, bs))
    pool.allocator.release(blocks)
    # spill both: the leaf goes first (victims need no resident children),
    # so the host LRU front is pa's DEEPER node — path[1] of a future hit
    while pc._spill_or_evict_one():
        pass
    assert pc.spilled_blocks == 2
    blobs = list(pc._host._entries.values())
    assert len(set(map(len, blobs))) == 1         # one-block blobs, equal
    # two idle resident non-path nodes: pressure victims for BOTH path
    # allocations, so pre-fix the loop reaches the dropped leaf with a
    # block in hand and dies on _host.pop
    for t in (21, 22):
        b2 = pool.allocator.allocate(1)
        pc.insert([t] * bs, BlockTable(b2, bs))
        pool.allocator.release(b2)
    # cap fits exactly the two path blobs: spilling the first victim will
    # overflow and drop the LRU front (pa's leaf)
    pc._host.cap = pc.host_bytes + 1
    held = pool.allocator.allocate(pool.allocator.available)
    hit = pc.match(pa + [0])
    parent = pc._root.children[tuple(pa[:bs])]
    assert hit == [parent.block]                  # truncated, reinjected
    assert parent.block is not None
    assert tuple(pa[bs:2 * bs]) not in parent.children   # dropped for real
    assert pc.resident_blocks == 2                # parent + untouched [22]*bs
    assert pc.spilled_blocks == 1                 # the first victim's payload
    pool.allocator.release(hit)
    assert pool.allocator.used == len(held) + 2   # held + cache refs: no leak
    pool.allocator.release(held)
    pc.evict_idle()
    assert pool.allocator.used == 0
    assert pc.spilled_blocks == 0 and pc.host_bytes == 0


def test_evict_idle_drains_fully_spilled_subtrees(lm, monkeypatch):
    """Regression: evict_idle (shutdown path) only walked RESIDENT
    victims, so a fully-spilled subtree hanging off the root kept its
    payloads in host RAM forever. It must drain the host tier too."""
    monkeypatch.setenv('PADDLE_TPU_PREFIX_CACHE_HOST_MB', '8')
    eng = make_engine(lm)
    pc = eng.prefix_cache
    with DecodeScheduler(eng) as sched:
        sched.submit(PROMPT, max_new_tokens=4).result(240)
    while pc._spill_or_evict_one():
        pass
    assert pc.spilled_blocks > 0 and pc.host_bytes > 0
    pc.evict_idle()
    assert pc.resident_blocks == 0 and pc.spilled_blocks == 0
    assert pc.host_bytes == 0
    assert not pc._root.children                  # nothing dangles
    assert eng.pool.allocator.used == 0


def test_truncated_reinject_refreshes_host_lru_recency(lm):
    """A matched-but-unreinjectable (OutOfBlocks) spilled path is HOT:
    truncation must refresh its host-LRU recency so a later overflow
    drops cold entries first, not the path that just hit."""
    eng = make_engine(lm, prefix_cache=False)
    pool = eng.pool
    pc = PrefixCache(pool, max_blocks=0, host_mb=8)
    bs = pool.block_size
    pa = list(range(5, 5 + 2 * bs))
    blocks = pool.allocator.allocate(2)
    pc.insert(pa, BlockTable(blocks, bs))
    pool.allocator.release(blocks)
    b2 = pool.allocator.allocate(1)
    pc.insert([31] * bs, BlockTable(b2, bs))
    pool.allocator.release(b2)
    while pc._spill_or_evict_one():
        pass
    assert pc.spilled_blocks == 3
    pz_node = pc._root.children[tuple([31] * bs)]
    # pool exhausted with nothing evictable: the reinject truncates at
    # path[0] with OutOfBlocks and must touch pa's two spilled nodes
    held = pool.allocator.allocate(pool.allocator.available)
    m0 = _counter('prefix_cache_misses')
    assert pc.match(pa + [0]) == []               # honest miss, no crash
    assert _counter('prefix_cache_misses') - m0 == 1
    assert pc.spilled_blocks == 3                 # nothing dropped
    assert next(iter(pc._host._entries)) is pz_node   # cold entry is LRU
    pool.allocator.release(held)


# -- kill -9 drill ---------------------------------------------------------

_DRILL = r"""
import os, sys
os.environ.setdefault('JAX_PLATFORMS', 'cpu')
os.environ['PADDLE_TPU_PREFIX_CACHE_HOST_MB'] = '8'
os.environ['PADDLE_TPU_KV_DTYPE'] = 'int8'
sys.path.insert(0, sys.argv[1])
from paddle_tpu.dygraph import guard
from paddle_tpu.serving import DecodeEngine, DecodeScheduler
from paddle_tpu.serving.tier.replica import build_tiny_lm
rounds = int(sys.argv[2])
with guard():
    lm = build_tiny_lm()
    eng = DecodeEngine(lm, slots=2, block_size=4, max_blocks=64,
                       max_prompt_len=16, max_new_tokens_cap=8,
                       prefix_cache=True)
    pc = eng.prefix_cache
    for rnd in range(rounds):
        prompt = [3 + rnd % 50] * 8 + [1 + rnd % 7]
        with DecodeScheduler(eng) as sched:
            cold = sched.submit(prompt, max_new_tokens=6).result(120)
            while pc._spill_or_evict_one():
                pass
            assert pc.resident_blocks == 0
            hit = sched.submit(prompt, max_new_tokens=6).result(120)
        assert hit == cold, (rnd, hit, cold)
        assert eng.pool.allocator.used == pc.resident_blocks
        print('ROUND-OK %d' % rnd, flush=True)
"""


def _spawn_drill(tmp_path, rounds):
    script = tmp_path / 'drill.py'
    script.write_text(_DRILL)
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    return subprocess.Popen(
        [sys.executable, str(script), _REPO, str(rounds)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)


def test_kill9_mid_spill_leaves_nothing_corrupt(tmp_path):
    """The drill: a subprocess loops cold→spill-everything→hit rounds,
    printing ROUND-OK only after verifying parity and pool accounting.
    SIGKILL lands mid-round; every round that completed before it had
    already verified, and a FRESH process (the only thing that exists
    after kill -9 — the spill tier is process RAM) runs the same round
    clean. There is no persistent state to corrupt, and this drill is the
    executable proof."""
    proc = _spawn_drill(tmp_path, rounds=1000)
    try:
        seen = []
        deadline = time.monotonic() + 300
        while len(seen) < 2 and time.monotonic() < deadline:
            line = proc.stdout.readline()
            if not line:
                break
            assert line.startswith('ROUND-OK'), line
            seen.append(line.strip())
        assert seen == ['ROUND-OK 0', 'ROUND-OK 1'], (
            seen, proc.stderr.read() if proc.poll() is not None else '')
        proc.send_signal(signal.SIGKILL)          # mid-round, no cleanup
        proc.wait(timeout=60)
        assert proc.returncode == -signal.SIGKILL
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
    fresh = _spawn_drill(tmp_path, rounds=1)
    out, err = fresh.communicate(timeout=300)
    assert fresh.returncode == 0, err[-3000:]
    assert 'ROUND-OK 0' in out
