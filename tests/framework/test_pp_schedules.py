"""Schedule-aware pipeline lowering (ISSUE 20): 1F1B and interleaved next
to GPipe, driven by the PADDLE_TPU_PP_SCHEDULE / PADDLE_TPU_PP_MICROBATCHES
knobs (strict-parse, env wins over the stamped dist_strategy), the
cost-model auto-cut + budget-driven microbatch solve, the staged planner's
peak-residency prediction, and the lifted pipeline+sparse restriction.

The load-bearing claim: 1F1B is the SAME arithmetic as the GPipe scan —
one backward per microbatch in reverse order against the same
constant-cotangent seed — so its loss/param trajectory must be BITWISE
identical, not merely close. Interleaved reassociates the wave loop, so
it matches at float tolerance."""
import os

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.analysis.stage import (plan_staged_program,
                                       solve_microbatches,
                                       solve_stage_cuts,
                                       stage_cut_candidates)
from paddle_tpu.core.scope import global_scope
from paddle_tpu.partition.pipeline import (PP_SCHEDULES, pp_microbatches,
                                           pp_schedule)


def _trajectory(schedule, monkeypatch, steps=5, n_micro=4):
    """Non-uniform 2-stage pipeline (scan lowering) under `schedule`;
    returns (losses, params) after `steps` SGD steps. Fresh unique-name
    generator + scope so the two builds are name-identical."""
    import paddle_tpu.core.scope as sm
    from paddle_tpu.core import unique_name
    from paddle_tpu.core.scope import Scope
    if schedule is None:
        monkeypatch.delenv('PADDLE_TPU_PP_SCHEDULE', raising=False)
    else:
        monkeypatch.setenv('PADDLE_TPU_PP_SCHEDULE', schedule)
    unique_name.generator = unique_name.UniqueNameGenerator()
    monkeypatch.setattr(sm, '_global_scope', Scope())
    main, start = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, start):
        fluid.framework.manual_seed(11)
        x = layers.data('x', [16], dtype='float32')
        y = layers.data('y', [1], dtype='float32')
        h1 = layers.fc(x, size=32, act='tanh')
        h2 = layers.fc(h1, size=8, act='tanh')
        s = layers.reduce_sum(h2, dim=1, keep_dim=True)
        loss = layers.reduce_mean(layers.square_error_cost(s, y))
        fluid.optimizer.PipelineOptimizer(
            fluid.optimizer.SGD(learning_rate=0.05), cut_list=[h1],
            num_microbatches=n_micro).minimize(loss)
    exe = fluid.Executor()
    exe.run(start)
    rng = np.random.RandomState(0)
    out = []
    for _ in range(steps):
        xv = rng.standard_normal((8, 16)).astype(np.float32)
        l, = exe.run(main, feed={'x': xv, 'y': xv[:, :1]},
                     fetch_list=[loss])
        out.append(np.asarray(l))
    params = {v.name: np.asarray(global_scope().find(v.name))
              for v in main.all_parameters()}
    return out, params


def test_1f1b_bitwise_matches_gpipe_scan(monkeypatch):
    base_l, base_p = _trajectory(None, monkeypatch)       # stamped gpipe
    got_l, got_p = _trajectory('1f1b', monkeypatch)
    for a, b in zip(got_l, base_l):
        assert a.tobytes() == b.tobytes()
    for n in base_p:
        assert got_p[n].tobytes() == base_p[n].tobytes(), n


def test_interleaved_matches_at_tolerance(monkeypatch):
    base_l, base_p = _trajectory(None, monkeypatch)
    got_l, got_p = _trajectory('interleaved', monkeypatch)
    np.testing.assert_allclose(np.ravel(got_l), np.ravel(base_l),
                               rtol=2e-4, atol=1e-5)
    for n in base_p:
        np.testing.assert_allclose(got_p[n], base_p[n],
                                   rtol=2e-4, atol=1e-5)


def test_schedule_knob_strict_parse(monkeypatch):
    monkeypatch.setenv('PADDLE_TPU_PP_SCHEDULE', 'pipedream')
    with pytest.raises(ValueError) as ei:
        pp_schedule()
    for name in PP_SCHEDULES:
        assert name in str(ei.value)
    monkeypatch.delenv('PADDLE_TPU_PP_SCHEDULE')
    with pytest.raises(ValueError):
        pp_schedule('bogus-default')
    assert pp_schedule('1f1b') == '1f1b'


def test_microbatch_knob_strict_parse(monkeypatch):
    monkeypatch.setenv('PADDLE_TPU_PP_MICROBATCHES', 'four')
    with pytest.raises(ValueError, match='positive integer'):
        pp_microbatches()
    monkeypatch.setenv('PADDLE_TPU_PP_MICROBATCHES', '-2')
    with pytest.raises(ValueError, match='> 0'):
        pp_microbatches()
    monkeypatch.setenv('PADDLE_TPU_PP_MICROBATCHES', '8')
    assert pp_microbatches(4) == 8          # env wins over the marker


def test_env_overrides_stamped_microbatches(monkeypatch):
    """PADDLE_TPU_PP_MICROBATCHES beats the stamped count at lowering."""
    from paddle_tpu.executor import _pipeline_plan
    from paddle_tpu.framework import BACKWARD_OP_TYPE
    monkeypatch.setenv('PADDLE_TPU_PP_MICROBATCHES', '2')
    main, start = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, start):
        fluid.framework.manual_seed(1)
        x = layers.data('x', [16], dtype='float32')
        y = layers.data('y', [1], dtype='float32')
        h1 = layers.fc(x, size=8, act='tanh')
        pred = layers.fc(h1, size=1)
        loss = layers.reduce_mean(layers.square_error_cost(pred, y))
        fluid.optimizer.PipelineOptimizer(
            fluid.optimizer.SGD(learning_rate=0.05), cut_list=[h1],
            num_microbatches=4).minimize(loss)
    ops = main.global_block().ops
    bwd = next(i for i, o in enumerate(ops) if o.type == BACKWARD_OP_TYPE)
    state_names = [v.name for v in main.list_vars() if v.persistable]
    plan = _pipeline_plan(main, ops[:bwd], ops[bwd], ['x', 'y'],
                          state_names)
    assert plan['m'] == 2, plan


def test_pipeline_optimizer_arg_validation():
    sgd = fluid.optimizer.SGD(learning_rate=0.05)
    with pytest.raises(ValueError, match='schedule'):
        fluid.optimizer.PipelineOptimizer(sgd, schedule='pipedream')
    with pytest.raises(ValueError, match='num_stages'):
        fluid.optimizer.PipelineOptimizer(sgd, num_stages=1)


def test_auto_cut_and_budget_microbatch_solve(monkeypatch):
    """num_stages + num_microbatches='auto': the optimizer auto-cuts via
    the cost model, stamps m=0, and the executor solves the smallest m
    fitting PADDLE_TPU_HBM_BUDGET_MB at lowering — then runs."""
    from paddle_tpu.executor import _pipeline_plan
    from paddle_tpu.framework import BACKWARD_OP_TYPE
    monkeypatch.setenv('PADDLE_TPU_HBM_BUDGET_MB', '48')
    monkeypatch.delenv('PADDLE_TPU_PP_SCHEDULE', raising=False)
    monkeypatch.delenv('PADDLE_TPU_PP_MICROBATCHES', raising=False)
    main, start = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, start):
        fluid.framework.manual_seed(3)
        x = layers.data('x', [256], dtype='float32')
        y = layers.data('y', [1], dtype='float32')
        h = x
        for _ in range(6):
            h = layers.fc(h, size=256, act='tanh')
        s = layers.reduce_sum(h, dim=1, keep_dim=True)
        loss = layers.reduce_mean(layers.square_error_cost(s, y))
        fluid.optimizer.PipelineOptimizer(
            fluid.optimizer.SGD(learning_rate=0.05), num_stages=2,
            schedule='1f1b', num_microbatches='auto').minimize(loss)
    ops = main.global_block().ops
    bwd = next(i for i, o in enumerate(ops) if o.type == BACKWARD_OP_TYPE)
    marker = ops[bwd]
    pipe = marker.attrs['pipeline']
    assert len(pipe['cut_vars']) == 1       # auto-cut picked a boundary
    assert pipe['num_microbatches'] == 0    # the auto sentinel
    state_names = [v.name for v in main.list_vars() if v.persistable]
    plan = _pipeline_plan(main, ops[:bwd], marker, ['x', 'y'], state_names,
                          fetch_names=(loss.name,),
                          feed_shapes={'x': (64, 256), 'y': (64, 1)})
    assert plan['schedule'] == '1f1b' and plan['m'] >= 2, plan
    exe = fluid.Executor()
    exe.run(start)
    xv = np.random.RandomState(0).standard_normal((64, 256)) \
        .astype(np.float32)
    l, = exe.run(main, feed={'x': xv, 'y': xv[:, :1]}, fetch_list=[loss])
    assert np.isfinite(np.asarray(l)).all()


def test_dist_strategy_pipeline_stamp():
    """DistributedStrategy pp knobs flow through DistributedOptimizer
    into the marker stamp (auto-cut; schedule + m recorded)."""
    from paddle_tpu.framework import BACKWARD_OP_TYPE
    from paddle_tpu.parallel import (DistributedOptimizer,
                                     DistributedStrategy)
    main, start = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, start):
        fluid.framework.manual_seed(3)
        x = layers.data('x', [16], dtype='float32')
        y = layers.data('y', [1], dtype='float32')
        h1 = layers.fc(x, size=32, act='tanh')
        h2 = layers.fc(h1, size=16, act='tanh')
        h3 = layers.fc(h2, size=8, act='tanh')
        s = layers.reduce_sum(h3, dim=1, keep_dim=True)
        loss = layers.reduce_mean(layers.square_error_cost(s, y))
        strat = DistributedStrategy()
        strat.pipeline_stages = 2
        strat.pp_schedule = '1f1b'
        strat.pp_microbatches = 4
        DistributedOptimizer(fluid.optimizer.SGD(learning_rate=0.05),
                             strat).minimize(loss)
    marker = next(op for op in reversed(main.global_block().ops)
                  if op.type == BACKWARD_OP_TYPE)
    pipe = marker.attrs['pipeline']
    assert pipe['schedule'] == '1f1b'
    assert pipe['num_microbatches'] == 4
    assert len(pipe['cut_vars']) == 1
    exe = fluid.Executor()
    exe.run(start)
    xv = np.random.RandomState(0).standard_normal((8, 16)) \
        .astype(np.float32)
    l, = exe.run(main, feed={'x': xv, 'y': xv[:, :1]}, fetch_list=[loss])
    assert np.isfinite(np.asarray(l)).all()


def test_dist_strategy_pp_setters_strict():
    from paddle_tpu.parallel import DistributedStrategy
    s = DistributedStrategy()
    with pytest.raises(ValueError):
        s.pp_schedule = 'bogus'
    with pytest.raises(ValueError):
        s.pipeline_stages = 1
    with pytest.raises(ValueError):
        s.pp_microbatches = -1
    s.pp_microbatches = 'auto'              # the sentinel is legal
    with pytest.raises(ValueError, match='pipeline_stages'):
        # schedule without a stage count cannot be stamped
        from paddle_tpu.parallel import DistributedOptimizer
        main, start = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, start):
            x = layers.data('x', [4], dtype='float32')
            y = layers.data('y', [1], dtype='float32')
            pred = layers.fc(x, size=1)
            loss = layers.reduce_mean(layers.square_error_cost(pred, y))
            st = DistributedStrategy()
            st.pp_schedule = '1f1b'
            DistributedOptimizer(fluid.optimizer.SGD(learning_rate=0.1),
                                 st).minimize(loss)


def _sparse_pipeline_losses(pipelined, schedule, monkeypatch):
    """DeepFM-style sparse embedding recipe, optionally pipelined —
    previously `NotImplementedError: pipeline + sparse`."""
    import paddle_tpu.core.scope as sm
    from paddle_tpu.core import unique_name
    from paddle_tpu.core.random import default_generator
    from paddle_tpu.core.scope import Scope
    if schedule is None:
        monkeypatch.delenv('PADDLE_TPU_PP_SCHEDULE', raising=False)
    else:
        monkeypatch.setenv('PADDLE_TPU_PP_SCHEDULE', schedule)
    unique_name.generator = unique_name.UniqueNameGenerator()
    default_generator.seed(42)
    V = 40
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = layers.data('ids', [5], dtype='int64')
        label = layers.data('label', [1], dtype='float32')
        emb = layers.embedding(ids, size=[V, 16], is_sparse=True)
        h = layers.fc(emb, size=8, act='relu')
        h2 = layers.fc(h, size=8, act='relu')
        out = layers.fc(h2, size=1)
        loss = layers.reduce_mean(layers.square_error_cost(out, label))
        sgd = fluid.optimizer.SGD(learning_rate=0.1)
        if pipelined:
            fluid.optimizer.PipelineOptimizer(
                sgd, cut_list=[h], num_microbatches=2).minimize(loss)
        else:
            sgd.minimize(loss)
    exe = fluid.Executor()
    old = sm._global_scope
    sm._global_scope = Scope()
    try:
        exe.run(startup)
        rng = np.random.RandomState(0)
        losses = []
        for _ in range(5):
            f = {'ids': rng.randint(0, V, (4, 5)).astype(np.int64),
                 'label': rng.rand(4, 1).astype(np.float32)}
            l, = exe.run(main, feed=f, fetch_list=[loss])
            losses.append(float(np.asarray(l).reshape(())[()]))
        params = {v.name: np.asarray(sm._global_scope.find(v.name))
                  for v in main.all_parameters()}
        return losses, params
    finally:
        sm._global_scope = old


@pytest.mark.parametrize('schedule', [None, '1f1b'])
def test_pipeline_sparse_restriction_lifted(schedule, monkeypatch):
    """Sparse embedding + pipeline runs (scan and 1F1B lowering) and
    matches the unpipelined sparse trajectory — the site-surrogate
    slices ride the microbatch scan."""
    lp, tp_ = _sparse_pipeline_losses(True, schedule, monkeypatch)
    ln, tn = _sparse_pipeline_losses(False, None, monkeypatch)
    np.testing.assert_allclose(lp, ln, rtol=2e-4, atol=1e-5)
    for n in tn:
        np.testing.assert_allclose(tp_[n], tn[n], rtol=2e-4, atol=1e-5)


def test_staged_planner_1f1b_peak_below_gpipe():
    """The liveness walk extended to staged programs: on an
    activation-heavy program 1F1B's predicted host peak (one wave of
    residuals) is below GPipe's (all m waves)."""
    main, start = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, start):
        fluid.framework.manual_seed(5)
        x = layers.data('x', [128], dtype='float32')
        y = layers.data('y', [1], dtype='float32')
        h = x
        for _ in range(6):
            h = layers.fc(h, size=128, act='relu')
        pred = layers.fc(h, size=1)
        loss = layers.reduce_mean(layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    cuts, report = solve_stage_cuts(main, 2, fetch_names=(loss.name,),
                                    feed_names=('x', 'y'), assume_dim=32)
    assert len(cuts) == 1 and report['balance'] < 2.0
    kw = dict(fetch_names=(loss.name,), feed_names=('x', 'y'),
              assume_dim=32)
    g = plan_staged_program(main, cuts, 8, schedule='gpipe', **kw)
    f = plan_staged_program(main, cuts, 8, schedule='1f1b', **kw)
    assert f.host_peak_bytes < g.host_peak_bytes
    # more microbatches shrink the 1F1B peak further, leave GPipe flat
    f16 = plan_staged_program(main, cuts, 16, schedule='1f1b', **kw)
    g16 = plan_staged_program(main, cuts, 16, schedule='gpipe', **kw)
    assert f16.host_peak_bytes < f.host_peak_bytes
    assert abs(g16.host_peak_bytes - g.host_peak_bytes) \
        <= 0.02 * g.host_peak_bytes
    # the budget solve lands on a count whose predicted peak fits
    budget = (f.host_peak_bytes + f16.host_peak_bytes) // 2
    m, peak, fits = solve_microbatches(main, cuts, '1f1b', budget, **kw)
    assert fits and peak <= budget and m == 16
    # auto-cut candidates cover the boundary set the solver used
    cands = stage_cut_candidates(main, **kw)
    assert cuts[0] in cands and len(cands) >= 2


def test_parallel_pipeline_shim_delegates():
    """The retired parallel.pipeline.gpipe warns once (through the
    warn_once registry — repo invariant: never print) and delegates to
    partition.pipeline (bitwise — same code, new home)."""
    import jax.numpy as jnp

    from paddle_tpu.parallel import pipeline as shim
    from paddle_tpu.parallel.mesh import make_mesh
    from paddle_tpu.partition import pipeline as owned
    from paddle_tpu.partition.partitioner import _DEPRECATION_WARNED
    assert shim.gpipe is not owned.gpipe        # wrapper, not alias
    assert shim.stack_stage_params is owned.stack_stage_params
    mesh = make_mesh({'pp': 2})
    rng = np.random.RandomState(0)
    W = jnp.asarray(rng.randn(2, 8, 8).astype(np.float32))
    xm = jnp.asarray(rng.randn(4, 2, 8).astype(np.float32))
    _DEPRECATION_WARNED.discard('parallel.pipeline.gpipe')
    old = shim.gpipe(lambda p, h: jnp.tanh(h @ p), W, xm, mesh=mesh)
    assert 'parallel.pipeline.gpipe' in _DEPRECATION_WARNED
    new = owned.gpipe(lambda p, h: jnp.tanh(h @ p), W, xm, mesh=mesh)
    assert np.array_equal(np.asarray(old), np.asarray(new))
