"""Stateful decode engine (paddle_tpu/serving/decode/): bitwise parity vs
uncached whole-sequence decode, bounded compile counts, continuous-batching
slot admission, KV-block lifecycle, deadlines/backpressure/drain, streaming
HTTP /generate, and the always-on decode_* metrics."""
import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from paddle_tpu import profiler
from paddle_tpu.dygraph import guard
from paddle_tpu.models.causal_lm import (CausalLMConfig, TransformerLM,
                                         greedy_generate)
from paddle_tpu.serving import (DeadlineExceeded, DecodeEngine,
                                DecodeScheduler, EngineClosed,
                                InvalidRequest, Overloaded, OutOfBlocks,
                                ServingServer)
from paddle_tpu.serving.decode.kv_cache import BlockAllocator


@pytest.fixture(scope='module')
def lm():
    with guard():
        model = TransformerLM(CausalLMConfig.tiny())
        model.eval()
        yield model


def make_engine(model, **kw):
    kw.setdefault('slots', 4)
    kw.setdefault('block_size', 4)
    kw.setdefault('max_blocks', 64)
    kw.setdefault('max_prompt_len', 16)
    kw.setdefault('max_new_tokens_cap', 16)
    return DecodeEngine(model, **kw)


def _counter(name):
    from paddle_tpu.observability import registry
    d = registry.to_dict().get(name)
    if not d or not d['samples']:
        return 0.0
    return sum(s['value'] for s in d['samples'])


# -- parity ----------------------------------------------------------------

def test_streamed_generation_bitwise_equals_uncached(lm):
    """The acceptance bar: ragged concurrent generations through the
    continuous-batching scheduler produce EXACTLY the uncached
    whole-sequence greedy tokens, per request."""
    eng = make_engine(lm)
    rng = np.random.RandomState(0)
    prompts = [list(map(int, rng.randint(3, 100, n)))
               for n in (3, 7, 12, 5, 9, 1, 16)]
    budgets = [10, 4, 16, 7, 12, 16, 2]
    refs = [greedy_generate(lm, p, m, pad_len=eng.padded_context)
            for p, m in zip(prompts, budgets)]
    with DecodeScheduler(eng) as sched:
        streams = [sched.submit(p, max_new_tokens=m)
                   for p, m in zip(prompts, budgets)]
        outs = [s.result(120) for s in streams]
    assert outs == refs
    for s in streams:
        assert s.finish_reason == 'length'


def test_eos_stops_generation_early(lm):
    eng = make_engine(lm)
    prompt = [5, 9, 2, 44]
    ref = greedy_generate(lm, prompt, 8, pad_len=eng.padded_context)
    eos = ref[0]                       # greedy will emit it immediately
    with DecodeScheduler(eng) as sched:
        s = sched.submit(prompt, max_new_tokens=8, eos_id=eos)
        assert s.result(60) == [eos]
        assert s.finish_reason == 'stop'


def test_stream_iterates_tokens_incrementally(lm):
    eng = make_engine(lm)
    prompt = [7, 3, 11]
    ref = greedy_generate(lm, prompt, 6, pad_len=eng.padded_context)
    with DecodeScheduler(eng) as sched:
        s = sched.submit(prompt, max_new_tokens=6)
        got = [t for t in s.iter_tokens(timeout=60)]
    assert got == ref
    assert s.tokens == ref and s.done()


# -- compile-count bounds --------------------------------------------------

def test_decode_compile_count_independent_of_generated_length(lm):
    """One prefill compile per bucket + one decode-step compile: after
    warmup, generations of ANY length and prompt bucket add ZERO eager
    kernel-cache misses."""
    eng = make_engine(lm)
    eng.warmup()
    profiler.reset_eager_kernel_cache_stats()
    rng = np.random.RandomState(1)
    with DecodeScheduler(eng) as sched:
        outs = [sched.submit(list(map(int, rng.randint(3, 100, n))),
                             max_new_tokens=m).result(120)
                for n, m in ((3, 4), (9, 14), (15, 16), (2, 2), (16, 9))]
    assert all(len(o) for o in outs)
    stats = profiler.eager_kernel_cache_stats()
    assert stats['misses'] == 0, stats
    assert stats['hits'] > 0


def test_prefill_compiles_bounded_by_bucket_ladder(lm):
    """A fresh engine compiles at most len(prompt_buckets) prefill shapes
    plus one decode-step shape — tracked by the decode_prefill_compiles
    counter regardless of how many requests run."""
    eng = make_engine(lm)
    before = _counter('decode_prefill_compiles')
    with DecodeScheduler(eng) as sched:
        for n in (1, 2, 3, 5, 9, 13, 2, 7, 16):
            sched.submit([1] * n, max_new_tokens=2).result(120)
    compiled = _counter('decode_prefill_compiles') - before
    assert 0 < compiled <= len(eng.prompt_buckets)


# -- continuous batching ---------------------------------------------------

def test_continuous_admission_uses_fewer_steps_than_drain(lm):
    """Admit-into-freed-slots must step less than drain-then-refill on a
    mixed workload (the bench's acceptance ratio, asserted structurally
    here via the decode_steps counter)."""
    work = [([3, 5], 16), ([7, 2], 2), ([9, 9], 2), ([4, 1], 2),
            ([8, 8], 16), ([6, 2], 2), ([5, 5], 2), ([2, 9], 2)]

    def run(admission):
        eng = make_engine(lm, slots=2)
        before = _counter('decode_steps')
        with DecodeScheduler(eng, admission=admission) as sched:
            streams = [sched.submit(p, max_new_tokens=m) for p, m in work]
            outs = [s.result(120) for s in streams]
        assert all(len(o) == m for o, (_, m) in zip(outs, work))
        return _counter('decode_steps') - before, outs

    steps_cont, outs_cont = run('continuous')
    steps_drain, outs_drain = run('drain')
    assert outs_cont == outs_drain          # policy changes speed, not math
    assert steps_cont < steps_drain, (steps_cont, steps_drain)


def test_short_request_admitted_into_freed_slot_finishes_first(lm):
    """With one slot-hogging long generation and S=2, later short requests
    flow through the second slot and complete while the long one is still
    decoding — the defining continuous-batching observable."""
    eng = make_engine(lm, slots=2)
    with DecodeScheduler(eng) as sched:
        long_s = sched.submit([3, 5, 7], max_new_tokens=16)
        shorts = [sched.submit([9, 2], max_new_tokens=2) for _ in range(3)]
        for s in shorts:
            s.result(120)
        assert not long_s.done(), \
            'short requests should finish while the long one decodes'
        long_s.result(120)


# -- KV-block lifecycle ----------------------------------------------------

def test_block_allocator_free_list_reuse_and_double_free():
    alloc = BlockAllocator(8)
    assert alloc.capacity == 7
    a = alloc.allocate(3)
    b = alloc.allocate(4)
    assert alloc.available == 0 and 0 not in a + b
    with pytest.raises(OutOfBlocks):
        alloc.allocate(1)
    alloc.free(a)
    c = alloc.allocate(3)
    assert sorted(c) == sorted(a)           # free list recycles
    with pytest.raises(ValueError):
        alloc.free(b + b[:1])               # double free detected
    with pytest.raises(ValueError):
        alloc.free([0])                     # scratch is untouchable


def test_blocks_released_at_completion_and_metrics(lm):
    from paddle_tpu.observability import registry
    eng = make_engine(lm)
    assert eng.pool.allocator.used == 0
    with DecodeScheduler(eng) as sched:
        sched.submit([1, 2, 3], max_new_tokens=4).result(120)
        sched.submit([1] * 10, max_new_tokens=8).result(120)
    assert eng.pool.allocator.used == 0, 'completed requests leak blocks'
    d = registry.to_dict()
    for name in ('decode_slots_total', 'decode_cache_blocks_total',
                 'decode_cache_blocks_used', 'decode_tokens_generated',
                 'decode_prefill_seconds', 'decode_step_seconds',
                 'decode_slot_occupancy'):
        assert name in d, f'missing decode metric {name}'


def test_pool_exhaustion_defers_admission_not_failure(lm):
    """A pool that can only hold one request at a time still serves a
    backlog FIFO — OutOfBlocks defers admission until blocks free."""
    # each request reserves ceil((2+14)/4)=4 blocks; pool holds 5 usable
    eng = make_engine(lm, slots=4, max_blocks=6, max_prompt_len=2,
                      max_new_tokens_cap=14, block_size=4)
    with DecodeScheduler(eng) as sched:
        streams = [sched.submit([1, 2], max_new_tokens=14)
                   for _ in range(3)]
        outs = [s.result(240) for s in streams]
    assert all(len(o) == 14 for o in outs)
    assert eng.pool.allocator.used == 0


# -- validation / backpressure / deadlines / shutdown ----------------------

def test_validation_rejects_bad_requests(lm):
    eng = make_engine(lm)
    with DecodeScheduler(eng) as sched:
        with pytest.raises(InvalidRequest):
            sched.submit([], max_new_tokens=4)
        with pytest.raises(InvalidRequest):
            sched.submit([1] * 99, max_new_tokens=4)      # prompt too long
        with pytest.raises(InvalidRequest):
            sched.submit([1, 2], max_new_tokens=0)
        with pytest.raises(InvalidRequest):
            sched.submit([1, 2], max_new_tokens=999)      # over the cap
        with pytest.raises(InvalidRequest):
            sched.submit(['a', 'b'], max_new_tokens=4)


def test_overload_backpressure(lm):
    eng = make_engine(lm, slots=1)
    with DecodeScheduler(eng, queue_depth=1, start=False) as sched:
        sched.submit([1, 2], max_new_tokens=2)            # queued
        with pytest.raises(Overloaded):
            sched.submit([3, 4], max_new_tokens=2)        # queue full
        sched._worker.start()


def test_waiting_deadline_expires(lm):
    eng = make_engine(lm, slots=1)
    with DecodeScheduler(eng) as sched:
        long_s = sched.submit([1, 2, 3], max_new_tokens=16)
        late = sched.submit([4, 5], max_new_tokens=2, timeout_ms=1)
        with pytest.raises(DeadlineExceeded):
            late.result(120)
        assert len(long_s.result(120)) == 16              # unharmed


def test_close_drain_completes_everything(lm):
    eng = make_engine(lm, slots=2)
    sched = DecodeScheduler(eng)
    streams = [sched.submit([1, 2], max_new_tokens=6) for _ in range(5)]
    sched.close(drain=True)
    assert all(len(s.result(1)) == 6 for s in streams)
    with pytest.raises(EngineClosed):
        sched.submit([1], max_new_tokens=2)
    assert eng.pool.allocator.used == 0


def test_close_fail_fast_errors_streams(lm):
    eng = make_engine(lm, slots=1)
    sched = DecodeScheduler(eng)
    streams = [sched.submit([1, 2, 3], max_new_tokens=16)
               for _ in range(3)]
    sched.close(drain=False)
    failures = 0
    for s in streams:
        try:
            s.result(5)
        except EngineClosed:
            failures += 1
    assert failures >= 2, 'waiting/in-flight requests must fail fast'
    assert eng.pool.allocator.used == 0


def test_engine_failure_isolated_to_batch(lm):
    """A decode-step blowup fails the in-flight generations with a typed
    error; the scheduler worker survives and serves the next request."""
    eng = make_engine(lm, slots=2)
    boom = {'armed': False}
    real_step = eng.decode_step

    def flaky_step(tokens, tables):
        if boom['armed']:
            boom['armed'] = False
            raise RuntimeError('injected device failure')
        return real_step(tokens, tables)

    eng.decode_step = flaky_step
    from paddle_tpu.serving.errors import ServingError
    with DecodeScheduler(eng) as sched:
        boom['armed'] = True
        s1 = sched.submit([1, 2], max_new_tokens=4)
        with pytest.raises(ServingError):
            s1.result(120)
        s2 = sched.submit([3, 4], max_new_tokens=3)
        assert len(s2.result(120)) == 3
    assert eng.pool.allocator.used == 0


# -- HTTP front end --------------------------------------------------------

def test_http_generate_streaming_e2e(lm):
    eng = make_engine(lm)
    ref = greedy_generate(lm, [5, 9, 2, 44], 8, pad_len=eng.padded_context)
    sched = DecodeScheduler(eng)
    srv = ServingServer(None, port=0, generator=sched).start()
    url = f'http://127.0.0.1:{srv.port}'
    try:
        # healthz exposes decode state
        health = json.load(urllib.request.urlopen(url + '/healthz'))
        assert health['decode']['slots'] == eng.slots
        # streaming: chunked NDJSON, one line per token + a final summary
        req = urllib.request.Request(
            url + '/generate',
            data=json.dumps({'prompt': [5, 9, 2, 44],
                             'max_new_tokens': 8}).encode())
        lines = [json.loads(ln) for ln in
                 urllib.request.urlopen(req).read().splitlines()]
        toks = [ln['token'] for ln in lines if 'token' in ln]
        assert toks == ref
        assert lines[-1]['done'] is True
        assert lines[-1]['tokens'] == ref
        assert lines[-1]['finish_reason'] == 'length'
        # non-streaming mode
        req = urllib.request.Request(
            url + '/generate',
            data=json.dumps({'prompt': [5, 9, 2, 44], 'max_new_tokens': 8,
                             'stream': False}).encode())
        body = json.load(urllib.request.urlopen(req))
        assert body['tokens'] == ref
        # validation maps to 400
        req = urllib.request.Request(url + '/generate',
                                     data=json.dumps({'prompt': []}).encode())
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req)
        assert ei.value.code == 400
        # decode metrics are scrape-able without telemetry
        prom = urllib.request.urlopen(url + '/metrics').read().decode()
        assert 'paddle_tpu_decode_tokens_generated' in prom
        assert 'paddle_tpu_decode_slot_occupancy' in prom
    finally:
        srv.shutdown()


def test_http_predict_404_on_decode_only_server(lm):
    eng = make_engine(lm)
    sched = DecodeScheduler(eng)
    srv = ServingServer(None, port=0, generator=sched).start()
    try:
        req = urllib.request.Request(
            f'http://127.0.0.1:{srv.port}/predict',
            data=json.dumps({'inputs': {'x': [[1.0]]}}).encode())
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req)
        assert ei.value.code == 404
    finally:
        srv.shutdown()
