"""Distributed-observability unit drills (ISSUE 17): trace-context header
round-trips, exact histogram percentiles, windowed series, fleet metric
merge semantics, the SLO monitor, the straggler monitor on synthetic
fleets, and the ``trace_merge --smoke`` tier-1 gate."""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from paddle_tpu import observability as obs
from paddle_tpu.observability import distributed as dobs
from paddle_tpu.observability.trace_context import (
    ENV_TRACE_DIR, ENV_TRACE_SAMPLE, TRACE_HEADER, TraceContext,
    maybe_sample)

_REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    """Empty registry/series/recorder and no trace env around each test."""
    for env in (ENV_TRACE_DIR, ENV_TRACE_SAMPLE, dobs.ENV_SLO):
        monkeypatch.delenv(env, raising=False)
    obs.reset()
    yield
    obs.reset()


# ---------------------------------------------------------------------------
# trace context
# ---------------------------------------------------------------------------

def test_trace_context_header_roundtrip():
    root = TraceContext.root()
    child = root.child()
    assert child.trace_id == root.trace_id
    assert child.parent_span_id == root.span_id
    assert child.span_id != root.span_id

    headers = child.to_headers()
    assert set(headers) == {TRACE_HEADER}
    back = TraceContext.from_headers(headers)
    assert (back.trace_id, back.span_id, back.sampled) == (
        child.trace_id, child.span_id, True)
    # a replica's spans hang off the id it RECEIVED, not a fresh root
    assert back.child().parent_span_id == child.span_id


@pytest.mark.parametrize('bad', [
    'nonsense', 'aaa-bbb-1', 'g' * 16 + '-' + 'a' * 16 + '-1',
    'a' * 16 + '-' + 'b' * 16 + '-7', 'a' * 16 + '-' + 'b' * 16,
])
def test_trace_context_malformed_header_raises(bad):
    with pytest.raises(ValueError):
        TraceContext.from_header_value(bad)
    assert TraceContext.from_headers({}) is None


def test_maybe_sample_respects_rate_env(monkeypatch):
    monkeypatch.delenv(ENV_TRACE_SAMPLE, raising=False)
    assert maybe_sample() is None            # default: tracing off
    monkeypatch.setenv(ENV_TRACE_SAMPLE, '0')
    assert maybe_sample() is None
    monkeypatch.setenv(ENV_TRACE_SAMPLE, '1')
    ctx = maybe_sample()
    assert ctx is not None and ctx.sampled
    monkeypatch.setenv(ENV_TRACE_SAMPLE, 'lots')
    with pytest.raises(ValueError, match='PADDLE_TPU_TRACE_SAMPLE'):
        maybe_sample()
    monkeypatch.setenv(ENV_TRACE_SAMPLE, '1.5')
    with pytest.raises(ValueError, match='PADDLE_TPU_TRACE_SAMPLE'):
        maybe_sample()


# ---------------------------------------------------------------------------
# exact histogram percentiles (satellite b)
# ---------------------------------------------------------------------------

def test_histogram_percentile_matches_numpy_exactly():
    """The bounded sample ring gives EXACT percentiles (not bucket upper
    bounds) while the ring is not full — numpy 'linear' convention."""
    h = obs.registry.histogram('pct_drill', 'x', bounds=(0.1, 1, 10))
    rng = np.random.RandomState(7)
    values = rng.lognormal(mean=-2.0, sigma=1.0, size=400)
    for v in values:
        h.observe(float(v))
    for q in (0, 25, 50, 90, 99, 100):
        assert h.percentile(q) == pytest.approx(
            float(np.percentile(values, q)), rel=1e-12)
    # and the export carries the retained ring for offline analysis
    sample = h.labels().sample()
    assert len(sample['recent']) == 400
    assert sample['recent'] == sorted(sample['recent'])


def test_histogram_percentile_ring_keeps_recent_tail():
    from paddle_tpu.observability.metrics import RECENT_SAMPLES
    h = obs.registry.histogram('pct_ring', 'x', bounds=(1,))
    for _ in range(RECENT_SAMPLES):
        h.observe(1000.0)                    # old regime
    for _ in range(RECENT_SAMPLES):
        h.observe(1.0)                       # new regime displaces it
    assert h.percentile(50) == pytest.approx(1.0)
    assert h.percentile(100) == pytest.approx(1.0)


# ---------------------------------------------------------------------------
# windowed series
# ---------------------------------------------------------------------------

def test_windowed_series_percentile_rate_and_mean():
    s = dobs.WindowedSeries('drill', window_s=1.0, windows=4)
    for i in range(101):
        s.observe(float(i), now=100.0 + i * 0.01)   # 101 obs in ~1s
    now = 100.0 + 1.01
    assert s.percentile(50, now=now) == pytest.approx(50.0)
    assert s.percentile(99, now=now) == pytest.approx(99.0)
    assert s.mean(now=now) == pytest.approx(50.0)
    assert s.rate(now=now) == pytest.approx(101 / 1.01, rel=0.02)
    assert s.count(now=now) == 101


def test_windowed_series_slides_old_data_out():
    s = dobs.WindowedSeries('slide', window_s=1.0, windows=2)
    s.observe(100.0, now=10.0)               # will age out: ring holds
    s.observe(1.0, now=20.0)                 # 2 windows + current
    assert s.percentile(99, now=20.5) == pytest.approx(1.0)
    assert s.count(now=20.5) == 1


def test_series_registry_shared_and_reset():
    dobs.series('shared').observe(3.0)
    assert dobs.series('shared').count() == 1
    snap = dobs.series_snapshot()
    assert snap['shared']['count'] == 1
    dobs.reset_distributed()
    assert dobs.series('shared').count() == 0


# ---------------------------------------------------------------------------
# fleet metric merge semantics (tentpole: cross-host aggregation)
# ---------------------------------------------------------------------------

_SCRAPE_A = """\
# HELP reqs total requests
# TYPE reqs counter
reqs{route="gen"} 3
# TYPE occupancy gauge
occupancy 0.25
# TYPE lat histogram
lat_bucket{le="0.1"} 1
lat_bucket{le="1"} 2
lat_bucket{le="+Inf"} 2
lat_sum 0.6
lat_count 2
"""

_SCRAPE_B = """\
# TYPE reqs counter
reqs{route="gen"} 5
reqs{route="health"} 1
# TYPE occupancy gauge
occupancy 0.75
# TYPE lat histogram
lat_bucket{le="0.1"} 0
lat_bucket{le="1"} 4
lat_bucket{le="+Inf"} 5
lat_sum 7.5
lat_count 5
"""


def _samples(parsed, family):
    return {(name, tuple(sorted(labels.items()))): value
            for name, labels, value in parsed[family]['samples']}


def test_merge_fleet_metrics_counter_gauge_histogram():
    text = dobs.merge_fleet_metrics([('r0', _SCRAPE_A), ('r1', _SCRAPE_B)])
    parsed = dobs.parse_prometheus_text(text)

    # counters: summed per label-set across sources
    reqs = _samples(parsed, 'reqs')
    assert reqs[('reqs', (('route', 'gen'),))] == 8.0
    assert reqs[('reqs', (('route', 'health'),))] == 1.0

    # gauges: never summed — one sample per source, source-labeled
    occ = _samples(parsed, 'occupancy')
    assert occ[('occupancy', (('replica', 'r0'),))] == 0.25
    assert occ[('occupancy', (('replica', 'r1'),))] == 0.75

    # histograms: bucket counts + _sum/_count summed (ladders agree)
    lat = _samples(parsed, 'lat')
    assert lat[('lat_bucket', (('le', '0.1'),))] == 1.0
    assert lat[('lat_bucket', (('le', '1'),))] == 6.0
    assert lat[('lat_bucket', (('le', '+Inf'),))] == 7.0
    assert lat[('lat_count', ())] == 7.0
    assert lat[('lat_sum', ())] == pytest.approx(8.1)


def test_merge_fleet_metrics_ladder_skew_falls_back_to_labeling():
    skewed = _SCRAPE_B.replace('le="0.1"', 'le="0.5"')
    text = dobs.merge_fleet_metrics([('r0', _SCRAPE_A), ('r1', skewed)])
    lat = _samples(dobs.parse_prometheus_text(text), 'lat')
    # no cross-source sums: every bucket line carries its source label
    assert lat[('lat_bucket', (('le', '0.1'), ('replica', 'r0')))] == 1.0
    assert lat[('lat_bucket', (('le', '0.5'), ('replica', 'r1')))] == 0.0
    assert lat[('lat_count', (('replica', 'r1'),))] == 5.0


# ---------------------------------------------------------------------------
# SLO monitor
# ---------------------------------------------------------------------------

def test_slo_spec_parse_and_malformed():
    clauses = dobs.parse_slo_spec('ttft.p99<0.2, tokens.rate>100')
    assert [(c.series, c.agg, c.op, c.bound) for c in clauses] == [
        ('ttft', 'p99', '<', 0.2), ('tokens', 'rate', '>', 100.0)]
    for bad in ('ttft.p99', 'ttft<0.2', 'ttft.p42<0.2', 'ttft.p99<fast'):
        with pytest.raises(ValueError, match='PADDLE_TPU_SLO'):
            dobs.parse_slo_spec(bad)


def test_slo_monitor_burn_counter_and_vacuous_cold_start(monkeypatch):
    monkeypatch.setenv(dobs.ENV_SLO, 'ttft.p99<0.5,ttft.mean>0')
    mon = dobs.SLOMonitor.from_env()
    # cold series: vacuously ok — cold start is not an outage
    verdict = mon.evaluate()
    assert verdict['ok'] and all(c['ok'] for c in verdict['clauses'])

    for _ in range(20):
        dobs.series('ttft').observe(1.0)     # p99=1.0 breaches <0.5
    verdict = mon.evaluate()
    assert not verdict['ok']
    by_slo = {c['slo']: c for c in verdict['clauses']}
    assert not by_slo['ttft.p99<0.5']['ok']
    assert by_slo['ttft.mean>0']['ok']

    reg = obs.registry.to_dict()
    ok = {tuple(sorted(s['labels'].items())): s['value']
          for s in reg['slo_ok']['samples']}
    assert ok[(('slo', 'ttft.p99<0.5'),)] == 0
    assert ok[(('slo', 'ttft.mean>0'),)] == 1
    burns = {tuple(sorted(s['labels'].items())): s['value']
             for s in reg['slo_breaches']['samples']}
    assert burns[(('slo', 'ttft.p99<0.5'),)] == 1
    mon.evaluate()                           # burn counter accumulates
    assert sum(s['value'] for s in obs.registry.to_dict()
               ['slo_breaches']['samples']) == 2


# ---------------------------------------------------------------------------
# straggler monitor (synthetic fleets)
# ---------------------------------------------------------------------------

def test_straggler_monitor_flags_slow_host_and_writes_record(tmp_path):
    mon = dobs.StragglerMonitor(out_dir=str(tmp_path))
    for step in range(4):
        for host in range(3):
            mon.record(host, 0.10 + 0.001 * host)
        mon.record(3, 0.45)                  # one sleeper
    verdict = mon.evaluate(step=4)
    assert verdict['stragglers'] == ['3']
    assert verdict['zscores']['3'] > mon.threshold
    recs = [json.loads(line) for line in
            (tmp_path / 'straggler.jsonl').read_text().splitlines()]
    assert recs and recs[-1]['host'] == '3' and recs[-1]['step'] == 4
    reg = obs.registry.to_dict()
    assert reg['straggler_count']['samples'][0]['value'] == 1
    z = {s['labels']['host']: s['value']
         for s in reg['straggler_zscore']['samples']}
    assert z['3'] > 3.5 > z['0']


def test_straggler_monitor_quiet_on_healthy_jitter(tmp_path):
    mon = dobs.StragglerMonitor(out_dir=str(tmp_path))
    rng = np.random.RandomState(3)
    for step in range(6):
        for host in range(4):
            mon.record(host, 0.1 + float(rng.uniform(-0.004, 0.004)))
    assert mon.evaluate()['stragglers'] == []
    assert not (tmp_path / 'straggler.jsonl').exists()
    # a single host can never be a straggler relative to itself
    solo = dobs.StragglerMonitor()
    solo.record(0, 99.0)
    assert solo.evaluate() == {'stragglers': [], 'zscores': {}}


# ---------------------------------------------------------------------------
# span recorder + merge tool (satellite a)
# ---------------------------------------------------------------------------

def test_span_recorder_streams_jsonl_with_clock_header(
        tmp_path, monkeypatch):
    monkeypatch.setenv(ENV_TRACE_DIR, str(tmp_path))
    dobs.set_process_label('unit-proc')
    root = TraceContext.root()
    dobs.record_span(root, 'unit/root', 1.0, 2.0)
    dobs.record_span(root.child(), 'unit/child', 1.2, 1.8, detail='x')
    dobs.record_clock_offset('peer', 0.25, rtt_s=0.01)
    path = os.path.join(str(tmp_path), 'spans-%d.jsonl' % os.getpid())
    lines = [json.loads(line) for line in open(path)]
    assert 'clock' in lines[0] and lines[0]['clock']['process'] == 'unit-proc'
    spans = [rec['span'] for rec in lines if 'span' in rec]
    assert [s['name'] for s in spans] == ['unit/root', 'unit/child']
    assert spans[1]['parent_span_id'] == root.span_id
    assert spans[1]['args'] == {'detail': 'x'}
    assert spans[1]['dur_s'] == pytest.approx(0.6)
    offs = [rec['offset'] for rec in lines if 'offset' in rec]
    assert offs == [{'process': 'peer', 'offset_s': 0.25, 'rtt_s': 0.01,
                     'unix_time': offs[0]['unix_time']}]

    from tools.trace_merge import merge_span_files
    _, summary = merge_span_files([path])
    assert summary['spans'] == 2
    assert summary['unresolved_parents'] == []


def test_record_span_without_trace_dir_is_inert():
    assert os.environ.get(ENV_TRACE_DIR) is None
    assert dobs.span_recorder() is None
    dobs.record_span(TraceContext.root(), 'noop', 0.0, 1.0)
    dobs.record_clock_offset('peer', 0.1)    # both no-op without the dir


def test_trace_merge_smoke_cli_gate():
    """Tier-1 gate (ISSUE 17 satellite a): the merge tool's self-check —
    two synthetic processes with a known 5s clock skew — must pass."""
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, 'tools', 'trace_merge.py'),
         '--smoke'],
        cwd=_REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    verdict = json.loads(proc.stdout)
    assert verdict['ok'] and all(verdict['checks'].values())
