"""Static autodiff depth: fluid.gradients w.r.t. data inputs, Recompute
(remat) lowering, and backward-through-While (bounded scan).

Ref parity targets: python/paddle/fluid/backward.py:1672 (gradients),
python/paddle/fluid/optimizer.py:3705 (RecomputeOptimizer),
paddle/fluid/operators/controlflow/while_op.cc:154 (WhileGradOp).
"""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers


def test_gradients_wrt_data_input():
    """fluid.gradients([y], [x]) for a FED variable (the round-3 KeyError
    repro): dy/dx of y = sum(3*x^2) is 6x."""
    x = layers.data('x', [4], dtype='float32')
    y = layers.reduce_sum(layers.scale(layers.square(x), scale=3.0))
    gx, = fluid.gradients([y], [x])
    exe = fluid.Executor()
    xv = np.arange(8, dtype=np.float32).reshape(2, 4)
    out, = exe.run(feed={'x': xv}, fetch_list=[gx])
    np.testing.assert_allclose(out, 6.0 * xv, rtol=1e-5)


def test_gradients_wrt_param_and_input_mixed():
    x = layers.data('x', [3], dtype='float32')
    y = layers.fc(x, size=1, bias_attr=False)
    loss = layers.reduce_sum(y)
    gx, = fluid.gradients([loss], [x])
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    xv = np.ones((2, 3), np.float32)
    w_name = fluid.default_main_program().all_parameters()[0].name
    wv = np.asarray(fluid.global_scope().find(w_name))
    out, = exe.run(feed={'x': xv}, fetch_list=[gx])
    np.testing.assert_allclose(out, np.tile(wv.sum(axis=1), (2, 1)), rtol=1e-5)


def _deep_mlp_with_checkpoints(n_blocks=3):
    x = layers.data('x', [8], dtype='float32')
    label = layers.data('y', [1], dtype='float32')
    h = x
    ckpts = []
    for _ in range(n_blocks):
        h = layers.fc(h, size=8, act='tanh')
        ckpts.append(h)
    pred = layers.fc(h, size=1)
    loss = layers.reduce_mean(layers.square_error_cost(pred, label))
    return x, label, loss, ckpts


def test_recompute_optimizer_remats():
    """RecomputeOptimizer must produce `remat` segments in the lowered jaxpr
    and train identically to plain SGD."""
    np.random.seed(0)
    xv = np.random.randn(4, 8).astype(np.float32)
    yv = np.random.randn(4, 1).astype(np.float32)

    # --- baseline: plain SGD
    losses_plain = _train(xv, yv, recompute=False)
    # --- recompute path
    losses_remat = _train(xv, yv, recompute=True)
    np.testing.assert_allclose(losses_plain, losses_remat, rtol=1e-5,
                               atol=1e-6)


def _train(xv, yv, recompute, steps=5):
    import paddle_tpu.framework as fw
    main, start = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, start):
        x, label, loss, ckpts = _deep_mlp_with_checkpoints()
        sgd = fluid.optimizer.SGD(learning_rate=0.1)
        if recompute:
            opt = fluid.optimizer.RecomputeOptimizer(sgd)
            opt._set_checkpoints(ckpts)
            opt.minimize(loss)
        else:
            sgd.minimize(loss)
    exe = fluid.Executor()
    exe.run(start)
    out = []
    for _ in range(steps):
        l, = exe.run(main, feed={'x': xv, 'y': yv}, fetch_list=[loss])
        out.append(float(l))
    return out


def test_recompute_jaxpr_contains_remat():
    import jax
    import jax.numpy as jnp
    from paddle_tpu.executor import _lower
    main, start = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, start):
        x, label, loss, ckpts = _deep_mlp_with_checkpoints()
        opt = fluid.optimizer.RecomputeOptimizer(
            fluid.optimizer.SGD(learning_rate=0.1))
        opt._set_checkpoints(ckpts)
        opt.minimize(loss)
    exe = fluid.Executor()
    exe.run(start)
    state_names = sorted(v.name for v in main.list_vars() if v.persistable)
    state = {n: jnp.asarray(fluid.global_scope().find(n))
             for n in state_names}
    feeds = {'x': jnp.zeros((4, 8), jnp.float32),
             'y': jnp.zeros((4, 1), jnp.float32)}
    step = _lower(main, list(feeds), [loss.name], state_names)
    jaxpr = jax.make_jaxpr(step)(state, {}, feeds, jax.random.PRNGKey(0))
    assert 'remat' in str(jaxpr), "no remat segments in lowered step"


def test_while_loop_backward_bounded():
    """Differentiating through while_loop(maximum_trip_count=N): loss =
    sum(x * 2^k) after k doublings; dloss/dx = 2^k."""
    k = 4
    x = layers.data('x', [3], dtype='float32')
    i = layers.fill_constant([1], 'int64', 0)
    n = layers.fill_constant([1], 'int64', k)

    def cond(i, v):
        return layers.less_than(i, n)

    def body(i, v):
        return [layers.increment(i, in_place=False),
                layers.scale(v, scale=2.0)]

    _, out = layers.while_loop(cond, body, [i, x], maximum_trip_count=8)
    loss = layers.reduce_sum(out)
    gx, = fluid.gradients([loss], [x])
    exe = fluid.Executor()
    xv = np.array([[1., 2., 3.]], np.float32)
    lv, gv = exe.run(feed={'x': xv}, fetch_list=[loss, gx])
    np.testing.assert_allclose(lv, (2.0 ** k) * xv.sum(), rtol=1e-6)
    np.testing.assert_allclose(gv, np.full_like(xv, 2.0 ** k), rtol=1e-6)


def test_while_loop_bounded_forward_matches_unbounded():
    x = layers.data('x', [2], dtype='float32')
    i = layers.fill_constant([1], 'int64', 0)
    n = layers.fill_constant([1], 'int64', 3)

    def cond(i, v):
        return layers.less_than(i, n)

    def body(i, v):
        return [layers.increment(i, in_place=False),
                layers.elementwise_add(v, v)]

    _, a = layers.while_loop(cond, body, [i, x])
    i2 = layers.fill_constant([1], 'int64', 0)
    _, b = layers.while_loop(cond, body, [i2, x], maximum_trip_count=10)
    exe = fluid.Executor()
    xv = np.array([[1., -2.]], np.float32)
    av, bv = exe.run(feed={'x': xv}, fetch_list=[a, b])
    np.testing.assert_allclose(av, bv, rtol=1e-6)
    np.testing.assert_allclose(av, xv * 8, rtol=1e-6)
