"""Initializer statistics/values (ref test model: unittests/
test_initializer.py) — each initializer drives a parameter in a startup
program; properties checked on the realized array."""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import initializer as I


def _init_param(init, shape, name):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        fluid.layers.create_parameter(
            shape, 'float32', name=name,
            attr=fluid.ParamAttr(name=name, initializer=init))
    exe = fluid.Executor()
    exe.run(startup)
    return np.asarray(fluid.global_scope().find(name))


def test_constant():
    w = _init_param(I.ConstantInitializer(3.25), [4, 5], 'ini_const')
    np.testing.assert_allclose(w, 3.25)


def test_uniform_range_and_spread():
    w = _init_param(I.UniformInitializer(low=-0.3, high=0.7, seed=1),
                    [200, 50], 'ini_unif')
    assert w.min() >= -0.3 and w.max() <= 0.7
    np.testing.assert_allclose(w.mean(), 0.2, atol=0.02)


def test_normal_stats():
    w = _init_param(I.NormalInitializer(loc=1.0, scale=0.5, seed=2),
                    [300, 40], 'ini_norm')
    np.testing.assert_allclose(w.mean(), 1.0, atol=0.02)
    np.testing.assert_allclose(w.std(), 0.5, atol=0.02)


def test_truncated_normal_bounds():
    w = _init_param(I.TruncatedNormalInitializer(loc=0.0, scale=1.0, seed=3),
                    [200, 50], 'ini_trunc')
    assert np.abs(w).max() <= 2.0 + 1e-5     # truncated at 2 std
    np.testing.assert_allclose(w.mean(), 0.0, atol=0.02)


def test_xavier_uniform_bound():
    fan_in, fan_out = 80, 120
    w = _init_param(I.XavierInitializer(uniform=True, seed=4),
                    [fan_in, fan_out], 'ini_xav')
    limit = np.sqrt(6.0 / (fan_in + fan_out))
    assert np.abs(w).max() <= limit + 1e-6
    assert w.std() == pytest.approx(limit / np.sqrt(3), rel=0.1)


def test_xavier_normal_std():
    fan_in, fan_out = 100, 100
    w = _init_param(I.XavierInitializer(uniform=False, seed=5),
                    [fan_in, fan_out], 'ini_xavn')
    assert w.std() == pytest.approx(np.sqrt(2.0 / (fan_in + fan_out)),
                                    rel=0.1)


def test_msra_std():
    fan_in = 90
    w = _init_param(I.MSRAInitializer(uniform=False, seed=6),
                    [fan_in, 110], 'ini_msra')
    assert w.std() == pytest.approx(np.sqrt(2.0 / fan_in), rel=0.1)


def test_bilinear_upsampling_kernel():
    # (C_out, C_in, k, k) deconv kernel: center-peaked, symmetric
    w = _init_param(I.BilinearInitializer(), [2, 2, 4, 4], 'ini_bil')
    k = w[0, 0]
    np.testing.assert_allclose(k, k[::-1, ::-1], rtol=1e-6)   # symmetric
    assert k.max() == k[1:3, 1:3].max()                       # center peak


def test_numpy_array():
    arr = np.arange(6, dtype='float32').reshape(2, 3)
    w = _init_param(I.NumpyArrayInitializer(arr), [2, 3], 'ini_np')
    np.testing.assert_allclose(w, arr)


def test_seed_determinism():
    w1 = _init_param(I.UniformInitializer(seed=42), [10, 10], 'ini_s1')
    w2 = _init_param(I.UniformInitializer(seed=42), [10, 10], 'ini_s2')
    w3 = _init_param(I.UniformInitializer(seed=43), [10, 10], 'ini_s3')
    np.testing.assert_allclose(w1, w2)
    assert not np.allclose(w1, w3)
