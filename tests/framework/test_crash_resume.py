"""The resilience acceptance test (ISSUE 7): a training run is `kill -9`ed
mid-epoch by the fault-injection hook (a real SIGKILL — no atexit, no
flushing, exactly what a preempted pod looks like), a second process resumes
from `latest()`, and the stitched loss trajectory is BITWISE-identical to an
uninterrupted reference run. Each run is a separate interpreter, so this
also proves the cross-process determinism story end to end: persistables,
Adam slots, dropout RNG salts, the executor step counter, and the
DataLoader mid-epoch cursor all survive the disk round trip.
"""
import json
import os
import signal
import subprocess
import sys

import pytest

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), '..', '..'))

# One deterministic training program, shared by all three runs. Dropout makes
# the loss depend on the per-step RNG stream; epoch-keyed batches make it
# depend on the DataLoader cursor; Adam makes it depend on slot state.
TRAIN_SCRIPT = r'''
import json, os, sys
import numpy as np
import paddle_tpu as fluid
from paddle_tpu import layers as L
from paddle_tpu import resilience

ckpt_dir, log_path, total_steps = sys.argv[1], sys.argv[2], int(sys.argv[3])

fluid.seed(1234)
main, startup = fluid.Program(), fluid.Program()
with fluid.program_guard(main, startup):
    x = L.data('cx', [8], dtype='float32')
    y = L.data('cy', [1], dtype='float32')
    h = L.fc(x, size=16, act='relu')
    h = L.dropout(h, dropout_prob=0.3)
    pred = L.fc(h, size=1)
    loss = L.reduce_mean(L.square_error_cost(pred, y))
    fluid.optimizer.Adam(learning_rate=1e-2).minimize(loss)

exe = fluid.Executor()
exe.run(startup)

blk = main.global_block()
loader = fluid.DataLoader.from_generator(
    feed_list=[blk.var('cx'), blk.var('cy')], capacity=4)

def epoch_batches(epoch, n=5):
    rng = np.random.RandomState(100 + epoch)
    return [(rng.randn(4, 8).astype(np.float32),
             rng.randn(4, 1).astype(np.float32)) for _ in range(n)]

loader.set_batch_generator(lambda: iter(epoch_batches(loader.epoch)))

mgr = resilience.CheckpointManager(ckpt_dir, every_n_steps=3, keep=2)
step = 0
got = mgr.restore()
if got is not None:
    arrays, meta = got
    resilience.restore_training_state(arrays, meta, executor=exe,
                                      program=main, loader=loader)
    step = meta['step']

log = open(log_path, 'a')
stopped = False
while step < total_steps and not stopped:
    for batch in loader():
        lv = exe.run(main, feed=batch, fetch_list=[loss])[0]
        step += 1
        log.write(json.dumps({'step': step,
                              'loss': np.asarray(lv).tobytes().hex()}) + '\n')
        log.flush()
        stopped = mgr.end_of_step(
            step, lambda: resilience.capture_training_state(
                executor=exe, program=main, loader=loader))
        if stopped or step >= total_steps:
            break
mgr.wait()
mgr.close()
log.close()
'''


def _run(tmp_path, name, ckpt_dir, total_steps, fault=None, timeout=300):
    script = tmp_path / 'train.py'
    if not script.exists():
        script.write_text(TRAIN_SCRIPT)
    log = tmp_path / f'{name}.jsonl'
    env = dict(os.environ, JAX_PLATFORMS='cpu', PYTHONPATH=REPO)
    env.pop('PADDLE_TPU_FAULT_INJECT', None)
    env.pop('PADDLE_TPU_ASYNC', None)
    if fault:
        env['PADDLE_TPU_FAULT_INJECT'] = fault
    r = subprocess.run(
        [sys.executable, str(script), str(ckpt_dir), str(log),
         str(total_steps)],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=timeout)
    losses = {}
    if log.exists():
        for line in log.read_text().splitlines():
            if line.strip():
                rec = json.loads(line)
                losses[rec['step']] = rec['loss']
    return r, losses


def test_kill9_then_resume_is_bitwise_identical(tmp_path):
    total = 12
    # reference: one uninterrupted run
    r_ref, ref = _run(tmp_path, 'ref', tmp_path / 'ck_ref', total)
    assert r_ref.returncode == 0, r_ref.stderr[-3000:]
    assert sorted(ref) == list(range(1, total + 1))

    # crashed run: fault injection SIGKILLs at the step-8 boundary
    # (checkpoints land at steps 3 and 6)
    ck = tmp_path / 'ck_crash'
    r_crash, crash = _run(tmp_path, 'crash', ck, total, fault='kill@step=8')
    assert r_crash.returncode == -signal.SIGKILL, \
        f'expected SIGKILL, got rc={r_crash.returncode}: ' \
        f'{r_crash.stderr[-2000:]}'
    assert max(crash) == 8                 # died mid-run, well short of 12
    # pre-crash steps already match the reference
    assert all(crash[s] == ref[s] for s in crash)

    # resume: a fresh interpreter picks up latest() and finishes the job
    r_res, resumed = _run(tmp_path, 'resume', ck, total)
    assert r_res.returncode == 0, r_res.stderr[-3000:]
    resume_start = min(resumed)
    assert resume_start <= 8, 'resume replayed nothing despite the crash'
    assert max(resumed) == total
    # THE acceptance: every resumed step's loss is bitwise the reference's
    mismatches = {s: (resumed[s], ref[s]) for s in resumed
                  if resumed[s] != ref[s]}
    assert not mismatches, \
        f'resumed trajectory diverged from uninterrupted run: {mismatches}'


def test_kill9_during_checkpoint_write_never_corrupts_discovery(tmp_path):
    """Crash AT a checkpoint boundary (the kill hook fires before the
    step-6 save can commit, and any in-flight async write from step 3 dies
    with the process): whatever state the writer was in, a fresh process
    must find a valid (older) checkpoint — never a torn one — and still
    finish with the reference trajectory."""
    total = 9
    r_ref, ref = _run(tmp_path, 'ref2', tmp_path / 'ck_ref2', total)
    assert r_ref.returncode == 0, r_ref.stderr[-3000:]

    ck = tmp_path / 'ck_crash2'
    r_crash, _ = _run(tmp_path, 'crash2', ck, total, fault='kill@step=6')
    assert r_crash.returncode == -signal.SIGKILL

    r_res, resumed = _run(tmp_path, 'resume2', ck, total)
    assert r_res.returncode == 0, r_res.stderr[-3000:]
    assert max(resumed) == total
    assert all(resumed[s] == ref[s] for s in resumed), \
        'post-crash-at-checkpoint resume diverged'
