"""Reader/DataLoader thread-lifecycle regressions (async-pipeline PR):

- `reader.buffered()` deadlock: an exception in the fill thread used to die
  without enqueuing the `end` sentinel, leaving the consumer blocked on
  q.get() forever — it must now propagate to the consumer;
- DataLoader producer-thread leak: a consumer that breaks out of iteration
  early used to leave the producer blocked on q.put holding staged device
  buffers — it must now notice abandonment and exit."""
import threading
import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import reader as R


def _run_with_deadline(fn, seconds=10.0):
    """Run `fn` on a worker so a regression deadlock fails the test instead
    of hanging the suite. Returns fn's result, re-raises its exception."""
    box = {}

    def work():
        try:
            box['result'] = fn()
        except BaseException as e:
            box['error'] = e

    t = threading.Thread(target=work, daemon=True)
    t.start()
    t.join(seconds)
    assert not t.is_alive(), 'deadlock: worker still blocked at deadline'
    if 'error' in box:
        raise box['error']
    return box.get('result')


# ---------------------------------------------------------------------------
# buffered(): producer exception propagation
# ---------------------------------------------------------------------------

def test_buffered_propagates_producer_exception():
    def bad_reader():
        yield 1
        yield 2
        raise ValueError('reader exploded')

    def consume():
        got = []
        with pytest.raises(ValueError, match='reader exploded'):
            for item in R.buffered(bad_reader, size=2)():
                got.append(item)
        return got

    got = _run_with_deadline(consume)
    assert got == [1, 2]          # items before the failure still arrive


def test_buffered_immediate_failure_does_not_deadlock():
    def bad_reader():
        raise RuntimeError('fails before first item')
        yield  # pragma: no cover

    def consume():
        with pytest.raises(RuntimeError, match='fails before first item'):
            list(R.buffered(bad_reader, size=1)())

    _run_with_deadline(consume)


def test_buffered_normal_path_unchanged():
    out = _run_with_deadline(
        lambda: list(R.buffered(lambda: iter(range(7)), size=3)()))
    assert out == list(range(7))


# ---------------------------------------------------------------------------
# DataLoader: producer thread exits when the consumer abandons iteration
# ---------------------------------------------------------------------------

def _producer_threads():
    return [t for t in threading.enumerate()
            if t.name.startswith('paddle_tpu_dataloader_producer')
            and t.is_alive()]


def _wait_no_producers(timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if not _producer_threads():
            return True
        time.sleep(0.02)
    return False


def test_dataloader_early_break_releases_producer():
    produced = []

    def gen():
        for i in range(100):
            produced.append(i)
            yield {'z': np.full((2, 2), i, np.float32)}

    # capacity 1 guarantees the producer is parked in q.put when the
    # consumer walks away
    loader = fluid.DataLoader.from_generator(capacity=1)
    loader.set_batch_generator(gen)

    def consume():
        for i, batch in enumerate(loader()):
            if i == 1:
                break                      # abandon mid-stream
        return True

    _run_with_deadline(consume)
    assert _wait_no_producers(), \
        'producer thread leaked after consumer break'
    # the producer stopped early instead of draining all 100 batches
    assert len(produced) < 100


def test_dataloader_generator_close_releases_producer():
    loader = fluid.DataLoader.from_generator(capacity=1)
    loader.set_batch_generator(
        lambda: ({'z': np.zeros((2,), np.float32)} for _ in range(50)))

    def consume():
        it = iter(loader())
        next(it)
        it.close()                        # explicit GeneratorExit
        return True

    _run_with_deadline(consume)
    assert _wait_no_producers(), \
        'producer thread leaked after generator close'


def test_dataloader_exception_still_surfaces_in_consumer():
    def gen():
        yield {'z': np.zeros((2,), np.float32)}
        raise ValueError('producer failed mid-stream')

    loader = fluid.DataLoader.from_generator(capacity=2)
    loader.set_batch_generator(gen)

    def consume():
        with pytest.raises(ValueError, match='producer failed mid-stream'):
            for _ in loader():
                pass

    _run_with_deadline(consume)
    assert _wait_no_producers()


def test_dataloader_int64_bounds_checked_at_staging():
    # staging-time bounds check (reader.py _stage): values beyond int32
    # must fail loudly in the consumer, not wrap silently on device
    loader = fluid.DataLoader.from_generator(capacity=2)
    loader.set_batch_generator(
        lambda: iter([{'ids': np.array([2 ** 40], np.int64)}]))

    def consume():
        with pytest.raises(OverflowError, match='int32'):
            for _ in loader():
                pass

    _run_with_deadline(consume)
