"""contrib completion: decoder (StateCell/TrainingDecoder/
BeamSearchDecoder), text-matching layer ops, QuantizeTranspiler,
reader/utils/model_stat/op_frequence, Trainer/Inferencer."""
import os

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import contrib


# ------------------------------------------------------------ decoder ----

def _run(main, startup, feed, fetch):
    exe = fluid.Executor()
    exe.run(startup)
    return exe.run(main, feed=feed, fetch_list=fetch)


def test_training_decoder_matches_manual_gru():
    B, T, D, H = 2, 4, 3, 5
    rng = np.random.RandomState(0)
    emb = rng.rand(B, T, D).astype('float32')
    boot = rng.rand(B, H).astype('float32')

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data('td_x', [B, T, D], 'float32')
        h0 = fluid.data('td_h0', [B, H], 'float32')
        state = contrib.InitState(init=h0)
        cell = contrib.StateCell(inputs={'w': None}, states={'h': state},
                                 out_state='h')

        @cell.state_updater
        def updater(c):
            w = c.get_input('w')
            h = c.get_state('h')
            new_h = fluid.layers.fc(
                fluid.layers.concat([w, h], axis=1), H, act='tanh',
                param_attr=fluid.ParamAttr(
                    name='td_w',
                    initializer=fluid.initializer.ConstantInitializer(0.1)),
                bias_attr=False)
            c.set_state('h', new_h)

        decoder = contrib.TrainingDecoder(cell)
        with decoder.block():
            w = decoder.step_input(x)
            cell.compute_state(inputs={'w': w})
            cell.update_states()
            decoder.output(cell.get_state('h'))
        out = decoder()
    res, = _run(main, startup, {'td_x': emb, 'td_h0': boot}, [out])
    assert res.shape == (B, T, H)
    # manual reference
    W = np.full((D + H, H), 0.1, 'float32')
    h = boot
    for t in range(T):
        h = np.tanh(np.concatenate([emb[:, t], h], axis=1) @ W)
        np.testing.assert_allclose(res[:, t], h, rtol=2e-5, atol=2e-5)


def test_beam_search_decoder_decodes():
    B, W, H, V, D = 2, 3, 6, 11, 4
    max_len = 5
    rng = np.random.RandomState(1)
    boot = rng.rand(B, H).astype('float32')

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        h0 = fluid.data('bsd_h0', [B, H], 'float32')
        init_ids = fluid.data('bsd_ids', [B, 1], 'int64')
        init_scores = fluid.data('bsd_scores', [B, 1], 'float32')
        state = contrib.InitState(init=h0)
        cell = contrib.StateCell(inputs={'w': None}, states={'h': state},
                                 out_state='h')

        @cell.state_updater
        def updater(c):
            w = c.get_input('w')
            h = c.get_state('h')
            new_h = fluid.layers.fc(fluid.layers.concat([w, h], axis=1), H,
                                    act='tanh', bias_attr=False)
            c.set_state('h', new_h)

        decoder = contrib.BeamSearchDecoder(
            cell, init_ids, init_scores, target_dict_dim=V, word_dim=D,
            topk_size=V, max_len=max_len, beam_size=W, end_id=1)
        decoder.decode()
        ids, scores = decoder()
    r_ids, r_scores = _run(
        main, startup,
        {'bsd_h0': boot, 'bsd_ids': np.zeros((B, 1), 'int64'),
         'bsd_scores': np.zeros((B, 1), 'float32')},
        [ids, scores])
    assert r_ids.shape == (B, W, max_len)
    assert r_scores.shape == (B, W)
    assert r_ids.min() >= 0 and r_ids.max() < V
    # beams are sorted best-first by construction of top-k
    assert np.all(np.diff(r_scores, axis=1) <= 1e-5)


# --------------------------------------------------- layer ops (masked) ----

def test_match_matrix_tensor():
    B, Lx, Ly, D, C = 2, 3, 4, 5, 2
    rng = np.random.RandomState(2)
    xv = rng.rand(B, Lx, D).astype('float32')
    yv = rng.rand(B, Ly, D).astype('float32')
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data('mm_x', [B, Lx, D], 'float32')
        y = fluid.data('mm_y', [B, Ly, D], 'float32')
        xl = fluid.data('mm_xl', [B], 'int32')
        out, tmp = contrib.layers.match_matrix_tensor(
            x, y, channel_num=C, x_len=xl)
    r, = _run(main, startup,
              {'mm_x': xv, 'mm_y': yv,
               'mm_xl': np.array([2, 3], 'int32')}, [out])[:1]
    assert r.shape == (B, C, Lx, Ly)
    # masked rows are zero
    assert np.allclose(r[0, :, 2:, :], 0)
    assert np.allclose(r[1, :, 3:, :], 0)
    assert not np.allclose(r[0, :, :2, :], 0)


def test_var_conv_2d_masks_extent():
    B, C, Hh, Ww = 2, 1, 6, 6
    rng = np.random.RandomState(3)
    xv = rng.rand(B, C, Hh, Ww).astype('float32')
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data('vc_x', [B, C, Hh, Ww], 'float32')
        row = fluid.data('vc_r', [B], 'int32')
        col = fluid.data('vc_c', [B], 'int32')
        out = contrib.layers.var_conv_2d(x, row, col, input_channel=C,
                                         output_channel=3, filter_size=3)
    r, = _run(main, startup,
              {'vc_x': xv, 'vc_r': np.array([4, 6], 'int32'),
               'vc_c': np.array([3, 6], 'int32')}, [out])
    assert r.shape == (B, 3, Hh, Ww)
    assert np.allclose(r[0, :, 4:, :], 0) and np.allclose(r[0, :, :, 3:], 0)
    assert not np.allclose(r[1], 0)


def test_sequence_topk_avg_pooling():
    B, C, R, Cc = 1, 1, 2, 5
    x = np.array([[[[5, 1, 3, 9, 7],
                    [2, 8, 4, 6, 0]]]], 'float32')
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        xv = fluid.data('tk_x', [B, C, R, Cc], 'float32')
        row = fluid.data('tk_r', [B], 'int32')
        col = fluid.data('tk_c', [B], 'int32')
        out = contrib.layers.sequence_topk_avg_pooling(
            xv, row, col, topks=[1, 3], channel_num=C)
    r, = _run(main, startup,
              {'tk_x': x, 'tk_r': np.array([2], 'int32'),
               'tk_c': np.array([4], 'int32')}, [out])
    assert r.shape == (B, R, C * 2)
    # valid cols of row0: [5,1,3,9] → top1=9, top3 avg=(9+5+3)/3
    np.testing.assert_allclose(r[0, 0], [9.0, 17 / 3], rtol=1e-6)
    # row1: [2,8,4,6] → top1=8, top3=(8+6+4)/3=6
    np.testing.assert_allclose(r[0, 1], [8.0, 6.0], rtol=1e-6)


def test_fused_embedding_seq_pool():
    B, T, V, D = 2, 3, 7, 4
    ids = np.array([[1, 2, 0], [3, 0, 0]], 'int64')
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        iv = fluid.data('fe_ids', [B, T], 'int64')
        ln = fluid.data('fe_len', [B], 'int32')
        out = contrib.layers.fused_embedding_seq_pool(
            iv, size=[V, D], combiner='sum', sequence_length=ln)
    exe = fluid.Executor()
    exe.run(startup)
    w = np.asarray(fluid.global_scope().find(
        fluid.io.get_program_parameter(main)[0].name))
    r, = exe.run(main, feed={'fe_ids': ids,
                             'fe_len': np.array([2, 1], 'int32')},
                 fetch_list=[out])
    np.testing.assert_allclose(r[0], w[1] + w[2], rtol=1e-5)
    np.testing.assert_allclose(r[1], w[3], rtol=1e-5)


def test_search_pyramid_hash_shapes_and_mask():
    B, T = 2, 5
    ids = np.array([[3, 4, 5, 6, 7], [8, 9, 1, 1, 1]], 'int64')
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        iv = fluid.data('ph_ids', [B, T], 'int64')
        ln = fluid.data('ph_len', [B], 'int32')
        out = contrib.layers.search_pyramid_hash(
            iv, num_emb=8, space_len=64, pyramid_layer=3, rand_len=8,
            drop_out_percent=0.0, is_training=False, use_filter=False,
            white_list_len=0, black_list_len=0, seed=7,
            sequence_length=ln)
    r, = _run(main, startup,
              {'ph_ids': ids, 'ph_len': np.array([5, 2], 'int32')}, [out])
    assert r.shape == (B, T, 8)
    assert np.allclose(r[1, 2:], 0)       # masked tail
    assert not np.allclose(r[0], 0)


def test_ctr_metric_bundle_accumulates():
    B = 4
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        p = fluid.data('ctr_p', [B, 1], 'float32')
        lab = fluid.data('ctr_l', [B, 1], 'float32')
        sqr, abse, prob, q, pos, ins = contrib.layers.ctr_metric_bundle(
            p, lab)
    exe = fluid.Executor()
    exe.run(startup)
    pv = np.array([[0.2], [0.8], [0.5], [0.9]], 'float32')
    lv = np.array([[0.0], [1.0], [0.0], [1.0]], 'float32')
    for _ in range(2):
        r = exe.run(main, feed={'ctr_p': pv, 'ctr_l': lv},
                    fetch_list=[sqr, abse, prob, q, pos, ins])
    err = pv - lv
    np.testing.assert_allclose(r[0], 2 * np.sum(err ** 2), rtol=1e-5)
    np.testing.assert_allclose(r[1], 2 * np.sum(np.abs(err)), rtol=1e-5)
    np.testing.assert_allclose(r[2], 2 * np.sum(pv), rtol=1e-5)
    np.testing.assert_allclose(r[3], 2 * np.sum(pv * lv), rtol=1e-5)
    np.testing.assert_allclose(r[4], 2 * np.sum(lv), rtol=1e-5)
    np.testing.assert_allclose(r[5], 2 * B, rtol=1e-5)


# --------------------------------------------------- QuantizeTranspiler ----

def test_quantize_transpiler_training_and_int8():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data('qt_x', [4, 8], 'float32')
        y = fluid.layers.fc(x, 4)
        loss = fluid.layers.reduce_mean(y)
        fluid.optimizer.SGD(0.01).minimize(loss)
    t = contrib.QuantizeTranspiler()
    n = t.training_transpile(main)
    assert n >= 1
    types = [op.type for op in main.global_block().ops]
    assert 'fake_quantize_dequantize_abs_max' in types
    # re-transpile is a no-op
    assert t.training_transpile(main) == 0
    exe = fluid.Executor()
    exe.run(startup)
    r1, = exe.run(main, feed={'qt_x': np.random.rand(4, 8).astype(
        'float32')}, fetch_list=[loss])
    assert np.isfinite(r1).all()
    w_name = fluid.io.get_program_parameter(main)[0].name
    w_before = np.asarray(fluid.global_scope().find(w_name)).copy()
    assert t.convert_to_int8(main) >= 1
    q = np.asarray(fluid.global_scope().find(w_name + '@INT8'))
    scale = np.asarray(fluid.global_scope().find(w_name + '@SCALE'))
    assert q.dtype == np.int8
    w_after = np.asarray(fluid.global_scope().find(w_name))
    np.testing.assert_allclose(w_after, q.astype('float32') * scale / 127.0,
                               rtol=1e-6)
    # reconstruction is close to, but genuinely different from, fp32
    assert np.abs(w_after - w_before).max() < scale / 64.0


def test_quantize_transpiler_covers_conv_weights():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data('qc_x', [1, 2, 6, 6], 'float32')
        y = fluid.layers.conv2d(x, 3, 3)
        loss = fluid.layers.reduce_mean(y)
    t = contrib.QuantizeTranspiler()
    t.training_transpile(main)
    conv = [op for op in main.global_block().ops
            if op.type == 'conv2d'][0]
    assert conv.inputs['x'][0].endswith('.dequantized')
    assert conv.inputs['weight'][0].endswith('.dequantized')
    exe = fluid.Executor()
    exe.run(startup)
    assert t.convert_to_int8(main) >= 1
    w_name = fluid.io.get_program_parameter(main)[0].name
    assert fluid.global_scope().find(w_name + '@INT8') is not None


# --------------------------------------------- misc contrib utilities ----

def test_distributed_batch_reader(monkeypatch):
    monkeypatch.setenv('PADDLE_TRAINER_ID', '1')
    monkeypatch.setenv('PADDLE_TRAINERS_NUM', '2')

    def batches():
        yield from range(10)
    r = contrib.distributed_batch_reader(batches)
    assert list(r()) == [1, 3, 5, 7, 9]


def test_hdfs_client_local_mapping(tmp_path, monkeypatch):
    monkeypatch.setenv('PADDLE_TPU_HDFS_ROOT', str(tmp_path))
    c = contrib.HDFSClient(None, {'fs.default.name': 'hdfs://x'})
    local = tmp_path / 'src.txt'
    local.write_text('hello')
    assert c.upload('/data/a.txt', str(local))
    assert c.is_exist('/data/a.txt')
    assert c.ls('/data') == ['/data/a.txt']
    got = tmp_path / 'out.txt'
    assert c.download('/data/a.txt', str(got))
    assert got.read_text() == 'hello'
    files = contrib.multi_download(c, '/data', str(tmp_path / 'dl'), 0, 1)
    assert files
    c.delete('/data/a.txt')
    assert not c.is_exist('/data/a.txt')


def test_model_stat_and_op_frequence(capsys):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data('ms_x', [2, 3, 8, 8], 'float32')
        y = fluid.layers.conv2d(x, 4, 3)
        y = fluid.layers.relu(y)
        y = fluid.layers.pool2d(y, 2)
    rows, params, flops = contrib.summary(main)
    out = capsys.readouterr().out
    assert 'Total PARAMs' in out and params > 0 and flops > 0
    uni, adj = contrib.op_freq_statistic(main)
    assert uni['conv2d'] == 1 and sum(uni.values()) >= 3
    with pytest.raises(ValueError):
        contrib.op_freq_statistic('not a program')


def test_lookup_table_utils():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = fluid.data('lt_ids', [4], 'int64')
        emb = fluid.layers.embedding(ids, size=[20, 4],
                                     is_distributed=True)
    sparse = contrib.convert_dist_to_sparse_program(main)
    for op in sparse.global_block().ops:
        if op.type == 'lookup_table':
            assert not op.attrs.get('is_distributed')
            assert op.attrs.get('is_sparse')
    # original untouched
    assert any(op.attrs.get('is_distributed')
               for op in main.global_block().ops
               if op.type == 'lookup_table')


# ------------------------------------------------- Trainer / Inferencer ----

def test_trainer_and_inferencer_roundtrip(tmp_path):
    rng = np.random.RandomState(5)
    X = rng.rand(64, 3).astype('float32')
    Wt = np.array([[1.0], [-2.0], [3.0]], 'float32')
    Y = X @ Wt

    def train_func():
        x = fluid.data('tr_x', [-1, 3], 'float32')
        y = fluid.data('tr_y', [-1, 1], 'float32')
        pred = fluid.layers.fc(
            x, 1, bias_attr=False,
            param_attr=fluid.ParamAttr(name='tr_fc_w'))
        return fluid.layers.reduce_mean(
            fluid.layers.square_error_cost(pred, y))

    def optimizer_func():
        return fluid.optimizer.Adam(0.1)

    def reader():
        for i in range(0, 64, 16):
            yield [(X[j], Y[j]) for j in range(i, i + 16)]

    losses = []

    def handler(event):
        if isinstance(event, contrib.EndStepEvent):
            losses.append(float(np.asarray(event.metrics[0])))

    trainer = contrib.Trainer(train_func, optimizer_func)
    trainer.train(num_epochs=25, event_handler=handler, reader=reader,
                  feed_order=['tr_x', 'tr_y'])
    assert losses[-1] < losses[0] * 0.05
    test_loss = trainer.test(reader, feed_order=['tr_x', 'tr_y'])
    assert test_loss[0] < losses[0]
    params_dir = str(tmp_path / 'params')
    trainer.save_params(params_dir)

    def infer_func():
        x = fluid.data('tr_x', [-1, 3], 'float32')
        return fluid.layers.fc(
            x, 1, bias_attr=False,
            param_attr=fluid.ParamAttr(name='tr_fc_w'))

    inf = contrib.Inferencer(infer_func, params_dir)
    pred, = inf.infer({'tr_x': X[:8]})
    np.testing.assert_allclose(pred, Y[:8], atol=0.5)
