"""int64→int32 device-boundary contract (VERDICT r4 item 6): library code
emits no truncation warnings, and data that would wrap raises instead of
silently corrupting (core/dtypes.py)."""
import warnings

import numpy as np
import pytest

import paddle_tpu as fluid
import paddle_tpu.layers as L


def test_int64_feed_no_truncation_warning():
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        ids = fluid.data('ids', [4, 3], 'int64')
        emb = L.embedding(ids, size=[50, 8])
        out = L.reduce_sum(emb)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    with warnings.catch_warnings():
        warnings.simplefilter('error', UserWarning)  # any truncation → fail
        r, = exe.run(prog, feed={
            'ids': np.random.randint(0, 50, (4, 3)).astype(np.int64)},
            fetch_list=[out])
    assert np.isfinite(r).all()


def test_int64_feed_out_of_range_raises():
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        ids = fluid.data('ids', [2, 2], 'int64')
        out = L.reduce_sum(L.cast(ids, 'float32'))
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    bad = np.array([[2 ** 31, 1], [2, 3]], np.int64)
    with pytest.raises(OverflowError, match='int32 range'):
        exe.run(prog, feed={'ids': bad}, fetch_list=[out])


def test_to_variable_out_of_range_raises():
    from paddle_tpu import dygraph
    with dygraph.guard():
        with pytest.raises(OverflowError, match='int32 range'):
            fluid.dygraph.to_variable(np.array([2 ** 40], np.int64))


def test_set_value_out_of_range_raises():
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        v = prog.global_block().create_var(name='ids64', shape=[2],
                                           dtype='int64', persistable=True)
    with pytest.raises(OverflowError, match='int32 range'):
        v.set_value(np.array([2 ** 50, 1], np.int64))


def test_in_range_int64_values_preserved():
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        ids = fluid.data('ids', [3], 'int64')
        out = L.scale(ids, scale=1.0)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    vals = np.array([0, 5, 2 ** 31 - 1], np.int64)
    r, = exe.run(prog, feed={'ids': vals}, fetch_list=[out])
    np.testing.assert_array_equal(np.asarray(r).astype(np.int64), vals)
