"""Async train-loop pipeline (executor.py + core/fetch_handle.py):
non-blocking FetchHandles, K-steps-in-flight window, snapshot semantics
under donation, zero-copy staged feeds, and the FLAGS_check_nan_inf
interaction. PERF.md §12 / tools/bench_pipeline.py measure the overlap win;
these tests pin the SEMANTICS."""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers as L
from paddle_tpu import observability as obs
from paddle_tpu.compiler import CompiledProgram, ExecutionStrategy
from paddle_tpu.core.fetch_handle import (FetchHandle,
                                          resolve_inflight_steps)


def _mlp_prog(prefix, width=32):
    """MNIST-shaped MLP regression (RNG-free, so parity is bitwise)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = L.data(prefix + 'x', [16], dtype='float32')
        y = L.data(prefix + 'y', [1], dtype='float32')
        h = L.fc(x, size=width, act='relu')
        h = L.fc(h, size=width, act='relu')
        pred = L.fc(h, size=1)
        loss = L.reduce_mean(L.square_error_cost(pred, y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return main, startup, loss


def _feeds(prefix, n, bs=8, seed=0):
    rng = np.random.RandomState(seed)
    return [{prefix + 'x': rng.randn(bs, 16).astype(np.float32),
             prefix + 'y': rng.randn(bs, 1).astype(np.float32)}
            for _ in range(n)]


def _loop(main, startup, loss, feeds, fetch_list=None):
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        out = [exe.run(main, feed=f, fetch_list=fetch_list or [loss])
               for f in feeds]
    return exe, out


# ---------------------------------------------------------------------------
# mode resolution
# ---------------------------------------------------------------------------

def test_resolve_inflight_env_and_strategy(monkeypatch):
    monkeypatch.delenv('PADDLE_TPU_ASYNC', raising=False)
    assert resolve_inflight_steps() == 0
    es = ExecutionStrategy()
    assert es.num_inflight_steps == 1          # sync default
    assert resolve_inflight_steps(es) == 0
    es.num_inflight_steps = 3
    assert resolve_inflight_steps(es) == 3
    monkeypatch.setenv('PADDLE_TPU_ASYNC', '1')
    assert resolve_inflight_steps() == 2       # default double buffer
    monkeypatch.setenv('PADDLE_TPU_ASYNC', '4')
    assert resolve_inflight_steps(es) == 4     # env beats strategy
    monkeypatch.setenv('PADDLE_TPU_ASYNC', '0')
    assert resolve_inflight_steps(es) == 0     # env 0 pins sync


def test_async_env_zero_restores_numpy_results(monkeypatch):
    monkeypatch.setenv('PADDLE_TPU_ASYNC', '0')
    main, startup, loss = _mlp_prog('az_')
    _, out = _loop(main, startup, loss, _feeds('az_', 2))
    assert all(isinstance(r[0], np.ndarray) for r in out)


# ---------------------------------------------------------------------------
# bitwise parity + window semantics
# ---------------------------------------------------------------------------

def test_sync_async_bitwise_parity(monkeypatch):
    main, startup, loss = _mlp_prog('pa_')
    feeds = _feeds('pa_', 6)
    monkeypatch.setenv('PADDLE_TPU_ASYNC', '0')
    _, sync_out = _loop(main, startup, loss, feeds)
    sync_losses = [r[0] for r in sync_out]
    monkeypatch.setenv('PADDLE_TPU_ASYNC', '2')
    _, async_out = _loop(main, startup, loss, feeds)
    async_losses = [np.asarray(r[0]) for r in async_out]
    for s, a in zip(sync_losses, async_losses):
        assert s.tobytes() == a.tobytes()


def test_inflight_window_never_exceeds_k(monkeypatch):
    k = 2
    monkeypatch.setenv('PADDLE_TPU_ASYNC', str(k))
    main, startup, loss = _mlp_prog('wk_')
    feeds = _feeds('wk_', 8)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        handles = []
        for f in feeds:
            h = exe.run(main, feed=f, fetch_list=[loss])[0]
            assert isinstance(h, FetchHandle)
            handles.append(h)
            # observable window bound: dispatch of step N waits for step
            # N-K, so every handle older than the last K is finished
            for old in handles[:-k]:
                assert old.done
            assert len(exe._window) <= k
    # drain is the user's read
    vals = [float(h) for h in handles]
    assert all(np.isfinite(v) for v in vals)


def test_async_uses_fresh_steady_state_each_run(monkeypatch):
    # regression guard: results must come from the run that produced them
    # (no off-by-one in the window) — fetch a deterministic function of
    # the feed alongside the loss
    monkeypatch.setenv('PADDLE_TPU_ASYNC', '2')
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = L.data('fr_x', [4], dtype='float32')
        out = L.scale(x, scale=2.0)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        handles = []
        feeds = [np.full((2, 4), i, np.float32) for i in range(5)]
        for f in feeds:
            handles.append(exe.run(main, feed={'fr_x': f},
                                   fetch_list=[out])[0])
        for i, h in enumerate(handles):
            np.testing.assert_array_equal(np.asarray(h), feeds[i] * 2.0)


# ---------------------------------------------------------------------------
# snapshot semantics
# ---------------------------------------------------------------------------

def test_handle_snapshot_survives_later_donated_runs(monkeypatch):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = L.data('sn_x', [16], dtype='float32')
        y = L.data('sn_y', [1], dtype='float32')
        h = L.fc(x, size=32, act='relu',
                 param_attr=fluid.ParamAttr(name='sn_w0'))
        pred = L.fc(h, size=1)
        loss = L.reduce_mean(L.square_error_cost(pred, y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    feeds = _feeds('sn_', 5)

    monkeypatch.setenv('PADDLE_TPU_ASYNC', '0')
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        ref_w0 = exe.run(main, feed=feeds[0], fetch_list=[loss, 'sn_w0'])[1]

    monkeypatch.setenv('PADDLE_TPU_ASYNC', '2')
    scope2 = fluid.Scope()
    with fluid.scope_guard(scope2):
        exe2 = fluid.Executor()
        exe2.run(startup)
        h0 = exe2.run(main, feed=feeds[0], fetch_list=[loss, 'sn_w0'])
        # the pending param fetch pins its name out of donation
        h0[1].block_until_ready()
        assert 'sn_w0' in exe2._window.protected_names()
        # later steps update sn_w0 (and would donate it); mix in sync
        # donated runs too — the pending handle must stay protected
        for i, f in enumerate(feeds[1:]):
            monkeypatch.setenv('PADDLE_TPU_ASYNC', '2' if i % 2 else '0')
            exe2.run(main, feed=f, fetch_list=[loss])
        got = h0[1].numpy()
        assert got.tobytes() == ref_w0.tobytes()
        # materialization releases the protection
        assert 'sn_w0' not in exe2._window.protected_names()


def test_return_numpy_false_handle_snapshot(monkeypatch):
    monkeypatch.delenv('PADDLE_TPU_ASYNC', raising=False)
    main, startup, loss = _mlp_prog('rn2_')
    feeds = _feeds('rn2_', 3)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        h = exe.run(main, feed=feeds[0], fetch_list=[loss],
                    return_numpy=False)[0]
        first = np.asarray(h)
        for f in feeds[1:]:
            exe.run(main, feed=f, fetch_list=[loss])
        # cached materialization is stable
        assert np.asarray(h).tobytes() == first.tobytes()


# ---------------------------------------------------------------------------
# knob plumbing: ExecutionStrategy through CompiledProgram
# ---------------------------------------------------------------------------

def test_num_inflight_steps_strategy_drives_async(monkeypatch):
    monkeypatch.delenv('PADDLE_TPU_ASYNC', raising=False)
    main, startup, loss = _mlp_prog('es_')
    es = ExecutionStrategy()
    es.num_inflight_steps = 2
    cp = CompiledProgram(main).with_data_parallel(loss_name=loss.name,
                                                 exec_strategy=es)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        r = exe.run(cp, feed=_feeds('es_', 1)[0], fetch_list=[loss])[0]
        assert isinstance(r, FetchHandle)
        assert np.isfinite(float(r))


# ---------------------------------------------------------------------------
# zero-copy staged feeds
# ---------------------------------------------------------------------------

def test_staged_feed_passthrough_no_second_device_put(monkeypatch):
    monkeypatch.delenv('PADDLE_TPU_ASYNC', raising=False)
    main, startup, loss = _mlp_prog('st_')
    feeds = _feeds('st_', 4)
    x = main.global_block().var('st_x')
    y = main.global_block().var('st_y')
    loader = fluid.DataLoader.from_generator(feed_list=[x, y], capacity=4)
    loader.set_batch_generator(
        lambda: iter([(f['st_x'], f['st_y']) for f in feeds]))
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        with obs.telemetry_guard(True):
            obs.reset()
            for batch in loader():
                exe.run(main, feed=batch, fetch_list=[loss])
            m = obs.registry.to_dict()
    staged = sum(s['value'] for s in m['dataloader_staged_bytes']['samples'])
    passed = sum(s['value']
                 for s in m['executor_feed_passthrough_bytes']['samples'])
    # every byte the producer staged went through without a second
    # device_put (the executor recognized the committed arrays)
    assert staged > 0
    assert passed == staged


def test_numpy_feeds_are_not_counted_as_passthrough():
    main, startup, loss = _mlp_prog('np_')
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        with obs.telemetry_guard(True):
            obs.reset()
            exe.run(main, feed=_feeds('np_', 1)[0], fetch_list=[loss])
            m = obs.registry.to_dict()
    assert 'executor_feed_passthrough_bytes' not in m


# ---------------------------------------------------------------------------
# FLAGS_check_nan_inf under pipelining
# ---------------------------------------------------------------------------

def test_check_nan_inf_moves_to_materialization_in_async(monkeypatch):
    import jax
    from paddle_tpu import debugging
    monkeypatch.setenv('PADDLE_TPU_ASYNC', '2')
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = L.data('nn_x', [4], dtype='float32')
        out = L.reduce_mean(L.sqrt(x))        # NaN for negative feeds
    debugging.enable_check_nan_inf(True)
    # isolate the fetch-scan path: jax_debug_nans raises from inside the
    # computation and is mode-independent
    jax.config.update('jax_debug_nans', False)
    try:
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe = fluid.Executor()
            with obs.telemetry_guard(True):
                obs.reset()
                h = exe.run(main,
                            feed={'nn_x': np.full((2, 4), -1.0, np.float32)},
                            fetch_list=[out])[0]
                # the run itself does NOT raise (no per-step sync) ...
                assert isinstance(h, FetchHandle)
                # ... the scan fires at the read
                with pytest.raises(FloatingPointError, match='check_nan_inf'):
                    h.numpy()
                m = obs.registry.to_dict()
        nf = sum(s['value'] for s in m['nonfinite_detections']['samples'])
        assert nf >= 1
    finally:
        debugging.enable_check_nan_inf(False)


# ---------------------------------------------------------------------------
# telemetry
# ---------------------------------------------------------------------------

def test_async_metrics_recorded(monkeypatch):
    monkeypatch.setenv('PADDLE_TPU_ASYNC', '2')
    main, startup, loss = _mlp_prog('tm_')
    feeds = _feeds('tm_', 3)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        with obs.telemetry_guard(True):
            obs.reset()
            hs = [exe.run(main, feed=f, fetch_list=[loss])[0]
                  for f in feeds]
            [h.numpy() for h in hs]
            m = obs.registry.to_dict()
    gauge = m['executor_inflight_steps']['samples'][0]['value']
    assert 0 <= gauge <= 2
    hist = m['fetch_materialize_seconds']['samples'][0]
    assert hist['count'] == len(feeds)


# ---------------------------------------------------------------------------
# TrainStep async_fetch
# ---------------------------------------------------------------------------

def _mse(m, x, y):
    from paddle_tpu.dygraph.tape import dispatch_op
    d = dispatch_op('elementwise_sub', {'x': m(x), 'y': y}, {})
    sq = dispatch_op('elementwise_mul', {'x': d, 'y': d}, {})
    return dispatch_op('reduce_mean', {'x': sq}, {})


def test_train_step_async_fetch_parity(monkeypatch):
    from paddle_tpu import dygraph
    from paddle_tpu.dygraph.jit import TrainStep
    from paddle_tpu.dygraph.nn import Linear
    from paddle_tpu.core.random import seed as set_seed
    monkeypatch.delenv('PADDLE_TPU_ASYNC', raising=False)
    rng = np.random.RandomState(0)
    batches = [(rng.randn(4, 8).astype(np.float32),
                rng.randn(4, 1).astype(np.float32)) for _ in range(4)]

    def run(**kw):
        with dygraph.guard():
            set_seed(7)
            model = Linear(8, 1)
            opt = fluid.optimizer.SGD(0.1,
                                      parameter_list=model.parameters())
            step = TrainStep(model, _mse, opt, **kw)
            return [step(x, y) for x, y in batches]

    sync_losses = [np.asarray(v) for v in run()]
    async_out = run(async_fetch=True, num_inflight_steps=2)
    assert all(isinstance(h, FetchHandle) for h in async_out)
    async_losses = [h.numpy() for h in async_out]
    for s, a in zip(sync_losses, async_losses):
        assert s.tobytes() == a.tobytes()

    # PADDLE_TPU_ASYNC=0 overrides the constructor opt-in
    monkeypatch.setenv('PADDLE_TPU_ASYNC', '0')
    plain = run(async_fetch=True)
    assert not any(isinstance(v, FetchHandle) for v in plain)
