"""Collective primitives on the 8-device CPU mesh (SURVEY §2.2 c_* ops):
numeric parity vs numpy reductions under shard_map."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from paddle_tpu.core.compat import shard_map

from paddle_tpu.parallel import collective as C


@pytest.fixture(scope='module')
def mesh8():
    devs = np.array(jax.devices()[:8])
    return Mesh(devs, ('dp',))


def _smap(mesh, fn, in_spec=P('dp'), out_spec=P('dp')):
    return shard_map(fn, mesh=mesh, in_specs=(in_spec,),
                     out_specs=out_spec)


def test_allreduce_family(mesh8):
    x = np.arange(8, dtype='float32') + 1.0        # one scalar per device

    def body(v):
        v = v.reshape(())
        return jnp.stack([C.allreduce_sum(v), C.allreduce_mean(v),
                          C.allreduce_max(v), C.allreduce_min(v)])[None]

    out = _smap(mesh8, body)(x)                     # (8, 4)
    np.testing.assert_allclose(out[0], [x.sum(), x.mean(), 8.0, 1.0])
    np.testing.assert_allclose(out, np.tile(out[0], (8, 1)))


def test_c_allreduce_prod_and_named_ops(mesh8):
    x = np.full(8, 2.0, 'float32')

    def body(v):
        v = v.reshape(())
        return jnp.stack([
            C.c_allreduce_sum(v), C.c_allreduce_prod(v),
            C.c_allreduce_max(v), C.c_allreduce_min(v)])[None]
    out = _smap(mesh8, body)(x)
    np.testing.assert_allclose(out[0], [16.0, 256.0, 2.0, 2.0])


def test_allgather_and_reduce_scatter(mesh8):
    x = np.arange(8, dtype='float32')

    def gather_body(v):
        return C.allgather(v.reshape(()))[None]
    g = _smap(mesh8, gather_body, out_spec=P('dp', None))(x)
    np.testing.assert_allclose(np.asarray(g)[0], x)

    xs = np.tile(np.arange(8, dtype='float32'), (8, 1))  # every dev holds 0..7

    def rs_body(v):
        return C.reduce_scatter(v.reshape(-1))[None]
    r = _smap(mesh8, rs_body)(xs)
    # psum_scatter: device i gets sum over devices of shard i = 8 * i
    np.testing.assert_allclose(np.asarray(r).ravel(),
                               8.0 * np.arange(8))


def test_broadcast_root_value(mesh8):
    x = np.arange(8, dtype='float32') * 10

    def body(v):
        return C.broadcast(v.reshape(()), root=3)[None]
    out = _smap(mesh8, body)(x)
    np.testing.assert_allclose(out, 30.0)


def test_ppermute_ring_shift(mesh8):
    x = np.arange(8, dtype='float32')
    perm = [(i, (i + 1) % 8) for i in range(8)]

    def body(v):
        return C.ppermute(v.reshape(()), perm)[None]
    out = _smap(mesh8, body)(x)
    np.testing.assert_allclose(np.asarray(out).ravel(),
                               np.roll(x, 1))


def test_alltoall_transpose(mesh8):
    # each device holds row i; after all-to-all each device holds column i
    x = np.arange(64, dtype='float32').reshape(8, 8)

    def body(v):
        return C.alltoall(v[0])[None]      # (8,) exchange → (8,)
    out = _smap(mesh8, body, in_spec=P('dp', None),
                out_spec=P('dp', None))(x)
    np.testing.assert_allclose(np.asarray(out), x.T)


def test_barrier_and_sync_shims(mesh8):
    x = np.ones(8, 'float32')

    def body(v):
        C.barrier('dp')
        v = C.c_sync_calc_stream(v)
        v = C.c_sync_comm_stream(v)
        return v
    out = _smap(mesh8, body)(x)
    np.testing.assert_allclose(out, x)
