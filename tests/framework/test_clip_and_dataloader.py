"""Static gradient clipping numerics + DataLoader iteration paths
(ref test model: unittests/test_gradient_clip.py, test_dataloader_*)."""
import numpy as np
import pytest

import paddle_tpu as fluid


def _sgd_step_with_clip(clip, lr=1.0):
    """One SGD step on w (shape [3]) whose grad is exactly `g`; returns
    (w_before - w_after) / lr = the applied (clipped) gradient."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data('cl_x', [1, 3], 'float32')
        w = fluid.layers.create_parameter(
            [3], 'float32', name='clip_w',
            attr=fluid.ParamAttr(
                name='clip_w',
                initializer=fluid.initializer.ConstantInitializer(0.0)))
        # loss = sum(x * w) → dL/dw = x
        loss = fluid.layers.reduce_sum(
            fluid.layers.elementwise_mul(
                fluid.layers.reshape(x, shape=[3]), w))
        if clip is not None:
            fluid.clip.set_gradient_clip(clip, program=main)
        fluid.optimizer.SGD(lr).minimize(loss)
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        g = np.array([[3.0, -4.0, 12.0]], 'float32')
        w0 = np.asarray(fluid.global_scope().find('clip_w')).copy()
        exe.run(main, feed={'cl_x': g}, fetch_list=[loss])
        w1 = np.asarray(fluid.global_scope().find('clip_w'))
    return (w0 - w1) / lr, g[0]


def test_no_clip_baseline():
    applied, g = _sgd_step_with_clip(None)
    np.testing.assert_allclose(applied, g, rtol=1e-5)


def test_clip_by_value():
    applied, g = _sgd_step_with_clip(
        fluid.clip.GradientClipByValue(max=2.0, min=-2.0))
    np.testing.assert_allclose(applied, np.clip(g, -2, 2), rtol=1e-5)


def test_clip_by_norm():
    applied, g = _sgd_step_with_clip(fluid.clip.GradientClipByNorm(6.5))
    norm = np.linalg.norm(g)          # 13
    np.testing.assert_allclose(applied, g * 6.5 / norm, rtol=1e-4)
    np.testing.assert_allclose(np.linalg.norm(applied), 6.5, rtol=1e-4)


def test_clip_by_global_norm():
    applied, g = _sgd_step_with_clip(
        fluid.clip.GradientClipByGlobalNorm(1.3))
    np.testing.assert_allclose(np.linalg.norm(applied), 1.3, rtol=1e-4)
    # direction preserved
    np.testing.assert_allclose(applied / np.linalg.norm(applied),
                               g / np.linalg.norm(g), rtol=1e-4)


def test_clip_below_threshold_is_identity():
    applied, g = _sgd_step_with_clip(
        fluid.clip.GradientClipByGlobalNorm(1000.0))
    np.testing.assert_allclose(applied, g, rtol=1e-5)


# -------------------------------------------------------- DataLoader ----

def _loader_prog():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data('dl_x', [-1, 3], 'float32')
        y = fluid.data('dl_y', [-1, 1], 'int64')
    return main, startup, [x, y]


def test_dataloader_sample_generator_batches():
    main, startup, feeds = _loader_prog()

    def samples():
        for i in range(10):
            yield np.full(3, i, 'float32'), np.array([i], 'int64')

    loader = fluid.DataLoader.from_generator(feed_list=feeds, capacity=4)
    loader.set_sample_generator(samples, batch_size=4, drop_last=True)
    batches = list(loader())
    assert len(batches) == 2            # 10 // 4 with drop_last
    assert batches[0]['dl_x'].shape == (4, 3)
    np.testing.assert_allclose(np.asarray(batches[1]['dl_x'])[:, 0],
                               [4, 5, 6, 7])


def test_dataloader_batch_generator_and_return_list():
    main, startup, feeds = _loader_prog()

    def batches():
        for i in range(3):
            yield (np.full((2, 3), i, 'float32'),
                   np.full((2, 1), i, 'int64'))

    loader = fluid.DataLoader.from_generator(feed_list=feeds,
                                             return_list=True)
    loader.set_batch_generator(batches)
    out = list(loader())
    assert len(out) == 3 and len(out[0]) == 2
    np.testing.assert_allclose(np.asarray(out[2][0]), 2.0)


def test_dataloader_feeds_training_loop():
    fluid.manual_seed(11)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data('tlx', [-1, 4], 'float32')
        y = fluid.data('tly', [-1, 1], 'float32')
        pred = fluid.layers.fc(x, 1)
        loss = fluid.layers.reduce_mean(
            fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.Adam(0.05).minimize(loss)
    rng = np.random.RandomState(0)
    X = rng.rand(64, 4).astype('float32')
    W = np.array([[1.0], [2.0], [3.0], [4.0]], 'float32')
    Y = X @ W

    def sample_list():
        for i in range(0, 64, 16):
            yield [(X[j], Y[j]) for j in range(i, i + 16)]

    loader = fluid.DataLoader.from_generator(feed_list=[x, y])
    loader.set_sample_list_generator(sample_list)
    exe = fluid.Executor()
    exe.run(startup)
    losses = []
    for epoch in range(25):
        for feed in loader():
            losses.append(float(exe.run(main, feed=feed,
                                        fetch_list=[loss])[0]))
    assert losses[-1] < losses[0] * 0.05


def test_dataloader_producer_errors_surface():
    main, startup, feeds = _loader_prog()

    def bad():
        yield np.zeros((2, 3), 'float32'), np.zeros((2, 1), 'int64')
        raise RuntimeError('boom in reader')

    loader = fluid.DataLoader.from_generator(feed_list=feeds)
    loader.set_batch_generator(bad)
    with pytest.raises(RuntimeError, match='boom in reader'):
        list(loader())
