"""Native C++ pipeline / tokenizer / packing (with python-fallback parity)."""
import numpy as np

from paddle_tpu import native


def test_pipeline_batches_all_samples():
    pl = native.DataPipeline((2,), 'float32', batch_size=3,
                             shuffle_capacity=4, seed=7)
    data = np.arange(20, dtype='float32').reshape(10, 2)
    pl.feed(iter(data))
    out = np.concatenate(list(pl))
    assert out.shape == (10, 2)
    assert sorted(out[:, 0].tolist()) == sorted(data[:, 0].tolist())


def test_pipeline_drop_last():
    pl = native.DataPipeline((1,), 'float32', batch_size=4, drop_last=True)
    pl.feed(np.arange(10, dtype='float32').reshape(10, 1))
    batches = list(pl)
    assert len(batches) == 2 and all(b.shape == (4, 1) for b in batches)


def test_tuple_pipeline_keeps_fields_aligned():
    img = np.arange(12, dtype='float32').reshape(6, 2)
    lab = np.arange(6, dtype='int64')
    tp = native.TupleDataPipeline([(2,), ()], ['float32', 'int64'],
                                  batch_size=2, shuffle_capacity=4, seed=3)
    tp.feed(zip(img, lab))
    for bi, bl in tp:
        assert bi.shape == (2, 2) and bl.shape == (2,)
        for row, l in zip(bi, bl):
            np.testing.assert_allclose(row, img[l])   # field alignment


def test_wordpiece():
    tok = native.WordPieceTokenizer(
        ['[UNK]', '[CLS]', 'un', '##aff', '##able', 'hello', ','])
    assert tok.tokenize('unaffable') == [2, 3, 4]
    assert tok.tokenize('Hello, unaffable') == [5, 6, 2, 3, 4]
    assert tok.tokenize('xyzzy') == [0]               # unk
    assert tok.vocab_size == 7 and tok.lookup('##aff') == 3


def test_pack_unpack_bucket():
    flat = np.arange(12, dtype='float32').reshape(6, 2)
    lens = np.array([2, 1, 3])
    p = native.pack_padded(flat, lens, pad_value=-1.0)
    assert p.shape == (3, 3, 2)
    np.testing.assert_allclose(p[1, 0], flat[2])
    assert (p[1, 1:] == -1).all()
    u = native.unpack_padded(p, lens)
    np.testing.assert_allclose(u, flat)
    ids = np.arange(5, dtype='int64').reshape(5, 1)
    pi = native.pack_padded(ids, np.array([3, 2]), pad_value=0)
    assert pi.dtype == np.int64 and pi.shape == (2, 3, 1)
    assert native.bucket_by_length(np.array([2, 9, 9, 1])).tolist() == \
        [1, 2, 0, 3]


def test_dataloader_uses_native_batching():
    import paddle_tpu as fluid
    from paddle_tpu import layers
    main, start = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, start):
        x = layers.data('x', shape=[2], dtype='float32')
        y = layers.data('y', shape=[1], dtype='int64')
        loader = fluid.io.DataLoader.from_generator(feed_list=[x, y],
                                                    capacity=4)

    def sample_gen():
        for i in range(7):
            yield np.full(2, i, 'float32'), np.array([i], 'int64')

    loader.set_sample_generator(sample_gen, batch_size=3, drop_last=False)
    batches = list(loader)
    total = sum(b['x'].shape[0] for b in batches)
    assert total == 7
    for b in batches:
        np.testing.assert_allclose(np.asarray(b['x'])[:, 0],
                                   np.asarray(b['y'])[:, 0])


def test_pipeline_propagates_producer_error():
    import pytest
    tp = native.TupleDataPipeline([(2,)], ['float32'], batch_size=2)

    def bad_gen():
        yield (np.zeros(2, 'float32'),)
        yield (np.zeros(3, 'float32'),)   # shape change mid-stream

    tp.feed(bad_gen())
    with pytest.raises(ValueError, match='shape'):
        list(tp)


def test_pipeline_early_break_cancels_producer():
    import threading
    before = threading.active_count()
    for _ in range(3):
        pl = native.DataPipeline((1,), 'float32', batch_size=1,
                                 ring_capacity=1)
        pl.feed(np.zeros((100, 1), 'float32'))
        for b in pl:
            break              # consumer bails; producer must unblock
    import time
    time.sleep(0.3)
    assert threading.active_count() <= before + 1


def test_dataloader_surfaces_producer_error():
    import pytest
    import paddle_tpu as fluid
    from paddle_tpu import layers
    main, start = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, start):
        x = layers.data('x', shape=[2], dtype='float32')
        loader = fluid.io.DataLoader.from_generator(feed_list=[x])

    def bad_gen():
        yield (np.zeros(2, 'float32'),)
        yield (np.zeros(3, 'float32'),)

    loader.set_sample_generator(bad_gen, batch_size=1)
    with pytest.raises(ValueError, match='shape'):
        list(loader)


def test_tokenizer_fallback_parity():
    # compare native vs pure-python on the tricky cases
    vocab = ['[UNK]', 'école', 'a' * 4, '##' + 'a' * 4]
    tok = native.WordPieceTokenizer(vocab)
    if native.is_native():
        long_word = 'a' * 150
        assert tok.tokenize(long_word) == tok._py_tokenize(long_word)
        assert tok.tokenize('École') == tok._py_tokenize('École')
        assert tok.tokenize('aaaaaaaa') == tok._py_tokenize('aaaaaaaa')
