"""Persistent cross-process XLA compilation cache (core/compile_cache.py):
a second COLD process running the same program must deserialize the compiled
executable from disk (jax cache-hit event) instead of recompiling."""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), '..', '..'))

_CHILD = r"""
import json, os
import numpy as np
import jax
from jax._src import monitoring
events = []
monitoring.register_event_listener(lambda name, **kw: events.append(name))
import paddle_tpu as fluid

main, startup = fluid.Program(), fluid.Program()
with fluid.program_guard(main, startup):
    x = fluid.data(name='x', shape=[2, 3], dtype='float32')
    y = fluid.layers.fc(input=x, size=2)
exe = fluid.Executor()   # configures the persistent cache
exe.run(startup)
out = exe.run(main, feed={'x': np.ones((2, 3), np.float32)},
              fetch_list=[y.name])
assert np.isfinite(out[0]).all()
print('CACHE_EVENTS ' + json.dumps({
    'hits': sum(e == '/jax/compilation_cache/cache_hits' for e in events),
    'misses': sum(e == '/jax/compilation_cache/cache_misses' for e in events),
}))
"""


def _run_child(cache_dir):
    env = dict(os.environ,
               JAX_PLATFORMS='cpu',
               PADDLE_TPU_COMPILE_CACHE='1',
               PADDLE_TPU_COMPILE_CACHE_DIR=str(cache_dir),
               PADDLE_TPU_COMPILE_CACHE_MIN_COMPILE_SECS='0')
    r = subprocess.run([sys.executable, '-c', _CHILD], cwd=REPO, env=env,
                       capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stderr[-3000:]
    line = next(ln for ln in r.stdout.splitlines()
                if ln.startswith('CACHE_EVENTS '))
    return json.loads(line.split(' ', 1)[1])


def test_second_cold_process_hits_disk_cache(tmp_path):
    cache_dir = tmp_path / 'xla_cache'
    first = _run_child(cache_dir)
    assert first['misses'] > 0 and first['hits'] == 0, first
    files = os.listdir(cache_dir)
    assert files, "first process must persist compiled executables"
    second = _run_child(cache_dir)
    assert second['hits'] > 0, second
    assert second['misses'] == 0, \
        f"second cold process recompiled despite the disk cache: {second}"


def test_env_hatch_disables_cache(tmp_path):
    cache_dir = tmp_path / 'xla_cache_off'
    env = dict(os.environ, JAX_PLATFORMS='cpu',
               PADDLE_TPU_COMPILE_CACHE='0',
               PADDLE_TPU_COMPILE_CACHE_DIR=str(cache_dir))
    r = subprocess.run(
        [sys.executable, '-c',
         "import paddle_tpu as fluid\n"
         "from paddle_tpu.core.compile_cache import setup_persistent_cache\n"
         "assert setup_persistent_cache() is None\n"
         "fluid.Executor()\n"
         "print('CACHE_OFF_OK')\n"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stderr[-3000:]
    assert 'CACHE_OFF_OK' in r.stdout
    assert not cache_dir.exists()
