"""Executor semantics depth (ref test model: unittests/test_executor_*):
scope isolation, compile-cache behavior across shapes/program edits,
multi-program interleaving, fetch forms, feed dtype coercion."""
import numpy as np
import pytest

import paddle_tpu as fluid


def _linear_prog(name):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data(f'{name}_x', [-1, 3], 'float32')
        out = fluid.layers.fc(x, 2, param_attr=fluid.ParamAttr(
            name=f'{name}_w',
            initializer=fluid.initializer.ConstantInitializer(1.0)),
            bias_attr=False)
    return main, startup, out


def test_scope_isolation():
    main, startup, out = _linear_prog('si')
    exe = fluid.Executor()
    s1, s2 = fluid.Scope(), fluid.Scope()
    x = np.ones((2, 3), 'float32')
    with fluid.scope_guard(s1):
        exe.run(startup)
        r1 = exe.run(main, feed={'si_x': x}, fetch_list=[out])[0]
        fluid.global_scope().set('si_w', np.zeros((3, 2), 'float32'))
        r1z = exe.run(main, feed={'si_x': x}, fetch_list=[out])[0]
    with fluid.scope_guard(s2):
        exe.run(startup)
        r2 = exe.run(main, feed={'si_x': x}, fetch_list=[out])[0]
    np.testing.assert_allclose(r1, 3.0)
    np.testing.assert_allclose(r1z, 0.0)     # s1 was mutated
    np.testing.assert_allclose(r2, 3.0)      # s2 unaffected


def test_variable_feed_shapes_recompile():
    """Different batch sizes must each produce correct results (shape-keyed
    compile cache)."""
    main, startup, out = _linear_prog('vs')
    exe = fluid.Executor()
    exe.run(startup)
    for b in (1, 4, 7, 4):
        r = exe.run(main, feed={'vs_x': np.ones((b, 3), 'float32')},
                    fetch_list=[out])[0]
        assert r.shape == (b, 2)
        np.testing.assert_allclose(r, 3.0)


def test_program_edit_invalidates_cache():
    main, startup, out = _linear_prog('pe')
    exe = fluid.Executor()
    exe.run(startup)
    x = np.ones((2, 3), 'float32')
    r1 = exe.run(main, feed={'pe_x': x}, fetch_list=[out])[0]
    with fluid.program_guard(main, startup):
        out2 = fluid.layers.scale(out, scale=10.0)
    r2 = exe.run(main, feed={'pe_x': x}, fetch_list=[out2])[0]
    np.testing.assert_allclose(r1, 3.0)
    np.testing.assert_allclose(r2, 30.0)


def test_two_programs_interleaved_shared_scope():
    m1, s1, o1 = _linear_prog('tp1')
    m2, s2, o2 = _linear_prog('tp2')
    exe = fluid.Executor()
    exe.run(s1)
    exe.run(s2)
    x = np.ones((2, 3), 'float32')
    for _ in range(2):
        r1 = exe.run(m1, feed={'tp1_x': x}, fetch_list=[o1])[0]
        r2 = exe.run(m2, feed={'tp2_x': 2 * x}, fetch_list=[o2])[0]
    np.testing.assert_allclose(r1, 3.0)
    np.testing.assert_allclose(r2, 6.0)


def test_fetch_by_name_and_by_var_and_empty():
    main, startup, out = _linear_prog('fn')
    exe = fluid.Executor()
    exe.run(startup)
    x = np.ones((2, 3), 'float32')
    by_var = exe.run(main, feed={'fn_x': x}, fetch_list=[out])[0]
    by_name = exe.run(main, feed={'fn_x': x}, fetch_list=[out.name])[0]
    np.testing.assert_allclose(by_var, by_name)
    assert exe.run(main, feed={'fn_x': x}) == []


def test_feed_dtype_coercion():
    """float64/int feeds coerce to the declared var dtype."""
    main, startup, out = _linear_prog('dc')
    exe = fluid.Executor()
    exe.run(startup)
    r = exe.run(main, feed={'dc_x': np.ones((2, 3), 'float64')},
                fetch_list=[out])[0]
    assert r.dtype == np.float32
    np.testing.assert_allclose(r, 3.0)


def test_uninitialized_persistable_raises():
    main, startup, out = _linear_prog('up')
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        with pytest.raises(RuntimeError, match='uninitialized'):
            exe.run(main, feed={'up_x': np.ones((2, 3), 'float32')},
                    fetch_list=[out])


def test_return_numpy_false_returns_fetch_handles():
    main, startup, out = _linear_prog('rn')
    exe = fluid.Executor()
    exe.run(startup)
    r = exe.run(main, feed={'rn_x': np.ones((2, 3), 'float32')},
                fetch_list=[out], return_numpy=False)[0]
    # non-blocking fetch: a FetchHandle over the on-device array —
    # np.asarray is the materialization point
    assert isinstance(r, fluid.FetchHandle)
    assert not r.materialized
    np.testing.assert_allclose(np.asarray(r), 3.0)
    assert r.materialized


def test_prune_keeps_only_needed_ops():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data('pr_x', [2, 3], 'float32')
        a = fluid.layers.scale(x, scale=2.0)
        b = fluid.layers.scale(x, scale=3.0)     # dead for fetch=a
    pruned = main._prune([a])
    types = [op.type for op in pruned.global_block().ops]
    assert len(types) < len(main.global_block().ops)
    exe = fluid.Executor()
    exe.run(startup)
    r = exe.run(pruned, feed={'pr_x': np.ones((2, 3), 'float32')},
                fetch_list=[a])[0]
    np.testing.assert_allclose(r, 2.0)


def test_startup_runs_idempotent():
    main, startup, out = _linear_prog('ip')
    exe = fluid.Executor()
    exe.run(startup)
    w1 = np.asarray(fluid.global_scope().find('ip_w')).copy()
    exe.run(startup)      # re-init: constant init → same values
    w2 = np.asarray(fluid.global_scope().find('ip_w'))
    np.testing.assert_allclose(w1, w2)


def test_clone_for_test_shares_params():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data('cl_x', [4, 3], 'float32')
        h = fluid.layers.fc(x, 4, name='cl_fc')
        h = fluid.layers.dropout(h, 0.5)
        loss = fluid.layers.reduce_mean(h)
        fluid.optimizer.SGD(0.1).minimize(loss)
    test_prog = main.clone(for_test=True)
    exe = fluid.Executor()
    exe.run(startup)
    x = np.ones((4, 3), 'float32')
    # deterministic in test mode: two runs agree
    r1 = exe.run(test_prog, feed={'cl_x': x}, fetch_list=[loss])[0]
    r2 = exe.run(test_prog, feed={'cl_x': x}, fetch_list=[loss])[0]
    np.testing.assert_allclose(r1, r2)
    # training updates the shared parameter; test program sees the change
    exe.run(main, feed={'cl_x': x}, fetch_list=[loss])
    r3 = exe.run(test_prog, feed={'cl_x': x}, fetch_list=[loss])[0]
    assert not np.allclose(r1, r3)
