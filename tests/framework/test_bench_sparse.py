"""tools/bench_sparse.py smoke in tier-1: the rows-only grad+update step
beats the dense scatter at a CI-sized table, the bytes-on-wire
accounting holds the acceptance ratios (dense/int8 ≥ 100×, f32-rows/int8
≥ 3.5×), and the executor-spine sparse path tracks dense losses."""
import os
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(__file__), '..', '..', 'tools'))


def test_bench_sparse_smoke():
    from bench_sparse import (measure_bytes_on_wire,
                              measure_executor_parity,
                              measure_lookup_throughput,
                              measure_step_time)
    lk = measure_lookup_throughput(10_000, 32, 512, iters=5)
    assert lk['lookups_per_sec'] > 0
    st = measure_step_time(100_000, 32, 512, iters=5, accept_ratio=2.0)
    assert st['ok'] and st['parity']
    wire = measure_bytes_on_wire(1_000_000, 64, 4096)
    assert wire['ok']
    assert wire['dense_over_sparse_int8'] >= 100.0
    assert wire['sparse_f32_over_int8'] >= 3.5
    par = measure_executor_parity(2_000, 16, 8, steps=5, batch=16)
    assert par['ok'] and par['loss_allclose']
