"""tier-1 guard for the IR pass-pipeline bench: tools/bench_passes.py must
run end-to-end under JAX_PLATFORMS=cpu at smoke sizes and demonstrate the
PERF.md §10 acceptance margins on the multi-param Adam model — ≥30% jaxpr
eqn-count reduction with fuse_all_optimizer_ops, strict op-count reduction
on every model, and well-formed JSON lines."""
import json
import os
import subprocess
import sys

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), '..', '..'))

MODEL_FIELDS = {'ops_off', 'ops_on', 'eqns_off', 'eqns_on',
                'trace_lower_ms_off', 'trace_lower_ms_on', 'eqn_reduction',
                'op_reduction', 'trace_lower_speedup'}


def test_bench_passes_smoke_runs_on_cpu():
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    env.pop('PADDLE_TPU_PASSES', None)
    r = subprocess.run(
        [sys.executable, os.path.join('tools', 'bench_passes.py'),
         '--smoke', '--iters', '2'],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stderr[-3000:]
    lines = [json.loads(ln) for ln in r.stdout.splitlines() if ln.strip()]
    benches = {d['bench']: d for d in lines if 'bench' in d}
    assert {'passes_mlp_adam', 'passes_resnet_block', 'passes_bert_layer',
            'passes_executor_compile'} <= set(benches)
    for name in ('passes_mlp_adam', 'passes_resnet_block',
                 'passes_bert_layer'):
        d = benches[name]
        assert MODEL_FIELDS <= set(d), d
        # every model: the pipeline strictly shrinks the traced op list
        assert d['ops_on'] < d['ops_off'], d
        assert d['trace_lower_ms_off'] > 0 and d['trace_lower_ms_on'] > 0

    # acceptance: the multi-param Adam bench with fuse_all_optimizer_ops
    # drops ≥30% of jaxpr equations (deterministic — not a timing claim)
    adam = benches['passes_mlp_adam']
    assert adam['eqn_reduction'] >= 0.30, adam
    # directionality of the timing claim (smoke noise allows a soft bound;
    # PERF.md §10 records the measured margin at real sizes)
    assert adam['trace_lower_speedup'] > 1.0, adam

    ec = benches['passes_executor_compile']
    assert {'cold_compile_s_off', 'cold_compile_s_on', 'warm_compile_s_off',
            'warm_compile_s_on', 'warm_compile_speedup'} <= set(ec), ec
    assert ec['warm_compile_s_off'] > 0 and ec['warm_compile_s_on'] > 0
