"""Golden single-step tests for every base optimizer update rule (ref:
tests/unittests/test_*_op.py per optimizer) plus EMA / ModelAverage /
Lookahead apply-restore semantics. Each op's update is checked against a
hand-computed numpy reference on small shapes."""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.ops.registry import get_op

RS = np.random.RandomState


def _pgl(rng, shape=(3, 4)):
    p = rng.standard_normal(shape).astype(np.float32)
    g = rng.standard_normal(shape).astype(np.float32)
    return p, g, np.float32(0.1)


def test_sgd_golden():
    p, g, lr = _pgl(RS(0))
    out = np.asarray(get_op('sgd').fn(p, g, lr))
    np.testing.assert_allclose(out, p - lr * g, rtol=1e-6)


def test_momentum_golden():
    p, g, lr = _pgl(RS(1))
    v = RS(2).standard_normal(p.shape).astype(np.float32)
    mu = 0.9
    pn, vn = get_op('momentum').fn(p, g, v, lr, mu=mu)
    v_ref = mu * v + g
    np.testing.assert_allclose(np.asarray(vn), v_ref, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(pn), p - lr * v_ref, rtol=1e-6)
    # nesterov
    pn2, vn2 = get_op('momentum').fn(p, g, v, lr, mu=mu, use_nesterov=True)
    np.testing.assert_allclose(np.asarray(pn2), p - lr * (g + mu * v_ref),
                               rtol=1e-6)


def test_adam_golden():
    rng = RS(3)
    p, g, lr = _pgl(rng)
    m1 = np.zeros_like(p)
    m2 = np.zeros_like(p)
    b1, b2, eps = 0.9, 0.999, 1e-8
    b1p = np.float32([b1])
    b2p = np.float32([b2])
    pn, m1n, m2n, b1n, b2n = get_op('adam').fn(
        p, g, m1, m2, b1p, b2p, lr, beta1=b1, beta2=b2, epsilon=eps)
    m1_ref = (1 - b1) * g
    m2_ref = (1 - b2) * g * g
    lr_t = lr * np.sqrt(1 - b2p) / (1 - b1p)
    p_ref = p - lr_t * m1_ref / (np.sqrt(m2_ref) + eps)
    np.testing.assert_allclose(np.asarray(pn), p_ref, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(b1n), b1p * b1, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(b2n), b2p * b2, rtol=1e-6)


def test_adamax_golden():
    rng = RS(4)
    p, g, lr = _pgl(rng)
    m = np.zeros_like(p)
    inf = np.zeros_like(p)
    b1, b2, eps = 0.9, 0.999, 1e-8
    b1p = np.float32([b1])
    pn, mn, infn, _ = get_op('adamax').fn(p, g, m, inf, b1p, lr,
                                          beta1=b1, beta2=b2, epsilon=eps)
    m_ref = (1 - b1) * g
    inf_ref = np.maximum(b2 * inf, np.abs(g))
    p_ref = p - (lr / (1 - b1p)) * m_ref / (inf_ref + eps)
    np.testing.assert_allclose(np.asarray(mn), m_ref, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(infn), inf_ref, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(pn), p_ref, rtol=1e-5)


def test_adagrad_golden():
    p, g, lr = _pgl(RS(5))
    mom = np.abs(RS(6).standard_normal(p.shape)).astype(np.float32)
    eps = 1e-6
    pn, mn = get_op('adagrad').fn(p, g, mom, lr, epsilon=eps)
    m_ref = mom + g * g
    np.testing.assert_allclose(np.asarray(mn), m_ref, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(pn),
                               p - lr * g / (np.sqrt(m_ref) + eps),
                               rtol=1e-5)


def test_decayed_adagrad_golden():
    p, g, lr = _pgl(RS(7))
    mom = np.abs(RS(8).standard_normal(p.shape)).astype(np.float32)
    decay, eps = 0.95, 1e-6
    pn, mn = get_op('decayed_adagrad').fn(p, g, mom, lr, decay=decay,
                                          epsilon=eps)
    m_ref = decay * mom + (1 - decay) * g * g
    np.testing.assert_allclose(np.asarray(mn), m_ref, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(pn),
                               p - lr * g / (np.sqrt(m_ref) + eps),
                               rtol=1e-5)


def test_rmsprop_golden():
    p, g, lr = _pgl(RS(9))
    ms = np.abs(RS(10).standard_normal(p.shape)).astype(np.float32)
    mom = RS(11).standard_normal(p.shape).astype(np.float32)
    mg = np.zeros_like(p)
    rho, eps, mu = 0.95, 1e-6, 0.9
    pn, msn, momn, _ = get_op('rmsprop').fn(p, g, ms, mom, mg, lr, rho=rho,
                                            epsilon=eps, momentum=mu)
    ms_ref = rho * ms + (1 - rho) * g * g
    mom_ref = mu * mom + lr * g / np.sqrt(ms_ref + eps)
    np.testing.assert_allclose(np.asarray(msn), ms_ref, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(momn), mom_ref, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(pn), p - mom_ref, rtol=1e-5)


def test_adadelta_golden():
    p, g, _ = _pgl(RS(12))
    asg = np.abs(RS(13).standard_normal(p.shape)).astype(np.float32)
    asu = np.abs(RS(14).standard_normal(p.shape)).astype(np.float32)
    rho, eps = 0.95, 1e-6
    pn, asgn, asun = get_op('adadelta').fn(p, g, asg, asu, rho=rho,
                                           epsilon=eps)
    asg_ref = rho * asg + (1 - rho) * g * g
    upd = np.sqrt(asu + eps) / np.sqrt(asg_ref + eps) * g
    asu_ref = rho * asu + (1 - rho) * upd * upd
    np.testing.assert_allclose(np.asarray(asgn), asg_ref, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(asun), asu_ref, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(pn), p - upd, rtol=1e-5)


def test_ftrl_golden():
    p, g, lr = _pgl(RS(15))
    sq = np.abs(RS(16).standard_normal(p.shape)).astype(np.float32)
    lin = RS(17).standard_normal(p.shape).astype(np.float32)
    l1, l2, lr_pow = 0.1, 0.2, -0.5
    pn, sqn, linn = get_op('ftrl').fn(p, g, sq, lin, lr, l1=l1, l2=l2,
                                      lr_power=lr_pow)
    new_acc = sq + g * g
    sigma = (new_acc ** (-lr_pow) - sq ** (-lr_pow)) / lr
    lin_ref = lin + g - sigma * p
    x = l1 * np.sign(lin_ref) - lin_ref
    y = new_acc ** (-lr_pow) / lr + 2 * l2
    p_ref = np.where(np.abs(lin_ref) > l1, x / y, 0.0)
    np.testing.assert_allclose(np.asarray(sqn), new_acc, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(linn), lin_ref, rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(pn), p_ref, rtol=1e-4, atol=1e-5)


def test_lamb_golden():
    rng = RS(18)
    p, g, lr = _pgl(rng)
    m1 = np.zeros_like(p)
    m2 = np.zeros_like(p)
    b1, b2, eps, wd = 0.9, 0.999, 1e-6, 0.01
    b1p = np.float32([b1])
    b2p = np.float32([b2])
    pn, m1n, m2n, _, _ = get_op('lamb').fn(
        p, g, m1, m2, b1p, b2p, lr, weight_decay=wd, beta1=b1, beta2=b2,
        epsilon=eps)
    m1_ref = (1 - b1) * g
    m2_ref = (1 - b2) * g * g
    m1h = m1_ref / (1 - b1p)
    m2h = m2_ref / (1 - b2p)
    r = m1h / (np.sqrt(m2h) + eps) + wd * p
    pnorm = np.sqrt((p * p).sum())
    rnorm = np.sqrt((r * r).sum())
    trust = pnorm / rnorm if pnorm > 0 and rnorm > 0 else 1.0
    np.testing.assert_allclose(np.asarray(pn), p - lr * trust * r,
                               rtol=1e-4, atol=1e-6)


def test_lars_momentum_golden():
    p, g, lr = _pgl(RS(19))
    v = RS(20).standard_normal(p.shape).astype(np.float32)
    mu, coeff, wd = 0.9, 0.001, 0.0005
    pn, vn = get_op('lars_momentum').fn(p, g, v, lr, mu=mu, lars_coeff=coeff,
                                        lars_weight_decay=wd)
    pnorm = np.sqrt((p * p).sum())
    gnorm = np.sqrt((g * g).sum())
    local_lr = lr * coeff * pnorm / (gnorm + wd * pnorm)
    v_ref = mu * v + local_lr * (g + wd * p)
    np.testing.assert_allclose(np.asarray(vn), v_ref, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(pn), p - v_ref, rtol=1e-5)


def test_dpsgd_updates_with_clipped_noisy_grad():
    import jax
    p, g, lr = _pgl(RS(21))
    out = np.asarray(get_op('dpsgd').fn(p, g, lr, clip=1.0, batch_size=4.0,
                                        sigma=0.1, key=jax.random.PRNGKey(0)))
    assert out.shape == p.shape
    assert np.abs(out - p).max() > 0
    # clipped: the applied gradient norm can't exceed clip + noise bound
    gn = np.sqrt((g * g).sum())
    applied = (p - out) / lr
    assert np.sqrt((applied * applied).sum()) < gn + 5.0


def test_dgc_momentum_golden_sparsity():
    p, g, lr = _pgl(RS(22))
    v = np.zeros_like(p)
    e = np.zeros_like(p)
    pn, vn, en = get_op('dgc_momentum').fn(p, g, v, e, lr, mu=0.9,
                                           sparsity=0.75)
    # 25% of 12 = 3 entries survive; error feedback keeps the rest
    acc = e + g
    k = max(1, int(acc.size * 0.25))
    thresh = np.sort(np.abs(acc).ravel())[-k]
    mask = np.abs(acc) >= thresh
    sparse = acc * mask
    np.testing.assert_allclose(np.asarray(en), acc - sparse, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(vn), 0.9 * v + sparse, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(pn), p - lr * np.asarray(vn),
                               rtol=1e-5)


# ---------------------------------------------------------------------------
# static-graph integration: each optimizer class trains a tiny regression
# ---------------------------------------------------------------------------
OPTIMIZER_FACTORIES = [
    lambda: fluid.optimizer.SGD(learning_rate=0.1),
    lambda: fluid.optimizer.Momentum(learning_rate=0.05, momentum=0.9),
    lambda: fluid.optimizer.LarsMomentum(learning_rate=50.0, momentum=0.5),
    lambda: fluid.optimizer.Adagrad(learning_rate=0.3),
    lambda: fluid.optimizer.Adam(learning_rate=0.1),
    lambda: fluid.optimizer.Adamax(learning_rate=0.1),
    lambda: fluid.optimizer.DecayedAdagrad(learning_rate=0.3),
    # epsilon floors RMS[Δx] for the first steps: with the paper default
    # 1e-6, genuine (lr-free) adadelta moves ~1e-3/step and cannot cut this
    # loss 30% in 100 steps — ε=1e-3 is the standard small-problem setting
    lambda: fluid.optimizer.Adadelta(learning_rate=1.0, epsilon=1e-3),
    lambda: fluid.optimizer.RMSProp(learning_rate=0.05),
    lambda: fluid.optimizer.Ftrl(learning_rate=0.5),
    lambda: fluid.optimizer.Lamb(learning_rate=0.1),
    lambda: fluid.optimizer.Dpsgd(learning_rate=0.05, clip=100.0, sigma=0.0),
]


@pytest.mark.parametrize('factory', OPTIMIZER_FACTORIES,
                         ids=lambda f: type(f()).__name__)
def test_optimizer_trains_static(factory):
    main, start = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, start):
        fluid.framework.manual_seed(0)
        x = layers.data('x', [4], dtype='float32')
        y = layers.data('y', [1], dtype='float32')
        pred = layers.fc(x, size=1)
        loss = layers.reduce_mean(layers.square_error_cost(pred, y))
        factory().minimize(loss)
    exe = fluid.Executor()
    exe.run(start)
    rng = RS(0)
    w = rng.standard_normal((4, 1)).astype(np.float32)
    losses = []
    for _ in range(100):
        xv = rng.standard_normal((16, 4)).astype(np.float32)
        l, = exe.run(main, feed={'x': xv, 'y': xv @ w}, fetch_list=[loss])
        losses.append(float(np.asarray(l).reshape(())[()]))
    assert losses[-1] < losses[0] * 0.7, losses[:3] + losses[-3:]


# ---------------------------------------------------------------------------
# EMA / ModelAverage / Lookahead (dygraph apply/restore semantics)
# ---------------------------------------------------------------------------
def test_exponential_moving_average_apply_restore():
    from paddle_tpu import dygraph
    import jax.numpy as jnp
    with dygraph.guard():
        fc = dygraph.nn.Linear(3, 2)
        ema = fluid.optimizer.ExponentialMovingAverage(decay=0.5)
        params = list(fc.parameters())
        orig = [np.asarray(p.value).copy() for p in params]
        ema.update(params)
        for p in params:
            p.value = p.value + 1.0
        moved = [np.asarray(p.value).copy() for p in params]
        ema.update(params)
        ema.apply(params)
        for p, o, m in zip(params, orig, moved):
            cur = np.asarray(p.value)
            assert not np.allclose(cur, m)     # averaged, not last value
        ema.restore(params)
        for p, m in zip(params, moved):
            np.testing.assert_allclose(np.asarray(p.value), m, rtol=1e-6)


def test_model_average_apply_restore():
    from paddle_tpu import dygraph
    with dygraph.guard():
        fc = dygraph.nn.Linear(3, 2)
        ma = fluid.optimizer.ModelAverage(0.15)
        params = list(fc.parameters())
        v0 = [np.asarray(p.value).copy() for p in params]
        ma.accumulate(params)
        for p in params:
            p.value = p.value + 2.0
        v1 = [np.asarray(p.value).copy() for p in params]
        ma.accumulate(params)
        ma.apply_params(params)
        for p, a, b in zip(params, v0, v1):
            np.testing.assert_allclose(np.asarray(p.value), (a + b) / 2,
                                       rtol=1e-5)
        ma.restore_params(params)
        for p, b in zip(params, v1):
            np.testing.assert_allclose(np.asarray(p.value), b, rtol=1e-6)


def test_lookahead_slow_weights():
    from paddle_tpu import dygraph
    with dygraph.guard():
        fc = dygraph.nn.Linear(2, 1)
        inner = fluid.optimizer.SGD(learning_rate=0.1,
                                    parameter_list=fc.parameters())
        look = fluid.optimizer.LookaheadOptimizer(inner, alpha=0.5, k=2)
        x = dygraph.to_variable(np.ones((4, 2), np.float32))
        w0 = np.asarray(fc.parameters()[0].value).copy()
        for i in range(2):
            out = fc(x)
            loss = layers.reduce_mean(out)
            loss.backward()
            look.minimize(loss, parameter_list=fc.parameters())
            inner.clear_gradients()
        # after k=2 steps, params are slow weights: w0 + alpha*(fast - w0)
        w_now = np.asarray(fc.parameters()[0].value)
        assert not np.allclose(w_now, w0)
