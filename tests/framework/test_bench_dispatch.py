"""tier-1 guard for the dispatch microbench harness: tools/bench_dispatch.py
must run end-to-end under JAX_PLATFORMS=cpu (2 slope iterations, smoke
shapes) and emit well-formed JSON lines with the PERF.md §9 fields."""
import json
import os
import subprocess
import sys

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), '..', '..'))

REQUIRED = {'eager_uncached_ms', 'eager_cached_ms', 'train_step_ms',
            'cache_speedup', 'eager_cached_vs_fused', 'cache_hits',
            'cache_misses'}


def test_bench_dispatch_smoke_runs_on_cpu():
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    r = subprocess.run(
        [sys.executable, os.path.join('tools', 'bench_dispatch.py'),
         '--smoke', '--iters', '2'],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stderr[-3000:]
    lines = [json.loads(ln) for ln in r.stdout.splitlines() if ln.strip()]
    benches = {d['bench']: d for d in lines if 'bench' in d}
    assert {'dispatch_resnet_block', 'dispatch_bert_layer'} <= set(benches)
    for d in benches.values():
        assert REQUIRED <= set(d), d
        assert d['eager_uncached_ms'] > 0 and d['eager_cached_ms'] > 0
        assert d['cache_hits'] > 0, \
            "a repeated eager step must hit the kernel cache"
        # directionality only (smoke timing is noisy; PERF.md §9 records the
        # real margin — >= 2x on the ResNet block at measurement sizes)
        assert d['cache_speedup'] > 1.0, d
