"""Fleet runtime unit suite (ISSUE 12): strict-parse bootstrap env,
cross-host primitives (single-host degenerate forms), the poison-flag
sentinel with its watchdog hook, partitioner-sharded checkpoints
(forced-sharded on the single-process 8-device mesh), DataLoader per-host
sharding, sync-BN parity, and the LARS large-batch pieces. The REAL
multi-process behaviors are covered by test_fleet_crash_resume.py."""
import json
import os

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers as L


# ---------------------------------------------------------------------------
# strict-parse env discovery
# ---------------------------------------------------------------------------

def _env(**kw):
    return {k: str(v) for k, v in kw.items()}


def test_discover_none_when_unset():
    from paddle_tpu.fleet_runtime.bootstrap import discover_fleet_env
    assert discover_fleet_env({}) is None


def test_discover_single_host():
    from paddle_tpu.fleet_runtime.bootstrap import discover_fleet_env
    spec = discover_fleet_env(_env(PADDLE_TRAINERS_NUM=1))
    assert spec.num_trainers == 1 and spec.trainer_id == 0


def test_discover_full_fleet_env():
    from paddle_tpu.fleet_runtime.bootstrap import discover_fleet_env
    spec = discover_fleet_env(_env(
        PADDLE_TRAINERS_NUM=2, PADDLE_TRAINER_ID=1,
        PADDLE_TRAINER_ENDPOINTS='a:1,b:2', PADDLE_CURRENT_ENDPOINT='b:2'))
    assert spec.num_trainers == 2 and spec.trainer_id == 1
    assert spec.coordinator_address == 'a:1'      # endpoint 0 convention
    assert spec.endpoints == ['a:1', 'b:2']


@pytest.mark.parametrize('env, frag', [
    (_env(PADDLE_TRAINERS_NUM='two'), 'must be an integer'),
    (_env(PADDLE_TRAINER_ID=0), 'PADDLE_TRAINERS_NUM is missing'),
    (_env(PADDLE_TRAINERS_NUM=2), 'PADDLE_TRAINER_ID is missing'),
    (_env(PADDLE_TRAINERS_NUM=2, PADDLE_TRAINER_ID=2,
          PADDLE_TRAINER_ENDPOINTS='a:1,b:2'), 'outside'),
    (_env(PADDLE_TRAINERS_NUM=2, PADDLE_TRAINER_ID=0,
          PADDLE_TRAINER_ENDPOINTS='a:1'), 'lists 1 endpoints'),
    (_env(PADDLE_TRAINERS_NUM=2, PADDLE_TRAINER_ID=0,
          PADDLE_TRAINER_ENDPOINTS='a:1,a:1'), 'duplicate'),
    (_env(PADDLE_TRAINERS_NUM=2, PADDLE_TRAINER_ID=0,
          PADDLE_TRAINER_ENDPOINTS='a:1,b:2',
          PADDLE_CURRENT_ENDPOINT='c:3'), 'not in'),
    (_env(PADDLE_TRAINERS_NUM=2, PADDLE_TRAINER_ID=0,
          PADDLE_TRAINER_ENDPOINTS='a:1,b:2',
          PADDLE_CURRENT_ENDPOINT='b:2'), 'contradictory rank'),
    (_env(PADDLE_TRAINERS_NUM=2, PADDLE_TRAINER_ID=0), 'rendezvous'),
    (_env(PADDLE_TRAINERS_NUM=2, PADDLE_TRAINER_ID=0,
          PADDLE_TRAINER_ENDPOINTS='bare'), 'host:port'),
])
def test_discover_strict_parse_raises_listing_vars(env, frag):
    from paddle_tpu.fleet_runtime.bootstrap import discover_fleet_env
    with pytest.raises(ValueError) as ei:
        discover_fleet_env(env)
    msg = str(ei.value)
    assert frag in msg
    # every error names the full expected-variable contract
    for var in ('PADDLE_TRAINERS_NUM', 'PADDLE_TRAINER_ID',
                'PADDLE_TRAINER_ENDPOINTS', 'PADDLE_CURRENT_ENDPOINT'):
        assert var in msg


def test_role_maker_reads_env_and_raises_on_contradiction(monkeypatch):
    from paddle_tpu.parallel.fleet import PaddleCloudRoleMaker
    monkeypatch.setenv('PADDLE_TRAINERS_NUM', '4')
    monkeypatch.setenv('PADDLE_TRAINER_ID', '3')
    monkeypatch.setenv('PADDLE_TRAINER_ENDPOINTS', 'a:1,b:2,c:3,d:4')
    monkeypatch.setenv('PADDLE_CURRENT_ENDPOINT', 'd:4')
    rm = PaddleCloudRoleMaker()
    assert rm.worker_num() == 4
    assert rm.worker_index() == 3
    assert not rm.is_first_worker()
    assert rm.worker_endpoints() == ['a:1', 'b:2', 'c:3', 'd:4']

    monkeypatch.setenv('PADDLE_TRAINER_ID', '9')
    with pytest.raises(ValueError, match='outside'):
        PaddleCloudRoleMaker().generate_role()


def test_incubate_role_maker_module_exports():
    from paddle_tpu.incubate.fleet.base import role_maker
    assert role_maker.MPISymetricRoleMaker is role_maker.PaddleCloudRoleMaker
    assert role_maker.GeneralRoleMaker is role_maker.PaddleCloudRoleMaker


# ---------------------------------------------------------------------------
# cross-host primitives: single-host degenerate forms
# ---------------------------------------------------------------------------

def test_primitives_single_host():
    from paddle_tpu import fleet_runtime as fr
    fr.fleet_barrier('t')                       # no-op, no raise
    assert fr.broadcast_from_host0({'a': 1}) == {'a': 1}
    assert fr.all_hosts_agree({'step': 3})
    assert fr.fleet_allreduce_scalars([1.0, 2.5]) == [1.0, 2.5]
    with pytest.raises(ValueError, match='unknown op'):
        fr.fleet_allreduce_scalars([1.0], op='median')


def test_bootstrap_single_host_wires_mesh():
    from paddle_tpu import fleet_runtime as fr
    from paddle_tpu.partition import get_partitioner, reset_partitioner
    reset_partitioner()
    try:
        assert fr.bootstrap() is None            # no fleet env → None spec
        import jax
        assert get_partitioner().axis_sizes() == {'dp': jax.device_count()}
    finally:
        reset_partitioner()


# ---------------------------------------------------------------------------
# the poison-flag sentinel (file backend) + watchdog hook
# ---------------------------------------------------------------------------

def test_sentinel_post_check_clear(tmp_path, monkeypatch):
    from paddle_tpu.fleet_runtime.coordinator import FleetSentinel
    monkeypatch.setenv('PADDLE_TPU_FLEET_DIR', str(tmp_path))
    a = FleetSentinel(source=0)
    b = FleetSentinel(source=1)
    assert b.check() is None
    rec = a.post('divergence detected', step=12, kind='supervisor')
    assert rec['source'] == 0
    # the poster never poisons itself; every OTHER host sees it
    assert a.check() is None or a.check()['source'] != 0
    got = b.check()
    assert got is not None and got['source'] == 0
    assert got['reason'] == 'divergence detected' and got['step'] == 12
    b.clear()
    assert b.check() is None


def test_sentinel_raise_if_poisoned(tmp_path, monkeypatch):
    from paddle_tpu.fleet_runtime.coordinator import (FleetSentinel,
                                                      FleetPoisoned)
    monkeypatch.setenv('PADDLE_TPU_FLEET_DIR', str(tmp_path))
    FleetSentinel(source=0).post('boom', step=1)
    with pytest.raises(FleetPoisoned, match='boom'):
        FleetSentinel(source=1).raise_if_poisoned()


def test_watchdog_breach_posts_poison(tmp_path, monkeypatch):
    """The fleet propagation ladder's watchdog rung: a deadline breach on
    one host posts the poison flag BEFORE the abort exit."""
    from paddle_tpu.fleet_runtime import coordinator as coord
    from paddle_tpu.resilience.watchdog import Watchdog
    monkeypatch.setenv('PADDLE_TPU_FLEET_DIR', str(tmp_path))
    coord.clear_sentinel()
    try:
        coord.install_sentinel(source=0)
        wd = Watchdog(floor_s=0.05, cold_s=0.05, abort=False,
                      dump_dir=str(tmp_path), poll_s=0.01)
        lease = wd.arm('fleet_step')
        import time
        deadline = time.monotonic() + 5
        while not wd.breaches and time.monotonic() < deadline:
            time.sleep(0.02)
        wd.stop()
        assert wd.breaches, 'watchdog never fired'
        observer = coord.FleetSentinel(source=1)
        rec = observer.check()
        assert rec is not None and rec['kind'] == 'watchdog'
        assert 'fleet_step' in rec['reason']
    finally:
        coord.clear_sentinel()


def test_manager_exits_for_resume_on_poison(tmp_path, monkeypatch):
    """CheckpointManager.end_of_step returns True (exit-for-resume) when
    another host poisoned the fleet, without saving."""
    from paddle_tpu import resilience
    from paddle_tpu.fleet_runtime import coordinator as coord
    monkeypatch.setenv('PADDLE_TPU_FLEET_DIR', str(tmp_path))
    coord.clear_sentinel()
    try:
        coord.install_sentinel(source=0)
        mgr = resilience.CheckpointManager(
            str(tmp_path / 'ck'), every_n_steps=1, async_save=False,
            install_signal_handlers=False)
        coord.FleetSentinel(source=9).post('peer died', step=3)
        calls = []
        stop = mgr.end_of_step(4, lambda: calls.append(1) or {})
        assert stop is True
        assert mgr.fleet_poisoned['reason'] == 'peer died'
        assert not calls, 'poisoned boundary must not capture state'
        assert mgr.latest() is None, 'poisoned boundary must not save'
        mgr.close()
    finally:
        coord.clear_sentinel()


# ---------------------------------------------------------------------------
# sharded checkpoints (forced, single process, 8-device mesh)
# ---------------------------------------------------------------------------

@pytest.fixture
def fsdp_mesh():
    from paddle_tpu.partition import configure, reset_partitioner
    reset_partitioner()
    configure(mesh_shape={'fsdp': 8})
    yield
    reset_partitioner()


def _sharded_state(part):
    import jax
    import jax.numpy as jnp
    rng = np.random.RandomState(0)
    W = rng.randn(16, 8).astype(np.float32)
    V = rng.randn(16, 8).astype(np.float32)
    w = jax.device_put(jnp.asarray(W), part.param_sharding('w', W.shape))
    v = jax.device_put(jnp.asarray(V), part.param_sharding('w_velocity',
                                                           V.shape))
    lr = jnp.asarray([0.1], jnp.float32)        # replicated scalar-ish
    return {'scope/w': w, 'scope/w_velocity': v, 'scope/lr': lr}, \
        {'scope/w': W, 'scope/w_velocity': V,
         'scope/lr': np.asarray([0.1], np.float32)}


def test_forced_sharded_roundtrip_bitwise(tmp_path, fsdp_mesh, monkeypatch):
    from paddle_tpu import resilience
    from paddle_tpu.partition import get_partitioner
    monkeypatch.setenv('PADDLE_TPU_FLEET_SHARDED', '1')
    state, want = _sharded_state(get_partitioner())
    mgr = resilience.CheckpointManager(str(tmp_path), every_n_steps=1,
                                       async_save=False,
                                       install_signal_handlers=False)
    mgr.save(7, state, {'rng': {'global_seed': 3},
                        'loader': {'epoch': 1, 'batch': 2}})
    ck = mgr.latest()
    assert ck.sharded and ck.manifest['world'] == 1
    arrays, meta = mgr.restore(ck)
    for k in want:
        assert np.array_equal(arrays[k], want[k]), k
    # this host's own meta came back through the shard manifest overlay
    assert meta['rng'] == {'global_seed': 3}
    assert meta['loader'] == {'epoch': 1, 'batch': 2}
    mgr.close()


def test_forced_sharded_tile_layout(tmp_path, fsdp_mesh, monkeypatch):
    """Tiles mirror the fsdp placement: the 2-D fsdp-sharded arrays are
    stored as 8 row tiles, replicated values as ONE full tile."""
    from paddle_tpu.fleet_runtime import sharded_ckpt as sc
    from paddle_tpu.partition import get_partitioner
    monkeypatch.setenv('PADDLE_TPU_FLEET_SHARDED', '1')
    state, _ = _sharded_state(get_partitioner())
    sm = sc.write_host_shard(str(tmp_path), 3, state, rank=0, world=1)
    tiles_w = sm['arrays']['scope/w']['tiles']
    assert len(tiles_w) == 8
    assert sorted(t['index'][0] for t in tiles_w) == \
        [[2 * i, 2 * i + 2] for i in range(8)]
    assert len(sm['arrays']['scope/lr']['tiles']) == 1


def test_sharded_strict_env(monkeypatch):
    from paddle_tpu.fleet_runtime.sharded_ckpt import sharded_save_enabled
    monkeypatch.setenv('PADDLE_TPU_FLEET_SHARDED', 'yes')
    with pytest.raises(ValueError, match='must be 0 or 1'):
        sharded_save_enabled()


def test_torn_host_shard_skipped_by_discovery(tmp_path, fsdp_mesh,
                                              monkeypatch):
    """A missing or truncated HOST SHARD makes the whole fleet checkpoint
    invisible — discovery falls back to the previous valid one."""
    from paddle_tpu import resilience
    from paddle_tpu.partition import get_partitioner
    monkeypatch.setenv('PADDLE_TPU_FLEET_SHARDED', '1')
    state, _ = _sharded_state(get_partitioner())
    mgr = resilience.CheckpointManager(str(tmp_path), async_save=False,
                                       install_signal_handlers=False)
    mgr.save(3, dict(state), {})
    mgr.save(6, dict(state), {})
    assert mgr.latest().step == 6
    shard6 = tmp_path / 'ckpt-00000006.shard00of01.npz'
    with open(shard6, 'r+b') as f:
        f.truncate(64)                           # torn shard write
    assert mgr.latest().step == 3
    os.unlink(shard6)                            # shard vanished entirely
    assert mgr.latest().step == 3
    mgr.close()


def test_sharded_gc_deletes_shard_files(tmp_path, fsdp_mesh, monkeypatch):
    from paddle_tpu import resilience
    from paddle_tpu.partition import get_partitioner
    monkeypatch.setenv('PADDLE_TPU_FLEET_SHARDED', '1')
    state, _ = _sharded_state(get_partitioner())
    mgr = resilience.CheckpointManager(str(tmp_path), keep=1,
                                       async_save=False,
                                       install_signal_handlers=False)
    for step in (1, 2, 3):
        mgr.save(step, dict(state), {})
    names = sorted(os.listdir(tmp_path))
    assert not any('00000001' in n or '00000002' in n for n in names), names
    assert any('00000003' in n for n in names)
    mgr.close()


def test_read_rejects_incomplete_tiles(tmp_path, fsdp_mesh, monkeypatch):
    """Tile coverage is validated: a shard manifest claiming fewer
    elements than the global shape raises instead of returning
    silently-partial state."""
    from paddle_tpu.fleet_runtime import sharded_ckpt as sc
    from paddle_tpu.resilience import snapshot as snap
    from paddle_tpu.partition import get_partitioner
    monkeypatch.setenv('PADDLE_TPU_FLEET_SHARDED', '1')
    state, _ = _sharded_state(get_partitioner())
    sc.write_host_shard(str(tmp_path), 5, state, rank=0, world=1)
    sc.commit_fleet_manifest(str(tmp_path), 5, 1)
    # drop one tile from the shard manifest (simulated writer bug)
    mpath = tmp_path / 'ckpt-00000005.shard00of01.json'
    m = json.loads(mpath.read_text())
    m['arrays']['scope/w']['tiles'] = m['arrays']['scope/w']['tiles'][:-1]
    mpath.write_text(json.dumps(m))
    # shard payload is untouched so discovery still validates...
    ck = snap.latest_checkpoint(str(tmp_path))
    assert ck is not None
    with pytest.raises(ValueError, match='cover'):
        snap.read_checkpoint(ck)


# ---------------------------------------------------------------------------
# DataLoader per-host sharding
# ---------------------------------------------------------------------------

def _loader(batches):
    main = fluid.Program()
    with fluid.program_guard(main, fluid.Program()):
        x = L.data('flx', [4], dtype='float32')
    loader = fluid.DataLoader.from_generator(
        feed_list=[main.global_block().var('flx')], capacity=2)
    loader.set_batch_generator(lambda: iter(batches))
    return loader


def test_loader_shard_slices_rows():
    rng = np.random.RandomState(0)
    batches = [(rng.randn(8, 4).astype('float32'),) for _ in range(3)]
    loader = _loader(batches).shard_for_fleet(num_shards=2, shard_id=1)
    got = [b['flx'] for b in loader()]
    assert len(got) == 3
    for full, mine in zip(batches, got):
        assert np.array_equal(np.asarray(mine), full[0][1::2])


def test_loader_shard_identity_and_validation():
    batches = [(np.zeros((4, 4), np.float32),)]
    loader = _loader(batches)
    assert loader.shard_for_fleet(num_shards=1, shard_id=0) is loader
    assert loader._shard_n is None               # 1-host fleet = no-op
    with pytest.raises(ValueError, match='outside'):
        loader.shard_for_fleet(num_shards=2, shard_id=2)


def test_loader_shard_batch_too_small():
    loader = _loader([(np.zeros((1, 4), np.float32),)])
    loader.shard_for_fleet(num_shards=2, shard_id=0)
    with pytest.raises(ValueError, match='smaller than'):
        list(loader())


def test_loader_shard_cursor_is_global(tmp_path):
    """The resume cursor counts GLOBAL batches: skipping applies before
    the shard slice, so a restored host re-reads exactly its own rows of
    the remaining stream."""
    rng = np.random.RandomState(1)
    batches = [(rng.randn(4, 4).astype('float32'),) for _ in range(4)]
    loader = _loader(batches).shard_for_fleet(num_shards=2, shard_id=0)
    it = iter(loader())
    next(it), next(it)
    st = loader.state_dict()
    assert st['batch'] == 2
    del it
    loader2 = _loader(batches).shard_for_fleet(num_shards=2, shard_id=0)
    loader2.set_state_dict(st)
    rest = [b['flx'] for b in loader2()]
    assert len(rest) == 2
    assert np.array_equal(np.asarray(rest[0]), batches[2][0][0::2])


# ---------------------------------------------------------------------------
# sync-BN
# ---------------------------------------------------------------------------

def test_sync_bn_matches_single_process_global_batch():
    """sync_stats under explicit SPMD (shard_map over the 8-way data
    mesh) reproduces single-process global-batch statistics; without it,
    per-shard stats diverge."""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from paddle_tpu.core import compat
    from paddle_tpu.ops.nn_ops import batch_norm
    from paddle_tpu.partition import configure, get_partitioner, \
        reset_partitioner
    reset_partitioner()
    try:
        configure(mesh_shape={'dp': 8})
        mesh = get_partitioner().mesh
        rng = np.random.RandomState(0)
        X = (rng.randn(32, 4, 6, 6) * 3 + 1).astype('float32')
        scale = np.ones(4, 'float32')
        bias = np.zeros(4, 'float32')
        mean = np.zeros(4, 'float32')
        var = np.ones(4, 'float32')
        y_ref, m_ref, v_ref = batch_norm(X, scale, bias, mean, var)

        def body(x, sync):
            y, m, v = batch_norm(x, scale, bias, mean, var,
                                 sync_stats=sync)
            return (y, compat.pcast(m, 'dp', to='varying'),
                    compat.pcast(v, 'dp', to='varying'))

        f = compat.shard_map(lambda x: body(x, True), mesh=mesh,
                             in_specs=P('dp'),
                             out_specs=(P('dp'), P(), P()))
        y, m, v = f(jnp.asarray(X))
        np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref),
                                   atol=1e-5)
        np.testing.assert_allclose(np.asarray(m), np.asarray(m_ref),
                                   atol=1e-6)
        np.testing.assert_allclose(np.asarray(v), np.asarray(v_ref),
                                   atol=1e-5)

        f0 = compat.shard_map(lambda x: body(x, False)[0], mesh=mesh,
                              in_specs=P('dp'), out_specs=P('dp'))
        y_unsync = f0(jnp.asarray(X))
        assert not np.allclose(np.asarray(y_unsync), np.asarray(y_ref),
                               atol=1e-5)
    finally:
        reset_partitioner()


def test_sync_bn_static_layer_attr_and_gspmd_identity():
    """The layer threads sync_stats through; on the GSPMD executor (no
    bound axis) it is the identity — same losses with and without."""
    def run(sync):
        fluid.seed(77)
        main, start = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, start):
            x = L.data('sx', [4, 6, 6], dtype='float32')
            y = L.data('sy', [1], dtype='float32')
            h = L.batch_norm(L.conv2d(x, num_filters=4, filter_size=3,
                                      padding=1),
                             act='relu', sync_stats=sync)
            pred = L.fc(h, size=1)
            loss = L.mean(L.square_error_cost(pred, y))
            fluid.optimizer.SGD(0.1).minimize(loss)
        assert any(op.attrs.get('sync_stats') == sync
                   for op in main.global_block().ops
                   if op.type == 'batch_norm')
        exe = fluid.Executor()
        rng = np.random.RandomState(5)
        X = rng.randn(8, 4, 6, 6).astype('float32')
        Y = rng.randn(8, 1).astype('float32')
        with fluid.scope_guard(fluid.Scope()):
            exe.run(start)
            return [np.asarray(exe.run(main, feed={'sx': X, 'sy': Y},
                                       fetch_list=[loss])[0])
                    for _ in range(3)]
    a, b = run(False), run(True)
    assert all(np.array_equal(x, y) for x, y in zip(a, b))


# ---------------------------------------------------------------------------
# LARS large-batch pieces
# ---------------------------------------------------------------------------

def test_lars_exclude_from_weight_decay_static():
    main, start = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, start):
        x = L.data('lx', [4], dtype='float32')
        y = L.data('ly', [1], dtype='float32')
        pred = L.fc(x, size=1)
        loss = L.mean(L.square_error_cost(pred, y))
        fluid.optimizer.LarsMomentumOptimizer(
            0.1, exclude_from_weight_decay_fn=lambda p: '.b_' in p.name,
        ).minimize(loss)
    ops = [op for op in main.global_block().ops
           if op.type == 'lars_momentum']
    assert len(ops) == 2
    by_wd = {op.attrs['lars_weight_decay'] for op in ops}
    assert by_wd == {0.0, 0.0005}, by_wd
    assert all('epsilon' in op.attrs for op in ops)


def test_lamb_exclude_fn_now_live():
    main, start = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, start):
        x = L.data('bx', [4], dtype='float32')
        pred = L.fc(x, size=1)
        loss = L.mean(pred)
        fluid.optimizer.LambOptimizer(
            0.01, exclude_from_weight_decay_fn=lambda p: '.b_' in p.name,
        ).minimize(loss)
    wds = sorted(op.attrs['weight_decay']
                 for op in main.global_block().ops if op.type == 'lamb')
    assert wds == [0.0, 0.01]


def test_fused_lars_bitwise_vs_per_param():
    """The multi-tensor LARS bundle is bit-identical to N per-param
    lars_momentum ops (trust-ratio norms reduced at member shape)."""
    from paddle_tpu.ops.fused_ops import fused_lars_momentum
    from paddle_tpu.ops.optimizer_ops import lars_momentum
    rng = np.random.RandomState(3)
    shapes = [(16, 8), (8,), (8, 4)]
    params = [rng.randn(*s).astype('float32') for s in shapes]
    grads = [rng.randn(*s).astype('float32') * 0.1 for s in shapes]
    vels = [np.zeros(s, np.float32) for s in shapes]
    lr = np.float32(0.05)
    fused_p, fused_v = fused_lars_momentum(params, grads, vels, lr)
    for i in range(len(shapes)):
        p_ref, v_ref = lars_momentum(params[i], grads[i], vels[i], lr)
        assert np.array_equal(np.asarray(fused_p[i]), np.asarray(p_ref)), i
        assert np.array_equal(np.asarray(fused_v[i]), np.asarray(v_ref)), i


def test_lars_fuse_pass_groups_and_bitwise():
    """fuse_all_optimizer_ops now covers lars_momentum: N update ops
    collapse into fused groups (excluded params in their OWN group), and
    the trajectory is bitwise pass-on/off."""
    from paddle_tpu.compiler import BuildStrategy, CompiledProgram

    def build():
        fluid.seed(11)
        main, start = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, start):
            x = L.data('fx', [8], dtype='float32')
            y = L.data('fy', [1], dtype='float32')
            h = L.fc(x, size=16, act='relu')
            h = L.fc(h, size=16, act='relu')
            pred = L.fc(h, size=1)
            loss = L.mean(L.square_error_cost(pred, y))
            fluid.optimizer.LarsMomentumOptimizer(
                0.05,
                exclude_from_weight_decay_fn=lambda p: '.b_' in p.name,
            ).minimize(loss)
        return main, start, loss

    from paddle_tpu import ir
    main, start, loss = build()
    bs = BuildStrategy()
    bs.fuse_all_optimizer_ops = True
    opt, ctx = ir.apply_pipeline(main, fetch_names=[loss.name],
                                 build_strategy=bs)
    stats = ctx.stats.get('fuse_all_optimizer_ops', {})
    assert stats.get('fused_groups', 0) >= 2     # wd group + excluded group
    assert any(op.type == 'fused_lars_momentum'
               for op in opt.global_block().ops)

    rng = np.random.RandomState(0)
    X = rng.randn(16, 8).astype('float32')
    Y = rng.randn(16, 1).astype('float32')
    runs = {}
    for tag, on in (('off', False), ('on', True)):
        main, start, loss = build()
        bs = BuildStrategy()
        bs.fuse_all_optimizer_ops = on
        exe = fluid.Executor()
        with fluid.scope_guard(fluid.Scope()):
            exe.run(start)
            cp = CompiledProgram(main, build_strategy=bs)
            runs[tag] = [np.asarray(exe.run(cp, feed={'fx': X, 'fy': Y},
                                            fetch_list=[loss])[0])
                         for _ in range(5)]
    assert all(np.array_equal(a, b)
               for a, b in zip(runs['off'], runs['on']))


def test_lars_example_program_verifies():
    """The large-batch example's program shape passes the static
    verifier: LARS + sync-BN + warmup/poly LR emit only ops with infer
    rules (rule coverage for the new attrs/ops)."""
    from paddle_tpu import analysis
    fluid.seed(1)
    main, start = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, start):
        x = L.data('image', shape=[3, 8, 8], dtype='float32')
        y = L.data('label', shape=[1], dtype='int64')
        h = L.conv2d(x, num_filters=4, filter_size=3, padding=1)
        h = L.batch_norm(h, act='relu', sync_stats=True)
        h = L.pool2d(h, pool_size=2, pool_type='avg', global_pooling=True)
        logits = L.fc(h, size=10)
        loss = L.mean(L.softmax_with_cross_entropy(logits, y))
        lr = L.linear_lr_warmup(
            L.polynomial_decay(0.1, decay_steps=10,
                               end_learning_rate=1e-4, power=2.0),
            warmup_steps=2, start_lr=0.0, end_lr=0.1)
        fluid.optimizer.LarsMomentumOptimizer(
            lr, exclude_from_weight_decay_fn=lambda p: '.b_' in p.name,
        ).minimize(loss)
    diags = analysis.verify_program(main, fetch_names=[loss.name])
    errors = [d for d in diags if d.severity == 'error']
    assert not errors, errors
