"""Training-fleet observability drill (ISSUE 17): a fault.py-injected
slow host must be NAMED by the straggler monitor within one aggregation
window of the fault firing, through the REAL spine — wall-timed steps →
per-host snapshot publish over the coordinator KV (file mirror) →
host-0 aggregation → ``straggler_*`` gauges + quarantine JSONL. Clean
fleets stay quiet."""
import json
import os
import time

import pytest

from paddle_tpu import observability as obs
from paddle_tpu.fleet_runtime.coordinator import ENV_FLEET_DIR
from paddle_tpu.observability import distributed as dobs
from paddle_tpu.resilience.fault import FaultInjector

_HOSTS = 4
_SLOW_RANK = 1
_SLOW_STEP = 2


@pytest.fixture(autouse=True)
def _fresh(tmp_path, monkeypatch):
    monkeypatch.setenv(ENV_FLEET_DIR, str(tmp_path / 'fleet'))
    obs.reset()
    yield
    obs.reset()


def _run_fleet_steps(steps, fault_spec_for_rank, straggler, out_dir,
                     base_step_s=0.002):
    """Drive _HOSTS simulated ranks through `steps` lock-steps: each rank
    runs its fault injector's on_step hook (wall-timed — exactly where
    the resilience manager measures), publishes its snapshot, and rank 0
    aggregates. Returns the last aggregate document."""
    injectors = {rank: FaultInjector(fault_spec_for_rank(rank))
                 for rank in range(_HOSTS)}
    fleet = None
    for step in range(1, steps + 1):
        for rank in range(_HOSTS):
            t0 = time.perf_counter()
            injectors[rank].on_step(step)
            step_time = base_step_s + (time.perf_counter() - t0)
            dobs.publish_host_snapshot(rank, step, step_time_s=step_time)
        fleet = dobs.aggregate_fleet_snapshots(
            straggler=straggler,
            out_path=os.path.join(out_dir, 'fleet_metrics.json'),
            step=step)
    return fleet


def test_fault_injected_slow_host_is_named_within_one_window(tmp_path):
    """``slow@step=N`` on one rank (every step ≥ N stays slow — a real
    straggler, not a blip): the very next host-0 aggregation after the
    fault fires must flag that host."""
    out = str(tmp_path / 'run')
    os.makedirs(out)
    straggler = dobs.StragglerMonitor(out_dir=out)
    spec = ('slow@step=%d,slow@secs=0.15' % _SLOW_STEP)

    def fault_for(rank):
        return spec if rank == _SLOW_RANK else ''

    flagged_at = None
    injectors = {rank: FaultInjector(fault_for(rank))
                 for rank in range(_HOSTS)}
    for step in range(1, _SLOW_STEP + 3):
        for rank in range(_HOSTS):
            t0 = time.perf_counter()
            injectors[rank].on_step(step)
            step_time = 0.002 + (time.perf_counter() - t0)
            dobs.publish_host_snapshot(rank, step, step_time_s=step_time)
        fleet = dobs.aggregate_fleet_snapshots(
            straggler=straggler,
            out_path=os.path.join(out, 'fleet_metrics.json'), step=step)
        if fleet['straggler']['stragglers']:
            flagged_at = step
            break
    # named within ONE aggregation window of the fault firing at step 2
    assert flagged_at == _SLOW_STEP
    assert fleet['straggler']['stragglers'] == [str(_SLOW_RANK)]
    assert fleet['straggler']['zscores'][str(_SLOW_RANK)] > 3.5

    # the quarantine-style JSONL names the host, with the z that flagged it
    recs = [json.loads(line) for line in
            open(os.path.join(out, 'straggler.jsonl'))]
    assert recs[0]['host'] == str(_SLOW_RANK)
    assert recs[0]['step'] == _SLOW_STEP
    assert recs[0]['zscore'] > 3.5

    # gauges for dashboards: straggler_count + per-host zscores
    reg = obs.registry.to_dict()
    assert reg['straggler_count']['samples'][0]['value'] == 1
    z = {s['labels']['host']: s['value']
         for s in reg['straggler_zscore']['samples']}
    assert z[str(_SLOW_RANK)] > 3.5 > z['0']

    # the exported fleet doc mirrors the aggregate (ops surface)
    doc = json.load(open(os.path.join(out, 'fleet_metrics.json')))
    assert doc['hosts'] == list(range(_HOSTS))
    assert doc['straggler']['stragglers'] == [str(_SLOW_RANK)]
    assert str(_SLOW_RANK) in doc['step_time_s']


def test_clean_fleet_stays_quiet(tmp_path):
    out = str(tmp_path / 'run')
    os.makedirs(out)
    straggler = dobs.StragglerMonitor(out_dir=out)
    fleet = _run_fleet_steps(5, lambda rank: '', straggler, out)
    assert fleet['straggler']['stragglers'] == []
    assert not os.path.exists(os.path.join(out, 'straggler.jsonl'))
    assert obs.registry.to_dict()[
        'straggler_count']['samples'][0]['value'] == 0
    # snapshots flowed: every host published and was folded in
    assert fleet['hosts'] == list(range(_HOSTS))
    assert len(fleet['step_time_s']) == _HOSTS


def test_fleet_aggregate_counter_and_gauge_semantics():
    """The KV aggregate mirrors merge_fleet_metrics semantics: counters
    sum across hosts, gauges stay per-host facts."""
    obs.registry.counter('fleet_drill_ticks', 'x').inc(3)
    obs.registry.gauge('fleet_drill_level', 'x').set(0.5)
    for rank in range(2):
        dobs.publish_host_snapshot(rank, step=1, step_time_s=0.01)
    fleet = dobs.aggregate_fleet_snapshots()
    # the same registry published twice ⇒ the fleet counter is the sum
    assert fleet['counters']['fleet_drill_ticks'] == 6.0
    assert fleet['gauges']['fleet_drill_level'] == {
        'host0': 0.5, 'host1': 0.5}
    # windowed series ride along per host for fleet dashboards
    assert set(fleet['series']) == {'host0', 'host1'}
