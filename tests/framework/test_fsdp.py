"""FSDP strategy: params + optimizer slots sharded 1/p per device over the
'fsdp' mesh axis via GSPMD; training parity vs unsharded run.
(SURVEY §2.8; ref knob surface incubate/fleet/collective/__init__.py:134)"""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.parallel import fsdp as F
from paddle_tpu.parallel.mesh import make_mesh, mesh_guard, set_default_mesh


def _train(sharded, steps=6):
    from paddle_tpu.parallel import fleet, DistributedStrategy
    main, start = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, start):
        fluid.framework.manual_seed(5)
        x = layers.data('x', [16], dtype='float32')
        y = layers.data('y', [1], dtype='float32')
        h = layers.fc(x, size=32, act='relu')
        pred = layers.fc(h, size=1)
        loss = layers.reduce_mean(layers.square_error_cost(pred, y))
        sgd = fluid.optimizer.Momentum(learning_rate=0.05, momentum=0.9)
        if sharded:
            strat = DistributedStrategy()
            strat.sharding = True
            opt = fleet.distributed_optimizer(sgd, strat)
            opt.minimize(loss)
        else:
            sgd.minimize(loss)
    exe = fluid.Executor()
    exe.run(start)
    rng = np.random.RandomState(1)
    losses = []
    for _ in range(steps):
        xv = rng.standard_normal((16, 16)).astype(np.float32)
        yv = xv[:, :1].astype(np.float32)
        l, = exe.run(main, feed={'x': xv, 'y': yv}, fetch_list=[loss])
        losses.append(float(np.asarray(l).reshape(())[()]))
    return losses, main


def test_fsdp_params_sharded_one_over_p():
    mesh = make_mesh({'fsdp': 8})
    with mesh_guard(mesh):
        losses, main = _train(sharded=True)
        w = next(p for p in main.all_parameters()
                 if np.prod(p.shape) >= 8)
        arr = fluid.global_scope().find(w.name)
        total = int(np.prod(arr.shape)) * arr.dtype.itemsize
        assert F.param_shard_bytes(arr) == total // 8
        # momentum slot sharded too
        slot = next(n for n in
                    (v.name for v in main.list_vars() if v.persistable)
                    if 'velocity' in n and w.name in n)
        sarr = fluid.global_scope().find(slot)
        assert F.param_shard_bytes(sarr) == total // 8
    set_default_mesh(None)
    assert losses[-1] < losses[0]


def test_fsdp_parity_vs_unsharded():
    base, _ = _train(sharded=False)
    mesh = make_mesh({'fsdp': 8})
    with mesh_guard(mesh):
        shard, _ = _train(sharded=True)
    set_default_mesh(None)
    np.testing.assert_allclose(shard, base, rtol=2e-4, atol=1e-5)


def test_fsdp_spec_picks_largest_divisible_dim():
    from jax.sharding import PartitionSpec as P
    mesh = make_mesh({'fsdp': 4})
    assert F.fsdp_spec((12, 64), mesh) == P(None, 'fsdp')
    assert F.fsdp_spec((64, 12), mesh) == P('fsdp', None)
    assert F.fsdp_spec((3, 5), mesh) == P()
    assert F.fsdp_spec((1,), mesh) == P()
    assert F.fsdp_spec((8, 8), mesh, axis='nope') == P()
