"""Radix prefix cache (paddle_tpu/serving/tier/prefix_cache.py): bitwise
hit-vs-cold parity, shared-prefix refcount lifecycle, LRU eviction under
pool pressure, block-boundary rules, and the always-on prefix_cache_*
metrics."""
import numpy as np
import pytest

from paddle_tpu.dygraph import guard
from paddle_tpu.models.causal_lm import greedy_generate
from paddle_tpu.serving import DecodeEngine, DecodeScheduler, PrefixCache
from paddle_tpu.serving.tier.replica import build_tiny_lm


@pytest.fixture(scope='module')
def lm():
    with guard():
        yield build_tiny_lm()


def make_engine(model, **kw):
    kw.setdefault('slots', 2)
    kw.setdefault('block_size', 4)
    kw.setdefault('max_blocks', 64)
    kw.setdefault('max_prompt_len', 16)
    kw.setdefault('max_new_tokens_cap', 8)
    kw.setdefault('prefix_cache', True)
    return DecodeEngine(model, **kw)


def _counter(name):
    from paddle_tpu.observability import registry
    d = registry.to_dict().get(name)
    if not d or not d['samples']:
        return 0.0
    return sum(s['value'] for s in d['samples'])


SYS_PROMPT = [7, 3, 11, 5, 9, 2, 44, 8]          # two whole 4-token blocks


# -- strict knob parse -----------------------------------------------------

def test_prefix_cache_env_strict_parse(lm, monkeypatch):
    monkeypatch.setenv('PADDLE_TPU_PREFIX_CACHE', 'yes')
    with pytest.raises(ValueError, match="'0', '1'"):
        make_engine(lm, prefix_cache=None)
    monkeypatch.setenv('PADDLE_TPU_PREFIX_CACHE', '1')
    eng = make_engine(lm, prefix_cache=None)
    assert eng.prefix_cache is not None
    monkeypatch.setenv('PADDLE_TPU_PREFIX_CACHE', '0')
    assert make_engine(lm, prefix_cache=None).prefix_cache is None


def test_prefix_cache_max_blocks_env_strict_parse(lm, monkeypatch):
    eng = make_engine(lm, prefix_cache=False)
    monkeypatch.setenv('PADDLE_TPU_PREFIX_CACHE_MAX_BLOCKS', 'many')
    with pytest.raises(ValueError, match='PADDLE_TPU_PREFIX_CACHE_MAX_BLOCKS'):
        PrefixCache(eng.pool)
    monkeypatch.setenv('PADDLE_TPU_PREFIX_CACHE_MAX_BLOCKS', '-3')
    with pytest.raises(ValueError, match='integers >= 0'):
        PrefixCache(eng.pool)
    monkeypatch.setenv('PADDLE_TPU_PREFIX_CACHE_MAX_BLOCKS', '7')
    assert PrefixCache(eng.pool).max_blocks == 7


# -- bitwise parity (the load-bearing contract) ----------------------------

def test_hit_bitwise_equals_cold_and_reference(lm):
    """Cold miss, then the identical prompt again as a cache hit: both
    generations must be array_equal to the uncached whole-sequence greedy
    reference — and to each other."""
    eng = make_engine(lm)
    prompt = SYS_PROMPT + [13, 21]
    ref = greedy_generate(lm, prompt, 6, pad_len=eng.padded_context)
    h0, s0 = _counter('prefix_cache_hits'), _counter('prefix_cache_tokens_saved')
    with DecodeScheduler(eng) as sched:
        cold = sched.submit(prompt, max_new_tokens=6).result(120)
        hit = sched.submit(prompt, max_new_tokens=6).result(120)
    assert cold == ref
    assert hit == ref
    assert _counter('prefix_cache_hits') - h0 == 1
    assert _counter('prefix_cache_tokens_saved') - s0 == 8  # 2 blocks * 4


def test_shared_system_prompt_different_suffixes(lm):
    """The tier's motivating workload: one shared system prompt, per-user
    suffixes. Every suffixed request after the first hits the shared
    blocks and still produces its OWN reference bytes."""
    eng = make_engine(lm)
    suffixes = ([13, 21], [17, 6], [99, 1, 2], [40])
    prompts = [SYS_PROMPT + s for s in suffixes]
    refs = [greedy_generate(lm, p, 5, pad_len=eng.padded_context)
            for p in prompts]
    h0 = _counter('prefix_cache_hits')
    with DecodeScheduler(eng) as sched:
        outs = [sched.submit(p, max_new_tokens=5).result(120)
                for p in prompts]
    assert outs == refs
    assert _counter('prefix_cache_hits') - h0 == len(prompts) - 1


def test_concurrent_mixed_workload_parity(lm):
    """Ragged concurrent mix of cold and hitting prompts through the
    continuous-batching scheduler stays bitwise."""
    eng = make_engine(lm, slots=3)
    rng = np.random.RandomState(3)
    prompts = [SYS_PROMPT + list(map(int, rng.randint(3, 100, n)))
               for n in (1, 3, 2, 5, 1, 4)]
    budgets = [6, 3, 8, 2, 5, 7]
    refs = [greedy_generate(lm, p, m, pad_len=eng.padded_context)
            for p, m in zip(prompts, budgets)]
    with DecodeScheduler(eng) as sched:
        streams = [sched.submit(p, max_new_tokens=m)
                   for p, m in zip(prompts, budgets)]
        outs = [s.result(120) for s in streams]
    assert outs == refs


# -- refcount lifecycle ----------------------------------------------------

def test_shared_prefix_refcount_lifecycle(lm):
    """cache-resident +1, one per sharing table: 2 while one request holds
    it, 3 while two share, back to 1 (cache only) after both retire, 0
    (freed) after eviction."""
    eng = make_engine(lm)
    alloc = eng.pool.allocator
    prompt = SYS_PROMPT + [13]
    t1 = eng.reserve_table(len(prompt), 4, prompt=prompt)
    assert t1.cached_len == 0                    # cold
    eng.prefill(prompt, t1)
    eng.publish_prefix(prompt, t1)
    shared_ids = eng.prefix_cache.resident_block_ids()
    assert len(shared_ids) == 2
    assert all(alloc.refcount(b) == 2 for b in shared_ids)   # t1 + cache
    t2 = eng.reserve_table(len(prompt), 4, prompt=prompt)
    assert t2.cached_len == 8
    assert t2.blocks[:2] == t1.blocks[:2]        # zero-copy sharing
    assert all(alloc.refcount(b) == 3 for b in shared_ids)
    eng.release_table(t1)
    assert all(alloc.refcount(b) == 2 for b in shared_ids)
    eng.release_table(t2)
    assert all(alloc.refcount(b) == 1 for b in shared_ids)   # cache only
    used_before = alloc.used
    assert eng.prefix_cache.evict_idle() == 2
    assert alloc.used == used_before - 2
    assert all(alloc.refcount(b) == 0 for b in shared_ids)


def test_sharing_request_never_writes_shared_blocks(lm):
    """A hitting request's writes all land in its fresh blocks: the shared
    prefix blocks' bytes are identical before and after the hit
    generation."""
    eng = make_engine(lm)
    prompt = SYS_PROMPT + [13, 21]
    with DecodeScheduler(eng) as sched:
        sched.submit(prompt, max_new_tokens=6).result(120)
        ids = eng.prefix_cache.resident_block_ids()
        before = [eng.pool.read_blocks(layer, ids)
                  for layer in range(eng.pool.num_layers)]
        sched.submit(prompt, max_new_tokens=6).result(120)
        after = [eng.pool.read_blocks(layer, ids)
                 for layer in range(eng.pool.num_layers)]
    for (kb, vb), (ka, va) in zip(before, after):
        assert np.array_equal(kb, ka) and np.array_equal(vb, va)


# -- eviction --------------------------------------------------------------

def test_eviction_under_pool_pressure(lm):
    """A pool too small to hold the cache AND a new request evicts idle
    cached blocks (LRU) instead of failing or waiting forever — and the
    evicted-and-recomputed generation is still bitwise."""
    # capacity 5; each request needs ceil((8+8)/4) = 4 blocks
    eng = make_engine(lm, max_blocks=6, max_prompt_len=8,
                      max_new_tokens_cap=8)
    p1 = SYS_PROMPT
    p2 = [91, 92, 93, 94, 95, 96, 97, 98]
    r1 = greedy_generate(lm, p1, 8, pad_len=eng.padded_context)
    r2 = greedy_generate(lm, p2, 8, pad_len=eng.padded_context)
    e0 = _counter('prefix_cache_evicted_blocks')
    with DecodeScheduler(eng) as sched:
        assert sched.submit(p1, max_new_tokens=8).result(120) == r1
        # p1's 2 cached blocks + 4 fresh would exceed capacity: evict
        assert sched.submit(p2, max_new_tokens=8).result(120) == r2
        # and p1 again — its cache entries were (partly) evicted, still exact
        assert sched.submit(p1, max_new_tokens=8).result(120) == r1
    assert _counter('prefix_cache_evicted_blocks') - e0 >= 1
    assert eng.pool.allocator.used == eng.prefix_cache.resident_blocks


def test_max_blocks_cap_bounds_residency(lm):
    eng = make_engine(lm, prefix_cache=False)
    eng.prefix_cache = PrefixCache(eng.pool, max_blocks=1)
    prompt = SYS_PROMPT                       # would publish 2 blocks
    table = eng.reserve_table(len(prompt), 4, prompt=prompt)
    eng.prefill(prompt, table)
    eng.publish_prefix(prompt, table)
    assert eng.prefix_cache.resident_blocks <= 1
    eng.release_table(table)
    eng.prefix_cache.evict_idle()


def test_lru_prefers_older_idle_leaves(lm):
    """Under pressure the LRU victim is the least-recently-matched leaf."""
    eng = make_engine(lm)
    pc = eng.prefix_cache
    pa = SYS_PROMPT + [13]                    # publishes 2 blocks
    pb = [91, 92, 93, 94, 95]                 # publishes 1 block, later
    for p in (pa, pb):
        t = eng.reserve_table(len(p), 4, prompt=p)
        eng.prefill(p, t)
        eng.publish_prefix(p, t)
        eng.release_table(t)
    # touch pa: the match re-stamps its whole path newer than pb's insert
    # (the retain is released right away — this is a recency touch only)
    blocks = pc.match(pa)
    assert len(blocks) == 2
    eng.pool.allocator.release(blocks)
    assert pc._evict_one()
    assert tuple(pb[:4]) not in pc._root.children    # older leaf evicted
    assert tuple(pa[:4]) in pc._root.children        # touched path survives


# -- block-boundary rules --------------------------------------------------

def test_sub_block_prompts_never_cached(lm):
    eng = make_engine(lm)
    with DecodeScheduler(eng) as sched:
        sched.submit([1, 2, 3], max_new_tokens=3).result(120)   # < 1 block
        assert eng.prefix_cache.resident_blocks == 0
        m0 = _counter('prefix_cache_misses')
        sched.submit([1, 2, 3], max_new_tokens=3).result(120)
        assert _counter('prefix_cache_misses') - m0 == 1        # still cold


def test_last_prompt_token_never_served_from_cache(lm):
    """A block-aligned prompt (P == k * block_size) may hit at most k-1
    blocks: at least one real token must run through the model to produce
    the first generated token's logits."""
    eng = make_engine(lm)
    prompt = SYS_PROMPT                       # exactly 2 blocks
    ref = greedy_generate(lm, prompt, 4, pad_len=eng.padded_context)
    with DecodeScheduler(eng) as sched:
        assert sched.submit(prompt, max_new_tokens=4).result(120) == ref
        t = eng.reserve_table(len(prompt), 4, prompt=prompt)
        assert t.cached_len == 4              # 1 block, not 2
        eng.release_table(t)
        assert sched.submit(prompt, max_new_tokens=4).result(120) == ref


def test_trie_deepens_with_longer_shared_prompts(lm):
    """A longer prompt sharing a cached prefix publishes the DEEPER blocks;
    later prompts hit the extended path."""
    eng = make_engine(lm)
    pa = SYS_PROMPT                               # blocks 0,1
    pb = SYS_PROMPT + [61, 62, 63, 64]            # + block 2
    pc_prompt = pb + [33]
    refs = [greedy_generate(lm, p, 4, pad_len=eng.padded_context)
            for p in (pa, pb, pc_prompt)]
    with DecodeScheduler(eng) as sched:
        assert sched.submit(pa, max_new_tokens=4).result(120) == refs[0]
        assert sched.submit(pb, max_new_tokens=4).result(120) == refs[1]
        assert eng.prefix_cache.resident_blocks == 3
        s0 = _counter('prefix_cache_tokens_saved')
        assert sched.submit(pc_prompt, max_new_tokens=4).result(120) == refs[2]
        assert _counter('prefix_cache_tokens_saved') - s0 == 12   # 3 blocks


def test_metrics_exported(lm):
    from paddle_tpu.observability import registry
    eng = make_engine(lm)
    with DecodeScheduler(eng) as sched:
        sched.submit(SYS_PROMPT, max_new_tokens=2).result(120)
        sched.submit(SYS_PROMPT, max_new_tokens=2).result(120)
    d = registry.to_dict()
    for name in ('prefix_cache_hits', 'prefix_cache_misses',
                 'prefix_cache_tokens_saved', 'prefix_cache_blocks_resident',
                 'prefix_cache_inserted_blocks',
                 'prefix_cache_evicted_blocks'):
        assert name in d, f'missing prefix-cache metric {name}'
