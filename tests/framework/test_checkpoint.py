"""Sharded/async checkpointing (SURVEY 2.7, VERDICT r1 #7): per-shard save
from a dp×tp-sharded state, background write, resume with shardings
preserved, rolling CheckpointManager.

ref analogue: python/paddle/fluid/io.py save_persistables scaled to pod
state (each host writes its shards; async overlaps IO with compute).
"""
import os
import pathlib

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from paddle_tpu.parallel import make_mesh, shard_params
from paddle_tpu.checkpoint import (save_checkpoint, load_checkpoint,
                                   latest_step, CheckpointManager)


@pytest.fixture
def mesh():
    return make_mesh({'dp': 4, 'tp': 2})


def _bert_like_state(mesh, rng):
    """Small dp×tp-sharded transformer-block state (megatron shardings)."""
    raw = {
        'block.q_proj.w': rng.randn(16, 32).astype('float32'),
        'block.out_proj.w': rng.randn(32, 16).astype('float32'),
        'block.ln.scale': rng.randn(16).astype('float32'),
    }
    return shard_params(raw, mesh=mesh, axis='tp')


def test_async_sharded_roundtrip_preserves_shardings(tmp_path, mesh):
    rng = np.random.RandomState(0)
    state = _bert_like_state(mesh, rng)
    state['step'] = jnp.int32(3)

    ck = save_checkpoint(state, str(tmp_path), step=3, use_async=True)
    ck.wait_until_finished()                      # background write completed
    # per-shard layout on disk (not one monolithic npz)
    files = [p for p in pathlib.Path(tmp_path).rglob('*') if p.is_file()]
    assert len(files) > 1

    restored = load_checkpoint(str(tmp_path), step=3, target=state)
    for n in state:
        np.testing.assert_allclose(np.asarray(restored[n]),
                                   np.asarray(state[n]), rtol=0, atol=0)
    # shardings survive the round trip
    assert restored['block.q_proj.w'].sharding.spec == P(None, 'tp')
    assert restored['block.out_proj.w'].sharding.spec == P('tp', None)


def test_manager_rolling_and_resume(tmp_path, mesh):
    rng = np.random.RandomState(1)
    mgr = CheckpointManager(str(tmp_path), max_to_keep=2, use_async=True)

    # tiny sharded training loop: w <- w - 0.1 * grad, checkpoint each step
    w = jax.device_put(jnp.asarray(rng.randn(8, 4).astype('float32')),
                       NamedSharding(mesh, P('dp', None)))
    x = jnp.asarray(rng.randn(4, 8).astype('float32'))

    @jax.jit
    def step(w):
        g = jax.grad(lambda w_: jnp.sum((x @ w_) ** 2))(w)
        return w - 0.1 * g

    history = {}
    for s in range(4):
        w = step(w)
        mgr.save(s, {'w': w})
        history[s] = np.asarray(w).copy()
    mgr.wait()

    # keep-last-2: steps 0/1 gone, 2/3 present
    assert latest_step(str(tmp_path)) == 3
    steps_on_disk = sorted(int(d) for d in os.listdir(tmp_path)
                           if d.isdigit())
    assert steps_on_disk == [2, 3]

    # resume from step 2 and recompute step 3 → identical trajectory
    restored = mgr.restore(step=2, target={'w': w})
    w2 = step(restored['w'])
    np.testing.assert_allclose(np.asarray(w2), history[3], rtol=1e-6)
    # restore(None) picks the latest
    latest = mgr.restore(target={'w': w})
    np.testing.assert_allclose(np.asarray(latest['w']), history[3],
                               rtol=1e-6)


def test_manager_gc_sees_foreign_steps(tmp_path):
    """ADVICE r2: steps written by ANOTHER manager/process after this
    manager's construction must still be garbage-collected."""
    state = {'w': jnp.ones((2, 2), jnp.float32)}
    mgr = CheckpointManager(str(tmp_path), max_to_keep=2)
    other = CheckpointManager(str(tmp_path), max_to_keep=2)
    other.save(0, state)
    other.save(1, state)
    # mgr never saw 0/1 at construction; its saves must still evict them
    mgr.save(2, state)
    mgr.save(3, state)
    steps = sorted(int(d) for d in os.listdir(tmp_path) if d.isdigit())
    assert steps == [2, 3]
