"""incubate.fleet.utils toolkit (ref: incubate/fleet/utils/{fleet_util,
fleet_barrier_util, utils}.py) + log_helper + annotations."""
import logging
import os

import numpy as np
import pytest

import paddle_tpu as fluid
import paddle_tpu.layers as L
from paddle_tpu.incubate.fleet.utils import (FleetUtil,
                                             check_all_trainers_ready)
from paddle_tpu.incubate.fleet.utils import utils as fuu


def _toy_program():
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.data('x', [4, 3], 'float32')
        loss = L.reduce_mean(L.fc(x, size=2))
        fluid.optimizer.SGD(0.1).minimize(loss)
    return prog, startup, loss


def test_fleet_util_auc_from_buckets():
    u = FleetUtil()
    # perfect separation: all negatives in bucket 0, positives in last
    pos = np.zeros(10); pos[-1] = 50
    neg = np.zeros(10); neg[0] = 50
    auc, total = u._auc_from_buckets(pos, neg)
    assert auc == 1.0 and total == 100
    # random: same bucket → 0.5
    pos2 = np.zeros(10); pos2[3] = 10
    neg2 = np.zeros(10); neg2[3] = 10
    auc2, _ = u._auc_from_buckets(pos2, neg2)
    assert abs(auc2 - 0.5) < 1e-9


def test_fleet_util_get_global_auc_from_scope():
    import jax.numpy as jnp
    scope = fluid.global_scope()
    pos = np.zeros((1, 8)); pos[0, -1] = 30
    neg = np.zeros((1, 8)); neg[0, 0] = 30
    scope.set('stat_pos', jnp.asarray(pos))
    scope.set('stat_neg', jnp.asarray(neg))
    u = FleetUtil()
    auc = u.get_global_auc(scope, 'stat_pos', 'stat_neg')
    assert auc == 1.0
    u.set_zero('stat_pos', scope)
    assert float(np.asarray(scope.find('stat_pos')).sum()) == 0.0


def test_fleet_util_model_protocol(tmp_path):
    prog, startup, loss = _toy_program()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    u = FleetUtil()
    out = str(tmp_path / 'models')
    d = u.save_model(out, 20260730, 3, program=prog)
    assert os.path.isdir(d)
    done = u.write_model_donefile(out, 20260730, 3, xbox_base_key=12345)
    day, pass_id, path, key = u.get_last_save_model(out)
    assert (day, pass_id, key) == (20260730, 3, 12345)
    assert path == d
    u.load_model(out, 20260730, 3, program=prog)  # round-trips


def test_fleet_util_online_pass_interval():
    u = FleetUtil()
    iv = u.get_online_pass_interval('{20190720..20190729}', '{0..23}',
                                    split_interval=30, split_per_pass=2,
                                    is_data_hourly_placed=False)
    assert len(iv) == 24           # 48 half-hour splits / 2 per pass
    assert iv[0] == ['0000', '0030']
    assert iv[-1] == ['2300', '2330']


def test_fleet_util_global_metrics_bundle():
    import jax.numpy as jnp
    scope = fluid.global_scope()
    pos = np.zeros((1, 100)); pos[0, 80] = 40
    neg = np.zeros((1, 100)); neg[0, 20] = 60
    scope.set('sp', jnp.asarray(pos)); scope.set('sn', jnp.asarray(neg))
    for name, v in [('sq', 5.0), ('ab', 10.0), ('pr', 40.0), ('qq', 30.0),
                    ('pi', 40.0), ('ti', 100.0)]:
        scope.set(name, jnp.asarray([v]))
    u = FleetUtil()
    m = u.get_global_metrics(scope, 'sp', 'sn', 'sq', 'ab', 'pr', 'qq',
                             'pi', 'ti')
    assert set(m) == {'auc', 'bucket_error', 'mae', 'rmse', 'actual_ctr',
                      'predicted_ctr', 'copc', 'mean_q', 'total_ins_num'}
    assert m['auc'] == 1.0 and m['actual_ctr'] == 0.4
    assert m['mae'] == 0.1 and abs(m['rmse'] - np.sqrt(0.05)) < 1e-9
    assert m['total_ins_num'] == 100
    # empty pass keeps the key set stable
    scope.set('ti', jnp.asarray([0.0]))
    m0 = u.get_global_metrics(scope, 'sp', 'sn', 'sq', 'ab', 'pr', 'qq',
                              'pi', 'ti')
    assert set(m0) == set(m) and m0['total_ins_num'] == 0


def test_utils_reader_ref_semantics(tmp_path):
    # one long line = several batches; trailing partial batch dropped
    p = tmp_path / 'feed.txt'
    p.write_text(' '.join(str(i) for i in range(14)) + '\n')
    batches = fuu.reader(batch_size=2, fn=str(p), dim=[3])
    assert len(batches) == 2                      # 14 // 6
    assert batches[0].shape == (2, 3)
    np.testing.assert_array_equal(batches[0],
                                  np.arange(6, dtype=float).reshape(2, 3))
    feeds = fuu.feed_gen(2, [[3]], [str(p)])
    assert len(feeds) == 1 and len(feeds[0]) == 2


def test_check_saved_vars_missing_state_fails(tmp_path):
    prog, startup, loss = _toy_program()
    fuu.save_program(prog, str(tmp_path / 'prog'))
    _, problems = fuu.check_saved_vars_try_dump(str(tmp_path), 'prog',
                                                False)
    assert problems and 'not found' in problems[0]


def test_fleet_util_pslib_ops_raise():
    u = FleetUtil()
    with pytest.raises(RuntimeError, match='pslib'):
        u.load_fleet_model('/tmp/x')


def test_barrier_single_trainer(tmp_path):
    assert check_all_trainers_ready(str(tmp_path / 'ready'), epoch=0,
                                    timeout=5)


def test_utils_program_roundtrip_and_checks(tmp_path):
    prog, startup, loss = _toy_program()
    path = str(tmp_path / '__model__')
    fuu.save_program(prog, path)
    p2 = fuu.load_program(path)
    assert p2.num_ops() == prog.num_ops()
    pruned = prog.clone(for_test=True)
    assert fuu.check_pruned_program_vars(prog, pruned) == []
    assert fuu.check_not_expected_ops(prog, ('nonexistent_op',)) == []
    report = fuu.parse_program(prog, str(tmp_path / 'rep'))
    assert os.path.exists(report)


def test_utils_save_load_var(tmp_path):
    arr = np.arange(12, dtype=np.float32)
    p = fuu.save_var(arr, 'v', [3, 4], np.float32,
                     str(tmp_path / 'v.bin'))
    back = fuu.load_var('v', [3, 4], np.float32, p)
    np.testing.assert_array_equal(back, arr.reshape(3, 4))


def test_log_helper_no_basicconfig_hijack():
    from paddle_tpu.log_helper import get_logger
    lg = get_logger('ptpu_test_logger', logging.INFO, fmt='%(message)s')
    lg2 = get_logger('ptpu_test_logger', logging.INFO)
    assert lg is lg2 and len(lg.handlers) == 1   # idempotent
    assert not lg.propagate


def test_annotations_deprecated(capsys):
    from paddle_tpu.annotations import deprecated

    @deprecated('1.8', 'new_fn')
    def old_fn(a):
        """doc."""
        return a + 1

    assert old_fn(1) == 2
    err = capsys.readouterr().err
    assert 'deprecated since 1.8' in err and 'new_fn' in err
    assert 'deprecated' in old_fn.__doc__
