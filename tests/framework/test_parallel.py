"""Distributed primitives on the 8-device CPU mesh: ring attention (SP),
tensor parallel matmuls, GPipe pipeline, gradient-merge/DGC optimizers."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as fluid
from paddle_tpu.parallel import (make_mesh, mesh_guard, ring_attention,
                                 column_parallel_matmul, row_parallel_matmul,
                                 vocab_parallel_embedding, gpipe,
                                 stack_stage_params)
from paddle_tpu.parallel.ring_attention import _full_attention


@pytest.fixture
def mesh8():
    return make_mesh({'sp': 8})


def test_ring_attention_matches_full(mesh8):
    rng = np.random.RandomState(0)
    B, S, H, D = 2, 32, 4, 8
    q = rng.randn(B, S, H, D).astype('float32')
    k = rng.randn(B, S, H, D).astype('float32')
    v = rng.randn(B, S, H, D).astype('float32')
    want = _full_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    with mesh_guard(mesh8):
        got = ring_attention(q, k, v, mesh8, axis='sp')
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


def test_ring_attention_causal_and_grad(mesh8):
    rng = np.random.RandomState(1)
    B, S, H, D = 1, 16, 2, 4
    q = jnp.asarray(rng.randn(B, S, H, D).astype('float32'))
    k = jnp.asarray(rng.randn(B, S, H, D).astype('float32'))
    v = jnp.asarray(rng.randn(B, S, H, D).astype('float32'))
    want = _full_attention(q, k, v, causal=True)
    got = ring_attention(q, k, v, mesh8, axis='sp', causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)
    # ring backward == full backward (vjp through ppermute)
    g_ring = jax.grad(lambda a: ring_attention(
        a, k, v, mesh8, axis='sp', causal=True).sum())(q)
    g_full = jax.grad(lambda a: _full_attention(
        a, k, v, causal=True).sum())(q)
    np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_full),
                               rtol=2e-3, atol=2e-4)


def test_tensor_parallel_matmuls():
    mesh = make_mesh({'tp': 8})
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(4, 16).astype('float32'))
    w1 = jnp.asarray(rng.randn(16, 32).astype('float32'))
    w2 = jnp.asarray(rng.randn(32, 16).astype('float32'))
    h = column_parallel_matmul(x, w1, mesh=mesh)       # (4, 32) col-sharded
    y = row_parallel_matmul(h, w2, mesh=mesh)          # (4, 16) replicated
    np.testing.assert_allclose(np.asarray(y), np.asarray(x @ w1 @ w2),
                               rtol=1e-4, atol=1e-4)


def test_vocab_parallel_embedding():
    mesh = make_mesh({'tp': 8})
    rng = np.random.RandomState(3)
    table = jnp.asarray(rng.randn(64, 8).astype('float32'))
    ids = jnp.asarray(rng.randint(0, 64, (4, 7)))
    out = vocab_parallel_embedding(ids, table, mesh=mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(table[ids]),
                               rtol=1e-5)


def test_gpipe_matches_sequential():
    mesh = make_mesh({'pp': 4}, jax.devices()[:4])
    rng = np.random.RandomState(4)
    n_stages, n_micro, mb, D = 4, 3, 2, 8
    ws = [rng.randn(D, D).astype('float32') * 0.3 for _ in range(n_stages)]
    bs = [rng.randn(D).astype('float32') * 0.1 for _ in range(n_stages)]
    stages = [{'w': jnp.asarray(w), 'b': jnp.asarray(b)}
              for w, b in zip(ws, bs)]
    x = rng.randn(n_micro, mb, D).astype('float32')

    def stage_fn(params, h):
        return jnp.tanh(h @ params['w'] + params['b'])

    stacked = stack_stage_params(stages)
    got = gpipe(stage_fn, stacked, jnp.asarray(x), mesh=mesh, axis='pp')

    want = jnp.asarray(x)
    for p in stages:
        want = jax.vmap(lambda h: stage_fn(p, h))(want)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-4,
                               atol=1e-5)


def test_gpipe_differentiable():
    mesh = make_mesh({'pp': 2}, jax.devices()[:2])
    rng = np.random.RandomState(5)
    stages = [{'w': jnp.asarray(rng.randn(4, 4).astype('float32') * 0.3)}
              for _ in range(2)]
    x = jnp.asarray(rng.randn(2, 2, 4).astype('float32'))

    def stage_fn(p, h):
        return jnp.tanh(h @ p['w'])

    stacked = stack_stage_params(stages)

    def loss(sp):
        return gpipe(stage_fn, sp, x, mesh=mesh, axis='pp').sum()

    g = jax.grad(loss)(stacked)

    def loss_seq(ps):
        h = x
        for p in ps:
            h = jnp.tanh(h @ p['w'])
        return h.sum()

    g_seq = jax.grad(loss_seq)(stages)
    np.testing.assert_allclose(np.asarray(g['w'][0]),
                               np.asarray(g_seq[0]['w']), rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(g['w'][1]),
                               np.asarray(g_seq[1]['w']), rtol=1e-4,
                               atol=1e-5)


def test_gradient_merge_optimizer():
    from paddle_tpu import layers
    main, start = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, start):
        x = layers.data('x', shape=[2], dtype='float32')
        y = layers.data('y', shape=[1], dtype='float32')
        pred = layers.fc(x, 1, bias_attr=False,
                         param_attr=fluid.ParamAttr(
                             name='gm_w',
                             initializer=fluid.initializer.
                             ConstantInitializer(0.0)))
        loss = layers.mean(layers.square_error_cost(pred, y))
        opt = fluid.optimizer.GradientMergeOptimizer(
            fluid.optimizer.SGDOptimizer(0.1), k_steps=2, avg=True)
        opt.minimize(loss)
        w = main.global_block().var('gm_w')
    exe = fluid.Executor()
    X = np.ones((4, 2), 'float32')
    Y = np.ones((4, 1), 'float32')
    with fluid.scope_guard(fluid.Scope()):
        exe.run(start)
        w0, = exe.run(main, feed={'x': X, 'y': Y}, fetch_list=[w])
        np.testing.assert_allclose(w0, np.zeros((2, 1)))   # step 0: no apply
        w1, = exe.run(main, feed={'x': X, 'y': Y}, fetch_list=[w])
        assert np.abs(w1).sum() > 0                        # step 1: applied
        # merged update == sgd on the mean of the two identical grads
        np.testing.assert_allclose(w1, np.full((2, 1), 0.2), rtol=1e-5)


def test_dgc_momentum_optimizer():
    from paddle_tpu import layers
    main, start = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, start):
        x = layers.data('x', shape=[4], dtype='float32')
        y = layers.data('y', shape=[1], dtype='float32')
        pred = layers.fc(x, 1)
        loss = layers.mean(layers.square_error_cost(pred, y))
        fluid.optimizer.DGCMomentumOptimizer(
            0.05, momentum=0.9, sparsity=[0.5]).minimize(loss)
    exe = fluid.Executor()
    rng = np.random.RandomState(0)
    X = rng.randn(16, 4).astype('float32')
    Y = (X @ rng.randn(4, 1)).astype('float32')
    with fluid.scope_guard(fluid.Scope()):
        exe.run(start)
        losses = [float(exe.run(main, feed={'x': X, 'y': Y},
                                fetch_list=[loss])[0]) for _ in range(30)]
    assert losses[-1] < losses[0] * 0.6


def test_hybrid_mesh_axes_and_collective():
    """make_hybrid_mesh: dcn axes lead, ici axes trail; a dp-over-dcn ×
    tp-over-ici psum works (hierarchical allreduce parity, SURVEY §2.8)."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from paddle_tpu.parallel.mesh import make_hybrid_mesh

    mesh = make_hybrid_mesh({'tp': 2}, {'dp': 4})
    assert mesh.axis_names == ('dp', 'tp')
    assert mesh.shape['dp'] == 4 and mesh.shape['tp'] == 2

    x = jnp.arange(16.0).reshape(8, 2)

    def f(xs):
        total = jax.lax.psum(jax.lax.psum(jnp.sum(xs), 'tp'), 'dp')
        return jnp.full_like(xs, total)

    from paddle_tpu.core import compat
    out = compat.shard_map(f, mesh=mesh, in_specs=P('dp', 'tp'),
                           out_specs=P('dp', 'tp'))(x)
    np.testing.assert_allclose(np.asarray(out)[0, 0], float(x.sum()))
