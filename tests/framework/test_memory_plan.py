"""Analysis-driven compilation (ISSUE 14): the static cost model
(analysis/cost.py), the peak-HBM memory planner (analysis/plan.py), the
budget-driven auto-remat IR pass (ir/auto_remat.py), bucket autotuning
(PADDLE_TPU_ALLREDUCE_BUCKET_MB=auto), and the RecomputeOptimizer
checkpoint validation satellite.

The two load-bearing claims, asserted here:

- predicted state+feed+fetch bytes match the executor's MEASURED
  accounting within tolerance on every tier-1 verifier recipe;
- auto-remat fits a simulated HBM budget the unplanned program exceeds,
  with losses BITWISE-identical both to the un-rematerialized run and to
  a manual RecomputeOptimizer run over the same checkpoint names.
"""
import os
import sys

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import analysis, ir, layers as L
from paddle_tpu import observability as obs
from paddle_tpu.analysis import (VarInfo, all_cost_rules, all_rules,
                                 gradient_bytes, plan_program,
                                 select_checkpoints)
from paddle_tpu.analysis.cost import (dtype_nbytes, info_nbytes, op_cost)
from paddle_tpu.core import unique_name
from paddle_tpu.framework import BACKWARD_OP_TYPE
from paddle_tpu.ir import auto_remat, bucket_allreduce, pipeline_signature

sys.path.insert(0, os.path.join(
    os.path.dirname(__file__), '..', '..', 'tools'))
from bench_passes import (build_bert_layer, build_mlp_adam,  # noqa: E402
                          build_resnet_block)


def _fresh_names():
    unique_name.generator = unique_name.UniqueNameGenerator()
    fluid.framework.manual_seed(0)


# ---------------------------------------------------------------------------
# recipe builders: (main, startup, feed dict, fetch names)
# ---------------------------------------------------------------------------

def _mnist_mlp():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = L.data('img', [64], dtype='float32')
        label = L.data('label', [1], dtype='int64')
        h = L.fc(img, size=32, act='relu')
        h = L.fc(h, size=32, act='relu')
        logits = L.fc(h, size=10)
        loss = L.reduce_mean(L.softmax_with_cross_entropy(logits, label))
        fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)
    rng = np.random.RandomState(0)
    feed = {'img': rng.randn(8, 64).astype(np.float32),
            'label': rng.randint(0, 10, (8, 1)).astype(np.int64)}
    return main, startup, feed, [loss.name]


def _fleet_dp():
    from paddle_tpu.parallel import DistributedStrategy, fleet
    fleet.init()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = L.data('x', shape=[32], dtype='float32')
        y = L.data('y', shape=[1], dtype='int64')
        h = L.fc(x, size=32, act='relu')
        h2 = L.fc(h, size=32, act='relu')
        logits = L.fc(h2, size=10)
        loss = L.reduce_mean(L.softmax_with_cross_entropy(logits, y))
        fleet.distributed_optimizer(
            fluid.optimizer.SGD(0.1),
            strategy=DistributedStrategy()).minimize(loss)
    rng = np.random.RandomState(1)
    feed = {'x': rng.randn(8, 32).astype(np.float32),
            'y': rng.randint(0, 10, (8, 1)).astype(np.int64)}
    return main, startup, feed, [loss.name]


def _decode_engine():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = L.data('ids', [8], dtype='int64')
        emb = L.embedding(ids, size=[100, 16])
        h = L.fc(emb, size=16, act='tanh')
        logits = L.fc(h, size=100)
        nxt = L.argmax(logits, axis=-1)
    rng = np.random.RandomState(2)
    feed = {'ids': rng.randint(0, 100, (4, 8)).astype(np.int64)}
    return main, startup, feed, [nxt.name]


def _from_builder(builder):
    main, startup, make_feed, fetch = builder(smoke=True)
    feed = make_feed() if callable(make_feed) else make_feed
    return main, startup, feed, [fetch.name]


_RECIPES = {
    'mnist_mlp': _mnist_mlp,
    'mlp_adam': lambda: _from_builder(build_mlp_adam),
    'resnet_block': lambda: _from_builder(build_resnet_block),
    'bert_layer': lambda: _from_builder(build_bert_layer),
    'fleet_dp': _fleet_dp,
    'decode_engine': _decode_engine,
}


# ---------------------------------------------------------------------------
# cost model
# ---------------------------------------------------------------------------

def test_cost_rule_coverage_matches_infer_registry():
    """Every op type with an inference rule has a cost rule — the same
    coverage contract the infer registry carries, so anything the tier-1
    recipes emit (pre- or post-pipeline) is costed."""
    missing = set(all_rules()) - set(all_cost_rules())
    assert not missing, f'infer rules without cost rules: {sorted(missing)}'
    for t in ('fused_adam', 'fused_momentum', 'fused_sgd',
              'fused_elemwise_add_activation', 'c_allreduce_sum_bucket'):
        assert analysis.has_cost_rule(t), t


def test_cost_rule_coverage_over_recipe_ops():
    for name, build in _RECIPES.items():
        main, _s, _f, _fetch = build()
        for b in main.blocks:
            for op in b.ops:
                if op.type == BACKWARD_OP_TYPE:
                    continue
                assert analysis.has_cost_rule(op.type), \
                    f'{name}: no cost rule for {op.type!r}'


def _one_op_cost(op_type, inputs, attrs, in_slots, out_names=('o',)):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        blk = main.global_block()
        env = {}
        for name, (shape, dtype) in inputs.items():
            blk.create_var(name=name, shape=shape, dtype=dtype)
            env[name] = VarInfo(shape, dtype)
        op = blk.append_op(op_type, inputs=in_slots,
                           outputs={'Out': list(out_names)}, attrs=attrs)
        from paddle_tpu.analysis.infer import infer_op
        res = infer_op(op, env, blk)
        if res:
            for n, info in zip(out_names, [res.get('Out')]):
                env[n] = info if isinstance(info, VarInfo) else info[0]
        return op_cost(op, env, blk)


def test_cost_matmul_flops_2mkn():
    c = _one_op_cost('matmul',
                     {'a': ((8, 16), 'float32'), 'b': ((16, 4), 'float32')},
                     {}, {'x': ['a'], 'y': ['b']})
    assert c.flops == 2 * 8 * 16 * 4
    # bytes: 8×16 + 16×4 read, 8×4 written, all f32
    assert c.bytes_in == (8 * 16 + 16 * 4) * 4
    assert c.bytes_out == 8 * 4 * 4


def test_cost_conv2d_flops():
    c = _one_op_cost('conv2d',
                     {'x': ((2, 3, 8, 8), 'float32'),
                      'w': ((16, 3, 3, 3), 'float32')},
                     {'stride': 1, 'padding': 1},
                     {'x': ['x'], 'weight': ['w']})
    out_elems = 2 * 16 * 8 * 8
    assert c.flops == 2 * 3 * 3 * 3 * out_elems


def test_cost_elementwise_and_movement():
    c = _one_op_cost('elementwise_add',
                     {'a': ((4, 8), 'float32'), 'b': ((4, 8), 'float32')},
                     {}, {'x': ['a'], 'y': ['b']})
    assert c.flops == 32
    c = _one_op_cost('reshape', {'a': ((4, 8), 'float32')},
                     {'shape': [8, 4]}, {'x': ['a']})
    assert c.flops == 0 and c.bytes == 2 * 32 * 4


def test_runtime_byte_widths():
    """int64 prices at 4 bytes — the device computes it as int32 under
    the default x64-off config, and the measured counterpart sums real
    device buffers."""
    assert dtype_nbytes('int64') == 4
    assert dtype_nbytes('bfloat16') == 2
    assert dtype_nbytes('bool') == 1
    assert info_nbytes(VarInfo((4, 2), 'int64')) == 32
    # UNKNOWN dims substitute assume_dim
    assert info_nbytes(VarInfo((-1, 8), 'float32'), assume_dim=16) == 512


# ---------------------------------------------------------------------------
# planner
# ---------------------------------------------------------------------------

def test_plan_accounting_and_report():
    main, _startup, feed, fetches = _mnist_mlp()
    shapes = {k: v.shape for k, v in feed.items()}
    plan = plan_program(main, fetch_names=fetches, feed_names=sorted(feed),
                        feed_shapes=shapes)
    assert plan.peak_bytes >= plan.accounted_bytes > 0
    assert plan.grad_bytes > 0 and plan.activation_bytes > 0
    assert plan.fwd_flops > 0 and plan.total_flops > plan.fwd_flops
    assert plan.donation_saved_bytes > 0      # params update in place
    assert len(plan.timeline) == len(main.global_block().ops)
    assert not plan.uncosted_ops
    assert plan.plan_seconds < 1.0            # milliseconds, zero tracing
    report = '\n'.join(plan.format_report(top=5))
    assert 'predicted peak HBM' in report and 'Top residents' in report
    d = plan.to_dict()
    assert d['peak_hbm_bytes'] == plan.peak_bytes


def test_plan_donation_split():
    """donate=False keeps written state out of the in-place set — the
    plan must price the copy-in/copy-out double buffer."""
    main, _startup, feed, fetches = _mnist_mlp()
    shapes = {k: v.shape for k, v in feed.items()}
    on = plan_program(main, fetch_names=fetches, feed_shapes=shapes,
                      donate=True)
    off = plan_program(main, fetch_names=fetches, feed_shapes=shapes,
                       donate=False)
    assert off.peak_bytes == on.peak_bytes + on.donation_saved_bytes
    assert off.donation_saved_bytes == 0


def test_gradient_bytes_matches_params():
    main, _startup, feed, _f = _mnist_mlp()
    expect = sum(int(np.prod(p.shape)) * 4 for p in main.all_parameters())
    assert gradient_bytes(main) == expect


def test_select_checkpoints_consistent_with_replan():
    main, _startup, feed, fetches = _mnist_mlp()
    shapes = {k: v.shape for k, v in feed.items()}
    base = plan_program(main, fetch_names=fetches, feed_shapes=shapes)
    names, peak = select_checkpoints(main, int(base.peak_bytes * 0.8),
                                     fetch_names=fetches,
                                     feed_shapes=shapes)
    assert names, 'selector found no boundary on a 17-op MLP'
    replanned = plan_program(main, fetch_names=fetches,
                             feed_shapes=shapes, checkpoints=names)
    assert replanned.peak_bytes == peak
    assert peak < base.peak_bytes


@pytest.mark.parametrize('name', sorted(_RECIPES))
def test_predicted_vs_measured_bytes(name):
    """The acceptance bar: the plan's state+feed+fetch prediction matches
    the executor's measured byte accounting within 10% on every tier-1
    verifier recipe (exact for fully-static programs)."""
    main, startup, feed, fetches = _RECIPES[name]()
    with obs.telemetry_guard(True):
        obs.reset()
        exe = fluid.Executor()
        exe.run(startup)
        exe.run(main, feed=feed, fetch_list=list(fetches))
        d = obs.registry.to_dict()
    predicted = d['program_plan_accounted_bytes']['samples'][0]['value']
    measured = d['program_measured_hbm_bytes']['samples'][0]['value']
    peak = d['program_peak_hbm_bytes']['samples'][0]['value']
    plan_s = d['program_plan_seconds']['samples'][0]
    assert 'program_plan_failures' not in d, d.get('program_plan_failures')
    assert predicted > 0 and measured > 0
    assert abs(measured - predicted) / measured <= 0.10, \
        f'{name}: predicted {predicted} vs measured {measured}'
    assert peak >= predicted
    assert plan_s['count'] >= 1 and plan_s['sum'] < 2.0


# ---------------------------------------------------------------------------
# auto-remat
# ---------------------------------------------------------------------------

def _remat_model(manual_ckpt_names=None, depth=6, width=64, bs=16):
    _fresh_names()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = L.data('x', [width], dtype='float32')
        y = L.data('y', [1], dtype='float32')
        h = x
        for _ in range(depth):
            h = L.fc(h, size=width, act='relu')
        pred = L.fc(h, size=1)
        loss = L.reduce_mean(L.square_error_cost(pred, y))
        opt = fluid.optimizer.SGD(0.1)
        if manual_ckpt_names:
            opt = fluid.optimizer.RecomputeOptimizer(opt)
            opt._set_checkpoints(list(manual_ckpt_names))
        opt.minimize(loss)
    rng = np.random.RandomState(0)
    feed = {'x': rng.randn(bs, width).astype(np.float32),
            'y': rng.randn(bs, 1).astype(np.float32)}
    return main, startup, feed, loss


def _run_steps(main, startup, feed, loss, steps=3):
    exe = fluid.Executor()
    exe.run(startup)
    return [exe.run(main, feed=feed, fetch_list=[loss])[0]
            for _ in range(steps)]


def test_auto_remat_fits_budget_bitwise(monkeypatch):
    """The tentpole acceptance: a simulated HBM budget the unplanned
    program exceeds; auto-remat fits it; losses bitwise-identical to the
    un-rematerialized run AND to manual RecomputeOptimizer checkpointing
    over the same names."""
    monkeypatch.delenv('PADDLE_TPU_HBM_BUDGET_MB', raising=False)
    base = _run_steps(*_remat_model())

    main, _s, feed, loss = _remat_model()
    shapes = {k: v.shape for k, v in feed.items()}
    kw = dict(fetch_names=[loss.name], feed_names=sorted(feed),
              feed_shapes=shapes)
    no_remat = plan_program(main, **kw)
    _n, floor_peak = select_checkpoints(main, 0, **kw)
    budget = (floor_peak + no_remat.peak_bytes) // 2
    assert no_remat.peak_bytes > budget        # the program OOMs it

    monkeypatch.setenv('PADDLE_TPU_HBM_BUDGET_MB',
                       repr(budget / float(1 << 20)))
    m2, s2, feed2, loss2 = _remat_model()
    auto = _run_steps(m2, s2, feed2, loss2)
    opt_prog, ctx = ir.apply_pipeline(m2, fetch_names=[loss2.name],
                                      feed_names=sorted(feed2),
                                      feed_shapes=shapes)
    marker = next(op for op in opt_prog.global_block().ops
                  if op.type == BACKWARD_OP_TYPE)
    chosen = marker.attrs.get('checkpoints')
    assert chosen, 'auto_remat chose no checkpoints'
    assert ctx.stats.get('auto_remat', {}).get('checkpoints') == len(chosen)
    remat_plan = plan_program(opt_prog, **kw)
    assert remat_plan.peak_bytes <= budget, \
        f'{remat_plan.peak_bytes} > budget {budget}'

    monkeypatch.delenv('PADDLE_TPU_HBM_BUDGET_MB')
    manual = _run_steps(*_remat_model(manual_ckpt_names=chosen))

    for a, b in zip(auto, base):
        assert np.array_equal(a, b), 'remat changed numerics vs base'
    for a, m in zip(auto, manual):
        assert np.array_equal(a, m), 'auto vs manual checkpoints differ'


def test_auto_remat_respects_manual_checkpoints(monkeypatch):
    main, _s, feed, loss = _remat_model()
    blk = main.global_block()
    marker = next(op for op in blk.ops if op.type == BACKWARD_OP_TYPE)
    manual = [blk.ops[2].output_names()[0]]
    marker.attrs['checkpoints'] = list(manual)
    monkeypatch.setenv('PADDLE_TPU_HBM_BUDGET_MB', '0.0001')
    opt_prog, _ = ir.apply_pipeline(main, fetch_names=[loss.name],
                                    feed_names=sorted(feed))
    m2 = next(op for op in opt_prog.global_block().ops
              if op.type == BACKWARD_OP_TYPE)
    assert m2.attrs.get('checkpoints') == manual


def test_auto_remat_noop_under_budget(monkeypatch):
    monkeypatch.setenv('PADDLE_TPU_HBM_BUDGET_MB', '65536')   # 64 GiB
    main, _s, feed, loss = _remat_model()
    opt_prog, ctx = ir.apply_pipeline(main, fetch_names=[loss.name],
                                      feed_names=sorted(feed))
    marker = next(op for op in opt_prog.global_block().ops
                  if op.type == BACKWARD_OP_TYPE)
    assert not marker.attrs.get('checkpoints')
    assert 'auto_remat' not in ctx.stats


def test_hbm_budget_strict_parse(monkeypatch):
    monkeypatch.setenv('PADDLE_TPU_HBM_BUDGET_MB', 'lots')
    with pytest.raises(ValueError, match='PADDLE_TPU_HBM_BUDGET_MB'):
        auto_remat.hbm_budget_bytes()
    monkeypatch.setenv('PADDLE_TPU_HBM_BUDGET_MB', '-3')
    with pytest.raises(ValueError, match='> 0'):
        auto_remat.hbm_budget_bytes()
    monkeypatch.setenv('PADDLE_TPU_HBM_BUDGET_MB', '2048')
    assert auto_remat.hbm_budget_bytes() == 2048 << 20
    monkeypatch.delenv('PADDLE_TPU_HBM_BUDGET_MB')
    assert auto_remat.hbm_budget_bytes() is None


def test_pipeline_signature_tags(monkeypatch):
    from paddle_tpu.compiler import BuildStrategy
    monkeypatch.delenv('PADDLE_TPU_HBM_BUDGET_MB', raising=False)
    sig = pipeline_signature()
    assert not any(n.startswith('auto_remat') for n in sig)
    monkeypatch.setenv('PADDLE_TPU_HBM_BUDGET_MB', '1')
    sig = pipeline_signature()
    assert f'auto_remat@{1 << 20}' in sig
    # the bucket tag only counts when its fuse flag is live
    bs = BuildStrategy()
    bs.fuse_all_reduce_ops = True
    monkeypatch.setenv('PADDLE_TPU_ALLREDUCE_BUCKET_MB', 'auto')
    assert 'bucket_allreduce@auto' in pipeline_signature(bs)
    monkeypatch.setenv('PADDLE_TPU_ALLREDUCE_BUCKET_MB', '8')
    assert f'bucket_allreduce@{8 << 20}' in pipeline_signature(bs)


# ---------------------------------------------------------------------------
# bucket autotuning
# ---------------------------------------------------------------------------

def test_bucket_cap_auto_arithmetic(monkeypatch):
    monkeypatch.setenv('PADDLE_TPU_ALLREDUCE_BUCKET_MB', 'auto')
    # 100 MiB of grads / target 4 buckets = 25 MiB cap
    assert bucket_allreduce.bucket_cap_bytes(grad_bytes=100 << 20) \
        == 25 << 20
    # tiny models floor at 1 MiB (no latency-dominated shattering)
    assert bucket_allreduce.bucket_cap_bytes(grad_bytes=1000) == 1 << 20
    assert bucket_allreduce.bucket_cap_bytes() is None
    assert bucket_allreduce.bucket_cap_is_auto()
    monkeypatch.setenv('PADDLE_TPU_ALLREDUCE_BUCKET_MB', '8')
    assert bucket_allreduce.bucket_cap_bytes(grad_bytes=100 << 20) \
        == 8 << 20
    monkeypatch.setenv('PADDLE_TPU_ALLREDUCE_BUCKET_MB', 'autoo')
    with pytest.raises(ValueError, match="'auto'"):
        bucket_allreduce.bucket_cap_bytes()


def test_bucket_auto_e2e(monkeypatch):
    """=auto forms buckets on the fleet DP recipe (grads ≪ 1 MiB floor →
    one bucket per compatible run) and stays bitwise vs per-grad ops."""
    monkeypatch.delenv('PADDLE_TPU_ALLREDUCE_BUCKET_MB', raising=False)
    from paddle_tpu.compiler import BuildStrategy
    _fresh_names()
    main, startup, feed, fetches = _fleet_dp()
    bs = BuildStrategy()
    bs.fuse_all_reduce_ops = True
    monkeypatch.setenv('PADDLE_TPU_ALLREDUCE_BUCKET_MB', 'auto')
    opt_prog, ctx = ir.apply_pipeline(main, fetch_names=fetches,
                                      feed_names=sorted(feed),
                                      build_strategy=bs)
    bucketed = [op for op in opt_prog.global_block().ops
                if op.type == 'c_allreduce_sum_bucket']
    assert bucketed, 'auto cap formed no bucket'
    assert ctx.stats['bucket_allreduce']['buckets'] >= 1
    # bitwise: bucketed (auto cap) vs unbucketed fetches
    exe = fluid.Executor()
    exe.run(startup)
    from paddle_tpu.compiler import CompiledProgram
    on = exe.run(CompiledProgram(main, build_strategy=bs), feed=feed,
                 fetch_list=list(fetches))
    monkeypatch.delenv('PADDLE_TPU_ALLREDUCE_BUCKET_MB')
    _fresh_names()
    main2, startup2, feed2, fetches2 = _fleet_dp()
    exe2 = fluid.Executor()
    exe2.run(startup2)
    off = exe2.run(main2, feed=feed2, fetch_list=list(fetches2))
    for a, b in zip(on, off):
        assert np.array_equal(a, b)


# ---------------------------------------------------------------------------
# RecomputeOptimizer validation satellite
# ---------------------------------------------------------------------------

def test_recompute_checkpoints_duplicate_raises():
    opt = fluid.optimizer.RecomputeOptimizer(fluid.optimizer.SGD(0.1))
    with pytest.raises(ValueError, match=r"duplicate.*\['h'\]"):
        opt._set_checkpoints(['h', 'h'])
    with pytest.raises(ValueError, match='Variables or var names'):
        opt._set_checkpoints([42])
    with pytest.raises(ValueError, match='list/tuple'):
        opt._set_checkpoints('h')


def test_recompute_checkpoints_unknown_name_raises():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = L.data('x', [8], dtype='float32')
        y = L.data('y', [1], dtype='float32')
        h = L.fc(x, size=8, act='relu')
        loss = L.reduce_mean(L.square_error_cost(L.fc(h, size=1), y))
        opt = fluid.optimizer.RecomputeOptimizer(fluid.optimizer.SGD(0.1))
        opt._set_checkpoints(['no_such_var'])
        with pytest.raises(ValueError, match="no_such_var"):
            opt.minimize(loss)


def test_recompute_checkpoints_valid_still_train():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = L.data('x', [8], dtype='float32')
        y = L.data('y', [1], dtype='float32')
        h = L.fc(x, size=8, act='relu')
        loss = L.reduce_mean(L.square_error_cost(L.fc(h, size=1), y))
        opt = fluid.optimizer.RecomputeOptimizer(fluid.optimizer.SGD(0.1))
        opt._set_checkpoints([h])
        opt.minimize(loss)
    marker = next(op for op in main.global_block().ops
                  if op.type == BACKWARD_OP_TYPE)
    assert marker.attrs['checkpoints'] == [h.name]
    exe = fluid.Executor()
    exe.run(startup)
    out, = exe.run(main, feed={'x': np.ones((4, 8), np.float32),
                               'y': np.zeros((4, 1), np.float32)},
                   fetch_list=[loss])
    assert np.isfinite(out).all()


# ---------------------------------------------------------------------------
# CLIs
# ---------------------------------------------------------------------------

def test_plan_program_cli_budget_gate(capsys):
    import plan_program as cli
    rc = cli.main(['--recipe', 'mnist_mlp', '--json', '--budget', '4096'])
    out = capsys.readouterr().out
    assert rc == 0
    import json
    doc = json.loads(out)
    assert doc['fits_budget'] and doc['peak_hbm_bytes'] > 0
    rc = cli.main(['--recipe', 'mnist_mlp', '--budget', '0.001'])
    assert rc == 1


def test_lint_program_plan_flag(capsys):
    import lint_program as cli
    rc = cli.main(['--recipe', 'mnist_mlp', '--plan'])
    out = capsys.readouterr().out
    assert rc == 0
    assert 'Memory plan' in out and 'predicted peak HBM' in out
