"""LoDTensor unified ragged container (SURVEY §2.1; ref
python/paddle/fluid/lod_tensor.py + framework/lod_tensor.h): creation
APIs, LoD/length accessors, implicit length threading through sequence
layers via the Executor feed path, and DataFeeder ragged batching."""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers


def test_create_lod_tensor_from_rows():
    t = fluid.create_lod_tensor([[1.0, 2.0], [3.0, 4.0, 5.0]], [[2, 3]],
                                fluid.CPUPlace())
    assert t.shape() == (2, 3)
    assert t.recursive_sequence_lengths() == [[2, 3]]
    assert t.lod() == [[0, 2, 5]]
    np.testing.assert_array_equal(t.lengths, [2, 3])
    rows = t.to_rows()
    np.testing.assert_allclose(rows[0], [1.0, 2.0])
    np.testing.assert_allclose(rows[1], [3.0, 4.0, 5.0])
    assert t.has_valid_recursive_sequence_lengths()


def test_create_lod_tensor_from_flat_array():
    flat = np.arange(10, dtype=np.float32).reshape(5, 2)
    t = fluid.create_lod_tensor(flat, [[2, 3]])
    assert t.shape() == (2, 3, 2)
    np.testing.assert_allclose(t.data[0, :2], flat[:2])
    np.testing.assert_allclose(t.data[1, :3], flat[2:])
    assert np.all(t.data[0, 2] == 0)      # padding


def test_set_lod_offsets_roundtrip():
    t = fluid.LoDTensor(np.zeros((3, 4), np.float32))
    t.set_lod([[0, 1, 3, 4]])
    assert t.recursive_sequence_lengths() == [[1, 2, 1]]


def test_create_random_int_lodtensor():
    t = fluid.create_random_int_lodtensor([[2, 4]], [1], None, 0, 7)
    assert t.shape() == (2, 4, 1)
    assert t.data.dtype == np.int64
    assert t.data.max() <= 7


def test_lod_tensor_feeds_sequence_layers_implicitly():
    """data(lod_level=1) + LoDTensor feed: sequence_pool sees the true
    lengths with no explicit sequence_length arg anywhere."""
    x = layers.data('seq', [4, 3], dtype='float32', lod_level=1)
    pooled = layers.sequence_pool(x, 'average')
    exe = fluid.Executor()
    rows = [np.ones((2, 3), np.float32) * 2.0,
            np.ones((4, 3), np.float32) * 3.0]
    t = fluid.create_lod_tensor(rows, [[2, 4]])
    out, = exe.run(feed={'seq': t}, fetch_list=[pooled])
    # averages over the VALID prefix only: 2.0 and 3.0 (not diluted by pad)
    np.testing.assert_allclose(out[0], np.full(3, 2.0), rtol=1e-6)
    np.testing.assert_allclose(out[1], np.full(3, 3.0), rtol=1e-6)


def test_lod_length_carries_through_chained_layers():
    x = layers.data('s2', [4, 1], dtype='float32', lod_level=1)
    sm = layers.sequence_softmax(x)
    last = layers.sequence_last_step(sm)
    exe = fluid.Executor()
    rows = [np.array([[1.], [2.]], np.float32),
            np.array([[1.], [1.], [1.], [1.]], np.float32)]
    t = fluid.create_lod_tensor(rows, [[2, 4]])
    sv, lv = exe.run(feed={'s2': t}, fetch_list=[sm, last])
    # row 0 softmax over 2 valid steps; padding stays 0
    np.testing.assert_allclose(sv[0, :2, 0].sum(), 1.0, rtol=1e-5)
    assert sv[0, 2:, 0].max() == 0.0
    # last VALID step of row 0 is index 1
    np.testing.assert_allclose(lv[0], sv[0, 1], rtol=1e-6)


def test_lod_program_exports_with_plain_example_feed():
    """lower_to_callable (the inference-export surface) on a lod_level>0
    program: the export path must synthesize full lengths for a plain
    example array."""
    x = layers.data('sx', [4, 3], dtype='float32', lod_level=1)
    pooled = layers.sequence_pool(x, 'average')
    exe = fluid.Executor()
    fn, args = exe.lower_to_callable(
        fluid.default_main_program(),
        {'sx': np.ones((2, 4, 3), np.float32)}, [pooled])
    out = fn(*args)
    assert np.asarray(out[0]).shape == (2, 3)


def test_data_feeder_builds_lod_tensor_for_ragged():
    x = layers.data('rag', [5, 2], dtype='float32', lod_level=1)
    feeder = fluid.DataFeeder(feed_list=[x])
    batch = [(np.ones((2, 2), np.float32),),
             (np.ones((5, 2), np.float32),)]
    feed = feeder.feed(batch)
    t = feed['rag']
    assert isinstance(t, fluid.LoDTensor)
    np.testing.assert_array_equal(t.lengths, [2, 5])
    assert t.data.shape == (2, 5, 2)
