"""Elastic runtime units (ISSUE 19): reshard-manifest legality, the
strict-parse resize schedule, the resize.json handoff, and the goodput
resize bucket — everything that doesn't need a live fleet (those drills
live in test_elastic_resize.py / test_autoscaler.py)."""
import time

import numpy as np
import pytest

from paddle_tpu.elastic import (ReshardError, ResizePlan, check_reshard,
                                clear_resize_request, current_mesh_axes,
                                parse_resize_env, parse_resize_spec,
                                read_resize_request, write_resize_request)
from paddle_tpu.elastic.schedule import ENV_ELASTIC_RESIZE
from paddle_tpu.resilience.goodput import GoodputTracker


class _Part:
    """Stand-in partitioner: just the mesh/axis_sizes surface
    check_reshard consumes."""

    def __init__(self, axes):
        self._axes = dict(axes)
        self.mesh = object() if axes else None

    def axis_sizes(self):
        return dict(self._axes)


SAVED_4 = {
    'mesh_axes': {'fsdp': 4},
    'axis_rules': {},
    'specs': {'fc_0.w_0': ['fsdp', None], 'fc_0.b_0': [None]},
}


# ---------------------------------------------------------------------------
# reshard manifest check
# ---------------------------------------------------------------------------
def test_check_reshard_same_mesh_is_not_a_reshard():
    info = check_reshard(SAVED_4, partitioner=_Part({'fsdp': 4}),
                         shapes={'fc_0.w_0': (16, 8)})
    assert info['resharded'] is False
    assert info['saved_axes'] == {'fsdp': 4}
    assert info['current_axes'] == {'fsdp': 4}


def test_check_reshard_shrink_and_grow_are_legal():
    for size in (1, 2, 8):
        info = check_reshard(SAVED_4, partitioner=_Part({'fsdp': size}),
                             shapes={'fc_0.w_0': (16, 8)})
        assert info['resharded'] is True, size
        assert info['current_axes'] == {'fsdp': size}


def test_check_reshard_no_mesh_means_replicated_restore():
    # a single-process restore (no mesh) reassembles full values and
    # places them replicated: always legal
    info = check_reshard(SAVED_4, partitioner=_Part({}), shapes=None)
    assert info['current_axes'] == {}
    assert info['resharded'] is True


def test_check_reshard_divisibility_error_is_typed_and_named():
    with pytest.raises(ReshardError) as ei:
        check_reshard(SAVED_4, partitioner=_Part({'fsdp': 3}),
                      shapes={'fc_0.w_0': (16, 8)})
    e = ei.value
    # the error NAMES the variable, the dim, and both meshes — the whole
    # point vs. a device_put shape error minutes later
    assert e.name == 'fc_0.w_0' and e.dim == 0
    assert e.saved_axes == {'fsdp': 4}
    assert e.current_axes == {'fsdp': 3}
    msg = str(e)
    assert 'fc_0.w_0' in msg and 'fsdp' in msg and '3' in msg
    assert isinstance(e, ValueError)       # callers catching ValueError work


def test_check_reshard_missing_axis_error():
    with pytest.raises(ReshardError) as ei:
        check_reshard(SAVED_4, partitioner=_Part({'mp': 2}),
                      shapes={'fc_0.w_0': (16, 8)})
    assert ei.value.name == 'fc_0.w_0'
    assert 'fsdp' in str(ei.value) and 'mp' in str(ei.value)


def test_check_reshard_scoped_shape_lookup():
    # manager shapes are often scope-qualified; the check must find them
    info = check_reshard(SAVED_4, partitioner=_Part({'fsdp': 2}),
                         shapes={'scope/fc_0.w_0': (16, 8)})
    assert info['resharded'] is True


def test_current_mesh_axes_without_mesh_is_empty():
    assert current_mesh_axes(_Part({})) == {}


def test_sharded_read_mesh_agnostic_then_restore_check_raises(tmp_path):
    """The read itself is mesh-agnostic (inspection tooling must be able
    to read any checkpoint from any process); the manifest a REAL sharded
    write commits then drives the restore-path check: a compatible mesh
    passes (resharded flagged), an incompatible one raises the typed,
    named ReshardError up front — not a shape error downstream."""
    from paddle_tpu.fleet_runtime import sharded_ckpt as sc
    from paddle_tpu.resilience import snapshot as snap
    import paddle_tpu.elastic.reshard as rs
    sc.write_host_shard(
        str(tmp_path), step=3,
        arrays={'w': np.arange(32, dtype=np.float32).reshape(16, 2)},
        rank=0, world=1)
    sc.commit_fleet_manifest(
        str(tmp_path), step=3, world=1,
        meta={'partition': {'mesh_axes': {'fsdp': 4},
                            'specs': {'w': ['fsdp', None]}}})
    ck = snap.latest_checkpoint(str(tmp_path))
    assert ck is not None and ck.sharded
    orig = rs.current_mesh_axes
    try:
        # the read never consults the process mesh — even one the saved
        # layout could not be laid onto
        rs.current_mesh_axes = lambda partitioner=None: {'fsdp': 3}
        arrays, meta = snap.read_checkpoint(ck)
        assert arrays['w'].shape == (16, 2)
        shapes = {k: v.shape for k, v in arrays.items()}
        # the restore-path check on the SAME manifest: compatible mesh
        # passes and flags the reshard ...
        rs.current_mesh_axes = lambda partitioner=None: {'fsdp': 2}
        info = rs.check_reshard(meta['partition'], shapes=shapes, step=3)
        assert info['resharded'] is True
        # ... incompatible (16 % 3 != 0) raises typed and named
        rs.current_mesh_axes = lambda partitioner=None: {'fsdp': 3}
        with pytest.raises(ReshardError) as ei:
            rs.check_reshard(meta['partition'], shapes=shapes, step=3)
        assert ei.value.name == 'w'
    finally:
        rs.current_mesh_axes = orig


# ---------------------------------------------------------------------------
# resize schedule: strict parse + handoff file
# ---------------------------------------------------------------------------
def test_parse_resize_spec():
    plan = parse_resize_spec('at_step=20:nproc=8')
    assert plan == ResizePlan(step=20, nproc=8)
    assert not plan.due(19) and plan.due(20) and plan.due(21)
    # order-insensitive
    assert parse_resize_spec('nproc=2:at_step=5') == ResizePlan(5, 2)


@pytest.mark.parametrize('raw', [
    'at_step=5',                # missing nproc
    'nproc=4',                  # missing at_step
    'at_step=0:nproc=4',        # step must be >= 1
    'at_step=5:nproc=0',        # nproc must be >= 1
    'at_step=x:nproc=4',        # not an int
    'at_step=5:nproc=4:bogus=1',  # unknown key
    'whatever',
])
def test_parse_resize_spec_rejects_malformed(raw):
    with pytest.raises(ValueError) as ei:
        parse_resize_spec(raw)
    assert ENV_ELASTIC_RESIZE in str(ei.value)   # error names the knob


def test_parse_resize_env(monkeypatch):
    monkeypatch.delenv(ENV_ELASTIC_RESIZE, raising=False)
    assert parse_resize_env() is None
    monkeypatch.setenv(ENV_ELASTIC_RESIZE, 'at_step=7:nproc=2')
    assert parse_resize_env() == ResizePlan(7, 2)
    monkeypatch.setenv(ENV_ELASTIC_RESIZE, 'nonsense')
    with pytest.raises(ValueError):
        parse_resize_env()


def test_resize_request_roundtrip(tmp_path):
    d = str(tmp_path)
    assert read_resize_request(d) is None
    write_resize_request(d, step=9, target_nproc=2, from_nproc=4)
    req = read_resize_request(d)
    assert req['step'] == 9
    assert req['target_nproc'] == 2 and req['from_nproc'] == 4
    assert req['unix_time'] > 0
    clear_resize_request(d)
    assert read_resize_request(d) is None
    clear_resize_request(d)                      # idempotent


# ---------------------------------------------------------------------------
# goodput: the resize bucket is distinct from crash loss
# ---------------------------------------------------------------------------
def test_goodput_resize_bucket_separate_from_crash_loss():
    g = GoodputTracker()
    hb = time.time() - 4.0
    g.record_restart(
        {'steps': 6, 'productive_s': 3.0, 'wall_s': 10.0,
         'resizes': 1, 'resize_lost_s': 2.0},
        {'steps': 6, 'productive_s': 3.0, 'wall_s': 10.5,
         'unix_time': hb, 'resize_exit': True})
    # scheduled resize: checkpoint was synchronous at the boundary →
    # zero crash loss; downtime books in the resize bucket and prior
    # resize counters carry forward
    assert g.lost_steps == 0 and g.lost_s == 0.0
    assert g.resizes == 2
    assert g.resize_lost_s >= 2.0 + 3.5
    meta = g.meta()
    assert meta['resizes'] == 2
    assert meta['resize_lost_s'] == pytest.approx(g.resize_lost_s, abs=1e-3)


def test_goodput_crash_loss_still_books_normally():
    g = GoodputTracker()
    g.record_restart(
        {'steps': 6, 'productive_s': 3.0, 'wall_s': 10.0},
        {'steps': 8, 'productive_s': 4.0, 'wall_s': 11.0,
         'unix_time': time.time() - 2.0})     # no resize_exit: a crash
    assert g.lost_steps == 2
    assert g.lost_s == pytest.approx(1.0)
    assert g.resizes == 0 and g.resize_lost_s == 0.0
