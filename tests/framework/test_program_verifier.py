"""Static Program verifier (paddle_tpu/analysis/): seeded-defect corpus
(every diagnostic class, asserting code + op + construction site), the
zero-false-positive sweep over the tier-1 recipe programs (pre- and
post-pass-pipeline), pass post-condition enforcement (an intentionally
broken pass is caught AT THE PASS BOUNDARY naming the pass), Executor
pre-lowering validation at PADDLE_TPU_VERIFY=full, the inference-rule
lattice, and regression tests for the latent defects the verifier
surfaced (clone(for_test) dead vars, generated-layer dtype fallback,
lstm/gru optional slots)."""
import os
import sys

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import analysis, ir, layers as L
from paddle_tpu.analysis import (Diagnostic, ProgramVerificationError,
                                 UNKNOWN, VarInfo)
from paddle_tpu.analysis.infer import (InferError, broadcast_shapes,
                                       infer_op)
from paddle_tpu.compiler import BuildStrategy
from paddle_tpu.ir.pass_base import Pass, PassContext, PassManager
from paddle_tpu.ir import get_pass

sys.path.insert(0, os.path.join(
    os.path.dirname(__file__), '..', '..', 'tools'))
from bench_passes import (build_bert_layer, build_mlp_adam,  # noqa: E402
                          build_resnet_block)

_THIS_FILE = os.path.abspath(__file__)


def _codes(diags):
    return [d.code for d in diags]


def _find(diags, code):
    hits = [d for d in diags if d.code == code]
    assert hits, f'no {code!r} diagnostic in {[d.format() for d in diags]}'
    return hits[0]


def _assert_site_here(diag):
    """Construction-site capture points into THIS test file."""
    assert diag.site is not None, diag.format()
    assert os.path.abspath(diag.site.rsplit(':', 1)[0]) == _THIS_FILE, \
        diag.site


# ---------------------------------------------------------------------------
# seeded-defect corpus: one program per defect class
# ---------------------------------------------------------------------------

def _prog():
    main, startup = fluid.Program(), fluid.Program()
    guard = fluid.program_guard(main, startup)
    guard.__enter__()
    return main, guard


def test_defect_read_before_write():
    main, g = _prog()
    try:
        L.data('x', [4], dtype='float32')
        blk = main.global_block()
        blk.create_var(name='ghost', shape=[4], dtype='float32')
        blk.append_op('relu', inputs={'x': 'ghost'}, outputs={'Out': 'o'})
        blk.create_var(name='o', shape=[4], dtype='float32')
    finally:
        g.__exit__(None, None, None)
    d = _find(analysis.verify_program(main, fetch_names=['o']),
              'read-before-write')
    assert d.severity == 'error' and d.op_type == 'relu' \
        and d.var == 'ghost'
    _assert_site_here(d)


def test_defect_dangling_var():
    main, g = _prog()
    try:
        x = L.data('x', [4], dtype='float32')
        main.global_block().append_op(
            'relu', inputs={'x': 'never_declared'},
            outputs={'Out': x.name})
    finally:
        g.__exit__(None, None, None)
    d = _find(analysis.verify_program(main, fetch_names=[x.name]),
              'dangling-var')
    assert d.severity == 'error' and d.var == 'never_declared'
    _assert_site_here(d)


def test_defect_shape_mismatch_matmul():
    main, g = _prog()
    try:
        L.data('a', [8, 3], dtype='float32', append_batch_size=False)
        L.data('b', [4, 5], dtype='float32', append_batch_size=False)
        blk = main.global_block()
        blk.create_var(name='mm', shape=None, dtype='float32')
        blk.append_op('matmul', inputs={'x': 'a', 'y': 'b'},
                      outputs={'Out': 'mm'})
    finally:
        g.__exit__(None, None, None)
    d = _find(analysis.verify_program(main, fetch_names=['mm']),
              'shape-mismatch')
    assert d.severity == 'error' and d.op_type == 'matmul'
    assert 'K=3' in d.message and 'K=4' in d.message
    _assert_site_here(d)


def test_defect_bad_attr_cast_without_dtype():
    main, g = _prog()
    try:
        L.data('a', [8], dtype='float32')
        blk = main.global_block()
        blk.create_var(name='c', shape=None, dtype='float32')
        blk.append_op('cast', inputs={'x': 'a'}, outputs={'Out': 'c'})
    finally:
        g.__exit__(None, None, None)
    d = _find(analysis.verify_program(main, fetch_names=['c']), 'bad-attr')
    assert d.severity == 'error' and d.op_type == 'cast'
    assert "'dtype'" in d.message
    _assert_site_here(d)


def test_defect_dtype_mismatch_hard_label():
    """softmax_with_cross_entropy with a FLOAT hard label — the op would
    gather with garbage indices at runtime."""
    main, g = _prog()
    try:
        logits = L.data('lg', [10], dtype='float32')
        lab = L.data('lb', [1], dtype='float32')       # wrong: float label
        blk = main.global_block()
        blk.create_var(name='loss', shape=None, dtype='float32')
        blk.create_var(name='sm', shape=None, dtype='float32')
        blk.append_op('softmax_with_cross_entropy',
                      inputs={'logits': logits.name, 'label': lab.name},
                      outputs={'Loss': 'loss', 'Softmax': 'sm'})
    finally:
        g.__exit__(None, None, None)
    d = _find(analysis.verify_program(main, fetch_names=['loss']),
              'dtype-mismatch')
    assert d.severity == 'error' and 'soft_label' in d.message
    _assert_site_here(d)


def test_defect_unknown_op():
    main, g = _prog()
    try:
        x = L.data('x', [4], dtype='float32')
        main.global_block().append_op('reluu', inputs={'x': x.name},
                                      outputs={'Out': x.name})
    finally:
        g.__exit__(None, None, None)
    d = _find(analysis.verify_program(main), 'unknown-op')
    assert d.severity == 'error' and d.op_type == 'reluu'
    _assert_site_here(d)


def test_defect_dtype_decl_mismatch():
    main, g = _prog()
    try:
        x = L.data('x', [8], dtype='float32')
        blk = main.global_block()
        blk.create_var(name='w', shape=[-1, 8], dtype='int64')
        blk.append_op('relu', inputs={'x': x.name}, outputs={'Out': 'w'})
    finally:
        g.__exit__(None, None, None)
    d = _find(analysis.verify_program(main, fetch_names=['w']),
              'dtype-decl-mismatch')
    assert d.severity == 'warning' and d.var == 'w'
    _assert_site_here(d)


def test_defect_dead_write():
    main, g = _prog()
    try:
        x = L.data('x', [8], dtype='float32')
        L.relu(x)                       # never read, never fetched
        out = L.scale(x, scale=2.0)
    finally:
        g.__exit__(None, None, None)
    d = _find(analysis.verify_program(main, fetch_names=[out.name]),
              'dead-write')
    assert d.op_type == 'relu'
    _assert_site_here(d)


def test_defect_donated_fetch():
    main, g = _prog()
    try:
        x = L.data('x', [4], dtype='float32')
        y = L.data('y', [1], dtype='float32')
        h = L.fc(x, size=4)
        loss = L.reduce_mean(L.square_error_cost(h, y))
        fluid.optimizer.SGD(0.1).minimize(loss)
    finally:
        g.__exit__(None, None, None)
    pname = main.all_parameters()[0].name
    d = _find(analysis.verify_program(
        main, fetch_names=[loss.name, pname]), 'donated-fetch')
    assert d.severity == 'warning' and d.var == pname
    assert d.op_type == 'sgd'


def test_defect_bucket_mixed_dtype():
    main, g = _prog()
    try:
        a = L.data('a', [4], dtype='float32')
        b = L.data('b', [4], dtype='bfloat16')
        blk = main.global_block()
        blk.append_op('c_allreduce_sum_bucket',
                      inputs={'xs': [a.name, b.name]},
                      outputs={'Out': [a.name, b.name]})
    finally:
        g.__exit__(None, None, None)
    d = _find(analysis.verify_program(main, fetch_names=[a.name]),
              'dtype-mismatch')
    assert d.severity == 'error' and 'dtype-uniform' in d.message
    _assert_site_here(d)


def test_defect_comm_dtype_drift():
    main, g = _prog()
    try:
        a = L.data('a', [4], dtype='float32')
        b = L.data('b', [4], dtype='float32')
        blk = main.global_block()
        blk.append_op('c_allreduce_sum', inputs={'x': a.name},
                      outputs={'Out': a.name}, attrs={'comm_dtype': 'f32'})
        blk.append_op('c_allreduce_sum', inputs={'x': b.name},
                      outputs={'Out': b.name}, attrs={'comm_dtype': 'int8'})
    finally:
        g.__exit__(None, None, None)
    d = _find(analysis.verify_program(
        main, fetch_names=[a.name, b.name]), 'comm-dtype-drift')
    assert d.severity == 'warning' and "'int8'" in d.message
    _assert_site_here(d)


def test_defect_bad_comm_dtype_attr():
    main, g = _prog()
    try:
        a = L.data('a', [4], dtype='float32')
        main.global_block().append_op(
            'c_allreduce_sum', inputs={'x': a.name},
            outputs={'Out': a.name}, attrs={'comm_dtype': 'fp8'})
    finally:
        g.__exit__(None, None, None)
    d = _find(analysis.verify_program(main, fetch_names=[a.name]),
              'bad-attr')
    assert "'fp8'" in d.message


def test_defect_allreduce_under_kstep():
    """Per-grad c_allreduce_sum in a gradient-merge program: the sync
    belongs at the k-step boundary (fleet skips insertion there; a hand-
    built or badly-rewritten program must be flagged)."""
    main, g = _prog()
    try:
        x = L.data('x', [16], dtype='float32')
        y = L.data('y', [1], dtype='float32')
        h = L.fc(x, size=16, act='relu')
        out = L.fc(h, size=1)
        loss = L.reduce_mean(L.square_error_cost(out, y))
        opt = fluid.optimizer.GradientMergeOptimizer(
            fluid.optimizer.SGD(0.1), k_steps=2)
        opt.minimize(loss)
    finally:
        g.__exit__(None, None, None)
    # seed the defect: insert a per-step allreduce after the marker
    from paddle_tpu.framework import BACKWARD_OP_TYPE, Operator
    blk = main.global_block()
    bwd = next(i for i, op in enumerate(blk.ops)
               if op.type == BACKWARD_OP_TYPE)
    grad = blk.ops[bwd].outputs['Grads'][0]
    blk.ops.insert(bwd + 1, Operator(
        blk, 'c_allreduce_sum', inputs={'x': grad}, outputs={'Out': grad},
        attrs={'axis': 'dp'}))
    d = _find(analysis.verify_program(main, fetch_names=[loss.name]),
              'allreduce-under-kstep')
    assert d.severity == 'warning'
    _assert_site_here(d)


def test_defect_rng_salt_missing_post_pass():
    main, g = _prog()
    try:
        x = L.data('x', [8], dtype='float32')
        h = L.dropout(x, dropout_prob=0.5)
    finally:
        g.__exit__(None, None, None)
    # pre stage: no complaint; post-pass stage: dropout lost its stamp
    assert 'rng-salt-missing' not in _codes(
        analysis.verify_program(main, fetch_names=[h.name]))
    d = _find(analysis.verify_program(
        main, fetch_names=[h.name], stage='post-pass'), 'rng-salt-missing')
    assert d.severity == 'warning' and d.op_type == 'dropout'


def test_defect_mixed_float_inputs():
    main, g = _prog()
    try:
        a = L.data('a', [8], dtype='float32')
        b = L.data('b', [8], dtype='bfloat16')
        c = L.elementwise_add(a, b)
    finally:
        g.__exit__(None, None, None)
    d = _find(analysis.verify_program(main, fetch_names=[c.name]),
              'mixed-float-inputs')
    assert d.severity == 'warning'
    # the same program under an AMP config is intentional → clean
    main._amp_config = {'white': set(), 'black': set(), 'dtype': None}
    assert 'mixed-float-inputs' not in _codes(
        analysis.verify_program(main, fetch_names=[c.name]))


def test_defect_missing_required_input():
    main, g = _prog()
    try:
        blk = main.global_block()
        blk.create_var(name='o', shape=[4, 4], dtype='float32')
        blk.append_op('matmul', inputs={}, outputs={'Out': 'o'})
    finally:
        g.__exit__(None, None, None)
    diags = analysis.verify_program(main, fetch_names=['o'])
    assert 'missing-input' in _codes(diags)
    assert _find(diags, 'missing-input').severity == 'error'


# ---------------------------------------------------------------------------
# zero-false-positive sweep: every tier-1 recipe, pre- and post-pipeline
# ---------------------------------------------------------------------------

def _fused_bs():
    bs = BuildStrategy()
    bs.fuse_elewise_add_act_ops = True
    bs.fuse_all_optimizer_ops = True
    bs.fuse_all_reduce_ops = True
    return bs


def _mnist_mlp():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = L.data('img', [64], dtype='float32')
        label = L.data('label', [1], dtype='int64')
        h = L.fc(img, size=32, act='relu')
        h = L.fc(h, size=32, act='relu')
        logits = L.fc(h, size=10)
        loss = L.reduce_mean(L.softmax_with_cross_entropy(logits, label))
        fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)
    return main, [loss.name], ['img', 'label']


def _fleet_dp():
    from paddle_tpu.parallel import DistributedStrategy, fleet
    fleet.init()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = L.data('x', shape=[32], dtype='float32')
        y = L.data('y', shape=[1], dtype='int64')
        h = L.fc(x, size=32, act='relu')
        h2 = L.fc(h, size=32, act='relu')
        logits = L.fc(h2, size=10)
        loss = L.reduce_mean(L.softmax_with_cross_entropy(logits, y))
        fleet.distributed_optimizer(
            fluid.optimizer.SGD(0.1),
            strategy=DistributedStrategy()).minimize(loss)
    return main, [loss.name], ['x', 'y']


def _decode_engine_prog():
    """Static decode-flavored program: embedding lookup + fc + softmax +
    greedy argmax over logits — the per-step program shape of the decode
    path, including an int64 id feed and an int64 argmax output."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = L.data('ids', [8], dtype='int64')
        emb = L.embedding(ids, size=[100, 16])
        h = L.fc(emb, size=16, act='tanh')
        logits = L.fc(h, size=100)
        nxt = L.argmax(logits, axis=-1)
    return main, [nxt.name], ['ids']


def _deepfm_sparse():
    """Static DeepFM over sparse id features: both embedding tables take
    the rows-only gradient path (is_sparse=True → padded-COO marker
    outputs + sparse_* update ops, docs/SPARSE.md) — the 7th recipe, so
    the sweep covers the sparse op family end to end."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = L.data('feat_ids', [8], dtype='int64')
        vals = L.data('feat_vals', [8], dtype='float32')
        label = L.data('ctr', [1], dtype='float32')
        w1 = L.embedding(ids, size=[500, 1], is_sparse=True)
        emb = L.embedding(ids, size=[500, 8], is_sparse=True)
        v3 = L.unsqueeze(vals, axes=[2])
        first = L.reduce_sum(w1 * v3, dim=1)
        e = emb * v3
        sum_sq = L.square(L.reduce_sum(e, dim=1))
        sq_sum = L.reduce_sum(L.square(e), dim=1)
        second = 0.5 * L.reduce_sum(sum_sq - sq_sum, dim=1, keep_dim=True)
        deep = L.fc(e, size=16, act='relu')
        logit = L.fc(L.concat([first, second, deep], axis=1), size=1)
        loss = L.reduce_mean(
            L.sigmoid_cross_entropy_with_logits(logit, label))
        fluid.optimizer.Adagrad(0.05).minimize(loss)
    return main, [loss.name], ['feat_ids', 'feat_vals', 'ctr']


_RECIPES = {
    'mnist_mlp': _mnist_mlp,
    'mlp_adam': lambda: _from_builder(build_mlp_adam),
    'resnet_block': lambda: _from_builder(build_resnet_block),
    'bert_layer': lambda: _from_builder(build_bert_layer),
    'fleet_dp': _fleet_dp,
    'decode_engine': _decode_engine_prog,
    'deepfm_sparse': _deepfm_sparse,
}


def _from_builder(builder):
    main, _startup, make_feed, fetch = builder(smoke=True)
    feed = make_feed() if callable(make_feed) else make_feed
    return main, [fetch.name], sorted(feed)


@pytest.mark.parametrize('name', sorted(_RECIPES))
def test_recipe_sweep_no_findings(name):
    """The acceptance bar: zero diagnostics of severity ≥ warning on
    every tier-1 recipe program, both before the pass pipeline and on
    its final output."""
    main, fetches, feeds = _RECIPES[name]()
    pre = analysis.verify_program(main, fetch_names=fetches,
                                  feed_names=feeds)
    bad = analysis.severity_at_least(pre, 'warning')
    assert not bad, '\n'.join(d.format() for d in bad)

    opt, _ = ir.apply_pipeline(main, fetch_names=fetches,
                               feed_names=feeds, build_strategy=_fused_bs())
    post = analysis.verify_program(opt, fetch_names=fetches,
                                   feed_names=feeds, stage='post-pipeline')
    bad = analysis.severity_at_least(post, 'warning')
    assert not bad, '\n'.join(d.format() for d in bad)


# ---------------------------------------------------------------------------
# pass post-condition: a broken pass is caught at its own boundary
# ---------------------------------------------------------------------------

class _BrokenRenamePass(Pass):
    """Test-only: rewrites the last op to read a nonexistent var."""
    name = 'test_broken_rename'
    order = 500

    def apply_impl(self, program, ctx):
        op = program.global_block().ops[-1]
        for k in op.inputs:
            op.inputs[k] = ['__not_a_var__']
        return True


class _BrokenProducerDropPass(Pass):
    """Test-only: deletes an op whose output a later op still reads."""
    name = 'test_broken_drop'
    order = 500

    def apply_impl(self, program, ctx):
        blk = program.global_block()
        blk.ops = [op for i, op in enumerate(blk.ops) if i != 0]
        return True


def _small_prog():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = L.data('x', [8], dtype='float32')
        h = L.fc(x, size=4, act='relu')
        loss = L.reduce_mean(h)
    return main, loss


@pytest.mark.parametrize('broken_cls', [_BrokenRenamePass,
                                        _BrokenProducerDropPass])
def test_broken_pass_caught_at_boundary(monkeypatch, broken_cls):
    monkeypatch.setenv('PADDLE_TPU_VERIFY', 'passes')
    main, loss = _small_prog()
    mgr = PassManager([get_pass('constant_fold'), broken_cls(),
                       get_pass('dce')])
    with pytest.raises(ProgramVerificationError) as ei:
        mgr.apply(main, PassContext(fetch_names=[loss.name],
                                    feed_names=['x']))
    assert ei.value.pass_name == broken_cls.name
    assert broken_cls.name in str(ei.value)
    assert ei.value.diagnostics           # the offending diagnostic rides


def test_broken_pass_not_blamed_for_preexisting_errors(monkeypatch):
    """Post-condition is 'no NEW errors': a pass that does not touch an
    already-broken region passes its boundary check."""
    monkeypatch.setenv('PADDLE_TPU_VERIFY', 'passes')
    main, loss = _small_prog()
    blk = main.global_block()
    # pre-existing defect, present BEFORE the pipeline runs
    from paddle_tpu.framework import Operator
    blk.ops.append(Operator(blk, 'relu', inputs={'x': '__preexisting__'},
                            outputs={'Out': loss.name}))
    mgr = PassManager([get_pass('constant_fold')])
    mgr.apply(main, PassContext(fetch_names=[loss.name],
                                feed_names=['x']))    # must not raise


def test_clean_pipeline_verifies_quietly(monkeypatch):
    monkeypatch.setenv('PADDLE_TPU_VERIFY', 'passes')
    main, fetches, feeds = _mnist_mlp()
    opt, _ = ir.apply_pipeline(main, fetch_names=fetches, feed_names=feeds,
                               build_strategy=_fused_bs())
    assert opt.num_ops() > 0


# ---------------------------------------------------------------------------
# executor integration: PADDLE_TPU_VERIFY=full pre-lowering validation
# ---------------------------------------------------------------------------

def test_executor_full_mode_rejects_malformed_program(monkeypatch):
    monkeypatch.setenv('PADDLE_TPU_VERIFY', 'full')
    main, g = _prog()
    try:
        x = L.data('x', [4], dtype='float32')
        blk = main.global_block()
        blk.create_var(name='o', shape=[-1, 4], dtype='float32')
        blk.append_op('relu', inputs={'x': 'missing_var'},
                      outputs={'Out': 'o'})
    finally:
        g.__exit__(None, None, None)
    exe = fluid.Executor()
    with pytest.raises(ProgramVerificationError) as ei:
        exe.run(main, feed={'x': np.zeros((2, 4), np.float32)},
                fetch_list=['o'])
    msg = str(ei.value)
    assert 'missing_var' in msg and 'relu' in msg
    assert os.path.basename(__file__) in msg     # construction site


def test_executor_full_mode_runs_clean_program(monkeypatch):
    monkeypatch.setenv('PADDLE_TPU_VERIFY', 'full')
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = L.data('x', [4], dtype='float32')
        h = L.fc(x, size=3, act='relu')
    exe = fluid.Executor()
    exe.run(startup)
    out, = exe.run(main, feed={'x': np.ones((2, 4), np.float32)},
                   fetch_list=[h])
    assert out.shape == (2, 3)


def test_trace_error_names_op_and_site(monkeypatch):
    """At passes level a PRE-EXISTING defect is not raised at the pass
    boundary (no-NEW-errors contract) — the trace then fails, and the
    exception carries the op type + construction site annotation."""
    monkeypatch.setenv('PADDLE_TPU_VERIFY', 'passes')
    main, g = _prog()
    try:
        L.data('a', [8, 3], dtype='float32', append_batch_size=False)
        L.data('b', [4, 5], dtype='float32', append_batch_size=False)
        blk = main.global_block()
        blk.create_var(name='mm', shape=None, dtype='float32')
        blk.append_op('matmul', inputs={'x': 'a', 'y': 'b'},
                      outputs={'Out': 'mm'})
    finally:
        g.__exit__(None, None, None)
    exe = fluid.Executor()
    with pytest.raises(Exception) as ei:
        exe.run(main, feed={'a': np.zeros((8, 3), np.float32),
                            'b': np.zeros((4, 5), np.float32)},
                fetch_list=['mm'])
    e = ei.value
    rendered = ' '.join([str(e)] + list(getattr(e, '__notes__', [])))
    assert "while lowering op 'matmul'" in rendered
    assert os.path.basename(__file__) in rendered     # construction site


def test_verify_level_strict_parse(monkeypatch):
    monkeypatch.setenv('PADDLE_TPU_VERIFY', 'everything')
    with pytest.raises(ValueError, match='PADDLE_TPU_VERIFY'):
        analysis.verify_level()
    monkeypatch.setenv('PADDLE_TPU_VERIFY', 'off')
    assert analysis.verify_level() == 'off'
    monkeypatch.delenv('PADDLE_TPU_VERIFY')
    assert analysis.verify_level() == 'off'


def test_site_capture_gated_by_env(monkeypatch):
    monkeypatch.setenv('PADDLE_TPU_VERIFY', 'off')
    main, g = _prog()
    try:
        x = L.data('x', [4], dtype='float32')
        h = L.relu(x)
    finally:
        g.__exit__(None, None, None)
    assert all(op._site is None for op in main.global_block().ops)

    monkeypatch.setenv('PADDLE_TPU_VERIFY', 'passes')
    main2, g = _prog()
    try:
        x = L.data('x2', [4], dtype='float32')
        h = L.relu(x)                                     # noqa: F841
    finally:
        g.__exit__(None, None, None)
    sites = [op._site for op in main2.global_block().ops]
    assert all(s is not None for s in sites)
    assert all(os.path.abspath(s.rsplit(':', 1)[0]) == _THIS_FILE
               for s in sites)


def test_clone_preserves_sites(monkeypatch):
    monkeypatch.setenv('PADDLE_TPU_VERIFY', 'passes')
    main, g = _prog()
    try:
        x = L.data('x', [4], dtype='float32')
        L.relu(x)
    finally:
        g.__exit__(None, None, None)
    clone = main.clone()
    for a, b in zip(main.global_block().ops, clone.global_block().ops):
        assert b._site == a._site


# ---------------------------------------------------------------------------
# inference-rule engine unit tests: the UNKNOWN lattice
# ---------------------------------------------------------------------------

def test_unknown_dims_never_poison():
    # dynamic batch broadcasts with anything
    assert broadcast_shapes((UNKNOWN, 4), (1, 4)) == (UNKNOWN, 4)
    assert broadcast_shapes((UNKNOWN, 4), (8, 1)) == (8, 4)
    with pytest.raises(InferError):
        broadcast_shapes((3, 4), (5, 4))


def test_varinfo_numel_and_display():
    v = VarInfo((-1, 8), 'float32')
    assert v.shape == (UNKNOWN, 8)
    assert v.numel() is None
    assert v.display_shape() == (-1, 8)
    assert VarInfo((2, 3), 'float32').numel() == 6


def _one_op_infer(op_type, inputs, attrs, outputs=('Out',), n_out=None):
    main, g = _prog()
    try:
        blk = main.global_block()
        env = {}
        for name, (shape, dtype) in inputs.items():
            blk.create_var(name=name, shape=shape, dtype=dtype)
            env[name] = VarInfo(shape, dtype)
        in_map = {}
        for slot, names in attrs.pop('__slots__').items():
            in_map[slot] = names
        out_map = {s: (n_out or {}).get(s, [f'{s}_out'])
                   for s in outputs}
        op = blk.append_op(op_type, inputs=in_map, outputs=out_map,
                           attrs=attrs)
        return infer_op(op, env, blk)
    finally:
        g.__exit__(None, None, None)


def test_rule_matmul_dynamic_batch():
    r = _one_op_infer('matmul',
                      {'a': ((-1, 16), 'float32'), 'b': ((16, 4), 'float32')},
                      {'__slots__': {'x': ['a'], 'y': ['b']}})
    assert r['Out'].shape == (UNKNOWN, 4)
    assert r['Out'].dtype == 'float32'


def test_rule_reshape_infers_minus_one():
    r = _one_op_infer('reshape', {'a': ((6, 4), 'float32')},
                      {'shape': [-1, 8], '__slots__': {'x': ['a']}})
    assert r['Out'].shape == (3, 8)
    with pytest.raises(InferError):
        _one_op_infer('reshape', {'a': ((6, 4), 'float32')},
                      {'shape': [5, 5], '__slots__': {'x': ['a']}})


def test_rule_concat_and_split():
    r = _one_op_infer('concat',
                      {'a': ((2, 3), 'float32'), 'b': ((4, 3), 'float32')},
                      {'axis': 0, '__slots__': {'xs': ['a', 'b']}})
    assert r['Out'].shape == (6, 3)
    with pytest.raises(InferError):
        _one_op_infer('concat',
                      {'a': ((2, 3), 'float32'), 'b': ((4, 5), 'float32')},
                      {'axis': 0, '__slots__': {'xs': ['a', 'b']}})
    r = _one_op_infer('split', {'a': ((2, 12), 'float32')},
                      {'num_or_sections': 3, 'dim': -1,
                       '__slots__': {'x': ['a']}},
                      n_out={'Out': ['s0', 's1', 's2']})
    assert [v.shape for v in r['Out']] == [(2, 4)] * 3


def test_rule_conv2d_shape():
    r = _one_op_infer('conv2d',
                      {'x': ((-1, 3, 8, 8), 'float32'),
                       'w': ((16, 3, 3, 3), 'float32')},
                      {'stride': 1, 'padding': 1,
                       '__slots__': {'x': ['x'], 'weight': ['w']}})
    assert r['Out'].shape == (UNKNOWN, 16, 8, 8)
    with pytest.raises(InferError):
        _one_op_infer('conv2d',
                      {'x': ((-1, 4, 8, 8), 'float32'),
                       'w': ((16, 3, 3, 3), 'float32')},
                      {'__slots__': {'x': ['x'], 'weight': ['w']}})


def test_rule_coverage_over_recipe_ops():
    """Every op type the tier-1 recipes emit has an inference rule —
    the coverage contract docs/ANALYSIS.md promises."""
    needed = set()
    for name, build in _RECIPES.items():
        main, _f, _d = build()
        for b in main.blocks:
            for op in b.ops:
                needed.add(op.type)
    from paddle_tpu.analysis import has_rule
    from paddle_tpu.framework import BACKWARD_OP_TYPE
    special = {BACKWARD_OP_TYPE}
    missing = {t for t in needed - special if not has_rule(t)}
    assert not missing, f'recipe ops without infer rules: {sorted(missing)}'


# ---------------------------------------------------------------------------
# regressions for latent defects the verifier surfaced
# ---------------------------------------------------------------------------

def test_regression_clone_for_test_drops_dead_grad_vars():
    """clone(for_test=True) used to keep the backward tail's @GRAD vars
    as dead declarations in every eval/inference program."""
    main, g = _prog()
    try:
        x = L.data('x', [16], dtype='float32')
        y = L.data('y', [1], dtype='float32')
        h = L.fc(x, size=16, act='relu')
        out = L.fc(h, size=1)
        loss = L.reduce_mean(L.square_error_cost(out, y))
        fluid.optimizer.Adam(1e-3).minimize(loss)
    finally:
        g.__exit__(None, None, None)
    test_prog = main.clone(for_test=True)
    names = set(test_prog.global_block().vars)
    assert not any(n.endswith('@GRAD') for n in names)
    diags = analysis.verify_program(test_prog, fetch_names=[out.name])
    assert 'dead-var' not in _codes(diags)
    # parameters and data vars survive the sweep
    assert all(p.name in names for p in main.all_parameters())
    assert 'x' in names and 'y' in names


def test_regression_static_dtype_fallback_for_unknown_shapes():
    """Generated layers used to declare their output with the INPUT's
    dtype whenever eval_shape could not run (unknown input shape);
    arg_max then carried a float32 declaration for an int64 result."""
    main, g = _prog()
    try:
        blk = main.global_block()
        from paddle_tpu.framework import Variable
        v = blk.create_var(name='mystery', shape=None, dtype='float32')
        out = L.argmax(v, axis=-1)
    finally:
        g.__exit__(None, None, None)
    assert out.dtype == 'int64'


def test_regression_lstm_gru_optional_initial_state():
    """lstm/gru tolerate absent h0/c0 at runtime; the registry now says
    so, and the verifier no longer flags recurrent layers built without
    an initial state."""
    from paddle_tpu.ops.registry import get_op
    assert {'h0', 'c0'} <= get_op('lstm').optional
    assert 'h0' in get_op('gru').optional
    main, g = _prog()
    try:
        x = L.data('x', [5, 12], dtype='float32')
        proj = L.fc(x, size=12, num_flatten_dims=2)
        hidden, _cell = L.dynamic_lstm(proj, size=12)
    finally:
        g.__exit__(None, None, None)
    diags = analysis.verify_program(main, fetch_names=[hidden.name])
    assert 'missing-input' not in _codes(diags)


def test_regression_dce_keeps_cond_writes_producer():
    """DCE used to drop the producer of a cond `writes` var that nothing
    else read — but _run_cond reads the OUTER value for the branch that
    leaves the var untouched, so the lowered program died at trace time
    with a bare KeyError. _op_read_names now counts control-flow
    passthrough reads (found via the verifier's dataflow model)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = L.data('x', [4], dtype='float32')
        pred = L.reduce_sum(x) > 0.0
        t = L.scale(x, scale=3.0)       # read only by the cond passthrough

        def true_fn():
            L.assign(L.scale(x, 2.0), output=t)
            return L.scale(x, 1.0)

        def false_fn():
            return L.scale(x, 0.5)

        r = L.cond(pred, true_fn, false_fn)
        final = L.reduce_sum(r)
    # DCE (default pipeline) must keep the scale producer alive
    opt, _ = ir.apply_pipeline(main, fetch_names=[final.name],
                               feed_names=['x'])
    kept = [op for op in opt.global_block().ops
            if op.type == 'scale' and op.outputs['Out'] == [t.name]]
    assert kept, 'DCE dropped the cond-writes producer again'
    exe = fluid.Executor()
    out, = exe.run(main, feed={'x': np.ones((2, 4), np.float32)},
                   fetch_list=[final])
    assert out == pytest.approx(8.0)    # true branch: sum(2x) over 8 ones


def test_register_op_rejects_unknown_optional_slot():
    from paddle_tpu.ops.registry import register_op
    with pytest.raises(ValueError, match='optional'):
        @register_op('___opt_probe___', optional=('nope',))
        def f(x):
            return x
