"""E2E distributed-observability drill (ISSUE 17 tentpole): a router and
TWO real replica processes with tracing on — a traced request is forced
through a kill -9 failover, and the merged cross-process timeline must
show the router's retry span plus BOTH replicas' spans under ONE
trace_id with every parent link resolving. Rides the same subprocess
pattern as test_router_failover.py; also drills /metrics/fleet
aggregation semantics against live scrapes, the kill -9 scrape-hardening
contract, the /healthz SLO block, and the sampled-off zero-span A/B."""
import glob
import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.request

import pytest

from paddle_tpu import observability as obs
from paddle_tpu.dygraph import guard
from paddle_tpu.models.causal_lm import greedy_generate
from paddle_tpu.observability import distributed as dobs
from paddle_tpu.observability.trace_context import (ENV_TRACE_DIR,
                                                    ENV_TRACE_SAMPLE)
from paddle_tpu.serving import Router
from paddle_tpu.serving.tier.replica import DEFAULT_SEED, build_tiny_lm
from paddle_tpu.serving.tier.router import RouterServer
from tools.trace_merge import load_span_file, merge_span_files

_REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
_MAX_NEW_CAP = 96          # long decode → wide kill window for the drill
_PAD = -(-(16 + _MAX_NEW_CAP) // 4) * 4
# ttft is only fed by REAL requests (warmup feeds decode_step but never
# emits request tokens), so the vacuous-cold-start check stays clean
_SLO_SPEC = 'ttft.p99<30,ttft.mean<0'


def _spawn_replica(rid, trace_dir):
    env = dict(os.environ, JAX_PLATFORMS='cpu',
               PADDLE_TPU_TRACE_DIR=trace_dir,
               PADDLE_TPU_TRACE_SAMPLE='1',
               PADDLE_TPU_SLO=_SLO_SPEC)
    env.pop('PADDLE_TPU_TELEMETRY', None)
    return subprocess.Popen(
        [sys.executable, '-m', 'paddle_tpu.serving.tier.replica',
         '--port', '0', '--slots', '2', '--seed', str(DEFAULT_SEED),
         '--max-new-tokens-cap', str(_MAX_NEW_CAP), '--replica-id', rid],
        cwd=_REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL, text=True)


def _wait_ready(proc):
    deadline = time.monotonic() + 180
    line = ''
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if line.strip():
            break
        if proc.poll() is not None:
            raise RuntimeError(f'replica died at startup rc={proc.returncode}')
    ready = json.loads(line)
    assert ready['ready'] and ready['pid'] == proc.pid
    return ready


def _counter(name, **labels):
    from paddle_tpu.observability import registry
    d = registry.to_dict().get(name)
    if not d:
        return 0.0
    return sum(s['value'] for s in d['samples']
               if all(s['labels'].get(k) == v for k, v in labels.items()))


def _span_file(trace_dir, pid):
    return os.path.join(trace_dir, 'spans-%d.jsonl' % pid)


def _line_count(path):
    try:
        with open(path) as f:
            return sum(1 for _ in f)
    except OSError:
        return 0


def _sum_counter_from_scrapes(scrapes, family):
    total = 0.0
    for _, text in scrapes:
        fam = dobs.parse_prometheus_text(text).get(family)
        if fam:
            total += sum(v for _, _, v in fam['samples'])
    return total


def test_traced_failover_fleet_metrics_and_scrape_hardening(
        tmp_path, monkeypatch):
    trace_dir = str(tmp_path / 'trace')
    monkeypatch.setenv(ENV_TRACE_DIR, trace_dir)
    monkeypatch.setenv(ENV_TRACE_SAMPLE, '1')
    dobs.reset_distributed()          # recorder must bind to trace_dir

    with guard():
        model = build_tiny_lm()
        short_ref = greedy_generate(model, [9, 2], 4, pad_len=_PAD)
        long_ref = greedy_generate(model, [3, 5, 7], _MAX_NEW_CAP,
                                   pad_len=_PAD)
    assert len(long_ref) == _MAX_NEW_CAP     # no early eos: wide window

    procs = [_spawn_replica('r0', trace_dir), _spawn_replica('r1', trace_dir)]
    router = http_front = None
    try:
        readies = [_wait_ready(p) for p in procs]
        urls = ['http://127.0.0.1:%d' % r['port'] for r in readies]
        by_pid = {p.pid: r['replica_id']
                  for p, r in zip(procs, readies)}
        assert all(r['trace_dir'] == trace_dir for r in readies)

        router = Router(urls, health_poll_s=0.5)
        assert all(r.healthy and r.warmed for r in router.replicas)

        # -- clock handshake: every poll estimated each replica's offset
        for rep in router.replicas:
            assert rep.replica_id in ('r0', 'r1')
            assert rep.clock_offset is not None
            assert abs(rep.clock_offset) < 5.0   # same machine
        assert abs(_counter('trace_clock_offset_seconds',
                            replica='r0')) < 5.0

        # -- /healthz SLO block: vacuously ok before any decode traffic
        for url in urls:
            with urllib.request.urlopen(url + '/healthz', timeout=10) as r:
                body = json.load(r)
            assert body['replica'] in ('r0', 'r1')
            assert body['unix_time'] == pytest.approx(time.time(), abs=30)
            assert body['slo']['ok'] is True
            assert {c['slo'] for c in body['slo']['clauses']} == set(
                _SLO_SPEC.split(','))

        # -- traced traffic: every request returns its trace_id
        fins = [router.generate_nonstream([9, 2], max_new_tokens=4,
                                          timeout=60) for _ in range(4)]
        for fin in fins:
            assert fin['tokens'] == short_ref
            assert len(fin['trace_id']) == 16
        assert len({f['trace_id'] for f in fins}) == 4

        # -- SLO breach: the serving replica's decode_step.mean<0 clause
        # must now burn; its p99<30 clause stays ok
        served_url = fins[0]['replica']
        with urllib.request.urlopen(served_url + '/healthz',
                                    timeout=10) as r:
            slo = json.load(r)['slo']
        assert slo['ok'] is False
        by_clause = {c['slo']: c for c in slo['clauses']}
        assert not by_clause['ttft.mean<0']['ok']
        assert by_clause['ttft.p99<30']['ok']

        # -- /metrics/fleet over HTTP: counters sum, gauges get labels
        http_front = RouterServer(router, port=0).start()
        scrapes = router.scrape_replica_metrics()
        assert [s[0] for s in scrapes] == ['r0', 'r1']
        fleet_url = 'http://127.0.0.1:%d/metrics/fleet' % http_front.port
        with urllib.request.urlopen(fleet_url, timeout=10) as r:
            assert r.status == 200
            fleet_text = r.read().decode()
        fleet = dobs.parse_prometheus_text(fleet_text)
        done = _sum_counter_from_scrapes(scrapes,
                                         'paddle_tpu_decode_requests_completed')
        assert done >= 4.0               # the 4 drill requests landed
        assert sum(v for _, _, v in
                   fleet['paddle_tpu_decode_requests_completed']['samples']) == done
        slots = {labels['replica']: v for _, labels, v in
                 fleet['paddle_tpu_decode_slots_total']['samples']}
        assert slots == {'r0': 2.0, 'r1': 2.0}   # gauge: labeled, not 4

        # -- the tentpole drill: traced request + kill -9 mid-generation
        before = {p.pid: _line_count(_span_file(trace_dir, p.pid))
                  for p in procs}
        result = {}

        def fire():
            result['fin'] = router.generate_nonstream(
                [3, 5, 7], max_new_tokens=_MAX_NEW_CAP, timeout=120)

        th = threading.Thread(target=fire)
        th.start()
        victim = None
        deadline = time.monotonic() + 60
        while victim is None and time.monotonic() < deadline:
            for p in procs:                  # first replica to emit a span
                if _line_count(_span_file(trace_dir, p.pid)) > before[p.pid]:
                    victim = p
                    break
            time.sleep(0.002)
        assert victim is not None, 'no replica span appeared'
        os.kill(victim.pid, signal.SIGKILL)  # the real thing
        th.join(120)

        fin = result['fin']
        assert fin['retries'] >= 1           # the failover actually fired
        assert fin['tokens'] == long_ref     # retried bitwise on survivor
        trace_id = fin['trace_id']
        survivor_id = by_pid[[p for p in procs if p is not victim][0].pid]

        # -- merge all three processes' span files into ONE timeline
        paths = sorted(glob.glob(os.path.join(trace_dir, 'spans-*.jsonl')))
        assert len(paths) == 3               # router (this process) + 2
        chrome, summary = merge_span_files(paths, trace_id=trace_id)
        assert summary['unresolved_parents'] == []   # parent links hold
        assert set(summary['offsets_s']) >= {'router', 'r0', 'r1'}

        spans = [s for p in paths for s in load_span_file(p)['spans']
                 if s['trace_id'] == trace_id]
        assert len(spans) >= 6
        by_name = {}
        for s in spans:
            by_name.setdefault(s['name'], []).append(s)
        assert {s['process'] for s in spans} == {'router', 'r0', 'r1'}
        root = by_name['router/request'][0]
        assert root['parent_span_id'] is None
        retry = by_name['router/retry'][0]
        dispatch = by_name['router/dispatch'][0]
        assert retry['parent_span_id'] == root['span_id']
        assert dispatch['parent_span_id'] == root['span_id']
        assert retry['args']['replica'] != dispatch['args']['replica']
        victim_id = by_pid[victim.pid]
        for s in spans:
            if s['process'] == victim_id:    # victim hangs off the RETRY
                assert s['parent_span_id'] == retry['span_id'], s
            elif s['process'] == survivor_id:  # survivor off the DISPATCH
                assert s['parent_span_id'] == dispatch['span_id'], s
        assert 'replica/prefill' in by_name
        assert any(s['process'] == survivor_id
                   for s in by_name['replica/token'])

        # -- scrape hardening: the kill -9'd replica costs one bounded
        # failure tick, never the fleet scrape
        f0 = _counter('router_scrape_failures', replica=victim_id)
        scrapes = router.scrape_replica_metrics(timeout_s=2.0)
        assert [s[0] for s in scrapes] == [survivor_id]
        assert _counter('router_scrape_failures', replica=victim_id) == f0 + 1
        with urllib.request.urlopen(fleet_url, timeout=15) as r:
            assert r.status == 200
            text = r.read().decode()
        assert 'decode_requests_completed' in text   # survivor's view
    finally:
        if http_front is not None:
            http_front.shutdown()
        if router is not None:
            router.close()
        for p in procs:
            if p.poll() is None:
                p.kill()
            p.wait(30)
        dobs.reset_distributed()


def test_trace_overhead_sampled_off_is_zero_span(tmp_path, monkeypatch):
    """Satellite d at smoke size: the A/B harness must show a structurally
    free disabled path — ZERO spans recorded with sampling off, spans
    flowing with it on, bitwise-identical tokens either way. (The p50
    numbers live in PERF.md §22; wall-clock ratios are not CI-stable.)"""
    monkeypatch.delenv(ENV_TRACE_DIR, raising=False)
    monkeypatch.delenv(ENV_TRACE_SAMPLE, raising=False)
    import threading as _t

    from tools.bench_router import build_shared_prompt_work
    from tools.bench_router import measure_trace_overhead
    with guard():
        model = build_tiny_lm()
        work = build_shared_prompt_work(4)
        pad = -(-(16 + 16) // 4) * 4
        refs = [greedy_generate(model, p, m, pad_len=pad)
                for p, m in work]
        res = measure_trace_overhead(model, _t.RLock(), work, refs)
    assert res['spans_off'] == 0             # disabled path does no work
    assert res['spans_on'] > 0
    assert res['bitwise_equal']
    assert res['p50_on_ms'] < 60e3           # sane, not hung
