"""Self-healing training (paddle_tpu/resilience/supervisor.py + watchdog.py,
ISSUE 8): divergence detection (non-finite + robust-z spike), the
skip/rollback/escalate policy ladder, AMP overflow-skip benignity,
quarantine records, fault-spec hygiene, and watchdog arm/deadline/breach
mechanics — all in-process (the subprocess recovery story lives in
test_self_healing.py)."""
import json
import os

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers as L
from paddle_tpu import observability, resilience
from paddle_tpu.core.fetch_handle import FetchHandle
from paddle_tpu.resilience import (TrainingDiverged, TrainingSupervisor,
                                   parse_supervisor_spec)
from paddle_tpu.resilience.fault import FaultInjector
from paddle_tpu.resilience.watchdog import Watchdog


def _metric(name):
    d = observability.registry.to_dict().get(name)
    if not d or not d['samples']:
        return 0.0
    return sum(s['value'] for s in d['samples'])


# ---------------------------------------------------------------------------
# spec hygiene (supervisor + fault injector)
# ---------------------------------------------------------------------------

def test_supervisor_spec_parses_policy_and_options():
    assert parse_supervisor_spec('') == (None, {})
    assert parse_supervisor_spec('skip') == ('skip', {})
    policy, opts = parse_supervisor_spec('rollback, window=32 , zmax=6')
    assert policy == 'rollback'
    assert opts == {'window': 32, 'zmax': 6.0}


def test_supervisor_spec_rejects_unknown_policy_and_keys():
    with pytest.raises(ValueError, match='unknown policy'):
        parse_supervisor_spec('rolback')          # typo must not pass
    with pytest.raises(ValueError, match='unknown option'):
        parse_supervisor_spec('skip,zmaxx=8')
    with pytest.raises(ValueError, match='two policies'):
        parse_supervisor_spec('skip,rollback')
    with pytest.raises(ValueError, match='bad value'):
        parse_supervisor_spec('skip,window=many')
    with pytest.raises(ValueError, match='unknown option'):
        TrainingSupervisor(policy='off', not_a_knob=1)
    with pytest.raises(ValueError, match='rollback'):
        TrainingSupervisor(policy='rollback')     # needs a manager


def test_fault_spec_rejects_typos_and_lists_supported_clauses():
    """A typo like kil@step=3 must raise, not silently make a
    fault-injection test vacuous."""
    for bad in ('kil@step=3', 'kill@steps=3', 'nan@loss=1', 'hang@sec=2',
                'garbage'):
        with pytest.raises(ValueError, match='supported'):
            FaultInjector(bad)
    inj = FaultInjector('nan@step=4,spike@step=9,hang@step=2,hang@secs=0.01')
    assert inj.active


def test_fault_loss_injections_fire_once():
    inj = FaultInjector('nan@step=4,spike@step=6')
    assert not inj.wants_loss(3)
    assert inj.wants_loss(4)
    assert np.isnan(inj.on_loss(4, 1.0))
    assert inj.on_loss(4, 1.0) == 1.0             # single-fire
    spiked = inj.on_loss(6, 2.0)
    assert spiked > 1e9
    assert inj.on_loss(6, 2.0) == 2.0


def test_fault_hang_bounded_by_secs():
    import time
    inj = FaultInjector('hang@step=2,hang@secs=0.05')
    t0 = time.monotonic()
    inj.on_step(2)
    assert 0.04 <= time.monotonic() - t0 < 5.0
    t0 = time.monotonic()
    inj.on_step(2)                                # single-fire
    assert time.monotonic() - t0 < 0.04


# ---------------------------------------------------------------------------
# executor-spine training helpers
# ---------------------------------------------------------------------------

def _build_net():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = L.data('sx', [4], dtype='float32')
        y = L.data('sy', [1], dtype='float32')
        h = L.fc(x, size=8, act='relu')
        pred = L.fc(h, size=1)
        loss = L.reduce_mean(L.square_error_cost(pred, y))
        fluid.optimizer.Adam(learning_rate=1e-2).minimize(loss)
    return main, startup, loss


def _feeds(n, seed=0):
    rng = np.random.RandomState(seed)
    return [{'sx': rng.randn(8, 4).astype(np.float32),
             'sy': rng.randn(8, 1).astype(np.float32)} for _ in range(n)]


def _scope_state(scope, program):
    return {v.name: np.asarray(scope.find(v.name))
            for v in program.list_vars() if v.persistable}


# ---------------------------------------------------------------------------
# detection + skip policy
# ---------------------------------------------------------------------------

def test_nonfinite_detection_skip_drops_the_update(tmp_path):
    """A NaN batch under policy=skip: the update is dropped bitwise (state
    returns to the last healthy boundary), a quarantine record lands, and
    training keeps going with finite losses."""
    fluid.seed(11)
    main, startup, loss = _build_net()
    scope = fluid.Scope()
    qpath = str(tmp_path / 'quarantine.jsonl')
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        sup = TrainingSupervisor(policy='skip', executor=exe, program=main,
                                 scope=scope, quarantine_path=qpath)
        feeds = _feeds(6)
        for step, feed in enumerate(feeds[:3], 1):
            lv, = exe.run(main, feed=feed, fetch_list=[loss])
            assert sup.end_of_step(step, lv,
                                   batch_desc={'i': step}).action == 'ok'
        healthy = _scope_state(scope, main)

        poisoned = dict(feeds[3], sx=feeds[3]['sx'] * np.nan)
        lv, = exe.run(main, feed=poisoned, fetch_list=[loss])
        assert not np.isfinite(lv).all()
        v = sup.end_of_step(4, lv, batch_desc={'i': 4})
        assert v.action == 'skip' and v.reason == 'nonfinite'

        # the poisoned update is GONE: state is bitwise the healthy boundary
        after = _scope_state(scope, main)
        assert set(after) == set(healthy)
        for name in healthy:
            assert np.array_equal(after[name], healthy[name]), name

        # and the loop keeps training with finite losses
        lv, = exe.run(main, feed=feeds[4], fetch_list=[loss])
        assert np.isfinite(lv).all()
        assert sup.end_of_step(5, lv).action == 'ok'

    records = [json.loads(ln) for ln in
               open(qpath).read().strip().splitlines()]
    assert len(records) == 1
    rec = records[0]
    assert rec['step'] == 4 and rec['reason'] == 'nonfinite'
    assert rec['action'] == 'skip' and rec['batch'] == {'i': 4}


def test_spike_detection_uses_robust_zscore(tmp_path):
    """An upward loss excursion past zmax is a spike; the same magnitude
    downward is progress, not divergence."""
    sup = TrainingSupervisor(policy='off', min_history=4, zmax=6.0,
                            quarantine_path=str(tmp_path / 'q.jsonl'))
    for step, x in enumerate([1.0, 1.1, 0.9, 1.05, 0.95], 1):
        assert sup.end_of_step(step, x).action == 'ok'
    down = sup.end_of_step(6, 0.001)              # collapse: fine
    assert down.action == 'ok'
    up = sup.end_of_step(7, 100.0)
    assert up.action == 'record' and up.reason == 'spike'
    assert up.zscore > 6.0
    rec = json.loads(open(tmp_path / 'q.jsonl').read().splitlines()[0])
    assert rec['reason'] == 'spike' and rec['action'] == 'record'
    # the spike was NOT folded into the rolling window: the next normal
    # loss is healthy
    assert sup.end_of_step(8, 1.0).action == 'ok'


def test_check_nan_handle_raise_is_absorbed_into_detection():
    """A FetchHandle armed with check_nan raises FloatingPointError at
    materialization; supervision converts that into a non-finite verdict
    instead of a dead loop."""
    import jax.numpy as jnp
    sup = TrainingSupervisor(policy='off')
    handle = FetchHandle(jnp.asarray(float('nan')), name='loss',
                         check_nan=True)
    v = sup.end_of_step(1, handle)
    assert v.action == 'record' and v.reason == 'nonfinite'


def test_skip_escalates_after_max_consecutive_skips():
    sup = TrainingSupervisor(policy='skip', max_skips=2)
    sup.end_of_step(1, 1.0)                       # healthy: something to
    sup._capture_state = ('scope', {}, None)      # restore (empty is fine)
    assert sup.end_of_step(2, float('nan')).action == 'skip'
    with pytest.raises(TrainingDiverged, match='consecutive'):
        sup.end_of_step(3, float('inf'))


def test_policy_escalate_raises_on_first_detection():
    sup = TrainingSupervisor(policy='escalate')
    assert sup.end_of_step(1, 0.5).action == 'ok'
    with pytest.raises(TrainingDiverged, match='nonfinite'):
        sup.end_of_step(2, float('nan'))


# ---------------------------------------------------------------------------
# rollback + escalation through a real manager
# ---------------------------------------------------------------------------

def _train_with_manager(tmp_path, poison_steps, total=12, **sup_kw):
    fluid.seed(5)
    main, startup, loss = _build_net()
    scope = fluid.Scope()
    events = []
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        mgr = resilience.CheckpointManager(
            str(tmp_path / 'ck'), every_n_steps=3, keep=2,
            install_signal_handlers=False)
        sup = TrainingSupervisor(policy='rollback', manager=mgr,
                                 executor=exe, program=main, scope=scope,
                                 **sup_kw)
        feeds = _feeds(total + 6, seed=1)
        step, i = 0, 0
        while step < total and i < len(feeds):
            feed = feeds[i]
            i += 1
            if i in poison_steps:
                feed = dict(feed, sx=feed['sx'] * np.nan)
            lv, = exe.run(main, feed=feed, fetch_list=[loss])
            step += 1
            mgr.end_of_step(step, lambda: resilience.capture_training_state(
                executor=exe, program=main, scope=scope), loss=lv)
            v = mgr.last_verdict
            if v is not None and v.action == 'rollback':
                events.append(('rollback', step, v.resume_step))
                step = v.resume_step
            else:
                events.append((step, np.asarray(lv).tobytes().hex()))
        mgr.wait()
        mgr.close()
    return events


def test_rollback_restores_last_checkpoint_and_run_is_deterministic(
        tmp_path):
    a = _train_with_manager(tmp_path / 'a', poison_steps={8})
    b = _train_with_manager(tmp_path / 'b', poison_steps={8})
    assert a == b, 'identically-faulted runs diverged'
    rollbacks = [e for e in a if e[0] == 'rollback']
    assert rollbacks == [('rollback', 8, 6)]      # ckpts at 3, 6 → resume 6
    # the run completed past the fault with new (forward) data
    assert max(e[0] for e in a if isinstance(e[0], int)) == 12
    q = (tmp_path / 'a' / 'ck' / 'quarantine.jsonl').read_text()
    assert json.loads(q.splitlines()[0])['action'] == 'rollback'


def test_rollback_budget_escalates_to_training_diverged(tmp_path):
    with pytest.raises(TrainingDiverged, match='rollbacks within'):
        _train_with_manager(tmp_path, poison_steps={5, 8, 11},
                            max_rollbacks=2, escalate_window=100)


def test_rollback_before_any_checkpoint_escalates(tmp_path):
    with pytest.raises(TrainingDiverged, match='before any checkpoint'):
        _train_with_manager(tmp_path, poison_steps={2})


def test_skip_boundary_never_checkpoints_the_poisoned_state(tmp_path):
    """A cadence-due boundary with a skip verdict must not save."""
    mgr = resilience.CheckpointManager(str(tmp_path), every_n_steps=2,
                                       keep=5, install_signal_handlers=False)
    sup = TrainingSupervisor(policy='skip', manager=mgr)
    state = {'w': np.ones((4,), np.float32)}
    mgr.end_of_step(1, lambda: (state, {}), loss=1.0)
    mgr.end_of_step(2, lambda: (state, {}), loss=1.0)   # due → saves
    mgr.wait()
    assert len(mgr.all_checkpoints()) == 1
    mgr.end_of_step(3, lambda: (state, {}), loss=1.0)
    mgr.end_of_step(4, lambda: (state, {}), loss=float('nan'))  # due + bad
    assert mgr.last_verdict.action == 'skip'
    mgr.wait()
    assert len(mgr.all_checkpoints()) == 1        # no new checkpoint
    mgr.close()


# ---------------------------------------------------------------------------
# AMP benignity
# ---------------------------------------------------------------------------

def test_amp_overflow_skip_is_benign_never_rolled_back():
    """A dygraph AMP overflow-skip step must not count as divergence even
    when the observed loss is non-finite (the optimizer already dropped
    the update by design)."""
    from paddle_tpu import dygraph
    from paddle_tpu.contrib import mixed_precision as mp
    with dygraph.guard():
        layer = dygraph.Linear(2, 1)
        opt = mp.decorate(
            fluid.optimizer.SGD(1e-3, parameter_list=layer.parameters()),
            dtype='float16', decr_every_n_nan_or_inf=1)
        sup = TrainingSupervisor(policy='escalate')
        assert sup.end_of_step(1, 0.5).action == 'ok'
        before = mp.total_overflow_skips()
        x = dygraph.to_variable(np.array([[1e30, 1e30]], 'float32'))
        loss = fluid.layers.reduce_mean(layer(x)) * 1e30
        loss.backward()
        opt.minimize(loss)                        # grads overflow → skip
        layer.clear_gradients()
        assert mp.total_overflow_skips() == before + 1
        # even policy=escalate absorbs it as benign
        v = sup.end_of_step(2, float('inf'))
        assert v.action == 'benign' and v.reason == 'amp_overflow_skip'
        # a later REAL divergence still escalates
        with pytest.raises(TrainingDiverged):
            sup.end_of_step(3, float('nan'))


def test_static_amp_exports_loss_scale_and_skip_counter():
    """Static fp16 path: the in-graph skip counter + loss scale surface
    through overflow_steps()/get_loss_scaling() and the registry export."""
    from paddle_tpu.contrib import mixed_precision as mp
    fluid.seed(3)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = L.data('ax', [4], dtype='float32')
        y = L.data('ay', [1], dtype='float32')
        pred = L.fc(x, size=1)
        loss = L.reduce_mean(L.square_error_cost(pred, y))
        opt = mp.decorate(fluid.optimizer.SGD(learning_rate=1e-3),
                          dtype='float16', init_loss_scaling=2.**15,
                          decr_every_n_nan_or_inf=1)
        opt.minimize(loss)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        assert opt.overflow_steps(scope) == 0
        big = {'ax': np.full((4, 4), 1e4, np.float32),
               'ay': np.zeros((4, 1), np.float32)}
        exe.run(main, feed=big, fetch_list=[loss])
        assert opt.overflow_steps(scope) == 1     # overflow → skipped
        assert opt.get_loss_scaling(scope) < 2.**15   # scale decayed
        export = observability.registry.to_dict()
        assert export['amp_loss_scale']['samples'][0]['value'] == \
            pytest.approx(opt.get_loss_scaling(scope))
        assert _metric('amp_overflow_skipped_steps') >= 1


# ---------------------------------------------------------------------------
# TrainStep spine
# ---------------------------------------------------------------------------

def test_train_step_supervisor_skip_restores_params():
    from paddle_tpu import dygraph
    from paddle_tpu.dygraph.jit import TrainStep
    from paddle_tpu.dygraph.tape import dispatch_op

    def loss_fn(model, x, y):
        d = dispatch_op('elementwise_sub', {'x': model(x), 'y': y}, {})
        sq = dispatch_op('elementwise_mul', {'x': d, 'y': d}, {})
        return dispatch_op('reduce_mean', {'x': sq}, {})

    with dygraph.guard():
        layer = dygraph.Linear(4, 1)
        opt = fluid.optimizer.SGD(0.1, parameter_list=layer.parameters())
        sup = TrainingSupervisor(policy='skip')
        step = TrainStep(layer, loss_fn, opt, supervisor=sup)
        x = np.random.RandomState(0).randn(8, 4).astype(np.float32)
        y = np.zeros((8, 1), np.float32)
        step(x, y)                                # healthy → captured
        assert sup.last_verdict.action == 'ok'
        healthy = {n: np.asarray(p.value)
                   for n, p in layer.named_parameters()}
        step(x * np.nan, y)                       # poisoned update
        assert sup.last_verdict.action == 'skip'
        for n, p in layer.named_parameters():
            assert np.array_equal(np.asarray(p.value), healthy[n]), n
        # training continues
        step(x, y)
        assert sup.last_verdict.action == 'ok'


# ---------------------------------------------------------------------------
# watchdog
# ---------------------------------------------------------------------------

def test_watchdog_deadline_tracks_rolling_median():
    wd = Watchdog(floor_s=1.0, factor=10.0, cold_s=300.0, abort=False,
                  poll_s=0.05, dump_dir='/tmp')
    try:
        assert wd.deadline_for('step') == 300.0   # cold: sized for compile
        for _ in range(5):
            wd.observe('step', 0.5)
        assert wd.deadline_for('step') == pytest.approx(5.0)
        for _ in range(10):
            wd.observe('step', 0.01)
        assert wd.deadline_for('step') == 1.0     # floor wins
    finally:
        wd.stop()


def test_watchdog_breach_dumps_stacks_and_counts(tmp_path):
    with observability.telemetry_guard(True):
        wd = Watchdog(floor_s=0.15, cold_s=0.15, abort=False, poll_s=0.03,
                      dump_dir=str(tmp_path))
        try:
            lease = wd.arm('wedged_step')
            import time
            time.sleep(0.5)
            assert lease.breached
            assert len(wd.breaches) == 1
            rec = wd.breaches[0]
            assert rec['name'] == 'wedged_step' and not rec['aborting']
            dump = rec['stack_dump']
            assert os.path.exists(dump)
            text = open(dump).read()
            assert 'Thread' in text or 'File' in text   # real stacks
            assert (tmp_path / 'watchdog_breach.json').exists()
            assert _metric('watchdog_breaches') == 1
            assert _metric('watchdog_stack_dumps') == 1
            # a breached lease fires once, not per poll
            time.sleep(0.1)
            assert len(wd.breaches) == 1
        finally:
            wd.stop()


def test_watchdog_disarm_prevents_breach_and_feeds_history(tmp_path):
    wd = Watchdog(floor_s=0.2, cold_s=0.2, abort=False, poll_s=0.03,
                  dump_dir=str(tmp_path))
    try:
        import time
        for _ in range(3):
            lease = wd.arm('fine_step')
            time.sleep(0.02)
            wd.disarm(lease)
        time.sleep(0.3)                           # idle: no lease armed
        assert not wd.breaches
        assert 0.2 <= wd.deadline_for('fine_step') <= 1.0
    finally:
        wd.stop()


def test_supervisor_holds_train_loop_lease(tmp_path):
    wd = Watchdog(floor_s=5.0, cold_s=5.0, abort=False, poll_s=0.05,
                  dump_dir=str(tmp_path))
    try:
        sup = TrainingSupervisor(policy='off', watchdog=wd)
        sup.end_of_step(1, 1.0)
        assert 'train_loop' in wd._leases
        sup.end_of_step(2, 1.0)
        assert wd._history['train_loop']          # boundary dt observed
        sup.close()
        assert 'train_loop' not in wd._leases
    finally:
        wd.stop()


# ---------------------------------------------------------------------------
# telemetry
# ---------------------------------------------------------------------------

def test_supervisor_metrics_flow_through_registry(tmp_path):
    with observability.telemetry_guard(True):
        sup = TrainingSupervisor(policy='skip',
                                 quarantine_path=str(tmp_path / 'q.jsonl'))
        sup.end_of_step(1, 1.0)
        sup._capture_state = ('scope', {}, None)
        sup.end_of_step(2, float('nan'))
        assert _metric('supervisor_detections') == 1
        assert _metric('supervisor_skipped_updates') == 1
        assert _metric('supervisor_quarantined_batches') == 1
