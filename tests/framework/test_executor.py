"""Executor/Program semantics (SURVEY §4: executor feed/fetch, startup init,
scope isolation, compile-cache behavior)."""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers


def test_feed_fetch_roundtrip():
    x = layers.data('x', [4], dtype='float32')
    y = layers.scale(x, scale=2.0)
    exe = fluid.Executor()
    xv = np.arange(8, dtype=np.float32).reshape(2, 4)
    out, = exe.run(feed={'x': xv}, fetch_list=[y])
    np.testing.assert_allclose(out, xv * 2.0, rtol=1e-6)


def test_startup_initializes_params():
    x = layers.data('x', [3])
    y = layers.fc(x, size=5)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    w = [p for p in fluid.default_main_program().all_parameters()]
    assert len(w) == 2  # weight + bias
    for p in w:
        assert fluid.global_scope().find(p.name) is not None


def test_train_loop_reduces_loss():
    np.random.seed(0)
    x = layers.data('x', [10])
    label = layers.data('y', [1])
    pred = layers.fc(x, size=1)
    loss = layers.reduce_mean(layers.square_error_cost(pred, label))
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    w_true = np.random.randn(10, 1).astype(np.float32)
    losses = []
    for i in range(50):
        xv = np.random.randn(32, 10).astype(np.float32)
        yv = xv @ w_true
        l, = exe.run(feed={'x': xv, 'y': yv}, fetch_list=[loss])
        losses.append(float(l))
    assert losses[-1] < losses[0] * 0.1


def test_compile_cache_reuse():
    x = layers.data('x', [4])
    y = layers.scale(x, scale=3.0)
    exe = fluid.Executor()
    xv = np.ones((2, 4), np.float32)
    exe.run(feed={'x': xv}, fetch_list=[y])
    assert len(exe._cache) == 1
    exe.run(feed={'x': xv}, fetch_list=[y])
    assert len(exe._cache) == 1  # same shapes → cache hit
    exe.run(feed={'x': np.ones((5, 4), np.float32)}, fetch_list=[y])
    assert len(exe._cache) == 2  # new batch size → new entry


def test_program_guard_isolation():
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data('x', [2])
        y = layers.scale(x, scale=1.0)
    assert len(main.global_block().ops) == 1
    assert len(fluid.default_main_program().global_block().ops) == 0


def test_clone_for_test_drops_backward():
    x = layers.data('x', [4])
    pred = layers.fc(x, size=2)
    loss = layers.reduce_mean(pred)
    fluid.optimizer.SGD(0.1).minimize(loss)
    prog = fluid.default_main_program()
    test_prog = prog.clone(for_test=True)
    types = [op.type for op in test_prog.global_block().ops]
    assert '__backward__' not in types
    assert 'sgd' not in types


def test_batch_norm_updates_running_stats():
    x = layers.data('x', [4, 8, 8])
    y = layers.batch_norm(x)
    loss = layers.reduce_mean(y)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    mean_name = [v.name for v in fluid.default_main_program().list_vars()
                 if '.mean' in v.name][0]
    before = np.asarray(fluid.global_scope().find(mean_name)).copy()
    xv = 5.0 + np.random.randn(16, 4, 8, 8).astype(np.float32)
    exe.run(feed={'x': xv}, fetch_list=[loss])
    after = np.asarray(fluid.global_scope().find(mean_name))
    assert not np.allclose(before, after)
    assert np.all(after > 0.1)  # moved toward batch mean ≈ 5


def test_framework_misc_api_parity():
    """name_scope / device_guard / require_version / cuda_pinned_places /
    load_op_library (ref fluid.framework misc surface)."""
    import warnings
    import paddle_tpu as fluid
    from paddle_tpu import layers
    with fluid.name_scope('stage1'):
        assert fluid.framework._current_name_scope() == 'stage1'
        with fluid.name_scope('block'):
            assert fluid.framework._current_name_scope() == 'stage1/block'
    assert fluid.framework._current_name_scope() == ''

    x = layers.data('dgx', [4])
    with fluid.device_guard('gpu:1'):
        y = layers.scale(x, scale=2.0)
    op = fluid.default_main_program().global_block().ops[-1]
    assert op.attrs.get('op_device') == 'gpu:1'
    assert y.shape is not None        # shape inference survives the attr
    layers.fc(y, size=3)              # downstream layers can size weights
    # annotated ops still execute (the attr must not leak into the kernel)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())   # init the fc params above
    out, = exe.run(feed={'dgx': np.ones((2, 4), np.float32)},
                   fetch_list=[y])
    np.testing.assert_allclose(out, 2.0 * np.ones((2, 4)), rtol=1e-6)

    fluid.require_version('1.0.0')
    fluid.require_version('1.0', '1.7')     # prefix max admits 1.7.x
    with pytest.raises(Exception):
        fluid.require_version('99.0')
    assert fluid.cuda_pinned_places(0) == []

    assert len(fluid.cuda_pinned_places(3)) == 3
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter('always')
        fluid.load_op_library('/tmp/libfoo.so')
        assert any('TPU' in str(x.message) for x in w)
