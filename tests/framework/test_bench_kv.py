"""tier-1 guard for the KV-quantization bench section: the
``decode_kv_quant`` A/B from tools/bench_decode.py must run on CPU and
hold the quality contract — f32 storage bitwise, int8 greedy match-rate
≥ 0.99 — plus the geometry acceptance: int8 pools ≥ 3.5× smaller in HBM
than f32 at head_dim 32 (measured pool bytes, not arithmetic), more
budget-solved slots per chip, and a host tier that extends the effective
cache beyond HBM. Run standalone here (the full bench_decode smoke is
tests/framework/test_bench_decode.py's job) so a kv-quant regression
points at this file."""
import json
import os
import subprocess
import sys

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), '..', '..'))

_RUNNER = (
    "import json, sys; sys.path.insert(0, %r); "
    "from bench_decode import measure_kv_quant; "
    "print(json.dumps(measure_kv_quant(smoke=True)))"
    % os.path.join(REPO, 'tools'))


def test_bench_kv_quant_smoke_runs_on_cpu():
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    r = subprocess.run([sys.executable, '-c', _RUNNER], cwd=REPO, env=env,
                       capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stderr[-3000:]
    kv = json.loads(r.stdout.strip().splitlines()[-1])
    assert kv['bench'] == 'decode_kv_quant'
    assert set(kv['per_dtype']) == {'f32', 'bf16', 'int8'}
    for d in kv['per_dtype'].values():
        assert d['tokens_per_s'] > 0
        assert d['kv_bytes_in_hbm'] > 0
        assert 0.0 <= d['match_rate_vs_f32'] <= 1.0

    # quality contract (docs/SERVING.md): f32 is the pre-quantization path
    # bit for bit; int8 may drift but must track the greedy trajectory
    assert kv['per_dtype']['f32']['bitwise_equal'] is True
    assert kv['per_dtype']['f32']['match_rate_vs_f32'] == 1.0
    assert kv['per_dtype']['int8']['match_rate_vs_f32'] >= 0.99

    # geometry acceptance at head_dim 32: f32 rows 128 B, int8 rows 36 B
    assert kv['head_dim'] == 32
    assert kv['hbm_bytes_f32_over_int8'] >= 3.5, kv
    assert kv['per_dtype']['bf16']['kv_bytes_in_hbm'] * 2 == \
        kv['per_dtype']['f32']['kv_bytes_in_hbm']

    # what the bytes buy: more solved slots per chip at the same budget,
    # and the host tier extends every dtype's effective cache
    assert kv['slots_per_chip']['int8'] > kv['slots_per_chip']['bf16'] \
        > kv['slots_per_chip']['f32'] > 0
    for d, eff in kv['effective_cache_blocks'].items():
        assert eff['with_host_tier'] > eff['hbm_only'], (d, eff)
