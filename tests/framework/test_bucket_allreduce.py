"""bucket_allreduce IR pass (ir/bucket_allreduce.py): fleet's per-grad
c_allreduce_sum insertion, size-capped bucket formation, the live
fuse_all_reduce_ops knobs (BuildStrategy AND DistributedStrategy), strict
env parsing, and — the acceptance — BITWISE pass-on/off parity on the
MNIST-MLP and ResNet-block recipes at comm_dtype=f32.
"""
import os

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import ir, layers
from paddle_tpu import observability as obs
from paddle_tpu.compiler import BuildStrategy, CompiledProgram
from paddle_tpu.ir.bucket_allreduce import ENV_BUCKET_MB, bucket_cap_bytes
from paddle_tpu.parallel import DistributedStrategy, fleet


def _fleet_mlp(depth=3, width=32, w_names=None):
    """MNIST-style MLP recipe built through fleet.distributed_optimizer so
    the per-grad c_allreduce_sum sync points exist."""
    fleet.init()
    main, start = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, start):
        x = layers.data('x', shape=[width], dtype='float32')
        y = layers.data('y', shape=[1], dtype='int64')
        h = x
        for _ in range(depth):
            h = layers.fc(h, size=width, act='relu')
        logits = layers.fc(h, size=10)
        loss = layers.reduce_mean(
            layers.softmax_with_cross_entropy(logits, y))
        fleet.distributed_optimizer(
            fluid.optimizer.SGD(0.1),
            strategy=DistributedStrategy()).minimize(loss)
    return main, start, loss


def _fleet_resnet_block():
    """ResNet bottleneck recipe (conv+BN+momentum) through fleet."""
    fleet.init()
    ch, hw = 8, 6
    main, start = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, start):
        x = layers.data('x', shape=[ch, hw, hw], dtype='float32')
        y = layers.data('y', shape=[1], dtype='float32')

        def conv_bn(inp, ch_out, k, act=None):
            c = layers.conv2d(inp, ch_out, k, padding=(k - 1) // 2,
                              bias_attr=False)
            return layers.batch_norm(c, act=act)

        h = conv_bn(x, ch // 2, 1, act='relu')
        h = conv_bn(h, ch // 2, 3, act='relu')
        h = conv_bn(h, ch, 1)
        h = layers.relu(layers.elementwise_add(h, x))
        pool = layers.reduce_mean(h, dim=[2, 3])
        pred = layers.fc(pool, size=1)
        loss = layers.reduce_mean(layers.square_error_cost(pred, y))
        fleet.distributed_optimizer(
            fluid.optimizer.Momentum(1e-2, momentum=0.9),
            strategy=DistributedStrategy()).minimize(loss)
    return main, start, loss


def _ar_ops(program, op_type='c_allreduce_sum'):
    return [o for o in program.global_block().ops if o.type == op_type]


# ---------------------------------------------------------------------------
# insertion
# ---------------------------------------------------------------------------

def test_fleet_minimize_inserts_grad_allreduce():
    main, _, _ = _fleet_mlp(depth=2)
    ops = _ar_ops(main)
    # one sync point per gradient, right after the backward marker
    from paddle_tpu.framework import BACKWARD_OP_TYPE
    blk_ops = main.global_block().ops
    bwd = next(i for i, o in enumerate(blk_ops)
               if o.type == BACKWARD_OP_TYPE)
    grads = blk_ops[bwd].outputs['Grads']
    assert len(ops) == len(grads) == 6          # 3 fc layers x (w, b)
    assert [o.inputs['x'][0] for o in blk_ops[bwd + 1:bwd + 1 + len(grads)]
            ] == list(grads)
    assert all(o.attrs['comm_dtype'] == 'f32' for o in ops)
    assert main._dist_fuse_all_reduce_ops is True


def test_fleet_k_step_schedules_skip_insertion():
    """Gradient-merge / local-SGD sync once per k steps — no per-step
    per-grad sync points are inserted for them."""
    fleet.init()
    for knob in ('gradient_merge_steps', 'local'):
        strat = DistributedStrategy()
        if knob == 'gradient_merge_steps':
            strat.gradient_merge_steps = 2
        else:
            strat.use_local_sgd = True
            strat.local_sgd_steps = 3
        main, start = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, start):
            x = layers.data('x', shape=[4], dtype='float32')
            y = layers.data('y', shape=[1], dtype='float32')
            loss = layers.mean(layers.square_error_cost(
                layers.fc(x, 1), y))
            fleet.distributed_optimizer(
                fluid.optimizer.SGD(0.1), strategy=strat).minimize(loss)
        assert not _ar_ops(main), knob


def test_comm_dtype_stamped_from_strategy():
    fleet.init()
    strat = DistributedStrategy()
    strat.comm_dtype = 'int8'
    main, start = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, start):
        x = layers.data('x', shape=[4], dtype='float32')
        y = layers.data('y', shape=[1], dtype='float32')
        loss = layers.mean(layers.square_error_cost(layers.fc(x, 1), y))
        fleet.distributed_optimizer(
            fluid.optimizer.SGD(0.1), strategy=strat).minimize(loss)
    assert all(o.attrs['comm_dtype'] == 'int8' for o in _ar_ops(main))


# ---------------------------------------------------------------------------
# bucket formation
# ---------------------------------------------------------------------------

def test_bucket_count_matches_cap(monkeypatch):
    """Cap arithmetic: width*width f32 weight grads + width bias grads,
    cap = 2 weight grads -> ceil-ish grouping by cumulative bytes."""
    width = 32
    main, _, loss = _fleet_mlp(depth=4, width=width)
    assert len(_ar_ops(main)) == 10
    # cap: two full fc layers (w+b each) per bucket
    cap_mb = 2 * (width * width + width) * 4 / 2 ** 20
    monkeypatch.setenv(ENV_BUCKET_MB, str(cap_mb))
    bs = BuildStrategy()
    bs.fuse_all_reduce_ops = True
    opt, ctx = ir.apply_pipeline(main, fetch_names=[loss.name],
                                 build_strategy=bs)
    stats = ctx.stats['bucket_allreduce']
    assert stats['bucketed_ops'] == 10
    # 10 grads at ~2-layers-per-bucket: logits layer differs in size but
    # the grouping is deterministic — just pin the observed invariants
    buckets = _ar_ops(opt, 'c_allreduce_sum_bucket')
    assert stats['buckets'] == len(buckets) >= 3
    assert not _ar_ops(opt)                     # no per-grad ops left
    fused_inputs = [n for b in buckets for n in b.inputs['xs']]
    assert len(fused_inputs) == 10              # every grad exactly once
    per_bucket_bytes = []
    blk = opt.global_block()
    for b in buckets:
        per_bucket_bytes.append(sum(
            int(np.prod(blk.var(n).shape)) * 4 for n in b.inputs['xs']))
    assert all(nb <= bucket_cap_bytes() or len(b.inputs['xs']) == 1
               for nb, b in zip(per_bucket_bytes, buckets))


def test_pass_idempotent_and_gated(monkeypatch):
    main, _, loss = _fleet_mlp(depth=3)
    bs = BuildStrategy()
    bs.fuse_all_reduce_ops = True
    opt, _ = ir.apply_pipeline(main, fetch_names=[loss.name],
                               build_strategy=bs)
    n1 = len(opt.global_block().ops)
    # re-running the pipeline on the rewritten program changes nothing
    opt2, ctx2 = ir.apply_pipeline(opt, fetch_names=[loss.name],
                                   build_strategy=bs)
    assert len(opt2.global_block().ops) == n1
    assert 'bucket_allreduce' not in ctx2.stats
    # knob off -> untouched
    bs_off = BuildStrategy()
    bs_off.fuse_all_reduce_ops = False
    opt3, ctx3 = ir.apply_pipeline(main, fetch_names=[loss.name],
                                   build_strategy=bs_off)
    assert not _ar_ops(opt3, 'c_allreduce_sum_bucket')
    assert len(_ar_ops(opt3)) == len(_ar_ops(main))


def test_distributed_strategy_knob_reaches_pass_without_build_strategy():
    """Programs run WITHOUT a CompiledProgram still bucket via the fleet
    stamp; DistributedStrategy.fuse_all_reduce_ops=False disables it."""
    main, _, loss = _fleet_mlp(depth=3)
    opt, ctx = ir.apply_pipeline(main, fetch_names=[loss.name])
    assert _ar_ops(opt, 'c_allreduce_sum_bucket')        # stamp honored

    fleet.init()
    strat = DistributedStrategy()
    strat.fuse_all_reduce_ops = False
    main2, start2 = fluid.Program(), fluid.Program()
    with fluid.program_guard(main2, start2):
        x = layers.data('x', shape=[8], dtype='float32')
        y = layers.data('y', shape=[1], dtype='float32')
        h = layers.fc(x, 8, act='relu')
        loss2 = layers.mean(layers.square_error_cost(layers.fc(h, 1), y))
        fleet.distributed_optimizer(
            fluid.optimizer.SGD(0.1), strategy=strat).minimize(loss2)
    assert main2._dist_fuse_all_reduce_ops is False
    opt2, _ = ir.apply_pipeline(main2, fetch_names=[loss2.name])
    assert not _ar_ops(opt2, 'c_allreduce_sum_bucket')
    assert _ar_ops(opt2)                        # sync points still there


def test_bucket_cap_env_strict(monkeypatch):
    monkeypatch.setenv(ENV_BUCKET_MB, 'lots')
    with pytest.raises(ValueError, match=ENV_BUCKET_MB):
        bucket_cap_bytes()
    monkeypatch.setenv(ENV_BUCKET_MB, '-1')
    with pytest.raises(ValueError, match=ENV_BUCKET_MB):
        bucket_cap_bytes()
    monkeypatch.setenv(ENV_BUCKET_MB, '0.5')
    assert bucket_cap_bytes() == 2 ** 19


def test_bucket_metrics(monkeypatch):
    main, _, loss = _fleet_mlp(depth=3)
    monkeypatch.setenv(ENV_BUCKET_MB, '0.005')
    with obs.telemetry_guard(True):
        obs.reset()
        bs = BuildStrategy()
        bs.fuse_all_reduce_ops = True
        ir.apply_pipeline(main, fetch_names=[loss.name], build_strategy=bs)
        m = obs.registry.to_dict()
        assert sum(s['value']
                   for s in m['collective_allreduce_buckets']['samples']) \
            >= 2


# ---------------------------------------------------------------------------
# THE acceptance: bitwise pass-on/off parity at comm_dtype=f32
# ---------------------------------------------------------------------------

def _run_recipe(main, start, loss, feed, fuse_on, steps=5):
    from paddle_tpu.core.random import seed as set_seed
    bs = BuildStrategy()
    bs.fuse_all_reduce_ops = fuse_on
    exe = fluid.Executor()
    out = []
    with fluid.scope_guard(fluid.Scope()):
        set_seed(0)
        exe.run(start)
        cp = CompiledProgram(main, build_strategy=bs)
        for _ in range(steps):
            out.append(np.asarray(
                exe.run(cp, feed=feed, fetch_list=[loss])[0]))
    return out


@pytest.mark.parametrize('recipe', ['mnist_mlp', 'resnet_block'])
def test_bitwise_parity_pass_on_off(recipe, monkeypatch):
    if recipe == 'mnist_mlp':
        main, start, loss = _fleet_mlp(depth=3, width=32)
        rng = np.random.RandomState(0)
        feed = {'x': rng.randn(16, 32).astype('float32'),
                'y': rng.randint(0, 10, (16, 1)).astype('int64')}
    else:
        main, start, loss = _fleet_resnet_block()
        rng = np.random.RandomState(0)
        feed = {'x': rng.randn(4, 8, 6, 6).astype('float32'),
                'y': rng.randn(4, 1).astype('float32')}
    # small cap => several buckets, so parity covers multi-bucket rewrites
    monkeypatch.setenv(ENV_BUCKET_MB, '0.005')
    off = _run_recipe(main, start, loss, feed, fuse_on=False)
    on = _run_recipe(main, start, loss, feed, fuse_on=True)
    for i, (a, b) in enumerate(zip(off, on)):
        assert np.array_equal(a, b), \
            f'{recipe}: step {i} loss differs pass-on vs pass-off'
