"""tier-1 guard for the serving-tier bench: tools/bench_router.py --smoke
must run end-to-end on CPU and hold the tier's hard guarantees — every
routed / cached / disaggregated generation bitwise-equal to the uncached
reference, prefix-cache hit rate AND prefill-compute-saved > 0 on the
shared-system-prompt workload (the acceptance metric pair), and the
failover drill completing every request with zero drops. Latency ratios
(p99 vs replica count, cache speedup) are reported but not asserted so a
loaded CI box cannot flake them; full-size numbers live in PERF.md §19."""
import json
import os
import subprocess
import sys

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), '..', '..'))


def test_bench_router_smoke_runs_on_cpu():
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    r = subprocess.run(
        [sys.executable, os.path.join('tools', 'bench_router.py'),
         '--smoke'],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stderr[-3000:]
    lines = [json.loads(ln) for ln in r.stdout.splitlines() if ln.strip()]
    benches = {d['bench']: d for d in lines if 'bench' in d}
    assert {'serving_tier_scaling', 'serving_tier_prefix_cache',
            'serving_tier_disagg', 'serving_tier_failover'} <= set(benches)

    scaling = benches['serving_tier_scaling']
    for key in ('one_replica', 'two_replicas'):
        sec = scaling[key]
        assert sec['completed'] == scaling['requests']
        assert sec['bitwise_equal'] is True, scaling
        assert sec['p99_ms'] > 0

    cache = benches['serving_tier_prefix_cache']
    assert cache['cache_off']['bitwise_equal'] is True
    assert cache['cache_on']['bitwise_equal'] is True
    # the acceptance pair: hit rate and prefill-compute-saved demonstrated
    # > 0 on a shared-system-prompt workload, via the always-on metrics
    assert cache['cache_on']['hit_rate'] > 0, cache
    assert cache['cache_on']['prefill_tokens_saved'] > 0, cache
    assert cache['cache_off']['hit_rate'] == 0

    disagg = benches['serving_tier_disagg']
    assert disagg['bitwise_equal'] is True
    assert disagg['handoffs'] == disagg['requests']
    assert disagg['kv_bytes'] > 0

    failover = benches['serving_tier_failover']
    assert failover['dropped'] == 0, failover
    assert failover['completed'] == failover['requests']
    assert failover['bitwise_equal'] is True
