"""slim distillation / pruning / NAS / Compressor pipeline tests
(ref parity: contrib/slim/{distillation,prune,nas,core} — VERDICT r4 §3)."""
import numpy as np
import pytest

import paddle_tpu as fluid
import paddle_tpu.layers as L
from paddle_tpu.contrib import slim

RNG = np.random.RandomState(7)
B, IN, H, C = 8, 6, 10, 3


def _reader(n=4, seed=0):
    rng = np.random.RandomState(seed)

    def r():
        for _ in range(n):
            x = rng.randn(B, IN).astype('float32')
            y = (np.abs(x[:, :C]).argmax(1)[:, None]).astype('int64')
            yield {'img': x, 'label': y}
    return r


def _build_student(prefix='s'):
    """fc→fc classifier; returns (program, startup, feat_name, logit_name,
    loss_name)."""
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.data('img', [B, IN], 'float32')
        y = fluid.data('label', [B, 1], 'int64')
        feat = L.fc(x, size=H, act='relu',
                    param_attr=fluid.ParamAttr(name=prefix + '_w1'))
        logits = L.fc(feat, size=C,
                      param_attr=fluid.ParamAttr(name=prefix + '_w2'))
        loss = L.reduce_mean(
            L.softmax_with_cross_entropy(logits, y))
    return prog, startup, feat.name, logits.name, loss.name


def _build_teacher():
    """Wider net with DISTINCT param names (merge shares same-named vars)."""
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.data('img', [B, IN], 'float32')
        feat = L.fc(x, size=H, act='relu',
                    param_attr=fluid.ParamAttr(name='t_w1'),
                    name='t_feat')
        logits = L.fc(feat, size=C,
                      param_attr=fluid.ParamAttr(name='t_w2'),
                      name='t_logits')
    return prog, startup, feat.name, logits.name


def test_distillation_strategy_trains_student():
    s_prog, s_start, s_feat, s_logits, s_loss = _build_student()
    t_prog, t_start, t_feat, t_logits = _build_teacher()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(s_start)
    exe.run(t_start)

    train_g = slim.GraphWrapper(s_prog, in_nodes={'img': 0, 'label': 1},
                                out_nodes={'loss': s_loss})
    teacher_g = slim.GraphWrapper(t_prog)
    strategy = slim.DistillationStrategy(
        distillers=[
            slim.L2Distiller(s_feat, t_feat, distillation_loss_weight=0.5),
            slim.SoftLabelDistiller(s_logits, t_logits,
                                    student_temperature=1.0,
                                    teacher_temperature=2.0,
                                    distillation_loss_weight=0.5),
        ], start_epoch=0, end_epoch=2)
    comp = slim.Compressor(
        place=fluid.CPUPlace(), scope=fluid.global_scope(),
        train_program=train_g, train_reader=_reader(6),
        teacher_programs=[teacher_g],
        distiller_optimizer=fluid.optimizer.Adam(5e-3), epoch=2)
    comp.add_strategy(strategy)

    w_before = np.asarray(fluid.global_scope().find('s_w1')).copy()
    t_before = np.asarray(fluid.global_scope().find('t_w1')).copy()
    comp.run()
    w_after = np.asarray(fluid.global_scope().find('s_w1'))
    t_after = np.asarray(fluid.global_scope().find('t_w1'))
    assert not np.allclose(w_before, w_after), "student params did not train"
    np.testing.assert_array_equal(t_before, t_after)  # teacher frozen


def test_fsp_distiller_adds_loss_node():
    s_prog, s_start, s_feat, s_logits, s_loss = _build_student('sf')
    t_prog, t_start, t_feat, t_logits = _build_teacher()
    g = slim.GraphWrapper(s_prog, out_nodes={'loss': s_loss})
    g.merge(slim.GraphWrapper(t_prog))
    d = slim.FSPDistiller([(s_feat, s_logits)], [(t_feat, t_logits)])
    g = d.distiller_loss(g)
    assert 'fsp_distillation_loss' in g.out_nodes
    assert g.out_nodes['loss'] != s_loss  # rebound to combined loss


def test_structure_pruner_idx_and_tensor():
    p = slim.StructurePruner({'*': 0}, {'*': 'l1_norm'})
    w = np.array([[3., 3.], [0.1, 0.1], [2., 2.], [0.2, 0.2]], np.float32)
    idx = p.cal_pruned_idx('w', w, 0.5)
    assert sorted(idx.tolist()) == [1, 3]  # two weakest rows
    lazy = p.prune_tensor(w, idx, 0, lazy=True)
    assert lazy.shape == w.shape
    assert np.all(lazy[1] == 0) and np.all(lazy[3] == 0)
    hard = p.prune_tensor(w, idx, 0, lazy=False)
    assert hard.shape == (2, 2)
    np.testing.assert_array_equal(hard, w[[0, 2]])


def test_uniform_prune_strategy_keeps_masks_through_training():
    prog, startup, feat, logits, loss = _build_student('p')
    with fluid.program_guard(prog):
        pass
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    train_g = slim.GraphWrapper(prog, out_nodes={'loss': loss})
    strategy = slim.UniformPruneStrategy(
        pruner=slim.StructurePruner({'*': 1}, {'*': 'l1_norm'}),
        start_epoch=0, end_epoch=2, target_ratio=0.5, params=['p_w1'])
    comp = slim.Compressor(
        place=fluid.CPUPlace(), scope=fluid.global_scope(),
        train_program=train_g, train_reader=_reader(5),
        train_optimizer=fluid.optimizer.SGD(0.05), epoch=2)
    comp.add_strategy(strategy)
    comp.run()
    w = np.asarray(fluid.global_scope().find('p_w1'))
    col_zero = np.all(w == 0, axis=0)
    assert col_zero.sum() == H // 2, \
        f"expected {H // 2} pruned columns, got {col_zero.sum()}"
    # and training actually happened on the surviving columns
    assert np.abs(w[:, ~col_zero]).sum() > 0


def test_compressor_two_strategy_yaml_config(tmp_path):
    cfg = """
version: 1.0
strategies:
  quant:
    class: QuantizationStrategy
    start_epoch: 0
    end_epoch: 2
    weight_bits: 8
    activation_bits: 8
  prune:
    class: UniformPruneStrategy
    start_epoch: 0
    end_epoch: 2
    target_ratio: 0.5
    pruning_axis: 1
    params: [c_w1]
compressor:
  epoch: 2
  strategies: [quant, prune]
"""
    f = tmp_path / 'compress.yaml'
    f.write_text(cfg)
    prog, startup, feat, logits, loss = _build_student('c')
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    train_g = slim.GraphWrapper(prog, out_nodes={'loss': loss})
    comp = slim.Compressor(
        place=fluid.CPUPlace(), scope=fluid.global_scope(),
        train_program=train_g, train_reader=_reader(4),
        train_optimizer=fluid.optimizer.SGD(0.05))
    comp.config(str(f))
    assert len(comp.strategies) == 2
    assert comp.epoch == 2
    comp.run()
    # prune strategy held: half the columns of c_w1 are zero
    w = np.asarray(fluid.global_scope().find('c_w1'))
    assert np.all(w == 0, axis=0).sum() == H // 2
    # quant strategy rewrote the train program with fake-quant ops
    graph = comp.context.optimize_graph or comp.context.train_graph
    assert any('fake_quant' in op.type for op in graph.ops())


def test_compressor_checkpoint_resume_keeps_prune_and_quant(tmp_path):
    """Kill the run after epoch 0, resume from the checkpoint: prune masks
    must re-apply and the quant rewrite must be re-inserted (strategy
    restore_from_checkpoint paths)."""
    def make(prefix):
        prog, startup, feat, logits, loss = _build_student(prefix)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        g = slim.GraphWrapper(prog, out_nodes={'loss': loss})
        comp = slim.Compressor(
            place=fluid.CPUPlace(), scope=fluid.global_scope(),
            train_program=g, train_reader=_reader(3),
            train_optimizer=fluid.optimizer.SGD(0.05),
            checkpoint_path=str(tmp_path / 'ckpt'), epoch=1)
        comp.add_strategy(slim.QuantizationStrategy(start_epoch=0,
                                                    end_epoch=3))
        comp.add_strategy(slim.UniformPruneStrategy(
            pruner=slim.StructurePruner({'*': 1}, {'*': 'l1_norm'}),
            start_epoch=0, end_epoch=3, target_ratio=0.5,
            params=[prefix + '_w1']))
        return comp

    comp = make('r')
    comp.epoch = 1          # first run: one epoch, then "dies"
    comp.run()
    # second run resumes from the checkpoint and finishes epochs 1..2
    comp2 = make('r')
    comp2.epoch = 3
    comp2.run()
    w = np.asarray(fluid.global_scope().find('r_w1'))
    assert np.all(w == 0, axis=0).sum() == H // 2, \
        "prune masks lost across checkpoint resume"
    graph = comp2.context.optimize_graph or comp2.context.train_graph
    assert any('fake_quant' in op.type for op in graph.ops()), \
        "quant rewrite lost across checkpoint resume"


def test_distillation_restore_from_checkpoint(tmp_path):
    """Resume mid-distillation: the merged teacher graph must be rebuilt
    (DistillationStrategy.restore_from_checkpoint) and training continue
    against the combined loss."""
    s_prog, s_start, s_feat, s_logits, s_loss = _build_student('dr')
    t_prog, t_start, t_feat, t_logits = _build_teacher()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(s_start)
    exe.run(t_start)

    calls = {'n': 0}
    base = _reader(3)

    def counting_reader():
        calls['n'] += 1
        yield from base()

    def make():
        train_g = slim.GraphWrapper(s_prog, out_nodes={'loss': s_loss})
        comp = slim.Compressor(
            place=fluid.CPUPlace(), scope=fluid.global_scope(),
            train_program=train_g, train_reader=counting_reader,
            teacher_programs=[slim.GraphWrapper(t_prog)],
            distiller_optimizer=fluid.optimizer.Adam(5e-3),
            checkpoint_path=str(tmp_path / 'ck'))
        comp.add_strategy(slim.DistillationStrategy(
            distillers=[slim.L2Distiller(s_feat, t_feat)],
            start_epoch=0, end_epoch=4))
        return comp

    c1 = make()
    c1.epoch = 1
    c1.run()                      # stops after epoch 0 (simulated death)
    assert calls['n'] == 1
    w_after_c1 = np.asarray(fluid.global_scope().find('dr_w1')).copy()
    # perturb the scope so only a real checkpoint load can restore it
    import jax.numpy as jnp
    fluid.global_scope().set('dr_w1', jnp.zeros_like(w_after_c1))

    c2 = make()
    c2.epoch = 3
    c2.run()
    # a REAL resume trains exactly epochs 1..2, not 0..2
    assert calls['n'] == 3, f"expected 2 resumed epochs, reader ran " \
        f"{calls['n'] - 1} in c2"
    g = c2.context.optimize_graph
    assert g is not None, "distillation graph not rebuilt on restore"
    assert any('l2loss' in k for k in g.out_nodes), g.out_nodes
    w = np.asarray(fluid.global_scope().find('dr_w1'))
    assert np.isfinite(w).all()
    assert np.abs(w).sum() > 0, \
        "checkpoint load did not restore the perturbed weights"


def test_save_quantized_model(tmp_path):
    from paddle_tpu import dygraph
    from paddle_tpu.dygraph.nn import Linear
    from paddle_tpu.contrib.slim import PostTrainingQuantization
    rng = np.random.RandomState(0)
    with dygraph.guard():
        model = Linear(4, 2)

        def reader():
            for _ in range(2):
                yield rng.randn(3, 4).astype('float32')
        ptq = PostTrainingQuantization(model=model, sample_generator=reader,
                                       batch_nums=2)
        out = ptq.save_quantized_model(str(tmp_path / 'q'))
    import os
    assert os.path.exists(os.path.join(out, 'quant_scales.npz'))


def test_sa_controller_finds_optimum():
    ctrl = slim.SAController(reduce_rate=0.9, init_temperature=1.0, seed=3)
    target = [3, 1, 4]
    ctrl.reset([5, 5, 5], [0, 0, 0])

    def reward(tokens):
        return -sum(abs(a - b) for a, b in zip(tokens, target))

    tokens = [0, 0, 0]
    ctrl.update(tokens, reward(tokens))
    for _ in range(200):
        tokens = ctrl.next_tokens()
        ctrl.update(tokens, reward(tokens))
    assert ctrl.best_tokens == target, \
        (ctrl.best_tokens, ctrl.max_reward)


class _TinySpace(slim.SearchSpace):
    """Search over fc width exponent; wider → better eval accuracy proxy."""

    def init_tokens(self):
        return [0]

    def range_table(self):
        return [3]

    def create_net(self, tokens):
        width = 4 * (tokens[0] + 1)
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            x = fluid.data('img', [B, IN], 'float32')
            y = fluid.data('label', [B, 1], 'int64')
            feat = L.fc(x, size=width, act='relu')
            logits = L.fc(feat, size=C)
            loss = L.reduce_mean(L.softmax_with_cross_entropy(logits, y))
            fluid.optimizer.SGD(0.05).minimize(loss)
        eval_prog = prog.clone(for_test=True)
        return (startup, prog, eval_prog,
                {'loss': loss.name}, {'loss': loss.name})


def test_light_nas_strategy_searches():
    strategy = slim.LightNASStrategy(
        controller=slim.SAController(seed=1), metric_name='loss',
        search_steps=3, retrain_epoch=1, max_train_batches=2)
    # reward == metric value; loss is positive so LOWER is worse reward —
    # invert by searching on negative loss via a wrapper space
    space = _TinySpace()
    ctx = slim.Context(place=fluid.CPUPlace(), scope=fluid.global_scope(),
                       train_reader=_reader(3), eval_reader=_reader(2),
                       search_space=space)
    strategy.on_compression_begin(ctx)
    assert ctx.get('best_tokens') is not None
    assert ctx.get('best_net') is not None


def test_sensitive_prune_strategy_scans():
    prog, startup, feat, logits, loss = _build_student('sp')
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    train_g = slim.GraphWrapper(prog, out_nodes={'loss': loss})
    eval_g = slim.GraphWrapper(prog.clone(for_test=True),
                               out_nodes={'loss': loss})
    strategy = slim.SensitivePruneStrategy(
        pruner=slim.StructurePruner({'*': 1}, {'*': 'l1_norm'}),
        start_epoch=0, end_epoch=1, delta_rate=0.3, target_ratio=0.9,
        metric_name='loss', sensitivities_tolerance=10.0,  # tolerate all
        params=['sp_w1'])
    ctx = slim.Context(place=fluid.CPUPlace(), scope=fluid.global_scope(),
                       train_graph=train_g, train_reader=_reader(2),
                       eval_graph=eval_g, eval_reader=_reader(2))
    strategy.on_epoch_begin(ctx)
    # with huge tolerance every tested ratio passes → ratio 0.9 chosen
    assert strategy.ratios and strategy.ratios[0] >= 0.89
    w = np.asarray(fluid.global_scope().find('sp_w1'))
    assert np.all(w == 0, axis=0).sum() == int(round(H * 0.9))
