"""fluid.dataset (MultiSlot files → train_from_dataset) + paddle.dataset
zoo readers."""
import os

import numpy as np
import pytest

import paddle_tpu as fluid


def _write_multislot(path, rows):
    """rows: list of (dense3, label1) — MultiSlot: count then values."""
    with open(path, 'w') as f:
        for feats, lab in rows:
            f.write(f"{len(feats)} {' '.join(str(v) for v in feats)} "
                    f"1 {lab}\n")


@pytest.fixture
def slot_files(tmp_path):
    rng = np.random.RandomState(0)
    files = []
    for i in range(2):
        rows = [(rng.rand(3).round(3).tolist(), int(rng.randint(0, 2)))
                for _ in range(6)]
        p = str(tmp_path / f'part-{i}.txt')
        _write_multislot(p, rows)
        files.append(p)
    return files


def _slot_vars():
    x = fluid.data('ds_x', [-1, 3], 'float32')
    y = fluid.data('ds_y', [-1, 1], 'int64')
    return x, y


def test_queue_dataset_batches(slot_files):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x, y = _slot_vars()
    ds = fluid.DatasetFactory().create_dataset('QueueDataset')
    ds.set_batch_size(4)
    ds.set_filelist(slot_files)
    ds.set_use_var([x, y])
    batches = list(ds._batches())
    assert len(batches) == 3            # 12 rows / bs 4
    assert batches[0]['ds_x'].shape == (4, 3)
    assert batches[0]['ds_y'].shape == (4, 1)
    with pytest.raises(NotImplementedError):
        ds.local_shuffle()
    assert 'MultiSlotDataFeed' in ds.desc()


def test_inmemory_dataset_shuffle_and_size(slot_files):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x, y = _slot_vars()
    ds = fluid.DatasetFactory().create_dataset('InMemoryDataset')
    ds.set_batch_size(3)
    ds.set_filelist(slot_files)
    ds.set_use_var([x, y])
    ds.load_into_memory()
    assert ds.get_memory_data_size() == 12
    before = [r[0].copy() for r in ds.memory]
    ds.local_shuffle()
    after = [r[0] for r in ds.memory]
    assert sorted(map(tuple, before)) == sorted(map(tuple, after))
    ds.release_memory()
    assert ds.memory is None


def test_pipe_command(tmp_path):
    p = str(tmp_path / 'raw.txt')
    with open(p, 'w') as f:
        f.write('SKIP\n3 1.0 2.0 3.0 1 0\n')
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x, y = _slot_vars()
    ds = fluid.DatasetFactory().create_dataset('QueueDataset')
    ds.set_batch_size(1)
    ds.set_filelist([p])
    ds.set_use_var([x, y])
    ds.set_pipe_command('grep -v SKIP')
    b = list(ds._batches())
    assert len(b) == 1
    np.testing.assert_allclose(b[0]['ds_x'][0], [1.0, 2.0, 3.0])


def test_train_from_dataset(slot_files, capsys):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x, y = _slot_vars()
        pred = fluid.layers.fc(x, 2, name='tfd_fc')
        loss = fluid.layers.reduce_mean(
            fluid.layers.softmax_with_cross_entropy(pred, y))
        fluid.optimizer.SGD(0.1).minimize(loss)
    exe = fluid.Executor()
    exe.run(startup)
    ds = fluid.DatasetFactory().create_dataset('InMemoryDataset')
    ds.set_batch_size(4)
    ds.set_filelist(slot_files)
    ds.set_use_var([x, y])
    ds.load_into_memory()
    w_name = fluid.io.get_program_parameter(main)[0].name
    w0 = np.asarray(fluid.global_scope().find(w_name)).copy()
    exe.train_from_dataset(main, ds, fetch_list=[loss], print_period=1)
    w1 = np.asarray(fluid.global_scope().find(w_name))
    assert not np.allclose(w0, w1)      # training actually stepped
    # fetch reporting goes through log_helper (stderr handler), never print
    cap = capsys.readouterr()
    assert 'step 0' in cap.err and 'step 0' not in cap.out


def test_lod_slot_packs_as_lodtensor(tmp_path):
    p = str(tmp_path / 'seq.txt')
    with open(p, 'w') as f:
        f.write('2 5 6 1 0\n3 7 8 9 1 1\n')
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        words = fluid.data('ds_w', [-1, -1], 'int64', lod_level=1)
        lab = fluid.data('ds_l', [-1, 1], 'int64')
    ds = fluid.DatasetFactory().create_dataset('QueueDataset')
    ds.set_batch_size(2)
    ds.set_filelist([p])
    ds.set_use_var([words, lab])
    (batch,) = list(ds._batches())
    t = batch['ds_w']
    from paddle_tpu.core.lod import LoDTensor
    assert isinstance(t, LoDTensor)
    assert t.recursive_sequence_lengths() == [[2, 3]]


# ------------------------------------------------------------- zoo -----

def test_zoo_readers_yield_consistent_samples():
    x, y = next(fluid.dataset.uci_housing.train()())
    assert x.shape == (13,) and y.shape == (1,)
    img, lab = next(fluid.dataset.mnist.train()())
    assert img.shape == (784,) and 0 <= lab < 10
    img, lab = next(fluid.dataset.cifar.train10()())
    assert img.shape == (3072,)
    img, lab = next(fluid.dataset.cifar.train100()())
    assert img.shape == (3072,)


def test_zoo_imdb_pipeline():
    wd = fluid.dataset.imdb.build_dict('train', 0)
    assert '<unk>' in wd
    ids, label = next(fluid.dataset.imdb.train(wd)())
    assert label in (0, 1) and all(i < len(wd) for i in ids)


def test_zoo_imikolov_ngram_and_seq():
    wd = fluid.dataset.imikolov.build_dict()
    gram = next(fluid.dataset.imikolov.train(wd, 5)())
    assert len(gram) == 5
    src, trg = next(fluid.dataset.imikolov.train(
        wd, -1, fluid.dataset.imikolov.DataType.SEQ)())
    assert src[0] == wd['<s>'] and trg[-1] == wd['<e>']
    assert src[1:] == trg[:-1]


def test_zoo_movielens_consistency():
    ml = fluid.dataset.movielens
    sample = next(ml.train()())
    assert len(sample) == 8
    uid = sample[0]
    assert 1 <= uid <= ml.max_user_id()
    assert sample[4] <= ml.max_movie_id()
    assert isinstance(ml.movie_info()[sample[4]], ml.MovieInfo)
    assert len(ml.get_movie_title_dict()) > 0


def test_zoo_wmt_translation_pairs():
    src, trg, trg_next = next(fluid.dataset.wmt14.train(30)())
    assert trg[1:] == trg_next[:-1]
    sd, td = fluid.dataset.wmt14.get_dict(30)
    assert isinstance(next(iter(sd)), int)   # reverse=True → id→word
    src, trg, trg_next = next(fluid.dataset.wmt16.train(30, 30)())
    assert trg[1:] == trg_next[:-1]
    d = fluid.dataset.wmt16.get_dict('en', 30)
    assert fluid.dataset.wmt16.START_MARK in d


def test_zoo_conll05_srl_shapes():
    r = fluid.dataset.conll05.test()
    s = next(r())
    assert len(s) == 9
    n = len(s[0])
    assert all(len(f) == n for f in s[1:])
    wd, vd, ld = fluid.dataset.conll05.get_dict()
    assert 'B-V' in ld
    emb_path = fluid.dataset.conll05.get_embedding()
    assert os.path.exists(emb_path)


def test_zoo_mq2007_formats():
    label, better, worse = next(fluid.dataset.mq2007.train())
    assert label == 1 and better.shape == worse.shape
    score, feats = next(fluid.dataset.mq2007.train(format='pointwise'))
    assert feats.ndim == 1
    labels, mat = next(fluid.dataset.mq2007.train(format='listwise'))
    assert mat.shape[0] == labels.shape[0]


def test_zoo_sentiment():
    wd = fluid.dataset.sentiment.get_word_dict()
    ids, label = next(fluid.dataset.sentiment.train()())
    assert label in (0, 1) and all(i < len(wd) for i in ids)


def test_zoo_image_transforms():
    img = np.arange(40 * 30 * 3, dtype='uint8').reshape(40, 30, 3)
    small = fluid.dataset.image.resize_short(img, 20)
    assert min(small.shape[:2]) == 20
    crop = fluid.dataset.image.center_crop(small, 16)
    assert crop.shape[:2] == (16, 16)
    chw = fluid.dataset.image.to_chw(crop)
    assert chw.shape[0] == 3
    out = fluid.dataset.image.simple_transform(img, 24, 16, is_train=True,
                                               mean=[1.0, 2.0, 3.0])
    assert out.shape == (3, 16, 16)
    flipped = fluid.dataset.image.left_right_flip(img)
    np.testing.assert_array_equal(flipped[:, 0], img[:, -1])


def test_zoo_common_split_and_cluster(tmp_path):
    def reader():
        yield from range(10)
    fluid.dataset.common.split(reader, 4, suffix=str(tmp_path / '%05d.pkl'))
    r = fluid.dataset.common.cluster_files_reader(
        str(tmp_path / '*.pkl'), trainer_count=1, trainer_id=0)
    assert sorted(r()) == list(range(10))
    two = fluid.dataset.common.cluster_files_reader(
        str(tmp_path / '*.pkl'), trainer_count=2, trainer_id=0)
    assert len(list(two())) < 10
