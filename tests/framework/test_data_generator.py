"""incubate.data_generator round-trip (ref: fluid/incubate/data_generator/
__init__.py): generator-produced MultiSlot file → InMemoryDataset →
train_from_dataset (VERDICT r4 item 5)."""
import io

import numpy as np

import paddle_tpu as fluid
import paddle_tpu.layers as L
from paddle_tpu.incubate.data_generator import (MultiSlotDataGenerator,
                                                MultiSlotStringDataGenerator)


class WordsGen(MultiSlotDataGenerator):
    def generate_sample(self, line):
        def local_iter():
            toks = [int(x) for x in line.split()]
            yield ("words", toks[:-1]), ("label", [toks[-1]])
        return local_iter


def test_multislot_format():
    g = WordsGen()
    out = io.StringIO()
    g._drain(["10 20 30 1"], out)
    assert out.getvalue() == "3 10 20 30 1 1\n"
    assert g._proto_info == [("words", "uint64"), ("label", "uint64")]


def test_multislot_float_promotes_schema():
    class FloatGen(MultiSlotDataGenerator):
        def generate_sample(self, line):
            def it():
                yield ("score", [0.5, 1.5]), ("label", [1])
            return it
    g = FloatGen()
    out = io.StringIO()
    g.run_from_memory(out)
    assert out.getvalue() == "2 0.5 1.5 1 1\n"
    assert g._proto_info[0] == ("score", "float")


def test_multislot_inconsistent_slots_rejected():
    class BadGen(MultiSlotDataGenerator):
        def __init__(self):
            super().__init__()
            self.n = 0

        def generate_sample(self, line):
            def it():
                self.n += 1
                if self.n == 1:
                    yield ("a", [1]), ("b", [2])
                else:
                    yield (("a", [1]),)
            return it
    g = BadGen()
    out = io.StringIO()
    try:
        g._drain(["x", "y"], out)
        raise AssertionError("inconsistent slot count not rejected")
    except ValueError as e:
        assert "inconsistent" in str(e)


def test_string_generator():
    class SGen(MultiSlotStringDataGenerator):
        def generate_sample(self, line):
            def it():
                yield ("words", line.split()), ("label", ["1"])
            return it
    g = SGen()
    out = io.StringIO()
    g._drain(["a b c"], out)
    assert out.getvalue() == "3 a b c 1 1\n"


def test_generator_file_roundtrip_train_from_dataset(tmp_path):
    """The full reference recipe: generator writes the MultiSlot file,
    fluid.dataset parses it, train_from_dataset runs a pass."""
    rng = np.random.RandomState(0)
    lines = []
    for _ in range(32):
        words = rng.randint(1, 50, 5)
        label = int(words.sum() % 2)
        lines.append(" ".join(map(str, words)) + f" {label}")
    path = str(tmp_path / "part-0.txt")
    WordsGen().write_to_file(lines, path)

    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        words = fluid.data('words', [8, 5], 'int64')
        label = fluid.data('label', [8, 1], 'int64')
        emb = L.embedding(words, size=[50, 8])
        feat = L.reduce_mean(emb, dim=1)
        logits = L.fc(feat, size=2)
        loss = L.reduce_mean(L.softmax_with_cross_entropy(logits, label))
        fluid.optimizer.SGD(0.1).minimize(loss)

    dataset = fluid.DatasetFactory().create_dataset('InMemoryDataset')
    dataset.set_batch_size(8)
    dataset.set_use_var([words, label])
    dataset.set_filelist([path])
    dataset.load_into_memory()

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    exe.train_from_dataset(program=prog, dataset=dataset)
    w = np.asarray(fluid.global_scope().find(
        prog.all_parameters()[0].name))
    assert np.isfinite(w).all()
