"""tier-1 guard for the resilience bench: tools/bench_resilience.py must run
end-to-end under JAX_PLATFORMS=cpu at smoke sizes and demonstrate the
ISSUE 7 + ISSUE 8 acceptances: async checkpointing adds < 1 step of stall
to the train loop, checkpointing/supervision never perturb the losses
(bitwise), restart lost work equals what the cadence predicts, and an
injected NaN under policy=rollback recovers from the newest committed
checkpoint."""
import json
import os
import subprocess
import sys

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), '..', '..'))

STALL_FIELDS = {'steps', 'ckpt_every', 'state_mb', 'base_median_ms',
                'async_p99_ms', 'blocking_p99_ms', 'async_stall_ms',
                'async_stall_steps', 'blocking_stall_steps',
                'stall_lt_one_step', 'bitwise_identical'}


def test_bench_resilience_smoke_runs_on_cpu():
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    env.pop('PADDLE_TPU_FAULT_INJECT', None)
    env.pop('PADDLE_TPU_ASYNC', None)
    r = subprocess.run(
        [sys.executable, os.path.join('tools', 'bench_resilience.py'),
         '--smoke'],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stderr[-3000:]
    lines = [json.loads(ln) for ln in r.stdout.splitlines() if ln.strip()]
    benches = {d['bench']: d for d in lines if 'bench' in d}
    assert {'resilience_stall', 'resilience_restart'} <= set(benches)

    st = benches['resilience_stall']
    assert STALL_FIELDS <= set(st), st
    # correctness is non-negotiable: checkpointing observes state, it must
    # never change the computation
    assert st['bitwise_identical'] is True, st
    # THE acceptance: async checkpoint stall < 1 baseline step
    assert st['stall_lt_one_step'] is True, st
    assert st['async_stall_steps'] < 1.0, st
    assert st['base_median_ms'] > 0

    rs = benches['resilience_restart']
    assert rs['lost_steps'] == rs['expected_lost_steps'], rs
    assert rs['restored_step'] == 10 and rs['restarts'] == 1, rs

    assert {'resilience_supervised', 'resilience_nan_recovery'} <= \
        set(benches)
    sv = benches['resilience_supervised']
    # supervision must OBSERVE the run, never change it — bitwise, always
    assert sv['bitwise_identical'] is True, sv
    # the ≤2% acceptance is asserted at full size (PERF.md §15); at smoke
    # sizes per-step time is ~10 ms so allow CI noise, but a gross
    # regression (supervision serializing or copying state) still fails
    assert sv['overhead_frac'] < 0.25, sv

    nr = benches['resilience_nan_recovery']
    assert nr['recovered'] is True, nr
    # rollback must use the NEWEST committed checkpoint — including one
    # whose async write was still in flight at detection time
    assert nr['resumed_from'] == nr['expected_resume'], nr
    assert nr['detected_at'] == nr['nan_step'], nr
