"""io.py strictness satellites (ISSUE 4): explicit save_vars/load_vars lists
and load_inference_model must fail loudly instead of silently saving object
arrays / skipping requested vars / serving uninitialized parameters."""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers


def _tiny_program():
    main, start = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, start):
        x = layers.data('x', shape=[4], dtype='float32')
        out = layers.fc(x, 2, act='softmax',
                        param_attr=fluid.ParamAttr(name='strict_w'))
    return main, start, out


def test_save_vars_missing_var_raises(tmp_path):
    main, start, _ = _tiny_program()
    exe = fluid.Executor()
    exe.run(start)
    # pre-fix: np.asarray(scope.find('nope')) silently saved an object array
    with pytest.raises(ValueError, match="'nope'"):
        fluid.io.save_vars(exe, str(tmp_path / 'm'), main,
                           vars=['strict_w', 'nope'])
    # the good path still works
    fluid.io.save_vars(exe, str(tmp_path / 'm'), main, vars=['strict_w'])
    with np.load(str(tmp_path / 'm' / 'params.npz')) as data:
        assert data['strict_w'].dtype == np.float32


def test_load_vars_missing_from_archive_raises(tmp_path):
    main, start, _ = _tiny_program()
    exe = fluid.Executor()
    exe.run(start)
    fluid.io.save_vars(exe, str(tmp_path / 'm'), main, vars=['strict_w'])
    # requesting a var the archive lacks must raise, listing the names
    b0 = main.global_block().var('strict_w')
    with pytest.raises(ValueError, match=r"\['fc_0\.b_0'\]"):
        fluid.io.load_vars(exe, str(tmp_path / 'm'), main,
                           vars=[b0, 'fc_0.b_0'])
    # exact-list round-trip unaffected
    fluid.io.load_vars(exe, str(tmp_path / 'm'), main, vars=['strict_w'])


def test_load_inference_model_missing_params_raises(tmp_path):
    main, start, out = _tiny_program()
    exe = fluid.Executor()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(start)
        fluid.io.save_inference_model(str(tmp_path / 'po'), ['x'], [out],
                                      exe, main, program_only=True)
    # fresh scope, no params file: pre-fix this returned a program whose
    # persistables were garbage — now it names them and raises
    with fluid.scope_guard(fluid.Scope()):
        with pytest.raises(RuntimeError, match='strict_w'):
            fluid.io.load_inference_model(str(tmp_path / 'po'), exe)


def test_load_inference_model_program_only_with_preset_scope(tmp_path):
    """The supported program_only workflow — persistables pre-populated in
    the scope — keeps working."""
    main, start, out = _tiny_program()
    exe = fluid.Executor()
    scope = fluid.Scope()
    X = np.random.RandomState(0).randn(3, 4).astype(np.float32)
    with fluid.scope_guard(scope):
        exe.run(start)
        ref, = exe.run(main, feed={'x': X}, fetch_list=[out])
        fluid.io.save_inference_model(str(tmp_path / 'po'), ['x'], [out],
                                      exe, main, program_only=True)
        prog, feeds, fetches = fluid.io.load_inference_model(
            str(tmp_path / 'po'), exe)
        got, = exe.run(prog, feed={'x': X}, fetch_list=fetches)
    np.testing.assert_allclose(got, ref, rtol=1e-6)


def test_load_inference_model_partial_params_raises(tmp_path):
    """A params archive missing SOME persistables is the same bug in
    miniature: raise, naming exactly the uninitialized ones."""
    import json
    import os
    main, start, out = _tiny_program()
    exe = fluid.Executor()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(start)
        fluid.io.save_inference_model(str(tmp_path / 'pp'), ['x'], [out],
                                      exe, main)
    # drop one entry from the saved archive
    path = str(tmp_path / 'pp' / 'params.npz')
    with np.load(path) as data:
        kept = {k: data[k] for k in data.files if k != 'strict_w'}
    os.remove(path)
    np.savez(path, **kept)
    with fluid.scope_guard(fluid.Scope()):
        with pytest.raises(RuntimeError, match='strict_w'):
            fluid.io.load_inference_model(str(tmp_path / 'pp'), exe)
    # sanity: the meta file is untouched
    with open(str(tmp_path / 'pp' / '__model__.json')) as f:
        assert json.load(f)['feed_names'] == ['x']
