"""The canonical fluid-book training recipe, end to end: paddle.dataset
reader → paddle.batch → DataLoader → conv net (nets.simple_img_conv_pool)
→ LR schedule + gradient clip + momentum → accuracy metric → save
inference model → Predictor inference. One test = the whole reference
user journey on TPU lowering."""
import numpy as np

import paddle_tpu as fluid
from paddle_tpu import nets


def test_mnist_recipe_end_to_end(tmp_path):
    fluid.manual_seed(3)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.data('img', [-1, 1, 28, 28], 'float32')
        label = fluid.data('label', [-1, 1], 'int64')
        conv = nets.simple_img_conv_pool(img, num_filters=8, filter_size=5,
                                         pool_size=2, pool_stride=2,
                                         act='relu')
        logits = fluid.layers.fc(conv, 10)
        prob = fluid.layers.softmax(logits)
        loss = fluid.layers.reduce_mean(
            fluid.layers.cross_entropy(prob, label))
        acc = fluid.layers.accuracy(prob, label)
        lr = fluid.layers.exponential_decay(0.05, decay_steps=20,
                                            decay_rate=0.9)
        opt = fluid.optimizer.Momentum(
            lr, momentum=0.9,
            grad_clip=fluid.clip.GradientClipByGlobalNorm(5.0))
        opt.minimize(loss)
    test_prog = main.clone(for_test=True)

    # zoo reader (synthetic fallback off-cache) → batch → DataLoader
    train_reader = fluid.dataset.mnist.train()
    batched = fluid.reader.batch(train_reader, batch_size=32,
                                 drop_last=True)

    def to_feed():
        for rows in batched():
            xs = np.stack([r[0].reshape(1, 28, 28) for r in rows])
            ys = np.array([[r[1]] for r in rows], 'int64')
            yield xs.astype('float32'), ys

    loader = fluid.DataLoader.from_generator(feed_list=[img, label])
    loader.set_batch_generator(to_feed)

    exe = fluid.Executor()
    exe.run(startup)
    losses = []
    for epoch in range(3):
        for feed in loader():
            l, a = exe.run(main, feed=feed, fetch_list=[loss, acc])
            losses.append(float(l))
    # synthetic labels are random, but the net must still fit SOMETHING
    # (training loss decreases) and the whole pipeline must be finite
    assert np.isfinite(losses).all()
    assert np.mean(losses[-10:]) < np.mean(losses[:10])

    # save → Predictor round trip
    model_dir = str(tmp_path / 'mnist_model')
    fluid.io.save_inference_model(model_dir, ['img'], [prob], exe,
                                  main_program=test_prog)
    from paddle_tpu.inference import Config, create_paddle_predictor
    pred = create_paddle_predictor(Config(model_dir))
    x = np.zeros((4, 1, 28, 28), 'float32')
    out = pred.run({'img': x})[0]
    assert out.shape == (4, 10)
    np.testing.assert_allclose(out.sum(-1), 1.0, rtol=1e-4)

    # the test program (clone for_test) evaluates without updates
    # (consume the loader fully — a dropped iterator would strand its
    # producer thread on the bounded queue)
    feed0 = list(loader())[0]
    before = exe.run(test_prog, feed=feed0, fetch_list=[loss])[0]
    after = exe.run(test_prog, feed=feed0, fetch_list=[loss])[0]
    np.testing.assert_allclose(before, after)
