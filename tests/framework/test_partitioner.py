"""Unified SPMD partitioner (paddle_tpu/partition, docs/PARTITIONER.md):
rule-table resolution, strict knob parsing, mesh ownership (the
deprecated ``set_default_mesh`` shim), spec parity vs the retired
per-module plumbing, bitwise parity of the refactored parallel modules
through both entry points, dp×tp / dp×fsdp compositions with the PR 9
quantized+bucketed gradient sync (telemetry asserted), the
sharding-consistency diagnostics corpus, and the partitioner-keyed
checkpoint spec manifest."""
import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as fluid
from paddle_tpu import analysis, layers, observability as obs, partition
from paddle_tpu.core.scope import Scope
from paddle_tpu.parallel import (DistributedStrategy, GeoSGDStep,
                                 LocalSGDStep, fleet)
from paddle_tpu.parallel import fsdp as F
from paddle_tpu.parallel.mesh import (get_default_mesh, make_mesh,
                                      mesh_guard, set_default_mesh)
from paddle_tpu.parallel.tensor_parallel import (column_parallel_matmul,
                                                 megatron_param_spec,
                                                 mp_allreduce, mp_copy,
                                                 row_parallel_matmul)
from paddle_tpu.partition import (AxisRules, Partitioner, get_partitioner,
                                  parse_axis_rules, parse_mesh_shape)
from paddle_tpu.partition.spmd_step import SpmdTrainStep
from jax.sharding import PartitionSpec as P

_THIS_FILE = os.path.abspath(__file__)


@pytest.fixture(autouse=True)
def _fresh_partitioner():
    partition.reset_partitioner()
    yield
    partition.reset_partitioner()


# ---------------------------------------------------------------------------
# rules + strict parsing
# ---------------------------------------------------------------------------

def test_default_rules_resolution():
    p = Partitioner(mesh_shape={'dp': 8})
    assert p.data_axes() == ('dp',)
    assert p.data_spec(16) == P('dp')
    p = Partitioner(mesh_shape={'dp': 2, 'fsdp': 4})
    assert p.data_axes() == ('dp', 'fsdp')
    assert p.data_spec(16) == P(('dp', 'fsdp'))
    # indivisible batch dim falls back to replicated
    assert p.data_spec(3) == P()
    # unconfigured partitioner replicates everything
    p = Partitioner()
    assert p.mesh is None or p.mesh  # env may configure it
    assert Partitioner(mesh=None).resolve_spec(('batch',)) == P()


def test_rule_table_order_first_match_wins():
    rules = AxisRules((('batch', 'sp'), ('batch', 'dp')))
    assert rules.resolve('batch', {'dp': 8}) == ('dp',)       # sp absent
    assert rules.resolve('batch', {'sp': 4, 'dp': 2}) == ('sp',)
    # divisibility skips to the next rule
    assert rules.resolve('batch', {'sp': 3, 'dp': 2}, dim=8) == ('dp',)


def test_spec_never_reuses_a_mesh_axis():
    p = Partitioner(mesh_shape={'tp': 8})
    rules = AxisRules((('mlp', 'tp'), ('heads', 'tp')))
    spec = rules.spec(('mlp', 'heads'), {'tp': 8})
    assert spec == P('tp')          # second dim loses: axis already taken


def test_axis_rules_strict_parse():
    with pytest.raises(ValueError, match='batch'):
        parse_axis_rules('bogus=dp')
    with pytest.raises(ValueError, match='dp, fsdp, tp, pp, sp'):
        parse_axis_rules('batch=nope')
    assert parse_axis_rules('batch=dp+fsdp,kv=') == \
        (('batch', ('dp', 'fsdp')), ('kv', None))


def test_mesh_shape_strict_parse():
    with pytest.raises(ValueError, match='dp, fsdp, tp, pp, sp'):
        parse_mesh_shape({'gpu': 8})
    with pytest.raises(ValueError, match='>= 1'):
        parse_mesh_shape('dp=0')
    with pytest.raises(ValueError, match='twice'):
        parse_mesh_shape('dp=2,dp=4')
    assert parse_mesh_shape('dp=2, tp=4') == {'dp': 2, 'tp': 4}


def test_distributed_strategy_fields_strict():
    strat = DistributedStrategy()
    with pytest.raises(ValueError, match='DistributedStrategy.mesh_shape'):
        strat.mesh_shape = {'cuda': 8}
    with pytest.raises(ValueError, match='DistributedStrategy.axis_rules'):
        strat.axis_rules = 'embedding=tp'
    strat.mesh_shape = 'dp=2,fsdp=4'
    strat.axis_rules = 'batch=dp,fsdp=fsdp'
    assert strat.mesh_shape == {'dp': 2, 'fsdp': 4}
    assert strat.axis_rules == (('batch', ('dp',)), ('fsdp', ('fsdp',)))


def test_env_knobs(monkeypatch):
    monkeypatch.setenv('PADDLE_TPU_MESH', 'dp=2,tp=4')
    monkeypatch.setenv('PADDLE_TPU_AXIS_RULES', 'batch=dp,mlp=tp')
    partition.reset_partitioner()
    p = get_partitioner()
    assert dict(p.mesh.shape) == {'dp': 2, 'tp': 4}
    assert p.data_axes() == ('dp',)
    monkeypatch.setenv('PADDLE_TPU_MESH', 'dp=2,bogus=4')
    partition.reset_partitioner()
    with pytest.raises(ValueError, match='PADDLE_TPU_MESH'):
        get_partitioner()


# ---------------------------------------------------------------------------
# mesh ownership: the deprecated shim + scoped override
# ---------------------------------------------------------------------------

def test_set_default_mesh_deprecated_shim_warns_once(monkeypatch):
    from paddle_tpu.partition import partitioner as pmod
    records = []

    class _Rec:
        def warning(self, msg, *a):
            records.append(msg % a if a else msg)

    monkeypatch.setattr('paddle_tpu.log_helper.get_logger',
                        lambda *a, **k: _Rec())
    pmod._DEPRECATION_WARNED.discard('set_default_mesh')
    mesh = make_mesh({'dp': 8})
    set_default_mesh(mesh)
    assert get_default_mesh() is mesh
    assert get_partitioner().mesh is mesh          # the partitioner owns it
    set_default_mesh(None)
    assert get_default_mesh() is None
    assert len(records) == 1 and 'deprecated' in records[0]
    assert 'set_default_mesh' in pmod._DEPRECATION_WARNED


def test_mesh_guard_scopes_the_owned_mesh():
    mesh = make_mesh({'sp': 8})
    assert get_default_mesh() is None
    with mesh_guard(mesh):
        assert get_default_mesh() is mesh
        assert get_partitioner().mesh is mesh
    assert get_default_mesh() is None


def test_configure_updates_global_in_place():
    p0 = get_partitioner()
    p1 = partition.configure(mesh_shape={'dp': 8})
    assert p1 is p0                                # identity stable
    assert dict(p0.mesh.shape) == {'dp': 8}


# ---------------------------------------------------------------------------
# spec parity vs the retired per-module plumbing
# ---------------------------------------------------------------------------

def test_fsdp_spec_parity_with_module():
    mesh = make_mesh({'fsdp': 8})
    p = Partitioner(mesh=mesh)
    for shape in [(64, 32), (32, 64), (8,), (3, 5), (1,), (16, 16, 4),
                  (24, 7), (8, 8)]:
        assert p.fsdp_spec(shape) == F.fsdp_spec(shape, mesh), shape
        assert p.param_spec('w', shape) == F.fsdp_spec(shape, mesh), shape


def test_megatron_spec_parity_with_module():
    p = Partitioner(mesh_shape={'tp': 8})
    arr = np.zeros((64, 32), np.float32)
    for name in ('l.ffn1.w', 'enc.q_proj.w', 'b.ffn2.w', 'a.out_proj.w',
                 'plain.w'):
        assert tuple(p.param_spec(name, arr.shape)) == \
            tuple(megatron_param_spec(name, arr)), name


def test_optimizer_slots_inherit_param_spec():
    p = Partitioner(mesh_shape={'dp': 2, 'tp': 4})
    w = p.param_spec('fc.ffn1.w_0', (64, 32))
    slot = p.param_spec('fc.ffn1.w_0_velocity_0', (64, 32))
    assert w == slot == P(None, 'tp')


def test_param_spec_composes_tp_and_fsdp():
    p = Partitioner(mesh_shape={'dp': 2, 'tp': 2, 'fsdp': 2})
    assert p.param_spec('x.ffn1.w', (64, 32)) == P(None, 'tp')
    assert p.param_spec('plain.w', (64, 32)) == P('fsdp', None)


# ---------------------------------------------------------------------------
# bitwise parity: refactored modules through both entry points
# ---------------------------------------------------------------------------

def _mse_loss(params, batch):
    return jnp.mean((batch[:, :-1] @ params['w'] - batch[:, -1:]) ** 2)


def _run_local_sgd(step_builder, steps=6):
    rng = np.random.RandomState(0)
    step = step_builder()
    return [float(step(rng.randn(16, 4).astype('float32')))
            for _ in range(steps)]


def test_local_sgd_bitwise_mesh_vs_partitioner():
    w0 = np.zeros((3, 1), np.float32)
    mesh = make_mesh({'dp': 8})
    legacy = _run_local_sgd(lambda: LocalSGDStep(
        _mse_loss, {'w': w0}, mesh, k_steps=2, lr=0.05))
    p = partition.configure(mesh_shape={'dp': 8})
    new = _run_local_sgd(lambda: LocalSGDStep(
        _mse_loss, {'w': w0}, k_steps=2, lr=0.05, partitioner=p))
    assert np.array_equal(legacy, new), (legacy, new)


def test_geo_sgd_bitwise_mesh_vs_partitioner():
    w0 = np.zeros((3, 1), np.float32)
    mesh = make_mesh({'dp': 8})
    legacy = _run_local_sgd(lambda: GeoSGDStep(
        _mse_loss, {'w': w0}, mesh, need_push_nums=2, lr=0.05))
    p = partition.configure(mesh_shape={'dp': 8})
    new = _run_local_sgd(lambda: GeoSGDStep(
        _mse_loss, {'w': w0}, need_push_nums=2, lr=0.05, partitioner=p))
    assert np.array_equal(legacy, new), (legacy, new)


def test_tensor_parallel_bitwise_mesh_vs_partitioner_default():
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(4, 16).astype('float32'))
    w1 = jnp.asarray(rng.randn(16, 32).astype('float32'))
    w2 = jnp.asarray(rng.randn(32, 16).astype('float32'))
    mesh = make_mesh({'tp': 8})
    y_explicit = row_parallel_matmul(
        column_parallel_matmul(x, w1, mesh=mesh), w2, mesh=mesh)
    partition.configure(mesh=mesh)
    y_default = row_parallel_matmul(
        column_parallel_matmul(x, w1), w2)       # partitioner-owned mesh
    assert np.array_equal(np.asarray(y_explicit), np.asarray(y_default))


def _build_fsdp_program():
    main, start = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, start):
        fluid.framework.manual_seed(5)
        x = layers.data('x', [16], dtype='float32')
        y = layers.data('y', [1], dtype='float32')
        h = layers.fc(x, size=32, act='relu')
        pred = layers.fc(h, size=1)
        loss = layers.reduce_mean(layers.square_error_cost(pred, y))
        strat = DistributedStrategy()
        strat.sharding = True
        opt = fleet.distributed_optimizer(
            fluid.optimizer.Momentum(learning_rate=0.05, momentum=0.9),
            strat)
        opt.minimize(loss)
    return main, start, loss


def _run_static(main, start, loss, steps=5):
    scope = Scope()
    exe = fluid.Executor()
    exe.run(start, scope=scope)
    rng = np.random.RandomState(1)
    out = []
    for _ in range(steps):
        xv = rng.standard_normal((16, 16)).astype(np.float32)
        yv = xv[:, :1].astype(np.float32)
        l, = exe.run(main, feed={'x': xv, 'y': yv}, fetch_list=[loss],
                     scope=scope)
        out.append(np.asarray(l))
    return np.concatenate([o.ravel() for o in out])


def test_fsdp_static_bitwise_legacy_vs_partitioner_entry():
    """The retired set_default_mesh entry and the partitioner entry
    lower the SAME fsdp program to bit-identical trajectories."""
    main, start, loss = _build_fsdp_program()
    with mesh_guard(make_mesh({'fsdp': 8})):       # legacy entry point
        legacy = _run_static(main, start, loss)
    partition.configure(mesh_shape={'fsdp': 8})    # partitioner entry
    new = _run_static(main, start, loss)
    assert np.array_equal(legacy, new), (legacy, new)


# ---------------------------------------------------------------------------
# compositions: dp×fsdp and dp×tp (ISSUE 11 acceptance)
# ---------------------------------------------------------------------------

def _composition_fixture():
    rng = np.random.RandomState(0)
    W1 = (rng.randn(16, 32) * 0.1).astype('float32')
    W2 = (rng.randn(32, 1) * 0.1).astype('float32')
    b = np.zeros((1,), 'float32')
    X = rng.randn(16, 16).astype('float32')
    batch = np.concatenate([X, X[:, :1]], axis=1)
    return {'ffn1.w': W1, 'ffn2.w': W2, 'b': b}, batch


def _ref_loss(params, bt):
    x, y = bt[:, :-1], bt[:, -1:]
    h = jnp.maximum(x @ params['ffn1.w'], 0.0)
    return jnp.mean(((h @ params['ffn2.w'] + params['b']) - y) ** 2)


def _reference_sgd(loss_fn, params, batch, lr, steps):
    ps = {k: jnp.asarray(v) for k, v in params.items()}
    losses = []
    for _ in range(steps):
        l, g = jax.value_and_grad(loss_fn)(ps, jnp.asarray(batch))
        ps = {k: v - lr * g[k] for k, v in ps.items()}
        losses.append(float(l))
    return losses, ps


def test_spmd_step_dp_fsdp_composition():
    """dp×fsdp with BOTH axes > 1: fc weights train as 1/4 fsdp tiles,
    batch shards over all 8 devices, every gradient sync runs through
    the PR 9 quantized-collective path (counters asserted)."""
    params, batch = _composition_fixture()
    ref_losses, ref_params = _reference_sgd(_ref_loss, params, batch,
                                            0.1, 5)
    p = partition.configure(mesh_shape={'dp': 2, 'fsdp': 4})
    assert all(s > 1 for s in p.mesh.shape.values())
    with obs.telemetry_guard(True):
        obs.reset()
        step = SpmdTrainStep(_ref_loss, params, partitioner=p, lr=0.1)
        assert step.param_kind('ffn1.w') == 'fsdp'
        assert step.param_kind('b') == 'replicated'
        losses = [float(step(batch)) for _ in range(5)]
        m = obs.registry.to_dict()
        calls = sum(s['value']
                    for s in m['collective_sync_calls']['samples']
                    if s['labels'].get('path') == 'spmd_step')
        assert calls == step.sync_calls_per_step * 5
        assert sum(s['value'] for s in
                   m['collective_bytes_on_wire']['samples']
                   if s['labels'].get('path') == 'spmd_step') > 0
    np.testing.assert_allclose(losses, ref_losses, rtol=5e-4, atol=1e-6)
    got = step.materialize()
    for n in params:
        np.testing.assert_allclose(got[n], np.asarray(ref_params[n]),
                                   rtol=5e-4, atol=1e-6)
    # the fsdp tiles really are 1/4 per device along the sharded dim
    w1 = step.sharded_params()['ffn1.w']
    assert w1.addressable_shards[0].data.shape == (16, 8)


def test_spmd_step_dp_tp_composition():
    """dp×tp with BOTH axes > 1: Megatron col+row MLP through the f/g
    conjugate collectives; tp tiles sync over dp only, replicated params
    bucket; trajectory matches the single-device reference."""
    params, batch = _composition_fixture()
    ref_losses, _ = _reference_sgd(_ref_loss, params, batch, 0.1, 5)

    def tp_loss(ps, bt):
        x, y = bt[:, :-1], bt[:, -1:]
        x = mp_copy(x, 'tp')
        h = jnp.maximum(x @ ps['ffn1.w'], 0.0)        # local columns
        part = h @ ps['ffn2.w']                       # partial products
        return jnp.mean(((mp_allreduce(part, 'tp') + ps['b']) - y) ** 2)

    p = partition.configure(mesh_shape={'dp': 2, 'tp': 4})
    with obs.telemetry_guard(True):
        obs.reset()
        step = SpmdTrainStep(tp_loss, params, partitioner=p, lr=0.1)
        assert step.param_kind('ffn1.w') == 'tp'
        assert step.param_kind('ffn2.w') == 'tp'
        assert step.param_kind('b') == 'replicated'
        losses = [float(step(batch)) for _ in range(5)]
        m = obs.registry.to_dict()
        calls = sum(s['value']
                    for s in m['collective_sync_calls']['samples']
                    if s['labels'].get('path') == 'spmd_step')
        assert calls == step.sync_calls_per_step * 5
    np.testing.assert_allclose(losses, ref_losses, rtol=5e-4, atol=1e-6)


def test_spmd_step_bucketed_replicated_grads():
    """Many small replicated params coalesce into ONE bucketed sync per
    data axis (the PR 9 bucketing semantics on the functional path)."""
    rng = np.random.RandomState(3)
    params = {f'b{i}': rng.randn(4).astype('float32') for i in range(6)}
    params['w'] = rng.randn(8, 8).astype('float32') * 0.1

    def loss_fn(ps, bt):
        acc = jnp.sum(bt @ ps['w'])
        for i in range(6):
            acc = acc + jnp.sum(ps[f'b{i}'])
        return acc / bt.shape[0]

    p = partition.configure(mesh_shape={'dp': 8})
    step = SpmdTrainStep(loss_fn, params, partitioner=p, lr=0.01)
    # 7 replicated params (w has no fsdp axis on a dp-only mesh), one
    # data axis → exactly ONE bucket → one sync per step
    assert step.sync_calls_per_step == 1
    step(rng.randn(8, 8).astype('float32'))


def test_spmd_step_int8_quantized_sync():
    """comm_dtype=int8 routes the composed gradient sync through the
    EQuARX block-quantized collectives: ~4× fewer bytes on wire, loss
    trajectory within quantization tolerance of f32. Sizes are large
    enough that the 256-elem block scales amortize (small tensors
    EXPAND under int8 — the PR 9 documented caveat)."""
    rng = np.random.RandomState(0)
    params = {'ffn1.w': (rng.randn(32, 512) * 0.1).astype('float32'),
              'ffn2.w': (rng.randn(512, 1) * 0.1).astype('float32'),
              'b': np.zeros((1,), 'float32')}
    X = rng.randn(16, 32).astype('float32')
    batch = np.concatenate([X, X[:, :1]], axis=1)
    ref_losses, _ = _reference_sgd(_ref_loss, params, batch, 0.1, 5)
    p = partition.configure(mesh_shape={'dp': 2, 'fsdp': 4})
    with obs.telemetry_guard(True):
        obs.reset()
        step = SpmdTrainStep(_ref_loss, params, partitioner=p, lr=0.1,
                             comm_dtype='int8')
        losses = [float(step(batch)) for _ in range(5)]
        m = obs.registry.to_dict()
        wire = sum(s['value']
                   for s in m['collective_bytes_on_wire']['samples']
                   if s['labels'].get('path') == 'spmd_step')
        f32eq = sum(s['value']
                    for s in m['collective_bytes_f32_equiv']['samples']
                    if s['labels'].get('path') == 'spmd_step')
        assert f32eq / wire >= 3.0, (wire, f32eq)
        dtypes = {s['labels'].get('dtype')
                  for s in m['collective_sync_calls']['samples']
                  if s['labels'].get('path') == 'spmd_step'}
        assert dtypes == {'int8'}
    np.testing.assert_allclose(losses, ref_losses, rtol=0.05, atol=5e-3)


def test_spmd_step_errors():
    params, batch = _composition_fixture()
    with pytest.raises(ValueError, match='no mesh'):
        SpmdTrainStep(_ref_loss, params)
    p = partition.configure(mesh_shape={'dp': 8})
    step = SpmdTrainStep(_ref_loss, params, partitioner=p)
    with pytest.raises(ValueError, match='divisible'):
        step(np.zeros((13, 17), np.float32))


def test_static_fleet_dp_fsdp_composition():
    """The STATIC path composes too: strategy.mesh_shape builds the
    dp×fsdp mesh at minimize, the Executor places persistables as fsdp
    tiles and shards feeds over both axes; trajectory matches the
    unsharded baseline."""
    from paddle_tpu.compiler import CompiledProgram

    def build(composed):
        main, start = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, start):
            fluid.framework.manual_seed(5)
            x = layers.data('x', [16], dtype='float32')
            y = layers.data('y', [1], dtype='float32')
            h = layers.fc(x, size=32, act='relu')
            pred = layers.fc(h, size=1)
            loss = layers.reduce_mean(layers.square_error_cost(pred, y))
            sgd = fluid.optimizer.Momentum(learning_rate=0.05, momentum=0.9)
            if composed:
                strat = DistributedStrategy()
                strat.sharding = True
                strat.mesh_shape = {'dp': 2, 'fsdp': 4}
                fleet.distributed_optimizer(sgd, strat).minimize(loss)
            else:
                sgd.minimize(loss)
        return main, start, loss

    partition.reset_partitioner()
    main, start, loss = build(False)
    base = _run_static(main, start, loss)

    partition.reset_partitioner()
    main, start, loss = build(True)
    assert dict(get_partitioner().mesh.shape) == {'dp': 2, 'fsdp': 4}
    assert getattr(main, '_partition_params', False)
    prog = CompiledProgram(main).with_data_parallel(loss_name=loss.name)
    scope = Scope()
    exe = fluid.Executor()
    exe.run(start, scope=scope)
    rng = np.random.RandomState(1)
    comp = []
    for _ in range(5):
        xv = rng.standard_normal((16, 16)).astype(np.float32)
        yv = xv[:, :1].astype(np.float32)
        l, = exe.run(prog, feed={'x': xv, 'y': yv}, fetch_list=[loss],
                     scope=scope)
        comp.append(float(np.asarray(l).reshape(())))
    np.testing.assert_allclose(comp, base.tolist(), rtol=2e-4, atol=1e-5)
    # a persistable really lives as dp-replicated fsdp tiles
    w = next(p_ for p_ in main.all_parameters()
             if int(np.prod(p_.shape)) >= 32)
    arr = scope.find(w.name)
    assert len(arr.addressable_shards) == 8
    assert F.param_shard_bytes(arr) * 4 == arr.nbytes


# ---------------------------------------------------------------------------
# sharding-consistency diagnostics (seeded-defect corpus)
# ---------------------------------------------------------------------------

def _find(diags, code):
    hits = [d for d in diags if d.code == code]
    assert hits, f'no {code!r} in {[d.format() for d in diags]}'
    return hits[0]


def _assert_site_here(diag):
    assert diag.site is not None, diag.format()
    assert os.path.abspath(diag.site.rsplit(':', 1)[0]) == _THIS_FILE, \
        diag.site


def _stamped_program(specs, mesh_axes):
    main, start = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, start):
        x = layers.data('x', [16], dtype='float32')
        h = layers.fc(x, size=30, act='relu')
        h2 = layers.fc(x, size=30)
        out = layers.elementwise_add(h, h2)
    main._partition_specs = specs(main)
    main._partition_mesh_axes = mesh_axes
    return main, out


def test_diag_spec_indivisible():
    main, out = _stamped_program(
        lambda m: {out_name(m): (None, 'fsdp')},     # 30 % 4 != 0
        {'dp': 2, 'fsdp': 4})
    d = _find(analysis.verify_program(main, fetch_names=[out.name]),
              'spec-indivisible')
    assert d.severity == 'error'
    assert d.op_type is not None
    _assert_site_here(d)


def out_name(main):
    """Last fc output var of the stamped corpus program."""
    blk = main.global_block()
    for op in reversed(blk.ops):
        if op.type == 'elementwise_add':
            return op.inputs['x'][0]
    raise AssertionError('corpus program shape changed')


def test_diag_spec_rank_mismatch():
    main, out = _stamped_program(
        lambda m: {out_name(m): (None, None, 'dp')},  # rank-2 var
        {'dp': 2, 'fsdp': 4})
    d = _find(analysis.verify_program(main, fetch_names=[out.name]),
              'spec-rank-mismatch')
    assert d.severity == 'error'
    _assert_site_here(d)


def test_diag_spec_conflict():
    def specs(m):
        blk = m.global_block()
        # the LAST elementwise_add is the explicit h + h2 (fc lowers its
        # bias through earlier adds)
        add = next(op for op in reversed(blk.ops)
                   if op.type == 'elementwise_add')
        xn, yn = add.inputs['x'][0], add.inputs['y'][0]
        return {xn: (None, 'tp'), yn: (None, 'dp')}
    main, out = _stamped_program(specs, {'dp': 2, 'tp': 2})
    d = _find(analysis.verify_program(main, fetch_names=[out.name]),
              'spec-conflict')
    assert d.severity == 'error' and d.op_type == 'elementwise_add'
    _assert_site_here(d)


def test_diag_spec_unknown_axis_and_reuse():
    main, out = _stamped_program(
        lambda m: {out_name(m): ('nope', None)}, {'dp': 2})
    d = _find(analysis.verify_program(main, fetch_names=[out.name]),
              'spec-unknown-axis')
    assert d.severity == 'error'
    main, out = _stamped_program(
        lambda m: {out_name(m): ('dp', 'dp')}, {'dp': 2})
    d = _find(analysis.verify_program(main, fetch_names=[out.name]),
              'spec-axis-reuse')
    assert d.severity == 'error'


def test_partitioner_stamps_are_clean():
    """Specs the partitioner itself resolves never trip its own
    diagnostics (zero-false-positive contract on the fsdp recipe)."""
    partition.configure(mesh_shape={'dp': 2, 'fsdp': 4})
    main, start, loss = _build_fsdp_program()
    assert getattr(main, '_partition_specs', None)
    diags = analysis.verify_program(main, fetch_names=[loss.name])
    bad = [d for d in diags
           if d.code.startswith('spec-') and d.severity == 'error']
    assert bad == [], [d.format() for d in bad]


# ---------------------------------------------------------------------------
# propagation + program specs
# ---------------------------------------------------------------------------

def test_propagation_carries_batch_sharding():
    partition.configure(mesh_shape={'dp': 8})
    main, start = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, start):
        x = layers.data('x', [16], dtype='float32')
        h = layers.fc(x, size=32, act='relu')
        out = layers.softmax(h)
    specs = get_partitioner().program_specs(main,
                                            include_activations=True)
    assert specs['x'] == ('dp',)
    assert specs[out.name] == ('dp', None)


def test_propagation_matmul_takes_weight_columns():
    p = Partitioner(mesh_shape={'dp': 2, 'tp': 4})
    main, start = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, start):
        x = layers.data('x', [16], dtype='float32')
        h = layers.fc(x, size=32, param_attr=fluid.ParamAttr(
            name='blk.ffn1.w'))
    specs = p.program_specs(main, include_activations=True)
    assert specs['blk.ffn1.w'] == (None, 'tp')
    # fc lowers to mul(+bias): the activation inherits batch rows and
    # the weight's column sharding
    assert specs[h.name] == ('dp', 'tp')


# ---------------------------------------------------------------------------
# checkpoint spec manifest
# ---------------------------------------------------------------------------

def test_checkpoint_manifest_records_partitioner_specs():
    from paddle_tpu.resilience.state import capture_training_state
    partition.configure(mesh_shape={'dp': 2, 'fsdp': 4})
    main, start, loss = _build_fsdp_program()
    scope = Scope()
    exe = fluid.Executor()
    exe.run(start, scope=scope)
    arrays, meta = capture_training_state(program=main, scope=scope,
                                          mode='copy')
    part = meta['partition']
    assert part['mesh_axes'] == {'dp': 2, 'fsdp': 4}
    assert part['axis_rules'][0][0] == 'batch'
    sharded = [n for n, e in part['specs'].items() if any(
        x is not None for x in e)]
    assert any('w_0' in n for n in sharded), part['specs']
    import json
    json.dumps(part)                              # JSON-safe by contract


def test_state_manifest_without_program():
    p = partition.configure(mesh_shape={'dp': 8})
    m = p.state_manifest()
    assert m['mesh_axes'] == {'dp': 8}
    assert 'specs' not in m
