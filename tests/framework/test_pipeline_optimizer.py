"""PipelineOptimizer lowering (ref python/paddle/fluid/optimizer.py:3405):
isomorphic stages → real SPMD GPipe over the 'pp' mesh axis; non-uniform
stages → microbatched scan with gradient accumulation. Both must match the
single-device loss trajectory."""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.parallel.mesh import make_mesh, mesh_guard


def _build_uniform(cut=True):
    """Two isomorphic fc blocks (16→16) + loss tail."""
    x = layers.data('x', [16], dtype='float32')
    y = layers.data('y', [1], dtype='float32')
    h1 = layers.fc(x, size=16, act='tanh')
    h2 = layers.fc(h1, size=16, act='tanh')
    s = layers.reduce_sum(h2, dim=1, keep_dim=True)
    loss = layers.reduce_mean(layers.square_error_cost(s, y))
    return loss, [h1, h2]


def _trajectory(pipelined, uniform, n_micro=4, steps=6, mesh=None):
    main, start = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, start):
        fluid.framework.manual_seed(11)
        if uniform:
            loss, cuts = _build_uniform()
        else:
            x = layers.data('x', [16], dtype='float32')
            y = layers.data('y', [1], dtype='float32')
            h1 = layers.fc(x, size=32, act='tanh')
            h2 = layers.fc(h1, size=8, act='tanh')
            s = layers.reduce_sum(h2, dim=1, keep_dim=True)
            loss = layers.reduce_mean(layers.square_error_cost(s, y))
            cuts = [h1]
        sgd = fluid.optimizer.SGD(learning_rate=0.05)
        if pipelined:
            opt = fluid.optimizer.PipelineOptimizer(
                sgd, cut_list=cuts, num_microbatches=n_micro)
            opt.minimize(loss)
        else:
            sgd.minimize(loss)
    exe = fluid.Executor()
    exe.run(start)
    rng = np.random.RandomState(0)
    out = []

    def run_steps():
        for _ in range(steps):
            xv = rng.standard_normal((8, 16)).astype(np.float32)
            yv = xv[:, :1].astype(np.float32)
            l, = exe.run(main, feed={'x': xv, 'y': yv}, fetch_list=[loss])
            out.append(float(np.asarray(l).reshape(())[()]))

    if mesh is not None:
        with mesh_guard(mesh):
            run_steps()
    else:
        run_steps()
    return out


def test_gpipe_mode_selected_for_uniform_stages():
    from paddle_tpu.executor import _pipeline_plan
    from paddle_tpu.framework import BACKWARD_OP_TYPE
    mesh = make_mesh({'pp': 2})
    with mesh_guard(mesh):
        main, start = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, start):
            loss, cuts = _build_uniform()
            opt = fluid.optimizer.PipelineOptimizer(
                fluid.optimizer.SGD(learning_rate=0.05), cut_list=cuts,
                num_microbatches=4)
            opt.minimize(loss)
        ops = main.global_block().ops
        bwd = next(i for i, o in enumerate(ops)
                   if o.type == BACKWARD_OP_TYPE)
        state_names = [v.name for v in main.list_vars() if v.persistable]
        plan = _pipeline_plan(main, ops[:bwd], ops[bwd], ['x', 'y'],
                              state_names)
        assert plan is not None and plan['mode'] == 'gpipe', plan


def test_pipeline_gpipe_matches_single_device():
    base = _trajectory(pipelined=False, uniform=True)
    mesh = make_mesh({'pp': 2})
    pp = _trajectory(pipelined=True, uniform=True, mesh=mesh)
    np.testing.assert_allclose(pp, base, rtol=2e-4, atol=1e-5)
    assert pp[-1] < pp[0]


def test_pipeline_scan_fallback_matches_single_device():
    base = _trajectory(pipelined=False, uniform=False)
    pp = _trajectory(pipelined=True, uniform=False)   # no pp mesh → scan
    np.testing.assert_allclose(pp, base, rtol=2e-4, atol=1e-5)
    assert pp[-1] < pp[0]


def _sum_loss_program(pipelined):
    main, start = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, start):
        fluid.framework.manual_seed(2)
        x = layers.data('x', [16], dtype='float32')
        y = layers.data('y', [1], dtype='float32')
        h1 = layers.fc(x, size=8, act='tanh')
        pred = layers.fc(h1, size=1)
        loss = layers.reduce_sum(layers.square_error_cost(pred, y))
        sgd = fluid.optimizer.SGD(learning_rate=0.001)
        if pipelined:
            fluid.optimizer.PipelineOptimizer(
                sgd, cut_list=[h1], num_microbatches=4).minimize(loss)
        else:
            sgd.minimize(loss)
    return main, start, loss, pred


def test_pipeline_scan_sum_reduced_loss_parity():
    """Sum-reduced losses must NOT be divided by num_microbatches."""
    rng = np.random.RandomState(3)
    xv = rng.standard_normal((8, 16)).astype(np.float32)
    yv = xv[:, :1].astype(np.float32)

    def run(pipelined):
        main, start, loss, _ = _sum_loss_program(pipelined)
        exe = fluid.Executor()
        exe.run(start)
        out = []
        for _ in range(4):
            l, = exe.run(main, feed={'x': xv, 'y': yv}, fetch_list=[loss])
            out.append(float(np.asarray(l).reshape(())[()]))
        return out

    np.testing.assert_allclose(run(True), run(False), rtol=2e-4, atol=1e-5)


def test_pipeline_scan_fetches_forward_intermediate():
    """Fetching a batch-major intermediate reassembles the microbatches."""
    main, start, loss, pred = _sum_loss_program(True)
    exe = fluid.Executor()
    exe.run(start)
    xv = np.random.RandomState(4).standard_normal((8, 16)).astype(np.float32)
    yv = xv[:, :1].astype(np.float32)
    pv, lv = exe.run(main, feed={'x': xv, 'y': yv},
                     fetch_list=[pred, loss])
    assert pv.shape == (8, 1)
    # parity with the unpipelined forward
    main2, start2, loss2, pred2 = _sum_loss_program(False)
    exe2 = fluid.Executor()
    exe2.run(start2)
    pv2, = exe2.run(main2, feed={'x': xv, 'y': yv}, fetch_list=[pred2])
    np.testing.assert_allclose(pv, pv2, rtol=2e-4, atol=1e-5)


def test_pipeline_mismatched_feed_dims_raise():
    main, start = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, start):
        x = layers.data('x', [16], dtype='float32')
        t = layers.data('table', [16], dtype='float32')
        y = layers.data('y', [1], dtype='float32')
        h1 = layers.fc(x, size=8, act='tanh')
        h1b = layers.elementwise_add(h1, layers.fc(t, size=8))
        pred = layers.fc(h1b, size=1)
        loss = layers.reduce_mean(layers.square_error_cost(pred, y))
        fluid.optimizer.PipelineOptimizer(
            fluid.optimizer.SGD(learning_rate=0.01), cut_list=[h1b],
            num_microbatches=4).minimize(loss)
    exe = fluid.Executor()
    exe.run(start)
    xv = np.zeros((8, 16), np.float32)
    tv = np.zeros((128, 16), np.float32)   # non-batch leading dim
    yv = np.zeros((8, 1), np.float32)
    with pytest.raises(Exception, match="leading dim"):
        exe.run(main, feed={'x': xv, 'table': tv, 'y': yv},
                fetch_list=[loss])
