"""Coverage for the remaining untested surfaces: distributed.launch,
ParallelExecutor, and regularizers (ref launch.py / parallel_executor.py /
regularizer.py)."""
import os

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers


def test_launch_helpers_single_host():
    from paddle_tpu.distributed import (get_rank, get_world_size,
                                        init_parallel_env)
    init_parallel_env()                 # single host → no-op
    assert get_rank() == 0
    assert get_world_size() == 1


def test_launch_runs_script(tmp_path):
    from paddle_tpu.distributed import launch
    script = tmp_path / 'train.py'
    out = tmp_path / 'out.txt'
    script.write_text(
        "import sys\n"
        f"open({str(out)!r}, 'w').write(' '.join(sys.argv[1:]))\n")
    launch(str(script), args=['--lr', '0.1'])
    assert out.read_text() == '--lr 0.1'


def test_parallel_executor_trains():
    """ParallelExecutor compat surface: feeds shard over the dp mesh."""
    x = layers.data('x', [8])
    y = layers.data('y', [1])
    pred = layers.fc(x, size=1)
    loss = layers.reduce_mean(layers.square_error_cost(pred, y))
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
    pe = fluid.ParallelExecutor(use_cuda=False, loss_name=loss.name)
    rng = np.random.RandomState(0)
    w = rng.standard_normal((8, 1)).astype(np.float32)
    losses = []
    for _ in range(20):
        xv = rng.standard_normal((16, 8)).astype(np.float32)
        l, = pe.run(feed={'x': xv, 'y': xv @ w}, fetch_list=[loss.name])
        losses.append(float(np.ravel(l)[0]))
    assert losses[-1] < losses[0] * 0.5


def test_l2_regularizer_changes_update():
    def run(reg):
        main, start = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, start):
            fluid.framework.manual_seed(3)
            x = layers.data('x', [4])
            pred = layers.fc(x, size=1, bias_attr=False)
            loss = layers.reduce_mean(pred)
            fluid.optimizer.SGD(learning_rate=0.1,
                                regularization=reg).minimize(loss)
        exe = fluid.Executor()
        exe.run(start)
        wname = main.all_parameters()[0].name
        w0 = np.asarray(fluid.global_scope().find(wname)).copy()
        exe.run(main, feed={'x': np.zeros((2, 4), np.float32)},
                fetch_list=[loss])
        return w0, np.asarray(fluid.global_scope().find(wname))

    w0, w_plain = run(None)
    _, w_l2 = run(fluid.regularizer.L2Decay(0.5))
    # zero input → zero data grad; L2 adds coeff*w to the grad
    np.testing.assert_allclose(w_plain, w0, atol=1e-6)
    np.testing.assert_allclose(w_l2, w0 * (1 - 0.1 * 0.5), rtol=1e-5)


def test_l1_regularizer_sign_decay():
    from paddle_tpu.regularizer import L1DecayRegularizer
    import jax.numpy as jnp
    reg = L1DecayRegularizer(0.1)
    p = jnp.asarray([1.0, -2.0, 0.0])
    g = jnp.zeros(3)
    out = np.asarray(reg.apply(p, g))
    np.testing.assert_allclose(out, [0.1, -0.1, 0.0], atol=1e-7)
