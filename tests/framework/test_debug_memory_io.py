"""Aux subsystems (SURVEY §2.11): memory_usage estimate, HBM stats report,
graphviz program debugger, profiler per-op table, Program._prune index
keying, and the legacy reader shims."""
import os

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers


def test_memory_usage_estimate():
    from paddle_tpu.contrib.memory_usage_calc import memory_usage
    x = layers.data('x', [128], dtype='float32')
    y = layers.fc(x, size=256)
    lower, upper, unit = memory_usage(fluid.default_main_program(),
                                      batch_size=32)
    assert unit in ('B', 'KB', 'MB', 'GB')
    assert 0 < lower <= upper
    # weight (128x256) + bias + x/y at bs=32: > 128KB worth of fp32
    lo2, up2, unit2 = memory_usage(fluid.default_main_program(),
                                   batch_size=64)
    # bigger batch → bigger estimate (compare in bytes)
    scale = {'B': 1, 'KB': 2**10, 'MB': 2**20, 'GB': 2**30}
    assert lo2 * scale[unit2] > lower * scale[unit]
    with pytest.raises(ValueError):
        memory_usage(fluid.default_main_program(), batch_size=0)
    with pytest.raises(TypeError):
        memory_usage('not a program', batch_size=4)


def test_device_memory_stats_shape():
    from paddle_tpu.contrib.memory_usage_calc import (device_memory_stats,
                                                      print_memory_report)
    report = device_memory_stats()
    assert isinstance(report, dict)     # may be {} on the CPU test backend
    print_memory_report()


def test_draw_block_graphviz(tmp_path):
    from paddle_tpu.debugger import draw_block_graphviz
    x = layers.data('x', [4], dtype='float32')
    y = layers.fc(x, size=2)
    loss = layers.reduce_mean(y)
    fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    path = str(tmp_path / 'g.dot')
    text = draw_block_graphviz(fluid.default_main_program().global_block(),
                               highlights=[loss.name], path=path)
    assert os.path.exists(path)
    assert text.startswith('digraph G {') and text.rstrip().endswith('}')
    assert 'fillcolor=red' in text          # highlighted loss var
    assert 'shape=box' in text and '->' in text


def test_pprint_program_codes(capsys):
    from paddle_tpu.debugger import pprint_program_codes
    x = layers.data('x', [4], dtype='float32')
    y = layers.scale(x, scale=2.0)
    text = pprint_program_codes(fluid.default_main_program())
    assert 'scale(' in text and 'data x' in text


def test_profiler_summary_table():
    import time
    from paddle_tpu import profiler
    profiler.reset_profiler()
    with profiler.record_event('fast'):
        time.sleep(0.001)
    for _ in range(3):
        with profiler.record_event('slow'):
            time.sleep(0.003)
    table = profiler.summary_table(sorted_key='total')
    lines = [l for l in table.splitlines() if l and not l.startswith('-')]
    assert lines[0].startswith('Event')
    # 'slow' has the larger total → first data row
    assert lines[1].split()[0] == 'slow'
    assert int(lines[1].split()[1]) == 3     # calls
    counts = profiler.get_op_times()
    assert counts['slow'][0] == 3


def test_prune_keeps_ops_by_index_not_signature():
    """Regression for the (type, outputs) aliasing: a later same-type op
    rewriting the same var must not survive pruning when it is dead."""
    main = fluid.Program()
    with fluid.program_guard(main, fluid.Program()):
        x = layers.data('x', [4], dtype='float32')
        blk = main.global_block()
        a = blk.create_var(name='a', shape=[-1, 4], dtype='float32')
        b = blk.create_var(name='b', shape=[-1, 4], dtype='float32')
        blk.append_op('scale', inputs={'x': 'x'}, outputs={'Out': 'a'},
                      attrs={'scale': 2.0})
        blk.append_op('scale', inputs={'x': 'a'}, outputs={'Out': 'b'},
                      attrs={'scale': 3.0})
        # dead reassignment of 'a' AFTER b is computed — same (type, outputs)
        blk.append_op('scale', inputs={'x': 'x'}, outputs={'Out': 'a'},
                      attrs={'scale': 100.0})
    pruned = main._prune(['b'])
    kept = pruned.global_block().ops
    assert len(kept) == 2, [repr(o) for o in kept]
    assert [o.attrs['scale'] for o in kept] == [2.0, 3.0]


def test_py_reader_shim_roundtrip():
    cap = 4
    r = layers.io.py_reader(capacity=cap, shapes=[(-1, 3), (-1, 1)],
                            dtypes=['float32', 'int64'], name='pr')
    feed_vars = layers.io.read_file(r)
    assert len(feed_vars) == 2
    y = layers.scale(feed_vars[0], scale=2.0)
    exe = fluid.Executor()

    def gen():
        for i in range(3):
            yield (np.full((2, 3), i, np.float32),
                   np.zeros((2, 1), np.int64))

    r.decorate_batch_generator(gen)
    seen = []
    for feed in r():          # loader yields feed dicts keyed by var name
        out, = exe.run(feed=feed, fetch_list=[y])
        seen.append(float(out[0, 0]))
    assert seen == [0.0, 2.0, 4.0]


def test_double_buffer_identity_and_load(tmp_path):
    r = object()
    assert layers.io.double_buffer(r) is r
    x = layers.data('xl', [3], dtype='float32')
    v = fluid.default_main_program().global_block().create_var(
        name='loaded_w', shape=[3], dtype='float32', persistable=True)
    arr = np.arange(3, dtype=np.float32)
    np.save(str(tmp_path / 'w.npy'), arr)
    layers.io.load(v, str(tmp_path / 'w'))
    np.testing.assert_allclose(
        np.asarray(fluid.global_scope().find('loaded_w')), arr)
