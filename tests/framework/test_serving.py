"""Serving subsystem (paddle_tpu/serving/, ISSUE 4): bucketed-batch engine
parity, micro-batcher robustness (deadlines, backpressure, malformed-request
isolation, graceful drain), and the HTTP front end.

The load-bearing guarantee is BITWISE parity: a request served through the
batcher (coalesced with strangers, padded to a bucket) returns exactly the
bytes single-request Predictor.run returns — for every bucket size and under
concurrency.
"""
import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers, serving
from paddle_tpu.inference import Predictor
from paddle_tpu.serving import (DeadlineExceeded, EngineClosed,
                                InferenceEngine, InvalidRequest, MicroBatcher,
                                Overloaded, ServingError, ServingServer,
                                bucket_ladder)

FEATURES = 8
MAX_BATCH = 8


@pytest.fixture(scope='module')
def saved_model(tmp_path_factory):
    """Tiny MLP saved as an inference model (module-scoped: the serving
    stack reloads it per engine, programs are independent of the default
    program the autouse fixture resets)."""
    main, start = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, start):
        x = layers.data('x', shape=[FEATURES], dtype='float32')
        h = layers.fc(x, 32, act='relu')
        out = layers.fc(h, 4, act='softmax')
    exe = fluid.Executor()
    path = str(tmp_path_factory.mktemp('serving') / 'model')
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(start)
        fluid.io.save_inference_model(path, ['x'], [out], exe, main)
    return path


@pytest.fixture(scope='module')
def reference(saved_model):
    """(X, per-row single-request Predictor outputs) — the bitwise oracle."""
    pred = Predictor(saved_model)
    X = np.random.RandomState(7).randn(32, FEATURES).astype(np.float32)
    refs = [pred.run([X[i:i + 1]])[0] for i in range(len(X))]
    return X, refs


# ---------------------------------------------------------------------------
# bucket ladder + engine
# ---------------------------------------------------------------------------

def test_bucket_ladder_defaults_and_validation():
    assert bucket_ladder(16) == [1, 2, 4, 8, 16]
    assert bucket_ladder(12) == [1, 2, 4, 8, 12]
    assert bucket_ladder(1) == [1]
    assert bucket_ladder(8, [2, 4, 8]) == [2, 4, 8]
    with pytest.raises(ValueError):
        bucket_ladder(8, [4, 2, 8])        # not increasing
    with pytest.raises(ValueError):
        bucket_ladder(8, [1, 2, 4])        # doesn't end at max
    with pytest.raises(ValueError):
        bucket_ladder(0)


def test_engine_parity_every_bucket(saved_model, reference):
    """run_batch at every bucket size and several padded row counts is
    bitwise-equal to single-request Predictor.run, row by row."""
    X, refs = reference
    eng = InferenceEngine(saved_model, max_batch_size=MAX_BATCH)
    assert eng.buckets == [1, 2, 4, 8]
    for bucket in eng.buckets:
        for nrows in {1, max(1, bucket - 1), bucket}:
            out, = eng.infer({'x': X[:nrows]})
            assert out.shape[0] == nrows
            for i in range(nrows):
                assert np.array_equal(out[i], refs[i][0]), \
                    f'bucket {bucket} rows {nrows} row {i} not bitwise-equal'
    # padded rows really were padded: each nrows ran at its ladder bucket
    assert eng.bucket_for(3) == 4 and eng.bucket_for(8) == 8


def test_engine_warmup_precompiles_all_buckets(saved_model):
    eng = InferenceEngine(saved_model, max_batch_size=MAX_BATCH)
    timings = eng.warmup()
    assert sorted(timings) == eng.buckets == eng.compiled_buckets
    cache_size = len(eng._exe._cache)
    assert cache_size >= len(eng.buckets)
    # traffic at any row count now hits a precompiled bucket: no new compile
    for nrows in (1, 2, 3, 5, 8):
        eng.infer({'x': np.zeros((nrows, FEATURES), np.float32)})
    assert len(eng._exe._cache) == cache_size


def test_engine_validation_rejects_before_device(saved_model):
    eng = InferenceEngine(saved_model, max_batch_size=4)
    ok = np.zeros((1, FEATURES), np.float32)
    with pytest.raises(InvalidRequest):
        eng.validate({'wrong_name': ok})
    with pytest.raises(InvalidRequest):
        eng.validate({'x': ok, 'extra': ok})
    with pytest.raises(InvalidRequest):
        eng.validate({'x': np.zeros((1, FEATURES + 1), np.float32)})
    with pytest.raises(InvalidRequest):
        eng.validate({'x': np.zeros((FEATURES,), np.float32)})  # no batch dim
    with pytest.raises(InvalidRequest):
        eng.validate({'x': [['a'] * FEATURES]})                 # non-numeric
    with pytest.raises(InvalidRequest):
        eng.validate({'x': np.zeros((0, FEATURES), np.float32)})  # empty
    with pytest.raises(InvalidRequest):
        eng.validate({'x': np.zeros((5, FEATURES), np.float32)})  # > max
    # list form maps by feed order; numeric lists cast
    feed, nrows = eng.validate([ok.tolist()])
    assert nrows == 1 and feed['x'].dtype == np.float32


# ---------------------------------------------------------------------------
# micro-batcher: e2e concurrency parity + robustness
# ---------------------------------------------------------------------------

def test_e2e_concurrent_clients_bitwise_parity(saved_model, reference):
    """The acceptance test: many threads, mixed row counts, coalesced into
    shared padded batches — every response bitwise-equals the single-request
    Predictor output for its rows."""
    X, refs = reference
    eng = InferenceEngine(saved_model, max_batch_size=MAX_BATCH)
    eng.warmup()
    results, errors = {}, []

    def client(cid, lo, nrows):
        try:
            for _ in range(5):
                out, = batcher.predict({'x': X[lo:lo + nrows]})
                results[(cid, lo, nrows)] = out
        except Exception as e:          # pragma: no cover - fail loudly
            errors.append(e)

    with MicroBatcher(eng, batch_timeout_ms=2) as batcher:
        threads = []
        for cid in range(12):
            nrows = (cid % 3) + 1       # 1-, 2-, 3-row requests interleaved
            lo = (cid * 2) % (len(X) - nrows)
            threads.append(threading.Thread(target=client,
                                            args=(cid, lo, nrows)))
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    assert not errors
    assert len(results) == 12
    for (cid, lo, nrows), out in results.items():
        for i in range(nrows):
            assert np.array_equal(out[i], refs[lo + i][0]), \
                f'client {cid} row {i} not bitwise-equal to Predictor.run'


class _StubEngine:
    """Duck-typed engine with controllable latency/failure — makes the
    robustness tests deterministic and device-free."""

    def __init__(self, delay_s=0.0, fail=False, max_batch_size=4):
        self.max_batch_size = max_batch_size
        self.delay_s = delay_s
        self.fail = fail
        self.batches = []

    def validate(self, inputs):
        arr = np.asarray(inputs['x'], np.float32)
        if arr.ndim != 2:
            raise InvalidRequest('rank')
        return {'x': arr}, arr.shape[0]

    def run_batch(self, feed, nrows=None):
        if self.delay_s:
            time.sleep(self.delay_s)
        if self.fail:
            raise RuntimeError('device on fire')
        self.batches.append(nrows)
        return [feed['x'][:nrows] * 2.0]


def test_malformed_request_never_poisons_a_batch():
    """A bad request raises at submit() — co-submitted good requests all
    complete. (Validation happens before enqueue, so there is no batch for
    the bad one to poison.)"""
    eng = _StubEngine()
    with MicroBatcher(eng, batch_timeout_ms=5) as b:
        good = [b.submit({'x': np.full((1, 3), i, np.float32)})
                for i in range(3)]
        with pytest.raises(InvalidRequest):
            b.submit({'x': np.zeros((3,), np.float32)})   # wrong rank
        more = b.submit({'x': np.full((1, 3), 9, np.float32)})
        for i, f in enumerate(good):
            assert np.array_equal(f.result(10)[0], np.full((1, 3), 2.0 * i))
        assert np.array_equal(more.result(10)[0], np.full((1, 3), 18.0))


def test_engine_failure_isolated_to_its_batch():
    """An engine error fails that batch's requests with ServingError; the
    worker survives and serves the next batch."""
    eng = _StubEngine()
    with MicroBatcher(eng, batch_timeout_ms=1) as b:
        eng.fail = True
        f1 = b.submit({'x': np.ones((1, 3), np.float32)})
        with pytest.raises(ServingError, match='device on fire'):
            f1.result(10)
        eng.fail = False
        f2 = b.submit({'x': np.ones((1, 3), np.float32)})
        assert np.array_equal(f2.result(10)[0], np.full((1, 3), 2.0))


def test_overload_typed_rejection_and_counters():
    """queue_depth bounds admission: a burst rejects with Overloaded (typed,
    immediate — no hang), admitted requests still complete, and the
    rejection counter is visible in the Prometheus export."""
    from paddle_tpu.observability import registry
    from paddle_tpu.serving import metrics as sm
    before = sm.requests_rejected_overload.value
    eng = _StubEngine(delay_s=0.05)
    rejected, futures = 0, []
    with MicroBatcher(eng, batch_timeout_ms=1, queue_depth=2) as b:
        for i in range(12):
            try:
                futures.append(b.submit({'x': np.ones((1, 3), np.float32)}))
            except Overloaded as e:
                assert 'retry' in str(e)
                rejected += 1
        for f in futures:
            f.result(30)
    assert rejected > 0 and len(futures) >= 2
    assert sm.requests_rejected_overload.value - before == rejected
    assert 'paddle_tpu_serving_requests_rejected_overload' \
        in registry.prometheus_text()


def test_deadline_expiry_drops_queued_request():
    """A request whose deadline passes while the worker is busy gets
    DeadlineExceeded and never reaches the device."""
    eng = _StubEngine(delay_s=0.15)
    with MicroBatcher(eng, batch_timeout_ms=0) as b:
        blocker = b.submit({'x': np.ones((1, 3), np.float32)})
        time.sleep(0.02)                   # worker is now inside run_batch
        doomed = b.submit({'x': np.ones((1, 3), np.float32)}, timeout_ms=20)
        with pytest.raises(DeadlineExceeded):
            doomed.result(30)
        blocker.result(30)                 # the in-flight one still lands
    assert eng.batches.count(1) == 1       # the doomed row never executed


def test_graceful_drain_completes_queued_requests():
    """close(drain=True) answers everything admitted before shutdown;
    submit() after close raises EngineClosed."""
    eng = _StubEngine(delay_s=0.03)
    b = MicroBatcher(eng, batch_timeout_ms=1, queue_depth=64)
    futures = [b.submit({'x': np.full((1, 3), i, np.float32)})
               for i in range(10)]
    b.close(drain=True)
    assert b.closed and b.pending() == 0
    for i, f in enumerate(futures):
        assert np.array_equal(f.result(1)[0], np.full((1, 3), 2.0 * i))
    with pytest.raises(EngineClosed):
        b.submit({'x': np.ones((1, 3), np.float32)})


def test_close_without_drain_fails_fast():
    eng = _StubEngine(delay_s=0.05)
    b = MicroBatcher(eng, batch_timeout_ms=0, queue_depth=64)
    futures = [b.submit({'x': np.ones((1, 3), np.float32)})
               for i in range(6)]
    b.close(drain=False)
    outcomes = {'ok': 0, 'closed': 0}
    for f in futures:
        try:
            f.result(5)
            outcomes['ok'] += 1
        except EngineClosed:
            outcomes['closed'] += 1
    assert outcomes['closed'] > 0          # queued ones failed fast
    assert outcomes['ok'] + outcomes['closed'] == 6


# ---------------------------------------------------------------------------
# HTTP front end
# ---------------------------------------------------------------------------

def _post(url, payload):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={'Content-Type': 'application/json'})
    return urllib.request.urlopen(req, timeout=30)


def test_http_server_end_to_end(saved_model, reference):
    X, refs = reference
    eng = InferenceEngine(saved_model, max_batch_size=MAX_BATCH)
    with ServingServer(eng, port=0, batch_timeout_ms=1) as srv:
        srv.start()
        url = f'http://127.0.0.1:{srv.port}'

        r = urllib.request.urlopen(url + '/healthz', timeout=30)
        health = json.loads(r.read())
        assert r.status == 200 and health['status'] == 'ok'
        assert health['buckets'] == eng.buckets

        r = _post(url + '/predict', {'inputs': {'x': X[:3].tolist()}})
        body = json.loads(r.read())
        assert r.status == 200 and body['rows'] == 3
        out = np.asarray(body['outputs'][eng.get_output_names()[0]],
                         np.float32)
        # JSON carries exact float32 values (repr round-trip): still bitwise
        for i in range(3):
            assert np.array_equal(out[i], refs[i][0])

        # malformed requests: typed 400s, never a hang
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(url + '/predict', {'inputs': {'bogus': [[1.0]]}})
        assert ei.value.code == 400
        assert json.loads(ei.value.read())['error'] == 'InvalidRequest'
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(url + '/predict', {'nope': 1})
        assert ei.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                urllib.request.Request(url + '/predict', data=b'not json{',
                                       headers={'Content-Type':
                                                'application/json'}),
                timeout=30)
        assert ei.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(url + '/nowhere', timeout=30)
        assert ei.value.code == 404

        # metrics endpoint: Prometheus text with the serving series
        r = urllib.request.urlopen(url + '/metrics', timeout=30)
        text = r.read().decode()
        assert r.status == 200
        assert 'paddle_tpu_serving_requests_accepted' in text
        assert 'paddle_tpu_serving_http_responses' in text
    assert srv.batcher.closed                  # context exit drained


def test_http_overload_maps_to_429(saved_model):
    eng = InferenceEngine(saved_model, max_batch_size=2)
    srv = ServingServer(eng, port=0, batch_timeout_ms=0, queue_depth=1)
    # deterministic overload: slow the engine down, then overfill the queue
    real_run = eng.run_batch

    def slow_run(feed, nrows=None):
        time.sleep(0.1)
        return real_run(feed, nrows)

    eng.run_batch = slow_run
    srv.start()
    url = f'http://127.0.0.1:{srv.port}/predict'
    payload = {'inputs': {'x': np.zeros((1, FEATURES)).tolist()}}
    codes = []

    def client():
        try:
            codes.append(_post(url, payload).status)
        except urllib.error.HTTPError as e:
            codes.append(e.code)

    threads = [threading.Thread(target=client) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    srv.shutdown()
    assert codes.count(200) >= 1
    assert 429 in codes, codes
    # draining server refuses: healthz already stopped
    with pytest.raises((urllib.error.URLError, ConnectionError, OSError)):
        urllib.request.urlopen(f'http://127.0.0.1:{srv.port}/healthz',
                               timeout=2)
