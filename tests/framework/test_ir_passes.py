"""Program-level IR pass pipeline (paddle_tpu/ir/): numerics parity,
idempotence, eqn-count accounting, per-pass safety rules, metrics export,
and compile-cache keying.

Parity contract: pass-on and pass-off runs of the SAME program from the
SAME initial state produce bit-identical fetches — including through
dropout, because every surviving op keeps its pre-rewrite RNG salt
(ir/pass_base.stamp_rng_salts + executor.run_seq)."""
import os
import sys

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import ir, layers as L
from paddle_tpu.compiler import BuildStrategy, CompiledProgram

sys.path.insert(0, os.path.join(
    os.path.dirname(__file__), '..', '..', 'tools'))
from bench_passes import (build_bert_layer, build_mlp_adam,  # noqa: E402
                          build_resnet_block, count_eqns)


def _fused_bs():
    bs = BuildStrategy()
    bs.fuse_elewise_add_act_ops = True
    bs.fuse_all_optimizer_ops = True
    return bs


def _snapshot(program):
    scope = fluid.global_scope()
    return {v.name: np.asarray(scope.find(v.name))
            for v in program.list_vars()
            if v.persistable and scope.find(v.name) is not None}


def _restore(snap):
    scope = fluid.global_scope()
    for k, v in snap.items():
        scope.set(k, v)


def _run_steps(program, feed, fetches, snap, passes_on, steps=3,
               build_strategy=None, seed=0):
    """Fresh Executor + restored state + reseeded RNG per mode: the ONLY
    difference between modes is the pass pipeline."""
    from paddle_tpu.core.random import seed as set_seed
    _restore(snap)
    set_seed(seed)
    old = os.environ.get('PADDLE_TPU_PASSES')
    os.environ['PADDLE_TPU_PASSES'] = '1' if passes_on else '0'
    try:
        exe = fluid.Executor()
        target = CompiledProgram(program,
                                 build_strategy=build_strategy or _fused_bs())
        outs = []
        for _ in range(steps):
            outs.append([np.asarray(o) for o in
                         exe.run(target, feed=feed, fetch_list=fetches)])
        return outs
    finally:
        if old is None:
            os.environ.pop('PADDLE_TPU_PASSES', None)
        else:
            os.environ['PADDLE_TPU_PASSES'] = old


def _assert_parity(program, feed, fetches, snap, **kw):
    a = _run_steps(program, feed, fetches, snap, False, **kw)
    b = _run_steps(program, feed, fetches, snap, True, **kw)
    for step_i, (xs, ys) in enumerate(zip(a, b)):
        for x, y in zip(xs, ys):
            np.testing.assert_array_equal(
                x, y, err_msg=f'pass-on/off diverged at step {step_i}')


# ---------------------------------------------------------------------------
# parity: the three ISSUE models
# ---------------------------------------------------------------------------

def _build_mnist_mlp():
    """MNIST-recipe MLP: two relu fc hiddens + softmax cross entropy, Adam
    (ref examples: recognize_digits). Sized down for tier-1 wall time."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = L.data('img', [64], dtype='float32')
        label = L.data('label', [1], dtype='int64')
        h = L.fc(img, size=32, act='relu')
        h = L.fc(h, size=32, act='relu')
        logits = L.fc(h, size=10)
        loss = L.reduce_mean(
            L.softmax_with_cross_entropy(logits, label))
        fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)
    rng = np.random.RandomState(0)
    feed = {'img': rng.randn(8, 64).astype(np.float32),
            'label': rng.randint(0, 10, (8, 1)).astype(np.int64)}
    return main, startup, feed, loss


def test_parity_mnist_mlp():
    main, startup, feed, loss = _build_mnist_mlp()
    fluid.Executor().run(startup)
    _assert_parity(main, feed, [loss], _snapshot(main))


def test_parity_resnet_bottleneck_block():
    main, startup, make_feed, loss = build_resnet_block(smoke=True)
    fluid.Executor().run(startup)
    _assert_parity(main, make_feed(), [loss], _snapshot(main))


def test_parity_bert_layer():
    main, startup, make_feed, loss = build_bert_layer(smoke=True)
    fluid.Executor().run(startup)
    _assert_parity(main, make_feed(), [loss], _snapshot(main))


def test_parity_through_dropout_with_dce():
    """The RNG-salt stamp: DCE removes a dead op BEFORE the dropout, which
    would shift the dropout's fold_in index — parity must survive because
    surviving ops keep their pre-rewrite salt."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = L.data('x', [16], dtype='float32')
        y = L.data('y', [1], dtype='float32')
        L.scale(x, scale=3.0)                  # dead: output never used
        h = L.fc(x, size=16, act='relu')
        h = L.dropout(h, dropout_prob=0.5)
        pred = L.fc(h, size=1)
        loss = L.reduce_mean(L.square_error_cost(pred, y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    fluid.Executor().run(startup)
    opt, ctx = ir.apply_pipeline(main, fetch_names=[loss.name])
    assert ctx.stats['dce']['removed_ops'] >= 1
    rng = np.random.RandomState(1)
    feed = {'x': rng.randn(8, 16).astype(np.float32),
            'y': rng.randn(8, 1).astype(np.float32)}
    _assert_parity(main, feed, [loss], _snapshot(main))


# ---------------------------------------------------------------------------
# idempotence & eqn-count guarantees
# ---------------------------------------------------------------------------

def _op_tuples(program):
    return [(op.type, {k: list(v) for k, v in op.inputs.items()},
             {k: list(v) for k, v in op.outputs.items()},
             {k: repr(v) for k, v in op.attrs.items()})
            for op in program.global_block().ops]


def test_pipeline_idempotent():
    main, startup, make_feed, loss = build_mlp_adam(smoke=True)
    once, _ = ir.apply_pipeline(main, fetch_names=[loss.name],
                                build_strategy=_fused_bs())
    twice, ctx2 = ir.apply_pipeline(once, fetch_names=[loss.name],
                                    build_strategy=_fused_bs())
    assert _op_tuples(once) == _op_tuples(twice)
    assert ctx2.stats['dce'] == {'removed_ops': 0, 'removed_vars': 0}


def _eqn_count(program, feed, fetches):
    import jax
    import jax.numpy as jnp
    from paddle_tpu.executor import _lower
    scope = fluid.global_scope()
    state = {v.name: jnp.asarray(scope.find(v.name))
             for v in program.list_vars() if v.persistable}
    feed_vals = {k: jnp.asarray(v) for k, v in feed.items()}
    step = _lower(program, sorted(feed_vals), fetches, sorted(state))
    j = jax.make_jaxpr(step)({}, state, feed_vals, jax.random.PRNGKey(0))
    return count_eqns(j.jaxpr)


def test_fused_optimizer_and_dce_strictly_shrink_adam_program():
    main, startup, make_feed, loss = build_mlp_adam(smoke=True)
    fluid.Executor().run(startup)
    feed = make_feed()
    base = _eqn_count(main, feed, [loss.name])
    opt, ctx = ir.apply_pipeline(main, fetch_names=[loss.name],
                                 build_strategy=_fused_bs())
    assert ctx.stats['fuse_all_optimizer_ops']['fused_groups'] >= 1
    fused = _eqn_count(opt, feed, [loss.name])
    assert fused < base, (base, fused)
    # the multi-param Adam acceptance margin (PERF.md §10)
    assert 1 - fused / base >= 0.30, (base, fused)
    assert len(opt.global_block().ops) < len(main.global_block().ops)


def test_dce_removes_dead_ops_and_vars():
    main = fluid.Program()
    with fluid.program_guard(main, fluid.Program()):
        x = L.data('x', [4], dtype='float32')
        live = L.scale(x, scale=2.0)
        d1 = L.scale(x, scale=5.0)             # dead chain root
        L.elementwise_add(d1, d1)              # dead consumer
    opt, ctx = ir.apply_pipeline(main, fetch_names=[live.name])
    assert ctx.stats['dce']['removed_ops'] == 2
    assert [op.type for op in opt.global_block().ops] == ['scale']
    assert not opt.global_block().has_var(d1.name)
    # original program untouched
    assert len(main.global_block().ops) == 3


def test_dce_keeps_persistable_writes_and_fetches():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = L.data('x', [4], dtype='float32')
        acc = fluid.layers.tensor.create_global_var(
            [4], 0.0, 'float32', persistable=True, name='acc_var')
        # write to persistable state: never dead, even though nothing
        # downstream reads it
        main.global_block().append_op(
            'elementwise_add', inputs={'x': acc.name, 'y': x.name},
            outputs={'Out': acc.name}, attrs={})
        out = L.scale(x, scale=2.0)
    opt, _ = ir.apply_pipeline(main, fetch_names=[out.name])
    assert [op.type for op in opt.global_block().ops] == \
        ['elementwise_add', 'scale']


# ---------------------------------------------------------------------------
# constant folding
# ---------------------------------------------------------------------------

def test_constant_folding_collapses_fill_scale_cast_chain():
    from paddle_tpu.layers import tensor as T
    main = fluid.Program()
    with fluid.program_guard(main, fluid.Program()):
        x = L.data('x', [4], dtype='float32')
        c = T.fill_constant([4], 'float32', 2.0)
        s = L.scale(c, scale=3.0, bias=1.0)          # → 7.0
        cst = L.cast(s, 'float32')
        y = L.elementwise_add(x, cst)
    opt, ctx = ir.apply_pipeline(main, fetch_names=[y.name])
    assert ctx.stats['constant_fold']['folded_ops'] == 2
    kinds = [op.type for op in opt.global_block().ops]
    assert kinds == ['fill_constant', 'elementwise_add']
    assert float(opt.global_block().ops[0].attrs['value']) == 7.0
    xv = np.ones((2, 4), np.float32)
    out, = fluid.Executor().run(main, feed={'x': xv}, fetch_list=[y])
    np.testing.assert_array_equal(out, xv + 7.0)


def test_constant_folding_respects_reassignment():
    """A var rewritten by a non-constant op between producer and consumer
    must not fold (current-value dataflow)."""
    from paddle_tpu.framework import Operator
    from paddle_tpu.layers import tensor as T
    main = fluid.Program()
    with fluid.program_guard(main, fluid.Program()):
        x = L.data('x', [4], dtype='float32')
        c = T.fill_constant([4], 'float32', 2.0)
        blk = main.global_block()
        # overwrite c with a runtime value, THEN scale it
        blk.append_op('elementwise_add', inputs={'x': c.name, 'y': x.name},
                      outputs={'Out': c.name}, attrs={})
        y = L.scale(c, scale=3.0)
    opt, ctx = ir.apply_pipeline(main, fetch_names=[y.name])
    assert ctx.stats['constant_fold']['folded_ops'] == 0
    assert [op.type for op in opt.global_block().ops] == \
        ['fill_constant', 'elementwise_add', 'scale']


# ---------------------------------------------------------------------------
# fuse_elewise_add_act safety
# ---------------------------------------------------------------------------

def _add_relu_program(fetch_mid=False):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = L.data('x', [8], dtype='float32')
        h = L.fc(x, size=8, act='relu')       # mul + add + relu
        out = L.reduce_sum(h)
    return main, startup, h, out


def test_fuse_add_act_fuses_fc_bias_relu():
    main, _, _, out = _add_relu_program()
    bs = BuildStrategy()
    bs.fuse_elewise_add_act_ops = True
    opt, ctx = ir.apply_pipeline(main, fetch_names=[out.name],
                                 build_strategy=bs)
    kinds = [op.type for op in opt.global_block().ops]
    assert 'fused_elemwise_add_activation' in kinds
    assert 'relu' not in kinds and 'elementwise_add' not in kinds
    assert ctx.stats['fuse_elewise_add_act']['fused_pairs'] == 1


def test_fuse_add_act_skips_fetched_intermediate():
    """The add's output is observable (fetched) → must not be fused away."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = L.data('x', [8], dtype='float32')
        y = L.data('y', [8], dtype='float32')
        mid = L.elementwise_add(x, y)
        out = L.relu(mid)
    bs = BuildStrategy()
    bs.fuse_elewise_add_act_ops = True
    opt, ctx = ir.apply_pipeline(main, fetch_names=[out.name, mid.name],
                                 build_strategy=bs)
    kinds = [op.type for op in opt.global_block().ops]
    assert 'fused_elemwise_add_activation' not in kinds


def test_fuse_add_act_requires_flag():
    main, _, _, out = _add_relu_program()
    opt, _ = ir.apply_pipeline(main, fetch_names=[out.name])  # default bs
    assert 'fused_elemwise_add_activation' not in \
        [op.type for op in opt.global_block().ops]


# ---------------------------------------------------------------------------
# fuse_all_optimizer_ops safety
# ---------------------------------------------------------------------------

def test_fuse_optimizer_groups_by_hyperparameters():
    """Two Adam families with different betas must not merge into one
    bundle (their updates are not interchangeable)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = L.data('x', [8], dtype='float32')
        y = L.data('y', [1], dtype='float32')
        h = L.fc(x, size=8)
        pred = L.fc(h, size=1)
        loss = L.reduce_mean(L.square_error_cost(pred, y))
        opt1 = fluid.optimizer.Adam(learning_rate=1e-3, beta1=0.9)
        opt2 = fluid.optimizer.Adam(learning_rate=1e-3, beta1=0.8)
        params = main.all_parameters()
        grads = opt1.backward(loss)
        half = len(grads) // 2
        opt1.apply_gradients(grads[:half])
        opt2.apply_gradients(grads[half:])
    bs = BuildStrategy()
    bs.fuse_all_optimizer_ops = True
    opt, ctx = ir.apply_pipeline(main, fetch_names=[loss.name],
                                 build_strategy=bs)
    fused = [op for op in opt.global_block().ops
             if op.type == 'fused_adam']
    assert len(fused) == 2
    betas = sorted(op.attrs['beta1'] for op in fused)
    assert betas == [0.8, 0.9]


def test_fused_state_roundtrips_through_scope():
    """Slots updated through the fused op land back in the scope under
    their per-param names (checkpoint/save_persistables compatibility)."""
    main, startup, make_feed, loss = build_mlp_adam(smoke=True, layers_n=2)
    fluid.Executor().run(startup)
    snap = _snapshot(main)
    _run_steps(main, make_feed(), [loss], snap, True, steps=2)
    scope = fluid.global_scope()
    pow_names = [n for n in snap if 'beta1_pow' in n]
    assert pow_names
    for n in pow_names:
        # two fused steps: beta1_pow advanced from 0.9 to 0.9^3
        np.testing.assert_allclose(np.asarray(scope.find(n)),
                                   np.asarray([0.9 ** 3]), rtol=1e-6)


# ---------------------------------------------------------------------------
# wiring: env escape hatch, cache keying, metrics
# ---------------------------------------------------------------------------

def test_env_escape_hatch_disables_pipeline(monkeypatch):
    main, _, _, out = _add_relu_program()
    monkeypatch.setenv('PADDLE_TPU_PASSES', '0')
    opt, ctx = ir.apply_pipeline(main, fetch_names=[out.name],
                                 build_strategy=_fused_bs())
    assert opt is main            # untouched, not even cloned
    assert ctx.stats == {}
    assert ir.pipeline_signature(_fused_bs()) == ()


def test_env_selects_explicit_pass_list(monkeypatch):
    monkeypatch.setenv('PADDLE_TPU_PASSES', 'dce,constant_fold')
    assert ir.build_pipeline().names() == ('constant_fold', 'dce')
    assert ir.pipeline_signature(None) == ('dce', 'constant_fold')


def test_pass_signature_keys_the_executor_cache():
    main, startup, feed, loss = _build_mnist_mlp()
    exe = fluid.Executor()
    exe.run(startup)
    exe.run(main, feed=feed, fetch_list=[loss])
    assert len(exe._cache) == 1
    bs = _fused_bs()
    exe.run(CompiledProgram(main, build_strategy=bs), feed=feed,
            fetch_list=[loss])
    # fuse flags changed the pipeline signature → fresh lowering
    assert len(exe._cache) == 2


def test_ir_pass_metrics_exported():
    from paddle_tpu import observability as obs
    main, startup, make_feed, loss = build_mlp_adam(smoke=True, layers_n=2)
    fluid.Executor().run(startup)
    with obs.telemetry_guard(True):
        obs.reset()
        exe = fluid.Executor()
        exe.run(CompiledProgram(main, build_strategy=_fused_bs()),
                feed=make_feed(), fetch_list=[loss])
        metrics = obs.registry.to_dict()
    assert 'ir_pass_applied_total' in metrics
    applied = {s['labels']['pass'] for s in
               metrics['ir_pass_applied_total']['samples']}
    assert {'constant_fold', 'fuse_elewise_add_act',
            'fuse_all_optimizer_ops', 'dce'} <= applied
    assert 'ir_pass_seconds' in metrics
    assert 'ir_pass_pipeline_runs' in metrics
