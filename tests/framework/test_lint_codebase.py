"""Repo-level codebase lint (tools/lint_codebase.py): the paddle_tpu/
tree satisfies its own invariants, and the AST walker actually detects
each violation class (seeded-file probes)."""
import os
import sys
import textwrap

sys.path.insert(0, os.path.join(
    os.path.dirname(__file__), '..', '..', 'tools'))
from lint_codebase import lint_file, lint_tree  # noqa: E402

_REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), '..', '..'))


def test_repo_is_clean():
    """The enforced invariants hold across paddle_tpu/ — any new bare
    print, non-atomic payload save, or cache-bypassing jax.jit fails
    tier-1 with the file:line."""
    violations = lint_tree(_REPO)
    assert violations == [], '\n'.join(v.format() for v in violations)


def _probe(tmp_path, body):
    p = tmp_path / 'paddle_tpu' / 'probe.py'
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(body))
    return lint_file(str(p), 'paddle_tpu/probe.py')


def test_detects_bare_print(tmp_path):
    vs = _probe(tmp_path, '''
        def f():
            print("leak")
        ''')
    assert [v.rule for v in vs] == ['bare-print']
    assert vs[0].line == 3


def test_detects_non_atomic_save(tmp_path):
    vs = _probe(tmp_path, '''
        import numpy as np
        def f(d):
            np.savez('/tmp/x.npz', **d)
        ''')
    assert [v.rule for v in vs] == ['atomic-io']


def test_detects_cache_bypassing_jit(tmp_path):
    vs = _probe(tmp_path, '''
        import jax
        step = jax.jit(lambda x: x)
        ''')
    assert [v.rule for v in vs] == ['jit-compile-cache']


def test_jit_ok_with_cache_setup(tmp_path):
    vs = _probe(tmp_path, '''
        import jax
        from paddle_tpu.core.compile_cache import setup_persistent_cache
        setup_persistent_cache()
        step = jax.jit(lambda x: x)
        ''')
    assert vs == []


def test_detects_direct_mesh_construction(tmp_path):
    vs = _probe(tmp_path, '''
        import numpy as np
        from jax.sharding import Mesh
        m = Mesh(np.array([0]), ('dp',))
        ''')
    assert [v.rule for v in vs] == ['mesh-construction']


def test_mesh_construction_allowed_in_partition(tmp_path):
    p = tmp_path / 'paddle_tpu' / 'partition' / 'probe.py'
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent('''
        import numpy as np
        from jax.sharding import Mesh
        m = Mesh(np.array([0]), ('dp',))
        '''))
    assert lint_file(str(p), 'paddle_tpu/partition/probe.py') == []


def test_suppression_markers(tmp_path):
    vs = _probe(tmp_path, '''
        import numpy as np
        def f(d):
            print("table")  # lint: allow-print (console API)
            # lint: allow-io (test fixture)
            np.savez('/tmp/x.npz', **d)
        ''')
    assert vs == []
