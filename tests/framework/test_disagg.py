"""Disaggregated prefill/decode (paddle_tpu/serving/tier/disagg.py):
handoff parity vs colocated, the serializable payload seam, failure
isolation, decode-not-stalled behavior, and the PADDLE_TPU_DISAGG knob."""
import time

import numpy as np
import pytest

from paddle_tpu.dygraph import guard
from paddle_tpu.models.causal_lm import greedy_generate
from paddle_tpu.serving import DecodeScheduler, ServingError
from paddle_tpu.serving.tier.disagg import (KVPayload, LocalPrefillWorker,
                                            PrefillReplica)
from paddle_tpu.serving.tier.replica import build_replica_stack, build_tiny_lm


@pytest.fixture(scope='module')
def lm():
    with guard():
        yield build_tiny_lm()


def _counter(name):
    from paddle_tpu.observability import registry
    d = registry.to_dict().get(name)
    if not d or not d['samples']:
        return 0.0
    return sum(s['value'] for s in d['samples'])


def test_disagg_env_strict_parse(lm, monkeypatch):
    monkeypatch.setenv('PADDLE_TPU_DISAGG', 'on')
    with pytest.raises(ValueError, match="'0', '1'"):
        build_replica_stack(model=lm)
    monkeypatch.setenv('PADDLE_TPU_DISAGG', '1')
    eng, sched, worker = build_replica_stack(model=lm)
    try:
        assert worker is not None and sched.disagg is worker
    finally:
        sched.close()
        worker.close()


def test_handoff_parity_vs_colocated_and_reference(lm):
    """The acceptance bar: generations whose prefill ran on a DIFFERENT
    engine (own pool, shipped KV blocks) are bitwise-identical to the
    colocated path and to the uncached whole-sequence reference."""
    prompts = [[7, 3, 11, 5, 9], [2, 44, 8, 13], [1, 2, 3], [9] * 7]
    eng_d, sched_d, worker = build_replica_stack(model=lm, disagg=True)
    refs = [greedy_generate(lm, p, 6, pad_len=eng_d.padded_context)
            for p in prompts]
    h0 = _counter('disagg_handoffs')
    try:
        outs = [sched_d.submit(p, max_new_tokens=6).result(120)
                for p in prompts]
    finally:
        sched_d.close()
        worker.close()
    assert outs == refs
    assert _counter('disagg_handoffs') - h0 == len(prompts)
    eng_c, sched_c, _ = build_replica_stack(model=lm, disagg=False)
    try:
        colocated = [sched_c.submit(p, max_new_tokens=6).result(120)
                     for p in prompts]
    finally:
        sched_c.close()
    assert colocated == outs
    assert eng_d.pool.allocator.used == 0     # handoff requests clean up


def test_payload_wire_roundtrip(lm):
    """to_bytes/from_bytes is the cross-host seam: arrays, context length,
    first token, and block size all survive exactly."""
    eng, sched, worker = build_replica_stack(model=lm, disagg=False)
    sched.close()
    replica = PrefillReplica(eng)
    pay = replica.prefill_to_payload([5, 6, 7, 8, 9], 0)
    assert eng.pool.allocator.used == 0       # prefill pool is scratch
    clone = KVPayload.from_bytes(pay.to_bytes())
    assert clone.context_len == 5
    assert clone.first_token == pay.first_token
    assert clone.block_size == pay.block_size
    assert len(clone.layers) == len(pay.layers) == eng.pool.num_layers
    for (k1, v1), (k2, v2) in zip(pay.layers, clone.layers):
        assert np.array_equal(k1, k2) and np.array_equal(v1, v2)
    assert pay.nbytes > 0


def test_handoff_failure_is_typed_and_isolated(lm):
    """A prefill-replica blowup fails exactly that request with a typed
    ServingError; the decode loop keeps serving the next request."""
    eng, sched, worker = build_replica_stack(model=lm, disagg=True)
    prefill_eng = worker.replicas[0].engine
    real = prefill_eng.prefill
    boom = {'armed': True}

    def flaky(prompt, table):
        if boom['armed']:
            boom['armed'] = False
            raise RuntimeError('injected prefill-replica failure')
        return real(prompt, table)

    prefill_eng.prefill = flaky
    f0 = _counter('disagg_handoff_failures')
    try:
        s1 = sched.submit([1, 2, 3], max_new_tokens=4)
        with pytest.raises(ServingError):
            s1.result(120)
        s2 = sched.submit([4, 5, 6], max_new_tokens=4)
        assert len(s2.result(120)) == 4
    finally:
        sched.close()
        worker.close()
    assert _counter('disagg_handoff_failures') - f0 == 1
    assert eng.pool.allocator.used == 0


def test_decode_keeps_stepping_while_prefill_pending(lm):
    """The disaggregation point: a slow prefill must not stall the
    lockstep decode loop — an active stream finishes its whole generation
    while the handoff is still in flight."""
    eng, sched, worker = build_replica_stack(model=lm, disagg=True)
    replica = worker.replicas[0]
    real = replica.prefill_to_payload

    def slow(prompt, max_new):
        if len(prompt) > 4:                   # only the long prompt is slow
            time.sleep(2.0)
        return real(prompt, max_new)

    replica.prefill_to_payload = slow
    try:
        fast = sched.submit([1, 2], max_new_tokens=8)
        next(fast.iter_tokens(timeout=60))              # it is decoding
        slow_s = sched.submit([5, 6, 7, 8, 9], max_new_tokens=4)
        assert len(fast.result(120)) == 8
        assert not slow_s.done(), \
            'fast stream must finish while the slow handoff is pending'
        assert len(slow_s.result(120)) == 4
    finally:
        sched.close()
        worker.close()


def test_disagg_with_prefix_cache_skips_handoff_on_hit(lm):
    """Cache hits are served by suffix fill on the decode engine — no
    second handoff for a repeated prompt."""
    eng, sched, worker = build_replica_stack(model=lm, disagg=True,
                                             prefix_cache=True)
    prompt = [7, 3, 11, 5, 9, 2, 44, 8, 13]
    ref = greedy_generate(lm, prompt, 5, pad_len=eng.padded_context)
    h0 = _counter('disagg_handoffs')
    try:
        assert sched.submit(prompt, max_new_tokens=5).result(120) == ref
        assert _counter('disagg_handoffs') - h0 == 1
        assert sched.submit(prompt, max_new_tokens=5).result(120) == ref
        assert _counter('disagg_handoffs') - h0 == 1    # hit: no handoff
    finally:
        sched.close()
        worker.close()
    assert _counter('prefix_cache_hits') > 0


def test_disagg_metrics_exported(lm):
    from paddle_tpu.observability import registry
    eng, sched, worker = build_replica_stack(model=lm, disagg=True)
    try:
        sched.submit([1, 2, 3], max_new_tokens=2).result(120)
    finally:
        sched.close()
        worker.close()
    d = registry.to_dict()
    for name in ('disagg_handoffs', 'disagg_handoff_seconds',
                 'disagg_kv_bytes', 'disagg_pending'):
        assert name in d, f'missing disagg metric {name}'
