"""ADVICE r5 slim regressions: Compressor must seed weights from
`init_model` (not silently train from random init), and SAController with a
latency constraint must survive the epoch-end checkpoint pickle."""
import pickle

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.contrib.slim.core import Compressor
from paddle_tpu.contrib.slim.nas import LightNASStrategy, SearchSpace
from paddle_tpu.contrib.slim.searcher import SAController


def _classifier_program():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data(name='x', shape=[4, 3], dtype='float32')
        y = fluid.layers.fc(input=x, size=2, name='clf')
        loss = fluid.layers.reduce_mean(fluid.layers.square(y))
    return main, startup, loss


def test_compressor_loads_init_model(tmp_path):
    # 1) pretrain: init, overwrite the weight with a sentinel, save
    main, startup, loss = _classifier_program()
    exe = fluid.Executor()
    exe.run(startup)
    scope = fluid.global_scope()
    wname = next(v.name for v in main.list_vars()
                 if v.persistable and '.w_' in v.name)
    sentinel = np.full_like(np.asarray(scope.find(wname)), 0.625)
    scope.set(wname, sentinel)
    fluid.io.save_persistables(exe, str(tmp_path / 'init'), main)

    # 2) fresh scope + re-initialized program (name generator reset: a new
    #    process rebuilds the net with identical var names): random weights
    import paddle_tpu.core.scope as scope_mod
    from paddle_tpu.core import unique_name
    scope_mod._global_scope = scope_mod.Scope()
    unique_name.generator = unique_name.UniqueNameGenerator()
    main2, startup2, loss2 = _classifier_program()
    exe2 = fluid.Executor()
    exe2.run(startup2)
    wname2 = next(v.name for v in main2.list_vars()
                  if v.persistable and '.w_' in v.name)
    assert not np.allclose(
        np.asarray(fluid.global_scope().find(wname2)), sentinel)

    # 3) Compressor.run() with init_model must load the pretrained weights
    #    before the (absent) checkpoint resume — no training (no reader)
    comp = Compressor(train_program=main2, train_reader=None,
                      train_feed_list=['x'], train_fetch_list=[loss2],
                      epoch=1, init_model=str(tmp_path / 'init'))
    comp.run()
    np.testing.assert_allclose(
        np.asarray(fluid.global_scope().find(wname2)), sentinel)


def test_compressor_missing_init_model_raises(tmp_path):
    main, startup, loss = _classifier_program()
    fluid.Executor().run(startup)
    comp = Compressor(train_program=main, train_reader=None,
                      train_feed_list=['x'], train_fetch_list=[loss],
                      epoch=1, init_model=str(tmp_path / 'nope'))
    with pytest.raises(ValueError, match='init_model'):
        comp.run()


class _Space(SearchSpace):
    def init_tokens(self):
        return [0, 0]

    def range_table(self):
        return [3, 3]

    def create_net(self, tokens):
        return None, ('prog', tuple(tokens)), None, None, None

    def get_model_latency(self, program):
        return float(sum(program[1]))


def test_sacontroller_with_constraint_pickles():
    strat = LightNASStrategy(target_latency=2.0, search_steps=1)
    space = _Space()
    strat.controller.reset(space.range_table(), space.init_tokens(),
                           strat._constrain(space))
    assert strat.controller._constrain_func is not None
    blob = pickle.dumps([strat])            # the epoch-end checkpoint path
    (restored,) = pickle.loads(blob)
    assert restored.controller._constrain_func is None
    # controller still searches without the constraint...
    toks = restored.controller.next_tokens()
    assert len(toks) == 2

    # ...and restore_from_checkpoint rebuilds it from the live context
    class _Ctx:
        search_space = space
    restored.restore_from_checkpoint(_Ctx())
    fn = restored.controller._constrain_func
    assert fn is not None
    assert fn([1, 1]) and not fn([2, 2])    # latency 2.0 <= vs 4.0 >

    # constrained next_tokens only proposes feasible candidates again
    restored.controller.reset(space.range_table(), [0, 0], fn)
    for _ in range(5):
        assert fn(restored.controller.next_tokens())


def test_sacontroller_state_roundtrip_preserves_search_state():
    c = SAController(seed=0)
    c.reset([4, 4], [1, 2], constrain_func=lambda t: True)
    c.update([1, 2], reward=0.5)
    c2 = pickle.loads(pickle.dumps(c))
    assert c2.best_tokens == [1, 2]
    assert c2.max_reward == 0.5
    assert c2._iter == c._iter
