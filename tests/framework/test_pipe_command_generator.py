"""The reference's pipe_command contract: fluid.dataset shells out to a
data_generator script that reads raw lines on stdin and emits MultiSlot
lines on stdout (ref: fluid/incubate/data_generator usage with
dataset.set_pipe_command). Exercises a REAL subprocess pipe."""
import os
import sys
import textwrap

import numpy as np

import paddle_tpu as fluid
import paddle_tpu.layers as L


GEN_SCRIPT = textwrap.dedent("""\
    import sys
    sys.path.insert(0, {repo!r})
    from paddle_tpu.incubate.data_generator import MultiSlotDataGenerator

    class Gen(MultiSlotDataGenerator):
        def generate_sample(self, line):
            def it():
                toks = [int(x) for x in line.split()]
                yield ("words", toks[:-1]), ("label", [toks[-1]])
            return it

    Gen().run_from_stdin()
""")


def test_pipe_command_generator_roundtrip(tmp_path):
    repo = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    gen_py = tmp_path / 'my_generator.py'
    gen_py.write_text(GEN_SCRIPT.format(repo=repo))

    # RAW data file (not MultiSlot): the pipe command transforms it
    rng = np.random.RandomState(0)
    lines = []
    for _ in range(16):
        words = rng.randint(1, 30, 4)
        lines.append(' '.join(map(str, words)) + f' {int(words.sum() % 2)}')
    raw = tmp_path / 'raw.txt'
    raw.write_text('\n'.join(lines) + '\n')

    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        words = fluid.data('words', [4, 4], 'int64')
        label = fluid.data('label', [4, 1], 'int64')
        emb = L.embedding(words, size=[30, 6])
        loss = L.reduce_mean(L.fc(L.reduce_mean(emb, dim=1), size=1))
        fluid.optimizer.SGD(0.1).minimize(loss)

    dataset = fluid.DatasetFactory().create_dataset('QueueDataset')
    dataset.set_batch_size(4)
    dataset.set_use_var([words, label])
    dataset.set_pipe_command(f'{sys.executable} {gen_py}')
    dataset.set_filelist([str(raw)])

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    exe.train_from_dataset(program=prog, dataset=dataset)
    w = np.asarray(fluid.global_scope().find(
        prog.all_parameters()[0].name))
    assert np.isfinite(w).all()
