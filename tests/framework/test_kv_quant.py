"""Quantized KV cache (PADDLE_TPU_KV_DTYPE): strict knob parsing, the
f32-is-bitwise / int8-match-rate quality contract, int8 interaction with
speculative-decode rollback and the disaggregated handoff wire format, and
the planner-backed pool sizing solve (PADDLE_TPU_DECODE_HBM_MB vs the
closed form, with the explicit MAX_BLOCKS overrides winning)."""
import numpy as np
import pytest

from paddle_tpu.dygraph import guard
from paddle_tpu.models.causal_lm import greedy_generate
from paddle_tpu.serving import DecodeEngine, DecodeScheduler
from paddle_tpu.serving.tier.disagg import KVPayload, PrefillReplica
from paddle_tpu.serving.tier.replica import build_replica_stack, build_tiny_lm


@pytest.fixture(scope='module')
def lm():
    with guard():
        yield build_tiny_lm()


def make_engine(model, **kw):
    kw.setdefault('slots', 2)
    kw.setdefault('block_size', 4)
    kw.setdefault('max_blocks', 64)
    kw.setdefault('max_prompt_len', 16)
    kw.setdefault('max_new_tokens_cap', 16)
    return DecodeEngine(model, **kw)


def _counter(name):
    from paddle_tpu.observability import registry
    d = registry.to_dict().get(name)
    if not d or not d['samples']:
        return 0.0
    return sum(s['value'] for s in d['samples'])


_WORK = [([7, 3, 11, 5, 9], 8), ([2, 44, 8, 13], 6), ([9] * 7, 10),
         ([1, 2, 3], 5)]


def _run(engine, work=_WORK):
    with DecodeScheduler(engine, queue_depth=len(work) + 1) as sched:
        streams = [sched.submit(p, max_new_tokens=m) for p, m in work]
        return [s.result(240) for s in streams]


# -- strict knob parsing ---------------------------------------------------

def test_kv_dtype_env_strict_parse(lm, monkeypatch):
    monkeypatch.setenv('PADDLE_TPU_KV_DTYPE', 'fp8')
    with pytest.raises(ValueError, match='PADDLE_TPU_KV_DTYPE') as e:
        make_engine(lm)
    assert 'int8' in str(e.value)                 # names the supported set
    for env, storage in (('f32', 'float32'), ('bf16', 'bfloat16'),
                         ('int8', 'int8')):
        monkeypatch.setenv('PADDLE_TPU_KV_DTYPE', env)
        eng = make_engine(lm)
        assert eng.pool.kv_dtype == env
        assert eng.pool.dtype == storage
    # an explicit argument wins over the env knob
    monkeypatch.setenv('PADDLE_TPU_KV_DTYPE', 'f32')
    assert make_engine(lm, kv_dtype='int8').pool.kv_dtype == 'int8'


def test_decode_hbm_mb_env_strict_parse(lm, monkeypatch):
    monkeypatch.setenv('PADDLE_TPU_DECODE_HBM_MB', 'lots')
    with pytest.raises(ValueError, match='PADDLE_TPU_DECODE_HBM_MB'):
        make_engine(lm, max_blocks=None)
    monkeypatch.setenv('PADDLE_TPU_DECODE_HBM_MB', '0')
    with pytest.raises(ValueError, match='integers >= 1'):
        make_engine(lm, max_blocks=None)


def test_prefix_cache_host_mb_env_strict_parse(lm, monkeypatch):
    from paddle_tpu.serving import PrefixCache
    eng = make_engine(lm)
    monkeypatch.setenv('PADDLE_TPU_PREFIX_CACHE_HOST_MB', 'big')
    with pytest.raises(ValueError, match='PADDLE_TPU_PREFIX_CACHE_HOST_MB'):
        PrefixCache(eng.pool)
    monkeypatch.setenv('PADDLE_TPU_PREFIX_CACHE_HOST_MB', '-1')
    with pytest.raises(ValueError, match='integers >= 0'):
        PrefixCache(eng.pool)
    monkeypatch.setenv('PADDLE_TPU_PREFIX_CACHE_HOST_MB', '2')
    assert PrefixCache(eng.pool).host_bytes == 0  # configured, still empty


# -- quality contract ------------------------------------------------------

def test_f32_pool_bitwise_and_untouched(lm):
    """f32 storage is the pre-quantization path exactly: generations match
    the whole-sequence reference bitwise, the pool dtype is float32, no
    scale arrays exist, and _encode_rows passes values through UNTOUCHED
    (object identity — the no-cast, no-copy guarantee)."""
    eng = make_engine(lm)
    refs = [greedy_generate(lm, p, m, pad_len=eng.padded_context)
            for p, m in _WORK]
    assert _run(eng) == refs
    assert eng.pool.dtype == 'float32'
    assert all(eng.pool.scales(layer) is None
               for layer in range(eng.pool.num_layers))
    import jax.numpy as jnp
    vals = jnp.ones((2, 3, 8), jnp.float32)
    enc, sc = eng.pool._encode_rows(vals)
    assert enc is vals and sc is None


@pytest.mark.parametrize('dtype', ['bf16', 'int8'])
def test_quantized_greedy_match_rate(lm, dtype):
    """Lossy storage keeps the greedy trajectory: ≥ 0.99 token-level match
    against the f32 reference (docs/SERVING.md quality contract). Length
    divergence counts against the rate."""
    eng = make_engine(lm, kv_dtype=dtype)
    refs = [greedy_generate(lm, p, m, pad_len=eng.padded_context)
            for p, m in _WORK]
    outs = _run(eng)
    matched = sum(sum(a == b for a, b in zip(o, r))
                  for o, r in zip(outs, refs))
    total = sum(len(r) for r in refs)
    assert matched / total >= 0.99, (outs, refs)
    if dtype == 'int8':
        assert all(eng.pool.scales(layer) is not None
                   for layer in range(eng.pool.num_layers))
    assert eng.pool.bytes_in_hbm() > 0


def test_int8_spec_decode_rollback_parity(lm):
    """Speculative verify + rollback over an int8 pool: the (S, k) verify
    rows read DEQUANTIZED keys, the rollback re-quantizes the accepted
    window — the trajectory must equal the int8 LOCKSTEP engine's (the
    spec machinery may not add quantization error on top)."""
    lockstep = _run(make_engine(lm, kv_dtype='int8'))
    r0 = _counter('decode_spec_rounds')
    spec = _run(make_engine(lm, kv_dtype='int8', spec_decode=True))
    assert spec == lockstep
    assert _counter('decode_spec_rounds') > r0   # spec path actually ran


def test_int8_disagg_handoff_parity(lm, monkeypatch):
    """Disaggregated prefill at int8: the payload ships the QUANTIZED pages
    + scales, the decode pool scatters them byte-exactly — generations
    equal the colocated int8 engine's."""
    monkeypatch.setenv('PADDLE_TPU_KV_DTYPE', 'int8')
    eng_d, sched_d, worker = build_replica_stack(model=lm, disagg=True)
    try:
        assert eng_d.pool.kv_dtype == 'int8'
        outs = [sched_d.submit(p, max_new_tokens=m).result(240)
                for p, m in _WORK]
    finally:
        sched_d.close()
        worker.close()
    eng_c, sched_c, _ = build_replica_stack(model=lm, disagg=False)
    try:
        colocated = [sched_c.submit(p, max_new_tokens=m).result(240)
                     for p, m in _WORK]
    finally:
        sched_c.close()
    assert outs == colocated
    assert eng_d.pool.allocator.used == 0


def test_int8_payload_wire_roundtrip(lm):
    eng = make_engine(lm, kv_dtype='int8')
    pay = PrefillReplica(eng).prefill_to_payload([5, 6, 7, 8, 9], 0)
    assert pay.kv_dtype == 'int8' and pay.scales is not None
    clone = KVPayload.from_bytes(pay.to_bytes())
    assert clone.kv_dtype == 'int8'
    for (k1, v1), (k2, v2) in zip(pay.layers, clone.layers):
        assert k2.dtype == np.int8 and v2.dtype == np.int8
        assert np.array_equal(k1, k2) and np.array_equal(v1, v2)
    for (ks1, vs1), (ks2, vs2) in zip(pay.scales, clone.scales):
        assert ks2.dtype == np.float32
        assert np.array_equal(ks1, ks2) and np.array_equal(vs1, vs2)
    # int8 payload + f32 scales beat the f32 bytes they replace
    f32 = PrefillReplica(make_engine(lm)).prefill_to_payload(
        [5, 6, 7, 8, 9], 0)
    assert pay.nbytes < f32.nbytes / 2


def test_legacy_three_int_meta_parses_as_f32():
    """Pre-quantization senders wrote meta = [ctx, first, bs]: the reader
    must accept it as an f32 payload with no scales (rolling-upgrade
    compatibility of the cross-host seam)."""
    import io
    arrays = {'meta': np.asarray([5, 42, 4], np.int64),
              'k0': np.zeros((2, 2, 4, 8), np.float32),
              'v0': np.zeros((2, 2, 4, 8), np.float32)}
    buf = io.BytesIO()
    np.savez(buf, **arrays)  # lint: allow-io (in-memory BytesIO)
    pay = KVPayload.from_bytes(buf.getvalue())
    assert pay.kv_dtype == 'f32' and pay.scales is None
    assert pay.context_len == 5 and pay.first_token == 42
    assert pay.block_size == 4


# -- planner-backed pool sizing --------------------------------------------

def test_budget_solve_matches_closed_form(lm):
    from paddle_tpu.analysis.plan import (decode_pool_block_bytes,
                                          decode_pool_report,
                                          solve_decode_pool_blocks)
    state = sum(getattr(p, 'value', p).nbytes for p in lm.parameters())
    for dtype in ('f32', 'bf16', 'int8'):
        block_bytes = decode_pool_block_bytes(lm, 4, dtype)
        closed = ((8 << 20) - state) // block_bytes
        solved = solve_decode_pool_blocks(lm, 8, block_size=4,
                                          kv_dtype=dtype)
        assert abs(solved - closed) <= 1, (dtype, solved, closed)
        rep = decode_pool_report(lm, 8, block_size=4, kv_dtype=dtype)
        assert rep['num_blocks'] == solved
        assert rep['num_blocks'] * rep['block_bytes'] <= (8 << 20) - state
    # int8 rows are head_dim + 4 scale bytes -> strictly more blocks
    assert (solve_decode_pool_blocks(lm, 8, block_size=4, kv_dtype='int8')
            > solve_decode_pool_blocks(lm, 8, block_size=4, kv_dtype='f32'))


def test_budget_sizes_engine_pool(lm, monkeypatch):
    from paddle_tpu.analysis.plan import solve_decode_pool_blocks
    monkeypatch.setenv('PADDLE_TPU_DECODE_HBM_MB', '8')
    eng = make_engine(lm, max_blocks=None)
    expect = solve_decode_pool_blocks(
        lm, 8, block_size=4, kv_dtype='f32',
        min_blocks=eng.pool.max_blocks_per_seq + 1)
    assert eng.pool.num_blocks == expect


def test_explicit_max_blocks_wins_over_budget(lm, monkeypatch):
    monkeypatch.setenv('PADDLE_TPU_DECODE_HBM_MB', '8')
    assert make_engine(lm, max_blocks=50).pool.num_blocks == 50
    monkeypatch.setenv('PADDLE_TPU_DECODE_MAX_BLOCKS', '77')
    assert make_engine(lm, max_blocks=None).pool.num_blocks == 77


def test_budget_smaller_than_state_raises(lm):
    from paddle_tpu.analysis.plan import solve_decode_pool_blocks
    with pytest.raises(ValueError, match='model state'):
        solve_decode_pool_blocks(lm, 0, block_size=4)


# -- analysis wiring -------------------------------------------------------

def _paged_op_cost(inputs, in_slots, op_type='paged_attention'):
    import paddle_tpu.fluid as fluid
    from paddle_tpu.analysis.cost import op_cost
    from paddle_tpu.analysis.infer import VarInfo, infer_op
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        blk = main.global_block()
        env = {}
        for name, (shape, dtype) in inputs.items():
            blk.create_var(name=name, shape=shape, dtype=dtype)
            env[name] = VarInfo(shape, dtype)
        op = blk.append_op(op_type, inputs=in_slots,
                           outputs={'Out': ['o']}, attrs={})
        env['o'] = infer_op(op, env, blk)['Out']
        return op_cost(op, env, blk)


def test_paged_attention_cost_prices_quantized_pool():
    """The generic byte model prices an int8 pool as 1 B/elem payload plus
    4 B/row scales — the pool-bytes delta vs f32 is exactly the storage
    saving (3.56x at head_dim 32), and the scale slots must be typed f32
    rank 3 matching the pages (InferError otherwise)."""
    from paddle_tpu.analysis.infer import InferError
    H, NB, BS, D, S, nbs = 2, 8, 16, 32, 3, 4
    base = {'q': ((S, H, D), 'float32'),
            'kp': ((H, NB, BS, D), 'float32'),
            'vp': ((H, NB, BS, D), 'float32'),
            'bt': ((S, nbs), 'int32'), 'cl': ((S,), 'int32')}
    slots = {'q': ['q'], 'k_pages': ['kp'], 'v_pages': ['vp'],
             'block_tables': ['bt'], 'context_lens': ['cl']}
    c32 = _paged_op_cost(base, slots)
    t_pad = nbs * BS
    assert c32.flops == S * H * t_pad * (4 * D + 8 + 2)

    q8 = dict(base, kp=((H, NB, BS, D), 'int8'), vp=((H, NB, BS, D), 'int8'),
              ks=((H, NB, BS), 'float32'), vs=((H, NB, BS), 'float32'))
    s8 = dict(slots, k_scales=['ks'], v_scales=['vs'])
    c8 = _paged_op_cost(q8, s8)
    assert c8.flops == c32.flops + 2 * S * H * t_pad * D  # dequant term
    pool_f32 = 2 * H * NB * BS * D * 4
    pool_i8 = 2 * H * NB * BS * (D + 4)                   # 1 B/elem + 4 B/row
    assert c32.bytes_in - c8.bytes_in == pool_f32 - pool_i8

    for bad in ({'ks': ((H, NB, BS), 'int32')},           # wrong dtype
                {'ks': ((H, NB), 'float32')},             # wrong rank
                {'ks': ((H, NB + 1, BS), 'float32')}):    # shape mismatch
        with pytest.raises(InferError, match='k_scales'):
            _paged_op_cost(dict(q8, **bad), s8)
