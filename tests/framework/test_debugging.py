"""debugging.py: NaN/Inf detection, device report, install_check
(SURVEY §2.11 failure handling; ref nan_inf_utils + install_check)."""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import debugging


def test_check_numerics_passes_and_raises():
    debugging.check_numerics(np.ones((3, 3), np.float32))
    debugging.check_numerics({'a': np.zeros(2), 'b': np.ones(2)})
    bad = np.array([1.0, np.nan, np.inf], np.float32)
    with pytest.raises(FloatingPointError, match='1 NaN, 1 Inf'):
        debugging.check_numerics(bad, 'grads')
    with pytest.raises(FloatingPointError):
        debugging.check_numerics({'ok': np.ones(2), 'bad': bad})


def test_assert_all_finite_poisons():
    import jax.numpy as jnp
    x = jnp.asarray([1.0, 2.0])
    np.testing.assert_allclose(
        np.asarray(debugging.assert_all_finite(x)), [1.0, 2.0])
    y = jnp.asarray([1.0, jnp.inf])
    out = np.asarray(debugging.assert_all_finite(y))
    assert np.isnan(out).all()     # whole tensor poisoned, unmissable


def test_enable_check_nan_inf_toggles():
    import jax
    debugging.enable_check_nan_inf(True)
    assert debugging.check_nan_inf_enabled()
    assert jax.config.jax_debug_nans
    debugging.enable_check_nan_inf(False)
    assert not debugging.check_nan_inf_enabled()
    assert not jax.config.jax_debug_nans


def test_device_report_contents():
    rep = debugging.device_report()
    assert 'jax' in rep and 'backend' in rep and 'devices' in rep


def test_install_check_end_to_end(capsys):
    # routed through log_helper instead of print(): capture via the logger
    import io
    import logging
    stream = io.StringIO()
    handler = logging.StreamHandler(stream)
    log = logging.getLogger('paddle_tpu.debugging')
    log.addHandler(handler)
    try:
        assert debugging.install_check() is True
    finally:
        log.removeHandler(handler)
    assert 'install check passed' not in capsys.readouterr().out
    assert 'install check passed' in stream.getvalue()
