"""Telemetry subsystem (paddle_tpu/observability/, docs/OBSERVABILITY.md):
metrics registry semantics + Prometheus round-trip, chrome-trace span trees,
spine instrumentation (executor phases, donation counts, compile-cache
hit/miss, DataLoader starvation, nonfinite detections), the disabled-path
zero-work guard, and the profiler kernel-cache stats-reset regression."""
import json
import math
import threading

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import debugging, dygraph, layers, observability as obs
from paddle_tpu import profiler
from paddle_tpu.observability.metrics import MetricsRegistry
from paddle_tpu.observability.tracer import StepTracer


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    """Telemetry off + empty registry/tracer around every test."""
    old = obs._ENABLED
    obs._ENABLED = False
    obs.reset()
    yield
    obs._ENABLED = old
    obs.reset()


def _run_tiny_program(steps=2, feed_x=None):
    main, start = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, start):
        x = layers.data('ob_x', shape=[4], dtype='float32')
        y = layers.data('ob_y', shape=[1], dtype='float32')
        loss = layers.mean(layers.square_error_cost(layers.fc(x, 1), y))
        fluid.optimizer.SGDOptimizer(0.1).minimize(loss)
    exe = fluid.Executor()
    exe.run(start)
    out = None
    for _ in range(steps):
        out, = exe.run(main, feed={
            'ob_x': feed_x if feed_x is not None
            else np.ones((8, 4), 'float32'),
            'ob_y': np.zeros((8, 1), 'float32')}, fetch_list=[loss])
    return out


# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------

def test_counter_gauge_histogram_basics():
    reg = MetricsRegistry()
    c = reg.counter('events', 'help text')
    c.inc()
    c.inc(2.5)
    assert c.value == 3.5
    g = reg.gauge('depth')
    g.set(7)
    g.set(3)
    assert g.value == 3
    h = reg.histogram('lat_seconds', bounds=(0.01, 0.1, 1.0))
    for v in (0.005, 0.05, 0.5, 5.0, 0.05):
        h.observe(v)
    s = h.labels().sample()
    assert s['buckets'] == [1, 2, 1, 1]       # last bucket = +Inf overflow
    assert s['count'] == 5 and s['min'] == 0.005 and s['max'] == 5.0
    assert abs(s['sum'] - 5.605) < 1e-9
    # same name returns the same metric; kind mismatch is an error
    assert reg.counter('events') is c
    with pytest.raises(TypeError):
        reg.gauge('events')


def test_labeled_series_are_distinct():
    reg = MetricsRegistry()
    c = reg.counter('ops')
    c.labels(op='matmul').inc(3)
    c.labels(op='relu').inc()
    d = reg.to_dict()['ops']
    by_op = {s['labels']['op']: s['value'] for s in d['samples']}
    assert by_op == {'matmul': 3, 'relu': 1}


def test_registry_thread_safety():
    reg = MetricsRegistry()
    c = reg.counter('n')
    h = reg.histogram('h', bounds=(1.0,))

    def work():
        for _ in range(1000):
            c.inc()
            h.observe(0.5)

    threads = [threading.Thread(target=work) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == 8000
    assert h.labels().sample()['count'] == 8000


def _parse_prometheus(text):
    """Tiny exposition-format parser: name{labels} value per sample."""
    types, samples = {}, {}
    for line in text.splitlines():
        if line.startswith('# TYPE'):
            _, _, name, kind = line.split()
            types[name] = kind
        elif line and not line.startswith('#'):
            metric, value = line.rsplit(' ', 1)
            samples[metric] = float(value)
    return types, samples


def test_prometheus_exposition_round_trips():
    reg = MetricsRegistry()
    reg.counter('steps', 'steps run').inc(4)
    reg.gauge('queue_depth').labels(loader='a').set(2.5)
    h = reg.histogram('wait_seconds', bounds=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(50.0)
    types, samples = _parse_prometheus(reg.prometheus_text())
    assert types['paddle_tpu_steps'] == 'counter'
    assert types['paddle_tpu_queue_depth'] == 'gauge'
    assert types['paddle_tpu_wait_seconds'] == 'histogram'
    assert samples['paddle_tpu_steps'] == 4
    assert samples['paddle_tpu_queue_depth{loader="a"}'] == 2.5
    # histogram buckets are CUMULATIVE; +Inf equals _count
    assert samples['paddle_tpu_wait_seconds_bucket{le="0.1"}'] == 1
    assert samples['paddle_tpu_wait_seconds_bucket{le="1.0"}'] == 2
    assert samples['paddle_tpu_wait_seconds_bucket{le="+Inf"}'] == 3
    assert samples['paddle_tpu_wait_seconds_count'] == 3
    assert abs(samples['paddle_tpu_wait_seconds_sum'] - 50.55) < 1e-9


def test_collectors_run_at_export():
    reg = MetricsRegistry()
    reg.register_collector(lambda: reg.gauge('snap').set(42))
    assert reg.to_dict()['snap']['samples'][0]['value'] == 42


# ---------------------------------------------------------------------------
# tracer
# ---------------------------------------------------------------------------

def test_tracer_chrome_trace_span_tree():
    tr = StepTracer()
    with tr.span('parent', step=1):
        with tr.span('child_a'):
            pass
        with tr.span('child_b'):
            pass
    doc = json.loads(tr.chrome_trace_json())
    events = {e['name']: e for e in doc['traceEvents']}
    assert set(events) == {'parent', 'child_a', 'child_b'}
    p = events['parent']
    assert p['ph'] == 'X' and p['args'] == {'step': 1}
    # tree = [ts, ts+dur] containment on one tid (how Perfetto nests X events)
    for name in ('child_a', 'child_b'):
        c = events[name]
        assert c['tid'] == p['tid']
        assert p['ts'] <= c['ts']
        assert c['ts'] + c['dur'] <= p['ts'] + p['dur'] + 1e-3


def test_tracer_bounds_events():
    tr = StepTracer(max_events=3)
    for i in range(5):
        with tr.span(f's{i}'):
            pass
    assert len(tr) == 3 and tr.dropped == 2
    assert json.loads(tr.chrome_trace_json())['otherData'][
        'dropped_events'] == 2


# ---------------------------------------------------------------------------
# disabled path: zero telemetry work (the ≤3% bench_dispatch budget is met
# structurally — one bool check per dispatch, nothing else runs)
# ---------------------------------------------------------------------------

def test_disabled_dispatch_does_no_telemetry_work(monkeypatch):
    def boom(*a, **k):
        raise AssertionError('telemetry touched while disabled')

    monkeypatch.setattr(obs, 'record_op_dispatch', boom)
    monkeypatch.setattr(obs.tracer, 'span', boom)
    with dygraph.guard():
        t = dygraph.to_variable(np.ones((2, 2), np.float32))
        dygraph.dispatch_op('scale', {'x': t}, {'scale': 2.0})
    assert obs.registry.to_dict().get('tape_dispatch_seconds') is None
    assert len(obs.tracer) == 0


def test_disabled_executor_records_nothing():
    _run_tiny_program(steps=1)
    d = obs.registry.to_dict()
    assert 'executor_steps' not in d
    assert len(obs.tracer) == 0
    assert obs.span('x') is obs.NULL_SPAN      # shared no-op, no allocation


# ---------------------------------------------------------------------------
# spine instrumentation (telemetry on)
# ---------------------------------------------------------------------------

def test_executor_phases_and_counters(tmp_path):
    with obs.telemetry_guard(True, directory=str(tmp_path)):
        _run_tiny_program(steps=2)
        d = obs.registry.to_dict()
        trace = obs.tracer.snapshot()

    def val(name):
        return d[name]['samples'][0]['value']

    assert val('executor_steps') == 2
    assert val('compile_cache_misses') == 1     # program compiled once
    assert val('compile_cache_hits') == 1       # second step reuses it
    assert val('executor_donated_buffers') > 0  # params/slots donated
    assert val('executor_feed_bytes') > 0 and val('executor_fetch_bytes') > 0
    assert d['executor_compile_seconds']['samples'][0]['count'] == 1
    names = [e['name'] for e in trace['traceEvents']]
    for phase in ('executor/run', 'executor/prepare', 'executor/lower',
                  'executor/execute', 'executor/fetch'):
        assert phase in names, names
    # one complete span tree per run (startup + 2 steps), phases nested
    # under executor/run by [ts, ts+dur] containment on the same tid
    runs = [e for e in trace['traceEvents'] if e['name'] == 'executor/run']
    assert len(runs) == 3
    execs = [e for e in trace['traceEvents']
             if e['name'] == 'executor/execute']
    assert len(execs) == 2
    assert all(any(r['ts'] <= e['ts'] and
                   e['ts'] + e['dur'] <= r['ts'] + r['dur'] + 1e-3 and
                   e['tid'] == r['tid']
                   for r in runs)
               for e in execs)
    # per-step structured log got one JSONL record per run
    lines = (tmp_path / 'steps.jsonl').read_text().splitlines()
    recs = [json.loads(ln) for ln in lines]
    assert len(recs) == 2
    assert {'kind', 'step', 'donated', 'execute_s'} <= set(recs[0])


def test_tape_dispatch_histogram_on():
    from paddle_tpu.dygraph.tape import kernel_cache
    kernel_cache.clear()        # cold cache: first dispatch must be a miss
    with obs.telemetry_guard(True):
        with dygraph.guard():
            t = dygraph.to_variable(np.ones((2, 2), np.float32))
            for _ in range(4):
                dygraph.dispatch_op('scale', {'x': t}, {'scale': 2.0})
        d = obs.registry.to_dict()
    samples = d['tape_dispatch_seconds']['samples']
    by_cached = {s['labels']['cached']: s for s in samples
                 if s['labels']['op'] == 'scale'}
    # first dispatch misses the kernel cache, the rest hit
    assert by_cached['false']['count'] >= 1
    assert by_cached['true']['count'] >= 2
    # kernel-cache counters surface as gauges via the export collector
    ek = {s['labels']['stat']: s['value']
          for s in d['eager_kernel_cache']['samples']}
    assert ek['hits'] >= 2 and ek['enabled'] == 1


def test_train_step_spans():
    from paddle_tpu.dygraph.jit import TrainStep
    from paddle_tpu.dygraph.nn import Linear
    with obs.telemetry_guard(True):
        with dygraph.guard():
            model = Linear(4, 2)
            opt = fluid.optimizer.SGD(0.1,
                                      parameter_list=model.parameters())

            def loss_fn(m, x):
                out = m(x)
                return dygraph.dispatch_op('reduce_mean',
                                           {'x': out * out}, {})

            step = TrainStep(model, loss_fn, opt)
            x = np.ones((3, 4), np.float32)
            step(x)
            step(x)
        names = [e['name'] for e in obs.tracer.snapshot()['traceEvents']]
        d = obs.registry.to_dict()
    assert names.count('train_step/call') == 2
    assert names.count('train_step/build') == 1     # compiled once
    assert 'train_step/execute' in names
    assert d['train_step_calls']['samples'][0]['value'] == 2


def test_dataloader_wait_metrics():
    with obs.telemetry_guard(True):
        loader = fluid.DataLoader.from_generator(capacity=4)

        def gen():
            for i in range(3):
                yield {'lx': np.full((2, 2), i, np.float32)}

        loader.set_batch_generator(gen)
        batches = list(loader)
        d = obs.registry.to_dict()
    assert len(batches) == 3
    assert d['dataloader_batches']['samples'][0]['value'] == 3
    assert d['dataloader_wait_seconds']['samples'][0]['count'] >= 3
    assert 'dataloader_last_wait_seconds' in d
    assert d['dataloader_staged_bytes']['samples'][0]['value'] == 3 * 16


def test_nonfinite_detection_counter_and_span():
    # env-flag style: scan-fetches path (jax_debug_nans stays off)
    old = debugging._check_enabled
    debugging._check_enabled = True
    try:
        with obs.telemetry_guard(True):
            bad = np.full((8, 4), np.nan, 'float32')
            with pytest.raises(FloatingPointError, match='check_nan_inf'):
                _run_tiny_program(steps=1, feed_x=bad)
            d = obs.registry.to_dict()
            names = [e['name'] for e in obs.tracer.snapshot()['traceEvents']]
    finally:
        debugging._check_enabled = old
    assert d['nonfinite_detections']['samples'][0]['value'] >= 1
    assert 'executor/check_nan_inf' in names
    assert 'nonfinite_detected' in names


# ---------------------------------------------------------------------------
# profiler satellites
# ---------------------------------------------------------------------------

def test_reset_stats_keeps_warm_kernels():
    """Regression (ISSUE 2 satellite): resetting the eager kernel-cache
    stats between two back-to-back profiled runs must NOT drop the compiled
    kernels — the second run stays warm (0 misses), with fresh counters."""
    from paddle_tpu.dygraph.tape import kernel_cache
    kernel_cache.clear()
    with dygraph.guard():
        t = dygraph.to_variable(np.ones((2, 2), np.float32))
        for _ in range(3):
            dygraph.dispatch_op('scale', {'x': t}, {'scale': 2.0})
        assert kernel_cache.stats()['misses'] == 1
        profiler.reset_eager_kernel_cache_stats()
        s = kernel_cache.stats()
        assert (s['hits'], s['misses'], s['evictions'], s['bypasses']) \
            == (0, 0, 0, 0)
        assert s['size'] == 1                   # kernels survived the reset
        for _ in range(3):
            dygraph.dispatch_op('scale', {'x': t}, {'scale': 2.0})
        s = kernel_cache.stats()
        assert s['misses'] == 0 and s['hits'] == 3
    kernel_cache.clear()
    s = kernel_cache.stats()
    assert s['size'] == 0 and s['hits'] == 0    # clear() zeroes BOTH


def test_stop_profiler_logs_not_prints(capsys):
    # capture the module logger itself (log_helper handlers hold whatever
    # stderr existed at import — attach our own to be deterministic)
    import io
    import logging
    stream = io.StringIO()
    handler = logging.StreamHandler(stream)
    log = logging.getLogger('paddle_tpu.profiler')
    log.addHandler(handler)
    try:
        profiler.reset_profiler()
        profiler.start_profiler(state='CPU')
        with profiler.record_event('obs_region'):
            pass
        profiler.stop_profiler(sorted_key='calls')
    finally:
        log.removeHandler(handler)
    assert 'obs_region' not in capsys.readouterr().out   # print() is gone
    assert 'obs_region' in stream.getvalue()             # logged instead


# ---------------------------------------------------------------------------
# artifacts
# ---------------------------------------------------------------------------

def test_dump_artifacts_and_prom_file(tmp_path):
    with obs.telemetry_guard(True, directory=str(tmp_path)):
        _run_tiny_program(steps=1)
        paths = obs.dump_artifacts()
    doc = json.loads((tmp_path / 'trace.json').read_text())
    assert doc['traceEvents']
    md = json.loads((tmp_path / 'metrics.json').read_text())['metrics']
    assert 'executor_steps' in md
    types, samples = _parse_prometheus((tmp_path / 'metrics.prom')
                                       .read_text())
    assert samples['paddle_tpu_executor_steps'] == 1
    assert set(paths) >= {'metrics', 'prometheus', 'trace'}
    for frac in samples.values():
        assert not math.isnan(frac)
