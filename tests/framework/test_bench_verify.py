"""tools/bench_verify.py smoke in tier-1: the static verifier's cost is
program-build-time only — ≤2% of the cold lower+compile it rides on, and
invisible (~1.0×) on the warm step path."""
import os
import sys

sys.path.insert(0, os.path.join(
    os.path.dirname(__file__), '..', '..', 'tools'))


def test_verify_overhead_smoke():
    from bench_verify import measure_all
    r = measure_all(iters=3, smoke=True)
    frac = r['verify_overhead']
    assert frac['verify_seconds'] > 0, 'verifier never ran'
    # acceptance: build-time share ≤ 2% (ISSUE 10); smoke sizes have the
    # LEAST compile to amortize against, so full size only gets better
    assert frac['verify_frac_of_compile'] <= 0.02, frac
    # warm steps never touch the verifier. The steps are sub-ms host
    # dispatches, so even best-of-N carries scheduler noise under a loaded
    # tier-1 session — the bound only guards against something CATASTROPHIC
    # landing on the step path (the real ratio is ~1.0, PERF.md §17)
    assert frac['warm_step_ratio'] < 3.0, frac
    ab = r['verify_pipeline_ab']
    assert ab['pipeline_on_s'] >= ab['pipeline_off_s'] * 0.5  # sane A/B
