"""Static AMP: cast insertion per white/black lists, fused dynamic loss
scaling, inf-step skipping. Ref parity: python/paddle/fluid/contrib/
mixed_precision/fp16_utils.py:156 (rewrite_program), :283
(update_loss_scaling)."""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.contrib import mixed_precision as mp


def _build(lr=0.1):
    x = layers.data('x', [8], dtype='float32')
    label = layers.data('y', [1], dtype='float32')
    h = layers.fc(x, size=16, act='relu')
    pred = layers.fc(h, size=1)
    loss = layers.reduce_mean(layers.square_error_cost(pred, label))
    return loss


def test_bf16_amp_casts_visible_in_hlo():
    """White-list ops (mul/matmul behind fc) must run in bf16: the lowered
    HLO carries bf16 convert/dot ops while master params stay fp32."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.executor import _lower
    main, start = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, start):
        loss = _build()
        opt = mp.decorate(fluid.optimizer.SGD(learning_rate=0.1),
                          dtype='bfloat16')
        opt.minimize(loss)
    exe = fluid.Executor()
    exe.run(start)
    state_names = sorted(v.name for v in main.list_vars() if v.persistable)
    state = {n: jnp.asarray(fluid.global_scope().find(n))
             for n in state_names}
    for n, v in state.items():
        if jnp.issubdtype(v.dtype, jnp.floating):
            assert v.dtype == jnp.float32  # master weights
    feeds = {'x': jnp.zeros((4, 8), jnp.float32),
             'y': jnp.zeros((4, 1), jnp.float32)}
    step = _lower(main, list(feeds), [loss.name], state_names)
    hlo = jax.jit(step).lower(state, {}, feeds,
                              jax.random.PRNGKey(0)).as_text()
    assert 'bf16' in hlo, "no bf16 in lowered HLO — AMP casts not applied"


def test_bf16_amp_trains_close_to_fp32():
    np.random.seed(0)
    xv = np.random.randn(16, 8).astype(np.float32)
    yv = (xv[:, :1] * 0.5 + 0.1).astype(np.float32)

    def run(amp):
        main, start = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, start):
            fluid.framework.manual_seed(7)
            loss = _build()
            sgd = fluid.optimizer.SGD(learning_rate=0.1)
            (mp.decorate(sgd, dtype='bfloat16') if amp else sgd).minimize(loss)
        exe = fluid.Executor()
        exe.run(start)
        losses = []
        for _ in range(10):
            l, = exe.run(main, feed={'x': xv, 'y': yv}, fetch_list=[loss])
            losses.append(float(l))
        return losses

    base = run(False)
    amp = run(True)
    assert amp[-1] < amp[0] * 0.8                 # it trains
    assert abs(amp[-1] - base[-1]) < 0.1 * max(abs(base[0]), 1e-3)


def test_fp16_dynamic_loss_scaling_skips_inf_steps():
    """Feed an inf batch: the fused finite-check must skip the update and
    decrease the loss scale; params stay unchanged."""
    main, start = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, start):
        loss = _build()
        opt = mp.decorate(fluid.optimizer.SGD(learning_rate=0.1),
                          dtype='float16', init_loss_scaling=2.**10,
                          decr_every_n_nan_or_inf=1)
        opt.minimize(loss)
    exe = fluid.Executor()
    exe.run(start)
    pname = main.all_parameters()[0].name
    w0 = np.asarray(fluid.global_scope().find(pname)).copy()
    scale0 = float(np.asarray(
        fluid.global_scope().find(opt._scale_var.name)).reshape(())[()])
    bad = np.full((4, 8), np.inf, np.float32)
    yv = np.zeros((4, 1), np.float32)
    exe.run(main, feed={'x': bad, 'y': yv}, fetch_list=[loss])
    w1 = np.asarray(fluid.global_scope().find(pname))
    scale1 = float(np.asarray(
        fluid.global_scope().find(opt._scale_var.name)).reshape(())[()])
    np.testing.assert_array_equal(w0, w1)        # step skipped
    assert scale1 < scale0                       # scale decreased

    # a good batch then updates params and the step trains
    good = np.random.RandomState(0).randn(4, 8).astype(np.float32)
    exe.run(main, feed={'x': good, 'y': yv}, fetch_list=[loss])
    w2 = np.asarray(fluid.global_scope().find(pname))
    assert np.abs(w2 - w1).max() > 0


def test_fp16_loss_scaling_matches_unscaled_trajectory():
    """With finite grads, scaling then unscaling must reproduce the plain
    fp32 SGD trajectory (modulo fp16 cast noise on white ops)."""
    np.random.seed(1)
    xv = np.random.randn(8, 8).astype(np.float32)
    yv = np.random.randn(8, 1).astype(np.float32)

    def run(amp):
        main, start = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, start):
            fluid.framework.manual_seed(3)
            loss = _build()
            sgd = fluid.optimizer.SGD(learning_rate=0.05)
            if amp:
                mp.decorate(sgd, dtype='float16',
                            init_loss_scaling=2.**8).minimize(loss)
            else:
                sgd.minimize(loss)
        exe = fluid.Executor()
        exe.run(start)
        out = []
        for _ in range(8):
            l, = exe.run(main, feed={'x': xv, 'y': yv}, fetch_list=[loss])
            out.append(float(l))
        return out

    base, amp = run(False), run(True)
    assert amp[-1] < amp[0]
    np.testing.assert_allclose(amp, base, rtol=0.1, atol=0.05)


def test_dygraph_amp_decorate_trains():
    """Dygraph decorate(): finite-check + skip/step bookkeeping wraps the
    inner optimizer (the dygraph path is a fused finiteness gate — the
    loss itself stays fp32; static mode owns the cast rewrite). Training
    must proceed normally through the wrapper, and `incr_every_n_steps`
    consecutive good steps must grow the dynamic scale."""
    from paddle_tpu import dygraph
    from paddle_tpu.contrib import mixed_precision as mp

    fluid.manual_seed(7)
    rng = np.random.RandomState(0)
    X = rng.rand(32, 4).astype('float32')
    W = np.array([[1.0], [-2.0], [0.5], [3.0]], 'float32')
    Y = X @ W
    with dygraph.guard():
        model = dygraph.Linear(4, 1)
        opt = mp.decorate(
            fluid.optimizer.Adam(0.05,
                                 parameter_list=model.parameters()),
            init_loss_scaling=4.0, incr_every_n_steps=10,
            incr_ratio=2.0, dtype='float16')
        losses = []
        for _ in range(40):
            pred = model(dygraph.to_variable(X))
            loss = fluid.layers.reduce_mean(
                fluid.layers.square_error_cost(
                    pred, dygraph.to_variable(Y)))
            losses.append(float(loss.numpy()))
            loss.backward()
            opt.minimize(loss)
            model.clear_gradients()
        assert losses[-1] < losses[0] * 0.3
        # 40 good steps at incr_every=10 → scale doubled 4 times
        assert opt.get_loss_scaling() == pytest.approx(4.0 * 2 ** 4)


def test_dygraph_amp_skips_inf_and_decays_scale():
    from paddle_tpu import dygraph
    from paddle_tpu.contrib import mixed_precision as mp

    with dygraph.guard():
        model = dygraph.Linear(2, 1)
        opt = mp.decorate(
            fluid.optimizer.SGD(0.1, parameter_list=model.parameters()),
            init_loss_scaling=4.0, decr_every_n_nan_or_inf=1,
            dtype='float16')
        w0 = np.asarray(model.parameters()[0].numpy()).copy()
        x = dygraph.to_variable(
            np.array([[1e30, 1e30]], 'float32'))   # 1e30*1e30 > fp32 max
        pred = model(x)
        loss = fluid.layers.reduce_mean(pred) * 1e30
        s0 = opt.get_loss_scaling()
        loss.backward()
        opt.minimize(loss)
        model.clear_gradients()
        w1 = np.asarray(model.parameters()[0].numpy())
        np.testing.assert_allclose(w0, w1)        # inf step skipped
        assert opt.get_loss_scaling() < s0        # scale decayed


def test_dygraph_amp_skips_inf_even_without_dynamic_scaling():
    from paddle_tpu import dygraph
    from paddle_tpu.contrib import mixed_precision as mp

    with dygraph.guard():
        model = dygraph.Linear(2, 1)
        opt = mp.decorate(
            fluid.optimizer.SGD(0.1, parameter_list=model.parameters()),
            use_dynamic_loss_scaling=False, dtype='float16')
        w0 = np.asarray(model.parameters()[0].numpy()).copy()
        x = dygraph.to_variable(np.array([[1e30, 1e30]], 'float32'))
        loss = fluid.layers.reduce_mean(model(x)) * 1e30
        loss.backward()
        opt.minimize(loss)
        model.clear_gradients()
        np.testing.assert_allclose(
            np.asarray(model.parameters()[0].numpy()), w0)
