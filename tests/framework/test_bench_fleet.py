"""tools/bench_fleet.py --smoke in tier-1: the weak-scaling bench spawns
REAL 1- and 2-process jax.distributed fleets through the executor spine
and must produce a well-formed summary with sane numbers. The ≥0.8
efficiency acceptance is for the FULL (compute-bound) sizes recorded in
PERF.md §18; smoke shrinks compute ~6×, so the collective-launch latency
floor shows through and the smoke bar is correspondingly lower."""
import json
import os
import subprocess
import sys

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), '..', '..'))


def test_bench_fleet_smoke():
    env = dict(os.environ, JAX_PLATFORMS='cpu', PYTHONPATH=REPO)
    env.pop('XLA_FLAGS', None)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, 'tools', 'bench_fleet.py'),
         '--smoke'],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=420)
    assert r.returncode == 0, r.stderr[-3000:]
    lines = [json.loads(l) for l in r.stdout.splitlines()
             if l.strip().startswith('{')]
    runs = [l for l in lines if l['bench'] == 'fleet_weak_scaling']
    summary = [l for l in lines
               if l['bench'] == 'fleet_weak_scaling_summary'][-1]
    assert {r_['nproc'] for r_ in runs} == {1, 2}
    for r_ in runs:
        assert r_['steps_per_s'] > 0
        assert r_['global_batch'] == 2048 * r_['nproc']
    eff2 = summary['efficiency']['2']
    # smoke floor: the fleet must deliver a real fraction of perfect
    # timesharing even at smoke compute (full-size acceptance is 0.8)
    assert eff2 >= 0.35, summary
    assert summary['efficiency']['1'] == 1.0
