"""BERT model tests: tied MLM decoder, pretrain loss, functionalized forward.

Reference parity: the LARK-style BERT the reference benchmarks — the MLM
output projection reuses the word-embedding matrix (weight tying).
"""
import numpy as np
import pytest


@pytest.fixture
def dy():
    from paddle_tpu import dygraph
    with dygraph.guard():
        yield dygraph


def test_mlm_decoder_tied_to_word_embedding(dy):
    from paddle_tpu.models.bert import BertConfig, BertForPretraining
    cfg = BertConfig.tiny()
    model = BertForPretraining(cfg)
    names = dict(model.named_parameters())
    # no untied [hidden, vocab] decoder matrix — only a vocab-sized bias
    decoder_mats = [n for n, p in names.items()
                    if list(p.shape) == [cfg.hidden_size, cfg.vocab_size]]
    assert not decoder_mats, f"untied decoder weights present: {decoder_mats}"
    assert any(list(p.shape) == [cfg.vocab_size] for p in names.values())


def test_pretrain_loss_finite_and_grads_reach_embedding(dy):
    import jax.numpy as jnp
    from paddle_tpu.models.bert import (BertConfig, BertForPretraining,
                                        pretrain_loss)
    cfg = BertConfig.tiny()
    model = BertForPretraining(cfg)
    b, s = 2, 16
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (b, s)).astype(np.int64)
    tt = np.zeros((b, s), np.int64)
    mlm = np.where(rng.rand(b, s) < 0.15,
                   rng.randint(0, cfg.vocab_size, (b, s)), -1).astype(np.int64)
    nsp = rng.randint(0, 2, (b, 1)).astype(np.int64)

    from paddle_tpu.dygraph.tape import Tensor
    loss = pretrain_loss(model, Tensor(ids), Tensor(tt), Tensor(mlm),
                         Tensor(nsp))
    assert np.isfinite(float(loss.value))
    loss.backward()
    g = model.bert.word_emb.weight.gradient()
    assert g is not None
    # tied decoder: masked-position vocab rows get gradient from the MLM head
    assert np.abs(np.asarray(g)).sum() > 0
