"""Regression guard for models/transformer.py decode retracing: greedy and
beam decode must run the decoder at ONE fixed shape (the seed grew the
target buffer by a token per step — a fresh XLA compile per generated
length), asserted via the eager kernel-cache counters. Also covers the
fixed-shape semantics (eos padding, prefix consistency across max_len)."""
import numpy as np

from paddle_tpu import dygraph, profiler
from paddle_tpu.dygraph.tape import Tensor
from paddle_tpu.models.transformer import (Transformer, TransformerConfig,
                                           beam_search_decode, greedy_decode)

BOS, EOS = 1, 2


def _model():
    cfg = TransformerConfig.tiny()
    m = Transformer(cfg)
    m.eval()
    return cfg, m


def test_greedy_decode_bounded_compiles_and_shape():
    with dygraph.guard():
        cfg, model = _model()
        rng = np.random.RandomState(0)
        src = Tensor(rng.randint(3, cfg.src_vocab_size,
                                 (2, 8)).astype(np.int64))
        out = greedy_decode(model, src, BOS, EOS, max_len=6)
        assert out.shape == (2, 6)
        # warm the fixed shape, then: a second decode of the SAME max_len
        # (different source → different generated content/length) must
        # compile NOTHING — compile count is independent of what decodes
        profiler.reset_eager_kernel_cache_stats()
        src2 = Tensor(rng.randint(3, cfg.src_vocab_size,
                                  (2, 8)).astype(np.int64))
        greedy_decode(model, src2, BOS, EOS, max_len=6)
        stats = profiler.eager_kernel_cache_stats()
        assert stats['misses'] == 0, stats
        assert stats['hits'] > 0


def test_greedy_decode_prefix_consistent_across_max_len():
    """Causal fixed-shape reads: the first tokens of a longer decode equal
    a shorter decode of the same source (the growing-buffer version had
    this property; the fixed buffer must keep it)."""
    with dygraph.guard():
        cfg, model = _model()
        rng = np.random.RandomState(1)
        src = Tensor(rng.randint(3, cfg.src_vocab_size,
                                 (2, 8)).astype(np.int64))
        short = greedy_decode(model, src, BOS, EOS, max_len=3)
        long = greedy_decode(model, src, BOS, EOS, max_len=7)
        assert np.array_equal(short, long[:, :3])


def test_beam_search_decode_bounded_compiles():
    with dygraph.guard():
        cfg, model = _model()
        rng = np.random.RandomState(2)
        src = Tensor(rng.randint(3, cfg.src_vocab_size,
                                 (2, 6)).astype(np.int64))
        out = beam_search_decode(model, src, BOS, EOS, beam_size=3,
                                 max_len=5)
        assert out.shape == (2, 5)
        profiler.reset_eager_kernel_cache_stats()
        src2 = Tensor(rng.randint(3, cfg.src_vocab_size,
                                  (2, 6)).astype(np.int64))
        beam_search_decode(model, src2, BOS, EOS, beam_size=3, max_len=5)
        stats = profiler.eager_kernel_cache_stats()
        assert stats['misses'] == 0, stats
        assert stats['hits'] > 0
