"""Model zoo: forward shapes + a training step for each family."""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import dygraph
from paddle_tpu.dygraph.tape import Tensor
from paddle_tpu import models


def _train_steps(model, loss_fn, n=3, lr=0.01):
    opt = fluid.optimizer.AdamOptimizer(lr,
                                        parameter_list=model.parameters())
    losses = []
    for _ in range(n):
        loss = loss_fn()
        loss.backward()
        opt.minimize(loss)
        model.clear_gradients()
        losses.append(float(loss.numpy()))
    return losses


def test_transformer_train_and_decode():
    from paddle_tpu.models.transformer import (TransformerConfig, Transformer,
                                               transformer_loss,
                                               greedy_decode)
    with dygraph.guard():
        cfg = TransformerConfig.tiny()
        model = Transformer(cfg)
        rng = np.random.RandomState(0)
        src = rng.randint(3, cfg.src_vocab_size, (2, 8)).astype(np.int64)
        trg_in = np.concatenate([np.ones((2, 1), np.int64), src[:, :-1]], 1)
        losses = _train_steps(
            model, lambda: transformer_loss(
                model(Tensor(src), Tensor(trg_in)), Tensor(src)), n=5)
        assert losses[-1] < losses[0]
        model.eval()
        out = greedy_decode(model, Tensor(src), 1, 2, max_len=4)
        assert out.shape[0] == 2


def test_mobilenets_and_vgg_forward():
    with dygraph.guard():
        x = Tensor(np.random.randn(2, 3, 32, 32).astype('float32'))
        m1 = models.MobileNetV1(num_classes=10, scale=0.25)
        m1.eval()
        assert m1(x).shape == (2, 10)
        m2 = models.MobileNetV2(num_classes=10, scale=0.35)
        m2.eval()
        assert m2(x).shape == (2, 10)
        vgg = models.VGG(11, num_classes=10, input_size=32, fc_dim=64)
        vgg.eval()
        assert vgg(x).shape == (2, 10)


def test_word2vec_trains():
    with dygraph.guard():
        model = models.Word2Vec(vocab_size=50, embedding_size=16, neg_num=3)
        rng = np.random.RandomState(0)
        center = rng.randint(0, 50, (8,)).astype(np.int64)
        targets = rng.randint(0, 50, (8, 4)).astype(np.int64)
        losses = _train_steps(
            model, lambda: model(Tensor(center), Tensor(targets)), n=10,
            lr=0.1)
        assert losses[-1] < losses[0]


def test_seq2seq_attention_shapes():
    with dygraph.guard():
        model = models.Seq2SeqAttn(src_vocab=30, trg_vocab=40, hidden=16,
                                   emb_dim=16)
        src = np.random.randint(0, 30, (2, 5)).astype(np.int64)
        trg = np.random.randint(0, 40, (2, 6)).astype(np.int64)
        logits = model(Tensor(src), Tensor(trg))
        assert logits.shape == (2, 6, 40)


def test_deepfm_and_gru4rec_train():
    with dygraph.guard():
        fm = models.DeepFM(field_num=4, feature_size=100, embedding_size=4,
                           deep_layers=(8, 8))
        rng = np.random.RandomState(1)
        ids = rng.randint(0, 100, (16, 4)).astype(np.int64)
        vals = np.ones((16, 4), 'float32')
        y = rng.randint(0, 2, (16, 1)).astype('float32')

        def fm_loss():
            logit = fm(Tensor(ids), Tensor(vals))
            from paddle_tpu.dygraph.tape import dispatch_op
            l = dispatch_op('sigmoid_cross_entropy_with_logits',
                            {'x': logit, 'label': Tensor(y)}, {})
            return dispatch_op('reduce_mean', {'x': l}, {})

        losses = _train_steps(fm, fm_loss, n=10, lr=0.05)
        assert losses[-1] < losses[0]

        g4r = models.GRU4Rec(vocab_size=30, hidden=16, emb_dim=16)
        seq = rng.randint(0, 30, (2, 5)).astype(np.int64)
        logits = g4r(Tensor(seq))
        assert logits.shape == (2, 5, 30)


def test_yolov3_forward_loss_infer():
    with dygraph.guard():
        model = models.YOLOv3(class_num=3)
        model.eval()
        img = Tensor(np.random.randn(1, 3, 64, 64).astype('float32'))
        outs = model(img)
        assert outs[0].shape == (1, 3 * 8, 2, 2)
        assert outs[1].shape == (1, 3 * 8, 4, 4)
        assert outs[2].shape == (1, 3 * 8, 8, 8)
        gt = np.zeros((1, 2, 4), 'float32')
        gt[0, 0] = [0.5, 0.5, 0.3, 0.3]
        loss = model.loss(outs, Tensor(gt),
                          Tensor(np.zeros((1, 2), np.int64)))
        assert np.isfinite(float(loss.numpy()))
        det = model.infer(outs, Tensor(np.array([[64, 64]], np.int32)),
                          keep_top_k=5)
        assert det.shape == (1, 5, 6)


def test_crnn_ctc_train_decode():
    with dygraph.guard():
        model = models.CRNN(num_classes=10, hidden=16)
        img = Tensor(np.random.randn(2, 1, 32, 48).astype('float32'))
        logits = model(img)
        B, T, V = logits.shape
        assert B == 2 and V == 11
        labels = np.random.randint(0, 10, (2, 4)).astype(np.int64)
        lab_len = np.array([4, 3], np.int64)
        loss = model.ctc_loss(logits, Tensor(labels), Tensor(lab_len))
        assert np.isfinite(float(loss.numpy()))
        out, lens = model.decode(logits)
        assert out.shape[0] == 2


def test_tsm_and_dcgan():
    with dygraph.guard():
        gen = models.DCGenerator(z_dim=8, base=8)
        disc = models.DCDiscriminator(base=8)
        z = Tensor(np.random.randn(2, 8).astype('float32'))
        fake = gen(z)
        assert fake.shape == (2, 1, 32, 32)
        score = disc(fake)
        assert score.shape == (2, 1)

        tsm = models.TSM(num_classes=5, seg_num=2, backbone_layers=18)
        tsm.eval()
        clip = Tensor(np.random.randn(4, 3, 32, 32).astype('float32'))
        out = tsm(clip)
        assert out.shape == (2, 5)


def test_ernie_classifier():
    with dygraph.guard():
        cfg = models.ErnieConfig(vocab_size=100, hidden_size=32,
                                 num_hidden_layers=2, num_attention_heads=2,
                                 intermediate_size=64,
                                 max_position_embeddings=32)
        model = models.ErnieForSequenceClassification(cfg, num_labels=3)
        ids = Tensor(np.random.randint(0, 100, (2, 16)).astype(np.int64))
        logits = model(ids)
        assert logits.shape == (2, 3)
