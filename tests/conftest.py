"""Test config: force CPU backend with 8 virtual devices so mesh/distributed
tests run without TPU hardware (SURVEY §4)."""
import os

os.environ['JAX_PLATFORMS'] = 'cpu'  # force: the session env exports 'axon'

# tier-1 runs with the static verifier live at every IR pass boundary, so
# every test doubles as a false-positive check on the analysis layer
# (paddle_tpu/analysis/; ISSUE 10). setdefault: a test (or CI matrix job)
# may still pin its own level, including 'off'.
os.environ.setdefault('PADDLE_TPU_VERIFY', 'passes')
flags = os.environ.get('XLA_FLAGS', '')
if 'xla_force_host_platform_device_count' not in flags:
    os.environ['XLA_FLAGS'] = (
        flags + ' --xla_force_host_platform_device_count=8').strip()

# The axon sitecustomize registers the TPU plugin at interpreter startup and
# pins jax_platforms before this file runs; re-pin to cpu post-import.
import jax
jax.config.update('jax_platforms', 'cpu')
assert jax.default_backend() == 'cpu', jax.default_backend()

import pytest


@pytest.fixture(autouse=True)
def fresh_programs():
    """Isolate each test: fresh default programs, scope, and name counter."""
    import paddle_tpu as fluid
    from paddle_tpu.core import unique_name
    from paddle_tpu.core.scope import Scope
    import paddle_tpu.core.scope as scope_mod
    old_main = fluid.framework._main_program_
    old_start = fluid.framework._startup_program_
    old_scope = scope_mod._global_scope
    old_gen = unique_name.generator
    fluid.framework._main_program_ = fluid.Program()
    fluid.framework._startup_program_ = fluid.Program()
    scope_mod._global_scope = Scope()
    unique_name.generator = unique_name.UniqueNameGenerator()
    yield
    fluid.framework._main_program_ = old_main
    fluid.framework._startup_program_ = old_start
    scope_mod._global_scope = old_scope
    unique_name.generator = old_gen
