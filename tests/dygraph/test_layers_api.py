"""Dygraph Layer API depth: hooks, containers, state_dict round-trips,
train/eval propagation, lr schedulers, save/load_dygraph (VERDICT r3 weak
#5 — dygraph surfaces previously exercised only indirectly)."""
import os

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import dygraph, layers


def test_forward_hooks_fire_in_order():
    events = []
    with dygraph.guard():
        fc = dygraph.nn.Linear(3, 2)

        def pre(layer, inputs):
            events.append('pre')

        def post(layer, inputs, output):
            events.append('post')
            return output

        h1 = fc.register_forward_pre_hook(pre)
        h2 = fc.register_forward_post_hook(post)
        fc(dygraph.to_variable(np.ones((1, 3), np.float32)))
        assert events == ['pre', 'post']
        h1.remove()
        h2.remove()
        fc(dygraph.to_variable(np.ones((1, 3), np.float32)))
        assert events == ['pre', 'post']       # removed hooks stay silent


def test_containers():
    from paddle_tpu.dygraph.container import (LayerList, ParameterList,
                                              Sequential)
    with dygraph.guard():
        seq = Sequential(dygraph.nn.Linear(4, 8, act='relu'),
                         dygraph.nn.Linear(8, 2))
        out = seq(dygraph.to_variable(np.ones((2, 4), np.float32)))
        assert out.shape == (2, 2)
        assert len(list(seq.parameters())) == 4

        ll = LayerList([dygraph.nn.Linear(2, 2) for _ in range(3)])
        assert len(ll) == 3
        ll.append(dygraph.nn.Linear(2, 2))
        assert len(list(ll.parameters())) == 8

        m = dygraph.Layer()
        pl = ParameterList([m.create_parameter([2, 2], None, 'float32')
                            for _ in range(2)])
        assert len(list(pl.parameters())) == 2


def test_train_eval_propagates():
    with dygraph.guard():
        from paddle_tpu.dygraph.container import Sequential
        m = Sequential(dygraph.nn.Linear(2, 2), dygraph.nn.Linear(2, 2))
        m.eval()
        assert all(not s.training for _, s in m.named_sublayers())
        m.train()
        assert all(s.training for _, s in m.named_sublayers())


def test_state_dict_roundtrip_and_save_load(tmp_path):
    with dygraph.guard():
        m = dygraph.nn.Linear(3, 2)
        sd = m.state_dict()
        assert len(sd) == 2
        path = str(tmp_path / 'model')
        dygraph.save_dygraph(sd, path)
        m2 = dygraph.nn.Linear(3, 2)
        loaded, _ = dygraph.load_dygraph(path)
        m2.set_dict(loaded)
        for (n1, p1), (n2, p2) in zip(sorted(m.state_dict().items()),
                                      sorted(m2.state_dict().items())):
            np.testing.assert_allclose(np.asarray(p1), np.asarray(p2))


@pytest.mark.parametrize('sched_cls,kwargs,decreases', [
    ('ExponentialDecay', dict(learning_rate=0.1, decay_steps=2,
                              decay_rate=0.5), True),
    ('NaturalExpDecay', dict(learning_rate=0.1, decay_steps=2,
                             decay_rate=0.5), True),
    ('InverseTimeDecay', dict(learning_rate=0.1, decay_steps=2,
                              decay_rate=0.5), True),
    ('PolynomialDecay', dict(learning_rate=0.1, decay_steps=4,
                             end_learning_rate=0.01), True),
    ('CosineDecay', dict(learning_rate=0.1, step_each_epoch=4,
                         epochs=2), True),
    ('NoamDecay', dict(d_model=64, warmup_steps=3), False),
])
def test_dygraph_lr_schedulers(sched_cls, kwargs, decreases):
    with dygraph.guard():
        sched = getattr(dygraph, sched_cls)(**kwargs)
        fc = dygraph.nn.Linear(2, 1)
        opt = fluid.optimizer.SGD(learning_rate=sched,
                                  parameter_list=fc.parameters())
        lrs = []
        for _ in range(6):
            out = fc(dygraph.to_variable(np.ones((2, 2), np.float32)))
            loss = layers.reduce_mean(out)
            loss.backward()
            lrs.append(opt.current_step_lr)
            opt.minimize(loss)
            opt.clear_gradients()
        assert len(set(np.round(lrs, 8))) > 1       # schedule moves
        if decreases:
            assert lrs[-1] < lrs[0]
        else:
            assert lrs[1] > lrs[0] or lrs[2] > lrs[1]   # warmup rises


def test_piecewise_decay_boundaries():
    with dygraph.guard():
        sched = dygraph.PiecewiseDecay([2, 4], [0.1, 0.01, 0.001], 0)
        fc = dygraph.nn.Linear(2, 1)
        opt = fluid.optimizer.SGD(learning_rate=sched,
                                  parameter_list=fc.parameters())
        seen = []
        for _ in range(5):
            out = fc(dygraph.to_variable(np.ones((1, 2), np.float32)))
            loss = layers.reduce_mean(out)
            loss.backward()
            seen.append(round(opt.current_step_lr, 6))
            opt.minimize(loss)
            opt.clear_gradients()
        assert seen[0] == 0.1 and seen[-1] in (0.01, 0.001)
