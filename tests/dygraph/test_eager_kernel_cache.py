"""Eager per-op jitted-kernel cache (dygraph/tape.py): hit/miss accounting,
LRU bound, cache-on/off numerical identity (seed-pinned, incl. RNG ops),
attr-hashability bypass, and the PADDLE_TPU_EAGER_CACHE env hatch."""
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import dygraph
from paddle_tpu.dygraph.tape import (_attr_sig, _Unhashable, dispatch_op,
                                     kernel_cache)
from paddle_tpu.dygraph.nn import Linear

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), '..', '..'))


@pytest.fixture(autouse=True)
def _fresh_cache():
    old_enabled, old_max = kernel_cache.enabled, kernel_cache.maxsize
    kernel_cache.clear()
    kernel_cache.enabled = True
    yield
    kernel_cache.clear()
    kernel_cache.enabled, kernel_cache.maxsize = old_enabled, old_max


def _train_trace(seed):
    """One seed-pinned fwd+bwd micro-trace; returns (loss, grads, dropout)."""
    from paddle_tpu.core.random import seed as set_seed
    set_seed(seed)
    model = Linear(4, 3)
    x = dygraph.to_variable(
        np.random.RandomState(seed).randn(8, 4).astype(np.float32))
    y = model(x)
    d = dispatch_op('dropout', {'x': y}, {'dropout_prob': 0.5})
    loss = dispatch_op('reduce_mean', {'x': d * d}, {})
    loss.backward()
    return (float(loss.value),
            {n: np.asarray(p.grad) for n, p in model.named_parameters()},
            np.asarray(d.value))


def test_cache_numerics_identical_on_off():
    with dygraph.guard():
        with dygraph.eager_kernel_cache_guard(False):
            l0, g0, d0 = _train_trace(7)
            assert kernel_cache.stats()['hits'] == 0
        with dygraph.eager_kernel_cache_guard(True):
            l1, g1, d1 = _train_trace(7)
            assert kernel_cache.stats()['misses'] > 0
            # second identical trace: every dispatch is a hit
            before = kernel_cache.stats()['misses']
            l2, g2, d2 = _train_trace(7)
            assert kernel_cache.stats()['misses'] == before
            assert kernel_cache.stats()['hits'] > 0
    np.testing.assert_allclose(l0, l1, rtol=1e-6)
    np.testing.assert_array_equal(d0, d1)   # same PRNG stream either way
    np.testing.assert_array_equal(d1, d2)
    for n in g0:
        np.testing.assert_allclose(g0[n], g1[n], rtol=1e-6, atol=1e-7)
        np.testing.assert_allclose(g1[n], g2[n], rtol=1e-6, atol=1e-7)


def test_repeat_dispatch_hits_cache():
    with dygraph.guard():
        t = dygraph.to_variable(np.ones((3, 3), np.float32))
        for _ in range(5):
            dispatch_op('scale', {'x': t}, {'scale': 2.0})
    s = kernel_cache.stats()
    assert s['misses'] == 1 and s['hits'] == 4


def test_distinct_shapes_and_attrs_miss():
    with dygraph.guard():
        a = dygraph.to_variable(np.ones((2, 2), np.float32))
        b = dygraph.to_variable(np.ones((4, 2), np.float32))
        dispatch_op('scale', {'x': a}, {'scale': 2.0})
        dispatch_op('scale', {'x': b}, {'scale': 2.0})   # new shape
        dispatch_op('scale', {'x': b}, {'scale': 3.0})   # new attr
    assert kernel_cache.stats()['misses'] == 3


def test_lru_bound_evicts():
    dygraph.set_eager_kernel_cache(True, maxsize=2)
    with dygraph.guard():
        t = dygraph.to_variable(np.ones((2, 2), np.float32))
        for s in (1.0, 2.0, 3.0, 4.0):
            dispatch_op('scale', {'x': t}, {'scale': s})
    st = kernel_cache.stats()
    assert st['size'] <= 2 and st['evictions'] == 2


def test_unhashable_attr_bypasses_not_breaks():
    assert _attr_sig({'a': [1, (2, 'x')], 'b': None}) is not None
    with pytest.raises(_Unhashable):
        _attr_sig(np.zeros(3))
    with dygraph.guard():
        t = dygraph.to_variable(np.ones((2,), np.float32))
        out = dispatch_op('scale', {'x': t}, {'scale': np.asarray(2.0)})
        np.testing.assert_allclose(np.asarray(out.value), [2.0, 2.0])
    assert kernel_cache.stats()['bypasses'] >= 1


def test_backward_through_cached_kernels_twice_raises():
    """retain_graph semantics survive the cached path: the freed-graph
    error must still fire on a second backward()."""
    with dygraph.guard():
        model = Linear(3, 1)
        x = dygraph.to_variable(np.ones((2, 3), np.float32))
        loss = dispatch_op('reduce_mean', {'x': model(x)}, {})
        loss.backward()
        with pytest.raises(RuntimeError, match='freed'):
            loss.backward()


def test_env_escape_hatch_disables_cache():
    code = (
        "import numpy as np\n"
        "import paddle_tpu as fluid\n"
        "from paddle_tpu import dygraph\n"
        "from paddle_tpu.dygraph.tape import dispatch_op, kernel_cache\n"
        "with dygraph.guard():\n"
        "    t = dygraph.to_variable(np.ones((2, 2), np.float32))\n"
        "    for _ in range(3):\n"
        "        dispatch_op('scale', {'x': t}, {'scale': 2.0})\n"
        "s = kernel_cache.stats()\n"
        "assert not s['enabled'] and s['size'] == 0 and s['hits'] == 0, s\n"
        "print('HATCH_OK')\n")
    env = dict(os.environ, PADDLE_TPU_EAGER_CACHE='0', JAX_PLATFORMS='cpu')
    r = subprocess.run([sys.executable, '-c', code], cwd=REPO, env=env,
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stderr[-2000:]
    assert 'HATCH_OK' in r.stdout
