"""to_static / declarative: real traced+jitted translation (VERDICT r1 #1).

ref: python/paddle/fluid/dygraph/dygraph_to_static/program_translator.py —
the reference AST-rewrites Python into a fluid Program; here the eager code
is traced with jax tracers into ONE cached XLA program per input signature.
"""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import dygraph
from paddle_tpu.dygraph import to_variable, Linear, BatchNorm
from paddle_tpu.dygraph.jit import (to_static, declarative, InputSpec,
                                    ProgramTranslator, StaticFunction)


def _rand(*shape):
    return np.random.RandomState(sum(shape)).randn(*shape).astype('float32')


def test_function_parity_and_single_compile():
    with dygraph.guard():
        lin = Linear(4, 3)

        @to_static
        def f(x):
            return fluid.layers.relu(lin(x))

        x = to_variable(_rand(2, 4))
        out = f(x)
        ProgramTranslator().enable(False)
        ref = f(x)
        ProgramTranslator().enable(True)
        np.testing.assert_allclose(np.asarray(out.value), np.asarray(ref.value),
                                   rtol=1e-5)
        assert f._compile_count == 1
        f(x)
        f(x)
        assert f._compile_count == 1  # cached: one trace for the signature


def test_method_decoration_grad_parity():
    with dygraph.guard():
        class Net(dygraph.Layer):
            def __init__(self):
                super().__init__()
                self.l1 = Linear(4, 8, act='relu')
                self.l2 = Linear(8, 2)

            @declarative
            def forward(self, x):
                return self.l2(self.l1(x))

        net = Net()
        x = to_variable(_rand(5, 4))

        loss = fluid.layers.reduce_sum(net.forward(x))
        loss.backward()
        static_grads = {n: np.asarray(p.grad)
                        for n, p in net.named_parameters()}
        for p in net.parameters():
            p.clear_gradient()

        ProgramTranslator().enable(False)
        loss_e = fluid.layers.reduce_sum(net.forward(x))
        loss_e.backward()
        ProgramTranslator().enable(True)
        np.testing.assert_allclose(loss.item(), loss_e.item(), rtol=1e-5)
        for n, p in net.named_parameters():
            np.testing.assert_allclose(static_grads[n], np.asarray(p.grad),
                                       rtol=1e-4, atol=1e-5)


def test_buffer_mutation_batchnorm():
    with dygraph.guard():
        bn = BatchNorm(3)
        bn.train()

        @to_static
        def f(x):
            return bn(x)

        x = to_variable(_rand(8, 3))
        mean_before = np.asarray(dict(bn.named_buffers())['_mean'].value).copy() \
            if '_mean' in dict(bn.named_buffers()) else None
        buf_names = list(dict(bn.named_buffers()))
        before = {n: np.asarray(b.value).copy()
                  for n, b in bn.named_buffers()}
        f(x)
        after = {n: np.asarray(b.value) for n, b in bn.named_buffers()}
        # running statistics must update through the compiled program
        changed = any(not np.allclose(before[n], after[n]) for n in buf_names)
        assert changed, f"no buffer updated; buffers={buf_names}"


def test_recompile_on_new_shape():
    with dygraph.guard():
        lin = Linear(4, 3)

        @to_static
        def f(x):
            return lin(x)

        f(to_variable(_rand(2, 4)))
        assert f._compile_count == 1
        out = f(to_variable(_rand(7, 4)))
        assert f._compile_count == 2
        assert out.shape == (7, 3)


def test_input_spec_dtype_cast():
    with dygraph.guard():
        lin = Linear(4, 3)
        sf = StaticFunction(lambda x: lin(x),
                            input_spec=[InputSpec([None, 4], 'float32')])
        out = sf(np.ones((2, 4), np.float64))
        assert out.dtype == 'float32'


def test_dropout_randomness_not_baked():
    with dygraph.guard():
        fluid.core.random.seed(0) if hasattr(fluid, 'core') else None

        @to_static
        def f(x):
            return fluid.layers.dropout(x, dropout_prob=0.5,
                                        dropout_implementation='upscale_in_train')

        x = to_variable(np.ones((64, 64), np.float32))
        a = np.asarray(f(x).value)
        b = np.asarray(f(x).value)
        assert f._compile_count == 1
        assert not np.allclose(a, b), \
            "dropout mask is identical across calls — key baked into trace"


def test_program_translator_disable():
    with dygraph.guard():
        lin = Linear(2, 2)

        @to_static
        def f(x):
            return lin(x)

        x = to_variable(_rand(3, 2))
        ProgramTranslator().enable(False)
        out = f(x)
        ProgramTranslator().enable(True)
        assert f._compile_count == 0  # never traced while disabled
        assert out.shape == (3, 2)


def test_kwarg_tensor_gets_grad():
    with dygraph.guard():
        @to_static
        def f(x):
            return fluid.layers.reduce_sum(x * x)

        t = dygraph.Parameter(np.array([2.0, 3.0], np.float32))
        f(x=t).backward()
        np.testing.assert_allclose(t.gradient(), [4.0, 6.0], rtol=1e-6)


def test_static_args_in_cache_key():
    with dygraph.guard():
        @to_static
        def f(x, scale):
            return fluid.layers.scale(x, scale=scale)

        x = to_variable(_rand(2, 2))
        a = f(x, 2.0)
        b = f(x, 3.0)
        np.testing.assert_allclose(np.asarray(b.value),
                                   1.5 * np.asarray(a.value), rtol=1e-5)
        assert f._compile_count == 2  # python scalars are static attrs
