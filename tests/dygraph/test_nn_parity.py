"""Dygraph nn layers: value parity vs numpy / the static-graph layer fns
(ref test model: unittests/test_imperative_* family)."""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import dygraph
from paddle_tpu.dygraph import to_variable

RNG = np.random.RandomState(3)


def const_attr(v):
    return fluid.ParamAttr(
        initializer=fluid.initializer.ConstantInitializer(v))


def test_linear_value():
    with dygraph.guard():
        lin = dygraph.Linear(4, 2, param_attr=const_attr(0.5),
                             bias_attr=const_attr(1.0))
        x = RNG.rand(3, 4).astype('float32')
        out = lin(to_variable(x))
        np.testing.assert_allclose(out.numpy(),
                                   x @ np.full((4, 2), 0.5) + 1.0,
                                   rtol=1e-5)


def test_conv2d_value():
    with dygraph.guard():
        conv = dygraph.Conv2D(1, 1, 3, param_attr=const_attr(1.0),
                              bias_attr=False)
        x = np.ones((1, 1, 4, 4), 'float32')
        out = conv(to_variable(x))
        # valid center taps of an all-ones 3x3 conv over ones = 9
        np.testing.assert_allclose(out.numpy()[0, 0], 9.0, rtol=1e-5)


def test_conv2d_transpose_shape_and_grad():
    with dygraph.guard():
        deconv = dygraph.Conv2DTranspose(2, 3, 4, stride=2, padding=1)
        x = to_variable(RNG.rand(2, 2, 5, 5).astype('float32'))
        out = deconv(x)
        assert out.shape == (2, 3, 10, 10)
        loss = fluid.layers.reduce_mean(out)
        loss.backward()
        assert deconv.weight.gradient() is not None


def test_pool2d_and_batchnorm_stats():
    with dygraph.guard():
        pool = dygraph.Pool2D(pool_size=2, pool_type='avg', pool_stride=2)
        x = np.arange(16, dtype='float32').reshape(1, 1, 4, 4)
        np.testing.assert_allclose(
            pool(to_variable(x)).numpy()[0, 0],
            [[2.5, 4.5], [10.5, 12.5]])
        bn = dygraph.BatchNorm(3)
        bn.train()
        xb = RNG.rand(8, 3, 2, 2).astype('float32')
        out = bn(to_variable(xb)).numpy()
        np.testing.assert_allclose(out.mean((0, 2, 3)), 0.0, atol=1e-4)
        np.testing.assert_allclose(out.std((0, 2, 3)), 1.0, atol=1e-2)


def test_embedding_and_layernorm():
    with dygraph.guard():
        emb = dygraph.Embedding([5, 4], param_attr=const_attr(2.0))
        ids = np.array([0, 3], 'int64')
        np.testing.assert_allclose(emb(to_variable(ids)).numpy(), 2.0)
        ln = dygraph.LayerNorm([6])
        x = RNG.rand(2, 6).astype('float32')
        out = ln(to_variable(x)).numpy()
        np.testing.assert_allclose(out.mean(1), 0.0, atol=1e-5)


def test_prelu_nce_bilinear_groupnorm_spectral():
    with dygraph.guard():
        x = RNG.rand(2, 4).astype('float32') - 0.5
        pr = dygraph.PRelu('all', param_attr=const_attr(0.25))
        got = pr(to_variable(x.astype('float32'))).numpy()
        np.testing.assert_allclose(
            got, np.where(x > 0, x, 0.25 * x), rtol=1e-5)

        gn = dygraph.GroupNorm(channels=4, groups=2)
        xg = RNG.rand(2, 4, 3, 3).astype('float32')
        og = gn(to_variable(xg)).numpy()
        grp = og.reshape(2, 2, 2 * 9)
        np.testing.assert_allclose(grp.mean(-1), 0.0, atol=1e-4)

        bt = dygraph.BilinearTensorProduct(3, 3, 2)
        o = bt(to_variable(RNG.rand(2, 3).astype('float32')),
               to_variable(RNG.rand(2, 3).astype('float32')))
        assert o.shape == (2, 2)


def test_sequential_and_parameterlist_training():
    """A Sequential MLP trains end-to-end in dygraph."""
    with dygraph.guard():
        model = dygraph.Sequential(
            dygraph.Linear(3, 8, act='relu'),
            dygraph.Linear(8, 1))
        opt = fluid.optimizer.Adam(0.05,
                                   parameter_list=model.parameters())
        X = RNG.rand(32, 3).astype('float32')
        W = np.array([[1.], [2.], [-1.]], 'float32')
        Y = X @ W
        losses = []
        for _ in range(60):
            pred = model(to_variable(X))
            loss = fluid.layers.reduce_mean(
                fluid.layers.square_error_cost(pred, to_variable(Y)))
            loss.backward()
            opt.minimize(loss)
            model.clear_gradients()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0] * 0.05


def test_dygraph_static_parity_mlp():
    """Same weights → same outputs in dygraph and static modes."""
    x = RNG.rand(4, 5).astype('float32')
    with dygraph.guard():
        lin = dygraph.Linear(5, 3, param_attr=const_attr(0.3),
                             bias_attr=const_attr(0.1))
        dy_out = lin(to_variable(x)).numpy()

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        xv = fluid.data('dp_x', [4, 5], 'float32')
        out = fluid.layers.fc(xv, 3, param_attr=const_attr(0.3),
                              bias_attr=const_attr(0.1))
    exe = fluid.Executor()
    exe.run(startup)
    st_out, = exe.run(main, feed={'dp_x': x}, fetch_list=[out])
    np.testing.assert_allclose(dy_out, st_out, rtol=1e-5)


def test_state_dict_roundtrip_changes_output():
    with dygraph.guard():
        m1 = dygraph.Linear(3, 2)
        m2 = dygraph.Linear(3, 2)
        x = to_variable(RNG.rand(2, 3).astype('float32'))
        assert not np.allclose(m1(x).numpy(), m2(x).numpy())
        m2.set_dict(m1.state_dict())
        np.testing.assert_allclose(m1(x).numpy(), m2(x).numpy(), rtol=1e-6)
