"""Double-grad / retain_graph semantics (VERDICT r1 #9) and eager
DataParallel grad parity (VERDICT r1 #8).

ref: paddle/fluid/imperative/partial_grad_engine.cc (dygraph.grad),
imperative/basic_engine (retain_graph), python/paddle/fluid/dygraph/
parallel.py (DataParallel scale_loss/apply_collective_grads).
"""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import dygraph
from paddle_tpu.dygraph import to_variable, Linear


def test_grad_first_order_matches_backward():
    with dygraph.guard():
        x = dygraph.Parameter(np.array([2.0, 3.0], np.float32))
        y = x * x + x
        loss = dygraph.dispatch_op('reduce_sum', {'x': y}, {})
        (g,) = dygraph.grad(loss, x)
        np.testing.assert_allclose(np.asarray(g.value), [5.0, 7.0],
                                   rtol=1e-6)
        # backward still works afterwards (grad() doesn't consume the tape)
        loss.backward()
        np.testing.assert_allclose(x.gradient(), [5.0, 7.0], rtol=1e-6)


def test_double_grad_elementwise():
    # y = x^3; dy/dx = 3x^2; d2y/dx2 = 6x
    with dygraph.guard():
        x = dygraph.Parameter(np.array([2.0], np.float32))
        y = x * x * x
        (g1,) = dygraph.grad(y, x, create_graph=True)
        np.testing.assert_allclose(np.asarray(g1.value), [12.0], rtol=1e-6)
        (g2,) = dygraph.grad(g1, x)
        np.testing.assert_allclose(np.asarray(g2.value), [12.0], rtol=1e-6)


def test_double_grad_matmul_chain():
    # f = sum((x @ w)^2); df/dw = 2 x^T x w ; d/dw sum(df/dw) checked
    rng = np.random.RandomState(0)
    X = rng.randn(3, 4).astype('float32')
    W = rng.randn(4, 2).astype('float32')
    with dygraph.guard():
        x = to_variable(X)
        w = dygraph.Parameter(W)
        h = dygraph.dispatch_op('matmul', {'x': x, 'y': w}, {})
        f = dygraph.dispatch_op('reduce_sum', {'x': h * h}, {})
        (gw,) = dygraph.grad(f, w, create_graph=True)
        np.testing.assert_allclose(np.asarray(gw.value), 2 * X.T @ X @ W,
                                   rtol=1e-4, atol=1e-5)
        s = dygraph.dispatch_op('reduce_sum', {'x': gw}, {})
        (ggw,) = dygraph.grad(s, w)
        # d/dW sum(2 X^T X W) = 2 X^T X @ ones-broadcast: column-constant
        want = 2 * (X.T @ X) @ np.ones((4, 2), np.float32)
        np.testing.assert_allclose(np.asarray(ggw.value), want, rtol=1e-4,
                                   atol=1e-5)


def test_backward_through_grad_result():
    """ADVICE r2 (high): backward() through a grad(create_graph=True)
    result — the gradient-penalty training pattern. g = dy/dx = 3x^2;
    L = sum(g); dL/dx = 6x must land in x.grad via backward()."""
    with dygraph.guard():
        x = dygraph.Parameter(np.array([2.0, -1.0], np.float32))
        y = x * x * x
        (g,) = dygraph.grad(y, x, create_graph=True)
        loss = dygraph.dispatch_op('reduce_sum', {'x': g}, {})
        loss.backward()
        np.testing.assert_allclose(x.gradient(), [12.0, -6.0], rtol=1e-6)


def test_grad_allow_unused():
    with dygraph.guard():
        x = dygraph.Parameter(np.array([2.0], np.float32))
        z = dygraph.Parameter(np.array([5.0], np.float32))  # unused
        y = dygraph.dispatch_op('reduce_sum', {'x': x * x}, {})
        with pytest.raises(ValueError, match='allow_unused'):
            dygraph.grad(y, [x, z])
        gx, gz = dygraph.grad(y, [x, z], allow_unused=True)
        np.testing.assert_allclose(np.asarray(gx.value), [4.0])
        assert gz is None


def test_grad_no_grad_vars():
    """no_grad_vars blocks gradient flow through the listed tensors."""
    with dygraph.guard():
        x = dygraph.Parameter(np.array([3.0], np.float32))
        h = x * x          # dh/dx = 6
        y = h * x          # y = x^3
        # blocking h: y is treated as const(h) * x → dy/dx = h = 9
        (g,) = dygraph.grad(y, x, no_grad_vars=[h])
        np.testing.assert_allclose(np.asarray(g.value), [9.0], rtol=1e-6)
        # unblocked: dy/dx = 3x^2 = 27
        (g2,) = dygraph.grad(y, x)
        np.testing.assert_allclose(np.asarray(g2.value), [27.0], rtol=1e-6)


def test_second_backward_raises_without_retain():
    with dygraph.guard():
        x = dygraph.Parameter(np.array([1.0], np.float32))
        loss = dygraph.dispatch_op('reduce_sum', {'x': x * x}, {})
        loss.backward()
        with pytest.raises(RuntimeError, match='retain_graph'):
            loss.backward()


def test_retain_graph_allows_second_backward():
    with dygraph.guard():
        x = dygraph.Parameter(np.array([3.0], np.float32))
        loss = dygraph.dispatch_op('reduce_sum', {'x': x * x}, {})
        loss.backward(retain_graph=True)
        np.testing.assert_allclose(x.gradient(), [6.0])
        loss.backward()                       # second pass accumulates
        np.testing.assert_allclose(x.gradient(), [12.0])


def test_eager_data_parallel_grad_parity():
    """Single-controller: DataParallel hooks must be identity — grads match
    the plain layer exactly even with a dp mesh installed (regression: the
    old code divided grads by the mesh dp size)."""
    from paddle_tpu.parallel import make_mesh, mesh_guard
    rng = np.random.RandomState(1)
    X = rng.randn(16, 4).astype('float32')
    with dygraph.guard():
        plain = Linear(4, 2)
        loss_p = dygraph.dispatch_op('reduce_sum',
                                     {'x': plain(to_variable(X))}, {})
        loss_p.backward()
        want = {n: np.asarray(p.grad) for n, p in plain.named_parameters()}

        dp_inner = Linear(4, 2)
        for (n, a), (_, b) in zip(dp_inner.named_parameters(),
                                  plain.named_parameters()):
            a.set_value(b.value)
        with mesh_guard(make_mesh({'dp': 8})):
            model = dygraph.DataParallel(dp_inner)
            out = model(to_variable(X))
            loss = dygraph.dispatch_op('reduce_sum', {'x': out}, {})
            loss = model.scale_loss(loss)
            loss.backward()
            model.apply_collective_grads()
        for n, p in dp_inner.named_parameters():
            np.testing.assert_allclose(np.asarray(p.grad), want[n],
                                       rtol=1e-6, err_msg=n)
