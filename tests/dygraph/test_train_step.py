"""TrainStep fused-step tests: basic SGD parity and gradient merge
(accum_steps, ref GradientMergeOptimizer semantics — optimizer.py:3870)."""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import dygraph
from paddle_tpu.dygraph.jit import TrainStep
from paddle_tpu.dygraph.nn import Linear
from paddle_tpu.dygraph.tape import dispatch_op


def _mse(m, x, y):
    d = dispatch_op('elementwise_sub', {'x': m(x), 'y': y}, {})
    sq = dispatch_op('elementwise_mul', {'x': d, 'y': d}, {})
    return dispatch_op('reduce_mean', {'x': sq}, {})


def _make(seed=0):
    from paddle_tpu.core.random import seed as set_seed
    set_seed(seed)  # param init draws from the framework PRNG stream
    model = Linear(4, 1)
    opt = fluid.optimizer.SGD(0.1, parameter_list=model.parameters())
    return model, opt


def test_train_step_matches_manual_sgd():
    rng = np.random.RandomState(0)
    x = rng.randn(8, 4).astype(np.float32)
    y = rng.randn(8, 1).astype(np.float32)
    with dygraph.guard():
        model, opt = _make()
        w0 = {n: np.asarray(p.value).copy()
              for n, p in model.named_parameters()}
        step = TrainStep(model, _mse, opt)
        step(x, y)
        got = {n: np.asarray(p.value) for n, p in model.named_parameters()}

    # manual: w -= lr * dL/dw for the same MSE
    w, b = w0['weight'], w0['bias']
    pred = x @ w + b
    d = (pred - y)
    gw = 2.0 * x.T @ d / d.size
    gb = 2.0 * d.sum(axis=0) / d.size
    np.testing.assert_allclose(got['weight'], w - 0.1 * gw, rtol=1e-5,
                               atol=1e-6)
    np.testing.assert_allclose(got['bias'], b - 0.1 * gb, rtol=1e-5,
                               atol=1e-6)


def test_grad_merge_applies_every_k_steps():
    rng = np.random.RandomState(1)
    batches = [(rng.randn(4, 4).astype(np.float32),
                rng.randn(4, 1).astype(np.float32)) for _ in range(4)]
    with dygraph.guard():
        model, opt = _make(seed=1)
        w0 = {n: np.asarray(p.value).copy()
              for n, p in model.named_parameters()}
        step = TrainStep(model, _mse, opt, accum_steps=4)
        for i, (x, y) in enumerate(batches):
            step(x, y)
            got = {n: np.asarray(p.value)
                   for n, p in model.named_parameters()}
            if i < 3:  # params must NOT move before the k-th call
                for n in w0:
                    np.testing.assert_array_equal(got[n], w0[n])
    # after k calls: one SGD update with the MEAN of the k grads
    mean_gw = np.zeros_like(w0['weight'])
    mean_gb = np.zeros_like(w0['bias'])
    for x, y in batches:
        d = x @ w0['weight'] + w0['bias'] - y
        mean_gw += 2.0 * x.T @ d / d.size
        mean_gb += 2.0 * d.sum(axis=0) / d.size
    mean_gw /= 4.0
    mean_gb /= 4.0
    np.testing.assert_allclose(got['weight'], w0['weight'] - 0.1 * mean_gw,
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(got['bias'], w0['bias'] - 0.1 * mean_gb,
                               rtol=1e-5, atol=1e-6)


def test_grad_merge_two_cycles():
    """Second merge cycle starts from a zeroed accumulator."""
    rng = np.random.RandomState(2)
    x = rng.randn(4, 4).astype(np.float32)
    y = rng.randn(4, 1).astype(np.float32)
    with dygraph.guard():
        model, opt = _make(seed=2)
        step = TrainStep(model, _mse, opt, accum_steps=2)
        for _ in range(4):
            step(x, y)
        merged = {n: np.asarray(p.value)
                  for n, p in model.named_parameters()}
    with dygraph.guard():
        model2, opt2 = _make(seed=2)
        plain = TrainStep(model2, _mse, opt2)
        for _ in range(2):  # same data k times → mean grad == plain grad
            plain(x, y)
        expect = {n: np.asarray(p.value)
                  for n, p in model2.named_parameters()}
    for n in merged:
        np.testing.assert_allclose(merged[n], expect[n], rtol=1e-5,
                                   atol=1e-6)
