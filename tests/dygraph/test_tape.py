"""Dygraph autograd-tape tests (SURVEY §4)."""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import dygraph
from paddle_tpu.dygraph import to_variable


def test_basic_backward():
    with dygraph.guard():
        x = dygraph.Parameter(np.array([2.0, 3.0], np.float32))
        y = x * x + x  # dy/dx = 2x + 1
        loss = dygraph.dispatch_op('reduce_sum', {'x': y}, {})
        loss.backward()
        np.testing.assert_allclose(x.gradient(), [5.0, 7.0], rtol=1e-6)


def test_grad_accumulation_two_uses():
    with dygraph.guard():
        x = dygraph.Parameter(np.array([1.0], np.float32))
        a = x * 3.0
        b = x * 4.0
        loss = dygraph.dispatch_op('reduce_sum', {'x': a + b}, {})
        loss.backward()
        np.testing.assert_allclose(x.gradient(), [7.0], rtol=1e-6)


def test_stop_gradient_blocks():
    with dygraph.guard():
        x = dygraph.Parameter(np.array([1.0], np.float32))
        y = to_variable(np.array([2.0], np.float32))  # stop_gradient
        loss = dygraph.dispatch_op('reduce_sum', {'x': x * y}, {})
        loss.backward()
        np.testing.assert_allclose(x.gradient(), [2.0])
        assert y.grad is None


def test_no_grad_context():
    with dygraph.guard():
        x = dygraph.Parameter(np.array([1.0], np.float32))
        with dygraph.no_grad_guard():
            y = x * 2.0
        assert y._node is None


def test_linear_layer_training_converges():
    np.random.seed(1)
    with dygraph.guard():
        model = dygraph.Linear(8, 1)
        opt = fluid.optimizer.SGD(0.1, parameter_list=model.parameters())
        w_true = np.random.randn(8, 1).astype(np.float32)
        losses = []
        for _ in range(60):
            xv = np.random.randn(16, 8).astype(np.float32)
            yv = xv @ w_true
            pred = model(to_variable(xv))
            diff = pred - to_variable(yv)
            loss = dygraph.dispatch_op('reduce_mean', {
                'x': dygraph.dispatch_op('square', {'x': diff}, {})}, {})
            loss.backward()
            opt.minimize(loss)
            model.clear_gradients()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0] * 0.05


def test_adam_converges():
    np.random.seed(2)
    with dygraph.guard():
        model = dygraph.Linear(4, 1)
        opt = fluid.optimizer.Adam(0.05, parameter_list=model.parameters())
        w_true = np.random.randn(4, 1).astype(np.float32)
        losses = []
        for _ in range(80):
            xv = np.random.randn(16, 4).astype(np.float32)
            yv = xv @ w_true
            loss = dygraph.dispatch_op('reduce_mean', {
                'x': dygraph.dispatch_op(
                    'square_error_cost',
                    {'x': model(to_variable(xv)), 'label': to_variable(yv)},
                    {})}, {})
            loss.backward()
            opt.minimize(loss)
            model.clear_gradients()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0] * 0.1


def test_conv_bn_pool_forward_shapes():
    with dygraph.guard():
        conv = dygraph.Conv2D(3, 8, 3, padding=1)
        bn = dygraph.BatchNorm(8)
        pool = dygraph.Pool2D(2, 'max', 2)
        x = to_variable(np.random.randn(2, 3, 16, 16).astype(np.float32))
        y = pool(bn(conv(x)))
        assert y.shape == (2, 8, 8, 8)


def test_batchnorm_eval_mode_uses_running_stats():
    with dygraph.guard():
        bn = dygraph.BatchNorm(4)
        x = to_variable(np.random.randn(8, 4, 5, 5).astype(np.float32) + 3.0)
        bn.train()
        bn(x)
        mean_after_train = bn._mean.numpy().copy()
        bn.eval()
        bn(x)
        np.testing.assert_allclose(bn._mean.numpy(), mean_after_train)


def test_state_dict_roundtrip(tmp_path):
    with dygraph.guard():
        m1 = dygraph.Linear(4, 3)
        m2 = dygraph.Linear(4, 3)
        path = str(tmp_path / 'model')
        fluid.save_dygraph(m1.state_dict(), path)
        state, _ = fluid.load_dygraph(path)
        m2.set_dict({k: v for k, v in zip(m2.state_dict(), state.values())})
        # names differ between instances; align by order
        for (k1, v1), (k2, v2) in zip(sorted(m1.state_dict().items()),
                                      sorted(m2.state_dict().items())):
            assert v1.shape == v2.shape


def test_finite_difference_matmul_grad():
    with dygraph.guard():
        np.random.seed(3)
        w = dygraph.Parameter(np.random.randn(3, 2).astype(np.float32))
        x = to_variable(np.random.randn(4, 3).astype(np.float32))
        out = dygraph.dispatch_op('matmul', {'x': x, 'y': w}, {})
        loss = dygraph.dispatch_op('reduce_sum', {'x': out}, {})
        loss.backward()
        g = w.gradient()
        eps = 1e-3
        for i in range(3):
            for j in range(2):
                wp = w.numpy().copy()
                wp[i, j] += eps
                lp = float(np.sum(x.numpy() @ wp))
                wm = w.numpy().copy()
                wm[i, j] -= eps
                lm = float(np.sum(x.numpy() @ wm))
                fd = (lp - lm) / (2 * eps)
                np.testing.assert_allclose(g[i, j], fd, rtol=1e-2, atol=1e-2)


def test_no_grad_guard_is_thread_local():
    """A worker thread inside no_grad_guard (the serving/decode engines
    run EVERY step under one) must not disable tape recording on other
    threads: the flag was process-global, so a scheduler thread mid-step
    made concurrent main-thread training build tensors with no grad
    history and backward() raised (latent race surfaced by tier-1
    ordering — fixed by per-thread grad state)."""
    import threading
    from paddle_tpu.dygraph.tape import grad_enabled, no_grad_guard

    entered = threading.Event()
    release = threading.Event()

    def worker():
        with no_grad_guard():
            assert not grad_enabled()
            entered.set()
            release.wait(timeout=10)

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    assert entered.wait(timeout=10)
    try:
        # main thread still records while the worker holds its guard
        assert grad_enabled()
        with dygraph.guard():
            x = to_variable(np.ones((2, 3), np.float32))
            w = dygraph.Parameter(np.ones((3, 2), np.float32))
            out = dygraph.dispatch_op('matmul', {'x': x, 'y': w}, {})
            loss = dygraph.dispatch_op('reduce_sum', {'x': out}, {})
            loss.backward()
            assert w.gradient() is not None
    finally:
        release.set()
        t.join(timeout=10)
    assert grad_enabled()
