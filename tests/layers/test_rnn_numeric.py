"""RNN layer numerics beyond shapes: rnn() over cells vs manual
recurrence, birnn, StaticRNN vs rnn() parity, gru_unit/lstm_unit single
steps, dynamic_decode greedy path."""
import numpy as np
import pytest

import paddle_tpu as fluid

L = fluid.layers
RNG = np.random.RandomState(5)


def _run(build, feeds):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        fetch = build()
    exe = fluid.Executor()
    exe.run(startup)
    fetch = fetch if isinstance(fetch, (list, tuple)) else [fetch]
    return exe.run(main, feed=feeds, fetch_list=list(fetch))


def const_attr(v):
    return fluid.ParamAttr(
        initializer=fluid.initializer.ConstantInitializer(v))


def test_rnn_over_grucell_matches_manual():
    B, T, D, H = 2, 4, 3, 5
    x = RNG.rand(B, T, D).astype('float32')

    def build():
        xv = fluid.data('rg_x', [B, T, D], 'float32')
        cell = L.GRUCell(H, param_attr=const_attr(0.1),
                         bias_attr=const_attr(0.0))
        out, final = L.rnn(cell, xv)
        return [out, final]
    out, final = _run(build, {'rg_x': x})
    assert out.shape == (B, T, H)
    # manual GRU with the same constant weights (gate order u, r)
    Wg = np.full((D + H, 2 * H), 0.1, 'float32')
    Wc = np.full((D + H, H), 0.1, 'float32')
    h = np.zeros((B, H), 'float32')
    for t in range(T):
        xh = np.concatenate([x[:, t], h], 1)
        g = 1 / (1 + np.exp(-(xh @ Wg)))
        u, r = g[:, :H], g[:, H:]
        c = np.tanh(np.concatenate([x[:, t], r * h], 1) @ Wc)
        h = u * h + (1 - u) * c
        np.testing.assert_allclose(out[:, t], h, rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(final, h, rtol=2e-4, atol=2e-4)


def test_rnn_sequence_length_freezes_state():
    B, T, D, H = 2, 5, 3, 4
    x = RNG.rand(B, T, D).astype('float32')

    def build():
        xv = fluid.data('rl_x', [B, T, D], 'float32')
        ln = fluid.data('rl_len', [B], 'int64')
        cell = L.LSTMCell(H)
        out, final = L.rnn(cell, xv, sequence_length=ln)
        return [out, final[0]]
    out, final_h = _run(build, {'rl_x': x,
                                'rl_len': np.array([2, 5], 'int64')})
    # beyond row 0's length the outputs are zero
    np.testing.assert_allclose(out[0, 2:], 0.0, atol=1e-6)
    assert not np.allclose(out[1, 2:], 0.0)
    # final state for row 0 is the step-2 state: recompute with len 5 and
    # compare the step-1 output (the last valid one) to final_h
    np.testing.assert_allclose(final_h[0], out[0, 1], rtol=1e-5)


def test_birnn_concats_directions():
    B, T, D, H = 2, 3, 4, 5
    x = RNG.rand(B, T, D).astype('float32')

    def build():
        xv = fluid.data('bi_x', [B, T, D], 'float32')
        fw = L.GRUCell(H, name='bi_fw')
        bw = L.GRUCell(H, name='bi_bw')
        out, states = L.birnn(fw, bw, xv)
        return out
    out, = _run(build, {'bi_x': x})
    assert out.shape == (B, T, 2 * H)


def test_static_rnn_matches_rnn_layer():
    B, T, D, H = 2, 4, 3, 4
    x = RNG.rand(B, T, D).astype('float32')

    def build():
        xv = fluid.data('sr_x', [B, T, D], 'float32')
        # rnn() path
        cell = L.GRUCell(H, param_attr=const_attr(0.15),
                         bias_attr=const_attr(0.0), name='sr_cell')
        out1, _ = L.rnn(cell, xv)

        # StaticRNN path reusing the SAME cell (params shared by name)
        xt = L.transpose(xv, perm=[1, 0, 2])
        srnn = L.StaticRNN()
        with srnn.step():
            w = srnn.step_input(xt)
            pre = srnn.memory(batch_ref=xv, shape=[-1, H],
                              ref_batch_dim_idx=0)
            _, new = cell.call(w, pre)
            srnn.update_memory(pre, new)
            srnn.step_output(new)
        out2 = L.transpose(srnn(), perm=[1, 0, 2])
        return [out1, out2]
    out1, out2 = _run(build, {'sr_x': x})
    np.testing.assert_allclose(out1, out2, rtol=1e-5, atol=1e-5)


def test_gru_unit_and_lstm_unit_single_step():
    B, D, H = 3, 4, 5
    x = RNG.rand(B, 3 * H).astype('float32')
    h = RNG.rand(B, H).astype('float32')

    def build():
        xv = fluid.data('gu_x', [B, 3 * H], 'float32')
        hv = fluid.data('gu_h', [B, H], 'float32')
        out = L.gru_unit(xv, hv, 3 * H)
        xl = fluid.data('lu_x', [B, D], 'float32')
        cl = fluid.data('lu_c', [B, H], 'float32')
        hl = fluid.data('lu_h', [B, H], 'float32')
        lh, lc = L.lstm_unit(xl, hl, cl)
        return [out[0], lh, lc]
    xo = RNG.rand(B, D).astype('float32')
    c0 = RNG.rand(B, H).astype('float32')
    r = _run(build, {'gu_x': x, 'gu_h': h, 'lu_x': xo, 'lu_c': c0,
                     'lu_h': h})
    assert r[0].shape == (B, H)
    assert r[1].shape == (B, H) and r[2].shape == (B, H)
    assert all(np.isfinite(a).all() for a in r)


def test_dynamic_decode_greedy_terminates_on_end_token():
    """GreedyEmbeddingHelper-style decode: with a fixed output layer that
    always argmaxes to the end token, decoding finishes immediately."""
    B, H, V = 2, 4, 6
    end_id = 3

    def build():
        h0 = fluid.data('dd_h', [B, H], 'float32')
        cell = L.GRUCell(H)
        from paddle_tpu.layers.rnn import (BasicDecoder,
                                           GreedyEmbeddingHelper)
        emb_w = L.create_parameter([V, H], 'float32', name='dd_emb',
                                   attr=const_attr(0.05))

        def embedding_fn(ids):
            return L.gather(emb_w, L.reshape(ids, shape=[-1]))

        # output layer biased so end_id always wins
        bias = np.zeros(V, 'float32'); bias[end_id] = 100.0

        def output_fn(h):
            logits = L.fc(h, V, bias_attr=False,
                          param_attr=const_attr(0.0))
            return logits + fluid.layers.tensor.fill_constant_array(bias)
        starts = fluid.layers.tensor.fill_constant([B], 'int64', 0)
        helper = GreedyEmbeddingHelper(embedding_fn, start_tokens=starts,
                                       end_token=end_id)
        decoder = BasicDecoder(cell, helper, output_fn=output_fn)
        outputs, states = L.dynamic_decode(decoder, inits=h0,
                                           max_step_num=4)
        return outputs[1]          # sampled ids
    ids, = _run(build, {'dd_h': np.zeros((B, H), 'float32')})
    assert (np.asarray(ids)[:, 0] == 3).all()
