"""RNN layers: cells + rnn(), dynamic_lstm/gru scan ops, beam search decode."""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers


def _run(main, start, feed, fetch):
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(start)
        return exe.run(main, feed=feed, fetch_list=fetch)


def test_rnn_grucell_shapes_and_mask():
    B, T, D, H = 2, 5, 3, 4
    main, start = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, start):
        x = layers.data('x', shape=[B, T, D], dtype='float32',
                        append_batch_size=False)
        lens = layers.data('lens', shape=[B], dtype='int64',
                           append_batch_size=False)
        cell = layers.GRUCell(hidden_size=H)
        out, final = layers.rnn(cell, x, sequence_length=lens)
    xv = np.random.RandomState(0).randn(B, T, D).astype(np.float32)
    lv = np.array([5, 2], np.int64)
    o, f = _run(main, start, {'x': xv, 'lens': lv}, [out, final])
    assert o.shape == (B, T, H)
    assert f.shape == (B, H)
    # padded steps must emit zero outputs and carry the final state
    assert np.all(o[1, 2:] == 0)
    np.testing.assert_allclose(f[1], o[1, 1], rtol=1e-5)
    np.testing.assert_allclose(f[0], o[0, -1], rtol=1e-5)


def test_rnn_lstmcell_matches_manual():
    B, T, D, H = 2, 3, 3, 2
    main, start = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, start):
        x = layers.data('x', shape=[B, T, D], dtype='float32',
                        append_batch_size=False)
        cell = layers.LSTMCell(hidden_size=H, name='lstm_t')
        out, (h_f, c_f) = layers.rnn(cell, x)
    xv = np.random.RandomState(1).randn(B, T, D).astype(np.float32)
    o, hf, cf = _run(main, start, {'x': xv}, [out, h_f, c_f])
    # manual recompute with fetched weights
    scope = fluid.global_scope()
    names = [v.name for v in main.all_parameters()]
    # weights survive in the test scope only inside _run's guard; rerun inline
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(start)
        w, b = [np.asarray(fluid.global_scope().find(n)) for n in names]
        o2, = exe.run(main, feed={'x': xv}, fetch_list=[out])
        h = np.zeros((B, H), np.float32)
        c = np.zeros((B, H), np.float32)
        sig = lambda v: 1 / (1 + np.exp(-v))
        for t in range(T):
            g = np.concatenate([xv[:, t], h], -1) @ w + b
            i, j, f, og = np.split(g, 4, -1)
            c = c * sig(f + 1.0) + sig(i) * np.tanh(j)
            h = np.tanh(c) * sig(og)
            np.testing.assert_allclose(o2[:, t], h, rtol=2e-5, atol=2e-5)


def test_dynamic_lstm_and_gru_shapes():
    B, T, D = 2, 4, 3
    main, start = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, start):
        x = layers.data('x', shape=[B, T, 4 * D], dtype='float32',
                        append_batch_size=False)
        h, c = layers.dynamic_lstm(x, size=4 * D, use_peepholes=True)
        xg = layers.data('xg', shape=[B, T, 3 * D], dtype='float32',
                         append_batch_size=False)
        hg = layers.dynamic_gru(xg, size=D)
    rng = np.random.RandomState(0)
    hv, cv, hgv = _run(main, start,
                       {'x': rng.randn(B, T, 4 * D).astype(np.float32),
                        'xg': rng.randn(B, T, 3 * D).astype(np.float32)},
                       [h, c, hg])
    assert hv.shape == (B, T, D) and cv.shape == (B, T, D)
    assert hgv.shape == (B, T, D)
    assert np.isfinite(hv).all() and np.isfinite(hgv).all()


def test_dynamic_gru_respects_length_mask():
    B, T, D = 2, 4, 3
    main, start = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, start):
        x = layers.data('x', shape=[B, T, 3 * D], dtype='float32',
                        append_batch_size=False)
        lens = layers.data('lens', shape=[B], dtype='int64',
                           append_batch_size=False)
        hg = layers.dynamic_gru(x, size=D, sequence_length=lens)
    xv = np.random.RandomState(0).randn(B, T, 3 * D).astype(np.float32)
    o, = _run(main, start, {'x': xv, 'lens': np.array([4, 2], np.int64)}, [hg])
    # beyond its length, row 1 carries the last valid hidden unchanged
    np.testing.assert_allclose(o[1, 2], o[1, 1], rtol=1e-6)
    np.testing.assert_allclose(o[1, 3], o[1, 1], rtol=1e-6)


def test_gather_tree():
    # T=3, B=1, W=2 beams; hand-built parents
    ids = np.array([[[2, 3]], [[4, 5]], [[6, 7]]], np.int64)
    parents = np.array([[[0, 0]], [[0, 0]], [[1, 0]]], np.int64)
    main, start = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, start):
        i = layers.data('i', shape=[3, 1, 2], dtype='int64',
                        append_batch_size=False)
        p = layers.data('p', shape=[3, 1, 2], dtype='int64',
                        append_batch_size=False)
        out = layers.gather_tree(i, p)
    r, = _run(main, start, {'i': ids, 'p': parents}, [out])
    # beam 0 at final step came from parent 1: path 2→5? parents[2]=1 →
    # step1 beam1=5, its parent 0 → step0 beam0=2
    np.testing.assert_array_equal(r[:, 0, 0], [2, 5, 6])
    np.testing.assert_array_equal(r[:, 0, 1], [2, 4, 7])


class _ToyCell(layers.RNNCell):
    """Deterministic toy cell: state += onehot-ish projection of input."""

    def __init__(self, vocab, hidden):
        self.vocab = vocab
        self.hidden = hidden
        self._built = False

    def call(self, inputs, states):
        from paddle_tpu.layers import nn as nn_layers
        if not self._built:
            from paddle_tpu.layer_helper import LayerHelper
            import paddle_tpu as fluid_mod
            helper = LayerHelper('toy_cell')
            self.w = helper.create_parameter(
                None, [inputs.shape[-1], self.hidden], 'float32',
                default_initializer=fluid_mod.initializer.ConstantInitializer(0.1))
            self._built = True
        new = layers.tanh(nn_layers.matmul(inputs, self.w) + states)
        return new, new

    @property
    def state_shape(self):
        return [self.hidden]


def test_beam_search_decoder_smoke():
    B, W, V, H, E = 2, 3, 7, 5, 4
    main, start = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, start):
        enc = layers.data('enc', shape=[B, H], dtype='float32',
                          append_batch_size=False)
        cell = _ToyCell(V, H)
        emb = lambda ids: layers.embedding(ids, size=[V, E])
        proj = lambda h: layers.fc(h, size=V)
        dec = layers.BeamSearchDecoder(cell, start_token=0, end_token=1,
                                       beam_size=W, embedding_fn=emb,
                                       output_fn=proj)
        ids, scores = layers.dynamic_decode(dec, inits=enc, max_step_num=4)
    ev = np.random.RandomState(0).randn(B, H).astype(np.float32)
    ridx, rsc = _run(main, start, {'enc': ev}, [ids, scores])
    assert ridx.shape == (B, 4, W)
    assert rsc.shape == (B, 4, W)
    assert (ridx >= 0).all() and (ridx < V).all()
    # scores per beam must be non-increasing along the beam dim at final step
    assert np.all(np.diff(rsc[:, -1, :], axis=-1) <= 1e-5)
