"""fused_embedding_seq_pool ≡ embedding + sequence_pool (PR satellite):
the fused op must match the unfused pair bit-for-bit across combiners,
padding_idx placements (incl. negative-index normalization), and ragged
LoD batches. Two real defects are pinned here: the fused 'mean' used to
exclude padding_idx rows from its denominator, and `embedding` dropped
the LoD length var so the downstream pool ignored raggedness."""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers as L
from paddle_tpu.core.lod import LoDTensor
from paddle_tpu.core.random import default_generator
import paddle_tpu.core.scope as sm
from paddle_tpu.core.scope import Scope


def _run_pair(combiner, padding_idx, feed_ids):
    default_generator.seed(3)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = L.data('ids', [4], dtype='int64', lod_level=1)
        fused = fluid.contrib.layers.fused_embedding_seq_pool(
            ids, [20, 6], padding_idx=padding_idx, combiner=combiner)
        emb = L.embedding(ids, size=[20, 6], padding_idx=padding_idx)
        pool = L.sequence_pool(
            emb, pool_type='sum' if combiner == 'sum' else 'average')
    exe = fluid.Executor()
    old = sm._global_scope
    sm._global_scope = Scope()
    try:
        exe.run(startup)
        # tie the two tables so only the op formulations differ
        params = [v.name for v in main.all_parameters()]
        sm._global_scope.set(
            params[1], np.asarray(sm._global_scope.find(params[0])))
        return exe.run(main, feed={'ids': feed_ids},
                       fetch_list=[fused, pool])
    finally:
        sm._global_scope = old


_IDS = np.array([[1, 2, 3, 19], [2, 2, 0, 5]], np.int64)


@pytest.mark.parametrize('combiner', ['sum', 'mean'])
@pytest.mark.parametrize('padding_idx', [None, 2, -1])
def test_dense_batch_parity(combiner, padding_idx):
    f, p = _run_pair(combiner, padding_idx, _IDS)
    assert np.array_equal(f, p), (combiner, padding_idx, f, p)


@pytest.mark.parametrize('combiner', ['sum', 'mean'])
@pytest.mark.parametrize('padding_idx', [None, 2, -1])
def test_ragged_lod_parity(combiner, padding_idx):
    """Ragged rows: lengths [3, 4] — step 3 of row 0 must be masked by
    BOTH paths (the embedding layer now carries the LoD length var)."""
    f, p = _run_pair(combiner, padding_idx, LoDTensor(_IDS, [[3, 4]]))
    assert np.array_equal(f, p), (combiner, padding_idx, f, p)


def test_mean_denominator_counts_padding_rows():
    """padding_idx rows contribute zero to the numerator but COUNT in
    the mean denominator (sequence_pool 'average' semantics — the fused
    op used to divide by the non-pad count only)."""
    f, _ = _run_pair('mean', 2, np.array([[2, 2, 1, 1]], np.int64))
    _, full = _run_pair('mean', None, np.array([[1, 1, 1, 1]], np.int64))
    # two pad rows of four → mean is half the all-ones-row mean
    assert np.allclose(f, full / 2, atol=1e-6)


def test_negative_padding_idx_normalizes():
    """padding_idx=-1 on a 20-row table masks id 19 in both layers."""
    fa, pa = _run_pair('sum', -1, np.array([[19, 19, 1, 1]], np.int64))
    fb, pb = _run_pair('sum', 19, np.array([[19, 19, 1, 1]], np.int64))
    assert np.array_equal(fa, fb) and np.array_equal(pa, pb)
    assert np.array_equal(fa, pa)


def test_fused_grad_flows_rows():
    """The fused op trains: its table gradient exists and only touched
    rows are non-zero."""
    import paddle_tpu.dygraph as dygraph
    from paddle_tpu.dygraph.tape import dispatch_op, Tensor
    with dygraph.guard():
        default_generator.seed(1)
        w = Tensor(np.random.RandomState(0).randn(20, 6).astype(np.float32),
                   stop_gradient=False)
        ids = Tensor(np.array([[1, 2, 3, 3]], np.int64),
                     stop_gradient=True)
        out = dispatch_op('fused_embedding_seq_pool',
                          {'ids': ids, 'w': w, 'length': None},
                          {'combiner': 'sum', 'padding_idx': -1})
        dispatch_op('reduce_sum', {'x': out}, {}).backward()
        g = np.asarray(w.grad)
        assert np.count_nonzero(g.sum(axis=1)) == 3
        assert np.allclose(g[3], 2.0)
