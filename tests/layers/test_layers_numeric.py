"""Numeric value checks for the fluid.layers API surface against numpy
references (ref test model: python/paddle/fluid/tests/unittests/
test_layers.py + per-op OpTests). Each case builds a tiny static program,
runs it, and asserts VALUES (not just shapes)."""
import numpy as np
import pytest

import paddle_tpu as fluid

L = fluid.layers
T = fluid.layers  # tensor fns re-exported at layers level

RNG = np.random.RandomState(7)


def run_prog(build, feeds):
    """build(vars...) inside a fresh program; returns fetched numpy."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        fetch = build()
    exe = fluid.Executor()
    exe.run(startup)
    fetch = fetch if isinstance(fetch, (list, tuple)) else [fetch]
    return exe.run(main, feed=feeds, fetch_list=list(fetch))


def feed_var(name, arr, lod_level=0):
    return fluid.data(name, list(arr.shape), str(arr.dtype),
                      lod_level=lod_level)


# ---------------------------------------------------------------- math ----

def test_elementwise_family_values():
    a = RNG.rand(3, 4).astype('float32') + 0.5
    b = RNG.rand(3, 4).astype('float32') + 0.5

    def build():
        x, y = feed_var('ew_a', a), feed_var('ew_b', b)
        return [L.elementwise_add(x, y), L.elementwise_sub(x, y),
                L.elementwise_mul(x, y), L.elementwise_div(x, y),
                L.elementwise_max(x, y), L.elementwise_min(x, y),
                L.elementwise_pow(x, y)]
    r = run_prog(build, {'ew_a': a, 'ew_b': b})
    for got, want in zip(r, [a + b, a - b, a * b, a / b, np.maximum(a, b),
                             np.minimum(a, b), a ** b]):
        np.testing.assert_allclose(got, want, rtol=1e-5)


def test_elementwise_broadcast_axis():
    a = RNG.rand(2, 3, 4).astype('float32')
    b = RNG.rand(3).astype('float32')

    def build():
        x, y = feed_var('eb_a', a), feed_var('eb_b', b)
        return L.elementwise_add(x, y, axis=1)
    r, = run_prog(build, {'eb_a': a, 'eb_b': b})
    np.testing.assert_allclose(r, a + b[None, :, None], rtol=1e-6)


def test_matmul_and_mul():
    a = RNG.rand(3, 4).astype('float32')
    b = RNG.rand(4, 5).astype('float32')

    def build():
        x, y = feed_var('mm_a', a), feed_var('mm_b', b)
        return [L.matmul(x, y), L.mul(x, y)]
    r = run_prog(build, {'mm_a': a, 'mm_b': b})
    np.testing.assert_allclose(r[0], a @ b, rtol=1e-5)
    np.testing.assert_allclose(r[1], a @ b, rtol=1e-5)


def test_matmul_transpose_flags():
    a = RNG.rand(4, 3).astype('float32')
    b = RNG.rand(5, 4).astype('float32')

    def build():
        x, y = feed_var('mt_a', a), feed_var('mt_b', b)
        return L.matmul(x, y, transpose_x=True, transpose_y=True)
    r, = run_prog(build, {'mt_a': a, 'mt_b': b})
    np.testing.assert_allclose(r, a.T @ b.T, rtol=1e-5)


def test_scale_clip_sign_abs():
    a = (RNG.rand(3, 4).astype('float32') - 0.5) * 4

    def build():
        x = feed_var('sc_a', a)
        return [L.scale(x, scale=2.5, bias=1.0), L.clip(x, min=-1.0, max=1.0),
                L.sign(x), L.abs(x)]
    r = run_prog(build, {'sc_a': a})
    np.testing.assert_allclose(r[0], a * 2.5 + 1.0, rtol=1e-5)
    np.testing.assert_allclose(r[1], np.clip(a, -1, 1), rtol=1e-6)
    np.testing.assert_allclose(r[2], np.sign(a))
    np.testing.assert_allclose(r[3], np.abs(a))


def test_reductions_with_axis_and_keepdim():
    a = RNG.rand(2, 3, 4).astype('float32')

    def build():
        x = feed_var('rd_a', a)
        return [L.reduce_sum(x, dim=[1]), L.reduce_mean(x, dim=[0, 2]),
                L.reduce_max(x, dim=[2], keep_dim=True),
                L.reduce_min(x), L.reduce_prod(x, dim=[1])]
    r = run_prog(build, {'rd_a': a})
    np.testing.assert_allclose(r[0], a.sum(1), rtol=1e-5)
    np.testing.assert_allclose(r[1], a.mean((0, 2)), rtol=1e-5)
    np.testing.assert_allclose(r[2], a.max(2, keepdims=True), rtol=1e-6)
    np.testing.assert_allclose(r[3], a.min(), rtol=1e-6)
    np.testing.assert_allclose(r[4], a.prod(1), rtol=1e-5)


def test_cumsum_and_logsumexp():
    a = RNG.rand(3, 4).astype('float32')

    def build():
        x = feed_var('cs_a', a)
        return [L.cumsum(x, axis=1), L.logsumexp(x)]
    r = run_prog(build, {'cs_a': a})
    np.testing.assert_allclose(r[0], np.cumsum(a, 1), rtol=1e-5)
    np.testing.assert_allclose(
        r[1], np.log(np.sum(np.exp(a))), rtol=1e-5)


# -------------------------------------------------------------- tensor ----

def test_concat_split_stack_unstack():
    a = RNG.rand(2, 3).astype('float32')
    b = RNG.rand(2, 3).astype('float32')

    def build():
        x, y = feed_var('ct_a', a), feed_var('ct_b', b)
        cat = L.concat([x, y], axis=0)
        s1, s2 = L.split(cat, 2, dim=0)
        st = L.stack([x, y], axis=0)
        return [cat, s1, s2, st]
    r = run_prog(build, {'ct_a': a, 'ct_b': b})
    np.testing.assert_allclose(r[0], np.concatenate([a, b], 0))
    np.testing.assert_allclose(r[1], a)
    np.testing.assert_allclose(r[2], b)
    np.testing.assert_allclose(r[3], np.stack([a, b], 0))


def test_reshape_transpose_squeeze_expand_tile():
    a = RNG.rand(2, 1, 6).astype('float32')

    def build():
        x = feed_var('rs_a', a)
        return [L.reshape(x, shape=[2, 6]), L.transpose(x, perm=[2, 0, 1]),
                L.squeeze(x, axes=[1]), L.unsqueeze(x, axes=[0]),
                L.expand(x, expand_times=[1, 3, 1])]
    r = run_prog(build, {'rs_a': a})
    np.testing.assert_allclose(r[0], a.reshape(2, 6))
    np.testing.assert_allclose(r[1], a.transpose(2, 0, 1))
    np.testing.assert_allclose(r[2], a[:, 0, :])
    np.testing.assert_allclose(r[3], a[None])
    np.testing.assert_allclose(r[4], np.tile(a, (1, 3, 1)))


def test_slice_strided_slice_reverse():
    a = np.arange(24, dtype='float32').reshape(4, 6)

    def build():
        x = feed_var('sl_a', a)
        return [L.slice(x, axes=[0, 1], starts=[1, 2], ends=[3, 5]),
                L.strided_slice(x, axes=[1], starts=[0], ends=[6],
                                strides=[2]),
                T.reverse(x, axis=[0])]
    r = run_prog(build, {'sl_a': a})
    np.testing.assert_allclose(r[0], a[1:3, 2:5])
    np.testing.assert_allclose(r[1], a[:, ::2])
    np.testing.assert_allclose(r[2], a[::-1])


def test_gather_scatter_family():
    a = np.arange(20, dtype='float32').reshape(5, 4)
    idx = np.array([3, 1], 'int64')

    def build():
        x = feed_var('gs_a', a)
        i = feed_var('gs_i', idx)
        upd = L.fill_constant([2, 4], 'float32', 100.0)
        return [L.gather(x, i), L.scatter(x, i, upd),
                L.gather_nd(x, L.reshape(i, shape=[2, 1]))]
    r = run_prog(build, {'gs_a': a, 'gs_i': idx})
    np.testing.assert_allclose(r[0], a[idx])
    want = a.copy(); want[idx] = 100.0
    np.testing.assert_allclose(r[1], want)
    np.testing.assert_allclose(r[2], a[idx])


def test_fill_arange_linspace_eye_diag():
    def build():
        return [T.fill_constant([2, 3], 'float32', 2.5),
                T.range(0, 10, 2, 'int64'),
                T.linspace(0.0, 1.0, 5, 'float32'),
                T.eye(3, 4),
                T.diag(T.fill_constant([3], 'float32', 7.0)),
                T.ones([2, 2], 'float32'), T.zeros([2], 'int64')]
    r = run_prog(build, {})
    np.testing.assert_allclose(r[0], np.full((2, 3), 2.5, 'float32'))
    np.testing.assert_allclose(r[1], np.arange(0, 10, 2))
    np.testing.assert_allclose(r[2], np.linspace(0, 1, 5), rtol=1e-6)
    np.testing.assert_allclose(r[3], np.eye(3, 4))
    np.testing.assert_allclose(r[4], np.diag([7.0] * 3))
    np.testing.assert_allclose(r[5], np.ones((2, 2)))
    np.testing.assert_allclose(r[6], np.zeros(2))


def test_argminmax_topk_argsort():
    a = np.array([[3., 1., 2.], [0., 5., 4.]], 'float32')
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = feed_var('am_a', a)
        am = L.argmax(x, axis=1)
        an = L.argmin(x, axis=0)
        tv, ti = L.topk(x, k=2)
        srt = T.argsort(x, axis=1)
    exe = fluid.Executor()
    exe.run(startup)
    srt_fetch = list(srt) if isinstance(srt, (list, tuple)) else [srt]
    r = exe.run(main, feed={'am_a': a},
                fetch_list=[am, an, tv, ti] + srt_fetch)
    np.testing.assert_allclose(r[0], [0, 1])
    np.testing.assert_allclose(r[1], [1, 0, 0])
    np.testing.assert_allclose(r[2], [[3., 2.], [5., 4.]])
    np.testing.assert_allclose(r[3], [[0, 2], [1, 2]])
    # argsort: sorted values first (ref returns (sorted, indices))
    np.testing.assert_allclose(np.asarray(r[4]),
                               np.sort(a, axis=1))
    if len(r) > 5:
        np.testing.assert_allclose(np.asarray(r[5]),
                                   np.argsort(a, axis=1))


def test_where_cond_and_masking():
    c = np.array([[True, False], [False, True]])
    a = np.ones((2, 2), 'float32')
    b = np.zeros((2, 2), 'float32')

    def build():
        cv = feed_var('wh_c', c)
        x, y = feed_var('wh_a', a), feed_var('wh_b', b)
        return L.where(cv, x, y)
    r, = run_prog(build, {'wh_c': c, 'wh_a': a, 'wh_b': b})
    np.testing.assert_allclose(r, np.where(c, a, b))


def test_cast_one_hot_label_smooth():
    ids = np.array([0, 2, 1], 'int64')

    def build():
        i = feed_var('oh_i', ids)
        oh = L.one_hot(i, 4)
        return [oh, T.cast(i, 'float32'),
                L.label_smooth(oh, epsilon=0.1)]
    r = run_prog(build, {'oh_i': ids})
    want = np.eye(4)[ids]
    np.testing.assert_allclose(r[0], want)
    np.testing.assert_allclose(r[1], ids.astype('float32'))
    np.testing.assert_allclose(r[2], want * 0.9 + 0.1 / 4, rtol=1e-5)


# ------------------------------------------------------------------ nn ----

def test_fc_value():
    a = RNG.rand(3, 4).astype('float32')

    def build():
        x = feed_var('fc_a', a)
        return L.fc(x, 2, param_attr=fluid.ParamAttr(
            name='fcv_w',
            initializer=fluid.initializer.ConstantInitializer(0.5)),
            bias_attr=fluid.ParamAttr(
                name='fcv_b',
                initializer=fluid.initializer.ConstantInitializer(1.0)))
    r, = run_prog(build, {'fc_a': a})
    np.testing.assert_allclose(r, a @ np.full((4, 2), 0.5) + 1.0, rtol=1e-5)


def test_conv2d_value_identity_kernel():
    a = RNG.rand(1, 1, 4, 4).astype('float32')

    def build():
        x = feed_var('cv_a', a)
        return L.conv2d(x, 1, 1, param_attr=fluid.ParamAttr(
            name='cv_w',
            initializer=fluid.initializer.ConstantInitializer(1.0)),
            bias_attr=False)
    r, = run_prog(build, {'cv_a': a})
    np.testing.assert_allclose(r, a, rtol=1e-5)


def test_pool2d_avg_and_max():
    a = np.arange(16, dtype='float32').reshape(1, 1, 4, 4)

    def build():
        x = feed_var('pl_a', a)
        return [L.pool2d(x, 2, 'max', pool_stride=2),
                L.pool2d(x, 2, 'avg', pool_stride=2),
                L.adaptive_pool2d(x, [1, 1], 'avg')]
    r = run_prog(build, {'pl_a': a})
    np.testing.assert_allclose(r[0][0, 0], [[5, 7], [13, 15]])
    np.testing.assert_allclose(r[1][0, 0], [[2.5, 4.5], [10.5, 12.5]])
    np.testing.assert_allclose(r[2][0, 0], [[7.5]])


def test_norm_layers_values():
    a = RNG.rand(4, 6).astype('float32')

    def build():
        x = feed_var('ln_a', a)
        return [L.layer_norm(x), L.softmax(x), L.l2_normalize(x, axis=1)]
    r = run_prog(build, {'ln_a': a})
    mu, var = a.mean(1, keepdims=True), a.var(1, keepdims=True)
    np.testing.assert_allclose(r[0], (a - mu) / np.sqrt(var + 1e-5),
                               rtol=1e-4, atol=1e-4)
    e = np.exp(a - a.max(1, keepdims=True))
    np.testing.assert_allclose(r[1], e / e.sum(1, keepdims=True), rtol=1e-5)
    np.testing.assert_allclose(
        r[2], a / np.sqrt((a * a).sum(1, keepdims=True)), rtol=1e-5)


def test_batch_norm_inference_stats():
    a = RNG.rand(8, 3).astype('float32')

    def build():
        x = feed_var('bn_a', a)
        return L.batch_norm(x, is_test=False)
    r, = run_prog(build, {'bn_a': a})
    mu, var = a.mean(0), a.var(0)
    np.testing.assert_allclose(r, (a - mu) / np.sqrt(var + 1e-5),
                               rtol=1e-3, atol=1e-3)


def test_dropout_test_mode_and_train_mask():
    a = np.ones((64, 64), 'float32')

    def build():
        x = feed_var('dp_a', a)
        return [L.dropout(x, 0.5, is_test=True),
                L.dropout(x, 0.5, is_test=True,
                          dropout_implementation='upscale_in_train'),
                L.dropout(x, 0.5, is_test=False,
                          dropout_implementation='upscale_in_train')]
    r = run_prog(build, {'dp_a': a})
    # default 'downgrade_in_infer': inference multiplies by (1-p)
    np.testing.assert_allclose(r[0], a * 0.5)
    # upscale_in_train: inference is identity
    np.testing.assert_allclose(r[1], a)
    kept = np.count_nonzero(r[2]) / r[2].size
    assert 0.3 < kept < 0.7                    # ~half kept
    nz = r[2][r[2] != 0]
    np.testing.assert_allclose(nz, 2.0, rtol=1e-5)   # upscaled


def test_embedding_and_padding_idx():
    ids = np.array([0, 1, 2], 'int64')

    def build():
        i = feed_var('em_i', ids)
        return L.embedding(i, size=[4, 3], padding_idx=1,
                           param_attr=fluid.ParamAttr(
                               name='em_w',
                               initializer=fluid.initializer
                               .ConstantInitializer(2.0)))
    r, = run_prog(build, {'em_i': ids})
    np.testing.assert_allclose(r[0], [2, 2, 2])
    np.testing.assert_allclose(r[1], [0, 0, 0])   # padding_idx row zeroed
    np.testing.assert_allclose(r[2], [2, 2, 2])


def test_interpolate_nearest_and_bilinear():
    a = np.arange(4, dtype='float32').reshape(1, 1, 2, 2)

    def build():
        x = feed_var('ip_a', a)
        return [L.resize_nearest(x, out_shape=[4, 4]),
                L.resize_bilinear(x, out_shape=[4, 4])]
    r = run_prog(build, {'ip_a': a})
    assert r[0].shape == (1, 1, 4, 4) and r[1].shape == (1, 1, 4, 4)
    np.testing.assert_allclose(r[0][0, 0, 0], [0, 0, 1, 1])
    assert r[1].min() >= 0 and r[1].max() <= 3


def test_pad_and_pad2d():
    a = np.ones((1, 1, 2, 2), 'float32')

    def build():
        x = feed_var('pd_a', a)
        return [L.pad(x, paddings=[0, 0, 0, 0, 1, 1, 1, 1], pad_value=5.0),
                L.pad2d(x, paddings=[1, 1, 1, 1], mode='constant',
                        pad_value=5.0)]
    r = run_prog(build, {'pd_a': a})
    for got in r:
        assert got.shape == (1, 1, 4, 4)
        assert got[0, 0, 0, 0] == 5.0 and got[0, 0, 1, 1] == 1.0


def test_pixel_shuffle_and_space_to_depth():
    a = np.arange(16, dtype='float32').reshape(1, 4, 2, 2)

    def build():
        x = feed_var('ps_a', a)
        return L.pixel_shuffle(x, upscale_factor=2)
    r, = run_prog(build, {'ps_a': a})
    assert r.shape == (1, 1, 4, 4)
    assert set(r.ravel()) == set(a.ravel())


def test_unfold_im2col():
    a = np.arange(16, dtype='float32').reshape(1, 1, 4, 4)

    def build():
        x = feed_var('uf_a', a)
        return L.unfold(x, kernel_sizes=[2, 2], strides=2)
    r, = run_prog(build, {'uf_a': a})
    assert r.shape == (1, 4, 4)
    np.testing.assert_allclose(sorted(r.ravel()), sorted(a.ravel()))


def test_maxout_and_prelu():
    a = np.array([[-1., 2., -3., 4.]], 'float32')

    def build():
        x = feed_var('mo_a', a)
        return [L.maxout(x, groups=2, axis=1),
                L.prelu(x, mode='all', param_attr=fluid.ParamAttr(
                    name='pr_w',
                    initializer=fluid.initializer
                    .ConstantInitializer(0.25)))]
    r = run_prog(build, {'mo_a': a})
    np.testing.assert_allclose(r[0], [[2., 4.]])
    np.testing.assert_allclose(r[1], [[-0.25, 2., -0.75, 4.]])


def test_activation_values():
    a = np.array([[-2., -0.5, 0.5, 2.]], 'float32')

    def build():
        x = feed_var('ac_a', a)
        return [L.relu(x), L.relu6(x), L.leaky_relu(x, alpha=0.1),
                L.elu(x), L.softsign(x), L.softplus(x), L.hard_swish(x),
                L.swish(x), L.tanh(x), L.sigmoid(x)]
    r = run_prog(build, {'ac_a': a})
    np.testing.assert_allclose(r[0], np.maximum(a, 0))
    np.testing.assert_allclose(r[1], np.clip(a, 0, 6))
    np.testing.assert_allclose(r[2], np.where(a > 0, a, 0.1 * a), rtol=1e-6)
    np.testing.assert_allclose(r[3], np.where(a > 0, a, np.exp(a) - 1),
                               rtol=1e-5)
    np.testing.assert_allclose(r[4], a / (1 + np.abs(a)), rtol=1e-5)
    np.testing.assert_allclose(r[5], np.log1p(np.exp(a)), rtol=1e-5)
    np.testing.assert_allclose(
        r[6], a * np.clip(a + 3, 0, 6) / 6, rtol=1e-5)
    sig = 1 / (1 + np.exp(-a))
    np.testing.assert_allclose(r[7], a * sig, rtol=1e-5)
    np.testing.assert_allclose(r[8], np.tanh(a), rtol=1e-5)
    np.testing.assert_allclose(r[9], sig, rtol=1e-5)


# ---------------------------------------------------------------- loss ----

def test_cross_entropy_and_softmax_ce():
    logits = RNG.rand(4, 5).astype('float32')
    labels = np.array([[1], [0], [4], [2]], 'int64')

    def build():
        x = feed_var('ce_x', logits)
        y = feed_var('ce_y', labels)
        sm = L.softmax(x)
        return [L.cross_entropy(sm, y),
                L.softmax_with_cross_entropy(x, y)]
    r = run_prog(build, {'ce_x': logits, 'ce_y': labels})
    e = np.exp(logits - logits.max(1, keepdims=True))
    p = e / e.sum(1, keepdims=True)
    want = -np.log(p[np.arange(4), labels[:, 0]])[:, None]
    np.testing.assert_allclose(r[0], want, rtol=1e-4)
    np.testing.assert_allclose(r[1], want, rtol=1e-4)


def test_regression_losses():
    x = RNG.rand(4, 3).astype('float32')
    y = RNG.rand(4, 3).astype('float32')

    def build():
        a, b = feed_var('rl_x', x), feed_var('rl_y', y)
        return [L.square_error_cost(a, b), L.mse_loss(a, b),
                L.huber_loss(a, b, delta=0.1)]
    r = run_prog(build, {'rl_x': x, 'rl_y': y})
    np.testing.assert_allclose(r[0], (x - y) ** 2, rtol=1e-5)
    np.testing.assert_allclose(r[1], ((x - y) ** 2).mean(), rtol=1e-5)
    d = np.abs(x - y)
    want = np.where(d <= 0.1, 0.5 * d * d, 0.1 * d - 0.005)
    np.testing.assert_allclose(r[2], want, rtol=1e-4, atol=1e-6)


def test_rank_and_margin_losses():
    left = np.array([[0.8], [0.2]], 'float32')
    right = np.array([[0.3], [0.7]], 'float32')
    label = np.array([[1.0], [0.0]], 'float32')

    def build():
        lv = feed_var('rk_l', left)
        rv = feed_var('rk_r', right)
        lb = feed_var('rk_y', label)
        return [L.rank_loss(lb, lv, rv),
                L.margin_rank_loss(lb, lv, rv, margin=0.1)]
    r = run_prog(build, {'rk_l': left, 'rk_r': right, 'rk_y': label})
    assert r[0].shape[0] == 2 and np.isfinite(r[0]).all()
    assert (r[1] >= 0).all()


def test_kldiv_and_log_loss():
    p = np.array([[0.2, 0.8], [0.6, 0.4]], 'float32')
    q = np.array([[0.5, 0.5], [0.3, 0.7]], 'float32')

    def build():
        x = feed_var('kl_x', np.log(p))
        t = feed_var('kl_t', q)
        pr = feed_var('ll_p', p[:, :1])
        lb = feed_var('ll_y', np.array([[1.], [0.]], 'float32'))
        return [L.kldiv_loss(x, t, reduction='none'),
                L.log_loss(pr, lb)]
    r = run_prog(build, {'kl_x': np.log(p), 'kl_t': q,
                         'll_p': p[:, :1],
                         'll_y': np.array([[1.], [0.]], 'float32')})
    np.testing.assert_allclose(r[0], q * (np.log(q) - np.log(p)),
                               rtol=1e-3, atol=1e-4)
    lab = np.array([[1.], [0.]])
    eps = 1e-4   # the reference log_loss epsilon
    want = -(lab * np.log(p[:, :1] + eps)
             + (1 - lab) * np.log(1 - p[:, :1] + eps))
    np.testing.assert_allclose(r[1], want, rtol=1e-5)


def test_sigmoid_ce_and_focal_style():
    x = RNG.randn(3, 4).astype('float32')
    lab = (RNG.rand(3, 4) > 0.5).astype('float32')

    def build():
        xv = feed_var('sce_x', x)
        lv = feed_var('sce_y', lab)
        return L.sigmoid_cross_entropy_with_logits(xv, lv)
    r, = run_prog(build, {'sce_x': x, 'sce_y': lab})
    want = np.maximum(x, 0) - x * lab + np.log1p(np.exp(-np.abs(x)))
    np.testing.assert_allclose(r, want, rtol=1e-4, atol=1e-5)


def test_dice_and_bpr():
    p = np.array([[0.8, 0.2], [0.3, 0.7]], 'float32')
    lab = np.array([[0], [1]], 'int64')

    def build():
        pv = feed_var('dc_p', p)
        lv = feed_var('dc_y', lab)
        return [L.dice_loss(pv, lv), L.bpr_loss(pv, lv)]
    r = run_prog(build, {'dc_p': p, 'dc_y': lab})
    assert np.isfinite(r[0]).all() and np.isfinite(r[1]).all()
    assert (r[1] > 0).all()


# ------------------------------------------------------------- compare ----

def test_compare_ops_values():
    a = np.array([1., 2., 3.], 'float32')
    b = np.array([2., 2., 2.], 'float32')

    def build():
        x, y = feed_var('cp_a', a), feed_var('cp_b', b)
        return [L.equal(x, y), L.not_equal(x, y), L.less_than(x, y),
                L.less_equal(x, y), L.greater_than(x, y),
                L.greater_equal(x, y)]
    r = run_prog(build, {'cp_a': a, 'cp_b': b})
    np.testing.assert_array_equal(r[0], a == b)
    np.testing.assert_array_equal(r[1], a != b)
    np.testing.assert_array_equal(r[2], a < b)
    np.testing.assert_array_equal(r[3], a <= b)
    np.testing.assert_array_equal(r[4], a > b)
    np.testing.assert_array_equal(r[5], a >= b)


# ------------------------------------------------------ misc nn extras ----

def test_cos_sim_and_bilinear():
    a = RNG.rand(3, 4).astype('float32')
    b = RNG.rand(3, 4).astype('float32')

    def build():
        x, y = feed_var('cs2_a', a), feed_var('cs2_b', b)
        return L.cos_sim(x, y)
    r, = run_prog(build, {'cs2_a': a, 'cs2_b': b})
    want = (a * b).sum(1) / (np.linalg.norm(a, axis=1)
                             * np.linalg.norm(b, axis=1))
    np.testing.assert_allclose(r.ravel(), want, rtol=1e-4)


def test_multiplex_and_sums():
    a = np.ones((3, 2), 'float32')
    b = np.full((3, 2), 2.0, 'float32')
    idx = np.array([[0], [1], [0]], 'int32')

    def build():
        x, y = feed_var('mx_a', a), feed_var('mx_b', b)
        i = feed_var('mx_i', idx)
        return [L.multiplex([x, y], i), T.sums([x, y])]
    r = run_prog(build, {'mx_a': a, 'mx_b': b, 'mx_i': idx})
    np.testing.assert_allclose(r[0], [[1, 1], [2, 2], [1, 1]])
    np.testing.assert_allclose(r[1], a + b)
