"""Control flow: cond / case / switch_case / while_loop / While / StaticRNN /
TensorArray — static lowering to lax.cond/while_loop/switch/scan."""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers


def _fresh():
    main, start = fluid.Program(), fluid.Program()
    return main, start


def _run(main, start, feed, fetch):
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(start)
        return exe.run(main, feed=feed, fetch_list=fetch)


def test_cond_static():
    main, start = _fresh()
    with fluid.program_guard(main, start):
        x = layers.data('x', shape=[3], dtype='float32', append_batch_size=False)
        pred = layers.reduce_sum(x) > 1.0
        out = layers.cond(pred, lambda: x * 2.0, lambda: x - 1.0)
    r_true, = _run(main, start, {'x': np.ones(3, np.float32)}, [out])
    np.testing.assert_allclose(r_true, 2 * np.ones(3), rtol=1e-6)
    r_false, = _run(main, start, {'x': np.zeros(3, np.float32)}, [out])
    np.testing.assert_allclose(r_false, -np.ones(3), rtol=1e-6)


def test_cond_multiple_outputs():
    main, start = _fresh()
    with fluid.program_guard(main, start):
        x = layers.data('x', shape=[2], dtype='float32', append_batch_size=False)
        pred = layers.reduce_sum(x) > 0.0
        a, b = layers.cond(pred, lambda: (x + 1.0, x + 2.0),
                           lambda: (x * 0.0, x * 3.0))
    ra, rb = _run(main, start, {'x': np.ones(2, np.float32)}, [a, b])
    np.testing.assert_allclose(ra, [2, 2], rtol=1e-6)
    np.testing.assert_allclose(rb, [3, 3], rtol=1e-6)


def test_switch_case():
    main, start = _fresh()
    with fluid.program_guard(main, start):
        idx = layers.data('i', shape=[1], dtype='int32', append_batch_size=False)
        out = layers.switch_case(
            idx,
            {1: lambda: layers.fill_constant([2], 'float32', 1.0),
             3: lambda: layers.fill_constant([2], 'float32', 3.0)},
            default=lambda: layers.fill_constant([2], 'float32', -1.0))
    for i, expect in [(1, 1.0), (3, 3.0), (7, -1.0)]:
        r, = _run(main, start, {'i': np.array([i], np.int32)}, [out])
        np.testing.assert_allclose(r, expect * np.ones(2), rtol=1e-6)


def test_case():
    main, start = _fresh()
    with fluid.program_guard(main, start):
        x = layers.data('x', shape=[1], dtype='float32', append_batch_size=False)
        s = layers.reduce_sum(x)
        out = layers.case(
            [(s < 0.0, lambda: layers.fill_constant([1], 'float32', -1.0)),
             (s < 10.0, lambda: layers.fill_constant([1], 'float32', 0.5))],
            default=lambda: layers.fill_constant([1], 'float32', 99.0))
    r, = _run(main, start, {'x': np.array([-5.0], np.float32)}, [out])
    assert r[0] == -1.0
    r, = _run(main, start, {'x': np.array([5.0], np.float32)}, [out])
    assert r[0] == 0.5
    r, = _run(main, start, {'x': np.array([50.0], np.float32)}, [out])
    assert r[0] == 99.0


def test_while_loop_functional():
    main, start = _fresh()
    with fluid.program_guard(main, start):
        i = layers.fill_constant([1], 'int32', 0)
        acc = layers.fill_constant([1], 'float32', 0.0)
        limit = layers.data('n', shape=[1], dtype='int32', append_batch_size=False)

        def cond_fn(i, acc):
            return layers.less_than(i, limit)

        def body_fn(i, acc):
            return [i + 1, acc + 2.0]

        i_out, acc_out = layers.while_loop(cond_fn, body_fn, [i, acc])
    ri, racc = _run(main, start, {'n': np.array([5], np.int32)},
                    [i_out, acc_out])
    assert ri[0] == 5
    np.testing.assert_allclose(racc, [10.0], rtol=1e-6)


def test_while_legacy_block():
    main, start = _fresh()
    with fluid.program_guard(main, start):
        n = layers.fill_constant([1], 'int64', 4)
        i = layers.fill_constant([1], 'int64', 0)
        total = layers.fill_constant([1], 'int64', 0)
        cond_var = layers.less_than(i, n)
        w = layers.While(cond_var)
        with w.block():
            layers.assign(total + i, total)
            layers.increment(i, value=1, in_place=True)
            layers.less_than(i, n, cond=cond_var)
    r, = _run(main, start, {}, [total])
    assert r[0] == 0 + 1 + 2 + 3


def test_static_rnn():
    T, B, D = 4, 2, 3
    main, start = _fresh()
    with fluid.program_guard(main, start):
        x = layers.data('x', shape=[T, B, D], dtype='float32',
                        append_batch_size=False)
        rnn = layers.StaticRNN()
        with rnn.step():
            x_t = rnn.step_input(x)
            h_prev = rnn.memory(shape=[B, D], batch_ref=x, init_value=0.0)
            h = h_prev + x_t
            rnn.update_memory(h_prev, h)
            rnn.step_output(h)
        out = rnn()
    xv = np.random.RandomState(0).randn(T, B, D).astype(np.float32)
    r, = _run(main, start, {'x': xv}, [out])
    np.testing.assert_allclose(r, np.cumsum(xv, axis=0), rtol=1e-5)


def test_tensor_array_concrete_index():
    main, start = _fresh()
    with fluid.program_guard(main, start):
        x = layers.data('x', shape=[2], dtype='float32', append_batch_size=False)
        arr = layers.create_array('float32')
        i0 = layers.fill_constant([1], 'int64', 0)
        i1 = layers.fill_constant([1], 'int64', 1)
        layers.array_write(x, i0, arr)
        layers.array_write(x * 2.0, i1, arr)
        back = layers.array_read(arr, i1)
        n = layers.array_length(arr)
    r, rn = _run(main, start, {'x': np.ones(2, np.float32)}, [back, n])
    np.testing.assert_allclose(r, [2, 2], rtol=1e-6)
    assert int(rn) == 2


def test_cond_parent_var_write():
    # assign(x, output=outer_var) inside a branch must merge out of the cond
    main, start = _fresh()
    with fluid.program_guard(main, start):
        x = layers.data('x', shape=[1], dtype='float32', append_batch_size=False)
        acc = layers.fill_constant([1], 'float32', 0.0)
        pred = layers.reduce_sum(x) > 0.0
        layers.cond(pred,
                    lambda: layers.assign(x * 10.0, output=acc),
                    lambda: layers.assign(x * -1.0, output=acc))
    r, = _run(main, start, {'x': np.array([2.0], np.float32)}, [acc])
    np.testing.assert_allclose(r, [20.0], rtol=1e-6)
    r, = _run(main, start, {'x': np.array([-3.0], np.float32)}, [acc])
    np.testing.assert_allclose(r, [3.0], rtol=1e-6)


def test_cond_branch_none_mismatch():
    main, start = _fresh()
    with fluid.program_guard(main, start):
        x = layers.data('x', shape=[1], dtype='float32', append_batch_size=False)
        with pytest.raises(ValueError, match='None'):
            layers.cond(layers.reduce_sum(x) > 0.0, lambda: x, lambda: None)


def test_static_rnn_dropout_rng_varies_per_step():
    T, B, D = 3, 2, 64
    main, start = _fresh()
    with fluid.program_guard(main, start):
        x = layers.data('x', shape=[T, B, D], dtype='float32',
                        append_batch_size=False)
        rnn = layers.StaticRNN()
        with rnn.step():
            x_t = rnn.step_input(x)
            d = layers.dropout(x_t, dropout_prob=0.5)
            rnn.step_output(d)
        out = rnn()
    xv = np.ones((T, B, D), np.float32)
    r, = _run(main, start, {'x': xv}, [out])
    masks = (r != 0)
    assert not np.array_equal(masks[0], masks[1]), \
        "dropout mask must differ across scan steps"


def test_assign_ndarray_output_dygraph():
    with fluid.dygraph.guard():
        t = fluid.dygraph.to_variable(np.zeros(2, np.float32))
        layers.assign(np.ones(2, np.float32), output=t)
        np.testing.assert_allclose(t.numpy(), [1, 1], rtol=1e-6)


def test_cond_dygraph():
    with fluid.dygraph.guard():
        x = fluid.dygraph.to_variable(np.ones(3, np.float32))
        out = layers.cond(layers.reduce_sum(x) > 1.0,
                          lambda: x * 2.0, lambda: x - 1.0)
        np.testing.assert_allclose(out.numpy(), 2 * np.ones(3), rtol=1e-6)


def test_while_loop_dygraph():
    with fluid.dygraph.guard():
        i = fluid.dygraph.to_variable(np.array([0], np.int32))
        acc = fluid.dygraph.to_variable(np.array([0.0], np.float32))
        res = layers.while_loop(lambda i, a: i < 3,
                                lambda i, a: [i + 1, a + 5.0], [i, acc])
        assert res[0].numpy()[0] == 3
        np.testing.assert_allclose(res[1].numpy(), [15.0], rtol=1e-6)
