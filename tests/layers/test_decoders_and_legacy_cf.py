"""Decode helpers (Training/GreedyEmbedding/BasicDecoder), lstm(), and the
legacy Switch / IfElse / DynamicRNN constructs."""
import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers


def _run(main, start, feed, fetch):
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(start)
        return exe.run(main, feed=feed, fetch_list=fetch)


def test_switch_first_true_wins():
    main, start = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, start):
        x = layers.data('x', shape=[1], dtype='float32',
                        append_batch_size=False)
        out = layers.create_global_var([1], 0.0, 'float32', persistable=True)
        one = layers.fill_constant([1], 'float32', 1.0)
        two = layers.fill_constant([1], 'float32', 2.0)
        three = layers.fill_constant([1], 'float32', 3.0)
        with layers.Switch() as sw:
            with sw.case(layers.reduce_sum(x) > 10.0):
                layers.assign(one, output=out)
            with sw.case(layers.reduce_sum(x) > 5.0):
                layers.assign(two, output=out)
            with sw.default():
                layers.assign(three, output=out)
    for val, want in [(20.0, 1.0), (7.0, 2.0), (1.0, 3.0)]:
        r, = _run(main, start, {'x': np.array([val], 'float32')}, [out])
        assert float(np.asarray(r).item()) == want, (val, r, want)


def test_ifelse_rowwise_merge():
    main, start = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, start):
        x = layers.data('x', shape=[1], dtype='float32')
        cond = layers.greater_than(
            x, layers.fill_constant([1], 'float32', 0.0))
        ie = layers.IfElse(cond)
        with ie.true_block():
            ie.output(ie.input(x) * 2.0)
        with ie.false_block():
            ie.output(ie.input(x) - 1.0)
        out = ie()[0]
    xin = np.array([[1.0], [-2.0], [3.0]], 'float32')
    r, = _run(main, start, {'x': xin}, [out])
    np.testing.assert_allclose(r, [[2.0], [-3.0], [6.0]])


def test_dynamic_rnn_masked_accumulation():
    main, start = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, start):
        x = layers.data('x', shape=[3, 2], dtype='float32')
        lens = layers.data('lens', shape=[1], dtype='int64')
        drnn = layers.DynamicRNN()
        with drnn.block():
            step = drnn.step_input(x, sequence_length=lens)
            acc = drnn.memory(shape=[2], value=0.0)
            new = acc + step
            drnn.update_memory(acc, new)
            drnn.output(new)
        out = drnn()
        final = layers.sequence_last_step(out)
    xin = np.ones((2, 3, 2), 'float32')
    lens_in = np.array([3, 1], 'int64')
    r, = _run(main, start, {'x': xin, 'lens': lens_in}, [final])
    # row 0 runs 3 steps → 3.0; row 1 freezes after 1 step → 1.0
    np.testing.assert_allclose(r, [[3.0, 3.0], [1.0, 1.0]])


def test_training_helper_basic_decoder():
    B, T, D, H = 2, 4, 3, 5
    rng = np.random.RandomState(0)
    main, start = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, start):
        tgt = layers.data('tgt', shape=[T, D], dtype='float32')
        cell = layers.LSTMCell(H)
        helper = layers.TrainingHelper(tgt)
        dec = layers.BasicDecoder(cell, helper)
        h0 = layers.zeros([B, H], 'float32')
        c0 = layers.zeros([B, H], 'float32')
        outs, _ = layers.dynamic_decode(dec, inits=[h0, c0], max_step_num=T)
    feed = {'tgt': rng.randn(B, T, D).astype('float32')}
    o, ids = _run(main, start, feed, [outs.cell_outputs, outs.sample_ids])
    assert o.shape == (B, T, H)
    assert ids.shape == (B, T)


def test_greedy_embedding_helper_decode():
    B, V, E = 2, 6, 4
    main, start = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, start):
        emb_w = layers.create_parameter([V, E], 'float32', name='dec_emb')

        def embed(ids):
            return layers.gather(emb_w, layers.reshape(ids, shape=[-1]))

        start_toks = layers.assign(np.zeros(B, 'int64'))
        cell = layers.GRUCell(E)
        helper = layers.GreedyEmbeddingHelper(embed, start_toks, end_token=1)
        dec = layers.BasicDecoder(cell, helper,
                                  output_fn=lambda h: layers.fc(h, V))
        h0 = layers.zeros([B, E], 'float32')
        outs, _ = layers.dynamic_decode(dec, inits=h0, max_step_num=5)
    o, ids = _run(main, start, {}, [outs.cell_outputs, outs.sample_ids])
    assert o.shape == (B, 5, V)
    assert ids.shape == (B, 5)
    assert (ids >= 0).all() and (ids < V).all()


def test_lstm_layer_shapes():
    T, B, D, H, L = 4, 2, 3, 6, 2
    main, start = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, start):
        x = layers.data('x', shape=[B, D], dtype='float32',
                        append_batch_size=False)
        xt = layers.expand(layers.unsqueeze(x, axes=[0]),
                           expand_times=[T, 1, 1])
        out, h, c = layers.lstm(xt, None, None, T, H, L)
        out2, h2, c2 = layers.lstm(xt, None, None, T, H, 1, is_bidirec=True)
    feed = {'x': np.random.RandomState(0).randn(B, D).astype('float32')}
    o, hh, cc, o2, hh2 = _run(main, start, feed, [out, h, c, out2, h2])
    assert o.shape == (T, B, H) and hh.shape == (L, B, H)
    assert o2.shape == (T, B, 2 * H) and hh2.shape == (2, B, H)


def test_lod_rank_table_reorder():
    main, start = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, start):
        x = layers.data('x', shape=[3, 2], dtype='float32')
        off = layers.data('off', shape=[3], dtype='int64',
                          append_batch_size=False)
        x2 = layers.lod_reset(x, y=off)   # y's data is a LoD offset table
        table = layers.lod_rank_table(x2)
        out = layers.reorder_lod_tensor_by_rank(x2, table)
    xin = np.arange(12, dtype='float32').reshape(2, 3, 2)
    r, = _run(main, start, {'x': xin, 'off': np.array([0, 1, 4], 'int64')},
              [out])
    np.testing.assert_allclose(r, xin[::-1])  # longer sequence first
